"""Batched AOI neighbor engine — the TPU-native hot loop.

What the reference does per entity move (Space.go:253-261 → go-aoi
``Moved(aoi, x, z)`` → synchronous OnEnterAOI/OnLeaveAOI callbacks), this
engine does for *all* entities of *all* spaces in one launch per tick.

Design (round 2): the engine is **event-native**. The reference's AOI (and
round 1's engine) materializes per-entity neighbor *sets* and diffs them;
sets are exactly what a TPU is bad at (variable degree, top-k truncation,
huge [N, candidates] intermediates). But the *product* the host consumes is
the enter/leave event stream — so the engine computes events directly as a
pairwise predicate diff and never materializes a neighbor list at all:

    valid_t(i, j) = av_t(i) ∧ av_t(j) ∧ space_t(i) = space_t(j)
                    ∧ dist_t(i, j) ≤ radius_t(i) ∧ i ≠ j
    enter(t) = valid_t ∧ ¬valid_{t-1}        leave(t) = valid_{t-1} ∧ ¬valid_t

where ``av`` (active-and-visible) folds grid-capacity drops into validity,
keeping the event stream *exactly* consistent for host-side incremental sets
even across drop windows. There is **no max_neighbors truncation**: interest
sets are the exact geometric sets, a superset of go-aoi semantics (which has
a single uniform distance, reference TODO.md:17 — per-entity radius is
supported here).

Enumeration uses two spatial-hash grids per tick, both with **static
shapes**:

- **enter pass** bins entities by their *current* positions: any pair valid
  at t is within radius ≤ cell_size, hence inside the 3×3 cell neighborhood.
- **leave pass** bins by the *previous* positions: any pair valid at t-1 is
  inside the previous grid's 3×3 neighborhood.

Each pass evaluates both epochs' predicates per pair (positions of both
ticks ride along as features), so arbitrarily large per-tick movement —
teleports, cross-game migration (EnterSpace, Entity.go:956-1115) — is exact:
no movement bound, no stale interest.

Two execution paths with identical semantics:

- **Pallas kernel** (TPU): entities packed into a dense per-cell layout
  ``[space_slot, gz, gx, F, 128]`` (the boids layout, ops/boids.py); one
  program per cell DMAs its 3×3 halo block HBM→VMEM, evaluates the pairwise
  predicates for 128 × 1152 pairs on the VPU, and bit-packs the event mask
  16-bits-per-word with integer shift-adds — no [N, candidates] float
  intermediate ever reaches HBM (round 1 shipped ~200 MB × several per
  tick). Around the kernel everything is gathers, cumsums and sorts — no
  large TPU scatters (round 2's feature scatter and nonzero-based drain
  were both scatter-bound).
- **jnp reference** (CPU tests / oracle): the same two-grid pairwise math
  over gathered candidate id matrices.

The engine is a pure function of (previous tick's inputs, current inputs);
device state is just the previous (pos, active, space, radius). Stateless-
per-tick is what keeps freeze/restore and migration semantics intact
(SURVEY.md §5.8): on restart the host simply re-uploads positions and takes
one enter storm.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from goworld_tpu.telemetry import sentinel

# Launch/trace accounting for every step jit built below; this module
# already owns the process's first jax import, so the persistent
# compile-cache listener installs here too.
sentinel.install_compile_cache_listener()

LANES = 128  # Pallas cell capacity = one TPU lane dimension
_PACK = 16  # event-mask bits packed per i32 word
_F = 8  # feature count (sublane multiple of 8)

# Feature rows in the dense cell layout. Epoch A = the epoch whose positions
# the grid is binned by; epoch B = the other epoch. The kernel computes
# valid_A ∧ ¬valid_B, so the same kernel serves both passes with A/B swapped.
# Empty slots carry NaN in their x rows instead of a separate occupancy row:
# NaN poisons d2 for both the query and candidate side of any pair touching
# an empty slot, and IEEE `NaN <= r2` is false — so 8 rows (one sublane
# tile) do the work 10-gated-to-16 did in round 2, halving feats traffic
# and the kernel's halo DMA.
_FX_A, _FZ_A, _FS_A, _FR_A = 0, 1, 2, 3
_FX_B, _FZ_B, _FS_B, _FR_B = 4, 5, 6, 7


@dataclasses.dataclass(frozen=True)
class NeighborParams:
    """Static configuration of a neighbor engine (shapes are compiled in)."""

    capacity: int = 16384  # max entity slots (N)
    cell_size: float = 100.0  # grid cell side; must be >= max AOI distance
    grid_x: int = 64  # grid extent in cells (wraps modulo)
    grid_z: int = 64
    space_slots: int = 8  # space-id folding slots for the shared grid
    cell_capacity: int = 64  # M: max entities visible per grid cell
    max_events: int = 65536  # enter/leave pairs fetched per host round trip
    # Pallas-drain select strategy (identical results, different gather/
    # scatter shapes — the on-chip bench sweep promotes the winner):
    #   bsearch: searchsorted row-find (log2(N) gathers/event) + binary-
    #            search word-find (log2(W) random scalar gathers/event)
    #   grouped: searchsorted row-find + two contiguous-row gathers
    #            ([E, G] group cumsums, then [E, W/G] words) per event
    #   scatter: one [N]→[E] scatter + cummax fill for the row-find
    #            (row-of-rank is a monotonic step function over the
    #            contiguous requested range) + the grouped word-find
    drain_mode: str = "bsearch"

    def __post_init__(self) -> None:
        if self.drain_mode not in ("bsearch", "grouped", "scatter"):
            raise ValueError(
                f"drain_mode must be bsearch|grouped|scatter, "
                f"got {self.drain_mode!r}"
            )
        if self.grid_x < 4 or self.grid_z < 4:
            # 3x3 neighborhoods must touch 9 distinct buckets after wrap.
            raise ValueError("grid_x and grid_z must be >= 4")
        if self.capacity % 8 != 0:
            raise ValueError("capacity must be a multiple of 8 (TPU sublanes)")
        # The Pallas drain's flat event-index space is capacity*9*LANES held
        # in int32 (ADVICE r2: overflow above ~1.86M slots must fail loudly).
        if self.capacity * 9 * LANES >= 2**31:
            raise ValueError(
                f"capacity {self.capacity} overflows the int32 event index "
                f"space (capacity * 9 * {LANES} must be < 2^31); shard the "
                f"engine instead (parallel.mesh)"
            )

    @property
    def num_buckets(self) -> int:
        return self.space_slots * self.grid_z * self.grid_x


# --- shared binning ----------------------------------------------------------


def _bins(p: NeighborParams, pos: jax.Array, space: jax.Array):
    """Wrapped (cell_x, cell_z, space_slot) coordinates per entity.

    Spaces sharing a slot are SPREAD across the torus by a per-space hash
    offset (in whole cells): game worlds cluster entities near similar
    coordinates in every space (spawn points at the origin), so without the
    offset, dozens of folded spaces pile their origin cells onto the same
    buckets and overflow cell_capacity (seen live: 1.6k entities dropped
    per tick at 100 bots). The offset is constant per space, so within-
    space geometry — the only thing the pair predicate accepts — is
    untouched.
    """
    cx = jnp.mod(jnp.floor(pos[:, 0] / p.cell_size).astype(jnp.int32), p.grid_x)
    cz = jnp.mod(jnp.floor(pos[:, 1] / p.cell_size).astype(jnp.int32), p.grid_z)
    # Two distinct Knuth-style multiplicative hashes (int32 wraparound is
    # fine — only the low bits survive the mod).
    ox = jnp.mod(space * jnp.int32(-1640531527), p.grid_x)
    oz = jnp.mod(space * jnp.int32(40503), p.grid_z)
    cx = jnp.mod(cx + ox, p.grid_x)
    cz = jnp.mod(cz + oz, p.grid_z)
    sm = jnp.mod(space, p.space_slots)
    return cx, cz, sm


def bins_reference(p: NeighborParams, pos: np.ndarray, space: np.ndarray):
    """Numpy mirror of :func:`_bins` (same hash constants, same int32
    wraparound) for host-side oracles — tests and the dryrun's engineered
    drop-count formula use THIS so a change to the binning scheme has a
    single source of truth."""
    s32 = space.astype(np.int32)
    with np.errstate(over="ignore"):
        ox = (s32 * np.int32(-1640531527)) % np.int32(p.grid_x)
        oz = (s32 * np.int32(40503)) % np.int32(p.grid_z)
    cx = (
        np.floor(pos[:, 0] / p.cell_size).astype(np.int32) % p.grid_x + ox
    ) % p.grid_x
    cz = (
        np.floor(pos[:, 1] / p.cell_size).astype(np.int32) % p.grid_z + oz
    ) % p.grid_z
    sm = s32 % p.space_slots
    return cx, cz, sm


def sorted_ranks(key: jax.Array, n: int, num_buckets: int):
    """Stable sort of bucket keys + within-bucket ranks, shared by the
    neighbor and boids table builds.

    Returns (order, sorted_key, rank): ``order`` is the stable argsort of
    ``key`` (sentinel ``num_buckets`` for inactive rows sorts last),
    ``rank`` the position of each sorted row within its key run.

    Fused single-array sort when ``(num_buckets+1)*n`` fits int32:
    key*n + iota is unique, sorts by (key, iota) — the stable-argsort
    order — and decomposes back without the pair-sort's payload lanes or
    the key[order] regather (the table build was 17.8 ms of the 112 ms
    on-chip tick, 2026-07-30; sort is its dominant term). Ranks come from
    segment boundaries + cummax — O(N) scan instead of searchsorted's
    log(N) gather passes.
    """
    iota = jnp.arange(n, dtype=jnp.int32)
    if (num_buckets + 1) * n < 2**31:
        fused = jnp.sort(key * jnp.int32(n) + iota)
        order = jax.lax.rem(fused, jnp.int32(n))
        sorted_key = fused // jnp.int32(n)
    else:
        order = jnp.argsort(key).astype(jnp.int32)  # stable
        sorted_key = key[order]
    boundary = jnp.concatenate(
        [jnp.ones((1,), jnp.bool_), sorted_key[1:] != sorted_key[:-1]]
    )
    first = jax.lax.cummax(jnp.where(boundary, iota, 0))
    return order, sorted_key, iota - first


def sorted_ranks_by(key: jax.Array, tie: jax.Array, n_rows: int):
    """Stable (key, tie) lexicographic sort + within-key-run ranks.

    Like :func:`sorted_ranks`, but ties within a bucket break by ``tie``
    (the entity SLOT id) instead of row position. The spatially sharded
    engine's strip-local table builds use this (parallel/spatial.py): a
    seam cell's rows exist as copies on two shards in different local
    orders, so cell-capacity drop choices must key on something globally
    stable — slot order, which is also exactly the single-device engine's
    row order. Returns (order, sorted_key, rank)."""
    iota = jnp.arange(n_rows, dtype=jnp.int32)
    sorted_key, _, order = jax.lax.sort(
        (key, tie, iota), num_keys=2
    )
    boundary = jnp.concatenate(
        [jnp.ones((1,), jnp.bool_), sorted_key[1:] != sorted_key[:-1]]
    )
    first = jax.lax.cummax(jnp.where(boundary, iota, 0))
    return order, sorted_key, iota - first


def _build_table(
    p: NeighborParams, bucket: jax.Array, active: jax.Array, stride: int
):
    """Bin entities into a [num_buckets * stride] slot table.

    Rank-within-bucket is derived from a stable argsort (deterministic).
    Entities beyond ``min(cell_capacity, stride)`` in a cell are dropped —
    invisible this tick, with the drop folded into the validity predicate so
    the event stream stays consistent. Returns
    (table i32[num_buckets*stride] with sentinel N, slot i32[N] with -1 for
    dropped/inactive, dropped_count, order, dst) — order/dst let callers
    scatter per-entity features into the same layout.
    """
    n = p.capacity
    cap = min(p.cell_capacity, stride)
    key = jnp.where(active, bucket, p.num_buckets)
    order, sorted_key, rank = sorted_ranks(key, n, p.num_buckets)
    ok = (sorted_key < p.num_buckets) & (rank < cap)
    dropped = jnp.sum((sorted_key < p.num_buckets) & ~ok).astype(jnp.int32)
    table_size = p.num_buckets * stride
    dst = jnp.where(ok, sorted_key * stride + rank, table_size)
    table = jnp.full((table_size,), n, dtype=jnp.int32)
    table = table.at[dst].set(order.astype(jnp.int32), mode="drop")
    slot_sorted = jnp.where(ok, dst, -1).astype(jnp.int32)
    slot = jnp.zeros((n,), jnp.int32).at[order].set(slot_sorted)
    return table, slot, dropped, order, dst


def _fast_guard(p: NeighborParams, ppos, pact, pspc, prad, pos, act, spc,
                dropped_c):
    """Single-pass eligibility: True when every pair valid in EITHER epoch
    provably sits inside the CURRENT grid's 3x3 halo — no entity
    deactivated, changed space, was capacity-dropped this tick, or moved
    more than (cell_size − r_prev)/2 (two points in cells ≥ 2 apart are
    > cell_size apart, and dist_now(a,b) ≤ r_prev + 2·max_disp for any
    previously-valid pair). Shared by the jnp, pallas and sharded steps."""
    both = pact & act
    deact = jnp.any(pact & ~act)
    spchg = jnp.any(both & (pspc != spc))
    disp = jnp.sqrt(
        jnp.max(jnp.where(both, jnp.sum((pos - ppos) ** 2, axis=1), 0.0))
    )
    prad_max = jnp.max(jnp.where(pact, prad, 0.0))
    return (
        (~deact)
        & (~spchg)
        & (dropped_c == 0)
        & (2.0 * disp + prad_max <= p.cell_size)
    )


def _pair_valid(
    q_av, q_space, q_r2, q_x, q_z, c_av, c_space, c_x, c_z, not_self
):
    """The per-pair interest predicate for one epoch (shared jnp/oracle)."""
    dx = c_x - q_x
    dz = c_z - q_z
    d2 = dx * dx + dz * dz
    return q_av & c_av & (q_space == c_space) & (d2 <= q_r2) & not_self


# --- jnp reference path ------------------------------------------------------


def _gather_cands(p: NeighborParams, table: jax.Array, cx, cz, sm) -> jax.Array:
    """Candidate id matrix [Q, 9*M] from each query's 3x3 cell block."""
    m = p.cell_capacity
    parts = []
    for dz in (-1, 0, 1):
        for dx in (-1, 0, 1):
            cxx = jnp.mod(cx + dx, p.grid_x)
            czz = jnp.mod(cz + dz, p.grid_z)
            b = (sm * p.grid_z + czz) * p.grid_x + cxx
            idx = (b * m)[:, None] + jnp.arange(m, dtype=jnp.int32)[None, :]
            parts.append(table[idx])
    return jnp.concatenate(parts, axis=1)  # [Q, 9M]


def _epoch_mask(
    p: NeighborParams,
    cand: jax.Array,  # i32[Q, 9M] candidate ids (sentinel N)
    q_ids: jax.Array,  # i32[Q] global ids of the queries
    q_pos, q_av, q_space, q_radius,  # query-side epoch arrays, [Q]
    pos, av, space,  # full per-entity epoch arrays, [N]
) -> jax.Array:
    n = p.capacity
    safe = jnp.minimum(cand, n - 1)
    # x and z gathered separately: a trailing dim of 2 would be padded to 128
    # lanes by TPU tiling (64x memory blowup on the [Q, 9M] intermediates).
    not_self = (cand < n) & (cand != q_ids[:, None])
    return _pair_valid(
        q_av[:, None],
        q_space[:, None],
        (q_radius * q_radius)[:, None],
        q_pos[:, 0][:, None],
        q_pos[:, 1][:, None],
        av[safe],
        space[safe],
        pos[:, 0][safe],
        pos[:, 1][safe],
        not_self,
    )


def _step_jnp(
    p: NeighborParams,
    ppos, pact, pspc, prad,  # previous-tick inputs (device state)
    pos, act, spc, rad,  # current-tick inputs
):
    """Two-grid pairwise diff, jnp path. Returns
    (enter_ids [N, 9M], leave_ids [N, 9M], n_enters, n_leaves, dropped)."""
    n = p.capacity
    m = p.cell_capacity
    q_ids = jnp.arange(n, dtype=jnp.int32)

    cxc, czc, smc = _bins(p, pos, spc)
    cxp, czp, smp = _bins(p, ppos, pspc)
    buc_c = (smc * p.grid_z + czc) * p.grid_x + cxc
    buc_p = (smp * p.grid_z + czp) * p.grid_x + cxp
    table_c, slot_c, dropped_c, _, _ = _build_table(p, buc_c, act, m)
    table_p, slot_p, _, _, _ = _build_table(p, buc_p, pact, m)
    av_c = slot_c >= 0
    av_p = slot_p >= 0

    # Enter pass: candidates from the current grid.
    cand_c = _gather_cands(p, table_c, cxc, czc, smc)
    vc = _epoch_mask(p, cand_c, q_ids, pos, av_c, spc, rad, pos, av_c, spc)
    vp_on_c = _epoch_mask(p, cand_c, q_ids, ppos, av_p, pspc, prad, ppos, av_p, pspc)
    enter_mask = vc & ~vp_on_c

    # Single-pass fast path (_fast_guard): the leave mask is just
    # vp_on_c & ~vc over cand_c, both already computed. Other ticks pay the
    # second gather + epoch-mask pair on the previous grid.
    fast = _fast_guard(p, ppos, pact, pspc, prad, pos, act, spc, dropped_c)

    def fast_fn():
        return vp_on_c & ~vc, cand_c

    def slow_fn():
        cand_p = _gather_cands(p, table_p, cxp, czp, smp)
        vp = _epoch_mask(p, cand_p, q_ids, ppos, av_p, pspc, prad,
                         ppos, av_p, pspc)
        vc_on_p = _epoch_mask(p, cand_p, q_ids, pos, av_c, spc, rad,
                              pos, av_c, spc)
        return vp & ~vc_on_p, cand_p

    leave_mask, cand_l = jax.lax.cond(fast, fast_fn, slow_fn)

    enter_ids = jnp.where(enter_mask, cand_c, n)
    leave_ids = jnp.where(leave_mask, cand_l, n)
    n_enters = jnp.sum(enter_mask).astype(jnp.int32)
    n_leaves = jnp.sum(leave_mask).astype(jnp.int32)
    return enter_ids, leave_ids, n_enters, n_leaves, dropped_c


def _drain_ids(ids: jax.Array, n: int, max_events: int, start_flat: jax.Array):
    """Compact one chunk of events from an id matrix.

    ``ids`` is i32[Q, W] with sentinel ``n`` in non-event slots. Returns
    (pairs i32[max_events, 2], flat_positions i32[max_events]) for the first
    ``max_events`` events at flat index >= start_flat. Host pages through by
    passing last_flat+1 as the next start.
    """
    q, w = ids.shape
    total = q * w
    flat = ids.reshape(-1)
    mask = (flat < n) & (jnp.arange(total, dtype=jnp.int32) >= start_flat)
    # Event k lives at the first flat index whose inclusive running count
    # reaches k+1: one O(total) cumsum + max_events binary searches. The
    # nonzero(size=...) formulation this replaces lowers to a total-sized
    # scatter, which XLA:CPU executes serially — 62 ms of the 150 ms
    # pinned-floor tick at [2048, 576]; the cumsum+searchsorted form is
    # ~2 ms there with the identical (index-ascending, total-filled)
    # output contract. total < 2^31 is a NeighborParams invariant, so the
    # int32 cumsum cannot overflow.
    csum = jnp.cumsum(mask.astype(jnp.int32))
    ranks = jnp.arange(1, max_events + 1, dtype=jnp.int32)
    idx = jnp.searchsorted(csum, ranks, side="left").astype(jnp.int32)
    valid = idx < total
    idx = jnp.where(valid, idx, total)
    safe = jnp.minimum(idx, total - 1)
    ent = jnp.where(valid, safe // w, n)
    oth = jnp.where(valid, flat[safe], n)
    return jnp.stack([ent, oth], axis=1), idx


def _pack_out(p: NeighborParams, enter_pairs, enter_idx, leave_pairs, leave_idx,
              n_enters, n_leaves, dropped):
    """Assemble the single packed host readback (ONE fetch per tick).

    out i32[3 + 2*max_events, 2]:
        out[0] = (n_enters, n_leaves)          total event counts
        out[1] = (dropped, 0)                  grid-capacity drop diagnostic
        out[2] = (enter_last_flat, leave_last_flat)  resume cursors
        out[3          : 3+E]  = first E enter pairs (slot, other)
        out[3+E : 3+2E]        = first E leave pairs
    """
    e = p.max_events
    header = jnp.stack(
        [
            jnp.stack([n_enters, n_leaves]),
            jnp.stack([dropped, jnp.int32(0)]),
            jnp.stack([enter_idx[e - 1], leave_idx[e - 1]]),
        ]
    ).astype(jnp.int32)
    return jnp.concatenate([header, enter_pairs, leave_pairs], axis=0)


def _step_packed_jnp(p: NeighborParams, ppos, pact, pspc, prad, pos, act, spc, rad):
    enter_ids, leave_ids, n_e, n_l, dropped = _step_jnp(
        p, ppos, pact, pspc, prad, pos, act, spc, rad
    )
    n = p.capacity
    ep, ei = _drain_ids(enter_ids, n, p.max_events, jnp.int32(0))
    lp, li = _drain_ids(leave_ids, n, p.max_events, jnp.int32(0))
    out = _pack_out(p, ep, ei, lp, li, n_e, n_l, dropped)
    return enter_ids, leave_ids, out


# --- Pallas path -------------------------------------------------------------


def _scatter_feats(p: NeighborParams, dst, order, feats_a, feats_b,
                   gx_ext: int | None = None):
    """Build the dense cell feature layout with ONE row-vector scatter.

    ``order``/``dst`` come from _build_table: sorted entity order and each
    sorted entity's flat slot (or table_size for dropped). All 8 feature
    rows ride a single [N, F] scatter into a NaN-initialized [TS, F] flat
    layout — measured 5x cheaper on-chip than 8 gathers through the table
    (2026-07-30; empty slots inherit NaN x, which is exactly the occupancy
    poisoning the kernel's validity math wants, see the _F comment).

    feats_a = (x, z, space, radius) of the epoch the grid is binned by;
    feats_b = the same four for the other epoch. Returns
    f32[space_slots, gz+2, gx+2, F, LANES] with a torus halo ring.

    ``gx_ext`` generalizes the x extent to a STRIP-LOCAL slab
    (parallel/spatial.py's Pallas tier): the extent already INCLUDES its
    ghost columns — real entities exchanged from the neighbor strips live
    there, so only z gets the torus wrap pad and x gets none. None keeps
    the full-torus layout (both dims wrap-padded).
    """
    gxe = p.grid_x if gx_ext is None else gx_ext
    table_size = p.space_slots * p.grid_z * gxe * LANES
    vals = jnp.stack(
        [f.astype(jnp.float32) for f in feats_a]
        + [f.astype(jnp.float32) for f in feats_b],
        axis=1,
    )  # [N, F]
    flat = jnp.full((table_size, _F), jnp.nan, jnp.float32)
    flat = flat.at[dst].set(vals[order], mode="drop")
    cells = flat.reshape(p.space_slots, p.grid_z, gxe, LANES, _F)
    cells = cells.transpose(0, 1, 2, 4, 3)  # [S, gz, gxe, F, LANES]
    # Halo ring per space slab: torus wrap on z always; on x only for the
    # full-torus layout (a strip slab's x halo holds real ghost rows).
    pad_x = (1, 1) if gx_ext is None else (0, 0)
    return jnp.pad(cells, ((0, 0), (1, 1), pad_x, (0, 0), (0, 0)), mode="wrap")


def _event_kernel(p: NeighborParams, dual: bool, drain_inline: int,
                  cells_hbm, *refs):
    """One program per grid cell: DMA the 3x3 halo block, evaluate
    valid_A ∧ ¬valid_B for all 128 × 1152 pairs, bit-pack the mask.

    ``dual`` additionally emits valid_B ∧ ¬valid_A (the leave mask) into the
    second half of the output words — the single-launch fast path when every
    epoch-B pair is guaranteed to sit inside epoch-A's 3x3 halo
    (_step_pallas's displacement guard).

    ``drain_inline > 0`` additionally DRAINS the masked events inside the
    same launch (ISSUE 19 leg b): a second input plane carries each tabled
    lane's SLOT id and OWN flag, and the kernel appends the (query slot,
    other slot) pair of every own-row event to a compacted pairs output
    through SMEM cursors — exact because the TPU grid executes
    SEQUENTIALLY on a core, so the cursors are plain scalar state. Region
    layout of the pairs block (i32[2, cap+1], row 0 = query, row 1 =
    other, sentinel ``capacity``): enters fill [0, drain_inline) and, when
    dual, leaves fill [drain_inline, 2*drain_inline); writes past a
    region's budget land in the trailing trash column, and the caller's
    authoritative popcount header detects the overflow and repages the
    whole tick from rank 0 (emission is cell-major, not the XLA drain's
    row-major rank order, so a partial inline window cannot be resumed).
    Per-event selection is VPU-shaped: masked-reduction scalar selects and
    prefix-compare bit ranking — no gathers. Validated under interpret;
    the scalar dynamic stores follow the TPU guide's dynamic-ref-store
    idiom but have not been Mosaic-compiled on real hardware yet (the
    kernel tier's standing honesty note).

    The halo DMA is double-buffered across grid steps: ~7.7k sequential
    73 KB copies at the headline config are latency-bound, and the serial
    start();wait() of round 2 made that latency ~half the kernel's runtime
    (measured on-chip 2026-07-30); prefetching cell k+1 during cell k's
    pair math hides it.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if drain_inline:
        (so_hbm, out_ref, pairs_ref, scratch, sem, so_scratch, so_sem,
         cur_ref) = refs
    else:
        out_ref, scratch, sem = refs
        so_hbm = pairs_ref = so_scratch = so_sem = cur_ref = None

    s = pl.program_id(0)
    i = pl.program_id(1)
    j = pl.program_id(2)
    rows = pl.num_programs(1)
    gx = pl.num_programs(2)
    lin = (s * rows + i) * gx + j
    total = pl.num_programs(0) * rows * gx
    slot = jax.lax.rem(lin, 2)
    nslot = jax.lax.rem(lin + 1, 2)

    def halo_copy(idx_lin, buf):
        s2 = idx_lin // (rows * gx)
        r = jax.lax.rem(idx_lin, rows * gx)
        return pltpu.make_async_copy(
            cells_hbm.at[s2, pl.ds(r // gx, 3), pl.ds(jax.lax.rem(r, gx), 3)],
            scratch.at[buf],
            sem.at[buf],
        )

    @pl.when(lin == 0)
    def _():
        halo_copy(lin, slot).start()

    @pl.when(lin + 1 < total)
    def _():
        halo_copy(lin + 1, nslot).start()

    if drain_inline:
        # Slot/own plane of THIS cell's 3x3 block: latency hides under the
        # pair math below (waited only at emission time).
        so_copy = pltpu.make_async_copy(
            so_hbm.at[s, pl.ds(i, 3), pl.ds(j, 3)], so_scratch, so_sem
        )
        so_copy.start()

        @pl.when(lin == 0)
        def _():
            cur_ref[0, 0] = 0
            cur_ref[1, 0] = drain_inline
            pairs_ref[:, :] = jnp.full(
                pairs_ref.shape, p.capacity, jnp.int32
            )

    halo_copy(lin, slot).wait()
    c = scratch[slot]  # [3, 3, F, LANES]
    cand = c.transpose(2, 0, 1, 3).reshape(_F, 9 * LANES)
    q = c[1, 1]  # [F, LANES]

    # Self-pairs: the center cell is candidate block 4 (row-major 3x3).
    lane = jax.lax.broadcasted_iota(jnp.int32, (LANES, 9 * LANES), 0)
    cidx = jax.lax.broadcasted_iota(jnp.int32, (LANES, 9 * LANES), 1)
    not_self = cidx != 4 * LANES + lane

    def valid(fx, fz, fs, fr):
        # Empty slots have NaN x (see _F comment): d2 goes NaN for any pair
        # touching one, and `NaN <= r2` is false — no occupancy rows needed.
        dx = cand[fx][None, :] - q[fx][:, None]
        dz = cand[fz][None, :] - q[fz][:, None]
        d2 = dx * dx + dz * dz
        r2 = (q[fr] * q[fr])[:, None]
        return (
            (q[fs][:, None] == cand[fs][None, :]) & (d2 <= r2) & not_self
        )

    v_a = valid(_FX_A, _FZ_A, _FS_A, _FR_A)
    v_b = valid(_FX_B, _FZ_B, _FS_B, _FR_B)

    # Bit-pack 16 candidate bits per i32 word via TWO half-word MXU matmuls.
    # Round 2's single matmul (weights up to 2^15) lost the LSB of sums near
    # 2^16 on hardware (f32 MXU emulation); round 3's integer shift-add
    # rewrite was exact but needs a [LANES, W, 16] reshape Mosaic's
    # infer-vector-layout rejects ("unsupported shape cast", seen on-chip
    # 2026-07-30). Splitting the word into 8-bit halves keeps the
    # Mosaic-supported matmul shape AND exactness: each half's weights are
    # 2^0..2^7 (exact in bf16) and its per-word sum is <= 255, exactly
    # representable under any MXU accumulation scheme; lo + 256*hi <= 65535
    # is exact in f32 on the VPU.
    w_words = 9 * LANES // _PACK
    c_iota = jax.lax.broadcasted_iota(jnp.int32, (9 * LANES, w_words), 0)
    w_iota = jax.lax.broadcasted_iota(jnp.int32, (9 * LANES, w_words), 1)
    bit = c_iota - w_iota * _PACK  # bit index within the word, or out of range
    half = _PACK // 2
    pmat_lo = jnp.where(
        (bit >= 0) & (bit < half), jnp.exp2(bit.astype(jnp.float32)), 0.0
    )
    pmat_hi = jnp.where(
        (bit >= half) & (bit < _PACK),
        jnp.exp2((bit - half).astype(jnp.float32)),
        0.0,
    )

    def pack(mask):
        mf = mask.astype(jnp.float32)
        lo = jnp.dot(mf, pmat_lo, preferred_element_type=jnp.float32)
        hi = jnp.dot(mf, pmat_hi, preferred_element_type=jnp.float32)
        return (lo + 256.0 * hi).astype(jnp.int32)  # [LANES, W]

    enter = pack(v_a & ~v_b)
    if dual:
        out_ref[0, 0, 0] = jnp.concatenate([enter, pack(v_b & ~v_a)], axis=1)
    else:
        out_ref[0, 0, 0] = enter

    if drain_inline:
        so_copy.wait()
        ctr = so_scratch[1, 1]  # [2, LANES]: this cell's slot ids + own flags
        q_slots = ctr[0:1]  # [1, LANES]
        own_col = jnp.transpose(ctr[1:2]) > 0  # [LANES, 1] query ownership
        slots9 = so_scratch[:, :, 0].reshape(9, LANES)  # candidate slot ids
        il = jax.lax.broadcasted_iota(jnp.int32, (1, LANES), 1)
        i9 = jax.lax.broadcasted_iota(jnp.int32, (9, LANES), 0)
        l9 = jax.lax.broadcasted_iota(jnp.int32, (9, LANES), 1)
        irow = jax.lax.broadcasted_iota(jnp.int32, (LANES, 9 * LANES), 0)
        trash = pairs_ref.shape[1] - 1

        def emit(mask, ci, lim):
            """Append every set bit of ``mask`` (pre-masked to OWN query
            lanes) as a (query slot, other slot) pair: row by prefix-count
            over the per-lane inclusive cumsum, bit by prefix-count within
            the selected row, scalars by masked reductions."""
            mi = mask.astype(jnp.int32)
            rcnt = jnp.transpose(
                jnp.sum(mi, axis=1, keepdims=True)
            )  # [1, LANES]
            rcum = jnp.cumsum(rcnt, axis=1)  # inclusive
            count = jnp.sum(mi)

            def body(jj, carry):
                row = jnp.sum(jnp.where(rcum <= jj, 1, 0))
                kk = jj - jnp.sum(jnp.where(il == row, rcum - rcnt, 0))
                mrow = jnp.sum(
                    jnp.where(irow == row, mi, 0), axis=0, keepdims=True
                )  # [1, 9*LANES]
                ccum = jnp.cumsum(mrow, axis=1)
                col = jnp.sum(jnp.where(ccum <= kk, 1, 0))
                hc = col // LANES
                lane = jax.lax.rem(col, LANES)
                other = jnp.sum(
                    jnp.where((i9 == hc) & (l9 == lane), slots9, 0)
                )
                qs = jnp.sum(jnp.where(il == row, q_slots, 0))
                cur = cur_ref[ci, 0]
                idx = jnp.where(cur < lim, cur, trash)
                pl.store(pairs_ref, (jnp.int32(0), idx), qs)
                pl.store(pairs_ref, (jnp.int32(1), idx), other)
                cur_ref[ci, 0] = cur + 1
                return carry

            jax.lax.fori_loop(0, count, body, 0)

        emit(v_a & ~v_b & own_col, 0, drain_inline)
        if dual:
            emit(v_b & ~v_a & own_col, 1, 2 * drain_inline)


@functools.lru_cache(maxsize=None)
def _compiled_event_kernel(p: NeighborParams, interpret: bool,
                           rows: int | None = None, dual: bool = False,
                           cols: int | None = None, drain_inline: int = 0):
    """``rows`` limits the kernel to a slab of grid rows (cells input is then
    the slab plus its 2 halo rows): the sharded engine launches one slab per
    device (parallel/mesh.py). ``cols`` limits it to a slab of grid COLUMNS
    the same way — the spatially sharded Pallas tier launches one strip-
    local column slab per device (parallel/spatial.py); the kernel body is
    row/column symmetric, so both ride the same program. ``dual`` emits
    enter+leave masks in one launch (words [0, W) enter, [W, 2W) leave).
    ``drain_inline`` adds the in-kernel event drain (see _event_kernel): a
    second input (the i32 slot/own plane, cells geometry with 2 planes in
    place of the F features) and a second output, the compacted pairs
    block i32[2, cap+1] with cap = drain_inline * (2 if dual else 1); its
    constant index map keeps the block VMEM-resident across the whole
    sequential grid."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if rows is None:
        rows = p.grid_z
    if cols is None:
        cols = p.grid_x
    w_words = (9 * LANES // _PACK) * (2 if dual else 1)
    kernel = functools.partial(_event_kernel, p, dual, drain_inline)
    words_spec = pl.BlockSpec(
        (1, 1, 1, LANES, w_words),
        lambda s, i, j: (s, i, j, 0, 0),
        memory_space=pltpu.VMEM,
    )
    words_shape = jax.ShapeDtypeStruct(
        (p.space_slots, rows, cols, LANES, w_words), jnp.int32
    )
    if not drain_inline:
        return pl.pallas_call(
            kernel,
            grid=(p.space_slots, rows, cols),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=words_spec,
            out_shape=words_shape,
            scratch_shapes=[
                pltpu.VMEM((2, 3, 3, _F, LANES), jnp.float32),
                pltpu.SemaphoreType.DMA((2,)),
            ],
            interpret=interpret,
        )
    cap = drain_inline * (2 if dual else 1)
    return pl.pallas_call(
        kernel,
        grid=(p.space_slots, rows, cols),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=(
            words_spec,
            pl.BlockSpec(
                (2, cap + 1), lambda s, i, j: (0, 0),
                memory_space=pltpu.VMEM,
            ),
        ),
        out_shape=(
            words_shape,
            jax.ShapeDtypeStruct((2, cap + 1), jnp.int32),
        ),
        scratch_shapes=[
            pltpu.VMEM((2, 3, 3, _F, LANES), jnp.float32),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.VMEM((3, 3, 2, LANES), jnp.int32),
            pltpu.SemaphoreType.DMA,
            pltpu.SMEM((2, 1), jnp.int32),
        ],
        interpret=interpret,
    )


def _drain_bits(
    p: NeighborParams,
    packed_e: jax.Array,  # i32[N, W] per-entity packed event mask
    cx, cz, sm,  # i32[N] bin coords of the pass's grid
    table: jax.Array,  # i32[num_buckets * LANES] id table of the pass's grid
    start_flat: jax.Array,  # EVENT RANK to resume from (name kept for the
    max_events: int | None = None,  # shared pager call signature)
    gx_ext: int | None = None,  # strip-local x extent (parallel/spatial.py)
    wrap_x: bool = True,  # False: x is a strip slab, halo cols are physical
):
    """Pallas-path drain: extract the (entity, other) pairs for event RANKS
    [start_rank, start_rank + max_events) out of the packed bit mask.

    Hierarchical rank-select instead of ``jnp.nonzero`` (round 2): nonzero's
    ``bincount(cumsum(mask))`` lowering scatter-adds over the full
    N * 9 * LANES flat space (118M elements at the headline config — a
    multi-second TPU scatter). Here the only full-size ops are popcounts
    and per-axis cumsums; each requested event then finds its row by binary
    search, its word by a 72-wide prefix compare, and its bit by a 16-wide
    prefix compare — ~max_events * 90 lanes of work, no scatter.

    Candidate c of entity i maps to halo cell c // LANES (row-major 3x3) and
    lane c % LANES. Returns (pairs i32[max_events, 2], row_counts' total) —
    paging resumes at start_rank + max_events.

    ``gx_ext``/``wrap_x`` generalize the candidate-cell arithmetic to a
    STRIP-LOCAL slab (parallel/spatial.py): ``cx`` is then the local slab
    column, the bucket space is ``space_slots * grid_z * gx_ext``, and x
    offsets index physical ghost columns instead of wrapping the torus
    (every own query's 3x3 block is inside the slab by the strip
    ownership invariant, so no x clamp is needed). ``packed_e`` may hold
    fewer rows than ``capacity`` there (own rows only); the pair's entity
    side is then a ROW index the caller maps to a slot.
    """
    if max_events is None:
        max_events = p.max_events
    start_rank = start_flat
    n = p.capacity
    n_rows = packed_e.shape[0]
    gxl = p.grid_x if gx_ext is None else gx_ext
    pc = jax.lax.population_count(packed_e)  # [N, W]
    row_counts = jnp.sum(pc, axis=1)  # [N]
    row_cum = jnp.cumsum(row_counts)  # inclusive
    row_starts = row_cum - row_counts  # exclusive
    total = row_cum[-1]

    j = start_rank + jnp.arange(max_events, dtype=jnp.int32)
    valid = j < total
    if p.drain_mode == "scatter":
        # Row-of-rank over the CONTIGUOUS range [start, start+E) is a
        # monotonic step function: each row with events intersecting the
        # range claims its first output position (one [N]→[E] scatter-max;
        # at most one row straddles `start`, and distinct rows have
        # distinct starts, so positions are unique), then cummax fills
        # forward — replacing searchsorted's log2(N) gather passes. The
        # scatter target is max_events-sized, nothing like the 118M-slot
        # round-2 pathology.
        first_pos = row_starts - start_rank
        intersects = (row_counts > 0) & (row_cum > start_rank) & (
            first_pos < max_events
        )
        target = jnp.where(
            intersects, jnp.maximum(first_pos, 0), max_events
        )
        seed = jnp.full((max_events,), -1, jnp.int32)
        seed = seed.at[target].max(
            jnp.arange(n_rows, dtype=jnp.int32), mode="drop"
        )
        row = jnp.clip(jax.lax.cummax(seed), 0, n_rows - 1)
    else:
        row = (
            jnp.searchsorted(row_starts, j, side="right").astype(jnp.int32)
            - 1
        )
        row = jnp.clip(row, 0, n_rows - 1)
    k = j - row_starts[row]  # event rank within its row

    # Word selection by binary search over the row's inclusive word-count
    # cumsum: computed ONCE as [N, W] and probed with ceil(log2(W+1)) flat
    # [E] gathers. (The round-3 predecessor gathered each event's full
    # 72-word row and re-cumsummed it — [E, W] traffic ~7x this, measured
    # on-chip 2026-07-30.)
    nw = pc.shape[1]
    word_cum = jnp.cumsum(pc, axis=1)  # [N, W] inclusive
    if p.drain_mode in ("grouped", "scatter"):
        # Two-level select via CONTIGUOUS row gathers: the bsearch mode's
        # ~log2(W) random scalar gathers per event are latency-bound on
        # TPU; here each event pulls its row's [G] group cumsums and the
        # [gsz] words of the chosen group in two row gathers, then finds
        # group/word with wide prefix compares (VPU-friendly).
        # Invariant: word w holds rank k iff word_cum[w] > k and
        # word_cum[w-1] <= k, so index = count of inclusive cumsums <= k.
        gsz = 8
        ng = (nw + gsz - 1) // gsz
        pad = ng * gsz - nw
        # edge-pad: padded words repeat the last cumsum (popcount 0).
        wc_pad = jnp.pad(word_cum, ((0, 0), (0, pad)), mode="edge")
        group_cum = wc_pad[:, gsz - 1 :: gsz]  # [N, G] inclusive per group
        g_rows = group_cum[row]  # [E, G]
        g = jnp.sum((g_rows <= k[:, None]).astype(jnp.int32), axis=1)
        g = jnp.minimum(g, ng - 1)
        # The chosen group's word cumsums per event: [E, gsz].
        idx = (row * (ng * gsz) + g * gsz)[:, None] + jnp.arange(
            gsz, dtype=jnp.int32
        )[None, :]
        wg = wc_pad.reshape(-1)[idx]
        wi = jnp.sum((wg <= k[:, None]).astype(jnp.int32), axis=1)
        w = jnp.minimum(g * gsz + wi, nw - 1)
        ev = jnp.arange(max_events)
        # Exclusive cumsum at w: last word of the previous group when the
        # event is the group's first word, else the group-local neighbor.
        prev_in_group = wg[ev, jnp.maximum(wi - 1, 0)]
        prev_group_end = jnp.where(g > 0, g_rows[ev, jnp.maximum(g - 1, 0)], 0)
        word_start = jnp.where(wi > 0, prev_in_group, prev_group_end)
        kk = k - word_start  # set-bit rank within the word
    else:
        wc_flat = word_cum.reshape(-1)
        pc_flat = pc.reshape(-1)
        base = row * nw
        lo = jnp.zeros((max_events,), jnp.int32)
        hi = jnp.full((max_events,), nw, jnp.int32)
        for _ in range(max(1, nw.bit_length())):
            mid = jnp.minimum((lo + hi) // 2, nw - 1)
            gt = wc_flat[base + mid] > k
            hi = jnp.where(gt, mid, hi)
            lo = jnp.where(gt, lo, mid + 1)
        w = jnp.minimum(lo, nw - 1)
        word_start = wc_flat[base + w] - pc_flat[base + w]
        kk = k - word_start  # set-bit rank within the word

    word = packed_e[row, w]
    bits = (word[:, None] >> jnp.arange(_PACK, dtype=jnp.int32)) & 1
    bcum = jnp.cumsum(bits, axis=1)  # inclusive set-bit counts
    b = jnp.sum((bcum <= kk[:, None]).astype(jnp.int32), axis=1)
    b = jnp.minimum(b, _PACK - 1)

    c = w * _PACK + b  # candidate index within the row's 3x3 halo
    hc = c // LANES
    lane = c % LANES
    dzo = hc // 3 - 1
    dxo = hc % 3 - 1
    czz = jnp.mod(cz[row] + dzo, p.grid_z)
    if wrap_x:
        cxx = jnp.mod(cx[row] + dxo, gxl)
    else:
        cxx = cx[row] + dxo  # strip slab: ghost columns are physical
    bucket = (sm[row] * p.grid_z + czz) * gxl + cxx
    other = table[bucket * LANES + lane]
    ent = jnp.where(valid, row, n)
    other = jnp.where(valid, other, n)
    return jnp.stack([ent, other], axis=1), total


def _step_pallas(
    p: NeighborParams, interpret: bool,
    ppos, pact, pspc, prad,  # previous-tick inputs
    pcx, pcz, psm, ptable, pslot, porder, pdst,  # prev tick's CARRIED grid
    pos, act, spc, rad,  # current-tick inputs
):
    """Pallas passes + XLA postlude. The previous grid's bins/table/slot are
    carried in engine state (they were this tick's current grid last tick),
    so only ONE argsort+table build runs per tick.

    Launch strategy (measured on-chip 2026-07-30: the second feats+kernel
    pass was ~88 ms of a 271 ms tick at 102k entities): when NO entity
    deactivated, changed space, was capacity-dropped, or moved more than
    (cell_size − r_prev)/2 since the previous tick, every pair valid in
    EITHER epoch sits inside the 3x3 halo of the CURRENT grid — two points
    in cells ≥ 2 apart are > cell_size apart, and dist_now(a,b) ≤ r_prev +
    2·max_disp for any previously-valid pair — so ONE dual-output launch on
    the current grid yields both masks. Despawn / space-hop / teleport /
    drop ticks take the exact two-launch path (enter on the current grid,
    leave on the previous). Returns the paging contexts, the packed
    readback, and the current grid artifacts for the next carry."""
    kernel = _compiled_event_kernel(p, interpret)
    kernel_dual = _compiled_event_kernel(p, interpret, dual=True)

    cxc, czc, smc = _bins(p, pos, spc)
    cxp, czp, smp = pcx, pcz, psm
    buc_c = (smc * p.grid_z + czc) * p.grid_x + cxc
    table_c, slot_c, dropped_c, order_c, dst_c = _build_table(
        p, buc_c, act, LANES
    )
    table_p, slot_p = ptable, pslot

    # Each epoch's x row is poisoned by its OWN slot validity: an entity
    # outside epoch E's table (inactive or capacity-dropped that tick) must
    # be invalid under E even when its row is written through the OTHER
    # epoch's table — e.g. a fresh spawn's stale previous position must not
    # suppress its enter event.
    xs_c = jnp.where(slot_c >= 0, pos[:, 0], jnp.nan)
    xs_p = jnp.where(slot_p >= 0, ppos[:, 0], jnp.nan)
    cur_feats = (xs_c, pos[:, 1], spc, rad)
    prev_feats = (xs_p, ppos[:, 1], pspc, prad)
    cells_c = _scatter_feats(p, dst_c, order_c, cur_feats, prev_feats)

    # dropped_c == 0 is required: a capacity-dropped entity is absent from
    # table_c entirely, so the single-launch path could never see its
    # epoch-B pairs — its neighbors' leave events must come from the
    # previous grid, where it is still tabled (code-review r3 finding).
    fast = _fast_guard(p, ppos, pact, pspc, prad, pos, act, spc, dropped_c)

    w_words = 9 * LANES // _PACK

    def per_entity(packed_cells, slot):
        nw = packed_cells.shape[-1]
        flat = packed_cells.reshape(-1, nw)
        safe = jnp.maximum(slot, 0)
        return jnp.where((slot >= 0)[:, None], flat[safe], 0)

    # Each branch returns its PER-ENTITY masks with the grid artifacts the
    # leave mask was computed on (current grid in fast mode, previous
    # otherwise) — the cond unifies them without per-array selects. The
    # slot gather runs INSIDE the branch so the fast path pays exactly one
    # [N, 2W] gather over the dual kernel's output instead of two [N, W]
    # gathers (the gather stage was ~7 ms of the 112 ms on-chip tick).
    def fast_fn():
        pk2 = per_entity(kernel_dual(cells_c), slot_c)  # i32[N, 2W]
        return (pk2[:, :w_words], pk2[:, w_words:],
                cxc, czc, smc, table_c)

    def slow_fn():
        cells_p = _scatter_feats(p, pdst, porder, prev_feats, cur_feats)
        return (per_entity(kernel(cells_c), slot_c),
                per_entity(kernel(cells_p), slot_p),
                cxp, czp, smp, table_p)

    packed_e, packed_l, lcx, lcz, lsm, ltable = (
        jax.lax.cond(fast, fast_fn, slow_fn)
    )
    n_enters = jnp.sum(jax.lax.population_count(packed_e)).astype(jnp.int32)
    n_leaves = jnp.sum(jax.lax.population_count(packed_l)).astype(jnp.int32)

    ep, _ = _drain_bits(p, packed_e, cxc, czc, smc, table_c, jnp.int32(0))
    lp, _ = _drain_bits(p, packed_l, lcx, lcz, lsm, ltable, jnp.int32(0))
    # Rank-based paging resumes at max_events, so the cursor row is unused.
    zero = jnp.int32(0)
    header = jnp.stack(
        [
            jnp.stack([n_enters, n_leaves]),
            jnp.stack([dropped_c, zero]),
            jnp.stack([zero, zero]),
        ]
    ).astype(jnp.int32)
    out = jnp.concatenate([header, ep, lp], axis=0)
    # Paging context: everything _drain_bits needs for overflow chunks.
    enter_ctx = (packed_e, cxc, czc, smc, table_c)
    leave_ctx = (packed_l, lcx, lcz, lsm, ltable)
    next_grid = (cxc, czc, smc, table_c, slot_c, order_c, dst_c)
    return enter_ctx, leave_ctx, out, next_grid


# --- fused entity logic ------------------------------------------------------
#
# [aoi] fuse_logic (ROADMAP item 2, the AsyncTaichi inter-kernel-fusion
# end-state): per-class pure tick programs (entity/columns.columnar_tick)
# ride the SAME device launch as the AOI step. The fused wrapper never
# changes what the step computes — the diff runs on the dispatched epoch
# exactly as before — it additionally applies each program elementwise to
# the dispatched (pos, y, yaw, columns) and returns the results as extra
# outputs. The host writes them back just before the NEXT dispatch
# (aoi/batched.py _consume_fused), so the program's output becomes the
# next dispatched epoch: logic rides the AOI cadence, trajectories are
# bit-identical to running the same vmapped program host-side after each
# dispatch, and every engine's event-exactness machinery (fast guards,
# carried grids, strip layout) is untouched.


def _fused_program_apply(prog, x, y, z, yaw, dt, cols):
    """One program, vmapped over every row (masking is the caller's)."""
    vfn = jax.vmap(prog.fn, in_axes=(0, 0, 0, 0, None) + (0,) * len(cols))
    return vfn(x, y, z, yaw, dt, *cols)


def _apply_fused_logic(programs, pos, y, yaw, sel, dt, cols):
    """Apply each fused program to its rows (``sel == k+1``; 0 = no
    program). ``cols`` is the flat per-program concatenation of column
    arrays. The Python loop over ``programs`` runs at TRACE time — the
    compiled launch contains only the unrolled elementwise ops. Returns
    (new_pos [N,2], new_y, new_yaw, new_cols tuple)."""
    x = pos[:, 0]
    z = pos[:, 1]
    new = [x, y, z, yaw]
    out_cols = list(cols)
    off = 0
    for k, prog in enumerate(programs):
        nc = len(prog.columns)
        pc = tuple(cols[off + i] for i in range(nc))
        outs = _fused_program_apply(prog, x, y, z, yaw, dt, pc)
        m = sel == jnp.int32(k + 1)
        for i in range(4):
            new[i] = jnp.where(m, outs[i].astype(new[i].dtype), new[i])
        for i in range(nc):
            base = out_cols[off + i]
            out_cols[off + i] = jnp.where(
                m, outs[4 + i].astype(base.dtype), base)
        off += nc
    new_pos = jnp.stack([new[0], new[2]], axis=1)
    return new_pos, new[1], new[3], tuple(out_cols)


def _step_packed_fused_jnp(
    p: NeighborParams, programs,
    ppos, pact, pspc, prad, pos, act, spc, rad, y, yaw, sel, dt, *cols,
):
    """The jnp step plus the fused entity logic in one launch (gwlint
    HOT_PATHS: body must stay loop-free — the trace-time program loop
    lives in _apply_fused_logic)."""
    enter_ids, leave_ids, out = _step_packed_jnp(
        p, ppos, pact, pspc, prad, pos, act, spc, rad
    )
    new_pos, new_y, new_yaw, new_cols = _apply_fused_logic(
        programs, pos, y, yaw, sel, dt, cols
    )
    return enter_ids, leave_ids, out, (new_pos, new_y, new_yaw) + new_cols


def _step_packed_fused_pallas(
    p: NeighborParams, interpret: bool, programs,
    ppos, pact, pspc, prad,
    pcx, pcz, psm, ptable, pslot, porder, pdst,
    pos, act, spc, rad, y, yaw, sel, dt, *cols,
):
    """The Pallas step plus the fused entity logic in one launch (the
    logic is jnp elementwise around the kernel; XLA fuses it into the
    same executable — still exactly one dispatch per tick)."""
    enter_ctx, leave_ctx, out, next_grid = _step_pallas(
        p, interpret,
        ppos, pact, pspc, prad,
        pcx, pcz, psm, ptable, pslot, porder, pdst,
        pos, act, spc, rad,
    )
    new_pos, new_y, new_yaw, new_cols = _apply_fused_logic(
        programs, pos, y, yaw, sel, dt, cols
    )
    return enter_ctx, leave_ctx, out, next_grid, (
        (new_pos, new_y, new_yaw) + new_cols
    )


@functools.lru_cache(maxsize=None)
def _jitted_step_packed_fused(params: NeighborParams, backend: str,
                              programs: tuple):
    """One jit per (params, backend, program tuple): the program set is
    part of the compiled launch. Program churn (a new class adopted) is a
    new trace — rare, like a tier jump, and prewarmable
    (NeighborEngine.warmup_fused)."""
    if backend == "jnp":
        fn = functools.partial(_step_packed_fused_jnp, params, programs)
    else:
        fn = functools.partial(
            _step_packed_fused_pallas, params,
            backend == "pallas_interpret", programs,
        )
    return sentinel.SentinelJit(f"aoi_step_fused_{backend}", jax.jit(fn))


# --- sync cadence tier pass ([sync]; rides the step launch) ------------------
#
# Adaptive per-client sync (ROADMAP item 5): each (subject, watcher)
# interest pair is classified into a sync cadence tier by distance and
# approach rate. The classification is ONE batched sweep over the edge
# list — all clients' range queries amortized into a single gather pass —
# and it rides the SAME device launch as the AOI step, so a steady-state
# tick stays one launch. The formula mirrors entity/slabs.classify_tiers
# (the host fallback used by non-batched backends), pinned equal by
# tests/test_synctier.py's parity oracle.


def _tier_pass(pos, ppos, radius, subj, wat, n_tiers: int,
               near_ratio: float, far_ratio: float):
    """uint8[Ecap] tier per padded edge: subj/wat are int32 slot ids with
    sentinel >= capacity on pad rows (tier 0 there — full rate is the
    conservative default). Distance uses the CURRENT epoch; a pair whose
    distance shrank since the PREVIOUS epoch is approaching and drops one
    tier toward full rate."""
    n = pos.shape[0]
    valid = (subj >= 0) & (subj < n) & (wat >= 0) & (wat < n)
    s = jnp.clip(subj, 0, n - 1)
    w = jnp.clip(wat, 0, n - 1)
    d = pos[s] - pos[w]
    d2 = d[:, 0] * d[:, 0] + d[:, 1] * d[:, 1]
    pd = ppos[s] - ppos[w]
    pd2 = pd[:, 0] * pd[:, 0] + pd[:, 1] * pd[:, 1]
    r = radius[w]
    r2 = jnp.maximum(r * r, jnp.float32(1e-12))
    ratio = jnp.sqrt(d2 / r2)
    span = max(far_ratio - near_ratio, 1e-9)
    tier = 1 + jnp.floor(
        (ratio - near_ratio) / span * (n_tiers - 1)).astype(jnp.int32)
    tier = jnp.clip(tier, 0, n_tiers - 1)
    tier = jnp.where(ratio <= near_ratio, 0, tier)
    tier = jnp.where(d2 < pd2, jnp.maximum(tier - 1, 0), tier)
    return jnp.where(valid, tier, 0).astype(jnp.uint8)


def _edge_verdicts(p: NeighborParams, out, subj, wat):
    """uint8[2*max_events]: per INLINE event row of the packed ``out``,
    1 = the event is a real edge-state change against the dispatched edge
    snapshot (an enter whose (subj, wat) edge is absent / a leave whose
    edge is present), 0 = a no-op the idempotent interest guards would
    swallow. This is the device half of the fused interest-edge delivery:
    the host decode applies verdict-1 rows through a thin bulk edge
    update and drops verdict-0 rows wholesale (unless the edge churned
    after the snapshot — the host-side delta log re-checks those).

    Keys are ``subj * (capacity+1) + wat`` in int32, so the caller must
    guarantee ``(capacity+1)**2 < 2**31`` (the batched service gates on
    this and falls back to host verdicts otherwise). Pad rows of the
    edge snapshot carry the slot sentinel ``capacity`` on both sides —
    their key is the maximum, so real keys never collide with them."""
    e = p.max_events
    n = p.capacity
    keys = jnp.sort(subj.astype(jnp.int32) * jnp.int32(n + 1)
                    + wat.astype(jnp.int32))
    rows = out[3:3 + 2 * e]
    # Event pairs are (watcher, other); the edge table keys
    # (subject=other, watcher) — see Entity._edge_update.
    k = rows[:, 1] * jnp.int32(n + 1) + rows[:, 0]
    idx = jnp.clip(jnp.searchsorted(keys, k), 0, keys.shape[0] - 1)
    present = keys[idx] == k
    return jnp.concatenate(
        [~present[:e], present[e:]]).astype(jnp.uint8)


@functools.lru_cache(maxsize=None)
def _jitted_step_packed_tiered(params: NeighborParams, backend: str,
                               programs: tuple | None,
                               tier_cfg: tuple | None,
                               edge_cap: int, verdicts: bool = False):
    """The step jit (plain or fused) with the edge-snapshot passes
    attached as extra outputs — still exactly one launch. Two optional
    passes ride here, in output order after the base step outputs:
    the [sync] cadence tier pass (``tier_cfg`` a (n_tiers, near, far)
    tuple; None skips it) and the fused-delivery edge-verdict pass
    (``verdicts=True``). Keyed by ``edge_cap`` (the padded edge-array
    size) ON PURPOSE: edge capacities grow in power-of-two tiers, and a
    fresh lru instance per capacity makes the growth compile a WARM
    trace on a new SentinelJit instead of a steady-state retrace on a
    hot one (telemetry/sentinel.py)."""
    if programs is None:
        if backend == "jnp":
            base = functools.partial(_step_packed_jnp, params)
        else:
            base = functools.partial(
                _step_pallas, params, backend == "pallas_interpret")
    elif backend == "jnp":
        base = functools.partial(_step_packed_fused_jnp, params, programs)
    else:
        base = functools.partial(
            _step_packed_fused_pallas, params,
            backend == "pallas_interpret", programs)
    # Offset of the CURRENT epoch's (pos, ..., radius) within the args
    # after the previous epoch's four: the pallas step additionally
    # carries 7 carried-grid artifacts first.
    off = 0 if backend == "jnp" else 7

    def fn(subj, wat, ppos, pact, pspc, prad, *rest):
        outs = base(ppos, pact, pspc, prad, *rest)
        if tier_cfg is not None:
            n_tiers, near_ratio, far_ratio = tier_cfg
            outs = outs + (_tier_pass(
                rest[off], ppos, rest[off + 3], subj, wat,
                n_tiers, near_ratio, far_ratio),)
        if verdicts:
            outs = outs + (_edge_verdicts(params, outs[2], subj, wat),)
        return outs

    label = ("aoi_step_tiered_" if tier_cfg is not None
             else "aoi_step_verdict_") + backend
    return sentinel.SentinelJit(label, jax.jit(fn))


def tier_edge_capacity(n_edges: int) -> int:
    """Padded edge-array size for ``n_edges`` live edges: power-of-two
    tiers from 256 so the tiered jit recompiles only on capacity growth
    (a handful of times over a process's life), never per edge churn."""
    cap = 256
    while cap < n_edges:
        cap *= 2
    return cap


# --- jit wrappers ------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _jitted_step_packed(params: NeighborParams, backend: str):
    if backend == "jnp":
        fn = functools.partial(_step_packed_jnp, params)
    else:
        fn = functools.partial(
            _step_pallas, params, backend == "pallas_interpret"
        )
    # NOTHING is donated. The previous-position arg used to be, but no
    # output of either step shares float32[N,2] layout, so XLA could never
    # alias it — every jit just warned "Some donated buffers were not
    # usable" (the multichip dryrun log flagged it). The carried grid
    # artifacts (pallas args 4-10) must stay undonated regardless: the
    # still-pending previous step's paging context references those exact
    # buffers; likewise the previous meta arrays (act/space/radius), which
    # with ``meta_dirty=False`` are the SAME device buffers as the current
    # epoch's meta.
    return sentinel.SentinelJit(f"aoi_step_{backend}", jax.jit(fn))


@functools.lru_cache(maxsize=None)
def _jitted_drain_ids(params: NeighborParams):
    return sentinel.SentinelJit("aoi_drain_ids", jax.jit(
        functools.partial(
            _drain_ids, n=params.capacity, max_events=params.max_events
        )
    ))


@functools.lru_cache(maxsize=None)
def _jitted_drain_bits(params: NeighborParams):
    return sentinel.SentinelJit(
        "aoi_drain_bits", jax.jit(functools.partial(_drain_bits, params)))


# --- host-facing engine ------------------------------------------------------


_async_copy_supported: dict[str, bool] = {}


def start_host_copy(arr: jax.Array) -> None:
    """Begin the device→host copy of a packed result, if the platform can.

    Capability is probed once per platform (ADVICE r2: do not classify
    JaxRuntimeError by message substring — wording drifts across jaxlib
    versions). If the probe call raises, async copies are disabled for that
    platform and the copy simply happens synchronously in ``collect()``,
    where any real device-side error surfaces on the blocking read.
    """
    try:
        platform = arr.devices().pop().platform
    except Exception:
        platform = "unknown"
    if not _async_copy_supported.get(platform, True):
        return
    try:
        arr.copy_to_host_async()
    except (NotImplementedError, jax.errors.JaxRuntimeError):
        _async_copy_supported[platform] = False


class PendingStep:
    """An in-flight tick: dispatched to the device, result not yet fetched.

    The device-to-host copy of the packed result starts immediately
    (``copy_to_host_async``); ``collect()`` blocks only on whatever is still
    outstanding. Dispatching tick t+1 before collecting tick t hides the
    fetch RTT behind compute — diffs arrive one tick late, which is the
    engine's documented delivery model anyway (batched.py docstring).
    """

    __slots__ = ("_engine", "_pager", "_out", "_collected", "fused",
                 "tiers", "verdicts", "edge_log")

    def __init__(self, engine: "NeighborEngine", pager, out) -> None:
        self._engine = engine
        self._pager = pager  # pager(which, remaining, start_flat) -> pairs
        self._out = out
        self._collected = False
        # Fused-tick payload, set by the dispatching caller when the step
        # carried entity logic: (programs, sel slot-space snapshot,
        # row→slot perm or None, device output arrays). Consumed exactly
        # once by BatchAOIService._consume_fused before the next dispatch.
        self.fused = None
        # Sync-tier payload ([sync]; set when the step carried the tier
        # pass): (edge_version snapshot, edge count, device tier array).
        # Consumed by BatchAOIService._consume_tiers before the next
        # dispatch; discarded there if the edge table churned meanwhile.
        self.tiers = None
        # Fused-delivery payload: device edge-verdict uint8[2E] array (or
        # None) and the edge delta log that was accumulating when this
        # step's snapshot was taken (aoi/batched.py _deliver_fused).
        self.verdicts = None
        self.edge_log = None
        start_host_copy(out)

    def is_ready(self) -> bool:
        """True when collect() will not block on device compute (the packed
        result is finished; the host copy may still be a memcpy away).
        Callers on a latency-critical thread — the single-threaded game loop
        — poll this to frame-skip instead of stalling (batched.py)."""
        try:
            return bool(self._out.is_ready())
        except AttributeError:  # older jax array types
            return True

    def wait_device(self) -> None:
        """Block until the device step has finished computing the packed
        result (collect() after this times only the host copy + unpack).
        Latency instrumentation seam: the BASELINE p99 diff-latency budget
        is measured from step completion to events-on-host (bench.py)."""
        jax.block_until_ready(self._out)

    def collect(self) -> tuple[np.ndarray, np.ndarray, int]:
        """Fetch (enter_pairs, leave_pairs, dropped); one blocking read."""
        assert not self._collected, "PendingStep already collected"
        self._collected = True
        eng = self._engine
        p = eng.params
        e = p.max_events
        out = np.asarray(self._out)  # THE round trip
        n_e, n_l = int(out[0, 0]), int(out[0, 1])
        dropped = int(out[1, 0])
        enter_last, leave_last = int(out[2, 0]), int(out[2, 1])
        enters = out[3:3 + min(n_e, e)]
        leaves = out[3 + e:3 + e + min(n_l, e)]
        # Storm paging (rare): the pallas drain pages by event RANK (resume
        # at e), the jnp drain by flat matrix index (resume after the last
        # drained position).
        rank_paging = eng.backend != "jnp"
        if n_e > e:
            enters = np.concatenate(
                [enters,
                 self._pager("enter", n_e - e, e if rank_paging else enter_last + 1)]
            )
        if n_l > e:
            leaves = np.concatenate(
                [leaves,
                 self._pager("leave", n_l - e, e if rank_paging else leave_last + 1)]
            )
        eng.last_grid_dropped = dropped
        if dropped:
            from goworld_tpu.utils import gwlog

            gwlog.warnf(
                "AOI grid overflow: %d active entities exceeded cell_capacity"
                "=%d and are invisible this tick; raise cell_capacity, or "
                "raise [aoi] grid/cell_size — the torus covers "
                "grid*cell_size (%.0f) world units, and a wider map FOLDS "
                "distant cells onto shared buckets",
                dropped,
                p.cell_capacity,
                p.grid_x * p.cell_size,
            )
        return enters, leaves, dropped


class NeighborEngine:
    """Stateful wrapper around the jitted step function.

    Usage (one engine per game process; all spaces batched together):

        eng = NeighborEngine(NeighborParams(capacity=1024))
        eng.reset()
        enters, leaves, dropped = eng.step(pos, active, space, radius)

    ``enters`` / ``leaves`` are numpy ``[E, 2]`` arrays of (slot, other_slot)
    pairs — the batched equivalent of the reference's OnEnterAOI/OnLeaveAOI
    callback invocations (Entity.go:227-246).

    ``backend``: "auto" picks the Pallas kernel on TPU and the jnp reference
    path elsewhere; "pallas_interpret" runs the kernel through the Pallas
    interpreter (slow — oracle tests only); "jnp" / "pallas" force a path.
    """

    def __init__(self, params: NeighborParams, backend: str = "auto"):
        if backend == "auto":
            backend = "pallas" if jax.default_backend() == "tpu" else "jnp"
        if backend not in ("jnp", "pallas", "pallas_interpret"):
            raise ValueError(f"unknown backend {backend!r}")
        if backend != "jnp" and params.cell_capacity > LANES:
            raise ValueError(
                f"pallas path supports cell_capacity <= {LANES}, "
                f"got {params.cell_capacity}"
            )
        self.params = params
        self.backend = backend
        self._jit_step = _jitted_step_packed(params, backend)
        if backend == "jnp":
            self._jit_drain = _jitted_drain_ids(params)
        else:
            self._jit_drain = _jitted_drain_bits(params)
        self._state: tuple | None = None
        self.last_grid_dropped = 0

    def reset(self) -> None:
        """Clear device state: the next step sees an all-inactive previous
        tick and emits the full enter storm (freeze/restore re-entry)."""
        n = self.params.capacity
        self._state = (
            jnp.zeros((n, 2), jnp.float32),
            jnp.zeros((n,), jnp.bool_),
            jnp.zeros((n,), jnp.int32),
            jnp.zeros((n,), jnp.float32),
        )
        if self.backend != "jnp":
            # Carried grid artifacts of the (all-inactive) previous tick:
            # sentinel table, -1 slots, all-dropped dst — exactly what
            # _build_table returns for active=False everywhere; bins and
            # order are irrelevant then.
            table_size = self.params.num_buckets * LANES
            self._state = self._state + (
                jnp.zeros((n,), jnp.int32),  # pcx
                jnp.zeros((n,), jnp.int32),  # pcz
                jnp.zeros((n,), jnp.int32),  # psm
                jnp.full((table_size,), n, jnp.int32),  # ptable
                jnp.full((n,), -1, jnp.int32),  # pslot
                jnp.arange(n, dtype=jnp.int32),  # porder
                jnp.full((n,), table_size, jnp.int32),  # pdst
            )

    def carried_epoch(self) -> tuple:
        """The last dispatched (pos, active, space, radius) as numpy in
        SLOT space — the tier-growth reseed contract every engine speaks
        (the spatial engine's device state is row-permuted, so callers
        must not peek at ``_state`` directly)."""
        assert self._state is not None, "call reset() first"
        return tuple(np.asarray(a) for a in self._state[0:4])

    def _page(self, ctx, remaining: int, start_flat: int) -> np.ndarray:
        chunks = []
        start = jnp.int32(start_flat)
        rank_paging = self.backend != "jnp"
        while remaining > 0:
            pairs, aux = self._jit_drain(*ctx, start_flat=start)
            take = min(self.params.max_events, remaining)
            chunks.append(np.asarray(pairs[:take]))
            remaining -= take
            if remaining > 0:
                start = start + take if rank_paging else aux[take - 1] + 1
        return np.concatenate(chunks)

    # The batched service may hand this engine a fused-logic payload
    # (aoi/batched.py _build_logic); sharded variants opt in separately.
    supports_fused_logic = True
    # The batched service may additionally ride the [sync] cadence tier
    # pass on the step launch (step_async tiers=); engines without it
    # fall back to the host classification in entity/slabs.py.
    supports_tier_pass = True

    def step_async(
        self,
        pos: np.ndarray,
        active: np.ndarray,
        space: np.ndarray,
        radius: np.ndarray,
        meta_dirty: bool = True,
        logic: tuple | None = None,
        tiers: tuple | None = None,
    ) -> PendingStep:
        """Dispatch one tick without blocking; collect() fetches the events.

        State advances immediately, so back-to-back step_async calls
        pipeline: tick t+1 computes while tick t's packed result is in
        flight to the host.

        ``meta_dirty=False`` asserts that active/space/radius are unchanged
        since the previous step: the device-resident copies are reused and
        only positions are uploaded (~half the per-tick host→device bytes;
        spawn/despawn/space/radius changes are rare relative to movement).

        ``logic = (programs, sel, y, yaw, dt, cols)`` fuses the per-class
        entity-logic programs into the SAME launch (see the fused-logic
        section above): the AOI diff is computed exactly as without logic,
        and the programs' outputs over the dispatched epoch ride back on
        ``pending.fused`` for the caller to write back before the next
        dispatch. ``sel`` is int32[capacity] (program index + 1, 0 = none),
        ``cols`` the flat per-program column arrays.
        """
        assert self._state is not None, "call reset() first"
        check_radius(self.params, radius, active)
        if self.backend != "jnp":
            check_space_ids(space, active)
        # jnp.array (not asarray): the arrays become next tick's PREVIOUS
        # state, so they must not alias the caller's numpy buffers — on the
        # CPU backend a zero-copy view would silently mutate history when
        # game code updates positions in place.
        if meta_dirty:
            meta = (
                jnp.array(active, jnp.bool_),
                jnp.array(space, jnp.int32),
                jnp.array(radius, jnp.float32),
            )
        else:
            meta = self._state[1:4]
        cur = (jnp.array(pos, jnp.float32),) + meta
        fused_out = None
        tier_out = None
        tier_meta = None
        extra: tuple = ()
        programs: tuple | None = None
        if logic is not None:
            programs, sel, y, yaw, dt, cols = logic
            programs = tuple(programs)
            extra = (
                jnp.array(y, jnp.float32),
                jnp.array(yaw, jnp.float32),
                jnp.array(sel, jnp.int32),
                jnp.float32(dt),
            ) + tuple(jnp.array(c) for c in cols)
        verdict_out = None
        if tiers is not None:
            # ``tiers = (edge_version, n_edges, subj_pad, wat_pad,
            # (n_tiers, near_ratio, far_ratio)[, want_verdicts])`` — the
            # [sync] cadence tier pass and/or the fused-delivery edge
            # verdict pass ride the SAME launch as the step (+ any fused
            # logic); the outputs are the step outputs plus one uint8
            # vector per requested pass. A 5-tuple is the legacy
            # tiers-only payload; the 6-tuple may set the tier config to
            # None for a verdicts-only launch.
            if len(tiers) == 5:
                t_ver, t_n, subj_pad, wat_pad, tcfg = tiers
                want_verdicts = False
            else:
                t_ver, t_n, subj_pad, wat_pad, tcfg, want_verdicts = tiers
            tier_meta = (t_ver, t_n)
            jit_tiered = _jitted_step_packed_tiered(
                self.params, self.backend, programs,
                tuple(tcfg) if tcfg is not None else None,
                len(subj_pad), want_verdicts,
            )
            outs = jit_tiered(
                jnp.array(subj_pad, jnp.int32),
                jnp.array(wat_pad, jnp.int32),
                *self._state, *cur, *extra,
            )
            if want_verdicts:
                verdict_out = outs[-1]
                outs = outs[:-1]
            if tcfg is not None:
                tier_out = outs[-1]
                outs = outs[:-1]
        elif logic is not None:
            jit_fused = _jitted_step_packed_fused(
                self.params, self.backend, programs
            )
            outs = jit_fused(*self._state, *cur, *extra)
        else:
            outs = self._jit_step(*self._state, *cur)
        if self.backend == "jnp":
            if logic is not None:
                enter_ids, leave_ids, out, fused_out = outs
            else:
                enter_ids, leave_ids, out = outs
            next_state = cur
        else:
            if logic is not None:
                enter_ctx, leave_ctx, out, next_grid, fused_out = outs
            else:
                enter_ctx, leave_ctx, out, next_grid = outs
            next_state = cur + next_grid

        if self.backend == "jnp":
            def pager(which, remaining, start):
                ids = enter_ids if which == "enter" else leave_ids
                return self._page((ids,), remaining, start)
        else:
            def pager(which, remaining, start):
                ctx = enter_ctx if which == "enter" else leave_ctx
                return self._page(ctx, remaining, start)

        self._state = next_state
        pending = PendingStep(self, pager, out)
        if fused_out is not None:
            for arr in fused_out:
                start_host_copy(arr)
            pending.fused = (tuple(logic[0]), np.asarray(logic[1]),
                             None, fused_out)
        if tier_out is not None:
            start_host_copy(tier_out)
            pending.tiers = tier_meta + (tier_out,)
        if verdict_out is not None:
            start_host_copy(verdict_out)
            pending.verdicts = verdict_out
        return pending

    def warmup_fused(self, programs: tuple, col_dtypes: tuple) -> None:
        """Compile the fused step jit for ``programs`` WITHOUT touching
        engine state: an all-zero dummy call at full capacity populates
        the lru jit cache so the first real fused dispatch (or the first
        one after a freeze→restore respawn) pays no XLA trace inside the
        game loop. ``col_dtypes`` must match the flat per-program column
        dtypes of the real calls."""
        n = self.params.capacity
        zeros = (
            jnp.zeros((n, 2), jnp.float32),
            jnp.zeros((n,), jnp.bool_),
            jnp.zeros((n,), jnp.int32),
            jnp.zeros((n,), jnp.float32),
        )
        state: tuple = zeros
        if self.backend != "jnp":
            table_size = self.params.num_buckets * LANES
            state = state + (
                jnp.zeros((n,), jnp.int32),
                jnp.zeros((n,), jnp.int32),
                jnp.zeros((n,), jnp.int32),
                jnp.full((table_size,), n, jnp.int32),
                jnp.full((n,), -1, jnp.int32),
                jnp.arange(n, dtype=jnp.int32),
                jnp.full((n,), table_size, jnp.int32),
            )
        extra = (
            jnp.zeros((n,), jnp.float32),  # y
            jnp.zeros((n,), jnp.float32),  # yaw
            jnp.zeros((n,), jnp.int32),  # sel
            jnp.float32(0.0),  # dt
        ) + tuple(jnp.zeros((n,), np.dtype(d)) for d in col_dtypes)
        jit_fused = _jitted_step_packed_fused(
            self.params, self.backend, tuple(programs)
        )
        jax.block_until_ready(jit_fused(*state, *zeros, *extra)[2])

    def warmup_tiered(self, programs: tuple | None, col_dtypes: tuple,
                      tier_cfg: tuple | None, edge_cap: int,
                      verdicts: bool = False) -> None:
        """Compile the tiered step jit (plain or fused variant) WITHOUT
        touching engine state — the warmup_fused analog for the [sync]
        tier pass. The batched service never dispatches an un-compiled
        tiered variant from the game loop (a ~seconds XLA trace there
        froze RPCs, seen live); this populates the lru cache off-thread
        or at boot."""
        n = self.params.capacity
        zeros = (
            jnp.zeros((n, 2), jnp.float32),
            jnp.zeros((n,), jnp.bool_),
            jnp.zeros((n,), jnp.int32),
            jnp.zeros((n,), jnp.float32),
        )
        state: tuple = zeros
        if self.backend != "jnp":
            table_size = self.params.num_buckets * LANES
            state = state + (
                jnp.zeros((n,), jnp.int32),
                jnp.zeros((n,), jnp.int32),
                jnp.zeros((n,), jnp.int32),
                jnp.full((table_size,), n, jnp.int32),
                jnp.full((n,), -1, jnp.int32),
                jnp.arange(n, dtype=jnp.int32),
                jnp.full((n,), table_size, jnp.int32),
            )
        extra: tuple = ()
        if programs:
            extra = (
                jnp.zeros((n,), jnp.float32),
                jnp.zeros((n,), jnp.float32),
                jnp.zeros((n,), jnp.int32),
                jnp.float32(0.0),
            ) + tuple(jnp.zeros((n,), np.dtype(d)) for d in col_dtypes)
        pads = jnp.full((edge_cap,), n, jnp.int32)
        jit_tiered = _jitted_step_packed_tiered(
            self.params, self.backend,
            tuple(programs) if programs else None,
            tuple(tier_cfg) if tier_cfg is not None else None,
            edge_cap, verdicts,
        )
        jax.block_until_ready(
            jit_tiered(pads, pads, *state, *zeros, *extra)[2])

    def fused_trace_count(self, programs: tuple) -> int:
        """Compiled-trace count of the fused step jit for ``programs`` —
        the one-launch regression gate asserts this stays at 1 across
        steady-state ticks (and across a restore after warmup_fused)."""
        jit_fused = _jitted_step_packed_fused(
            self.params, self.backend, tuple(programs)
        )
        try:
            return int(jit_fused._cache_size())
        except Exception:  # pragma: no cover - private-API drift
            return -1

    def step(
        self,
        pos: np.ndarray,
        active: np.ndarray,
        space: np.ndarray,
        radius: np.ndarray,
    ) -> tuple[np.ndarray, np.ndarray, int]:
        """Run one tick; returns (enter_pairs, leave_pairs, dropped) on host.

        One upload batch + ONE blocking readback (the packed result); event
        counts are still unbounded — a mass spawn's "enter storm" pages extra
        chunks beyond the inline max_events.
        """
        return self.step_async(pos, active, space, radius).collect()


def check_space_ids(space: np.ndarray, active: np.ndarray) -> None:
    """The Pallas path carries space ids as f32 cell features; ids >= 2^24
    lose integer precision and distinct spaces could silently compare equal
    (cross-space enter events — ADVICE r2). Reject them loudly."""
    s = np.asarray(space)
    a = np.asarray(active)
    if a.any() and int(s[a].max()) >= (1 << 24):
        raise ValueError(
            f"space id {int(s[a].max())} not exactly representable as f32 "
            f"(>= 2^24); the pallas backend requires space ids < {1 << 24}"
        )


def check_radius(params: NeighborParams, radius: np.ndarray, active: np.ndarray) -> None:
    """The 3x3 cell gather only covers AOI distance <= cell_size: a larger
    radius would silently miss true neighbors, so reject it loudly."""
    r = np.asarray(radius)
    a = np.asarray(active)
    if a.any() and float(r[a].max()) > params.cell_size:
        raise ValueError(
            f"AOI radius {float(r[a].max())} exceeds cell_size "
            f"{params.cell_size}; enlarge cell_size (it must be >= "
            f"the maximum AOI distance)"
        )

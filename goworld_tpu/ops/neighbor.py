"""Batched AOI neighbor engine — the TPU-native hot loop.

What the reference does per entity move (Space.go:253-261 → go-aoi
``Moved(aoi, x, z)`` → synchronous OnEnterAOI/OnLeaveAOI callbacks), this
engine does for *all* entities of *all* spaces in one jitted launch per tick:

1. **Spatial hash grid build** — entities are binned into grid cells of side
   ``cell_size`` (= max AOI distance). Static shapes throughout: the grid is a
   ``[space_slots * grid_z * grid_x, cell_capacity]`` table of entity slots,
   built with a sort + rank-within-cell + scatter (no data-dependent shapes,
   XLA-friendly).
2. **Candidate gather** — each entity reads the 3×3 neighborhood of its cell:
   ``9 * cell_capacity`` candidate slots. Cell coords wrap modulo the grid
   (torus); false adjacencies from wrap/space folding are removed by the
   distance and space-id masks, so correctness never depends on grid extents.
3. **Neighbor set** — the K lowest-id candidates within radius form the
   entity's interest set, as a sorted, ``capacity``-padded id list. Sorted
   fixed-K lists make set-diff a vectorized searchsorted, and make results
   deterministic (ties cannot occur: ids are unique).
4. **Diff** — enter = in new set but not old, leave = in old but not new.
   Diffs are compacted on-device into a ``[max_events, 2]`` pair list so the
   host readback is O(events), not O(N·K).

The engine is a pure function of (previous neighbor state, current positions);
the stateful wrapper just carries the device arrays. Statelessness per tick is
what keeps freeze/restore and migration semantics intact (SURVEY.md §5.8): on
restart the host simply re-uploads positions.

Asymmetric interest (per-entity radius) is supported — a superset of the
reference's single uniform distance per AOIManager (go-aoi limitation noted in
reference TODO.md:17).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class NeighborParams:
    """Static configuration of a neighbor engine (shapes are compiled in)."""

    capacity: int = 16384  # max entity slots (N)
    max_neighbors: int = 128  # K: interest-set capacity per entity
    cell_size: float = 100.0  # grid cell side; must be >= max AOI distance
    grid_x: int = 64  # grid extent in cells (wraps modulo)
    grid_z: int = 64
    space_slots: int = 8  # space-id folding slots for the shared grid
    cell_capacity: int = 64  # M: max entities stored per grid cell
    max_events: int = 65536  # enter/leave pairs fetched per host round trip

    def __post_init__(self) -> None:
        if self.grid_x < 4 or self.grid_z < 4:
            # 3x3 neighborhoods must touch 9 distinct buckets after wrap.
            raise ValueError("grid_x and grid_z must be >= 4")
        if self.capacity % 8 != 0:
            raise ValueError("capacity must be a multiple of 8 (TPU sublanes)")

    @property
    def num_buckets(self) -> int:
        return self.space_slots * self.grid_z * self.grid_x


class MatrixStepResult(NamedTuple):
    """Step output with device-resident event matrices (drained in chunks)."""

    neighbors: jax.Array  # i32[N, K]
    enter_ids: jax.Array  # i32[N, K]: other-id where entered, else sentinel N
    leave_ids: jax.Array  # i32[N, K]: other-id where left, else sentinel N
    n_enters: jax.Array  # i32[] total enter events
    n_leaves: jax.Array  # i32[] total leave events
    overflow: jax.Array  # i32[] entities whose true neighbor count exceeded K
    grid_dropped: jax.Array  # i32[] active entities not inserted in the grid


def _bucket_of(p: NeighborParams, cx: jax.Array, cz: jax.Array, space: jax.Array) -> jax.Array:
    """Fold (cell_x, cell_z, space_id) into a grid bucket index (torus wrap)."""
    cxm = jnp.mod(cx, p.grid_x)
    czm = jnp.mod(cz, p.grid_z)
    sm = jnp.mod(space, p.space_slots)
    return (sm * p.grid_z + czm) * p.grid_x + cxm


def _build_grid(
    p: NeighborParams, bucket: jax.Array, active: jax.Array
) -> tuple[jax.Array, jax.Array]:
    """Scatter entity slots into the [num_buckets * M] grid table.

    Rank-within-bucket is derived from a stable sort: after sorting slots by
    bucket id, an entity's rank is its position minus the first position of
    its bucket. Entities beyond ``cell_capacity`` in a cell are dropped from
    the grid (they still *query*, so they receive neighbors; they are just
    invisible to others this tick). Returns (grid, dropped_count) so callers
    can alert operators to size cell_capacity / space_slots properly.
    """
    n = p.capacity
    # Inactive entities sort to the end with an out-of-range bucket.
    key = jnp.where(active, bucket, p.num_buckets)
    order = jnp.argsort(key)  # stable
    sorted_key = key[order]
    first_pos = jnp.searchsorted(sorted_key, sorted_key, side="left")
    rank = jnp.arange(n, dtype=jnp.int32) - first_pos.astype(jnp.int32)
    ok = (sorted_key < p.num_buckets) & (rank < p.cell_capacity)
    dropped = jnp.sum((sorted_key < p.num_buckets) & ~ok).astype(jnp.int32)
    table_size = p.num_buckets * p.cell_capacity
    # Out-of-range index + mode="drop" discards non-ok writes.
    flat_idx = jnp.where(ok, sorted_key * p.cell_capacity + rank, table_size)
    grid = jnp.full((table_size,), n, dtype=jnp.int32)
    grid = grid.at[flat_idx].set(order.astype(jnp.int32), mode="drop")
    return grid, dropped


def _neighbor_sets(
    p: NeighborParams,
    grid: jax.Array,
    pos: jax.Array,  # f32[N,2] global positions
    active: jax.Array,  # bool[N] global
    space: jax.Array,  # i32[N] global
    q_ids: jax.Array,  # i32[Q] global slot ids of the query entities
    q_pos: jax.Array,  # f32[Q,2]
    q_active: jax.Array,  # bool[Q]
    q_space: jax.Array,  # i32[Q]
    q_radius: jax.Array,  # f32[Q]
) -> tuple[jax.Array, jax.Array]:
    """Compute sorted fixed-K neighbor id lists for the Q query entities
    against the full (possibly all-gathered) world.

    Single-device: Q == N and q_ids == arange(N). Sharded: each device passes
    only the slots it owns (SURVEY.md §2.9: entity-sharded global query).
    """
    n, k, m = p.capacity, p.max_neighbors, p.cell_capacity

    q_cx = jnp.floor(q_pos[:, 0] / p.cell_size).astype(jnp.int32)
    q_cz = jnp.floor(q_pos[:, 1] / p.cell_size).astype(jnp.int32)

    # Gather 3x3 cell neighborhoods → candidate slot ids [Q, 9*M].
    offsets = [(dx, dz) for dz in (-1, 0, 1) for dx in (-1, 0, 1)]
    cand_parts = []
    for dx, dz in offsets:
        b = _bucket_of(p, q_cx + dx, q_cz + dz, q_space)  # [Q]
        base = b * m
        idx = base[:, None] + jnp.arange(m, dtype=jnp.int32)[None, :]  # [Q, M]
        cand_parts.append(grid[idx])
    cand = jnp.concatenate(cand_parts, axis=1)  # [Q, 9M]

    cand_safe = jnp.minimum(cand, n - 1)  # safe gather index for sentinel rows
    # Gather x and z separately: a trailing dim of 2 would be padded to 128
    # lanes by TPU tiling (64x memory blowup on the [Q, 9M] intermediates).
    dx = pos[:, 0][cand_safe] - q_pos[:, 0][:, None]  # [Q, 9M]
    dz = pos[:, 1][cand_safe] - q_pos[:, 1][:, None]
    d2 = dx * dx + dz * dz
    r2 = (q_radius * q_radius)[:, None]

    valid = (
        (cand < n)
        & (cand != q_ids[:, None])
        & q_active[:, None]
        & active[cand_safe]
        & (space[cand_safe] == q_space[:, None])
        & (d2 <= r2)
    )
    # True neighbor degree (before K-truncation) for overflow accounting.
    degree = jnp.sum(valid, axis=1)

    # K lowest ids among valid candidates; sentinel n pads the tail. A cell
    # neighborhood holds at most 9*M candidates, so clamp the top_k width and
    # pad the remaining columns with the sentinel.
    keys = jnp.where(valid, cand, n)
    kk = min(k, 9 * m)
    neg_topk, _ = jax.lax.top_k(-keys, kk)  # top_k of negated → kk smallest
    neighbors = -neg_topk  # ascending, padded with n
    if kk < k:
        pad = jnp.full((neighbors.shape[0], k - kk), n, neighbors.dtype)
        neighbors = jnp.concatenate([neighbors, pad], axis=1)
    overflow = jnp.sum(degree > k)
    return neighbors.astype(jnp.int32), overflow.astype(jnp.int32)


def _row_membership(sorted_ref: jax.Array, queries: jax.Array, sentinel: int) -> jax.Array:
    """For each row: is queries[i,j] present in sorted_ref[i,:]? (vectorized)"""

    def one_row(ref_row, q_row):
        pos = jnp.searchsorted(ref_row, q_row)
        pos = jnp.minimum(pos, ref_row.shape[0] - 1)
        return (ref_row[pos] == q_row) & (q_row < sentinel)

    return jax.vmap(one_row)(sorted_ref, queries)


def _step(
    p: NeighborParams,
    prev_neighbors: jax.Array,
    pos: jax.Array,
    active: jax.Array,
    space: jax.Array,
    radius: jax.Array,
) -> MatrixStepResult:
    n = p.capacity
    cx = jnp.floor(pos[:, 0] / p.cell_size).astype(jnp.int32)
    cz = jnp.floor(pos[:, 1] / p.cell_size).astype(jnp.int32)
    bucket = _bucket_of(p, cx, cz, space)

    grid, grid_dropped = _build_grid(p, bucket, active)
    q_ids = jnp.arange(n, dtype=jnp.int32)
    neighbors, overflow = _neighbor_sets(
        p, grid, pos, active, space, q_ids, pos, active, space, radius
    )

    entered = ~_row_membership(prev_neighbors, neighbors, n) & (neighbors < n)
    left = ~_row_membership(neighbors, prev_neighbors, n) & (prev_neighbors < n)

    enter_ids = jnp.where(entered, neighbors, n)
    leave_ids = jnp.where(left, prev_neighbors, n)
    n_enters = jnp.sum(entered).astype(jnp.int32)
    n_leaves = jnp.sum(left).astype(jnp.int32)
    return MatrixStepResult(
        neighbors, enter_ids, leave_ids, n_enters, n_leaves, overflow, grid_dropped
    )


def _drain(
    p: NeighborParams, ids: jax.Array, start_flat: jax.Array
) -> tuple[jax.Array, jax.Array]:
    """Compact one chunk of events from an id matrix.

    ``ids`` is i32[N,K] with sentinel N in non-event slots. Returns
    (pairs i32[max_events, 2], flat_positions i32[max_events]) for the first
    ``max_events`` events at flat index >= start_flat. Host pages through by
    passing last_flat+1 as the next start.
    """
    n, k = p.capacity, p.max_neighbors
    total = n * k
    flat = ids.reshape(-1)
    mask = (flat < n) & (jnp.arange(total, dtype=jnp.int32) >= start_flat)
    (idx,) = jnp.nonzero(mask, size=p.max_events, fill_value=total)
    idx = idx.astype(jnp.int32)
    valid = idx < total
    safe = jnp.minimum(idx, total - 1)
    ent = jnp.where(valid, safe // k, n)
    oth = jnp.where(valid, flat[safe], n)
    return jnp.stack([ent, oth], axis=1), idx


def _step_packed(
    p: NeighborParams,
    prev_neighbors: jax.Array,
    pos: jax.Array,
    active: jax.Array,
    space: jax.Array,
    radius: jax.Array,
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """One tick, with everything the host needs packed into ONE array.

    Host↔device round trips are the latency budget (a blocking fetch costs a
    full RTT — ~100 ms through a tunneled chip, ~100 µs locally), so the step
    emits a single i32 ``out`` of shape [3 + 2*max_events, 2]:

        out[0] = (n_enters, n_leaves)          total event counts
        out[1] = (overflow, grid_dropped)      diagnostics
        out[2] = (enter_last_flat, leave_last_flat)  resume cursors
        out[3          : 3+E]  = first E enter pairs (slot, other)
        out[3+E : 3+2E]        = first E leave pairs

    One ``np.asarray(out)`` per tick replaces the previous design's ~6
    separate scalar/array fetches. If a tick produces more than E events
    (mass spawns), the host pages the remainder from the returned
    ``enter_ids``/``leave_ids`` matrices starting at the resume cursors.
    """
    res = _step(p, prev_neighbors, pos, active, space, radius)
    e = p.max_events
    enter_pairs, enter_idx = _drain(p, res.enter_ids, jnp.int32(0))
    leave_pairs, leave_idx = _drain(p, res.leave_ids, jnp.int32(0))
    header = jnp.stack(
        [
            jnp.stack([res.n_enters, res.n_leaves]),
            jnp.stack([res.overflow, res.grid_dropped]),
            jnp.stack([enter_idx[e - 1], leave_idx[e - 1]]),
        ]
    ).astype(jnp.int32)
    out = jnp.concatenate([header, enter_pairs, leave_pairs], axis=0)
    return res.neighbors, res.enter_ids, res.leave_ids, out


@functools.lru_cache(maxsize=None)
def _jitted_step(params: NeighborParams):
    """One compiled step per distinct NeighborParams (shared across engines)."""
    return jax.jit(functools.partial(_step, params), donate_argnums=(0,))


@functools.lru_cache(maxsize=None)
def _jitted_step_packed(params: NeighborParams):
    return jax.jit(functools.partial(_step_packed, params), donate_argnums=(0,))


@functools.lru_cache(maxsize=None)
def _jitted_drain(params: NeighborParams):
    return jax.jit(functools.partial(_drain, params))


class PendingStep:
    """An in-flight tick: dispatched to the device, result not yet fetched.

    The device-to-host copy of the packed result starts immediately
    (``copy_to_host_async``); ``collect()`` blocks only on whatever is still
    outstanding. Dispatching tick t+1 before collecting tick t hides the
    fetch RTT behind compute — diffs arrive one tick late, which is the
    engine's documented delivery model anyway (batched.py docstring).
    """

    __slots__ = ("_engine", "_enter_ids", "_leave_ids", "_out", "_collected")

    def __init__(self, engine: "NeighborEngine", enter_ids, leave_ids, out) -> None:
        self._engine = engine
        self._enter_ids = enter_ids
        self._leave_ids = leave_ids
        self._out = out
        self._collected = False
        try:
            out.copy_to_host_async()
        except NotImplementedError:
            pass  # platforms without async host copies just block in collect()
        except jax.errors.JaxRuntimeError as err:
            # Only "unimplemented on this platform" may be deferred to
            # collect(); a real device-side failure must surface here, not be
            # misattributed to the later blocking fetch.
            if "unimplemented" not in str(err).lower():
                raise

    def collect(self) -> tuple[np.ndarray, np.ndarray, int]:
        """Fetch (enter_pairs, leave_pairs, overflow); one blocking read."""
        assert not self._collected, "PendingStep already collected"
        self._collected = True
        eng = self._engine
        p = eng.params
        e = p.max_events
        out = np.asarray(self._out)  # THE round trip
        n_e, n_l = int(out[0, 0]), int(out[0, 1])
        overflow, dropped = int(out[1, 0]), int(out[1, 1])
        enter_last, leave_last = int(out[2, 0]), int(out[2, 1])
        enters = out[3:3 + min(n_e, e)]
        leaves = out[3 + e:3 + e + min(n_l, e)]
        if n_e > e:  # mass-spawn storm: page the rest (rare)
            more = eng._page_events(self._enter_ids, n_e - e, enter_last + 1)
            enters = np.concatenate([enters, more])
        if n_l > e:
            more = eng._page_events(self._leave_ids, n_l - e, leave_last + 1)
            leaves = np.concatenate([leaves, more])
        eng.last_overflow = overflow
        eng.last_grid_dropped = dropped
        if dropped:
            from goworld_tpu.utils import gwlog

            gwlog.warnf(
                "AOI grid overflow: %d active entities exceeded cell_capacity=%d "
                "and are invisible to neighbors this tick; raise cell_capacity "
                "or space_slots/grid size",
                dropped,
                p.cell_capacity,
            )
        return enters, leaves, overflow


class NeighborEngine:
    """Stateful wrapper around the jitted step function.

    Usage (one engine per game process; all spaces batched together):

        eng = NeighborEngine(NeighborParams(capacity=1024))
        eng.reset()
        enters, leaves = eng.step(pos, active, space, radius)

    ``enters`` / ``leaves`` are numpy ``[E, 2]`` arrays of (slot, other_slot)
    pairs — the batched equivalent of the reference's OnEnterAOI/OnLeaveAOI
    callback invocations (Entity.go:227-246).
    """

    def __init__(self, params: NeighborParams, device: jax.Device | None = None):
        self.params = params
        self.device = device
        self._jit_step = _jitted_step(params)
        self._jit_step_packed = _jitted_step_packed(params)
        self._jit_drain = _jitted_drain(params)
        self._neighbors: jax.Array | None = None
        # Diagnostics from the latest step() (see MatrixStepResult).
        self.last_grid_dropped = 0
        self.last_overflow = 0

    def reset(self) -> None:
        n, k = self.params.capacity, self.params.max_neighbors
        arr = jnp.full((n, k), n, dtype=jnp.int32)
        if self.device is not None:
            arr = jax.device_put(arr, self.device)
        self._neighbors = arr

    @property
    def neighbors(self) -> jax.Array:
        assert self._neighbors is not None, "call reset() first"
        return self._neighbors

    def step_device(self, pos, active, space, radius) -> MatrixStepResult:
        """Run one tick; returns device arrays (no host sync)."""
        assert self._neighbors is not None, "call reset() first"
        res = self._jit_step(self._neighbors, pos, active, space, radius)
        self._neighbors = res.neighbors
        return res

    def _page_events(self, ids: jax.Array, remaining: int, start_flat: int = 0) -> np.ndarray:
        """Page events out of an id matrix in max_events-sized chunks,
        starting at flat index ``start_flat`` (used for the overflow tail
        beyond the packed result's inline buffer)."""
        if remaining <= 0:
            return np.empty((0, 2), np.int32)
        chunks = []
        start = jnp.int32(start_flat)
        while remaining > 0:
            pairs, idx = self._jit_drain(ids, start)
            take = min(self.params.max_events, remaining)
            chunks.append(np.asarray(pairs[:take]))
            remaining -= take
            if remaining > 0:
                start = idx[take - 1] + 1
        return np.concatenate(chunks)

    def step_async(
        self,
        pos: np.ndarray,
        active: np.ndarray,
        space: np.ndarray,
        radius: np.ndarray,
    ) -> PendingStep:
        """Dispatch one tick without blocking; collect() fetches the events.

        The neighbor state advances immediately, so back-to-back step_async
        calls pipeline: tick t+1 computes while tick t's packed result is in
        flight to the host.
        """
        assert self._neighbors is not None, "call reset() first"
        self._check_radius(radius, active)
        neighbors, enter_ids, leave_ids, out = self._jit_step_packed(
            self._neighbors,
            jnp.asarray(pos, jnp.float32),
            jnp.asarray(active, jnp.bool_),
            jnp.asarray(space, jnp.int32),
            jnp.asarray(radius, jnp.float32),
        )
        self._neighbors = neighbors
        return PendingStep(self, enter_ids, leave_ids, out)

    def step(
        self,
        pos: np.ndarray,
        active: np.ndarray,
        space: np.ndarray,
        radius: np.ndarray,
    ) -> tuple[np.ndarray, np.ndarray, int]:
        """Run one tick; returns (enter_pairs, leave_pairs, overflow) on host.

        One upload batch + ONE blocking readback (the packed result); event
        counts are still unbounded — a mass spawn's "enter storm" pages extra
        chunks beyond the inline max_events.
        """
        return self.step_async(pos, active, space, radius).collect()

    def _check_radius(self, radius: np.ndarray, active: np.ndarray) -> None:
        check_radius(self.params, radius, active)


def check_radius(params: NeighborParams, radius: np.ndarray, active: np.ndarray) -> None:
    """The 3x3 cell gather only covers AOI distance <= cell_size: a larger
    radius would silently miss true neighbors, so reject it loudly."""
    r = np.asarray(radius)
    a = np.asarray(active)
    if a.any() and float(r[a].max()) > params.cell_size:
        raise ValueError(
            f"AOI radius {float(r[a].max())} exceeds cell_size "
            f"{params.cell_size}; enlarge cell_size (it must be >= "
            f"the maximum AOI distance)"
        )

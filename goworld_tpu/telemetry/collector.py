"""Cluster telemetry collector: every process's snapshot behind ONE URL.

Each goworld_tpu process already serves rich *local* telemetry on its
debug port (``/healthz``, ``/metrics``, ``/trace``, ``/flight``) — but an
operator of a 2-dispatcher / 2-game / 2-gate deployment had to poll six
ports and merge by hand. This module is the single pane of glass: a
:class:`ClusterCollector` (hosted by the **driver dispatcher**, the same
process that plans rebalancing) periodically fetches one compact snapshot
per process and serves the aggregate as ``GET /cluster`` on its own debug
port, which ``python -m goworld_tpu.tools.gwtop`` renders live.

Design choice — **loopback scrape**, not a pushed MsgType (README
"Cluster observability" states the full argument): dispatchers do not
interconnect, so a pushed snapshot from dispatcher 2 has no wire path to
the driver's collector, while a scrape covers all three process kinds
with one code path; the per-process endpoints stay authoritative (the
``/cluster`` row is literally the process's own ``/snapshot``, seconds
old); zero bytes ride the cluster links and no PROTO_VERSION bump is
needed; and the deployment is already enumerable from the shared ini —
tools/tracecat.py scrapes ``/trace`` from the same addresses. The
trade-off is that the collector must reach each ``http_addr`` (loopback
on the single-host deployments this repo targets; front multi-host runs
with a tunnel, noted in the README).

Transport is pluggable: production targets fetch
``http://<http_addr>/snapshot``; the in-process chaos harness hands the
collector direct callables over its service objects, so scenario
recovery is judged from the *aggregated* view with the same summary code
paths production uses.
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import Any, Awaitable, Callable, Optional

from goworld_tpu.telemetry.metrics import REGISTRY

#: Snapshot fetcher for one process: returns the /snapshot-shaped dict
#: ({"health": ..., "metrics": ...}) or raises.
Fetch = Callable[[], Awaitable[dict[str, Any]]]

#: Metric families worth shipping in the per-process snapshot row — the
#: cluster plane's working set, not the full exposition (that stays on
#: the per-process /metrics).
SNAPSHOT_FAMILY_PREFIXES: tuple[str, ...] = (
    "game_tick_phase_seconds",
    "game_entities",
    "aoi_",
    "jit_",
    "dispatcher_",
    "gate_",
    "cluster_",
    "rebalance_",
    "sync_",
    "chaos_recovery_seconds",
    "net_packets_total",
    "net_bytes_total",
    "history_",
)


def selected_metrics(snapshot: dict[str, Any]) -> dict[str, Any]:
    """The cluster-plane subset of a registry snapshot (series only)."""
    out: dict[str, Any] = {}
    for name, fam in snapshot.items():
        if name.startswith(SNAPSHOT_FAMILY_PREFIXES):
            out[name] = {"type": fam["type"], "series": fam["series"]}
    return out


def build_local_snapshot() -> dict[str, Any]:
    """THIS process's observability row (the ``GET /snapshot`` payload):
    its /healthz object plus the cluster-plane metric families."""
    from goworld_tpu.utils import debug_http

    return {
        "health": debug_http.health_snapshot(),
        "metrics": selected_metrics(REGISTRY.snapshot()),
    }


async def http_fetch_json(addr: str, path: str,
                          timeout: float = 2.0) -> dict[str, Any]:
    """Minimal asyncio HTTP/1.1 GET of a JSON body from ``host:port``
    (the debug servers speak exactly this; no external HTTP client in
    the image's async stack)."""
    host, _, port_s = addr.rpartition(":")
    reader, writer = await asyncio.wait_for(
        asyncio.open_connection(host or "127.0.0.1", int(port_s)),
        timeout=timeout)
    try:
        writer.write(
            f"GET {path} HTTP/1.1\r\nHost: {addr}\r\n"
            f"Connection: close\r\n\r\n".encode())
        await writer.drain()
        raw = await asyncio.wait_for(reader.read(), timeout=timeout)
    finally:
        writer.close()
        try:
            await writer.wait_closed()
        except Exception:
            pass
    head, _, body = raw.partition(b"\r\n\r\n")
    status_line = head.split(b"\r\n", 1)[0].decode(errors="replace")
    parts = status_line.split()
    if len(parts) < 2 or parts[1] != "200":
        raise ValueError(f"{addr}{path}: {status_line}")
    return dict(json.loads(body))


def http_target(name: str, http_addr: str,
                timeout: float = 2.0) -> tuple[str, Fetch]:
    async def fetch() -> dict[str, Any]:
        return await http_fetch_json(http_addr, "/snapshot", timeout)

    return (name, fetch)


def http_targets_from_config(cfg: Any) -> list[tuple[str, Fetch]]:
    """(name, fetch) for every configured process with an ``http_addr``
    — the same deployment enumeration tools/tracecat.py scrapes."""
    out: list[tuple[str, Fetch]] = []
    for i, d in sorted(cfg.dispatchers.items()):
        if d.http_addr:
            out.append(http_target(f"dispatcher{i}", d.http_addr))
    for i, g in sorted(cfg.games.items()):
        if g.http_addr:
            out.append(http_target(f"game{i}", g.http_addr))
    for i, g in sorted(cfg.gates.items()):
        if g.http_addr:
            out.append(http_target(f"gate{i}", g.http_addr))
    return out


def _series_sum(metrics: dict[str, Any], family: str,
                label: Optional[str] = None,
                value: Optional[str] = None) -> float:
    fam = metrics.get(family)
    if not fam:
        return 0.0
    total = 0.0
    for s in fam["series"]:
        if label is not None and s["labels"].get(label) != value:
            continue
        total += float(s.get("value", 0.0))
    return total


class ClusterCollector:
    """Periodic scrape of every target + the aggregate ``view()``.

    A target that errors or goes silent keeps its LAST snapshot with
    ``ok: false`` and the error string — a crashed game must show up as
    a red row holding its final state, not vanish from the pane.
    """

    def __init__(self, targets: list[tuple[str, Fetch]],
                 interval: float = 1.0,
                 stale_after: Optional[float] = None,
                 slo: Any = None) -> None:
        self.interval = max(0.05, float(interval))
        # A row older than this is stale even if the fetch "worked"
        # (default: three scrape cycles, mirroring [rebalance]
        # stale_after's relationship to report_interval).
        self.stale_after = (3.0 * self.interval if stale_after is None
                            else float(stale_after))
        self._targets = list(targets)
        self._rows: dict[str, dict[str, Any]] = {}
        self._task: Optional[asyncio.Task[None]] = None
        self._polls = 0
        # SLO plane (telemetry/slo.py): judged once per poll so the burn
        # windows advance at scrape cadence, not reader cadence.
        self._judge = None
        if slo is not None and slo.enabled():
            from goworld_tpu.telemetry.slo import SLOJudge
            self._judge = SLOJudge(slo)

    # --- lifecycle ----------------------------------------------------------

    async def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._loop())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass
            self._task = None

    async def _loop(self) -> None:
        while True:
            try:
                await self.poll_once()
            except Exception:  # a scrape round must never kill the loop
                pass
            await asyncio.sleep(self.interval)

    async def poll_once(self) -> None:
        """One scrape round: all targets concurrently, per-target errors
        captured into the row (never raised)."""
        self._polls += 1
        results = await asyncio.gather(
            *(self._fetch_one(name, fetch) for name, fetch in self._targets)
        )
        for name, row in results:
            if row.get("snapshot") is None and name in self._rows:
                # keep the last good snapshot under the error marker
                prev = self._rows[name]
                row["snapshot"] = prev.get("snapshot")
                row["fetched_at"] = prev.get("fetched_at", 0.0)
            self._rows[name] = row
        if self._judge is not None:
            self._judge.judge_poll(self._process_rows())

    async def _fetch_one(self, name: str,
                         fetch: Fetch) -> tuple[str, dict[str, Any]]:
        try:
            snap = await fetch()
            return (name, {"snapshot": snap, "error": None,
                           "fetched_at": time.monotonic()})
        except Exception as exc:
            return (name, {"snapshot": None,
                           "error": f"{type(exc).__name__}: {exc}",
                           "fetched_at": 0.0})

    # --- the aggregate view -------------------------------------------------

    def _process_rows(self) -> dict[str, dict[str, Any]]:
        now = time.monotonic()
        processes: dict[str, dict[str, Any]] = {}
        for name, raw in sorted(self._rows.items()):
            snap = raw.get("snapshot") or {}
            fetched = float(raw.get("fetched_at") or 0.0)
            age = round(now - fetched, 3) if fetched else None
            ok = (raw.get("error") is None and age is not None
                  and age <= self.stale_after)
            processes[name] = {
                "ok": ok,
                "age_s": age,
                "error": raw.get("error"),
                "health": snap.get("health") or {},
                "metrics": snap.get("metrics") or {},
            }
        return processes

    def view(self) -> dict[str, Any]:
        """The ``GET /cluster`` object: one row per process + a cluster
        summary (census conservation, generation consistency, migration
        and retrace counters, alerts). Built on demand — the reader pays,
        the scrape loop just stores."""
        processes = self._process_rows()
        summary = summarize(processes)
        if self._judge is not None:
            summary["slo"] = self._judge.summary()
            summary["alerts"].extend(self._judge.alerts())
        return {
            "collector": {
                "interval_s": self.interval,
                "stale_after_s": self.stale_after,
                "polls": self._polls,
                "targets": len(self._targets),
                "ts": time.time(),
            },
            "processes": processes,
            "summary": summary,
        }


def summarize(processes: dict[str, dict[str, Any]]) -> dict[str, Any]:
    """Cluster-level invariants from the per-process rows.

    - **census**: clients bound on games vs clients connected on gates —
      a real cross-process conservation law (every connected client has
      exactly one avatar binding), judged from the aggregated view.
    - **generations**: every gate names its boot generation; any game
      client-binding or dispatcher gate-registration carrying a DIFFERENT
      generation for that gate is a stale row (a dead incarnation's
      binding that should have been detached).
    - **counters**: migrations routed/bounced/cancelled, steady-state
      retraces, chaos recoveries — summed across rows.
    """
    reporting = [n for n, r in processes.items() if r["ok"]]
    down = [n for n, r in processes.items() if not r["ok"]]
    game_entities = 0
    game_clients = 0
    gate_clients = 0
    gate_gens: dict[str, int] = {}
    stale_gens: list[dict[str, Any]] = []
    migrates = {"routed": 0.0, "bounced": 0.0, "cancel": 0.0}
    retraces = 0.0
    fused_classes = 0.0
    fused_slots = 0.0
    # Device-resident tick (ISSUE 19): the delivery-class split and the
    # host wall-clock the fused decode / columnar persist shrink.
    fused_delivery_classes = 0.0
    host_fallback_classes = 0.0
    host_phase = {"delivery": 0.0, "persist": 0.0}
    # Rebalance plane (ISSUE 18): planner host + pause/failover state for
    # the /cluster REBAL view and its alerts.
    space_outcomes = {"done": 0.0, "aborted": 0.0, "timeout": 0.0,
                      "rolled_back": 0.0}
    paused_reasons = {"paused_stale": 0.0, "paused_links": 0.0,
                      "paused_few": 0.0}
    spaces_in_flight = 0.0
    space_handoffs_parked = 0
    # Device comms (ROADMAP item 5): per-link halo / allgather bytes
    # rolled up by tier for the /cluster summary (the per-link series
    # stay on each row's metrics and in its history frames).
    comms_tiers: dict[str, float] = {}
    comms_links: set = set()
    planner_host = None
    planner_last = None
    planner_service = False
    rebalance_enabled = False
    for name, row in processes.items():
        h = row["health"]
        kind = h.get("kind")
        if kind == "game":
            game_entities += int(h.get("entities", 0))
            game_clients += int(h.get("clients", 0))
            ps = h.get("rebalance_planner")
            if ps:
                # This game hosts the sharded planner service right now.
                planner_host = name
                planner_last = ps.get("last_result")
        elif kind == "dispatcher":
            rb = h.get("rebalance") or {}
            rebalance_enabled = rebalance_enabled or bool(rb.get("enabled"))
            planner_service = planner_service or bool(
                rb.get("planner_service"))
            space_handoffs_parked += int(rb.get("space_handoffs", 0))
            if rb.get("driver") and not rb.get("planner_service"):
                planner_host = name
                planner_last = rb.get("last_result")
        elif kind == "gate":
            gate_clients += int(h.get("clients", 0))
            gen = h.get("generation")
            if gen is not None:
                gate_gens[str(h.get("id"))] = int(gen)
        m = row["metrics"]
        for outcome in migrates:
            migrates[outcome] += _series_sum(
                m, "dispatcher_migrates_total", "kind", outcome)
        retraces += _series_sum(m, "jit_retrace_events_total")
        fused_classes = max(fused_classes,
                            _series_sum(m, "aoi_fused_classes"))
        fused_slots = max(fused_slots, _series_sum(m, "aoi_fused_slots"))
        fused_delivery_classes = max(
            fused_delivery_classes,
            _series_sum(m, "aoi_fused_delivery_classes"))
        host_fallback_classes = max(
            host_fallback_classes,
            _series_sum(m, "aoi_host_fallback_classes"))
        for ph in host_phase:
            host_phase[ph] += _series_sum(
                m, "aoi_host_phase_seconds_total", "phase", ph)
        for outcome in space_outcomes:
            space_outcomes[outcome] += _series_sum(
                m, "rebalance_space_migrations_total", "outcome", outcome)
        link_fam = m.get("aoi_link_bytes_total")
        if link_fam:
            for s in link_fam.get("series", []):
                tier = s.get("labels", {}).get("tier", "")
                comms_tiers[tier] = (comms_tiers.get(tier, 0.0)
                                     + float(s.get("value", 0.0)))
                comms_links.add((tier, s.get("labels", {}).get("link", "")))
        for reason in paused_reasons:
            paused_reasons[reason] += _series_sum(
                m, "rebalance_plans_total", "result", reason)
        spaces_in_flight += _series_sum(m, "rebalance_spaces_in_flight")
        if (planner_host is None
                and _series_sum(m, "rebalance_planner_host") >= 1.0):
            # Gauge fallback for hosts whose healthz row predates the
            # rebalance_planner field (or non-game scrapes).
            planner_host = name
    # Generation consistency: compare every binding against the gate's
    # own announced generation (only for gates that are reporting).
    for name, row in processes.items():
        h = row["health"]
        if h.get("kind") == "game":
            for gid, gens in (h.get("client_gate_gens") or {}).items():
                want = gate_gens.get(str(gid))
                for g in gens:
                    # gen 0 = pre-generation binding (legacy path): unknown,
                    # not stale — only a DIFFERENT nonzero generation is.
                    if want is not None and int(g) != 0 and int(g) != want:
                        stale_gens.append({
                            "where": name, "gate": gid,
                            "bound_gen": int(g), "gate_gen": want})
        elif h.get("kind") == "dispatcher":
            for gid, info in (h.get("gates") or {}).items():
                want = gate_gens.get(str(gid))
                got = info.get("gen")
                if (want is not None and got is not None and int(got) != 0
                        and int(got) != want and info.get("connected")):
                    stale_gens.append({
                        "where": name, "gate": gid,
                        "bound_gen": int(got), "gate_gen": want})
    clients_conserved = game_clients == gate_clients
    alerts: list[str] = []
    if down:
        alerts.append(f"processes not reporting: {', '.join(down)}")
    if not clients_conserved:
        alerts.append(
            f"census mismatch: {game_clients} clients bound on games vs "
            f"{gate_clients} connected on gates")
    if stale_gens:
        alerts.append(f"{len(stale_gens)} stale generation row(s)")
    if retraces:
        alerts.append(
            f"{int(retraces)} steady-state jit retrace(s) — see the "
            f"retrace WARN and /flight on the offending game")
    # Rebalance-plane alerts (ISSUE 18): a paused planner names its guard
    # reason, and an enabled planner service with NO live host is a
    # failover in flight (or a wedged one — either way worth eyes).
    if planner_last in paused_reasons:
        alerts.append(
            f"rebalance paused: {planner_last} (planner on "
            f"{planner_host})")
    if rebalance_enabled and planner_service and planner_host is None:
        alerts.append(
            "rebalance planner service has no live host "
            "(failover in flight?)")
    return {
        "reporting": len(reporting),
        "expected": len(processes),
        "down": down,
        "census": {
            "game_entities": game_entities,
            "game_clients": game_clients,
            "gate_clients": gate_clients,
            "clients_conserved": clients_conserved,
        },
        "generations": {
            "gates": gate_gens,
            "stale": stale_gens,
        },
        "migrations": {k: int(v) for k, v in migrates.items()},
        "rebalance": {
            "enabled": rebalance_enabled,
            "planner_service": planner_service,
            "planner_host": planner_host,
            "last_result": planner_last,
            "rounds_paused": {k: int(v) for k, v in paused_reasons.items()},
            "spaces_in_flight": int(spaces_in_flight),
            "space_handoffs_parked": space_handoffs_parked,
            "space_migrations": {
                k: int(v) for k, v in space_outcomes.items()},
        },
        "steady_state_retraces": int(retraces),
        "comms": {
            "links": len(comms_links),
            "bytes": {k: int(v) for k, v in sorted(comms_tiers.items())},
        },
        "fused": {"classes": int(fused_classes), "slots": int(fused_slots)},
        "delivery": {
            "fused_classes": int(fused_delivery_classes),
            "host_fallback_classes": int(host_fallback_classes),
            "host_phase_seconds": {
                k: round(v, 3) for k, v in host_phase.items()},
        },
        "alerts": alerts,
    }

"""goworld_tpu.telemetry — typed metrics, Prometheus exposition, phase tracing.

The engine-wide observability subsystem (README "Telemetry"):

- :mod:`metrics` — Counter / Gauge / Histogram families in a process-wide
  registry (zero-dep, allocation-light hot path).
- :mod:`phases` — :class:`PhaseTracer`, per-tick wall-time attribution for
  the game/gate/dispatcher hot loops.
- Exposition: ``render()`` (Prometheus text 0.0.4, served as ``/metrics``
  by utils/debug_http.py) and ``snapshot()``/``dump()`` (JSON), which
  absorb and supersede the old ``opmon.dump()`` —
  ``utils/opmon.Operation`` is now a thin shim recording into the
  ``op_duration_seconds`` histogram family here.

Module-level helpers record into the default :data:`REGISTRY`; pass an
explicit :class:`Registry` for isolated use (tests, embedded drivers).
"""

from __future__ import annotations

from typing import Optional, Sequence

from goworld_tpu.telemetry.metrics import (  # noqa: F401
    DEFAULT_BUCKETS,
    REGISTRY,
    Counter,
    Gauge,
    Histogram,
    MetricFamily,
    Registry,
    exponential_buckets,
)
from goworld_tpu.telemetry.phases import PhaseTracer, TOTAL_PHASE  # noqa: F401
from goworld_tpu.telemetry import tracing  # noqa: F401


def counter(name: str, help: str = "", labelnames: Sequence[str] = ()):
    """Get-or-create a counter in the default registry (child when
    unlabeled, family when labeled)."""
    return REGISTRY.counter(name, help, labelnames)


def gauge(name: str, help: str = "", labelnames: Sequence[str] = ()):
    return REGISTRY.gauge(name, help, labelnames)


def histogram(name: str, help: str = "", labelnames: Sequence[str] = (),
              buckets: Optional[Sequence[float]] = None):
    return REGISTRY.histogram(name, help, labelnames, buckets)


def family(name: str):
    """The registered MetricFamily for ``name`` (None when absent)."""
    return REGISTRY.family(name)


def render() -> str:
    """Prometheus text exposition of the default registry (``/metrics``)."""
    return REGISTRY.render()


def snapshot() -> dict:
    """JSON-able structured dump of every family and series."""
    return REGISTRY.snapshot()


# dump() is the name opmon historically exported for "give me the JSON
# view"; keep the alias so the supersession reads naturally at call sites.
dump = snapshot


def reset_for_tests() -> None:
    REGISTRY.clear()

"""Crash-survivable telemetry history rings (the per-process black box).

Every process (game, gate, dispatcher, bench) can append compact periodic
telemetry *frames* — counter deltas, gauge values, histogram bucket deltas
plus live percentiles, and the flight recorder's per-tick rows — to a
bounded on-disk ring of fixed-size segment files. The ring survives the
process: after a kill -9 the segments hold every completed frame, and the
reader tolerates (and counts) the one torn frame a crash mid-append can
leave at the write head.

File format — one frame is::

    <III header: MAGIC, payload_len, crc32(payload)><payload JSON bytes>

appended to segment files named ``seg-%08d`` under the history directory.
A writer always starts a fresh segment (never appends into a file a dead
incarnation may have torn), rotates to a new segment when the current one
would exceed ``segment_bytes``, and unlinks the oldest segments beyond
``segments`` — drop-oldest, so disk use is bounded by
``segments * segment_bytes`` regardless of uptime.

Hot-loop cost is near zero by construction: the writer rides the snapshot
cadence (an asyncio task *off* the logic loop), and the per-frame encode
path (:meth:`HistoryWriter._encode_frame`) is loop-free over a
preallocated grow-only buffer — gwlint HOT_PATHS keeps it that way.
"""

from __future__ import annotations

import asyncio
import collections
import json
import os
import struct
import time
import zlib
from typing import Any, Callable, Optional

from goworld_tpu.telemetry.metrics import REGISTRY, Registry

#: "GWH1" little-endian — first 4 bytes of every frame.
MAGIC = 0x31485747
_HEADER = struct.Struct("<III")
HEADER_SIZE = _HEADER.size

_SEG_PREFIX = "seg-"

_M_WRITTEN = REGISTRY.counter(
    "history_frames_written_total",
    "Telemetry history frames appended to the on-disk ring.")
_M_TRUNCATED = REGISTRY.counter(
    "history_frames_truncated_total",
    "Torn history frames tolerated (and dropped) on ring recovery.")
_M_BYTES = REGISTRY.counter(
    "history_bytes_written_total",
    "Bytes appended to the telemetry history ring.")
_M_ROTATIONS = REGISTRY.counter(
    "history_segment_rotations_total",
    "History ring segment rotations (drop-oldest beyond the bound).")


def _labels_key(labels: dict) -> tuple:
    return tuple(sorted(labels.items()))


def _seg_index(name: str) -> int:
    return int(name[len(_SEG_PREFIX):])


def list_segments(dir: str) -> list[str]:
    """Segment file paths under ``dir``, oldest first."""
    try:
        names = [n for n in os.listdir(dir)
                 if n.startswith(_SEG_PREFIX) and n[len(_SEG_PREFIX):].isdigit()]
    except FileNotFoundError:
        return []
    return [os.path.join(dir, n)
            for n in sorted(names, key=_seg_index)]


def read_segment(path: str) -> tuple[list[dict], int]:
    """Parse one segment: ``(frames, torn)`` where ``torn`` is 1 when the
    segment ends in a torn frame (crash mid-append) and 0 otherwise. A
    torn tail — short header, short payload, bad magic, or CRC mismatch —
    ends the segment; everything before it is returned."""
    with open(path, "rb") as f:
        data = f.read()
    frames: list[dict] = []
    off = 0
    n = len(data)
    while off < n:
        if off + HEADER_SIZE > n:
            return frames, 1
        magic, plen, crc = _HEADER.unpack_from(data, off)
        if magic != MAGIC:
            return frames, 1
        end = off + HEADER_SIZE + plen
        if end > n:
            return frames, 1
        payload = data[off + HEADER_SIZE:end]
        if zlib.crc32(payload) != crc:
            return frames, 1
        try:
            frames.append(json.loads(payload))
        except ValueError:
            return frames, 1
        off = end
    return frames, 0


def read_frames(dir: str) -> tuple[list[dict], int]:
    """Every complete frame under ``dir`` (oldest first) plus the number
    of torn tails tolerated — counted on
    ``history_frames_truncated_total``. A clean shutdown leaves 0 torn
    tails; a kill -9 mid-append leaves exactly one."""
    frames: list[dict] = []
    truncated = 0
    for path in list_segments(dir):
        got, torn = read_segment(path)
        frames.extend(got)
        truncated += torn
    if truncated:
        _M_TRUNCATED.inc(truncated)
    return frames, truncated


class HistoryWriter:
    """Appends periodic telemetry frames for one process to a bounded
    on-disk ring.

    ``health`` is a zero-arg callable returning the process's health dict
    (the same one its debug HTTP ``/healthz`` serves); ``flight`` is the
    process's FlightRecorder (or None) — only per-tick rows newer than the
    previous frame are included, so frames stay compact. Counter and
    histogram series are written as *deltas* against the previous frame;
    gauges as current values.

    ``write_frame`` is synchronous (bench drives it directly);
    :meth:`run` is the asyncio cadence loop services spawn next to their
    other housekeeping tasks. :meth:`close` writes one last frame marked
    ``final`` — after a cooperative shutdown (including a chaos kill that
    cancels the service task) the ring's newest frame holds the process's
    final ticks and census.
    """

    def __init__(self, dir: str, process: str, *,
                 interval: float = 1.0,
                 segment_bytes: int = 262144,
                 segments: int = 8,
                 health: Optional[Callable[[], dict]] = None,
                 flight: Any = None,
                 registry: Optional[Registry] = None) -> None:
        self.dir = dir
        self.process = process
        self.interval = max(0.01, float(interval))
        self.segment_bytes = max(4096, int(segment_bytes))
        self.segments = max(2, int(segments))
        self._health = health
        self._flight = flight
        self._registry = registry if registry is not None else REGISTRY
        self._buf = bytearray(4096)  # grow-only frame encode buffer
        self._prev_counters: dict[tuple, float] = {}
        self._prev_hist: dict[tuple, tuple] = {}  # key -> (count, sum, cum)
        self._last_flight_ts = 0.0
        self._seq = 0
        self.frames_written = 0
        self.recent: collections.deque = collections.deque(maxlen=64)
        self._f = None
        self._seg_bytes_left = 0
        os.makedirs(dir, exist_ok=True)
        self._open_segment()

    # --- segment management --------------------------------------------------

    def _open_segment(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None
        paths = list_segments(self.dir)
        nxt = _seg_index(os.path.basename(paths[-1])) + 1 if paths else 0
        path = os.path.join(self.dir, f"{_SEG_PREFIX}{nxt:08d}")
        self._f = open(path, "ab")
        self._seg_bytes_left = self.segment_bytes
        # Drop-oldest: the new segment counts toward the bound.
        excess = len(paths) + 1 - self.segments
        for old in paths[:max(0, excess)]:
            try:
                os.unlink(old)
            except OSError:
                pass
            _M_ROTATIONS.inc()

    # --- frame encode (gwlint HOT_PATHS: no per-frame object churn) ---------

    def _encode_frame(self, payload: bytes) -> memoryview:
        n = HEADER_SIZE + len(payload)
        if len(self._buf) < n:
            self._buf.extend(bytes(n - len(self._buf)))
        _HEADER.pack_into(self._buf, 0, MAGIC, len(payload),
                          zlib.crc32(payload))
        self._buf[HEADER_SIZE:n] = payload
        return memoryview(self._buf)[:n]

    # --- collection ----------------------------------------------------------

    def _collect(self, final: bool) -> dict:
        counters: dict[str, list] = {}
        gauges: dict[str, list] = {}
        hists: dict[str, list] = {}
        snap = self._registry.snapshot()
        for name, fam_snap in snap.items():
            kind = fam_snap["type"]
            if kind == "counter":
                out = []
                for s in fam_snap["series"]:
                    key = (name,) + _labels_key(s["labels"])
                    prev = self._prev_counters.get(key, 0.0)
                    self._prev_counters[key] = s["value"]
                    d = s["value"] - prev
                    if d:
                        out.append([s["labels"], d])
                if out:
                    counters[name] = out
            elif kind == "gauge":
                out = [[s["labels"], s["value"]]
                       for s in fam_snap["series"]]
                if out:
                    gauges[name] = out
            else:
                fam = self._registry.family(name)
                if fam is None:
                    continue
                out = []
                for values, child in fam.children():
                    labels = dict(zip(fam.labelnames, values))
                    key = (name,) + _labels_key(labels)
                    cum = [c for _, c in child.cumulative_buckets()]
                    le = [b for b, _ in child.cumulative_buckets()]
                    pc, ps, pcum = self._prev_hist.get(
                        key, (0, 0.0, [0] * len(cum)))
                    if len(pcum) != len(cum):
                        pcum = [0] * len(cum)
                    self._prev_hist[key] = (child.count, child.sum, cum)
                    count_d = child.count - pc
                    if not count_d and not final:
                        continue
                    out.append([labels, {
                        "count_d": count_d,
                        "sum_d": child.sum - ps,
                        "buckets_d": [c - p for c, p in zip(cum, pcum)],
                        "le": [("inf" if b == float("inf") else b)
                               for b in le],
                        "max": child.max,
                        "p50": child.percentile(0.50),
                        "p95": child.percentile(0.95),
                        "p99": child.percentile(0.99),
                        "p999": child.percentile(0.999),
                    }])
                if out:
                    hists[name] = out
        frame: dict = {
            "v": 1,
            "ts": round(time.time(), 6),
            "seq": self._seq,
            "process": self.process,
            "counters": counters,
            "gauges": gauges,
            "hist": hists,
        }
        if final:
            frame["final"] = True
        if self._health is not None:
            try:
                frame["health"] = self._health()
            except Exception:
                frame["health"] = None
        if self._flight is not None:
            ticks = [t for t in self._flight.ticks()
                     if t.get("ts", 0.0) > self._last_flight_ts]
            if ticks:
                self._last_flight_ts = ticks[-1]["ts"]
            frame["flight"] = ticks
        return frame

    # --- writing -------------------------------------------------------------

    def write_frame(self, final: bool = False) -> dict:
        """Collect and append one frame; returns the frame dict. Flushes
        to the OS so a subsequent kill -9 loses at most the frame a crash
        tears mid-``write``."""
        if self._f is None:  # closed (shutdown race with the run() task)
            return {}
        frame = self._collect(final)
        payload = json.dumps(frame, separators=(",", ":")).encode()
        view = self._encode_frame(payload)
        if len(view) > self._seg_bytes_left:
            self._open_segment()
        assert self._f is not None
        self._f.write(view)
        self._f.flush()
        self._seg_bytes_left -= len(view)
        self._seq += 1
        self.frames_written += 1
        _M_WRITTEN.inc()
        _M_BYTES.inc(len(view))
        self.recent.append(frame)
        return frame

    async def run(self) -> None:
        """Cadence loop: one frame per ``interval``. Cancel-safe — the
        service's shutdown path calls :meth:`close` for the final frame."""
        while True:
            await asyncio.sleep(self.interval)
            try:
                self.write_frame()
            except Exception:
                # The black box must never take the process down with it.
                from goworld_tpu.utils import gwlog
                gwlog.warnf("history frame write failed (dir=%s)", self.dir)

    def close(self, final: bool = True) -> None:
        if self._f is None:
            return
        if final:
            try:
                self.write_frame(final=True)
            except Exception:
                pass
        self._f.close()
        self._f = None

    def snapshot(self) -> dict:
        """The ``/history`` debug route payload: ring location plus the
        most recent frames (in-memory mirror — no disk read)."""
        return {
            "dir": self.dir,
            "process": self.process,
            "interval": self.interval,
            "segment_bytes": self.segment_bytes,
            "segments": self.segments,
            "frames_written": self.frames_written,
            "recent": list(self.recent)[-16:],
        }


# --- module state (the process's writer; debug_http's /history serves it) ----

_active: Optional[HistoryWriter] = None


def set_active_writer(w: HistoryWriter) -> None:
    global _active
    _active = w


def clear_active_writer(w: Optional[HistoryWriter] = None) -> None:
    global _active
    if w is None or _active is w:
        _active = None


def active_writer() -> Optional[HistoryWriter]:
    return _active

"""Sampled distributed tracing + slow-tick flight recorder.

PR 1's metrics say *that* a phase is slow; this module says *where one
specific request spent its time* as it crosses the paper's multi-process
routing path (gate → dispatcher → game → dispatcher → gate). The design
follows the AsyncTaichi / CheetahGIS observation that once execution is
batched and asynchronous, per-request causal traces — not aggregate
counters — are the only way to attribute latency:

- A :class:`TraceContext` (trace_id u64, span_id u64, flags u8) is minted
  at ingress seams (gate client RPC receive, game timer origination) with
  head sampling at ``[telemetry] trace_sample_rate`` (1/N; default 1/1024,
  0 disables). Unsampled traffic never allocates anything — every helper
  early-returns on a single global read, and the wire stays byte-identical
  to an untraced build.
- Sampled contexts piggyback across cluster links as a 17-byte packet
  trailer flagged by the high bit of the u16 msgtype (proto/conn.py;
  PROTO_VERSION 4). Each process strips the trailer at its recv seam and
  parents its own spans onto the sender's span id, so one trace id names
  the whole cross-process tree including dispatcher queue-dwell time.
- Finished spans land in a fixed-size, lock-cheap ring (drop-oldest,
  counted on ``trace_spans_dropped_total``), served by debug_http as
  ``GET /trace`` (Chrome trace-event JSON for one process; ``?raw=1`` for
  the span list tools/tracecat.py merges across processes).
- :class:`FlightRecorder` keeps the last N game ticks (phase durations,
  queue depth, entity/AOI counts); a tick over ``[telemetry]
  slow_tick_budget`` dumps the ring plus the tick's sampled spans as ONE
  structured WARN, retrievable at ``GET /flight``.

Thread model: the active-context global is only touched by the process's
single logic loop (scopes are entered and exited synchronously, never
across an await); the span ring takes one lock per *finished sampled
span* so off-loop recorders (the storage worker) stay safe.
"""

from __future__ import annotations

import collections
import json
import random
import struct
import time
from typing import Optional

from goworld_tpu.telemetry.metrics import REGISTRY

#: flags bit 0: sampled (the only flag so far; the u8 is wire-reserved).
FLAG_SAMPLED = 0x01

#: Wire trailer appended to sampled cluster packets: trace_id u64 LE,
#: span_id u64 LE, flags u8 — 17 bytes (proto/conn.py attaches/strips it).
TRAILER = struct.Struct("<QQB")
TRAILER_SIZE = TRAILER.size

#: monotonic → epoch offset, sampled once: every process on a host derives
#: the same offset (same clocks), so merged timelines line up to ~µs.
_EPOCH_OFFSET = time.time() - time.monotonic()

_DROPPED = REGISTRY.counter(
    "trace_spans_dropped_total",
    "Finished spans evicted from the trace ring (drop-oldest).")


def mono_to_epoch(t: float) -> float:
    return t + _EPOCH_OFFSET


class TraceContext:
    """Identity of one sampled request as it crosses processes.

    ``span_id`` is the id of the *currently active* span — the parent for
    any child span or downstream process. ``born`` is the local monotonic
    receive time when the context arrived by wire (None for locally
    minted roots); queue-dwell spans measure from it.
    """

    __slots__ = ("trace_id", "span_id", "flags", "born")

    def __init__(self, trace_id: int, span_id: int,
                 flags: int = FLAG_SAMPLED,
                 born: Optional[float] = None) -> None:
        self.trace_id = trace_id
        self.span_id = span_id
        self.flags = flags
        self.born = born

    @property
    def sampled(self) -> bool:
        return bool(self.flags & FLAG_SAMPLED)

    def __repr__(self) -> str:
        return (f"TraceContext<{self.trace_id:016x}/{self.span_id:016x}"
                f" flags={self.flags:#x}>")


def encode_trailer(ctx: TraceContext) -> bytes:
    return TRAILER.pack(ctx.trace_id, ctx.span_id, ctx.flags)


def decode_trailer(data: bytes) -> TraceContext:
    trace_id, span_id, flags = TRAILER.unpack(data)
    return TraceContext(trace_id, span_id, flags, born=time.monotonic())


class SpanRing:
    """Fixed-size ring of finished spans; drop-oldest, counted."""

    def __init__(self, capacity: int = 4096) -> None:
        import threading

        self.capacity = max(1, capacity)
        self._buf: collections.deque = collections.deque()
        self._lock = threading.Lock()

    def append(self, span: dict) -> None:
        with self._lock:
            if len(self._buf) >= self.capacity:
                self._buf.popleft()
                _DROPPED.inc()
            self._buf.append(span)

    def snapshot(self) -> list[dict]:
        with self._lock:
            return list(self._buf)

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()

    def __len__(self) -> int:
        return len(self._buf)


# --- module state -------------------------------------------------------------

# 1/N head-sampling denominator; 0 = tracing off. A plain int read is the
# entire unsampled fast path at every instrumentation point.
_sample_n: int = 1024
_ring = SpanRing(4096)
_current: Optional[TraceContext] = None


def configure(sample_rate: Optional[int] = None,
              ring_size: Optional[int] = None) -> None:
    """Set the head-sampling denominator (1/N; 0 disables) and/or resize
    the span ring (existing spans are kept up to the new capacity)."""
    global _sample_n, _ring
    if sample_rate is not None:
        _sample_n = max(0, int(sample_rate))
    if ring_size is not None and ring_size != _ring.capacity:
        old = _ring.snapshot()
        _ring = SpanRing(ring_size)
        for s in old[-ring_size:]:
            _ring.append(s)


def configure_from_config(tcfg) -> None:
    """Apply a read_config.TelemetryConfig (each process at boot). Also
    the [telemetry] seam for the device-runtime sentinel: every process
    that configures tracing gets its retrace warm threshold set here."""
    configure(sample_rate=tcfg.trace_sample_rate,
              ring_size=tcfg.trace_ring_size)
    from goworld_tpu.telemetry import sentinel

    sentinel.configure_from_config(tcfg)


def sample_rate() -> int:
    return _sample_n


def current() -> Optional[TraceContext]:
    """The active sampled context, or None (the common case)."""
    return _current


def maybe_sample() -> Optional[TraceContext]:
    """Head-sampling coin flip at an ingress seam: a fresh root context
    1-in-N times, else None. Cost when unsampled: one int compare + one
    getrandbits."""
    n = _sample_n
    if n <= 0:
        return None
    if n > 1 and random.getrandbits(30) % n:
        return None
    return TraceContext(_new_id(), _new_id())


def _new_id() -> int:
    # Nonzero 64-bit ids: zero is the "no parent" sentinel in exports.
    return random.getrandbits(64) | 1


#: public alias for off-loop recorders (storage worker span ids).
new_span_id = _new_id


class SpanScope:
    """One in-progress span; activates a child context while entered.

    Use via the helpers (:func:`root_scope`, :func:`continue_from_packet`)
    in an ``if scope is None: ... else: with scope: ...`` shape so the
    unsampled path never constructs anything.
    """

    __slots__ = ("name", "ctx", "parent_id", "args", "_prev", "_t0")

    def __init__(self, name: str, parent: TraceContext,
                 start: Optional[float] = None) -> None:
        self.name = name
        self.parent_id = parent.span_id
        self.ctx = TraceContext(parent.trace_id, _new_id(), parent.flags)
        self.args: dict = {}
        self._prev: Optional[TraceContext] = None
        self._t0 = time.monotonic() if start is None else start

    def __enter__(self) -> "SpanScope":
        global _current
        self._prev = _current
        _current = self.ctx
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        global _current
        _current = self._prev
        end = time.monotonic()
        if exc_type is not None:
            self.args["error"] = exc_type.__name__
        record_span(self.name, self._t0, end - self._t0, self.ctx.trace_id,
                    self.ctx.span_id, self.parent_id,
                    self.args or None)


def root_scope(name: str) -> Optional[SpanScope]:
    """Ingress helper: head-sample and open a root span, or None."""
    ctx = maybe_sample()
    if ctx is None:
        return None
    scope = SpanScope(name, ctx)
    # The root scope's own span IS the minted context's span (not a child
    # of it): keep the ids identical so the wire parent is the root.
    scope.ctx = ctx
    scope.parent_id = 0
    return scope


def child_scope(name: str) -> Optional[SpanScope]:
    """A child span of the active context, or None when untraced."""
    ctx = _current
    if ctx is None:
        return None
    return SpanScope(name, ctx)


def continue_from_packet(packet, name: str,
                         dwell_name: str = "queue_dwell"
                         ) -> Optional[SpanScope]:
    """Resume a trace that arrived on ``packet`` (recv seam attached
    ``packet.trace``): opens a handling span parented on the sender's
    span and records the local queue-dwell (recv → handling start) as its
    own child span — the dispatcher's dwell is exactly this."""
    ctx = packet.trace
    if ctx is None:
        return None
    scope = SpanScope(name, ctx)
    born = ctx.born
    if born is not None:
        now = time.monotonic()
        # Dwell is a child of the handling span so the timeline reads
        # handle = [dwell][processing].
        record_span(dwell_name, born, now - born, ctx.trace_id,
                    _new_id(), scope.ctx.span_id)
        scope._t0 = born  # the handling span covers dwell + processing
    return scope


def record_span(name: str, start_mono: float, duration: float,
                trace_id: int, span_id: int, parent_id: int = 0,
                args: Optional[dict] = None) -> None:
    """Low-level append of a finished span (storage worker, dwell spans,
    phase spans). ``start_mono`` is local monotonic; stored as epoch."""
    span = {
        "name": name,
        "ts": mono_to_epoch(start_mono),
        "dur": duration if duration >= 0.0 else 0.0,
        "trace": trace_id,
        "span": span_id,
        "parent": parent_id,
    }
    if args:
        span["args"] = args
    _ring.append(span)


def record_phase_spans(trace_id: int, t0_mono: float,
                       phases: dict[str, float]) -> None:
    """Emit one span per tick phase as consecutive intervals from the
    tick start — the PhaseTracer boundaries of a tick that handled a
    sampled packet, placed on the same timeline as that packet's spans.
    (Re-marked phases are merged segments, so the layout is the tick's
    phase *budget*, not an exact interleaving.)"""
    at = t0_mono
    for phase, took in phases.items():
        record_span(f"tick.{phase}", at, took, trace_id, _new_id())
        at += took


def snapshot() -> list[dict]:
    """The ring's finished spans, oldest first (``/trace?raw=1``)."""
    return _ring.snapshot()


def export_chrome(process_name: str, pid: int = 1) -> dict:
    """Chrome trace-event JSON for THIS process's ring — loadable directly
    in Perfetto / chrome://tracing; tools/tracecat.py merges several."""
    return {"traceEvents": chrome_events(snapshot(), process_name, pid)}


def chrome_events(spans: list[dict], process_name: str,
                  pid: int) -> list[dict]:
    """Span dicts → chrome trace events (one metadata row + X events)."""
    events: list[dict] = [{
        "ph": "M", "pid": pid, "tid": 0, "name": "process_name",
        "args": {"name": process_name},
    }]
    for s in spans:
        events.append({
            "ph": "X",
            "pid": pid,
            "tid": 0,
            "ts": round(s["ts"] * 1e6, 1),
            "dur": max(round(s["dur"] * 1e6, 1), 0.1),
            "name": s["name"],
            "cat": s["name"].split(".", 1)[0],
            "args": {
                "trace_id": f"{s['trace']:016x}",
                "span_id": f"{s['span']:016x}",
                "parent_id": f"{s['parent']:016x}",
                **(s.get("args") or {}),
            },
        })
    return events


def reset_for_tests() -> None:
    global _current, _sample_n, _flight
    _current = None
    _sample_n = 1024
    _ring.clear()
    _flight = None
    configure(ring_size=4096)


# --- slow-tick flight recorder ------------------------------------------------


class FlightRecorder:
    """Ring of the last N game-tick records + slow-tick dump.

    Every tick costs one small dict + deque append. A tick whose busy
    span exceeds ``slow_budget`` seconds dumps the ring, the offending
    tick, and the trace ring's sampled spans overlapping that tick as ONE
    structured WARN (rate-limited), kept retrievable at ``GET /flight``.
    """

    def __init__(self, capacity: int = 240, slow_budget: float = 0.1,
                 warn_interval: float = 10.0) -> None:
        self.capacity = max(1, capacity)
        self.slow_budget = slow_budget
        self.warn_interval = warn_interval
        self._ticks: collections.deque = collections.deque(
            maxlen=self.capacity)
        self.slow_ticks = 0
        self.last_slow: Optional[dict] = None
        self._last_warn = 0.0

    def ticks(self) -> list:
        """Snapshot of the ring (oldest first) — the load report's
        tick-p95 source (rebalance/report.py)."""
        return list(self._ticks)

    def record(self, t0_mono: float, total: float,
               phases: dict[str, float], **extra) -> None:
        entry = {
            "ts": round(mono_to_epoch(t0_mono), 6),
            "total_ms": round(total * 1000.0, 3),
            "phases_ms": {p: round(v * 1000.0, 3)
                          for p, v in phases.items()},
        }
        entry.update(extra)
        self._ticks.append(entry)
        if 0 < self.slow_budget <= total:
            self._dump(entry, t0_mono, total)

    def _dump(self, entry: dict, t0_mono: float, total: float) -> None:
        self.slow_ticks += 1
        t0, t1 = mono_to_epoch(t0_mono), mono_to_epoch(t0_mono) + total
        spans = [s for s in snapshot()
                 if s["ts"] < t1 and s["ts"] + s["dur"] > t0]
        self.last_slow = {
            "tick": entry,
            "budget_ms": round(self.slow_budget * 1000.0, 3),
            "spans": spans,
            "recent_ticks": list(self._ticks),
            "slow_ticks_total": self.slow_ticks,
        }
        now = time.monotonic()
        if now - self._last_warn >= self.warn_interval:
            self._last_warn = now
            from goworld_tpu.utils import gwlog

            # ONE structured line: the whole incident is machine-readable
            # from the log alone (the /flight endpoint serves the same
            # record with the full ring).
            gwlog.warnf(
                "slow tick: %s",
                json.dumps({
                    "tick": entry,
                    "budget_ms": self.last_slow["budget_ms"],
                    "spans": spans[-40:],
                    "recent_ticks": list(self._ticks)[-20:],
                    "slow_ticks_total": self.slow_ticks,
                }, separators=(",", ":")))

    def snapshot(self) -> dict:
        return {
            "capacity": self.capacity,
            "slow_budget_ms": round(self.slow_budget * 1000.0, 3),
            "slow_ticks_total": self.slow_ticks,
            "recent": list(self._ticks),
            "last_slow": self.last_slow,
        }


# The game process registers its recorder here; debug_http's /flight
# serves it (None on processes without a tick loop).
_flight: Optional[FlightRecorder] = None


def set_flight_recorder(rec: Optional[FlightRecorder]) -> None:
    global _flight
    _flight = rec


def flight_recorder() -> Optional[FlightRecorder]:
    return _flight

"""Tick-phase tracer: per-iteration wall-time attribution for hot loops.

The instrument CheetahGIS-style streaming engines live on: every stage of
the update pipeline gets its own duration histogram, continuously, in
production — so a regression names its phase instead of hiding in an
aggregate tick time (the failure mode that let round 5's 16% CPU-bench
regression pass unnoticed).

Usage, inside a loop that must stay cheap (the 5 ms game tick):

    tracer = PhaseTracer("game_tick_phase_seconds",
                         ("dispatch", "entity_logic", "aoi", "sync_send"))
    while True:
        ...wait for work...
        tracer.begin()            # tick starts AFTER the idle wait
        handle_packets()
        tracer.mark("dispatch")
        tick_timers()
        tracer.mark("entity_logic")
        aoi_tick()
        tracer.mark("aoi")
        post_tick()
        tracer.mark("entity_logic")   # same phase twice: segments accumulate
        tracer.commit()               # observe phases + "total"

Cost per tick: one monotonic() call per mark, a small-dict accumulate, and
one histogram observe per touched phase at commit — microseconds against a
5 ms tick budget.

Phase semantics under the fused tick ([aoi] fuse_logic, entity/columns.py):
per-class columnar tick programs compile INTO the AOI device launch, so
``run_tick_batches`` skips them and ``entity_logic`` collapses to the
residual host work (timers, crontab, post queue, non-fusable hooks) while
the logic cost moves inside the ``aoi`` phase's device step — the collapse
is the observable signature that fusion is live (``bench.py --fused``
reports it; aoi_fused_classes/aoi_fused_slots on /metrics name the cause).
"""

from __future__ import annotations

import time
from typing import Optional, Sequence

from goworld_tpu.telemetry.metrics import REGISTRY, Registry

#: Label value reserved for the whole begin()→commit() span.
TOTAL_PHASE = "total"


class PhaseTracer:
    """Histogram family labeled by ``phase``, fed by begin/mark/commit."""

    __slots__ = ("_family", "_children", "_t0", "_last", "_acc")

    def __init__(self, name: str, phases: Sequence[str], help: str = "",
                 registry: Optional[Registry] = None) -> None:
        reg = registry or REGISTRY
        self._family = reg.histogram(
            name,
            help or "Wall seconds per loop-tick phase (telemetry PhaseTracer).",
            labelnames=("phase",),
        )
        # Pre-resolve children: no labels() dict lookup on the hot path.
        self._children = {p: self._family.labels(p) for p in phases}
        self._children[TOTAL_PHASE] = self._family.labels(TOTAL_PHASE)
        self._t0 = 0.0
        self._last = 0.0
        self._acc: dict[str, float] = {}

    def begin(self) -> None:
        """Start a tick. Call AFTER any idle wait so queue-blocked time
        doesn't pollute the first phase."""
        self._t0 = self._last = time.monotonic()
        self._acc.clear()

    def mark(self, phase: str) -> None:
        """Attribute the segment since the previous mark (or begin) to
        ``phase``. Re-marking a phase within one tick accumulates."""
        now = time.monotonic()
        self._acc[phase] = self._acc.get(phase, 0.0) + (now - self._last)
        self._last = now

    def commit(self):
        """Observe every accumulated phase plus the whole-tick total.

        Returns ``(t0_monotonic, total_seconds, phases_dict)`` so the
        caller can feed the same attribution to the flight recorder /
        trace ring without re-timing anything (None when no begin()
        preceded). The returned dict is a copy — safe to keep."""
        if not self._t0:
            return None  # commit without begin: nothing to attribute
        phases = dict(self._acc)
        for phase, took in phases.items():
            child = self._children.get(phase)
            if child is None:  # late-declared phase: resolve once, keep
                child = self._children[phase] = self._family.labels(phase)
            child.observe(took)
        total = self._last - self._t0
        self._children[TOTAL_PHASE].observe(total)
        t0 = self._t0
        self._t0 = 0.0
        self._acc.clear()
        return t0, total, phases

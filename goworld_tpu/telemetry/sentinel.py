"""Device-runtime sentinel: launch/trace accounting for the engine step jits.

After PR 12 the engine's most important steady-state invariant — a fused
tick is ONE device launch with ZERO retraces — was enforced only by a
test (``test_fused_service_one_launch_trace_counts``). This module makes
it *observable in a live cluster* (the AsyncTaichi point: once execution
is batched and asynchronous, per-launch runtime attribution is the only
way to see regressions):

- :class:`SentinelJit` wraps a jitted callable returned by the engine's
  lru-cached jit factories (ops/neighbor.py, parallel/spatial.py,
  parallel/mesh.py). Every call bumps ``jit_launches_total{fn}``; the
  trace-cache size of the underlying jit (``_cache_size``) is compared
  after the call, so a compile is detected *without touching the traced
  function* — gwlint R1's whole-program view of the step bodies is
  unchanged, and the per-launch overhead is a counter bump plus one
  integer compare, never a device sync.
- A **steady-state retrace detector**: once an instance has served more
  than ``[telemetry] retrace_warm_ticks`` launches, any further trace is
  a regression — ``jit_retrace_events_total{fn}`` increments and ONE
  structured WARN names the arg shape/dtype delta against the previous
  trace signature and carries the flight recorder's recent ticks
  (repeat retraces with the *same* signature do not re-WARN; a new
  distinct signature does). Warm-up traces (first compile, tier growth,
  program-set churn on a *fresh* jit instance) are counted on
  ``jit_traces_total{fn}`` but never alarmed.
- ``jit_cached_traces{fn}`` mirrors each instrumented jit's live trace
  cache, and :func:`install_compile_cache_listener` forwards jax's
  persistent-compilation-cache monitoring events onto
  ``jit_compile_cache_hits_total`` / ``jit_compile_cache_misses_total``
  (the [aoi] compilation_cache story, live).

Thread model: launches happen on the game loop; the prewarm threads
(BatchAOIService / spatial fallback warmup) may drive the same instance
concurrently. The rare trace path takes one per-instance lock; the
launch path is lock-free beside the counter's own lock.
"""

from __future__ import annotations

import json
import threading
from typing import Any, Optional

from goworld_tpu.telemetry.metrics import REGISTRY

_LAUNCHES = REGISTRY.counter(
    "jit_launches_total",
    "Dispatches of each instrumented engine step jit.", ("fn",))
_TRACES = REGISTRY.counter(
    "jit_traces_total",
    "XLA traces (compiles) of each instrumented engine step jit.", ("fn",))
_RETRACES = REGISTRY.counter(
    "jit_retrace_events_total",
    "Steady-state retraces: traces that happened after the warm-tick "
    "threshold on an already-compiled jit (each one is a regression).",
    ("fn",))
_CACHED = REGISTRY.gauge(
    "jit_cached_traces",
    "Live trace-cache entries held by each instrumented jit.", ("fn",))
_CACHE_HITS = REGISTRY.counter(
    "jit_compile_cache_hits_total",
    "Persistent XLA compile-cache hits (jax monitoring).")
_CACHE_MISSES = REGISTRY.counter(
    "jit_compile_cache_misses_total",
    "Persistent XLA compile-cache misses (jax monitoring).")

#: Launches after which a fresh trace on an instance is a steady-state
#: retrace ([telemetry] retrace_warm_ticks).
_warm_launches: int = 32


def configure(warm_launches: Optional[int] = None) -> None:
    global _warm_launches
    if warm_launches is not None:
        _warm_launches = max(1, int(warm_launches))


def configure_from_config(tcfg: Any) -> None:
    """Apply a read_config.TelemetryConfig (each process at boot)."""
    configure(warm_launches=getattr(tcfg, "retrace_warm_ticks", None))


def warm_launches() -> int:
    return _warm_launches


def _sig_of(args: tuple[Any, ...], kwargs: dict[str, Any]) -> tuple[str, ...]:
    """Shape/dtype signature of one call, for the retrace WARN delta.
    Positional args first, then keywords sorted by name. The array KIND
    (the type's top-level package: jaxlib vs numpy) is part of the
    signature — jax caches a numpy-array call separately from a
    device-array call of the same shape, and host code regressing to
    numpy args mid-run is exactly the per-tick-transfer retrace this
    sentinel exists to name."""

    def one(a: Any) -> str:
        dtype = getattr(a, "dtype", None)
        shape = getattr(a, "shape", None)
        if dtype is not None and shape is not None:
            dims = ",".join(str(d) for d in shape)
            kind = type(a).__module__.split(".")[0]
            return f"{kind}:{dtype}[{dims}]"
        return f"py:{type(a).__name__}"

    sig = [one(a) for a in args]
    sig.extend(f"{k}={one(v)}" for k, v in sorted(kwargs.items()))
    return tuple(sig)


def _sig_delta(prev: tuple[str, ...],
               cur: tuple[str, ...]) -> list[dict[str, Any]]:
    """Positions where the signatures disagree (arity changes included)."""
    out: list[dict[str, Any]] = []
    for i in range(max(len(prev), len(cur))):
        p = prev[i] if i < len(prev) else "<absent>"
        c = cur[i] if i < len(cur) else "<absent>"
        if p != c:
            out.append({"arg": i, "was": p, "now": c})
    return out


class SentinelJit:
    """One instrumented jitted callable (see module docstring).

    Wraps the object ``jax.jit`` returned; the engines keep calling it
    (and its ``_cache_size``) exactly as before. Per-instance state, not
    per-label: the lru-cached factories return a fresh instance per
    (params, backend, programs) key, so a tier jump or program-set churn
    compiles inside its own warm window and never false-alarms.
    """

    __slots__ = ("label", "_jitted", "_lock", "_launches", "_traces_seen",
                 "_cs_ok", "_sig", "_warned_sig", "_launch_child",
                 "_trace_child", "_retrace_child", "_cached_gauge")

    def __init__(self, label: str, jitted: Any) -> None:
        self.label = label
        self._jitted = jitted
        self._lock = threading.Lock()
        self._launches = 0
        self._traces_seen = 0
        self._cs_ok = True
        self._sig: Optional[tuple[str, ...]] = None
        self._warned_sig: Optional[tuple[str, ...]] = None
        self._launch_child = _LAUNCHES.labels(label)
        self._trace_child = _TRACES.labels(label)
        self._retrace_child = _RETRACES.labels(label)
        self._cached_gauge = _CACHED.labels(label)

    def _cache_size(self) -> int:
        """Delegate for the engines' ``fused_trace_count`` probes."""
        size = self._jitted._cache_size()
        return int(size)

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        self._launches += 1
        self._launch_child.inc()
        out = self._jitted(*args, **kwargs)
        if self._cs_ok:
            try:
                cs = int(self._jitted._cache_size())
            except Exception:  # pragma: no cover - private-API drift
                self._cs_ok = False
            else:
                if cs != self._traces_seen:
                    self._note_trace(cs, args, kwargs)
        return out

    def _note_trace(self, cache_size: int, args: tuple[Any, ...],
                    kwargs: dict[str, Any]) -> None:
        """Bookkeep one observed trace (rare path: first compile, tier
        warmup, or — past the warm threshold — a steady-state retrace)."""
        with self._lock:
            fresh = cache_size - self._traces_seen
            if fresh <= 0:  # cache shrank (jax GC'd an entry): resync only
                self._traces_seen = cache_size
                self._cached_gauge.set(cache_size)
                return
            self._traces_seen = cache_size
            self._trace_child.inc(fresh)
            self._cached_gauge.set(cache_size)
            sig = _sig_of(args, kwargs)
            prev, self._sig = self._sig, sig
            # Warm window: the launch that triggered this trace is within
            # the threshold, or this instance had never compiled before.
            if prev is None or self._launches <= _warm_launches:
                return
            self._retrace_child.inc(fresh)
            if sig == self._warned_sig:
                return  # identical delta already alarmed once
            self._warned_sig = sig
        self._warn_retrace(prev, sig)

    def _warn_retrace(self, prev: tuple[str, ...],
                      sig: tuple[str, ...]) -> None:
        """ONE structured WARN per distinct retrace signature: the shape/
        dtype delta against the previous trace plus the flight recorder's
        recent ticks — the whole incident is machine-readable from the
        log alone (same contract as the slow-tick dump)."""
        from goworld_tpu.telemetry import tracing
        from goworld_tpu.utils import gwlog

        rec = tracing.flight_recorder()
        flight = rec.snapshot().get("recent", [])[-20:] if rec else []
        gwlog.warnf(
            "steady-state retrace: %s",
            json.dumps({
                "fn": self.label,
                "launches": self._launches,
                "cached_traces": self._traces_seen,
                "warm_launches": _warm_launches,
                "delta": _sig_delta(prev, sig),
                "prev_signature": list(prev),
                "new_signature": list(sig),
                "flight": flight,
            }, separators=(",", ":"), default=str))


def steady_state_retraces() -> float:
    """Sum of ``jit_retrace_events_total`` across every instrumented jit
    (the bench floor headlines assert this stays 0)."""
    fam = REGISTRY.family("jit_retrace_events_total")
    if fam is None:
        return 0.0
    return sum(child.value for _, child in fam.children())


def launches_total(fn: str) -> float:
    return float(_LAUNCHES.labels(fn).value)


def traces_total(fn: str) -> float:
    return float(_TRACES.labels(fn).value)


def retrace_events_total(fn: str) -> float:
    return float(_RETRACES.labels(fn).value)


_cache_listener_installed = False


def install_compile_cache_listener() -> None:
    """Forward jax's persistent compile-cache monitoring events onto the
    hit/miss counters. Idempotent; a jax without the monitoring API (or
    no jax at all) leaves the counters at 0. Called by the engine jit
    factories — processes that never touch jax never import it here."""
    global _cache_listener_installed
    if _cache_listener_installed:
        return
    _cache_listener_installed = True
    try:
        from jax import monitoring

        def on_event(event: str, **kwargs: Any) -> None:
            if event == "/jax/compilation_cache/cache_hits":
                _CACHE_HITS.inc()
            elif event == "/jax/compilation_cache/cache_misses":
                _CACHE_MISSES.inc()

        monitoring.register_event_listener(on_event)
    except Exception:  # pragma: no cover - monitoring API drift
        pass

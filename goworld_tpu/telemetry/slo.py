"""SLO plane: declarative budgets judged continuously over /cluster.

The chaos matrix and the floor gates judge point-in-time numbers; an
operator (and CheetahGIS-style streaming pipelines, PAPERS.md) needs a
*continuously evaluated* verdict: is the cluster inside its latency and
correctness budgets right now, and how fast is it burning its error
budget? This module turns the ``[slo]`` config section
(:class:`~goworld_tpu.config.read_config.SLOConfig`) into that verdict:

- :func:`observe` extracts the budgeted observables from the
  ClusterCollector's per-process rows (tick p99, delivery p99,
  steady-state retraces — the same snapshot series gwtop renders).
- :class:`SLOJudge` judges one poll at a time, keeping bounded windows of
  verdicts per budget and deriving **compliance** (fraction of polls in
  budget over the long window) and **multi-window burn rate**
  (violation_rate / error_budget over a short page-now window and the
  long trend window — the SRE convention: burn 1.0 = exactly spending
  the budget, >1 = on course to exhaust it).
- :func:`judge_values` is the one-shot form for batch gates
  (``run_scenario``, the chaos harness) that already hold the observed
  numbers: returns per-budget verdicts, raises nothing — callers raise
  :class:`SLOViolation` with the rendered verdict when they want a hard
  failure.
"""

from __future__ import annotations

import collections
from typing import Any, Optional


class SLOViolation(RuntimeError):
    """A configured SLO budget was exceeded by a gated run."""


def _hist_stat_max(metrics: dict[str, Any], family: str, label: str,
                   value: str, stat: str) -> Optional[float]:
    """Max of one histogram stat across matching series (None = no data)."""
    fam = metrics.get(family)
    if not fam:
        return None
    best: Optional[float] = None
    for s in fam["series"]:
        if s["labels"].get(label) != value or not s.get("count"):
            continue
        v = s.get(stat)
        if v is None:
            continue
        best = v if best is None else max(best, v)
    return best


def _series_sum(metrics: dict[str, Any], family: str) -> float:
    fam = metrics.get(family)
    if not fam:
        return 0.0
    return sum(float(s.get("value", 0.0)) for s in fam["series"])


def observe(processes: dict[str, dict[str, Any]]) -> dict[str, Any]:
    """The budgeted observables from ClusterCollector process rows:
    worst (max) game tick p99 and delivery (sync_send phase) p99 across
    reporting games, and steady-state retraces summed cluster-wide.
    None = no data yet (not a violation)."""
    tick: Optional[float] = None
    delivery: Optional[float] = None
    retraces = 0.0
    for row in processes.values():
        m = row.get("metrics") or {}
        t = _hist_stat_max(
            m, "game_tick_phase_seconds", "phase", "total", "p99")
        if t is not None:
            tick = t if tick is None else max(tick, t)
        d = _hist_stat_max(
            m, "game_tick_phase_seconds", "phase", "sync_send", "p99")
        if d is not None:
            delivery = d if delivery is None else max(delivery, d)
        retraces += _series_sum(m, "jit_retrace_events_total")
    return {
        "tick_p99": tick,
        "delivery_p99": delivery,
        "steady_state_retraces": retraces,
    }


def _budget_specs(slo) -> list[tuple[str, Optional[float]]]:
    return [
        ("tick_p99", slo.tick_p99_budget),
        ("delivery_p99", slo.delivery_p99_budget),
        ("steady_state_retraces",
         None if slo.steady_state_retraces is None
         else float(slo.steady_state_retraces)),
    ]


def judge_values(slo, *, tick_p99: Optional[float] = None,
                 delivery_p99: Optional[float] = None,
                 bot_error_rate: Optional[float] = None,
                 steady_state_retraces: Optional[float] = None
                 ) -> dict[str, Any]:
    """One-shot verdict for batch gates holding observed values directly
    (run_scenario / chaos). ``{"ok": bool, "budgets": {name: {...}}}`` —
    only configured budgets appear; observed None = no data = in budget."""
    observed = {
        "tick_p99": tick_p99,
        "delivery_p99": delivery_p99,
        "bot_error_rate": bot_error_rate,
        "steady_state_retraces": steady_state_retraces,
    }
    specs = _budget_specs(slo) + [("bot_error_rate", slo.bot_error_rate)]
    budgets: dict[str, Any] = {}
    ok_all = True
    for name, budget in specs:
        if budget is None:
            continue
        obs = observed.get(name)
        violated = obs is not None and obs > budget
        budgets[name] = {"budget": budget, "observed": obs,
                         "ok": not violated}
        ok_all = ok_all and not violated
    return {"ok": ok_all, "budgets": budgets}


def render_verdict(verdict: dict[str, Any]) -> str:
    """Human line for logs / SLOViolation messages."""
    parts = []
    for name, b in verdict["budgets"].items():
        obs = b["observed"]
        obs_s = "n/a" if obs is None else f"{obs:.6g}"
        mark = "OK" if b["ok"] else "VIOLATED"
        parts.append(f"{name}={obs_s} (budget {b['budget']:.6g}) {mark}")
    return "; ".join(parts) if parts else "no budgets configured"


class SLOJudge:
    """Per-poll SLO evaluation with bounded burn-rate windows.

    The driver dispatcher's ClusterCollector owns one of these and calls
    :meth:`judge_poll` every scrape round; ``view()`` ships
    :meth:`summary` as ``summary["slo"]`` and appends :meth:`alerts`.
    """

    def __init__(self, slo) -> None:
        self.slo = slo
        self._windows: dict[str, collections.deque] = {}
        self._polls = 0
        self._last: dict[str, Any] = {
            "enabled": slo.enabled(), "ok": True, "polls": 0, "budgets": {},
        }

    def judge_poll(self, processes: dict[str, dict[str, Any]]) -> dict:
        obs = observe(processes)
        budgets: dict[str, Any] = {}
        ok_all = True
        self._polls += 1
        for name, budget in _budget_specs(self.slo):
            if budget is None:
                continue
            observed = obs.get(name)
            violated = observed is not None and observed > budget
            win = self._windows.setdefault(
                name,
                collections.deque(maxlen=max(1, self.slo.burn_long_polls)))
            win.append(1 if violated else 0)
            short = list(win)[-max(1, self.slo.burn_short_polls):]
            rate_short = sum(short) / len(short)
            rate_long = sum(win) / len(win)
            eb = self.slo.error_budget
            budgets[name] = {
                "budget": budget,
                "observed": observed,
                "ok": not violated,
                "compliance": round(1.0 - rate_long, 4),
                "burn_short": round(rate_short / eb, 2),
                "burn_long": round(rate_long / eb, 2),
            }
            ok_all = ok_all and not violated
        if self.slo.bot_error_rate is not None:
            # Declared for completeness: no cluster metric carries bot
            # errors — chaos/bench gates judge this budget directly.
            budgets["bot_error_rate"] = {
                "budget": self.slo.bot_error_rate,
                "observed": None,
                "ok": True,
                "note": "judged by chaos/bench gates",
            }
        self._last = {
            "enabled": True,
            "ok": ok_all,
            "polls": self._polls,
            "error_budget": self.slo.error_budget,
            "windows": {"short_polls": self.slo.burn_short_polls,
                        "long_polls": self.slo.burn_long_polls},
            "budgets": budgets,
        }
        return self._last

    def summary(self) -> dict[str, Any]:
        return self._last

    def alerts(self) -> list[str]:
        out = []
        for name, b in self._last.get("budgets", {}).items():
            if not b.get("ok", True):
                out.append(
                    f"SLO {name} out of budget: {b['observed']:.6g} > "
                    f"{b['budget']:.6g} (burn {b.get('burn_short', 0):.1f}x "
                    f"short / {b.get('burn_long', 0):.2f}x long)")
            elif b.get("burn_long", 0) >= 1.0:
                out.append(
                    f"SLO {name} burning error budget: "
                    f"{b['burn_long']:.2f}x over the long window")
        return out

"""Typed metric registry: Counter / Gauge / Histogram families + exposition.

The observability core every goworld_tpu process shares (game, gate,
dispatcher, bench). Zero-dep (stdlib only) and allocation-light on the hot
path: recording a sample is one lock acquisition plus integer/float updates
on preallocated slots — no per-observation allocation, no string formatting.
Exposition cost (Prometheus text render, JSON snapshot) is paid by the
*reader* on the debug HTTP port, never by the recording loop.

Design notes:

- Metrics are **families**: a name plus a fixed tuple of label names, with
  one child per label-value combination (``family.labels("dispatch")``).
  An unlabeled metric is a family with one implicit child; the registry
  returns the child directly so call sites stay one-liners.
- Get-or-create semantics: re-registering the same name returns the
  existing family (services are constructed repeatedly in tests), but a
  kind or label-schema mismatch raises — two subsystems silently sharing
  one name with different meanings is the bug this catches.
- Histograms use **fixed exponential buckets** (default 0.1 ms → ~26 s,
  factor 2): cumulative bucket counts are computed at render time, so
  ``observe`` touches exactly one bucket slot. A bounded sample ring
  additionally yields live p50/p99 (the opmon shim's percentile contract —
  utils/opmon.py predates this module and now feeds it).
- Gauges accept either a value (``set``) or a zero-arg callable
  (``set_function``) evaluated at collection time — queue depths and
  backlog sizes are pull-sampled, costing the hot loop nothing.
"""

from __future__ import annotations

import bisect
import re
import threading
from typing import Any, Callable, Optional, Sequence

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

_RING = 512  # bounded per-histogram sample ring for live percentiles


def exponential_buckets(start: float, factor: float, count: int) -> tuple[float, ...]:
    """``count`` upper bounds starting at ``start``, each ``factor`` apart."""
    if start <= 0 or factor <= 1 or count < 1:
        raise ValueError("need start > 0, factor > 1, count >= 1")
    out = []
    v = start
    for _ in range(count):
        out.append(v)
        v *= factor
    return tuple(out)


# 0.1 ms .. ~26 s: spans a 5 ms loop tick through a 10+ s jit compile.
DEFAULT_BUCKETS = exponential_buckets(0.0001, 2.0, 19)


def _fmt(v: float) -> str:
    """Prometheus sample formatting: integral values render as integers."""
    if v != v:  # NaN
        return "NaN"
    if v in (float("inf"), float("-inf")):
        return "+Inf" if v > 0 else "-Inf"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


class Counter:
    """Monotonic counter child. ``inc`` only — decreasing raises."""

    __slots__ = ("_lock", "_value")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError("counters only go up; use a Gauge")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value


class Gauge:
    """Point-in-time value child; ``set_function`` makes it pull-sampled."""

    __slots__ = ("_lock", "_value", "_fn")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0.0
        self._fn: Optional[Callable[[], float]] = None

    def set(self, v: float) -> None:
        with self._lock:
            self._fn = None
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._fn = None
            self._value += n

    def dec(self, n: float = 1.0) -> None:
        self.inc(-n)

    def set_function(self, fn: Callable[[], float]) -> None:
        """Evaluate ``fn`` at every collection instead of storing a value
        (queue depths, backlog sizes — zero hot-loop cost)."""
        with self._lock:
            self._fn = fn

    @property
    def value(self) -> float:
        fn = self._fn
        if fn is not None:
            try:
                return float(fn())
            except Exception:
                return float("nan")  # a broken probe must not kill /metrics
        return self._value


class Histogram:
    """Fixed-bucket histogram child with count/sum/max and a bounded
    sample ring for live p50/p99 (nearest-rank, opmon parity)."""

    __slots__ = ("_lock", "_bounds", "_bucket_counts", "_count", "_sum",
                 "_max", "_ring", "_ring_i")

    def __init__(self, bounds: Sequence[float]) -> None:
        self._lock = threading.Lock()
        self._bounds = tuple(bounds)
        self._bucket_counts = [0] * (len(self._bounds) + 1)  # +Inf overflow
        self._count = 0
        self._sum = 0.0
        self._max = 0.0
        self._ring: list[float] = []
        self._ring_i = 0

    def observe(self, v: float) -> None:
        i = bisect.bisect_left(self._bounds, v)  # le-inclusive upper bound
        with self._lock:
            self._bucket_counts[i] += 1
            self._count += 1
            self._sum += v
            if v > self._max:
                self._max = v
            if len(self._ring) < _RING:
                self._ring.append(v)
            else:
                self._ring[self._ring_i] = v
                self._ring_i = (self._ring_i + 1) % _RING

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    @property
    def max(self) -> float:
        return self._max

    def percentile(self, q: float) -> float:
        """Nearest-rank percentile over the bounded ring: ceil(q*n)-1, NOT
        int(q*n) — the latter returns the max (p100) for n in 100..101 and
        overstates p99 generally (carried over from opmon). Rank arithmetic
        is integer per-mille so p999 is distinct from p99 and no float-ceil
        precision leaks in (0.95 * 100 is not 95 in binary)."""
        with self._lock:
            s = sorted(self._ring)
        if not s:
            return 0.0
        q1000 = int(round(q * 1000))
        return s[max(0, -(-len(s) * q1000 // 1000) - 1)]

    def cumulative_buckets(self) -> list[tuple[float, int]]:
        """[(upper_bound, cumulative_count)], ending with (+Inf, count)."""
        with self._lock:
            counts = list(self._bucket_counts)
        out = []
        acc = 0
        for bound, c in zip(self._bounds, counts):
            acc += c
            out.append((bound, acc))
        out.append((float("inf"), acc + counts[-1]))
        return out


class MetricFamily:
    """One named metric: fixed label schema, one child per value tuple."""

    def __init__(self, name: str, help: str, kind: str,
                 labelnames: Sequence[str],
                 buckets: Optional[Sequence[float]] = None) -> None:
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for ln in labelnames:
            if not _LABEL_RE.match(ln):
                raise ValueError(f"invalid label name {ln!r}")
        self.name = name
        self.help = help
        self.kind = kind  # counter | gauge | histogram
        self.labelnames = tuple(labelnames)
        self._buckets = tuple(buckets) if buckets is not None else None
        self._lock = threading.Lock()
        self._children: dict[tuple[str, ...], Any] = {}

    def _new_child(self) -> Any:
        if self.kind == "counter":
            return Counter()
        if self.kind == "gauge":
            return Gauge()
        return Histogram(self._buckets or DEFAULT_BUCKETS)

    def labels(self, *values: str, **kv: str) -> Any:
        """The child for one label-value combination (cached). Accepts
        positional values in labelname order or keyword form."""
        if kv:
            if values:
                raise ValueError("pass labels positionally OR by keyword")
            try:
                values = tuple(str(kv[ln]) for ln in self.labelnames)
            except KeyError as e:
                raise ValueError(
                    f"{self.name}: missing label {e.args[0]!r}"
                ) from None
            if len(kv) != len(self.labelnames):
                extra = set(kv) - set(self.labelnames)
                raise ValueError(f"{self.name}: unknown labels {sorted(extra)}")
        else:
            values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"{self.name} takes {len(self.labelnames)} label values "
                f"({self.labelnames}), got {len(values)}"
            )
        with self._lock:
            child = self._children.get(values)
            if child is None:
                child = self._children[values] = self._new_child()
            return child

    def remove(self, *values: str) -> None:
        """Drop one child (stopped services must not keep themselves alive
        through gauge closures — same reasoning as gwvar.unset)."""
        with self._lock:
            self._children.pop(tuple(str(v) for v in values), None)

    def clear(self) -> None:
        with self._lock:
            self._children.clear()

    def children(self) -> list[tuple[tuple[str, ...], Any]]:
        with self._lock:
            return list(self._children.items())


class Registry:
    """name → MetricFamily, with get-or-create typed constructors."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._families: dict[str, MetricFamily] = {}

    def _get_or_create(self, name: str, help: str, kind: str,
                       labelnames: Sequence[str],
                       buckets: Optional[Sequence[float]] = None) -> Any:
        labelnames = tuple(labelnames)
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = self._families[name] = MetricFamily(
                    name, help, kind, labelnames, buckets
                )
            else:
                if fam.kind != kind:
                    raise ValueError(
                        f"metric {name!r} already registered as {fam.kind}, "
                        f"not {kind}"
                    )
                if fam.labelnames != labelnames:
                    raise ValueError(
                        f"metric {name!r} already registered with labels "
                        f"{fam.labelnames}, not {labelnames}"
                    )
        if not labelnames:
            return fam.labels()  # unlabeled: hand back the single child
        return fam

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> Any:
        return self._get_or_create(name, help, "counter", labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> Any:
        return self._get_or_create(name, help, "gauge", labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Optional[Sequence[float]] = None) -> Any:
        return self._get_or_create(name, help, "histogram", labelnames, buckets)

    def family(self, name: str) -> Optional[MetricFamily]:
        with self._lock:
            return self._families.get(name)

    def unregister(self, name: str) -> None:
        with self._lock:
            self._families.pop(name, None)

    def clear(self) -> None:
        with self._lock:
            self._families.clear()

    # --- exposition ---------------------------------------------------------

    def _families_snapshot(self) -> list[MetricFamily]:
        with self._lock:
            return sorted(self._families.values(), key=lambda f: f.name)

    def render(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        lines: list[str] = []
        for fam in self._families_snapshot():
            if fam.help:
                lines.append(f"# HELP {fam.name} {fam.help}")
            lines.append(f"# TYPE {fam.name} {fam.kind}")
            for values, child in fam.children():
                base = "".join(
                    f'{ln}="{_escape_label(lv)}",'
                    for ln, lv in zip(fam.labelnames, values)
                )
                if fam.kind == "histogram":
                    for bound, cum in child.cumulative_buckets():
                        le = "+Inf" if bound == float("inf") else _fmt(bound)
                        lines.append(
                            f'{fam.name}_bucket{{{base}le="{le}"}} {cum}'
                        )
                    sfx = f"{{{base[:-1]}}}" if base else ""
                    lines.append(f"{fam.name}_sum{sfx} {_fmt(child.sum)}")
                    lines.append(f"{fam.name}_count{sfx} {child.count}")
                    lines.append(
                        f"{fam.name}_p999{sfx} "
                        f"{_fmt(child.percentile(0.999))}"
                    )
                else:
                    sfx = f"{{{base[:-1]}}}" if base else ""
                    lines.append(f"{fam.name}{sfx} {_fmt(child.value)}")
        return "\n".join(lines) + "\n"

    def snapshot(self) -> dict:
        """JSON-able structured dump (the ``/opmon`` superset: every family,
        every series; histograms carry count/avg/max/p50/p95/p99/p999)."""
        out: dict = {}
        for fam in self._families_snapshot():
            series = []
            for values, child in fam.children():
                labels = dict(zip(fam.labelnames, values))
                if fam.kind == "histogram":
                    cnt = child.count
                    series.append({
                        "labels": labels,
                        "count": cnt,
                        "sum": child.sum,
                        "avg": child.sum / cnt if cnt else 0.0,
                        "max": child.max,
                        "p50": child.percentile(0.50),
                        "p95": child.percentile(0.95),
                        "p99": child.percentile(0.99),
                        "p999": child.percentile(0.999),
                    })
                else:
                    series.append({"labels": labels, "value": child.value})
            out[fam.name] = {"type": fam.kind, "help": fam.help,
                             "series": series}
        return out


#: The process-wide default registry every subsystem records into and the
#: debug HTTP ``/metrics`` route renders from.
REGISTRY = Registry()

"""Post-mortem bundles: one directory holding a whole cluster's black box.

A bundle is emitted on abnormal exit, chaos failure, or an explicit
``tools/gwpost.py`` run, and collects per process: the on-disk history
ring (telemetry/history.py — survives the process), the span ring and
flight dump (when the process was alive to ask), plus the final
``GET /cluster`` aggregate. Layout::

    <bundle>/
      MANIFEST.json                 {v, reason, created, processes}
      cluster.json                  final /cluster view (when available)
      processes/<name>/history/seg-*  copied history ring segments
      processes/<name>/spans.json     raw span-ring dump (live scrape)
      processes/<name>/flight.json    flight-recorder dump (live scrape)

Rendering reuses tracecat's Perfetto merge (:func:`merge_spans` is the
shared implementation tools/tracecat.py delegates to): every process's
spans — including spans *synthesized from the dead process's
flight-recorder rows in its history ring* — become one merged
chrome://tracing / Perfetto timeline. That last part is the point of the
whole exercise: the killed game's final ticks, which no live endpoint
can serve anymore, come back out of its black box.
"""

from __future__ import annotations

import json
import os
import shutil
import time
from typing import Any, Optional

from goworld_tpu.telemetry import history as history_mod

MANIFEST_NAME = "MANIFEST.json"


# --- flight rows → span dicts -------------------------------------------------

def flight_ticks_to_spans(ticks: list[dict]) -> list[dict]:
    """Synthesize span dicts (telemetry/tracing.py shape) from
    flight-recorder tick rows: one ``tick.total`` span per row carrying
    the row's extras (entities, queue_depth, ...) as args, plus one
    ``tick.<phase>`` child per phase laid out as consecutive intervals —
    the same layout record_phase_spans uses for sampled ticks."""
    spans: list[dict] = []
    sid = 0
    for t in ticks:
        ts = float(t.get("ts", 0.0))
        total = float(t.get("total_ms", 0.0)) / 1000.0
        sid += 1
        root = sid
        args = {k: v for k, v in t.items()
                if k not in ("ts", "total_ms", "phases_ms")}
        spans.append({"name": "tick.total", "ts": ts, "dur": total,
                      "trace": 0, "span": root, "parent": 0,
                      "args": args})
        at = ts
        for ph, ms in (t.get("phases_ms") or {}).items():
            if ph == "total":
                continue
            sid += 1
            spans.append({"name": f"tick.{ph}", "ts": at,
                          "dur": float(ms) / 1000.0, "trace": 0,
                          "span": sid, "parent": root})
            at += float(ms) / 1000.0
    return spans


# --- the Perfetto merge (tracecat's, shared) ---------------------------------

def merge_spans(process_spans: list[tuple[str, list[dict]]],
                trace_id: Optional[int] = None) -> dict:
    """Merge per-process span lists into one chrome trace-event object —
    the implementation behind tools/tracecat.py's ``merge`` (pid is the
    list index, so re-running yields comparable files)."""
    from goworld_tpu.telemetry.tracing import chrome_events

    events: list[dict] = []
    for pid, (name, spans) in enumerate(process_spans, start=1):
        if trace_id is not None:
            spans = [s for s in spans if s["trace"] == trace_id]
        events.extend(chrome_events(spans, name, pid))
    return {"traceEvents": events, "displayTimeUnit": "ms"}


# --- collection ---------------------------------------------------------------

def collect_bundle(out_dir: str, *, reason: str = "",
                   history_dir: Optional[str] = None,
                   cluster_view: Optional[dict] = None,
                   process_spans: Optional[dict[str, list[dict]]] = None,
                   flights: Optional[dict[str, dict]] = None) -> dict:
    """Assemble a bundle directory. ``history_dir`` is the configured
    ``[telemetry] history_dir`` root (one subdirectory per process —
    copied verbatim, torn tails and all); ``process_spans`` / ``flights``
    are live scrapes keyed by process name (dead processes simply have
    none — their history ring speaks for them). Returns the manifest."""
    os.makedirs(out_dir, exist_ok=True)
    names: set[str] = set()

    def proc_dir(name: str) -> str:
        d = os.path.join(out_dir, "processes", name)
        os.makedirs(d, exist_ok=True)
        names.add(name)
        return d

    if history_dir and os.path.isdir(history_dir):
        for name in sorted(os.listdir(history_dir)):
            src = os.path.join(history_dir, name)
            if not os.path.isdir(src):
                continue
            segs = history_mod.list_segments(src)
            if not segs:
                continue
            dst = os.path.join(proc_dir(name), "history")
            os.makedirs(dst, exist_ok=True)
            for seg in segs:
                shutil.copy2(seg, dst)
    for name, spans in (process_spans or {}).items():
        with open(os.path.join(proc_dir(name), "spans.json"), "w",
                  encoding="utf-8") as f:
            json.dump(spans, f, separators=(",", ":"))
    for name, flight in (flights or {}).items():
        with open(os.path.join(proc_dir(name), "flight.json"), "w",
                  encoding="utf-8") as f:
            json.dump(flight, f, separators=(",", ":"))
    if cluster_view is not None:
        with open(os.path.join(out_dir, "cluster.json"), "w",
                  encoding="utf-8") as f:
            json.dump(cluster_view, f, separators=(",", ":"))
    manifest = {
        "v": 1,
        "reason": reason,
        "created": round(time.time(), 3),
        "processes": sorted(names),
    }
    with open(os.path.join(out_dir, MANIFEST_NAME), "w",
              encoding="utf-8") as f:
        json.dump(manifest, f, indent=2)
    return manifest


# --- loading / rendering ------------------------------------------------------

def _read_json(path: str) -> Any:
    try:
        with open(path, encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def load_bundle(dir: str) -> dict:
    """Parse a bundle back: manifest, cluster view, and per process the
    history frames (torn tails tolerated + counted), raw spans, and
    flight dump — whichever of those the bundle holds."""
    out: dict = {
        "manifest": _read_json(os.path.join(dir, MANIFEST_NAME)) or {},
        "cluster": _read_json(os.path.join(dir, "cluster.json")),
        "processes": {},
    }
    proc_root = os.path.join(dir, "processes")
    if os.path.isdir(proc_root):
        for name in sorted(os.listdir(proc_root)):
            pdir = os.path.join(proc_root, name)
            if not os.path.isdir(pdir):
                continue
            frames, truncated = history_mod.read_frames(
                os.path.join(pdir, "history"))
            out["processes"][name] = {
                "frames": frames,
                "truncated": truncated,
                "spans": _read_json(os.path.join(pdir, "spans.json")),
                "flight": _read_json(os.path.join(pdir, "flight.json")),
            }
    return out


def bundle_process_spans(dir: str) -> list[tuple[str, list[dict]]]:
    """Per-process span lists from a bundle, merge-ready: the scraped
    span ring (when present) plus spans synthesized from every
    flight-recorder row the process's history frames carry — the dead
    process's final ticks land on the timeline through the latter."""
    loaded = load_bundle(dir)
    out: list[tuple[str, list[dict]]] = []
    for name, proc in loaded["processes"].items():
        spans = list(proc["spans"] or [])
        ticks: list[dict] = []
        for frame in proc["frames"]:
            ticks.extend(frame.get("flight") or [])
        if not ticks and proc["flight"]:
            ticks = list(proc["flight"].get("recent") or [])
        spans.extend(flight_ticks_to_spans(ticks))
        if spans:
            out.append((name, spans))
    return out


def bundle_summary(dir: str) -> dict:
    """Compact stdout object for gwpost: what the bundle holds."""
    loaded = load_bundle(dir)
    procs = {}
    for name, proc in loaded["processes"].items():
        ticks = sum(len(f.get("flight") or []) for f in proc["frames"])
        procs[name] = {
            "frames": len(proc["frames"]),
            "truncated_tails": proc["truncated"],
            "final_frame": bool(proc["frames"]
                                and proc["frames"][-1].get("final")),
            "flight_ticks": ticks,
            "spans": len(proc["spans"] or []),
        }
    cluster = loaded["cluster"] or {}
    summary = (cluster.get("summary") or {})
    return {
        "reason": loaded["manifest"].get("reason"),
        "processes": procs,
        "cluster": {
            "present": loaded["cluster"] is not None,
            "alerts": summary.get("alerts"),
            "slo": (summary.get("slo") or {}).get("ok"),
        },
    }

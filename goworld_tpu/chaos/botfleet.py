"""Subprocess bot fleets: thousands of REAL client sockets for the
massive fan-out floor (``bench.py --fanout-massive``).

The ``--multigame`` move applied to the CLIENT side: one parent process
cannot pump 1000+ asyncio sockets beside an in-process cluster on a small
host, so the bots live in K fleet subprocesses of N bots each, each a full
:class:`goworld_tpu.client.ClientBot` (entity mirrors, keyframe/delta
decode, strict protocol checks) — not a byte-counting stub. The parent
drives fleets over a line-oriented JSON stdio protocol:

    parent -> child   {"cmd": "report"}
                      {"cmd": "reconnect_dead"}
                      {"cmd": "quit"}
    child -> parent   one JSON object per command (see _report)

plus a spontaneous ``{"ready": N}`` line once every bot's socket is
connected. Counters of interest per fleet: delivered sync records split
keyframe/delta, client-wire sync payload bytes (the bytes/client/s
numerator), players assigned, live sockets, and protocol errors — a delta
record arriving before any keyframe (stale baseline) is counted as an
error by the ClientBot decode, which is exactly the reconnect-storm
assertion.

Run directly:  python -m goworld_tpu.chaos.botfleet --gates 7001,7002 \
                      --bots 252 [--host 127.0.0.1] [--stagger-ms 3]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
import threading
from typing import Optional

from goworld_tpu.client.client import ClientBot
from goworld_tpu.netutil.packet import Packet
from goworld_tpu.proto.msgtypes import MsgType


class CountingBot(ClientBot):
    """ClientBot plus fleet counters (records + client-wire sync bytes)."""

    def __init__(self, name: str, gate_index: int) -> None:
        # Long heartbeat: 1000 bots heartbeating every 5 s is pure noise
        # next to the sync streams being measured; the gates in the
        # massive harness run with heartbeat kills disabled.
        super().__init__(name=name, strict=False, heartbeat_interval=30.0)
        self.gate_index = gate_index
        self.sync_bytes = 0
        self.sync_packets = 0
        # A remote close surfaces only as the recv pump exiting (the
        # conn object's closed flag is set by local close/send errors),
        # so liveness is tracked at the pump.
        self.dead = False

    async def connect(self, host: str, port: int) -> None:
        self.dead = False
        await super().connect(host, port)

    async def _recv_loop(self) -> None:
        try:
            await super()._recv_loop()
        finally:
            self.dead = True

    def _handle(self, msgtype: int, packet: Packet) -> None:
        if msgtype in (MsgType.SYNC_POSITION_YAW_ON_CLIENTS,
                       MsgType.SYNC_POSITION_YAW_DELTA_ON_CLIENTS):
            self.sync_bytes += packet.payload_len()
            self.sync_packets += 1
        super()._handle(msgtype, packet)

    @property
    def alive(self) -> bool:
        return (not self.dead and self.conn is not None
                and not self.conn.closed)


class Fleet:
    """The child-process side: N bots across the given gate ports."""

    def __init__(self, host: str, ports: list[int], n_bots: int,
                 stagger_ms: float) -> None:
        self.host = host
        self.ports = ports
        self.n_bots = n_bots
        self.stagger = stagger_ms / 1000.0
        self.bots: list[CountingBot] = []

    async def connect_all(self) -> None:
        for i in range(self.n_bots):
            bot = CountingBot(f"fleet-bot{i}", i % len(self.ports))
            self.bots.append(bot)
            await bot.connect(self.host, self.ports[bot.gate_index])
            if self.stagger:
                # Pace the dial storm: 1000 simultaneous SYNs against a
                # 1-core host's accept loop time out before boot.
                await asyncio.sleep(self.stagger)

    async def reconnect_dead(self) -> dict:
        """Re-dial every bot whose socket died (the gate-kill reconnect
        storm): each tries the gates round-robin starting after its old
        one, so a killed gate's clients land on the survivor. The old
        mirror state is dropped — a reconnected client is a NEW client
        and must be served creation + keyframes from scratch."""
        moved = 0
        failed = 0
        for bot in self.bots:
            if bot.alive:
                continue
            await bot.close()
            bot.entities.clear()
            bot.player = None
            ok = False
            for k in range(1, len(self.ports) + 1):
                idx = (bot.gate_index + k) % len(self.ports)
                try:
                    await bot.connect(self.host, self.ports[idx])
                    bot.gate_index = idx
                    ok = True
                    break
                except OSError:
                    continue
            if ok:
                moved += 1
                if self.stagger:
                    await asyncio.sleep(self.stagger)
            else:
                failed += 1
        return {"reconnected": moved, "failed": failed}

    def report(self) -> dict:
        keyframes = sum(e.keyframes for b in self.bots
                        for e in b.entities.values())
        deltas = sum(e.deltas for b in self.bots
                     for e in b.entities.values())
        return {
            "bots": len(self.bots),
            "alive": sum(1 for b in self.bots if b.alive),
            "players": sum(1 for b in self.bots if b.player is not None),
            "entities": sum(len(b.entities) for b in self.bots),
            "keyframes": keyframes,
            "deltas": deltas,
            "records": keyframes + deltas,
            "sync_bytes": sum(b.sync_bytes for b in self.bots),
            "sync_packets": sum(b.sync_packets for b in self.bots),
            "errors": sum(len(b.errors) for b in self.bots),
            "error_samples": [err for b in self.bots
                              for err in b.errors][:5],
        }

    async def close_all(self) -> None:
        for bot in self.bots:
            await bot.close()


async def _amain(args: argparse.Namespace) -> int:
    fleet = Fleet(args.host, [int(p) for p in args.gates.split(",")],
                  args.bots, args.stagger_ms)
    loop = asyncio.get_running_loop()
    cmd_q: asyncio.Queue = asyncio.Queue()

    def stdin_pump() -> None:
        for line in sys.stdin:
            loop.call_soon_threadsafe(cmd_q.put_nowait, line)
        loop.call_soon_threadsafe(cmd_q.put_nowait, "")

    threading.Thread(target=stdin_pump, daemon=True).start()

    def emit(obj: dict) -> None:
        sys.stdout.write(json.dumps(obj, separators=(",", ":")) + "\n")
        sys.stdout.flush()

    await fleet.connect_all()
    emit({"ready": len(fleet.bots)})
    try:
        while True:
            line = await cmd_q.get()
            if not line.strip():
                return 0  # parent closed stdin
            cmd = json.loads(line).get("cmd")
            if cmd == "report":
                emit(fleet.report())
            elif cmd == "reconnect_dead":
                emit(await fleet.reconnect_dead())
            elif cmd == "quit":
                emit({"ok": True})
                return 0
            else:
                emit({"error": f"unknown cmd {cmd!r}"})
    finally:
        await fleet.close_all()


def main(argv: Optional[list] = None) -> int:
    parser = argparse.ArgumentParser(description="goworld_tpu bot fleet")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--gates", required=True,
                        help="comma-separated gate ports")
    parser.add_argument("--bots", type=int, required=True)
    parser.add_argument("--stagger-ms", type=float, default=3.0)
    args = parser.parse_args(argv)
    return asyncio.run(_amain(args))


if __name__ == "__main__":
    sys.exit(main())

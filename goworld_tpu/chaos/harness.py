"""In-process chaos cluster: real sockets, real services, injected faults.

The cluster plumbing mirrors ``bench.py --fanout`` (dispatcher + game +
gate over localhost TCP, protocol bots on the gate) extended to N
dispatchers and fault injectors. Everything runs in ONE asyncio loop and
ONE process — "killing a dispatcher" stops its service after aborting its
sockets (RST, not FIN: peers see a crash, not a shutdown), "pausing" one
stalls its logic/tick loops with sockets open (the half-open-link case the
liveness heartbeats exist for), the storage fault wraps the live backend
in a write-failing decorator, "killing the game" cancels its loop and
wipes the per-process entity world (registry kept — a fresh interpreter
re-importing the same server module), and "killing the gate" aborts every
client socket so a NEW gate process (fresh generation) takes the port.

Invariants every scenario asserts (ISSUE 3 acceptance):
- zero bot errors (bots run strict — any protocol inconsistency records);
- zero entity loss (every avatar still live on the game afterward);
- recovery within the scenario deadline, proven by a full RPC round trip
  (each bot Ping→Pong through gate → dispatcher → game and back).
"""

from __future__ import annotations

import asyncio
import os
import time
from typing import Optional

from goworld_tpu import telemetry
from goworld_tpu.client import ClientBot
from goworld_tpu.config.read_config import (
    AOIConfig,
    ClusterConfig,
    DeploymentConfig,
    DispatcherConfig,
    GameConfig,
    GateConfig,
    GoWorldConfig,
    KVDBConfig,
    StorageConfig,
    SyncConfig,
    TelemetryConfig,
)
from goworld_tpu.dispatcher import DispatcherService
from goworld_tpu.entity.entity import Entity
from goworld_tpu.entity.space import Space
from goworld_tpu.entity.vector import Vector3
from goworld_tpu.game import GameService
from goworld_tpu.game.service import RS_RUNNING
from goworld_tpu.gate import GateService
from goworld_tpu.utils import gwlog

AOI_DISTANCE = 100.0

# Per-scenario recovery time, scraped from /metrics and summed into the
# bench --chaos headline (satellite of ISSUE 10: today's harness only
# surfaced the worst recovery). Gauge, not histogram: each scenario runs
# once per suite and the CURRENT value is the interesting one.
_RECOVERY = telemetry.gauge(
    "chaos_recovery_seconds",
    "Recovery (or detection) seconds of the last run of each chaos "
    "scenario.", ("scenario", "transport"))


class _Holder:
    arena = None
    joined = 0


class ChaosSpace(Space):
    def on_space_created(self):
        if self.kind == 1:
            self.enable_aoi(AOI_DISTANCE)
            _Holder.arena = self


class ChaosAvatar(Entity):
    """Boot avatar: joins the shared arena and echoes Ping→Pong.

    ``pings`` is a Column attr (entity/columns.py): every scenario's RPC
    traffic reads/writes a slab column through the attrs surface, so the
    chaos catalog exercises columnar attrs across crashes, restarts and
    reconnect waves for free."""

    @classmethod
    def describe_entity_type(cls, desc):
        desc.set_use_aoi(True, AOI_DISTANCE)
        desc.define_attr("pings", "Column", dtype="int32")

    def on_client_connected(self):
        arena = _Holder.arena
        if arena is not None:
            # Clustered inside one AOI radius: full mutual interest, so
            # position syncs and creates fan out bot-to-bot (real traffic
            # shapes, like the fanout bench).
            x = 3.0 * _Holder.joined
            _Holder.joined += 1
            self.enter_space(arena.id, Vector3(x, 0.0, 10.0))
        self.set_client_syncing(True)

    def Ping_Client(self, n):
        self.attrs["pings"] = self.attrs.get_int("pings") + 1
        self.call_client("Pong", n)

    def on_client_disconnected(self):
        # A detached chaos avatar has no re-attach path (its client either
        # closed or died with a gate): despawn cleanly — AOI leaves fire
        # to the survivors, the slab slot quarantines per contract, and
        # the avatar census stays exact across gate kills.
        if not self.is_destroyed():
            self.destroy()


class FlakyBackend:
    """Storage-backend decorator failing the next ``fail_writes`` writes
    (reads stay healthy — the fault under test is a sick write path)."""

    def __init__(self, inner) -> None:
        self.inner = inner
        self.fail_writes = 0
        self.writes = 0
        self.failed = 0

    def write(self, typename: str, eid: str, data: dict) -> None:
        if self.fail_writes > 0:
            self.fail_writes -= 1
            self.failed += 1
            raise IOError("chaos: injected storage write failure")
        self.inner.write(typename, eid, data)
        self.writes += 1

    def read(self, typename: str, eid: str):
        return self.inner.read(typename, eid)

    def exists(self, typename: str, eid: str) -> bool:
        return self.inner.exists(typename, eid)

    def list_entity_ids(self, typename: str):
        return self.inner.list_entity_ids(typename)


def dropped_packet_count() -> float:
    """Sum of cluster_dropped_packets_total across all reasons."""
    fam = telemetry.family("cluster_dropped_packets_total")
    if fam is None:
        return 0.0
    return sum(child.value for _, child in fam.children())


class ChaosCluster:
    """N dispatchers + 1 game + 1 gate + strict bots, with fault hooks."""

    def __init__(
        self,
        run_dir: str,
        n_dispatchers: int = 2,
        n_bots: int = 12,
        *,
        peer_heartbeat_timeout: float = 1.0,
        down_buffer_bytes: int = 2 * 1024 * 1024,
        reconnect_max_interval: float = 1.0,
        sync_interval: float = 0.05,
        storage_knobs: Optional[dict] = None,
        sync_knobs: Optional[dict] = None,
        transport: str = "tcp",
    ) -> None:
        self.run_dir = run_dir
        self.n_dispatchers = n_dispatchers
        self.n_bots = n_bots
        self.peer_heartbeat_timeout = peer_heartbeat_timeout
        # "uds": the game/gate↔dispatcher links ride Unix-domain sockets
        # (socket files under run_dir) — crash/replay/liveness semantics
        # must be transport-identical, and every scenario asserts exactly
        # that when run on both transports (bench.py --chaos).
        self.transport = transport
        self.uds_dir = run_dir if transport == "uds" else None
        self.cluster_cfg = ClusterConfig(
            down_buffer_bytes=down_buffer_bytes,
            peer_heartbeat_timeout=peer_heartbeat_timeout,
            reconnect_max_interval=reconnect_max_interval,
            transport=transport,
            uds_dir=run_dir if transport == "uds" else "",
        )
        self.sync_interval = sync_interval
        self.storage_knobs = storage_knobs or {}
        # [sync] overrides (tier cadences / quantize bits) — the
        # keyframe-storm scenario needs the delta plane live so enter
        # waves force attributable new_pair keyframes.
        self.sync_knobs = sync_knobs or {}
        self.dispatchers: list[Optional[DispatcherService]] = []
        self.ports: list[int] = []
        self.game: Optional[GameService] = None
        self.gate: Optional[GateService] = None
        self.bots: list[ClientBot] = []
        self._game_task: Optional[asyncio.Task] = None
        self._ping_seq = 0
        self._pongs: dict[str, list] = {}
        self._bot_gen = 0

    # --- lifecycle ----------------------------------------------------------

    async def start(self) -> None:
        from goworld_tpu.entity import entity_manager as em

        em.cleanup_for_tests()
        _Holder.arena = None
        _Holder.joined = 0
        em.register_space(ChaosSpace)
        em.register_entity(ChaosAvatar)
        for i in range(self.n_dispatchers):
            d = DispatcherService(
                i + 1, desired_games=1, desired_gates=1,
                peer_heartbeat_timeout=self.peer_heartbeat_timeout)
            await d.start(uds_dir=self.uds_dir)
            self.dispatchers.append(d)
            self.ports.append(d.port)

        cfg = GoWorldConfig()
        cfg.deployment = DeploymentConfig(
            desired_games=1, desired_gates=1,
            desired_dispatchers=self.n_dispatchers)
        cfg.dispatchers = {
            i + 1: DispatcherConfig(port=p) for i, p in enumerate(self.ports)
        }
        cfg.games = {1: GameConfig(
            boot_entity="ChaosAvatar", save_interval=0.0,
            position_sync_interval=self.sync_interval)}
        cfg.gates = {1: GateConfig(
            port=0, position_sync_interval=self.sync_interval,
            heartbeat_timeout=30.0)}
        cfg.aoi = AOIConfig(backend="xzlist")  # host pipeline only, no jax
        cfg.storage = StorageConfig(
            type="filesystem", directory=self.run_dir + "/es",
            **self.storage_knobs)
        cfg.kvdb = KVDBConfig(
            type="filesystem", directory=self.run_dir + "/kv")
        if self.sync_knobs:
            cfg.sync = SyncConfig(**self.sync_knobs)
        cfg.cluster = self.cluster_cfg
        # Black boxes (ISSUE 20): every chaos service appends telemetry
        # frames to a crash-survivable history ring under run_dir — after
        # a kill the ring is the only record of the victim's final ticks,
        # and emit_postmortem() bundles it.
        self.history_dir = os.path.join(self.run_dir, "history")
        cfg.telemetry = TelemetryConfig(
            history_dir=self.history_dir, history_interval=0.2)
        self.cfg = cfg

        self.game = GameService(1, cfg, restore=False)
        self._game_task = asyncio.get_running_loop().create_task(
            self.game.run_async())
        self.gate = GateService(1, cfg)
        await self.gate.start()
        await self._wait(lambda: self.game.deployment_ready, 15.0,
                         "cluster never became deployment-ready")
        em.create_space_locally(1)
        assert _Holder.arena is not None
        # The gate's bound port survives restarts: a recreated GateService
        # must come back on the SAME address or clients could never
        # reconnect to a crashed gate in production either.
        self.cfg.gates[1].port = self.gate.port
        await self._spawn_bots()

    async def _spawn_bots(self) -> None:
        """Connect a fresh strict-bot fleet (initial boot, and the client
        reconnect wave after a game or gate crash)."""
        from goworld_tpu.entity import entity_manager as em

        self._bot_gen += 1
        gen = self._bot_gen
        for i in range(self.n_bots):
            bot = ClientBot(name=f"chaosbot{gen}.{i}", strict=True,
                            heartbeat_interval=1.0)
            self._pongs[bot.name] = []
            bot.rpc_handlers[(None, "Pong")] = (
                lambda entity, n, name=bot.name: self._pongs[name].append(n))
            await bot.connect("127.0.0.1", self.gate.port)
            await bot.wait_player(timeout=10)
            self.bots.append(bot)
        await self._wait(
            lambda: sum(1 for e in em.entities().values()
                        if e.typename == "ChaosAvatar"
                        and e.client is not None) == self.n_bots,
            15.0, "bots never all attached to avatars")

    async def close_bots(self) -> None:
        for b in self.bots:
            await b.close()
        self.bots.clear()

    async def stop(self) -> None:
        from goworld_tpu import kvdb, storage
        from goworld_tpu.entity import entity_manager as em
        from goworld_tpu.utils import post

        for b in self.bots:
            await b.close()
        if self.gate is not None:
            await self.gate.stop()
        if self.game is not None:
            self.game.terminate()
            try:
                await asyncio.wait_for(self._game_task, timeout=10)
            except Exception:
                pass
        for d in self.dispatchers:
            if d is not None:
                await d.stop()
        storage.set_backend(None)
        kvdb.set_backend(None)
        em.cleanup_for_tests()
        post.clear()

    async def _wait(self, cond, timeout: float, what: str) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if cond():
                return
            await asyncio.sleep(0.01)
        raise AssertionError(f"chaos: {what} (after {timeout:.1f}s)")

    # --- invariants ---------------------------------------------------------

    def bot_errors(self) -> list[str]:
        return [err for b in self.bots for err in b.errors]

    def live_avatars(self) -> int:
        from goworld_tpu.entity import entity_manager as em

        return sum(1 for e in em.entities().values()
                   if e.typename == "ChaosAvatar")

    def links_up(self) -> bool:
        return all(
            m.proxy is not None
            for svc in (self.game, self.gate)
            for m in svc.cluster._mgrs
        )

    def collector_targets(self):
        """Cluster-collector targets over the LIVE service objects (the
        in-process analog of the production loopback scrape — the health
        provider slot is process-global, so an in-process cluster feeds
        the collector directly; the summary code path is identical).
        Closures read ``self.<service>`` at fetch time, so a killed and
        recreated service is picked up without rebuilding targets."""

        def disp_fetch(i: int):
            async def fetch() -> dict:
                d = self.dispatchers[i]
                if d is None:
                    raise RuntimeError("dispatcher killed")
                return {"health": d._health(), "metrics": {}}

            return fetch

        async def game_fetch() -> dict:
            if self.game is None or self.game.run_state != RS_RUNNING:
                raise RuntimeError("game down")
            return {"health": self.game._health(), "metrics": {}}

        async def gate_fetch() -> dict:
            if self.gate is None:
                raise RuntimeError("gate down")
            return {"health": self.gate._health(), "metrics": {}}

        targets = [(f"dispatcher{i + 1}", disp_fetch(i))
                   for i in range(self.n_dispatchers)]
        targets.append(("game1", game_fetch))
        targets.append(("gate1", gate_fetch))
        return targets

    async def assert_cluster_view_converged(
            self, deadline: float = 20.0) -> float:
        """ISSUE 13: after a scenario, recovery is judged from the
        AGGREGATED view too — poll a ClusterCollector over the live
        services until every process reports, the client census is
        conserved at the bot count, and no stale generation row (or any
        other alert) remains. Returns seconds until convergence."""
        import json as _json

        from goworld_tpu.telemetry.collector import ClusterCollector

        coll = ClusterCollector(self.collector_targets(), interval=0.05)
        t0 = time.monotonic()
        last = None
        while time.monotonic() - t0 < deadline:
            await coll.poll_once()
            summary = coll.view()["summary"]
            census = summary["census"]
            if (summary["reporting"] == summary["expected"]
                    and not summary["alerts"]
                    and census["clients_conserved"]
                    and census["gate_clients"] == len(self.bots)):
                return time.monotonic() - t0
            last = summary
            await asyncio.sleep(0.05)
        raise AssertionError(
            "chaos: /cluster view never re-converged: "
            f"{_json.dumps(last, default=str)}")

    async def assert_rpc_roundtrip(self, deadline: float = 10.0) -> float:
        """Every bot pings its avatar; returns seconds until every pong
        landed. Packets buffered in replay rings count — the deadline spans
        reconnect + replay, which is exactly the recovery being measured."""
        self._ping_seq += 1
        n = self._ping_seq
        t0 = time.monotonic()
        for b in self.bots:
            assert b.player is not None, f"{b.name}: player mirror lost"
            b.player.call_server("Ping_Client", n)
        await self._wait(
            lambda: all(n in self._pongs[b.name] for b in self.bots),
            deadline, f"ping {n}: not every bot got its pong")
        return time.monotonic() - t0

    async def emit_postmortem(self, reason: str) -> str:
        """ISSUE 20: bundle the cluster's black box — every history ring
        under run_dir (dead incarnations included: their rings outlive
        them), the live span ring and flight dump, plus one final
        aggregated cluster view. Returns the bundle directory path."""
        from goworld_tpu.telemetry import tracing
        from goworld_tpu.telemetry.collector import ClusterCollector
        from goworld_tpu.telemetry.postmortem import collect_bundle

        view = None
        try:
            coll = ClusterCollector(self.collector_targets(), interval=0.05)
            await coll.poll_once()
            view = coll.view()
        except Exception:
            pass  # a half-dead cluster still gets its rings bundled
        # One asyncio loop, one process-global span ring: the scrape is
        # shared, like a whole cluster co-hosted on one box.
        spans = {"chaos": tracing.snapshot()}
        flights = {}
        if self.game is not None and self.game.flight is not None:
            flights["game1"] = self.game.flight.snapshot()
        out = os.path.join(
            self.run_dir, f"postmortem-{reason.replace('/', '_')}")
        collect_bundle(out, reason=reason, history_dir=self.history_dir,
                       cluster_view=view, process_spans=spans,
                       flights=flights)
        gwlog.infof("chaos: post-mortem bundle at %s (reason=%s)",
                    out, reason)
        return out

    # --- fault injectors ----------------------------------------------------

    async def kill_dispatcher(self, i: int) -> None:
        """Crash semantics: RST every peer socket, then stop the service
        (a clean stop would FIN-close, which a crash never does)."""
        d = self.dispatchers[i]
        assert d is not None
        for proxy in list(d._conns):
            proxy.conn.abort()
        await d.stop()
        self.dispatchers[i] = None
        gwlog.infof("chaos: dispatcher %d killed (port %d)",
                    i + 1, self.ports[i])

    async def restart_dispatcher(self, i: int) -> None:
        d = DispatcherService(
            i + 1, desired_games=1, desired_gates=1,
            peer_heartbeat_timeout=self.peer_heartbeat_timeout)
        for _ in range(100):  # the old socket may linger briefly
            try:
                await d.start(port=self.ports[i], uds_dir=self.uds_dir)
                break
            except OSError:
                await asyncio.sleep(0.05)
        else:
            raise AssertionError(
                f"chaos: could not rebind dispatcher port {self.ports[i]}")
        self.dispatchers[i] = d
        gwlog.infof("chaos: dispatcher %d restarted", i + 1)

    def sever_game_link(self, i: int) -> None:
        """Abort the game↔dispatcher-i socket mid-tick (RST, not close)."""
        m = self.game.cluster._mgrs[i]
        assert m.proxy is not None, "link already down"
        m.proxy.conn.abort()

    def pause_dispatcher(self, i: int) -> None:
        self.dispatchers[i].pause()

    def resume_dispatcher(self, i: int) -> None:
        self.dispatchers[i].resume()

    async def kill_game(self) -> None:
        """Crash the game process-equivalent: RST its dispatcher links,
        cancel its loop, and wipe the per-process entity world (the type
        registry survives, exactly like a fresh interpreter re-importing
        the same server module). Peers see a died game, not a shutdown."""
        from goworld_tpu.entity import entity_manager as em

        assert self.game is not None
        for m in self.game.cluster._mgrs:
            if m.proxy is not None:
                m.proxy.conn.abort()
        self._game_task.cancel()
        try:
            await self._game_task
        except (asyncio.CancelledError, Exception):
            pass
        self.game = None
        self._game_task = None
        em.reset_world()
        _Holder.arena = None
        _Holder.joined = 0
        gwlog.infof("chaos: game killed (world wiped, registry kept)")

    async def restart_game(self) -> None:
        """Cold-boot a replacement game with the same gameid (restore=False
        — a crash left no freeze file; entities are gone, not frozen)."""
        from goworld_tpu.entity import entity_manager as em

        self.game = GameService(1, self.cfg, restore=False)
        self._game_task = asyncio.get_running_loop().create_task(
            self.game.run_async())
        await self._wait(lambda: self.game.deployment_ready, 15.0,
                         "recreated game never became ready")
        em.create_space_locally(1)
        assert _Holder.arena is not None
        gwlog.infof("chaos: game recreated")

    async def kill_gate(self) -> None:
        """Crash the gate: RST every client socket and dispatcher link,
        then drop the listeners. Clients see a dead server; the
        dispatcher sees a vanished gate (reconnect-grace window starts)."""
        assert self.gate is not None
        for cp in list(self.gate.clients.values()):
            cp.conn.conn.abort()
        for m in self.gate.cluster._mgrs:
            if m.proxy is not None:
                m.proxy.conn.abort()
        await self.gate.stop()
        self.gate = None
        gwlog.infof("chaos: gate killed")

    async def restart_gate(self) -> None:
        """A NEW gate process on the same port: its fresh handshake makes
        the dispatchers detach the dead predecessor's client bindings on
        every game before traffic flows."""
        gate = GateService(1, self.cfg)
        for _ in range(100):  # the old socket may linger briefly
            try:
                await gate.start()
                break
            except OSError:
                await asyncio.sleep(0.05)
        else:
            raise AssertionError(
                f"chaos: could not rebind gate port "
                f"{self.cfg.gates[1].port}")
        self.gate = gate
        gwlog.infof("chaos: gate restarted on port %d", gate.port)


# --- scenarios ---------------------------------------------------------------


async def scenario_dispatcher_restart(
    cluster: ChaosCluster, downtime: float = 0.3, victim: int = 0,
    recovery_deadline: float = 10.0,
) -> dict:
    """Kill one dispatcher (of >= 2) under live bots, ping THROUGH the
    outage (sends buffer in replay rings), restart it, and require every
    pong + zero drops + zero bot errors + zero entity loss."""
    await cluster.assert_rpc_roundtrip()
    drops0 = dropped_packet_count()
    await cluster.kill_dispatcher(victim)
    # Pings issued while the dispatcher is DOWN: gate/game sends to it park
    # in the replay rings and must be delivered after the reconnect.
    cluster._ping_seq += 1
    mid = cluster._ping_seq
    for b in cluster.bots:
        b.player.call_server("Ping_Client", mid)
    await asyncio.sleep(downtime)
    t0 = time.monotonic()
    await cluster.restart_dispatcher(victim)
    await cluster._wait(cluster.links_up, recovery_deadline,
                        "links never reconnected after dispatcher restart")
    await cluster._wait(
        lambda: all(mid in cluster._pongs[b.name] for b in cluster.bots),
        recovery_deadline, "mid-outage pings were lost")
    rt = await cluster.assert_rpc_roundtrip(recovery_deadline)
    recovery = time.monotonic() - t0
    drops = dropped_packet_count() - drops0
    errors = cluster.bot_errors()
    assert not errors, f"bot errors during dispatcher restart: {errors[:5]}"
    assert drops == 0, f"{drops} packets dropped (ring overflow?)"
    assert cluster.live_avatars() == cluster.n_bots, "entity loss"
    # Column attrs rode the outage: every avatar's ping counter (a slab
    # column behind the attrs surface) recorded the mid-outage ping too.
    from goworld_tpu.entity import entity_manager as em

    for e in em.entities().values():
        if e.typename == "ChaosAvatar":
            assert e.attrs.get_int("pings") >= 2, "column attr lost pings"
    _RECOVERY.labels("dispatcher_restart", cluster.transport).set(recovery)
    return {"scenario": "dispatcher_restart", "recovery_s": round(recovery, 3),
            "post_roundtrip_s": round(rt, 3), "dropped": drops,
            "bot_errors": len(errors)}


async def scenario_severed_link(
    cluster: ChaosCluster, victim: int = 0, recovery_deadline: float = 10.0,
) -> dict:
    """RST the game↔dispatcher link mid-tick; the reconnect loop must
    restore it and buffered sends must replay."""
    await cluster.assert_rpc_roundtrip()
    drops0 = dropped_packet_count()
    t0 = time.monotonic()
    cluster.sever_game_link(victim)
    await cluster._wait(cluster.links_up, recovery_deadline,
                        "severed link never reconnected")
    rt = await cluster.assert_rpc_roundtrip(recovery_deadline)
    recovery = time.monotonic() - t0
    drops = dropped_packet_count() - drops0
    errors = cluster.bot_errors()
    assert not errors, f"bot errors after severed link: {errors[:5]}"
    assert drops == 0, f"{drops} packets dropped after severed link"
    assert cluster.live_avatars() == cluster.n_bots, "entity loss"
    _RECOVERY.labels("severed_link", cluster.transport).set(recovery)
    return {"scenario": "severed_link", "recovery_s": round(recovery, 3),
            "post_roundtrip_s": round(rt, 3), "dropped": drops,
            "bot_errors": len(errors)}


async def scenario_paused_dispatcher(
    cluster: ChaosCluster, victim: int = 0, recovery_deadline: float = 15.0,
) -> dict:
    """Stall a dispatcher past the heartbeat deadline (sockets open, loops
    frozen — the half-open case). Peers' liveness watchdogs must abort the
    silent links (converting the stall into reconnects) instead of waiting
    on the OS; after resume, traffic must flow again."""
    await cluster.assert_rpc_roundtrip()
    hb_kills0 = telemetry.counter(
        "cluster_link_heartbeat_kills_total").value
    cluster.pause_dispatcher(victim)
    # Past the deadline the game/gate watchdogs must have aborted the
    # victim's silent links at least once.
    pause_span = cluster.peer_heartbeat_timeout * 2.0 + 1.0
    t0 = time.monotonic()
    await cluster._wait(
        lambda: telemetry.counter(
            "cluster_link_heartbeat_kills_total").value > hb_kills0,
        pause_span + 5.0, "no liveness kill while dispatcher was stalled")
    detected = time.monotonic() - t0
    cluster.resume_dispatcher(victim)
    await cluster._wait(cluster.links_up, recovery_deadline,
                        "links never recovered after resume")
    rt = await cluster.assert_rpc_roundtrip(recovery_deadline)
    errors = cluster.bot_errors()
    assert not errors, f"bot errors across paused dispatcher: {errors[:5]}"
    assert cluster.live_avatars() == cluster.n_bots, "entity loss"
    _RECOVERY.labels("paused_dispatcher", cluster.transport).set(detected)
    return {"scenario": "paused_dispatcher",
            "detect_s": round(detected, 3),
            "post_roundtrip_s": round(rt, 3), "bot_errors": len(errors)}


async def scenario_storage_outage(
    cluster: ChaosCluster, failures: int = 25, n_saves: int = 10,
    recovery_deadline: float = 10.0,
) -> dict:
    """Fail the next N storage writes: the circuit must OPEN (worker not
    wedged — reads still served), saves defer, and once the backend heals
    every deferred save must land within the deadline."""
    from goworld_tpu import storage
    from goworld_tpu.storage.circuit import CircuitBreaker

    flaky = FlakyBackend(storage.get_backend())
    storage.set_backend(flaky)
    flaky.fail_writes = failures
    t0 = time.monotonic()
    for k in range(n_saves):
        storage.save("ChaosDoc", f"doc{k:03d}", {"k": k})
    await cluster._wait(
        lambda: storage.circuit_state() == CircuitBreaker.OPEN,
        recovery_deadline, "circuit never opened under write failures")
    opened = time.monotonic() - t0
    # Worker must still serve reads while the circuit is open.
    got: list = []
    storage.load("ChaosDoc", "doc000", lambda r, e: got.append((r, e)))
    from goworld_tpu.utils import post as _post

    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and not got:
        _post.tick()  # completion callbacks ride the post queue
        await asyncio.sleep(0.02)
    assert got and got[0][1] is None, "worker wedged: load never completed"
    # Backend heals; periodic saves (the save_interval crontab in prod)
    # probe the half-open circuit and flush the deferred queue.
    flaky.fail_writes = 0
    t1 = time.monotonic()
    k = n_saves
    while (storage.deferred_count()
           or storage.circuit_state() != CircuitBreaker.CLOSED):
        if time.monotonic() - t1 > recovery_deadline:
            raise AssertionError(
                f"storage never recovered: state={storage.circuit_state()} "
                f"deferred={storage.deferred_count()}")
        storage.save("ChaosDoc", f"doc{k:03d}", {"k": k})
        k += 1
        await asyncio.sleep(0.1)
    storage.wait_clear(10.0)
    recovery = time.monotonic() - t1
    missing = [i for i in range(n_saves)
               if flaky.inner.read("ChaosDoc", f"doc{i:03d}") is None]
    assert not missing, f"saves lost across the outage: {missing}"
    _RECOVERY.labels("storage_outage", cluster.transport).set(recovery)
    return {"scenario": "storage_outage", "open_after_s": round(opened, 3),
            "recovery_s": round(recovery, 3),
            "failed_writes": flaky.failed, "lost_saves": len(missing),
            "bot_errors": len(cluster.bot_errors())}


async def scenario_service_outage_dispatcher_restart(
    cluster: ChaosCluster, failures: int = 25, ops: int = 96,
    recovery_deadline: float = 15.0,
) -> dict:
    """ISSUE 18 catalog cross: the service_heavy workload's storage
    outage UNDER a dispatcher restart — both control planes sick at
    once. Shard-routed service receipts + storage saves flow while (a)
    the backend fails writes past the breaker threshold AND (b) a
    dispatcher dies and restarts mid-outage. The circuit must open (not
    wedge), pings issued through the dispatcher outage must all land
    after the reconnect (replay rings), the routing trajectory must stay
    exactly-once per shard, and once the backend heals every deferred
    save must land: zero lost documents, zero bot errors, zero entity
    loss."""
    from goworld_tpu import service, storage
    from goworld_tpu.storage.circuit import CircuitBreaker

    flaky = FlakyBackend(storage.get_backend())
    storage.set_backend(flaky)
    kind_shards = {"chat": 4, "mail": 2, "ranking": 2}
    kinds = tuple(kind_shards)
    receipts: dict[str, list[int]] = {
        k: [0] * s for k, s in kind_shards.items()}
    expected: dict[str, dict] = {}
    seq = 0

    def issue(n: int) -> None:
        nonlocal seq
        for _ in range(n):
            kind = kinds[seq % len(kinds)]
            shard = service.shard_by_key(
                f"user{seq}", kind_shards[kind])
            receipts[kind][shard] += 1
            doc = f"svc-{kind}-{shard}-{seq % 8}"
            payload = {"seq": seq, "kind": kind}
            expected[doc] = payload
            storage.save("ChaosSvcDoc", doc, payload)
            seq += 1

    await cluster.assert_rpc_roundtrip()
    issue(ops // 3)  # healthy baseline traffic
    # The cross: storage outage and dispatcher kill land TOGETHER.
    flaky.fail_writes = failures
    await cluster.kill_dispatcher(0)
    cluster._ping_seq += 1
    mid = cluster._ping_seq
    for b in cluster.bots:
        b.player.call_server("Ping_Client", mid)  # parks in replay rings
    issue(ops // 3)  # service traffic INTO the double fault
    await cluster._wait(
        lambda: storage.circuit_state() == CircuitBreaker.OPEN,
        recovery_deadline,
        "circuit never opened under the dispatcher-restart cross")
    t0 = time.monotonic()
    await cluster.restart_dispatcher(0)
    await cluster._wait(
        cluster.links_up, recovery_deadline,
        "links never reconnected (storage outage + dispatcher restart)")
    await cluster._wait(
        lambda: all(mid in cluster._pongs[b.name] for b in cluster.bots),
        recovery_deadline, "mid-cross pings were lost")
    # Backend heals AFTER the cluster plane: saves keep probing the
    # half-open circuit until it closes and the deferred queue drains.
    flaky.fail_writes = 0
    issue(ops - 2 * (ops // 3))
    t1 = time.monotonic()
    while (storage.deferred_count()
           or storage.circuit_state() != CircuitBreaker.CLOSED):
        if time.monotonic() - t1 > recovery_deadline:
            raise AssertionError(
                f"storage never recovered under the cross: "
                f"state={storage.circuit_state()} "
                f"deferred={storage.deferred_count()}")
        issue(1)
        await asyncio.sleep(0.1)
    storage.wait_clear(10.0)
    recovery = time.monotonic() - t0
    # Exactly-once receipts: the shard routing trajectory is
    # deterministic in seq, so a replayed/duplicated op would break the
    # recomputed totals.
    want: dict[str, list[int]] = {
        k: [0] * s for k, s in kind_shards.items()}
    for i in range(seq):
        kind = kinds[i % len(kinds)]
        want[kind][service.shard_by_key(f"user{i}", kind_shards[kind])] += 1
    assert receipts == want, (
        f"shard receipts not exactly-once: {receipts} != {want}")
    missing = [d for d, payload in expected.items()
               if flaky.inner.read("ChaosSvcDoc", d) != payload]
    assert not missing, (
        f"saves lost/stale across the cross: {missing[:5]}")
    rt = await cluster.assert_rpc_roundtrip(recovery_deadline)
    errors = cluster.bot_errors()
    assert not errors, f"bot errors across the cross: {errors[:5]}"
    assert cluster.live_avatars() == cluster.n_bots, "entity loss"
    _RECOVERY.labels(
        "service_outage_dispatcher_restart", cluster.transport).set(recovery)
    return {"scenario": "service_outage_dispatcher_restart",
            "recovery_s": round(recovery, 3),
            "post_roundtrip_s": round(rt, 3),
            "ops": seq, "failed_writes": flaky.failed,
            "lost_saves": len(missing), "bot_errors": len(errors)}


async def scenario_game_kill_recreate(
    cluster: ChaosCluster, downtime: float = 0.3,
    recovery_deadline: float = 20.0,
) -> dict:
    """Crash THE GAME under live bots, recreate it cold, and require a
    consistent world afterwards: the dispatcher purges the dead
    incarnation's entity routes at the cold-boot handshake (no RPC ever
    routes at a ghost), clients reconnect and get fresh avatars, the
    avatar census returns to exactly n_bots with full AOI interest, and
    no bot sees a protocol inconsistency (strict mode)."""
    await cluster.assert_rpc_roundtrip()
    await cluster.kill_game()
    await asyncio.sleep(downtime)
    t0 = time.monotonic()
    await cluster.restart_game()
    # The dead incarnation's clients can't be re-attached (no boot flow
    # re-runs for an existing connection) — clients reconnect, exactly as
    # they would after a real server crash.
    await cluster.close_bots()
    await cluster._spawn_bots()
    await cluster._wait(cluster.links_up, recovery_deadline,
                        "links never recovered after game recreate")
    rt = await cluster.assert_rpc_roundtrip(recovery_deadline)
    recovery = time.monotonic() - t0
    errors = cluster.bot_errors()
    assert not errors, f"bot errors across game kill: {errors[:5]}"
    assert cluster.live_avatars() == cluster.n_bots, (
        f"avatar census wrong after recreate: {cluster.live_avatars()} "
        f"!= {cluster.n_bots}")
    # AOI consistency: the recreated arena re-derived full mutual
    # interest (every avatar sees every other).
    from goworld_tpu.entity import entity_manager as em

    avs = [e for e in em.entities().values()
           if e.typename == "ChaosAvatar"]
    assert all(len(a.interested_by) == cluster.n_bots - 1 for a in avs), (
        "AOI interest not re-derived after game recreate")
    _RECOVERY.labels("game_kill_recreate", cluster.transport).set(recovery)
    return {"scenario": "game_kill_recreate",
            "recovery_s": round(recovery, 3),
            "post_roundtrip_s": round(rt, 3), "bot_errors": len(errors)}


async def scenario_gate_kill_reconnect(
    cluster: ChaosCluster, downtime: float = 0.3,
    recovery_deadline: float = 20.0,
) -> dict:
    """Crash THE GATE under strict bots: every client socket dies. A NEW
    gate process takes the port; its fresh handshake makes the
    dispatchers detach the dead incarnation's client bindings on the game
    (orphaned avatars despawn cleanly, with AOI leaves), clients
    reconnect and get fresh avatars, and no record ever misroutes across
    clients (strict bots would flag a sync/RPC for an entity they never
    saw)."""
    await cluster.assert_rpc_roundtrip()
    await cluster.kill_gate()
    # Client sockets are dead: drop the bot objects (their recv loops
    # already exited) before anything reconnects.
    await cluster.close_bots()
    await asyncio.sleep(downtime)
    t0 = time.monotonic()
    await cluster.restart_gate()
    await cluster._spawn_bots()
    await cluster._wait(cluster.links_up, recovery_deadline,
                        "links never recovered after gate restart")
    rt = await cluster.assert_rpc_roundtrip(recovery_deadline)
    # The dead incarnation's avatars must despawn (detach → destroy), the
    # new fleet's census must be exact.
    await cluster._wait(
        lambda: cluster.live_avatars() == cluster.n_bots,
        recovery_deadline,
        f"orphaned avatars never despawned "
        f"(census {cluster.live_avatars()} != {cluster.n_bots})")
    recovery = time.monotonic() - t0
    errors = cluster.bot_errors()
    assert not errors, f"bot errors across gate kill: {errors[:5]}"
    _RECOVERY.labels("gate_kill_reconnect", cluster.transport).set(recovery)
    return {"scenario": "gate_kill_reconnect",
            "recovery_s": round(recovery, 3),
            "post_roundtrip_s": round(rt, 3), "bot_errors": len(errors)}


async def _royale_collapse(cluster: ChaosCluster, t_from: int, t_to: int,
                           ticks: int, r0: float = 400.0,
                           rf: float = 10.0) -> None:
    """Drive every live ChaosAvatar along the battle-royale shrinking
    ring (scenarios/battle_royale.py zone math — the SAME scenario
    definition the bench engines run, here moving real entities through
    real AOI).  Avatars are indexed by sorted eid so a respawned fleet
    resumes the collapse deterministically."""
    from goworld_tpu.entity import entity_manager as em
    from goworld_tpu.scenarios.battle_royale import royale_ring_positions

    for t in range(t_from, t_to):
        avs = sorted(
            (e for e in em.entities().values()
             if e.typename == "ChaosAvatar"), key=lambda e: e.id)
        ring = royale_ring_positions(
            len(avs), t, ticks, (0.0, 0.0), r0, rf)
        for a, (x, z) in zip(avs, ring):
            a.set_position(Vector3(x, 0.0, z))
        # One sync interval per zone tick: AOI diffs + position syncs
        # flow to the strict bots between moves.
        await asyncio.sleep(cluster.sync_interval)


def _royale_edges(cluster: ChaosCluster) -> int:
    """Directed interest-edge count across the live avatar fleet."""
    from goworld_tpu.entity import entity_manager as em

    return sum(len(e.interested_by) for e in em.entities().values()
               if e.typename == "ChaosAvatar")


def _edge_table_eids() -> list:
    """The slabs' device edge columns (the subj/wat slot pairs a batched
    AOI dispatch ships for the tier/verdict passes) canonicalized to eid
    space and sorted: slots are reassigned on restore, eids are the
    identity, so equality of two snapshots is bit-identity of the edge
    TABLE contents independent of slot numbering and row order."""
    from goworld_tpu.entity import entity_manager as em

    by_slot = {e._slot: e.id for e in em.entities().values()
               if e._slot >= 0}
    _ver, n, subj, wat = em.runtime.slabs.snapshot_edges_for_tiering()
    return sorted((by_slot[int(s)], by_slot[int(w)])
                  for s, w in zip(subj[:n], wat[:n]))


async def scenario_battle_royale_kill_game(
    cluster: ChaosCluster, ticks: int = 16, recovery_deadline: float = 20.0,
) -> dict:
    """The battle-royale workload on LIVE avatars crossed with a game
    crash: the boot cluster (full mutual interest) scatters onto the wide
    zone ring — a mass LEAVE wave, every edge dissolved — then the zone
    collapse begins; mid-collapse the game is killed and recreated cold,
    the clients reconnect onto fresh avatars, and the collapse resumes to
    the endgame disc — the mass ENTER wave back to full mutual interest.
    Census conserved at exactly n_bots, zero strict-bot errors, and the
    aggregated /cluster view re-converges with zero alerts."""
    n = cluster.n_bots
    await cluster.assert_rpc_roundtrip()
    assert _royale_edges(cluster) == n * (n - 1), (
        "boot fleet not fully mutually interested")
    # Scatter: ring spacing at the full zone exceeds AOI_DISTANCE.
    await _royale_collapse(cluster, 0, 2, ticks)
    scattered = _royale_edges(cluster)
    assert scattered == 0, (
        f"mass leave wave incomplete: {scattered} interest edges survive "
        f"the scatter onto the wide ring")
    await _royale_collapse(cluster, 2, ticks // 2, ticks)
    # Survivor-side census at the kill point: the aggregated view the
    # rest of the cluster agrees on, held against the victim's black box
    # after the crash (ISSUE 20 acceptance).
    from goworld_tpu.telemetry.collector import ClusterCollector

    coll = ClusterCollector(cluster.collector_targets(), interval=0.05)
    await coll.poll_once()
    pre_census = int(
        coll.view()["processes"]["game1"]["health"]["entities"])
    await cluster.kill_game()
    # The dead game can no longer serve /flight — its history ring is the
    # only record of its final ticks. Bundle it and hold the black box to
    # the survivor-side census: the newest flight rows must carry exactly
    # the entity count the aggregated view reported before the crash.
    bundle_dir = await cluster.emit_postmortem("battle_royale_kill_game")
    from goworld_tpu.telemetry.postmortem import load_bundle

    box = load_bundle(bundle_dir)["processes"].get("game1")
    assert box is not None and box["frames"], (
        "killed game left no history frames in the bundle")
    assert box["frames"][-1].get("final"), (
        "game ring missing its final (shutdown-path) frame")
    flight_rows = [t for f in box["frames"]
                   for t in (f.get("flight") or [])]
    assert len(flight_rows) >= 3, (
        f"bundle holds only {len(flight_rows)} of the victim's ticks")
    tail = flight_rows[-3:]
    assert all(int(t["entities"]) == pre_census for t in tail), (
        f"black-box census {[t['entities'] for t in tail]} != "
        f"survivor-side /cluster census {pre_census}")
    t0 = time.monotonic()
    await cluster.restart_game()
    # The dead incarnation's clients reconnect, exactly like a real crash.
    await cluster.close_bots()
    await cluster._spawn_bots()
    await cluster._wait(cluster.links_up, recovery_deadline,
                        "links never recovered after game kill mid-royale")
    # Resume the collapse on the fresh fleet, down to the endgame disc.
    await _royale_collapse(cluster, ticks // 2, ticks, ticks)
    rt = await cluster.assert_rpc_roundtrip(recovery_deadline)
    recovery = time.monotonic() - t0
    errors = cluster.bot_errors()
    assert not errors, f"bot errors across royale game kill: {errors[:5]}"
    assert cluster.live_avatars() == n, (
        f"royale census broken: {cluster.live_avatars()} != {n}")
    endgame = _royale_edges(cluster)
    assert endgame == n * (n - 1), (
        f"mass enter wave incomplete: {endgame} edges at the endgame disc, "
        f"expected full mutual interest {n * (n - 1)}")
    converge = await cluster.assert_cluster_view_converged()
    _RECOVERY.labels("battle_royale_kill_game", cluster.transport).set(
        recovery)
    return {"scenario": "battle_royale_kill_game",
            "recovery_s": round(recovery, 3),
            "post_roundtrip_s": round(rt, 3),
            "cluster_view_converge_s": round(converge, 3),
            "endgame_edges": endgame, "bot_errors": len(errors),
            "bundle": bundle_dir,
            "black_box_ticks": len(flight_rows)}


async def scenario_battle_royale_freeze_restore(
    cluster: ChaosCluster, ticks: int = 16, recovery_deadline: float = 20.0,
) -> dict:
    """The battle-royale collapse crossed with a freeze→restore reload
    (the SIGHUP hot-reload path): mid-collapse the game freezes to
    ``game<N>_freezed.dat`` and exits rc 2, the world is wiped (process
    death analog, registry kept), and a ``restore=True`` GameService
    resurrects every avatar — same eids, same positions, same column
    attrs, client bindings reattached quietly while the bots stay
    connected to the gate.  The collapse then resumes on the RESTORED
    fleet to full endgame interest; census conserved, zero strict-bot
    errors, /cluster re-converges alert-free."""
    import os

    from goworld_tpu.entity import entity_manager as em

    n = cluster.n_bots
    await cluster.assert_rpc_roundtrip()
    await _royale_collapse(cluster, 0, ticks // 2, ticks)
    frozen = {
        e.id: (e.position.x, e.position.z, e.attrs.get_int("pings"))
        for e in em.entities().values() if e.typename == "ChaosAvatar"}
    assert len(frozen) == n
    # Pre-freeze device edge columns in eid space (ISSUE 19): restore
    # rebuilds interest from scratch (freeze data carries no edges), and
    # identical positions must reconverge to a bit-identical edge table.
    pre_edges = _edge_table_eids()
    # The freeze file lands in cwd (game/service.py freeze_filename) —
    # point cwd at the run dir for the freeze->restore window.
    prev_cwd = os.getcwd()
    os.chdir(cluster.run_dir)
    try:
        cluster.game.start_freeze()
        rc = await asyncio.wait_for(cluster._game_task, timeout=15)
        assert rc == 2, f"freeze exit code {rc} != 2"
        t0 = time.monotonic()
        # Process-death analog: wipe the world, keep the type registry.
        em.reset_world()
        _Holder.arena = None
        _Holder.joined = 0
        cluster.game = GameService(1, cluster.cfg, restore=True)
        cluster._game_task = asyncio.get_running_loop().create_task(
            cluster.game.run_async())
        await cluster._wait(lambda: cluster.game.deployment_ready, 15.0,
                            "restored game never became ready")
    finally:
        os.chdir(prev_cwd)
    # Restore re-creates spaces without on_space_created: re-point the
    # holder at the resurrected arena (and keep spawn offsets moving).
    for e in em.entities().values():
        if isinstance(e, ChaosSpace) and e.kind == 1:
            _Holder.arena = e
    assert _Holder.arena is not None, "arena space did not survive restore"
    _Holder.joined = n
    await cluster._wait(cluster.links_up, recovery_deadline,
                        "links never recovered after freeze restore")
    # Same avatars, not replacements: eids, positions and the pings
    # column attr all survived the reload.
    restored = {
        e.id: (e.position.x, e.position.z, e.attrs.get_int("pings"))
        for e in em.entities().values() if e.typename == "ChaosAvatar"}
    assert restored.keys() == frozen.keys(), (
        "avatar identity not conserved across freeze restore")
    for eid, (x, z, pings) in frozen.items():
        rx, rz, rpings = restored[eid]
        assert abs(rx - x) < 1e-6 and abs(rz - z) < 1e-6, (
            f"{eid}: position drifted across restore")
        assert rpings == pings, f"{eid}: pings column lost across restore"
    # Interest rebuilt from scratch must land on the SAME edge table the
    # frozen world had: positions are bit-identical, so the rebuilt
    # device edge columns must be too (eid space — slots renumber).
    await cluster._wait(
        lambda: _edge_table_eids() == pre_edges, recovery_deadline,
        "post-restore edge table never reconverged bit-identical to the "
        "pre-freeze device edge columns")
    # Resume the collapse on the restored fleet.
    await _royale_collapse(cluster, ticks // 2, ticks, ticks)
    rt = await cluster.assert_rpc_roundtrip(recovery_deadline)
    recovery = time.monotonic() - t0
    errors = cluster.bot_errors()
    assert not errors, f"bot errors across freeze restore: {errors[:5]}"
    assert cluster.live_avatars() == n, (
        f"royale census broken: {cluster.live_avatars()} != {n}")
    endgame = _royale_edges(cluster)
    assert endgame == n * (n - 1), (
        f"endgame interest incomplete after restore: {endgame} != "
        f"{n * (n - 1)}")
    converge = await cluster.assert_cluster_view_converged()
    _RECOVERY.labels("battle_royale_freeze_restore", cluster.transport).set(
        recovery)
    return {"scenario": "battle_royale_freeze_restore",
            "recovery_s": round(recovery, 3),
            "post_roundtrip_s": round(rt, 3),
            "cluster_view_converge_s": round(converge, 3),
            "restored_edge_table_rows": len(pre_edges),
            "endgame_edges": endgame, "bot_errors": len(errors)}


def _kf_forced(reason: str) -> float:
    """Current sync_keyframes_forced_total{reason=...} value."""
    fam = telemetry.family("sync_keyframes_forced_total")
    if fam is None:
        return 0.0
    return sum(child.value for labels, child in fam.children()
               if reason in labels)


async def scenario_battle_royale_keyframe_storm(
    cluster: ChaosCluster, ticks: int = 16, waves: int = 2,
    recovery_deadline: float = 30.0,
) -> dict:
    # recovery_deadline spans a possible heartbeat-dropped link reconnect
    # (5s buffering window) on a loaded CI host, not just the sync lag.
    """ISSUE 18 keyframe-storm assertion: battle-royale ENTER waves on a
    cluster running the delta sync plane ([sync] quantize_bits > 0 — the
    cluster must be built with sync_knobs). Each wave scatters the fleet
    (every interest edge dissolves) then collapses it back to the endgame
    disc (a mass enter wave re-forming full mutual interest); every
    re-formed (subject, watcher) pair's FIRST record must be a forced
    full-precision keyframe, so sync_keyframes_forced_total{reason=
    new_pair} must grow in lockstep with the wave's edge census — at
    least one keyframe per re-formed pair, every wave. The strict bots
    independently prove the same contract from the wire: a delta record
    before a keyframe is a protocol error."""
    n = cluster.n_bots
    await cluster.assert_rpc_roundtrip()
    per_wave: list[int] = []
    for _ in range(waves):
        # Scatter: ring spacing at the full zone exceeds AOI_DISTANCE, so
        # the NEXT collapse is a pure enter wave over invalid baselines.
        await _royale_collapse(cluster, 0, 2, ticks)
        await cluster._wait(
            lambda: _royale_edges(cluster) == 0, recovery_deadline,
            "scatter never dissolved the fleet's interest edges")
        kf0 = _kf_forced("new_pair")
        await _royale_collapse(cluster, 2, ticks, ticks)
        await cluster._wait(
            lambda: _royale_edges(cluster) == n * (n - 1),
            recovery_deadline, "enter wave never re-formed full interest")
        # Lockstep: one forced keyframe per re-formed directed pair (the
        # emission may trail the edge census by a sync interval or two).
        await cluster._wait(
            lambda: _kf_forced("new_pair") - kf0 >= n * (n - 1),
            recovery_deadline,
            "enter wave did not force a keyframe per new pair")
        per_wave.append(int(_kf_forced("new_pair") - kf0))
    rt = await cluster.assert_rpc_roundtrip(recovery_deadline)
    errors = cluster.bot_errors()
    assert not errors, (
        f"strict bots saw sync errors in the keyframe storm: {errors[:5]}")
    assert cluster.live_avatars() == n, "entity loss across the storm"
    return {"scenario": "battle_royale_keyframe_storm",
            "waves": waves, "edges_per_wave": n * (n - 1),
            "keyframes_per_wave": per_wave,
            "post_roundtrip_s": round(rt, 3), "bot_errors": len(errors)}


def run_chaos(run_dir: str, n_dispatchers: int = 2, n_bots: int = 12,
              transport: str = "tcp", slo=None) -> dict:
    """Run the single-cluster scenario suite (``bench.py --chaos``;
    ``transport`` = "tcp" or "uds" — the fault semantics must be
    transport-identical and every scenario asserts its own invariants
    either way). Returns a JSON-able summary with per-scenario recovery
    times and bot-error counts; a scenario failure is CAPTURED (named in
    ``failures``) and aborts the remaining scenarios on this cluster —
    the caller decides the exit code, so one red scenario can never hide
    the others' numbers. A failed scenario also leaves a post-mortem
    bundle (named in its failure entry) holding every history ring.

    ``slo`` is an optional :class:`SLOConfig`: with a
    ``bot_error_rate`` budget set, the suite's aggregate bot-error rate
    (errors per bot per scenario) is judged at the end and a violation
    lands in ``failures`` like any red scenario."""

    async def _run() -> dict:
        cluster = ChaosCluster(
            run_dir, n_dispatchers=n_dispatchers, n_bots=n_bots,
            transport=transport,
            storage_knobs=dict(
                retry_base_interval=0.05, retry_max_interval=0.2,
                circuit_failure_threshold=3, circuit_cooldown=0.3,
            ))
        await cluster.start()
        results: list[dict] = []
        failures: list[dict] = []
        scenario_fns = (
            scenario_dispatcher_restart,
            scenario_severed_link,
            scenario_paused_dispatcher,
            scenario_storage_outage,
            # ISSUE 18 catalog cross: the service-heavy storage outage
            # UNDER a dispatcher restart (both planes sick at once).
            scenario_service_outage_dispatcher_restart,
            scenario_game_kill_recreate,
            scenario_gate_kill_reconnect,
            # Scenario-matrix workloads (ISSUE 16) crossed with faults:
            # the battle-royale collapse on live avatars under a game
            # kill and under a freeze->restore reload.
            scenario_battle_royale_kill_game,
            scenario_battle_royale_freeze_restore,
        )
        try:
            for fn in scenario_fns:
                name = fn.__name__.removeprefix("scenario_")
                try:
                    r = await fn(cluster)
                    # ISSUE 13: recovery is also judged from the
                    # AGGREGATED cluster view — every process reporting,
                    # census conserved, no stale generation rows.
                    r["cluster_view_converge_s"] = round(
                        await cluster.assert_cluster_view_converged(), 3)
                    results.append(r)
                except Exception as exc:  # captured, not swallowed
                    gwlog.trace_error("chaos: scenario %s failed", name)
                    failure = {
                        "scenario": name,
                        "error": f"{type(exc).__name__}: {exc}",
                        "bot_errors": len(cluster.bot_errors()),
                    }
                    # The black box outlives the failure: bundle every
                    # history ring before tearing the cluster down.
                    try:
                        failure["bundle"] = await cluster.emit_postmortem(
                            f"{name}-failed")
                    except Exception:
                        gwlog.trace_error(
                            "chaos: post-mortem bundle failed for %s", name)
                    failures.append(failure)
                    break  # cluster state is suspect; stop this transport
        finally:
            await cluster.stop()
        bot_errors = sum(r.get("bot_errors", 0) for r in results)
        summary = {
            "scenarios": results,
            "failures": failures,
            "passed": len(results),
            "bot_errors": bot_errors,
            "dispatchers": n_dispatchers,
            "bots": n_bots,
            "transport": transport,
        }
        if slo is not None and slo.enabled():
            from goworld_tpu.telemetry.slo import judge_values, render_verdict

            rate = (bot_errors / (n_bots * len(results))
                    if results else 0.0)
            verdict = judge_values(slo, bot_error_rate=rate)
            summary["slo"] = verdict
            if not verdict["ok"]:
                failures.append({
                    "scenario": "slo_gate",
                    "error": f"SLOViolation: {render_verdict(verdict)}",
                    "bot_errors": bot_errors,
                })
        return summary

    return asyncio.run(_run())

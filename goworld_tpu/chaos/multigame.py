"""Multigame harness: N REAL game processes + in-parent dispatchers/gate.

The entity manager is per-process state, so a genuine multi-game world
needs real game processes: this harness spawns ``n_games``
``chaos/game_proc.py`` children against dispatchers, a gate, and strict
bots living in the PARENT process — which is exactly what makes it
measurable: the parent holds the dispatcher objects, so the rebalancer's
report table, the migration counters, the space-handoff park table, and
the kvreg store are directly observable with no scraping.

Entry points, all used by bench.py:

- ``run_multigame`` (the ``--multigame`` floor): the pinned 2-game shape —
  boot with a deliberately fully skewed placement (boot is game1-only,
  every avatar lands in game1's arena), resume the planner at t0, and
  measure rebalance convergence — then run the
  migrate-during-dispatcher-restart chaos phase on the same cluster.
- ``run_multigame_spaces`` (ISSUE 18): 3+ games where the receivers start
  with ZERO arenas, so balancing is only reachable through WHOLE-SPACE
  handoffs, planned by the sharded RebalancePlannerService. The same
  cluster then survives three kill crosses: receiver killed mid-PREPARE
  (the handoff aborts/bounces, the space never leaves the donor),
  donor killed mid-COMMIT (the in-flight SPACE_MIGRATE_DATA still lands
  — a space is never in zero places), and the planner-HOST game killed
  (the service shard fails over and rebalancing resumes).
- ``scenario_migrate_during_dispatcher_restart`` (the chaos-catalog
  cross): kill a dispatcher while commanded migrations are mid-window.
"""

from __future__ import annotations

import asyncio
import os
import subprocess
import sys
import time
from typing import Optional

from goworld_tpu.client import ClientBot
from goworld_tpu.common import hash_entity_id
from goworld_tpu.config.read_config import (
    ClusterConfig,
    DeploymentConfig,
    DispatcherConfig,
    GateConfig,
    GoWorldConfig,
    RebalanceConfig,
)
from goworld_tpu.dispatcher import DispatcherService
from goworld_tpu.gate import GateService
from goworld_tpu.netutil.packet import Packet
from goworld_tpu.proto.msgtypes import MsgType
from goworld_tpu.utils import gwlog

ARENA_KIND = 1
PLANNER_SHARD_KEY = "Service/RebalancePlannerService#0"
_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_INI = """\
[deployment]
dispatchers = {n_disp}
games = {n_games}
gates = 1

{dispatcher_sections}
[game_common]
save_interval = 0
position_sync_interval = 0.05
log_level = info

{game_sections}
[gate1]
port = {gate_port}

[storage]
type = filesystem
directory = {dir}/es

[kvdb]
type = filesystem
directory = {dir}/kv

[aoi]
backend = xzlist

[cluster]
peer_heartbeat_timeout = {hb}
reconnect_max_interval = 1.0
transport = {transport}
uds_dir = {uds_dir}

[rebalance]
enabled = true
driver_dispatcher = 1
interval = {interval}
report_interval = {report_interval}
stale_after = {stale_after}
min_entity_delta = {min_delta}
max_moves_per_round = {max_moves}
max_space_moves_per_round = {max_space_moves}
planner_service = {planner_service}
migrate_timeout = {migrate_timeout}
cooldown = {cooldown}
"""




class MultigameCluster:
    """N game subprocesses × M spaces, dispatchers + gate + bots in-parent.

    ``arenas`` is the per-game MG_ARENAS list (how many kind-1 arenas each
    child creates at deployment-ready); the default — one everywhere —
    is the pinned 2-game floor shape. The whole-space scenarios give
    game1 several and every receiver ZERO: a receiver without a same-kind
    space is exactly what makes the planner reach for whole-space moves.
    """

    def __init__(self, run_dir: str, n_bots: int = 12,
                 n_dispatchers: int = 2, transport: str = "tcp",
                 n_games: int = 2, arenas: Optional[list] = None,
                 planner_service: bool = False,
                 max_space_moves: int = 0) -> None:
        self.run_dir = run_dir
        self.n_bots = n_bots
        self.n_dispatchers = n_dispatchers
        self.n_games = n_games
        self.transport = transport
        self.arenas = (list(arenas) if arenas is not None
                       else [1] * n_games)
        assert len(self.arenas) == n_games
        self.rebalance_cfg = RebalanceConfig(
            enabled=True, driver_dispatcher=1, interval=0.5,
            report_interval=0.25, stale_after=3.0, min_entity_delta=4,
            max_moves_per_round=4, migrate_timeout=4.0, cooldown=2.0,
            max_space_moves_per_round=max_space_moves,
            planner_service=planner_service)
        # 3 s, not the chaos harness's 1 s: the children are real
        # processes competing for the same (often 1-core) host — a busy
        # box legitimately deschedules a child past 1 s, and a flapping
        # link mid-boot turns a timing artifact into a spurious restart.
        self.peer_heartbeat_timeout = 3.0
        self.dispatchers: list[Optional[DispatcherService]] = []
        # Every dispatcher object ever started (dead ones included): the
        # migration counters are summed over OBJECTS, because a stopped
        # service unregisters its telemetry children and family sums would
        # go backwards across a restart.
        self._all_dispatchers: list[DispatcherService] = []
        self.ports: list[int] = []
        self.gate: Optional[GateService] = None
        self.games: list[Optional[subprocess.Popen]] = []
        self.bots: list[ClientBot] = []
        self._sync_tasks: list[asyncio.Task] = []
        self._ping_seq = 0
        self._pongs: dict[str, list] = {}

    def game_ids(self) -> list[int]:
        return list(range(1, self.n_games + 1))

    def live_game_ids(self) -> list[int]:
        return [g for g in self.game_ids()
                if self.games and self.games[g - 1] is not None
                and self.games[g - 1].poll() is None]

    # --- lifecycle ----------------------------------------------------------

    async def start(self, boot_deadline: float = 60.0) -> None:
        uds_dir = self.run_dir if self.transport == "uds" else None
        for i in range(self.n_dispatchers):
            d = DispatcherService(
                i + 1, desired_games=self.n_games, desired_gates=1,
                peer_heartbeat_timeout=self.peer_heartbeat_timeout,
                rebalance=self.rebalance_cfg)
            d.rebalance_pause()  # resumed at the measured t0
            await d.start(uds_dir=uds_dir)
            self.dispatchers.append(d)
            self._all_dispatchers.append(d)
            self.ports.append(d.port)

        cfg = GoWorldConfig()
        cfg.deployment = DeploymentConfig(
            desired_games=self.n_games, desired_gates=1,
            desired_dispatchers=self.n_dispatchers)
        cfg.dispatchers = {
            i + 1: DispatcherConfig(port=p)
            for i, p in enumerate(self.ports)}
        cfg.gates = {1: GateConfig(
            port=0, position_sync_interval=0.05, heartbeat_timeout=30.0)}
        cfg.cluster = ClusterConfig(
            peer_heartbeat_timeout=self.peer_heartbeat_timeout,
            reconnect_max_interval=1.0,
            transport=self.transport,
            uds_dir=self.run_dir if self.transport == "uds" else "")
        cfg.rebalance = self.rebalance_cfg
        self.cfg = cfg
        self.gate = GateService(1, cfg)
        await self.gate.start()

        # Debug ports for the REAL game children: the cluster-view
        # convergence check scrapes their /snapshot over HTTP — the same
        # production path the driver dispatcher's collector uses.
        self.game_http = [self._free_port() for _ in self.game_ids()]
        rb = self.rebalance_cfg
        game_sections = ""
        for gid in self.game_ids():
            boot = "boot_entity = MGAvatar\n" if gid == 1 else ""
            game_sections += (
                f"[game{gid}]\n{boot}log_file = game{gid}.log\n"
                f"http_addr = 127.0.0.1:{self.game_http[gid - 1]}\n\n")
        ini = _INI.format(
            n_games=self.n_games, game_sections=game_sections,
            n_disp=self.n_dispatchers,
            dispatcher_sections="".join(
                f"[dispatcher{i + 1}]\nport = {p}\n\n"
                for i, p in enumerate(self.ports)),
            gate_port=self.gate.port, dir=self.run_dir,
            transport=self.transport,
            uds_dir=self.run_dir if self.transport == "uds" else "",
            hb=self.peer_heartbeat_timeout,
            interval=rb.interval, report_interval=rb.report_interval,
            stale_after=rb.stale_after, min_delta=rb.min_entity_delta,
            max_moves=rb.max_moves_per_round,
            max_space_moves=rb.max_space_moves_per_round,
            planner_service="true" if rb.planner_service else "false",
            migrate_timeout=rb.migrate_timeout, cooldown=rb.cooldown)
        self.ini_path = os.path.join(self.run_dir, "goworld.ini")
        with open(self.ini_path, "w", encoding="utf-8") as f:
            f.write(ini)

        self.games = [None] * self.n_games
        for gid in self.game_ids():
            self._spawn_game(gid)

        await self._wait(
            lambda: all(
                sum(1 for gi in d.games.values() if gi.connected)
                == self.n_games
                for d in self.dispatchers if d is not None)
            and self.dispatchers[0].deployment_ready,
            boot_deadline, "game processes never all connected",
            on_fail=self._game_log_tails)
        # Every game must have reported (arena ids come from the reports);
        # arena-less games (MG_ARENAS=0) legitimately report no spaces.
        await self._wait(
            lambda: len(self._planner().reports.games()) == self.n_games
            and all(self._arena(g) is not None
                    for g in self.game_ids() if self.arenas[g - 1] > 0),
            boot_deadline, "games never reported their arenas")

        for i in range(self.n_bots):
            bot = ClientBot(name=f"mgbot{i}", strict=True,
                            heartbeat_interval=1.0)
            self._pongs[bot.name] = []
            bot.rpc_handlers[(None, "Pong")] = (
                lambda entity, n, name=bot.name: self._pongs[name].append(n))
            await bot.connect("127.0.0.1", self.gate.port)
            await bot.wait_player(timeout=15)
            self.bots.append(bot)
            self._sync_tasks.append(
                asyncio.get_running_loop().create_task(self._sync_loop(bot)))
        # Skew barrier: every avatar sits in a game1 arena (boot is
        # game1-only), visible through the load reports.
        await self._wait(
            lambda: self._game_pop(1) == self.n_bots,
            30.0, "avatars never all collected in game1's arenas")

    def _spawn_game(self, gid: int) -> None:
        env = dict(os.environ, PYTHONPATH=_REPO, JAX_PLATFORMS="cpu",
                   MG_ARENAS=str(self.arenas[gid - 1]))
        logf = open(os.path.join(self.run_dir, f"game{gid}.out.log"), "ab")
        proc = subprocess.Popen(
            [sys.executable, "-m", "goworld_tpu.chaos.game_proc",
             "-gid", str(gid), "-configfile", self.ini_path],
            cwd=self.run_dir, env=env, stdout=logf,
            stderr=subprocess.STDOUT)
        logf.close()
        self.games[gid - 1] = proc

    async def _kill_game(self, gid: int) -> None:
        """SIGKILL a game child — the crash model of the kill crosses
        (no atexit, no socket shutdown beyond the kernel's RST)."""
        proc = self.games[gid - 1]
        assert proc is not None and proc.poll() is None, f"game{gid} dead"
        proc.kill()
        deadline = time.monotonic() + 10.0
        while proc.poll() is None and time.monotonic() < deadline:
            await asyncio.sleep(0.02)
        gwlog.infof("multigame: game%d killed", gid)

    async def _respawn_game(self, gid: int, deadline: float = 30.0) -> None:
        """Restart a killed child and wait until every dispatcher sees it
        connected AND it reports again (census helpers read the reports)."""
        self._spawn_game(gid)
        await self._wait(
            lambda: all(d._game(gid).connected
                        for d in self.dispatchers if d is not None)
            and self._report(gid) is not None,
            deadline, f"game{gid} never rejoined after respawn",
            on_fail=self._game_log_tails)

    async def stop(self) -> None:
        for t in self._sync_tasks:
            t.cancel()
        self._sync_tasks.clear()
        for b in self.bots:
            await b.close()
        self.bots.clear()
        if self.gate is not None:
            await self.gate.stop()
            self.gate = None
        for proc in self.games:
            if proc is not None and proc.poll() is None:
                proc.terminate()
        deadline = time.monotonic() + 10.0
        for proc in self.games:
            if proc is None:
                continue
            while proc.poll() is None and time.monotonic() < deadline:
                await asyncio.sleep(0.05)
            if proc.poll() is None:
                proc.kill()
        self.games.clear()
        for d in self.dispatchers:
            if d is not None:
                await d.stop()
        self.dispatchers.clear()

    def _game_log_tails(self) -> str:
        tails = []
        for gid in self.game_ids():
            try:
                with open(os.path.join(self.run_dir,
                                       f"game{gid}.out.log"), "rb") as f:
                    data = f.read()[-800:]
                tails.append(f"game{gid}: ...{data.decode(errors='replace')}")
            except OSError:
                pass
        return "\n".join(tails)

    async def _wait(self, cond, timeout: float, what: str,
                    on_fail=None) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if cond():
                return
            await asyncio.sleep(0.02)
        extra = f"\n{on_fail()}" if on_fail is not None else ""
        raise AssertionError(f"multigame: {what} (after {timeout:.1f}s)"
                             f"{extra}")

    async def _sync_loop(self, bot: ClientBot) -> None:
        """Light client-driven position jitter (the sync plane the migrate
        window must buffer — records sent mid-migrate must land on the
        entity's NEW game, never a stale one)."""
        import random

        while True:
            await asyncio.sleep(0.1)
            p = bot.player
            if p is not None:
                p.sync_position(p.x + random.uniform(-0.5, 0.5), p.y,
                                p.z + random.uniform(-0.5, 0.5), p.yaw)

    # --- observability -------------------------------------------------------

    @staticmethod
    def _free_port() -> int:
        import socket

        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return int(s.getsockname()[1])

    def collector_targets(self):
        """Cluster-collector targets: the REAL game children are scraped
        over their debug HTTP ports (the exact production path), the
        in-parent dispatchers/gate feed the collector directly — the
        process-global health-provider slot can't tell them apart."""
        from goworld_tpu.telemetry.collector import http_target

        def disp_fetch(i: int):
            async def fetch() -> dict:
                d = self.dispatchers[i]
                if d is None:
                    raise RuntimeError("dispatcher killed")
                return {"health": d._health(), "metrics": {}}

            return fetch

        async def gate_fetch() -> dict:
            if self.gate is None:
                raise RuntimeError("gate down")
            return {"health": self.gate._health(), "metrics": {}}

        targets = [(f"dispatcher{i + 1}", disp_fetch(i))
                   for i in range(self.n_dispatchers)]
        for gid in self.game_ids():
            targets.append(http_target(
                f"game{gid}", f"127.0.0.1:{self.game_http[gid - 1]}"))
        targets.append(("gate1", gate_fetch))
        return targets

    async def game_metric(self, gid: int, family: str,
                          label: Optional[str] = None,
                          value: Optional[str] = None) -> float:
        """One metric family's series sum scraped from a child's
        /snapshot — how the parent asserts child-side rebalance counters
        (space-handoff outcomes, the planner-host gauge)."""
        from goworld_tpu.telemetry.collector import (
            _series_sum,
            http_fetch_json,
        )

        snap = await http_fetch_json(
            f"127.0.0.1:{self.game_http[gid - 1]}", "/snapshot")
        return _series_sum(snap.get("metrics", {}), family, label, value)

    async def assert_cluster_view_converged(
            self, deadline: float = 25.0) -> float:
        """ISSUE 13: the aggregated view over every real game process +
        dispatchers + gate must re-converge — every process reporting,
        client census conserved at the bot count across the games, no
        stale generation rows. Returns seconds until convergence."""
        import json as _json

        from goworld_tpu.telemetry.collector import ClusterCollector

        coll = ClusterCollector(self.collector_targets(), interval=0.1)
        t0 = time.monotonic()
        last = None
        while time.monotonic() - t0 < deadline:
            await coll.poll_once()
            summary = coll.view()["summary"]
            census = summary["census"]
            if (summary["reporting"] == summary["expected"]
                    and not summary["alerts"]
                    and census["clients_conserved"]
                    and census["gate_clients"] == len(self.bots)):
                return time.monotonic() - t0
            last = summary
            await asyncio.sleep(0.1)
        raise AssertionError(
            "multigame: /cluster view never re-converged: "
            f"{_json.dumps(last, default=str)}")

    def _planner(self):
        for d in self.dispatchers:
            if d is not None:
                return d.planner
        raise AssertionError("no live dispatcher")

    def _live_dispatcher(self) -> DispatcherService:
        for d in self.dispatchers:
            if d is not None:
                return d
        raise AssertionError("no live dispatcher")

    def _report(self, gameid: int) -> dict | None:
        return self._planner().reports.get(gameid)

    def _arenas_of(self, gameid: int) -> list[tuple[str, int]]:
        r = self._report(gameid) or {}
        return [(sid, int(count)) for sid, kind, count in
                r.get("spaces", []) if kind == ARENA_KIND]

    def _arena(self, gameid: int):
        arenas = self._arenas_of(gameid)
        return arenas[0][0] if arenas else None

    def _game_pop(self, gameid: int) -> int:
        return sum(count for _sid, count in self._arenas_of(gameid))

    def census(self) -> tuple:
        return tuple(self._game_pop(g) for g in self.game_ids())

    def space_handoffs(self) -> int:
        """Spaces currently parked at any live dispatcher (the handoff
        table every PREPARE fills and every abort/ack/deadline drains)."""
        return sum(len(d._space_handoffs)
                   for d in self.dispatchers if d is not None)

    def kvreg_lookup(self, key: str) -> Optional[str]:
        for d in self.dispatchers:
            if d is not None and key in d.kvreg:
                return d.kvreg[key]
        return None

    def planner_host_game(self) -> Optional[int]:
        """Which game owns the RebalancePlannerService shard, per the
        dispatchers' replicated kvreg store ("game<N>")."""
        val = self.kvreg_lookup(PLANNER_SHARD_KEY)
        if val is None or not val.startswith("game"):
            return None
        try:
            return int(val[4:])
        except ValueError:
            return None

    def command_space_move(self, spaceid: str, donor: int,
                           to_game: int) -> None:
        """Inject one whole-space handoff command through a live
        dispatcher's plan-dispatch path (the same packet a planning round
        or a REBALANCE_PLAN push would produce)."""
        from goworld_tpu.rebalance.planner import SpaceMove

        self._live_dispatcher()._dispatch_plan(
            [SpaceMove(donor, to_game, spaceid, 0)], time.monotonic())

    async def _command_until(self, sid: str, donor: int, to_game: int,
                             cond, deadline: float, what: str) -> None:
        """Re-issue a space-move command until its observable effect
        lands: ``handle_space_command`` refuses SILENTLY while the space
        is on its post-arrival / post-rollback cooldown (by design — a
        stale command degrades to nothing), so a chaos phase that needs
        the handoff to actually START must keep asking."""
        end = time.monotonic() + deadline
        while time.monotonic() < end:
            self.command_space_move(sid, donor, to_game)
            retry_at = min(end, time.monotonic() + 1.0)
            while time.monotonic() < retry_at:
                if cond():
                    return
                await asyncio.sleep(0.02)
        raise AssertionError(
            f"multigame: {what} (after {deadline:.1f}s)\n"
            + self._game_log_tails())

    def _mig_counters(self) -> dict[str, int]:
        return {
            "routed": sum(d.migrates_routed for d in self._all_dispatchers),
            "bounced": sum(d.migrates_bounced
                           for d in self._all_dispatchers),
            "cancel": sum(d.migrates_cancelled
                          for d in self._all_dispatchers),
        }

    def bot_errors(self) -> list[str]:
        return [err for b in self.bots for err in b.errors]

    async def assert_rpc_roundtrip(self, deadline: float = 15.0) -> float:
        """Every bot pings its avatar (wherever it now lives) and must get
        its pong — the client-visible zero-loss probe."""
        self._ping_seq += 1
        n = self._ping_seq
        t0 = time.monotonic()
        for b in self.bots:
            assert b.player is not None, f"{b.name}: player mirror lost"
            b.player.call_server("Ping_Client", n)
        await self._wait(
            lambda: all(n in self._pongs[b.name] for b in self.bots),
            deadline, f"ping {n}: not every bot got its pong")
        return time.monotonic() - t0

    def _pause_planners(self) -> None:
        for d in self.dispatchers:
            if d is not None:
                d.rebalance_pause()

    def _resume_planners(self) -> None:
        for d in self.dispatchers:
            if d is not None:
                d.rebalance_resume()

    # --- phases --------------------------------------------------------------

    async def wait_balanced(self, deadline: float = 30.0,
                            what: str = "never balanced") -> float:
        """Wait until per-game populations are balanced AND stable.
        Stability must SPAN report cycles (the census is read from the
        cached reports): balanced and unchanged for 3 report intervals,
        with the sum conserved (an in-flight migration makes it dip).
        Does NOT touch planner pause state. Returns the wait's length."""
        tol = self.rebalance_cfg.min_entity_delta
        span = 3.0 * self.rebalance_cfg.report_interval
        t0 = time.monotonic()
        state = {"census": None, "since": 0.0}

        def balanced() -> bool:
            c = self.census()
            now = time.monotonic()
            if c != state["census"]:
                state["census"], state["since"] = c, now
            return (sum(c) == self.n_bots
                    and max(c) - min(c) <= tol
                    and now - state["since"] >= span)

        await self._wait(
            balanced, deadline, what,
            on_fail=lambda: (
                f"census {self.census()}, reports "
                f"{ {g: self._report(g) for g in self.game_ids()} }\n"
                + self._game_log_tails()))
        return time.monotonic() - t0

    async def converge(self, deadline: float = 30.0) -> dict:
        """Resume the planner at t0; wait until the per-game populations
        are balanced AND stable (two consecutive report snapshots agree
        and the full census is conserved — in-flight migrations make the
        sum dip, so a conserved sum means nothing is mid-air)."""
        mig0 = self._mig_counters()
        t0 = time.monotonic()
        self._resume_planners()
        await self.wait_balanced(deadline, "never converged")
        convergence_s = time.monotonic() - t0
        rt = await self.assert_rpc_roundtrip()
        mig1 = self._mig_counters()
        return {
            "convergence_s": round(convergence_s, 3),
            "census": list(self.census()),
            "migrations_done": int(mig1["routed"] - mig0["routed"]),
            "migrations_rolled_back": int(
                (mig1["cancel"] - mig0["cancel"])
                + (mig1["bounced"] - mig0["bounced"])),
            "post_roundtrip_s": round(rt, 3),
            "zero_loss": sum(self.census()) == self.n_bots,
            "bot_errors": len(self.bot_errors()),
        }

    async def _wait_census_settled(self, games: list[int], deadline: float,
                                   what: str) -> None:
        """Sum over ``games`` back at n_bots and unchanged for 3 report
        intervals, with no space parked at any dispatcher."""
        span = 3.0 * self.rebalance_cfg.report_interval
        state = {"census": None, "since": 0.0}

        def settled() -> bool:
            c = tuple(self._game_pop(g) for g in games)
            t = time.monotonic()
            if c != state["census"]:
                state["census"], state["since"] = c, t
            return (sum(c) == self.n_bots
                    and self.space_handoffs() == 0
                    and t - state["since"] >= span)

        await self._wait(
            settled, deadline, what,
            on_fail=lambda: (
                f"census {tuple(self._game_pop(g) for g in games)}, "
                f"handoffs {self.space_handoffs()}, reports "
                f"{ {g: self._report(g) for g in games} }\n"
                + self._game_log_tails()))

    async def kill_receiver_mid_prepare(
            self, deadline: float = 30.0) -> dict:
        """ISSUE 18 kill cross 1: command a whole-space handoff and kill
        the RECEIVER game in the same instant — its death races the
        PREPARE fan-out. Whichever window the kill lands in (dispatcher
        already knows → the PREPARE is refused with an ABORT; dispatchers
        parked first → the packed SPACE_MIGRATE_DATA bounces home off the
        dead link), the space must end up back on the donor, unfrozen,
        with every member and every bot answering — never in zero places,
        never lost."""
        self._pause_planners()
        donor = 1
        receivers = [g for g in self.live_game_ids()
                     if g != donor and self._game_pop(g) == 0]
        assert receivers, "no empty receiver to kill mid-PREPARE"
        receiver = receivers[0]
        census0 = self.census()
        arenas = self._arenas_of(donor)
        assert arenas, "donor has no arena"
        sid = max(arenas, key=lambda a: a[1])[0]
        t0 = time.monotonic()
        # Command + SIGKILL in the same event-loop turn: the command is
        # still in the parent→donor socket buffer when the receiver dies,
        # so the donor's PREPARE broadcast races the dispatchers' dead-
        # link detection — the exact window the two-phase protocol exists
        # for.
        self.command_space_move(sid, donor, receiver)
        await self._kill_game(receiver)
        survivors = [g for g in self.game_ids() if g != receiver]
        await self._wait(
            lambda: (self.space_handoffs() == 0
                     and self._game_pop(donor) == census0[donor - 1]),
            deadline, "space never returned home after receiver kill",
            on_fail=lambda: (
                f"census {self.census()}, handoffs "
                f"{self.space_handoffs()}\n" + self._game_log_tails()))
        await self._wait_census_settled(
            survivors, deadline, "census never settled after receiver kill")
        # The donor's own counters must classify the outcome: exactly one
        # handoff ended aborted / rolled_back / timeout, zero done.
        failed = sum([
            await self.game_metric(
                donor, "rebalance_space_migrations_total",
                "outcome", outcome)
            for outcome in ("aborted", "rolled_back", "timeout")])
        done = await self.game_metric(
            donor, "rebalance_space_migrations_total", "outcome", "done")
        assert failed >= 1.0 and done == 0.0, (failed, done)
        await self._respawn_game(receiver)
        rt = await self.assert_rpc_roundtrip(deadline)
        errors = self.bot_errors()
        assert not errors, f"bot errors in mid-PREPARE kill: {errors[:5]}"
        return {
            "scenario": "space_kill_receiver_mid_prepare",
            "recovery_s": round(time.monotonic() - t0, 3),
            "census_before": list(census0),
            "census_after": list(self.census()),
            "donor_outcomes_failed": int(failed),
            "post_roundtrip_s": round(rt, 3),
            "zero_loss": sum(self.census()) == self.n_bots,
            "bot_errors": len(errors),
        }

    async def kill_donor_mid_commit(self, deadline: float = 30.0) -> dict:
        """ISSUE 18 kill cross 2: kill the DONOR game the instant its
        SPACE_MIGRATE_DATA has passed the space-owner dispatcher (the
        parent watches the routed counter, so the kill provably lands
        inside the commit window — data sent, ACK not yet seen). The
        space and every member must survive on the receiver: the payload
        in flight IS the space's one live copy, and the dispatcher is
        obligated to deliver it."""
        self._pause_planners()
        # The donor must hold its WHOLE population inside one arena —
        # killing it then loses nothing but the space already in flight.
        candidates = [
            g for g in self.live_game_ids()
            if len(self._arenas_of(g)) == 1 and self._game_pop(g) > 0
            and g != 1]
        if not candidates:
            candidates = [g for g in self.live_game_ids()
                          if len(self._arenas_of(g)) == 1
                          and self._game_pop(g) > 0]
        assert candidates, f"no single-arena donor in {self.census()}"
        donor = candidates[0]
        sid, count0 = self._arenas_of(donor)[0]
        receiver = min(
            (g for g in self.live_game_ids() if g != donor),
            key=self._game_pop)
        census0 = self.census()
        mig0 = self._mig_counters()["routed"]
        t0 = time.monotonic()
        # Tight poll: the routed counter increments in the dispatcher's
        # own handler (same process), so routed > mig0 means the payload
        # is PAST the dispatcher and queued toward the live receiver.
        # Re-issued because the arena may still sit on its post-arrival
        # cooldown from the convergence phase.
        await self._command_until(
            sid, donor, receiver,
            lambda: self._mig_counters()["routed"] > mig0,
            deadline, "SPACE_MIGRATE_DATA never crossed a dispatcher")
        await self._kill_game(donor)
        survivors = [g for g in self.game_ids() if g != donor]
        await self._wait(
            lambda: any(s == sid and c == count0
                        for s, c in self._arenas_of(receiver)),
            deadline,
            f"space {sid} never restored on game{receiver} with "
            f"{count0} members",
            on_fail=lambda: (
                f"census {self.census()}, receiver arenas "
                f"{self._arenas_of(receiver)}\n" + self._game_log_tails()))
        await self._wait_census_settled(
            survivors, deadline, "census never settled after donor kill")
        await self._respawn_game(donor)
        # The respawned donor's slot holds the DEAD incarnation's report
        # until the fresh (empty) game reports in — wait it out so the
        # census below counts live entities, not ghosts.
        await self._wait(
            lambda: sum(self.census()) == self.n_bots, deadline,
            "census never matched the fleet after donor respawn",
            on_fail=lambda: f"census {self.census()}")
        rt = await self.assert_rpc_roundtrip(deadline)
        errors = self.bot_errors()
        assert not errors, f"bot errors in mid-COMMIT kill: {errors[:5]}"
        return {
            "scenario": "space_kill_donor_mid_commit",
            "recovery_s": round(time.monotonic() - t0, 3),
            "census_before": list(census0),
            "census_after": list(self.census()),
            "moved_space": sid,
            "moved_members": count0,
            "post_roundtrip_s": round(rt, 3),
            "zero_loss": sum(self.census()) == self.n_bots,
            "bot_errors": len(errors),
        }

    async def kill_planner_host(self, deadline: float = 45.0) -> dict:
        """ISSUE 18 kill cross 3 (planner failover): evacuate the planner-
        host game through whole-space handoffs (zero loss), SIGKILL it,
        and require the sharded RebalancePlannerService to fail over — the
        dispatcher purges the dead game's kvreg claims, a survivor
        re-claims the shard, and its planner RESUMES rebalancing the skew
        the earlier kills left behind. Needs [rebalance]
        planner_service."""
        assert self.rebalance_cfg.planner_service
        self._pause_planners()
        await self._wait(
            lambda: self.planner_host_game() in self.live_game_ids(),
            deadline, "planner shard never claimed by a live game")
        host = self.planner_host_game()
        # Evacuate: every arena on the host moves whole to the emptiest
        # other game, through the same two-phase handoff under test.
        for sid, _count in self._arenas_of(host):
            target = min(
                (g for g in self.live_game_ids() if g != host),
                key=self._game_pop)
            await self._command_until(
                sid, host, target,
                lambda s=sid, t=target: any(
                    row[0] == s for row in self._arenas_of(t)),
                deadline, f"evacuation of {sid} off game{host} never landed")
        await self._wait(
            lambda: self._game_pop(host) == 0
            and self.space_handoffs() == 0,
            deadline, f"game{host} never drained before the kill")
        census0 = self.census()
        t0 = time.monotonic()
        await self._kill_game(host)
        survivors = [g for g in self.game_ids() if g != host]
        # Failover: the purge must release the shard claim and a SURVIVOR
        # must win the re-registration race.
        await self._wait(
            lambda: self.planner_host_game() in survivors,
            deadline, "planner shard never failed over to a survivor")
        new_host = self.planner_host_game()
        failover_s = time.monotonic() - t0
        # The new host's own gauge must agree with the kvreg claim (the
        # claim lands first; the entity — and its gauge — follows on the
        # winner's next reconcile pass).
        host_gauge = 0.0
        gauge_deadline = time.monotonic() + deadline
        while time.monotonic() < gauge_deadline:
            try:
                host_gauge = await self.game_metric(
                    new_host, "rebalance_planner_host")
            except (OSError, ValueError):
                host_gauge = 0.0
            if host_gauge >= 1.0:
                break
            await asyncio.sleep(0.1)
        assert host_gauge >= 1.0, (
            f"game{new_host} claims the planner shard but its "
            f"rebalance_planner_host gauge is {host_gauge}")
        # ...and resumed planning must fix the skew the kills left: the
        # evacuated arenas sit wherever we pushed them, so the failed-over
        # planner has real work to do.
        self._resume_planners()
        tol = self.rebalance_cfg.min_entity_delta
        span = 3.0 * self.rebalance_cfg.report_interval
        state = {"census": None, "since": 0.0}

        def balanced() -> bool:
            c = tuple(self._game_pop(g) for g in survivors)
            now = time.monotonic()
            if c != state["census"]:
                state["census"], state["since"] = c, now
            return (sum(c) == self.n_bots
                    and max(c) - min(c) <= tol
                    and now - state["since"] >= span)

        await self._wait(
            balanced, deadline,
            "failed-over planner never rebalanced the survivors",
            on_fail=lambda: (
                f"census {self.census()}, planner host "
                f"{self.planner_host_game()}\n" + self._game_log_tails()))
        rebalanced_s = time.monotonic() - t0
        await self._respawn_game(host)
        await self._wait_census_settled(
            self.game_ids(), deadline,
            "census never settled after planner-host respawn")
        rt = await self.assert_rpc_roundtrip(deadline)
        errors = self.bot_errors()
        assert not errors, f"bot errors in planner-host kill: {errors[:5]}"
        return {
            "scenario": "space_kill_planner_host",
            "old_host": host,
            "new_host": new_host,
            "new_host_gauge": host_gauge,
            "failover_s": round(failover_s, 3),
            "recovery_s": round(rebalanced_s, 3),
            "census_before": list(census0),
            "census_after": list(self.census()),
            "post_roundtrip_s": round(rt, 3),
            "zero_loss": sum(self.census()) == self.n_bots,
            "bot_errors": len(errors),
        }

    async def migrate_during_dispatcher_restart(
        self, moves: int = 4, downtime: float = 1.0,
        deadline: float = 25.0,
    ) -> dict:
        """THE ROADMAP-named scenario: command a batch of migrations, kill
        a dispatcher inside the migrate window (before yielding to the
        event loop, so nothing has completed yet), restart it, and require
        every migration to complete (possibly via the replay-ring flush)
        or roll back — census conserved, every bot answering."""
        self._pause_planners()
        donor = max(self.live_game_ids(), key=self._game_pop)
        recv = min((g for g in self.live_game_ids() if g != donor),
                   key=self._game_pop)
        from_space, to_space = self._arena(donor), self._arena(recv)
        assert from_space and to_space, "arenas unknown"
        mig0 = self._mig_counters()
        census0 = self.census()
        # The migrate chain fans over dispatchers by id hash: the space
        # query rides hash(to_space)'s dispatcher, the per-entity blocks
        # ride hash(eid)'s. Kill the one NOT owning the space query so
        # queries still flow and ~half the entities' MIGRATE_REQUESTs are
        # mid-air when the link dies (they park in the games' replay
        # rings and must resolve after the restart).
        owner_idx = hash_entity_id(to_space) % self.n_dispatchers
        victim = (owner_idx + 1) % self.n_dispatchers
        # The command itself must ride a SURVIVING dispatcher's game link
        # (sending it through the victim would abort it in the socket
        # buffer and nothing would ever be mid-air).
        commander = self.dispatchers[owner_idx]
        p = Packet()
        p.append_entity_id(from_space)
        p.append_entity_id(to_space)
        p.append_uint16(recv)
        p.append_uint16(moves)
        now = time.monotonic()
        commander._game(donor).dispatch(MsgType.REBALANCE_MIGRATE, p, now)
        # Same event-loop turn: the command is in the socket buffer but no
        # ack has come back — the kill lands inside the migrate window.
        d = self.dispatchers[victim]
        for proxy in list(d._conns):
            proxy.conn.abort()
        await d.stop()
        self.dispatchers[victim] = None
        gwlog.infof("multigame: dispatcher %d killed mid-migrate",
                    victim + 1)
        await asyncio.sleep(downtime)
        t0 = time.monotonic()
        nd = DispatcherService(
            victim + 1, desired_games=self.n_games, desired_gates=1,
            peer_heartbeat_timeout=self.peer_heartbeat_timeout,
            rebalance=self.rebalance_cfg)
        nd.rebalance_pause()
        self._all_dispatchers.append(nd)
        for _ in range(100):
            try:
                await nd.start(
                    port=self.ports[victim],
                    uds_dir=(self.run_dir if self.transport == "uds"
                             else None))
                break
            except OSError:
                await asyncio.sleep(0.05)
        else:
            raise AssertionError("could not rebind dispatcher port")
        self.dispatchers[victim] = nd

        # Settled = census conserved and unchanged for 3 report intervals
        # (an in-flight migration makes the sum dip; a just-landing one
        # changes the split).
        span = 3.0 * self.rebalance_cfg.report_interval
        state = {"census": None, "since": 0.0}

        def settled() -> bool:
            c = self.census()
            t = time.monotonic()
            if c != state["census"]:
                state["census"], state["since"] = c, t
            return sum(c) == self.n_bots and t - state["since"] >= span

        def diag() -> str:
            lines = [
                f"reports: "
                f"{ {g: self._report(g) for g in self.game_ids()} }"]
            for i, d in enumerate(self.dispatchers):
                if d is None:
                    lines.append(f"dispatcher[{i}]: None")
                    continue
                lines.append(
                    f"dispatcher[{i}] id={d.dispid} games="
                    f"{ {g: gi.connected for g, gi in d.games.items()} } "
                    f"planner_games={d.planner.reports.games()}")
            lines.append(self._game_log_tails())
            return "\n".join(lines)
        await self._wait(settled, deadline,
                         f"census never settled (is {self.census()})",
                         on_fail=diag)
        rt = await self.assert_rpc_roundtrip(deadline)
        recovery = time.monotonic() - t0
        mig1 = self._mig_counters()
        errors = self.bot_errors()
        assert not errors, f"bot errors during migrate+restart: {errors[:5]}"
        done = int(mig1["routed"] - mig0["routed"])
        rolled = int((mig1["cancel"] - mig0["cancel"])
                     + (mig1["bounced"] - mig0["bounced"]))
        view_converge = await self.assert_cluster_view_converged()
        return {
            "scenario": "migrate_during_dispatcher_restart",
            "recovery_s": round(recovery, 3),
            "cluster_view_converge_s": round(view_converge, 3),
            "post_roundtrip_s": round(rt, 3),
            "census_before": list(census0),
            "census_after": list(self.census()),
            "migrations_done": done,
            "migrations_rolled_back": rolled,
            "commanded": moves,
            "zero_loss": sum(self.census()) == self.n_bots,
            "bot_errors": len(errors),
        }


async def _run_multigame(run_dir: str, n_bots: int, transport: str,
                         with_restart_phase: bool) -> dict:
    cluster = MultigameCluster(run_dir, n_bots=n_bots, transport=transport)
    # start() INSIDE the try: a boot failure must still tear the cluster
    # down — its game children are real OS processes, and two leaked
    # games silently eating a 1-core host skew every measurement that
    # follows (found the hard way: a failed boot leaked children that
    # depressed the pinned floor a full tier-1 run later).
    try:
        await cluster.start()
        out = await cluster.converge()
        out["skew_initial"] = [n_bots, 0]
        if with_restart_phase:
            out["dispatcher_restart_phase"] = (
                await cluster.migrate_during_dispatcher_restart())
        out["bot_errors"] = len(cluster.bot_errors())
        assert not cluster.bot_errors(), cluster.bot_errors()[:5]
    finally:
        await cluster.stop()
    return out


def run_multigame(run_dir: str, n_bots: int = 12, transport: str = "tcp",
                  with_restart_phase: bool = True) -> dict:
    """Blocking driver (bench.py --multigame / the dispatcher-restart
    chaos scenario): the pinned 2-game floor shape."""
    return asyncio.run(
        _run_multigame(run_dir, n_bots, transport, with_restart_phase))


async def _run_multigame_spaces(run_dir: str, n_bots: int, n_games: int,
                                transport: str) -> dict:
    # Receivers start with ZERO arenas: the only way the planner can
    # balance is moving WHOLE spaces (no same-kind receiver space exists
    # for plain entity moves until a handoff plants one).
    arenas = [n_games] + [0] * (n_games - 1)
    cluster = MultigameCluster(
        run_dir, n_bots=n_bots, transport=transport, n_games=n_games,
        arenas=arenas, planner_service=True, max_space_moves=1)
    try:
        await cluster.start()
        phases: dict = {}
        phases["kill_receiver_mid_prepare"] = (
            await cluster.kill_receiver_mid_prepare())
        out = await cluster.converge()
        out["skew_initial"] = [n_bots] + [0] * (n_games - 1)
        phases["kill_donor_mid_commit"] = (
            await cluster.kill_donor_mid_commit())
        phases["kill_planner_host"] = await cluster.kill_planner_host()
        out["phases"] = phases
        # The planner is live again after the failover phase: require the
        # whole fleet (respawned ex-host included) to settle balanced
        # before the final snapshots — a racing handoff would otherwise
        # photograph a transient skew as the "final" census.
        out["final_rebalance_s"] = round(await cluster.wait_balanced(
            30.0, "fleet never re-balanced after the kill crosses"), 3)
        out["cluster_view_converge_s"] = round(
            await cluster.assert_cluster_view_converged(), 3)
        out["census_final"] = list(cluster.census())
        out["bot_errors"] = len(cluster.bot_errors())
        assert not cluster.bot_errors(), cluster.bot_errors()[:5]
    finally:
        await cluster.stop()
    return out


def run_multigame_spaces(run_dir: str, n_bots: int = 12, n_games: int = 3,
                         transport: str = "tcp") -> dict:
    """Blocking driver of the ISSUE 18 whole-space chaos run: N games,
    arena-less receivers, sharded planner service, and the three kill
    crosses (receiver mid-PREPARE, donor mid-COMMIT, planner host)."""
    return asyncio.run(
        _run_multigame_spaces(run_dir, n_bots, n_games, transport))

"""Multigame harness: 2 REAL game processes + in-parent dispatchers/gate.

The entity manager is per-process state, so a genuine multi-game world
needs real game processes: this harness spawns two ``chaos/game_proc.py``
children against dispatchers, a gate, and strict bots living in the
PARENT process — which is exactly what makes it measurable: the parent
holds the dispatcher objects, so the rebalancer's report table, the
migration counters, and the planner state are directly observable with no
scraping.

Two entry points, both used by bench.py:

- ``run_multigame`` (the ``--multigame`` floor): boot with a deliberately
  fully skewed placement (game2 is boot-banned, every avatar lands in
  game1's arena), resume the planner at t0, and measure rebalance
  convergence — time until the arena populations are balanced and stable
  with zero entity loss and zero strict-bot errors — then run the
  migrate-during-dispatcher-restart chaos phase on the same cluster.
- ``scenario_migrate_during_dispatcher_restart`` (the 7th chaos
  scenario): kill a dispatcher while commanded migrations are mid-window;
  every migration must complete (possibly after the replay-ring flush) or
  roll back, with the avatar census conserved and every bot answering.
"""

from __future__ import annotations

import asyncio
import os
import subprocess
import sys
import time
from typing import Optional

from goworld_tpu.client import ClientBot
from goworld_tpu.common import hash_entity_id
from goworld_tpu.config.read_config import (
    ClusterConfig,
    DeploymentConfig,
    DispatcherConfig,
    GateConfig,
    GoWorldConfig,
    RebalanceConfig,
)
from goworld_tpu.dispatcher import DispatcherService
from goworld_tpu.gate import GateService
from goworld_tpu.netutil.packet import Packet
from goworld_tpu.proto.msgtypes import MsgType
from goworld_tpu.utils import gwlog

ARENA_KIND = 1
_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_INI = """\
[deployment]
dispatchers = {n_disp}
games = 2
gates = 1

{dispatcher_sections}
[game_common]
save_interval = 0
position_sync_interval = 0.05
log_level = info

[game1]
boot_entity = MGAvatar
log_file = game1.log
http_addr = 127.0.0.1:{g1_http}

[game2]
log_file = game2.log
http_addr = 127.0.0.1:{g2_http}

[gate1]
port = {gate_port}

[storage]
type = filesystem
directory = {dir}/es

[kvdb]
type = filesystem
directory = {dir}/kv

[aoi]
backend = xzlist

[cluster]
peer_heartbeat_timeout = {hb}
reconnect_max_interval = 1.0
transport = {transport}
uds_dir = {uds_dir}

[rebalance]
enabled = true
driver_dispatcher = 1
interval = {interval}
report_interval = {report_interval}
stale_after = {stale_after}
min_entity_delta = {min_delta}
max_moves_per_round = {max_moves}
migrate_timeout = {migrate_timeout}
cooldown = {cooldown}
"""




class MultigameCluster:
    """2 game subprocesses × N spaces, dispatchers + gate + bots in-parent."""

    def __init__(self, run_dir: str, n_bots: int = 12,
                 n_dispatchers: int = 2, transport: str = "tcp") -> None:
        self.run_dir = run_dir
        self.n_bots = n_bots
        self.n_dispatchers = n_dispatchers
        self.transport = transport
        self.rebalance_cfg = RebalanceConfig(
            enabled=True, driver_dispatcher=1, interval=0.5,
            report_interval=0.25, stale_after=3.0, min_entity_delta=4,
            max_moves_per_round=4, migrate_timeout=4.0, cooldown=2.0)
        # 3 s, not the chaos harness's 1 s: the children are real
        # processes competing for the same (often 1-core) host — a busy
        # box legitimately deschedules a child past 1 s, and a flapping
        # link mid-boot turns a timing artifact into a spurious restart.
        self.peer_heartbeat_timeout = 3.0
        self.dispatchers: list[Optional[DispatcherService]] = []
        # Every dispatcher object ever started (dead ones included): the
        # migration counters are summed over OBJECTS, because a stopped
        # service unregisters its telemetry children and family sums would
        # go backwards across a restart.
        self._all_dispatchers: list[DispatcherService] = []
        self.ports: list[int] = []
        self.gate: Optional[GateService] = None
        self.games: list[Optional[subprocess.Popen]] = []
        self.bots: list[ClientBot] = []
        self._sync_tasks: list[asyncio.Task] = []
        self._ping_seq = 0
        self._pongs: dict[str, list] = {}

    # --- lifecycle ----------------------------------------------------------

    async def start(self, boot_deadline: float = 60.0) -> None:
        uds_dir = self.run_dir if self.transport == "uds" else None
        for i in range(self.n_dispatchers):
            d = DispatcherService(
                i + 1, desired_games=2, desired_gates=1,
                peer_heartbeat_timeout=self.peer_heartbeat_timeout,
                rebalance=self.rebalance_cfg)
            d.rebalance_pause()  # resumed at the measured t0
            await d.start(uds_dir=uds_dir)
            self.dispatchers.append(d)
            self._all_dispatchers.append(d)
            self.ports.append(d.port)

        cfg = GoWorldConfig()
        cfg.deployment = DeploymentConfig(
            desired_games=2, desired_gates=1,
            desired_dispatchers=self.n_dispatchers)
        cfg.dispatchers = {
            i + 1: DispatcherConfig(port=p)
            for i, p in enumerate(self.ports)}
        cfg.gates = {1: GateConfig(
            port=0, position_sync_interval=0.05, heartbeat_timeout=30.0)}
        cfg.cluster = ClusterConfig(
            peer_heartbeat_timeout=self.peer_heartbeat_timeout,
            reconnect_max_interval=1.0,
            transport=self.transport,
            uds_dir=self.run_dir if self.transport == "uds" else "")
        cfg.rebalance = self.rebalance_cfg
        self.cfg = cfg
        self.gate = GateService(1, cfg)
        await self.gate.start()

        # Debug ports for the REAL game children: the cluster-view
        # convergence check scrapes their /snapshot over HTTP — the same
        # production path the driver dispatcher's collector uses.
        self.game_http = [self._free_port(), self._free_port()]
        rb = self.rebalance_cfg
        ini = _INI.format(
            g1_http=self.game_http[0], g2_http=self.game_http[1],
            n_disp=self.n_dispatchers,
            dispatcher_sections="".join(
                f"[dispatcher{i + 1}]\nport = {p}\n\n"
                for i, p in enumerate(self.ports)),
            gate_port=self.gate.port, dir=self.run_dir,
            transport=self.transport,
            uds_dir=self.run_dir if self.transport == "uds" else "",
            hb=self.peer_heartbeat_timeout,
            interval=rb.interval, report_interval=rb.report_interval,
            stale_after=rb.stale_after, min_delta=rb.min_entity_delta,
            max_moves=rb.max_moves_per_round,
            migrate_timeout=rb.migrate_timeout, cooldown=rb.cooldown)
        ini_path = os.path.join(self.run_dir, "goworld.ini")
        with open(ini_path, "w", encoding="utf-8") as f:
            f.write(ini)

        env = dict(os.environ, PYTHONPATH=_REPO, JAX_PLATFORMS="cpu")
        for gid in (1, 2):
            logf = open(os.path.join(self.run_dir, f"game{gid}.out.log"),
                        "ab")
            proc = subprocess.Popen(
                [sys.executable, "-m", "goworld_tpu.chaos.game_proc",
                 "-gid", str(gid), "-configfile", ini_path],
                cwd=self.run_dir, env=env, stdout=logf,
                stderr=subprocess.STDOUT)
            logf.close()
            self.games.append(proc)

        await self._wait(
            lambda: all(
                sum(1 for gi in d.games.values() if gi.connected) == 2
                for d in self.dispatchers if d is not None)
            and self.dispatchers[0].deployment_ready,
            boot_deadline, "game processes never all connected",
            on_fail=self._game_log_tails)
        # Both games must have reported (arena ids come from the reports).
        await self._wait(
            lambda: len(self._planner().reports.games()) == 2
            and all(self._arena(g) is not None for g in (1, 2)),
            boot_deadline, "games never reported their arenas")

        for i in range(self.n_bots):
            bot = ClientBot(name=f"mgbot{i}", strict=True,
                            heartbeat_interval=1.0)
            self._pongs[bot.name] = []
            bot.rpc_handlers[(None, "Pong")] = (
                lambda entity, n, name=bot.name: self._pongs[name].append(n))
            await bot.connect("127.0.0.1", self.gate.port)
            await bot.wait_player(timeout=15)
            self.bots.append(bot)
            self._sync_tasks.append(
                asyncio.get_running_loop().create_task(self._sync_loop(bot)))
        # Skew barrier: every avatar sits in game1's arena (game2 is
        # boot-banned), visible through the load reports.
        await self._wait(
            lambda: self._arena_pop(1) == self.n_bots,
            30.0, "avatars never all collected in game1's arena")

    async def stop(self) -> None:
        for t in self._sync_tasks:
            t.cancel()
        self._sync_tasks.clear()
        for b in self.bots:
            await b.close()
        self.bots.clear()
        if self.gate is not None:
            await self.gate.stop()
            self.gate = None
        for proc in self.games:
            if proc is not None and proc.poll() is None:
                proc.terminate()
        deadline = time.monotonic() + 10.0
        for proc in self.games:
            if proc is None:
                continue
            while proc.poll() is None and time.monotonic() < deadline:
                await asyncio.sleep(0.05)
            if proc.poll() is None:
                proc.kill()
        self.games.clear()
        for d in self.dispatchers:
            if d is not None:
                await d.stop()
        self.dispatchers.clear()

    def _game_log_tails(self) -> str:
        tails = []
        for gid in (1, 2):
            try:
                with open(os.path.join(self.run_dir,
                                       f"game{gid}.out.log"), "rb") as f:
                    data = f.read()[-800:]
                tails.append(f"game{gid}: ...{data.decode(errors='replace')}")
            except OSError:
                pass
        return "\n".join(tails)

    async def _wait(self, cond, timeout: float, what: str,
                    on_fail=None) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if cond():
                return
            await asyncio.sleep(0.02)
        extra = f"\n{on_fail()}" if on_fail is not None else ""
        raise AssertionError(f"multigame: {what} (after {timeout:.1f}s)"
                             f"{extra}")

    async def _sync_loop(self, bot: ClientBot) -> None:
        """Light client-driven position jitter (the sync plane the migrate
        window must buffer — records sent mid-migrate must land on the
        entity's NEW game, never a stale one)."""
        import random

        while True:
            await asyncio.sleep(0.1)
            p = bot.player
            if p is not None:
                p.sync_position(p.x + random.uniform(-0.5, 0.5), p.y,
                                p.z + random.uniform(-0.5, 0.5), p.yaw)

    # --- observability -------------------------------------------------------

    @staticmethod
    def _free_port() -> int:
        import socket

        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return int(s.getsockname()[1])

    def collector_targets(self):
        """Cluster-collector targets: the REAL game children are scraped
        over their debug HTTP ports (the exact production path), the
        in-parent dispatchers/gate feed the collector directly — the
        process-global health-provider slot can't tell them apart."""
        from goworld_tpu.telemetry.collector import http_target

        def disp_fetch(i: int):
            async def fetch() -> dict:
                d = self.dispatchers[i]
                if d is None:
                    raise RuntimeError("dispatcher killed")
                return {"health": d._health(), "metrics": {}}

            return fetch

        async def gate_fetch() -> dict:
            if self.gate is None:
                raise RuntimeError("gate down")
            return {"health": self.gate._health(), "metrics": {}}

        targets = [(f"dispatcher{i + 1}", disp_fetch(i))
                   for i in range(self.n_dispatchers)]
        for gid in (1, 2):
            targets.append(http_target(
                f"game{gid}", f"127.0.0.1:{self.game_http[gid - 1]}"))
        targets.append(("gate1", gate_fetch))
        return targets

    async def assert_cluster_view_converged(
            self, deadline: float = 25.0) -> float:
        """ISSUE 13: the aggregated view over BOTH real game processes +
        dispatchers + gate must re-converge — every process reporting
        (the restarted dispatcher included), client census conserved at
        the bot count across the two games, no stale generation rows.
        Returns seconds until convergence."""
        import json as _json

        from goworld_tpu.telemetry.collector import ClusterCollector

        coll = ClusterCollector(self.collector_targets(), interval=0.1)
        t0 = time.monotonic()
        last = None
        while time.monotonic() - t0 < deadline:
            await coll.poll_once()
            summary = coll.view()["summary"]
            census = summary["census"]
            if (summary["reporting"] == summary["expected"]
                    and not summary["alerts"]
                    and census["clients_conserved"]
                    and census["gate_clients"] == len(self.bots)):
                return time.monotonic() - t0
            last = summary
            await asyncio.sleep(0.1)
        raise AssertionError(
            "multigame: /cluster view never re-converged: "
            f"{_json.dumps(last, default=str)}")

    def _planner(self):
        for d in self.dispatchers:
            if d is not None:
                return d.planner
        raise AssertionError("no live dispatcher")

    def _report(self, gameid: int) -> dict | None:
        return self._planner().reports.get(gameid)

    def _arena(self, gameid: int):
        r = self._report(gameid)
        if r is None:
            return None
        for sid, kind, _count in r.get("spaces", []):
            if kind == ARENA_KIND:
                return sid
        return None

    def _arena_pop(self, gameid: int) -> int:
        r = self._report(gameid) or {}
        for _sid, kind, count in r.get("spaces", []):
            if kind == ARENA_KIND:
                return int(count)
        return 0

    def census(self) -> tuple[int, int]:
        return self._arena_pop(1), self._arena_pop(2)

    def _mig_counters(self) -> dict[str, int]:
        return {
            "routed": sum(d.migrates_routed for d in self._all_dispatchers),
            "bounced": sum(d.migrates_bounced
                           for d in self._all_dispatchers),
            "cancel": sum(d.migrates_cancelled
                          for d in self._all_dispatchers),
        }

    def bot_errors(self) -> list[str]:
        return [err for b in self.bots for err in b.errors]

    async def assert_rpc_roundtrip(self, deadline: float = 15.0) -> float:
        """Every bot pings its avatar (wherever it now lives) and must get
        its pong — the client-visible zero-loss probe."""
        self._ping_seq += 1
        n = self._ping_seq
        t0 = time.monotonic()
        for b in self.bots:
            assert b.player is not None, f"{b.name}: player mirror lost"
            b.player.call_server("Ping_Client", n)
        await self._wait(
            lambda: all(n in self._pongs[b.name] for b in self.bots),
            deadline, f"ping {n}: not every bot got its pong")
        return time.monotonic() - t0

    # --- phases --------------------------------------------------------------

    async def converge(self, deadline: float = 30.0) -> dict:
        """Resume the planner at t0; wait until the arena populations are
        balanced AND stable (two consecutive report snapshots agree and
        the full census is conserved — in-flight migrations make the sum
        dip, so a conserved sum means nothing is mid-air)."""
        mig0 = self._mig_counters()
        tol = self.rebalance_cfg.min_entity_delta
        t0 = time.monotonic()
        for d in self.dispatchers:
            if d is not None:
                d.rebalance_resume()
        # Stability must SPAN report cycles (the census is read from the
        # cached reports): balanced and unchanged for 3 report intervals,
        # with the sum conserved (an in-flight migration makes it dip).
        span = 3.0 * self.rebalance_cfg.report_interval
        state = {"census": None, "since": 0.0}

        def balanced() -> bool:
            c = self.census()
            now = time.monotonic()
            if c != state["census"]:
                state["census"], state["since"] = c, now
            return (sum(c) == self.n_bots
                    and abs(c[0] - c[1]) <= tol
                    and now - state["since"] >= span)

        await self._wait(
            balanced, deadline, "never converged",
            on_fail=lambda: (
                f"census {self.census()}, reports "
                f"{ {g: self._report(g) for g in (1, 2)} }\n"
                + self._game_log_tails()))
        convergence_s = time.monotonic() - t0
        rt = await self.assert_rpc_roundtrip()
        mig1 = self._mig_counters()
        return {
            "convergence_s": round(convergence_s, 3),
            "census": list(self.census()),
            "migrations_done": int(mig1["routed"] - mig0["routed"]),
            "migrations_rolled_back": int(
                (mig1["cancel"] - mig0["cancel"])
                + (mig1["bounced"] - mig0["bounced"])),
            "post_roundtrip_s": round(rt, 3),
            "zero_loss": sum(self.census()) == self.n_bots,
            "bot_errors": len(self.bot_errors()),
        }

    async def migrate_during_dispatcher_restart(
        self, moves: int = 4, downtime: float = 1.0,
        deadline: float = 25.0,
    ) -> dict:
        """THE ROADMAP-named scenario: command a batch of migrations, kill
        a dispatcher inside the migrate window (before yielding to the
        event loop, so nothing has completed yet), restart it, and require
        every migration to complete (possibly via the replay-ring flush)
        or roll back — census conserved, every bot answering."""
        for d in self.dispatchers:
            if d is not None:
                d.rebalance_pause()
        donor = 1 if self._arena_pop(1) >= self._arena_pop(2) else 2
        recv = 2 if donor == 1 else 1
        from_space, to_space = self._arena(donor), self._arena(recv)
        assert from_space and to_space, "arenas unknown"
        mig0 = self._mig_counters()
        census0 = self.census()
        # The migrate chain fans over dispatchers by id hash: the space
        # query rides hash(to_space)'s dispatcher, the per-entity blocks
        # ride hash(eid)'s. Kill the one NOT owning the space query so
        # queries still flow and ~half the entities' MIGRATE_REQUESTs are
        # mid-air when the link dies (they park in the games' replay
        # rings and must resolve after the restart).
        owner_idx = hash_entity_id(to_space) % self.n_dispatchers
        victim = (owner_idx + 1) % self.n_dispatchers
        # The command itself must ride a SURVIVING dispatcher's game link
        # (sending it through the victim would abort it in the socket
        # buffer and nothing would ever be mid-air).
        commander = self.dispatchers[owner_idx]
        p = Packet()
        p.append_entity_id(from_space)
        p.append_entity_id(to_space)
        p.append_uint16(recv)
        p.append_uint16(moves)
        now = time.monotonic()
        commander._game(donor).dispatch(MsgType.REBALANCE_MIGRATE, p, now)
        # Same event-loop turn: the command is in the socket buffer but no
        # ack has come back — the kill lands inside the migrate window.
        d = self.dispatchers[victim]
        for proxy in list(d._conns):
            proxy.conn.abort()
        await d.stop()
        self.dispatchers[victim] = None
        gwlog.infof("multigame: dispatcher %d killed mid-migrate",
                    victim + 1)
        await asyncio.sleep(downtime)
        t0 = time.monotonic()
        nd = DispatcherService(
            victim + 1, desired_games=2, desired_gates=1,
            peer_heartbeat_timeout=self.peer_heartbeat_timeout,
            rebalance=self.rebalance_cfg)
        nd.rebalance_pause()
        self._all_dispatchers.append(nd)
        for _ in range(100):
            try:
                await nd.start(
                    port=self.ports[victim],
                    uds_dir=(self.run_dir if self.transport == "uds"
                             else None))
                break
            except OSError:
                await asyncio.sleep(0.05)
        else:
            raise AssertionError("could not rebind dispatcher port")
        self.dispatchers[victim] = nd

        # Settled = census conserved and unchanged for 3 report intervals
        # (an in-flight migration makes the sum dip; a just-landing one
        # changes the split).
        span = 3.0 * self.rebalance_cfg.report_interval
        state = {"census": None, "since": 0.0}

        def settled() -> bool:
            c = self.census()
            t = time.monotonic()
            if c != state["census"]:
                state["census"], state["since"] = c, t
            return sum(c) == self.n_bots and t - state["since"] >= span

        def diag() -> str:
            lines = [f"reports: { {g: self._report(g) for g in (1, 2)} }"]
            for i, d in enumerate(self.dispatchers):
                if d is None:
                    lines.append(f"dispatcher[{i}]: None")
                    continue
                lines.append(
                    f"dispatcher[{i}] id={d.dispid} games="
                    f"{ {g: gi.connected for g, gi in d.games.items()} } "
                    f"planner_games={d.planner.reports.games()}")
            lines.append(self._game_log_tails())
            return "\n".join(lines)
        await self._wait(settled, deadline,
                         f"census never settled (is {self.census()})",
                         on_fail=diag)
        rt = await self.assert_rpc_roundtrip(deadline)
        recovery = time.monotonic() - t0
        mig1 = self._mig_counters()
        errors = self.bot_errors()
        assert not errors, f"bot errors during migrate+restart: {errors[:5]}"
        done = int(mig1["routed"] - mig0["routed"])
        rolled = int((mig1["cancel"] - mig0["cancel"])
                     + (mig1["bounced"] - mig0["bounced"]))
        view_converge = await self.assert_cluster_view_converged()
        return {
            "scenario": "migrate_during_dispatcher_restart",
            "recovery_s": round(recovery, 3),
            "cluster_view_converge_s": round(view_converge, 3),
            "post_roundtrip_s": round(rt, 3),
            "census_before": list(census0),
            "census_after": list(self.census()),
            "migrations_done": done,
            "migrations_rolled_back": rolled,
            "commanded": moves,
            "zero_loss": sum(self.census()) == self.n_bots,
            "bot_errors": len(errors),
        }


async def _run_multigame(run_dir: str, n_bots: int, transport: str,
                         with_restart_phase: bool) -> dict:
    cluster = MultigameCluster(run_dir, n_bots=n_bots, transport=transport)
    # start() INSIDE the try: a boot failure must still tear the cluster
    # down — its game children are real OS processes, and two leaked
    # games silently eating a 1-core host skew every measurement that
    # follows (found the hard way: a failed boot leaked children that
    # depressed the pinned floor a full tier-1 run later).
    try:
        await cluster.start()
        out = await cluster.converge()
        out["skew_initial"] = [n_bots, 0]
        if with_restart_phase:
            out["dispatcher_restart_phase"] = (
                await cluster.migrate_during_dispatcher_restart())
        out["bot_errors"] = len(cluster.bot_errors())
        assert not cluster.bot_errors(), cluster.bot_errors()[:5]
    finally:
        await cluster.stop()
    return out


def run_multigame(run_dir: str, n_bots: int = 12, transport: str = "tcp",
                  with_restart_phase: bool = True) -> dict:
    """Blocking driver (bench.py --multigame / the 7th chaos scenario)."""
    return asyncio.run(
        _run_multigame(run_dir, n_bots, transport, with_restart_phase))

"""Child game-process entry of the multigame harness.

Run as ``python -m goworld_tpu.chaos.game_proc -gid N -configfile
goworld.ini``: registers the mg_server world and hands off to the normal
game process lifecycle (goworld_tpu.game.service.run parses the argv).
The multigame harness (chaos/multigame.py) spawns two of these beside its
in-parent dispatchers + gate — the entity manager is per-process state,
so a REAL multi-game world needs real processes.
"""

from __future__ import annotations

import sys

from goworld_tpu.chaos import mg_server
from goworld_tpu.game import service as game_service


def main() -> int:
    mg_server.register()
    return game_service.run()


if __name__ == "__main__":
    sys.exit(main())

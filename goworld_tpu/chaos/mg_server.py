"""Server module of the multigame harness's CHILD game processes.

Registered by ``chaos/game_proc.py`` (the ``python -m`` entry each child
runs) and imported by the parent only for the class names. The world is
deliberately minimal but real: each game creates ``MG_ARENAS`` kind-1 AOI
arenas at deployment-ready (default 1; the whole-space scenarios give the
donor several and the receivers ZERO — a receiver with no same-kind space
is exactly what makes the planner reach for whole-space moves), boot
avatars spread round-robin across their LOCAL arenas (boot is game1-only,
so the initial placement is fully skewed onto game1 — the shape the
rebalancer must fix), and avatars answer Ping→Pong for the harness's
zero-loss roundtrip probes.
"""

from __future__ import annotations

import os

from goworld_tpu.entity import entity_manager as em
from goworld_tpu.entity.entity import Entity
from goworld_tpu.entity.space import Space
from goworld_tpu.entity.vector import Vector3

ARENA_KIND = 1
AOI_DISTANCE = 100.0


def _n_arenas() -> int:
    """How many arenas THIS game process creates at deployment-ready.
    Set per-child by the harness (MG_ARENAS); bad values mean 1."""
    try:
        return max(0, int(os.environ.get("MG_ARENAS", "1")))
    except ValueError:
        return 1


def local_arenas() -> list:
    out = [s for s in em._spaces.values()
           if s.kind == ARENA_KIND and not s.is_destroyed()]
    out.sort(key=lambda s: s.id)  # deterministic round-robin order
    return out


class MGSpace(Space):
    def on_space_created(self):
        if self.kind == ARENA_KIND:
            self.enable_aoi(AOI_DISTANCE)

    def on_game_ready(self):
        # Runs on the nil space at deployment-ready: create this game's
        # configured arena count (a game with MG_ARENAS=0 hosts none — the
        # whole-space receivers start arena-less on purpose).
        if self.is_nil():
            for _ in range(_n_arenas() - len(local_arenas())):
                em.create_space_locally(ARENA_KIND)


class MGAvatar(Entity):
    """Boot avatar: joins a local arena (round-robin across them when the
    game hosts several), echoes Ping→Pong, lets its client drive position
    (the sync plane the migrate window buffers)."""

    _joined = 0

    @classmethod
    def describe_entity_type(cls, desc):
        desc.set_use_aoi(True, AOI_DISTANCE)

    def on_client_connected(self):
        self.set_client_syncing(True)
        self._join_arena()

    def _join_arena(self):
        if self.is_destroyed() or self.client is None:
            return
        arenas = local_arenas()
        if not arenas:
            # Boot raced deployment-ready; the arenas appear momentarily.
            self.add_callback(0.1, "_join_arena")
            return
        if self.space is not None and self.space in arenas:
            return
        arena = arenas[MGAvatar._joined % len(arenas)]
        x = 2.0 * (MGAvatar._joined % 40)
        MGAvatar._joined += 1
        self.enter_space(arena.id, Vector3(x, 0.0, 10.0))

    def Ping_Client(self, n):
        self.call_client("Pong", n)


def register() -> None:
    em.register_space(MGSpace)
    em.register_entity(MGAvatar)

"""Server module of the multigame harness's CHILD game processes.

Registered by ``chaos/game_proc.py`` (the ``python -m`` entry each child
runs) and imported by the parent only for the class names. The world is
deliberately minimal but real: every game creates one kind-1 AOI arena at
deployment-ready, boot avatars join their LOCAL arena (game2 is
boot-banned, so the initial placement is fully skewed onto game1 — the
shape the rebalancer must fix), and avatars answer Ping→Pong for the
harness's zero-loss roundtrip probes.
"""

from __future__ import annotations

from goworld_tpu.entity import entity_manager as em
from goworld_tpu.entity.entity import Entity
from goworld_tpu.entity.space import Space
from goworld_tpu.entity.vector import Vector3

ARENA_KIND = 1
AOI_DISTANCE = 100.0


def local_arena():
    for s in em._spaces.values():
        if s.kind == ARENA_KIND and not s.is_destroyed():
            return s
    return None


class MGSpace(Space):
    def on_space_created(self):
        if self.kind == ARENA_KIND:
            self.enable_aoi(AOI_DISTANCE)

    def on_game_ready(self):
        # Runs on the nil space at deployment-ready: every game hosts one
        # arena, so the rebalancer always has a same-kind receiver space.
        if self.is_nil() and local_arena() is None:
            em.create_space_locally(ARENA_KIND)


class MGAvatar(Entity):
    """Boot avatar: joins the local arena, echoes Ping→Pong, lets its
    client drive position (the sync plane the migrate window buffers)."""

    _joined = 0

    @classmethod
    def describe_entity_type(cls, desc):
        desc.set_use_aoi(True, AOI_DISTANCE)

    def on_client_connected(self):
        self.set_client_syncing(True)
        self._join_arena()

    def _join_arena(self):
        if self.is_destroyed() or self.client is None:
            return
        arena = local_arena()
        if arena is None:
            # Boot raced deployment-ready; the arena appears momentarily.
            self.add_callback(0.1, "_join_arena")
            return
        if self.space is arena:
            return
        x = 2.0 * (MGAvatar._joined % 40)
        MGAvatar._joined += 1
        self.enter_space(arena.id, Vector3(x, 0.0, 10.0))

    def Ping_Client(self, n):
        self.call_client("Pong", n)


def register() -> None:
    em.register_space(MGSpace)
    em.register_entity(MGAvatar)

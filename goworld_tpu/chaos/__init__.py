"""Fault-injection (chaos) harness.

Drives a REAL in-process cluster — N dispatchers + one game + one gate over
localhost TCP or unix sockets, with strict protocol bots — while injecting
the faults the resilience layer exists for: dispatcher crash + restart,
mid-tick link severing (socket abort, not clean close), a process stalled
past the heartbeat deadline, a storage backend failing N writes, a GAME
crash + cold recreate, and a GATE crash + client reconnect wave. Scenarios
assert zero bot errors, zero entity loss, and recovery within a deadline.

The seventh scenario — migrate-during-dispatcher-restart — needs two real
game processes (the entity manager is per-process state) and lives in the
subprocess-backed multigame harness (``chaos/multigame.py``), which also
carries the ``bench.py --multigame`` rebalance floor.

Entry points: the scenario coroutines here (used by tests/test_chaos.py)
and ``bench.py --chaos`` (one compact JSON headline like the other bench
modes).
"""

from goworld_tpu.chaos.harness import (  # noqa: F401
    ChaosCluster,
    FlakyBackend,
    dropped_packet_count,
    run_chaos,
    scenario_battle_royale_freeze_restore,
    scenario_battle_royale_keyframe_storm,
    scenario_battle_royale_kill_game,
    scenario_dispatcher_restart,
    scenario_game_kill_recreate,
    scenario_gate_kill_reconnect,
    scenario_paused_dispatcher,
    scenario_service_outage_dispatcher_restart,
    scenario_severed_link,
    scenario_storage_outage,
)

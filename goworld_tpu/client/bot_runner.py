"""N-bot stress harness: the framework's distributed correctness+perf gate.

Reference parity: ``examples/test_client/test_client.go:35-84`` (spawn N
bots, wait, report) and ``ClientEntity.go:160-242`` (one weighted-random
"thing" at a time per bot, 5 s timeout each, ``-strict`` promotes timeouts
and protocol errors to fatal). The CI gate shape is
``.travis.yml:22-34``: 200 bots, strict, 300 s, across a hot reload.

Run:  python -m goworld_tpu.client -N 200 -strict -duration 300

Design differences from the reference (asyncio-native, not a port): all
bots share one event loop; each bot is a task driving a ClientBot; position
sync runs as a background 100 ms random-walk while the bot is in a space
(ClientBot.go:225-237's sync tick).
"""

from __future__ import annotations

import asyncio
import random
import time
from typing import Optional

from goworld_tpu.client.client import ClientBot, StrictError

THING_TIMEOUT = 5.0

# (method name, weight, timeout_fatal_in_strict). Mirrors the reference's
# _DO_THINGS table (ClientEntity.go:166-180): prof-channel chat may have no
# listener with few bots, so its timeout never escalates; mail and pubsub
# are enabled here (the reference lists them commented out but the server
# supports them end-to-end).
THINGS = [
    ("DoEnterRandomSpace", 1, True),
    ("DoEnterRandomNilSpace", 1, True),
    ("DoSayInWorldChannel", 1, True),
    ("DoSayInProfChannel", 1, False),
    ("DoTestListField", 1, True),
    ("DoTestAOI", 1, True),
    ("DoTestCallAll", 1, True),
    ("DoTestComplexAttr", 1, True),
    ("DoTestPublish", 1, True),
    ("DoSendMail", 1, True),
    ("DoGetMails", 1, True),
]

# Every thing is safe to re-issue, so bots retry within the budget instead
# of failing on the first silent loss (see _do_one_thing). Retry counts are
# reported so a noisy cluster is still visible.
RETRYABLE_THINGS = {t[0] for t in THINGS}


class ScenarioBot:
    """One bot: login → loop weighted random scenarios until the deadline."""

    def __init__(
        self,
        index: int,
        host: str,
        port: int,
        *,
        strict: bool = False,
        n_clients: int = 1,
        ws: bool = False,
        rudp: bool = False,
        rudp_protocol: str = "kcp",
        rudp_fec: str = "10,3",
        tls: bool = False,
        compress: bool = False,
        seed: Optional[int] = None,
        thing_timeout: float = THING_TIMEOUT,
    ) -> None:
        self.index = index
        self.thing_timeout = thing_timeout
        self.host = host
        self.port = port
        self.ws = ws
        self.rudp = rudp
        self.rudp_protocol = rudp_protocol
        self.rudp_fec = rudp_fec
        self.n_clients = n_clients
        self.rng = random.Random(seed)
        self.bot = ClientBot(
            name=f"bot{index}", strict=strict,
            heartbeat_interval=2.0, tls=tls, compress=compress,
        )
        self.space_kind = 0
        self.current_thing: Optional[str] = None
        self._done: Optional[asyncio.Future] = None
        self.stats: dict[str, list[float]] = {}
        self.timeouts: dict[str, int] = {}
        self.retries: dict[str, int] = {}
        self._install_handlers()

    # --- completion plumbing -------------------------------------------------

    def _thing_done(self, thing: str) -> None:
        if self.current_thing == thing and self._done and not self._done.done():
            self._done.set_result(thing)

    def _install_handlers(self) -> None:
        h = self.bot.rpc_handlers
        h[(None, "OnLogin")] = lambda e, ok: None
        h[(None, "OnEnterSpace")] = self._on_enter_space
        h[(None, "OnEnterRandomNilSpace")] = (
            lambda e: self._thing_done("DoEnterRandomNilSpace")
        )
        h[(None, "OnSay")] = self._on_say
        h[(None, "OnTestListField")] = (
            lambda e, lst: self._thing_done("DoTestListField")
        )
        h[(None, "OnTestAOI")] = lambda e, tid: self._thing_done("DoTestAOI")
        h[(None, "OnTestCallAll")] = lambda e: self._thing_done("DoTestCallAll")
        h[(None, "TestCallAllPlzEcho")] = self._on_call_all_echo
        h[(None, "OnTestComplexAttrStep1")] = self._on_complex_step1
        h[(None, "OnTestComplexAttrClear")] = self._on_complex_clear
        h[(None, "OnTestPublish")] = self._on_publish
        h[(None, "OnSendMail")] = lambda e, ok: self._thing_done("DoSendMail")
        h[(None, "OnGetMails")] = lambda e, ok: self._thing_done("DoGetMails")

    def _on_enter_space(self, e, kind: int) -> None:
        self.space_kind = int(kind)
        self._thing_done("DoEnterRandomSpace")

    def _on_say(self, e, eid: str, name: str, channel: str, content: str) -> None:
        if self.bot.player is not None and eid == self.bot.player.id:
            if channel == "world":
                self._thing_done("DoSayInWorldChannel")
            elif channel == "prof":
                self._thing_done("DoSayInProfChannel")

    def _on_call_all_echo(self, e, eid: str) -> None:
        # AllClients echo countdown: every client echoes back to the server
        # (Avatar.TestCallAllEcho_AllClients decrements the caller's counter).
        if self.bot.player is not None:
            self.bot.player.call_server("TestCallAllEcho_AllClients", eid)

    def _on_complex_step1(self, e) -> None:
        # Strict check: the nested attr tree must have synced to the mirror
        # before the clear lands (ClientEntity.go DoTestComplexAttr).
        attrs = self.bot.player.attrs if self.bot.player else {}
        node = attrs.get("complexAttr", {})
        try:
            final = node["key1"]["key2"][1][0]["finalkey"]
        except (KeyError, IndexError, TypeError):
            final = None
        if final != "iamhere":
            self.bot.error(
                f"complexAttr desync: expected finalkey, got {node!r}"
            )

    def _on_complex_clear(self, e) -> None:
        attrs = self.bot.player.attrs if self.bot.player else {}
        if attrs.get("complexAttr"):
            self.bot.error(
                f"complexAttr not cleared: {attrs.get('complexAttr')!r}"
            )
        self._thing_done("DoTestComplexAttr")

    def _on_publish(self, e, publisher: str, subject: str, content: str) -> None:
        if self.bot.player is not None and publisher == self.bot.player.id:
            self._thing_done("DoTestPublish")

    # --- things --------------------------------------------------------------

    def _start_thing(self, thing: str) -> None:
        p = self.bot.player
        assert p is not None
        if thing == "DoEnterRandomSpace":
            # Space-kind pool scales with fleet size (ClientEntity.go:247-252).
            # Never the *current* kind: the server early-returns on a same-kind
            # enter (Avatar._enter_space_kind) and no ack would ever arrive.
            kind_max = max(2, self.n_clients // 400)
            kind = 1 + self.rng.randrange(kind_max)
            if kind == self.space_kind:
                kind = 1 + (kind % kind_max)
            p.call_server("EnterSpace_Client", kind)
        elif thing == "DoEnterRandomNilSpace":
            p.call_server("EnterRandomNilSpace_Client")
        elif thing == "DoSayInWorldChannel":
            p.call_server("Say_Client", "world", f"hello from {self.bot.name}")
        elif thing == "DoSayInProfChannel":
            p.call_server("Say_Client", "prof", f"prof ping {self.bot.name}")
        elif thing == "DoTestListField":
            p.call_server("TestListField_Client")
        elif thing == "DoTestAOI":
            p.call_server("TestAOI_Client")
        elif thing == "DoTestCallAll":
            p.call_server("TestCallAll_Client")
        elif thing == "DoTestComplexAttr":
            p.call_server("TestComplexAttr_Client")
        elif thing == "DoTestPublish":
            p.call_server("TestPublish_Client")
        elif thing == "DoSendMail":
            p.call_server("SendMail_Client", p.id, {"text": "stress mail"})
        elif thing == "DoGetMails":
            p.call_server("GetMails_Client")
        else:  # pragma: no cover
            raise ValueError(thing)

    def _choose_thing(self) -> tuple[str, bool]:
        if self.space_kind == 0:
            # Not in a real space yet: must enter one first (doSomething's
            # forced first thing).
            return "DoEnterRandomSpace", True
        import os

        only = os.environ.get("STRESS_THINGS", "")
        things = THINGS
        if only:
            allow = set(only.split(","))
            things = [t for t in THINGS if t[0] in allow] or THINGS
        total = sum(w for _, w, _ in things)
        r = self.rng.randrange(total)
        for method, w, fatal in things:
            if r < w:
                return method, fatal
            r -= w
        raise AssertionError("unreachable")

    async def _do_one_thing(self) -> None:
        thing, timeout_fatal = self._choose_thing()
        self.current_thing = thing
        self._done = asyncio.get_running_loop().create_future()
        t0 = time.perf_counter()
        self._start_thing(thing)
        try:
            if thing in RETRYABLE_THINGS:
                # Things are re-sent within the budget rather than one-shot.
                # A scenario's server-side context is legitimately
                # invalidated by concurrent distributed activity — e.g.
                # DoTestPublish races the avatar's own ack-less async
                # subscriptions after login; an enter-space request dies
                # with a freezing game (deliberately not freeze data); a
                # TestCallAll countdown snapshots AOI neighbors that may
                # migrate before their echo lands. Re-issuing is the
                # recovery path; only persistent failure (timeout despite
                # retries) escalates. The reference instead runs its bots
                # strictly outside reload windows and with the raciest
                # scenarios disabled (ClientEntity.go:166-180).
                deadline = t0 + self.thing_timeout
                while True:
                    budget = min(2.5, deadline - time.perf_counter())
                    if budget <= 0:
                        raise asyncio.TimeoutError
                    try:
                        await asyncio.wait_for(
                            asyncio.shield(self._done), budget
                        )
                        break
                    except asyncio.TimeoutError:
                        if time.perf_counter() >= deadline:
                            raise
                        if (
                            self.bot.player is None
                            or self.bot.player.typename != "Avatar"
                        ):
                            # Player mirror mid-recreate (GiveClientTo /
                            # migration / reload): the run loop guards the
                            # FIRST issue but the retry path must too —
                            # keep waiting, retry once it's back.
                            continue
                        self.retries[thing] = self.retries.get(thing, 0) + 1
                        self._start_thing(thing)
            else:
                await asyncio.wait_for(self._done, self.thing_timeout)
            self.stats.setdefault(thing, []).append(time.perf_counter() - t0)
        except asyncio.TimeoutError:
            self.timeouts[thing] = self.timeouts.get(thing, 0) + 1
            if timeout_fatal:
                self.bot.error(
                    f"{thing} TIMEOUT after {self.thing_timeout:.0f}s"
                )
        finally:
            self.current_thing = None
            self._done = None

    async def _sync_loop(self) -> None:
        """100 ms position random walk while in a space (the AOI/sync-plane
        load, ClientBot.go:225-237)."""
        while True:
            await asyncio.sleep(0.1)
            p = self.bot.player
            if p is not None and self.space_kind > 0 and p.typename == "Avatar":
                x = p.x + self.rng.uniform(-10, 10)
                z = p.z + self.rng.uniform(-10, 10)
                p.sync_position(x, p.y, z, self.rng.uniform(0, 360))

    # --- lifecycle -----------------------------------------------------------

    async def run(self, duration: float) -> None:
        if self.ws:
            await self.bot.connect_ws(self.host, self.port)
        elif self.rudp:
            from goworld_tpu.config.read_config import parse_fec

            await self.bot.connect_rudp(
                self.host, self.port, protocol=self.rudp_protocol,
                fec=parse_fec(self.rudp_fec),
            )
        else:
            await self.bot.connect(self.host, self.port)
        sync_task: Optional[asyncio.Task] = None
        try:
            acct = await self.bot.wait_player(timeout=30)
            acct.call_server(
                "Login_Client", f"stress_{self.index}", "123456"
            )
            deadline = time.monotonic() + duration
            while self.bot.player is None or self.bot.player.typename != "Avatar":
                if time.monotonic() > deadline:
                    self.bot.error("login never completed")
                    return
                await asyncio.sleep(0.05)
            # World-ready barrier: on a cold cluster the first server-side
            # space entry (on_client_connected → SpaceService) can be dropped
            # while the sharded services are still spinning up, so actively
            # re-request entry every few seconds — the client-side retry the
            # reference gets from its forced first DoEnterRandomSpace
            # (ClientEntity.go doSomething when space kind == 0).
            t0 = time.monotonic()
            kind_max = max(2, self.n_clients // 400)
            # The entry barrier scales with fleet size: a 600-bot login
            # storm on a single-core host legitimately takes >30 s of
            # server work before the last bots' first EnterSpace lands
            # (measured: fixed 30 s failed at N=600, flaked at N=400).
            entry_budget = max(30.0, 0.15 * self.n_clients)
            while (
                self.space_kind == 0
                and time.monotonic() - t0 < entry_budget
            ):
                self.bot.player.call_server(
                    "EnterSpace_Client", 1 + self.rng.randrange(kind_max)
                )
                t1 = time.monotonic()
                while self.space_kind == 0 and time.monotonic() - t1 < 4.0:
                    await asyncio.sleep(0.05)
            if self.space_kind == 0:
                self.bot.error("initial space entry never completed")
                return
            sync_task = asyncio.get_running_loop().create_task(self._sync_loop())
            while time.monotonic() < deadline:
                if self.bot.player is None or self.bot.player.typename != "Avatar":
                    # Player mirror mid-recreate (migration/GiveClientTo).
                    await asyncio.sleep(0.05)
                    continue
                await self._do_one_thing()
                await asyncio.sleep(self.rng.uniform(0.0, 0.1))
        finally:
            if sync_task is not None:
                sync_task.cancel()
            await self.bot.close()


async def run_fleet(
    n: int,
    gates: list[tuple[str, int]],
    duration: float,
    *,
    strict: bool = False,
    ws: bool = False,
    rudp: bool = False,
    rudp_protocol: str = "kcp",
    rudp_fec: str = "10,3",
    tls: bool = False,
    compress: bool = False,
    seed: Optional[int] = None,
    spawn_interval: float = 0.02,
    thing_timeout: float = THING_TIMEOUT,
    index_base: int = 0,
) -> dict:
    """Spawn ``n`` bots round-robin over ``gates``; gather a fleet report.

    Returns {"bots", "errors", "timeouts", "things": {name: {count, avg_ms,
    max_ms}}}. In strict mode the first StrictError propagates after all
    bots have been cancelled (the reference's fatal semantics).
    """
    rng = random.Random(seed)
    # index_base offsets bot indices (and thus the stress_<i> usernames /
    # avatar identities) so CONCURRENT fleets against one cluster don't
    # fight over the same avatars (each login steals the client binding).
    bots = [
        ScenarioBot(
            index_base + i, *gates[i % len(gates)], strict=strict,
            n_clients=n,
            ws=ws, rudp=rudp, rudp_protocol=rudp_protocol,
            rudp_fec=rudp_fec, tls=tls, compress=compress,
            seed=rng.randrange(2**31), thing_timeout=thing_timeout,
        )
        for i in range(n)
    ]

    async def staggered(i: int, bot: ScenarioBot):
        await asyncio.sleep(i * spawn_interval)  # avoid an accept() stampede
        await bot.run(duration)

    results = await asyncio.gather(
        *(staggered(i, b) for i, b in enumerate(bots)),
        return_exceptions=True,
    )
    first_err: Optional[BaseException] = None
    errors: list[str] = []
    for bot, res in zip(bots, results):
        errors.extend(bot.bot.errors)
        if isinstance(res, BaseException) and first_err is None:
            first_err = res
    if first_err is not None and strict:
        raise first_err
    things: dict[str, dict] = {}
    timeouts: dict[str, int] = {}
    for bot in bots:
        for thing, times in bot.stats.items():
            agg = things.setdefault(thing, {"count": 0, "_sum": 0.0, "max_ms": 0.0})
            agg["count"] += len(times)
            agg["_sum"] += sum(times)
            agg["max_ms"] = max(agg["max_ms"], max(times) * 1000.0)
        for thing, cnt in bot.timeouts.items():
            timeouts[thing] = timeouts.get(thing, 0) + cnt
    retries: dict[str, int] = {}
    for bot in bots:
        for thing, cnt in bot.retries.items():
            retries[thing] = retries.get(thing, 0) + cnt
    for agg in things.values():
        agg["avg_ms"] = round(agg.pop("_sum") / max(agg["count"], 1) * 1000.0, 1)
        agg["max_ms"] = round(agg["max_ms"], 1)
    return {
        "bots": n,
        "errors": errors,
        "timeouts": timeouts,
        "retries": retries,
        "things": things,
    }


def format_report(report: dict) -> str:
    lines = [f"bots={report['bots']} errors={len(report['errors'])}"]
    for thing in sorted(report["things"]):
        agg = report["things"][thing]
        t = report["timeouts"].get(thing, 0)
        lines.append(
            f"  {thing:24s} x{agg['count']:<6d} avg {agg['avg_ms']:7.1f} ms"
            f"  max {agg['max_ms']:8.1f} ms  timeouts {t}"
        )
    for thing, t in sorted(report["timeouts"].items()):
        if thing not in report["things"]:
            lines.append(f"  {thing:24s} x0      (all {t} timed out)")
    for err in report["errors"][:10]:
        lines.append(f"  ERROR: {err}")
    return "\n".join(lines)

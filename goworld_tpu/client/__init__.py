"""Client-side protocol implementation (headless).

Reference parity: ``examples/test_client`` — a complete protocol-level client
mirroring entities/attrs on the client side, used both as the bot-army stress
harness and as the reference implementation of the gate↔client protocol
(ClientBot.go:40-579, ClientEntity.go:99-242). Depends only on
``netutil``/``proto``, like the reference's client.
"""

from goworld_tpu.client.client import ClientBot, ClientEntity, StrictError

__all__ = ["ClientBot", "ClientEntity", "StrictError"]

"""Headless game client: entity mirrors, attr sync, RPC, position sync.

Reference parity: ``examples/test_client/ClientBot.go:40-579`` (connection,
packet pump, entity bookkeeping, sync records) and ``ClientEntity.go:99-242``
(client-side entity with attrs applied from NOTIFY_*_ON_CLIENT messages and
server-callable methods dispatched by name).

``strict`` mode promotes any protocol inconsistency to :class:`StrictError`
(the reference's ``-strict`` flag turns errors fatal, ClientBot.go:571-578).
"""

from __future__ import annotations

import asyncio
import ssl
from typing import Callable, Optional

from goworld_tpu.netutil.packet import Packet
from goworld_tpu.netutil.packet_conn import ConnectionClosed, PacketConnection
from goworld_tpu.proto.conn import (
    DELTA_SYNC_RECORD_SIZE,
    SYNC_RECORD_SIZE,
    GoWorldConnection,
    pack_sync_record,
)
from goworld_tpu.proto.msgtypes import MsgType
from goworld_tpu.utils import gwlog


class StrictError(Exception):
    """A protocol inconsistency observed in strict mode."""


class ClientEntity:
    """Client-side mirror of a server entity (ClientEntity.go:99-242)."""

    def __init__(
        self, bot: "ClientBot", eid: str, typename: str, is_player: bool,
        attrs: dict, x: float, y: float, z: float, yaw: float,
    ) -> None:
        self.bot = bot
        self.id = eid
        self.typename = typename
        self.is_player = is_player
        self.attrs = attrs
        self.x, self.y, self.z, self.yaw = x, y, z, yaw
        self.destroyed = False
        # v6 adaptive sync: deltas are only decodable after a full-
        # precision keyframe established the baseline — the CREATE
        # position deliberately does NOT count (the server forces a
        # keyframe as every pair's first emission, so a delta arriving
        # first is a stale-baseline protocol violation, strict-checked).
        self.delta_ready = False
        self.keyframes = 0
        self.deltas = 0

    # --- server → client ----------------------------------------------------

    def _navigate(self, path: list):
        """Walk the attr tree along ``path`` (root first)."""
        node = self.attrs
        for key in path:
            node = node[key]
        return node

    def apply_attr_change(self, msgtype: int, path: list, args: tuple) -> None:
        try:
            node = self._navigate(path)
            if msgtype == MsgType.NOTIFY_MAP_ATTR_CHANGE_ON_CLIENT:
                node[args[0]] = args[1]
            elif msgtype == MsgType.NOTIFY_MAP_ATTR_DEL_ON_CLIENT:
                node.pop(args[0], None)
            elif msgtype == MsgType.NOTIFY_MAP_ATTR_CLEAR_ON_CLIENT:
                node.clear()
            elif msgtype == MsgType.NOTIFY_LIST_ATTR_CHANGE_ON_CLIENT:
                node[args[0]] = args[1]
            elif msgtype == MsgType.NOTIFY_LIST_ATTR_APPEND_ON_CLIENT:
                node.append(args[0])
            elif msgtype == MsgType.NOTIFY_LIST_ATTR_POP_ON_CLIENT:
                node.pop()
        except (KeyError, IndexError, TypeError) as exc:
            self.bot.error(f"attr change {msgtype} at path {path!r} failed: {exc}")

    def on_call(self, method: str, args: list) -> None:
        """Dispatch a server→client RPC to ``method`` on this mirror, if the
        user subclass/handler defines it (ClientEntity method dispatch)."""
        fn = getattr(self, method, None)
        if callable(fn):
            fn(*args)
        else:
            handler = self.bot.rpc_handlers.get((self.typename, method)) or (
                self.bot.rpc_handlers.get((None, method))
            )
            if handler is not None:
                handler(self, *args)
            else:
                self.bot.error(f"no client method {self.typename}.{method}")

    # --- client → server ----------------------------------------------------

    def call_server(self, method: str, *args) -> None:
        self.bot.call_server_method(self.id, method, args)

    def sync_position(self, x: float, y: float, z: float, yaw: float) -> None:
        self.x, self.y, self.z, self.yaw = x, y, z, yaw
        self.bot.send_sync_position(self.id, x, y, z, yaw)

    def __repr__(self) -> str:
        return f"ClientEntity<{self.typename}|{self.id}|player={self.is_player}>"


class ClientBot:
    """One headless client connection to a gate."""

    def __init__(
        self,
        name: str = "bot",
        strict: bool = False,
        heartbeat_interval: float = 5.0,
        tls: bool = False,
        compress: bool = False,
        compress_format: str = "snappy",
    ) -> None:
        self.name = name
        self.strict = strict
        self.heartbeat_interval = heartbeat_interval
        self.tls = tls
        self.compress = compress
        self.compress_format = compress_format
        self.conn: Optional[GoWorldConnection] = None
        self.entities: dict[str, ClientEntity] = {}
        self.player: Optional[ClientEntity] = None
        self.errors: list[str] = []
        # (typename|None, method) → handler(entity, *args); plus subclass hooks
        self.rpc_handlers: dict[tuple[Optional[str], str], Callable] = {}
        self.on_create_entity: Optional[Callable[[ClientEntity], None]] = None
        self.on_destroy_entity: Optional[Callable[[ClientEntity], None]] = None
        self._player_waiters: list[asyncio.Future] = []
        self._tasks: list[asyncio.Task] = []
        self.entity_class: type[ClientEntity] = ClientEntity

    # --- lifecycle ----------------------------------------------------------

    async def connect(self, host: str, port: int) -> None:
        ssl_ctx = None
        if self.tls:
            ssl_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
            ssl_ctx.check_hostname = False
            ssl_ctx.verify_mode = ssl.CERT_NONE
        reader, writer = await asyncio.open_connection(host, port, ssl=ssl_ctx)
        pconn = PacketConnection(reader, writer)
        if self.compress:
            pconn.enable_compression(self.compress_format)
        self.conn = GoWorldConnection(pconn)
        self._start_pumps()

    async def connect_ws(self, host: str, port: int) -> None:
        """Connect over WebSocket (reference bots pick -mode ws,
        ClientBot.go transport selection)."""
        import websockets

        from goworld_tpu.netutil.ws_conn import WSPacketConnection

        scheme = "wss" if self.tls else "ws"
        ssl_ctx = None
        if self.tls:
            # Same relaxed context as the TCP path: the gate's cert is
            # self-signed in dev/test deployments.
            ssl_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
            ssl_ctx.check_hostname = False
            ssl_ctx.verify_mode = ssl.CERT_NONE
        from goworld_tpu import consts

        ws = await websockets.connect(
            f"{scheme}://{host}:{port}/", max_size=consts.MAX_PACKET_SIZE, ssl=ssl_ctx
        )
        self.conn = GoWorldConnection(WSPacketConnection(ws))
        self._start_pumps()

    async def connect_rudp(
        self, host: str, port: int, loss_simulation: float = 0.0,
        protocol: str = "kcp", fec: tuple[int, int] | None = (10, 3),
    ) -> None:
        """Connect over reliable UDP. ``protocol``: "kcp" = the real KCP
        wire protocol (the reference's -mode kcp; netutil/kcp.py) or
        "native" = the in-repo ARQ (netutil/rudp.py). ``fec`` (kcp only)
        must MATCH the gate's [gate] rudp_fec — the FEC framing is not
        self-identifying; (10, 3) is both sides' default.
        ``loss_simulation`` drops that fraction of outgoing datagrams —
        the ARQ layer must recover (tests). Protocol must match the
        gate's [gate] rudp_protocol."""
        if protocol == "kcp":
            from goworld_tpu.netutil.kcp import connect_kcp

            pconn = await connect_kcp(host, port, loss_simulation, fec=fec)
        else:
            from goworld_tpu.netutil.rudp import connect_rudp

            pconn = await connect_rudp(host, port, loss_simulation)
        if self.compress:
            pconn.enable_compression(self.compress_format)
        self.conn = GoWorldConnection(pconn)
        self._start_pumps()

    def _start_pumps(self) -> None:
        loop = asyncio.get_running_loop()
        self._tasks.append(loop.create_task(self._recv_loop()))
        self._tasks.append(loop.create_task(self._heartbeat_loop()))

    async def close(self) -> None:
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except (asyncio.CancelledError, Exception):
                pass
        self._tasks.clear()
        if self.conn is not None:
            self.conn.close()

    async def wait_player(self, timeout: float = 10.0) -> ClientEntity:
        """Wait until the server assigns this client a player entity."""
        if self.player is not None:
            return self.player
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._player_waiters.append(fut)
        return await asyncio.wait_for(fut, timeout)

    def error(self, msg: str) -> None:
        full = f"{self.name}: {msg}"
        self.errors.append(full)
        if self.strict:
            raise StrictError(full)
        gwlog.warnf("client %s", full)

    # --- send side ----------------------------------------------------------

    def call_server_method(self, eid: str, method: str, args: tuple) -> None:
        assert self.conn is not None
        p = Packet()
        p.append_entity_id(eid)
        p.append_varstr(method)
        p.append_args(args)
        self.conn.send(MsgType.CALL_ENTITY_METHOD_FROM_CLIENT, p)

    def send_sync_position(self, eid: str, x: float, y: float, z: float, yaw: float) -> None:
        assert self.conn is not None
        self.conn.send_packet_raw(
            MsgType.SYNC_POSITION_YAW_FROM_CLIENT, pack_sync_record(eid, x, y, z, yaw)
        )

    async def _heartbeat_loop(self) -> None:
        while True:
            await asyncio.sleep(self.heartbeat_interval)
            if self.conn is not None:
                self.conn.send_heartbeat()

    # --- recv side ----------------------------------------------------------

    async def _recv_loop(self) -> None:
        assert self.conn is not None
        try:
            while True:
                msgtype, packet = await self.conn.recv()
                try:
                    self._handle(msgtype, packet)
                except StrictError:
                    raise
                except Exception:
                    gwlog.trace_error("client %s: error handling msgtype %s", self.name, msgtype)
        except ConnectionClosed:
            pass

    def _handle(self, msgtype: int, packet: Packet) -> None:
        if msgtype == MsgType.CREATE_ENTITY_ON_CLIENT:
            self._handle_create_entity(packet)
        elif msgtype == MsgType.DESTROY_ENTITY_ON_CLIENT:
            typename = packet.read_varstr()
            eid = packet.read_entity_id()
            e = self.entities.pop(eid, None)
            if e is None:
                # No-op by protocol contract: the reference client ignores
                # destroys of unknown entities (ClientBot.go:474-480) — the
                # server legitimately re-derives interest after a restore
                # and timing windows can double-report.
                gwlog.debugf("%s: destroy of unknown entity %s %s",
                             self.name, typename, eid)
                return
            e.destroyed = True
            if e.is_player and self.player is e:
                self.player = None
            if self.on_destroy_entity is not None:
                self.on_destroy_entity(e)
        elif msgtype == MsgType.CALL_ENTITY_METHOD_ON_CLIENT:
            eid = packet.read_entity_id()
            method = packet.read_varstr()
            args = packet.read_args()
            e = self.entities.get(eid)
            if e is None:
                self.error(f"call {method} on unknown entity {eid}")
                return
            e.on_call(method, args)
        elif msgtype == MsgType.CALL_FILTERED_CLIENTS:
            method = packet.read_varstr()
            args = packet.read_args()
            # Filtered calls target the player entity (reference behavior).
            if self.player is not None:
                self.player.on_call(method, args)
        elif msgtype in (
            MsgType.NOTIFY_MAP_ATTR_CHANGE_ON_CLIENT,
            MsgType.NOTIFY_MAP_ATTR_DEL_ON_CLIENT,
            MsgType.NOTIFY_MAP_ATTR_CLEAR_ON_CLIENT,
            MsgType.NOTIFY_LIST_ATTR_CHANGE_ON_CLIENT,
            MsgType.NOTIFY_LIST_ATTR_POP_ON_CLIENT,
            MsgType.NOTIFY_LIST_ATTR_APPEND_ON_CLIENT,
        ):
            self._handle_attr_change(msgtype, packet)
        elif msgtype == MsgType.SYNC_POSITION_YAW_ON_CLIENTS:
            data = packet.payload
            for off in range(0, len(data), SYNC_RECORD_SIZE):
                rec = data[off : off + SYNC_RECORD_SIZE]
                eid = rec[:16].decode("ascii")
                e = self.entities.get(eid)
                if e is not None:
                    import struct

                    e.x, e.y, e.z, e.yaw = struct.unpack_from("<4f", rec, 16)
                    e.delta_ready = True
                    e.keyframes += 1
        elif msgtype == MsgType.SYNC_POSITION_YAW_DELTA_ON_CLIENTS:
            self._handle_sync_delta(packet)
        else:
            self.error(f"unhandled server msgtype {msgtype}")

    def _handle_sync_delta(self, packet: Packet) -> None:
        """Decode v6 quantized-delta sync records: [u8 quantize_bits] +
        concatenated 24 B [eid + dx,dy,dz,dyaw int16] records. The
        position advances in FLOAT32 arithmetic — the server's baseline
        column is float32, and matching its rounding bit-for-bit is what
        keeps decode error bounded by the quantization step forever
        (entity/slabs.py encoding contract)."""
        import struct

        import numpy as np

        data = packet.payload
        if not data:
            return
        step = np.float32(2.0 ** -data[0])
        for off in range(1, len(data) - DELTA_SYNC_RECORD_SIZE + 1,
                         DELTA_SYNC_RECORD_SIZE):
            rec = data[off : off + DELTA_SYNC_RECORD_SIZE]
            eid = rec[:16].decode("ascii")
            e = self.entities.get(eid)
            if e is None:
                continue  # same contract as full records for unknown eids
            if not e.delta_ready:
                self.error(
                    f"delta sync for {eid} before any keyframe — stale "
                    f"baseline (server must keyframe first)")
                continue
            dx, dy, dz, dyaw = struct.unpack_from("<4h", rec, 16)
            e.x = float(np.float32(e.x) + np.float32(dx) * step)
            e.y = float(np.float32(e.y) + np.float32(dy) * step)
            e.z = float(np.float32(e.z) + np.float32(dz) * step)
            e.yaw = float(np.float32(e.yaw) + np.float32(dyaw) * step)
            e.deltas += 1

    def _handle_create_entity(self, packet: Packet) -> None:
        is_player = packet.read_bool()
        eid = packet.read_entity_id()
        typename = packet.read_varstr()
        attrs = packet.read_data()
        x = packet.read_float32()
        y = packet.read_float32()
        z = packet.read_float32()
        yaw = packet.read_float32()
        if eid in self.entities and not is_player:
            old = self.entities[eid]
            if not old.is_player:
                # Idempotent by protocol contract: the reference server
                # re-sends creates when AOI re-derives interest after a
                # freeze/restore, and its client KEEPS the existing mirror
                # untouched (ClientBot.go:459-471) — replacing it would
                # orphan references scenario code still holds.
                gwlog.debugf("%s: create for existing entity %s (kept)",
                             self.name, eid)
                return
        e = self.entity_class(self, eid, typename, is_player, attrs, x, y, z, yaw)
        self.entities[eid] = e
        if is_player:
            self.player = e
            for fut in self._player_waiters:
                if not fut.done():
                    fut.set_result(e)
            self._player_waiters.clear()
        if self.on_create_entity is not None:
            self.on_create_entity(e)

    def _handle_attr_change(self, msgtype: int, packet: Packet) -> None:
        eid = packet.read_entity_id()
        path = packet.read_data()
        if msgtype == MsgType.NOTIFY_MAP_ATTR_CHANGE_ON_CLIENT:
            args: tuple = (packet.read_varstr(), packet.read_data())
        elif msgtype == MsgType.NOTIFY_MAP_ATTR_DEL_ON_CLIENT:
            args = (packet.read_varstr(),)
        elif msgtype == MsgType.NOTIFY_LIST_ATTR_CHANGE_ON_CLIENT:
            args = (packet.read_uint32(), packet.read_data())
        elif msgtype == MsgType.NOTIFY_LIST_ATTR_APPEND_ON_CLIENT:
            args = (packet.read_data(),)
        else:  # clear / pop carry no extra fields
            args = ()
        e = self.entities.get(eid)
        if e is None:
            self.error(f"attr change for unknown entity {eid}")
            return
        e.apply_attr_change(msgtype, path, args)

    # --- introspection ------------------------------------------------------

    def entities_of_type(self, typename: str) -> list[ClientEntity]:
        return [e for e in self.entities.values() if e.typename == typename]

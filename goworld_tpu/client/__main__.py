"""Standalone bot-army entry point.

Reference parity: ``examples/test_client/test_client.go:35-84`` — flags
``-N`` (bot count), ``-strict``, ``-duration`` seconds, gates resolved from
the deployment ini (bots pick gates round-robin, ClientBot.go:82-85).

    python -m goworld_tpu.client -N 200 -strict -duration 300

Exit code 0 = clean run; 1 = strict failure or any bot error.
"""

from __future__ import annotations

import argparse
import asyncio
import sys

from goworld_tpu.client.bot_runner import format_report, run_fleet


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="python -m goworld_tpu.client")
    ap.add_argument("-N", type=int, default=10, help="number of bots")
    ap.add_argument("-strict", action="store_true",
                    help="promote any protocol error/timeout to fatal")
    ap.add_argument("-duration", type=float, default=30.0,
                    help="seconds to run scenarios")
    ap.add_argument("-configfile", default="goworld.ini",
                    help="deployment ini to resolve gate addresses from")
    ap.add_argument("-gate", action="append", default=[],
                    help="explicit gate host:port (repeatable; overrides ini)")
    ap.add_argument("-ws", action="store_true", help="connect over WebSocket")
    ap.add_argument("-rudp", action="store_true",
                    help="connect over reliable UDP (the reference's kcp mode)")
    ap.add_argument("-rudp-protocol", dest="rudp_protocol", default="kcp",
                    choices=("kcp", "native"),
                    help="reliable-UDP wire protocol; must match the "
                         "gate's [gate] rudp_protocol")
    ap.add_argument("-rudp-fec", dest="rudp_fec", default="10,3",
                    help="kcp FEC shards 'data,parity' or 'off'; must "
                         "match the gate's [gate] rudp_fec")
    ap.add_argument("-tls", action="store_true", help="TLS client link")
    ap.add_argument("-compress", action="store_true",
                    help="compressed client link")
    ap.add_argument("-seed", type=int, default=None)
    ap.add_argument("-index-base", dest="index_base", type=int, default=0,
                    help="offset bot indices (stress_<i> identities) so "
                         "CONCURRENT fleets against one cluster don't "
                         "fight over the same avatars")
    ap.add_argument("-timeout", type=float, default=None,
                    help="per-scenario completion budget in seconds "
                         "(retries happen within it); large fleets on "
                         "loaded hosts need more than the reference's 5. "
                         "Default: [client] rpc_timeout from the ini "
                         "(5.0 when unset) — widen the config instead of "
                         "eating a strict-mode flake on slow rigs")
    args = ap.parse_args(argv)
    # Normalize + fail fast (same rules as the gate-side config): a bad
    # spec must die here as a usage error, not as N per-bot ValueErrors
    # mid-fleet.
    args.rudp_fec = args.rudp_fec.strip().lower()
    from goworld_tpu.config.read_config import parse_fec

    try:
        parse_fec(args.rudp_fec)
    except ValueError as exc:
        ap.error(str(exc))

    if args.timeout is None:
        # [client] rpc_timeout: the strict-bot budget is deployment
        # config, not a constant — a rig whose reload window exceeds 5 s
        # widens it HERE honestly instead of eating a strict flake.
        args.timeout = 5.0
        import os

        if os.path.exists(args.configfile):
            from goworld_tpu.config import read_config

            read_config.set_config_file(args.configfile)
            args.timeout = read_config.get().client.rpc_timeout

    gates: list[tuple[str, int]] = []
    for spec in args.gate:
        host, _, port = spec.rpartition(":")
        gates.append((host or "127.0.0.1", int(port)))
    if not gates:
        from goworld_tpu.config import read_config

        read_config.set_config_file(args.configfile)
        cfg = read_config.get()
        if args.ws:
            for g in cfg.gates.values():
                if g.ws_addr:
                    host, _, port = g.ws_addr.rpartition(":")
                    gates.append((host or "127.0.0.1", int(port)))
        else:
            gates = [(g.host, g.port) for g in cfg.gates.values()]
    if not gates:
        print("no gates found (use -gate host:port or -configfile)",
              file=sys.stderr)
        return 2

    report = asyncio.run(
        run_fleet(
            args.N, gates, args.duration,
            strict=args.strict, ws=args.ws, rudp=args.rudp,
            rudp_protocol=args.rudp_protocol, rudp_fec=args.rudp_fec,
            tls=args.tls, index_base=args.index_base,
            compress=args.compress, seed=args.seed,
            thing_timeout=args.timeout,
        )
    )
    print(format_report(report))
    return 1 if report["errors"] else 0


if __name__ == "__main__":
    sys.exit(main())

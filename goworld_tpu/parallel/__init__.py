"""Multi-chip parallelism: entity-sharded global AOI queries over a device
mesh (jax.sharding + shard_map), the TPU-native analog of the reference's
entity-sharding across game processes (SURVEY.md §2.9).
"""

from goworld_tpu.parallel.mesh import ShardedNeighborEngine, make_mesh
from goworld_tpu.parallel.spatial import SpatialShardedNeighborEngine

__all__ = [
    "ShardedNeighborEngine",
    "SpatialShardedNeighborEngine",
    "make_mesh",
]

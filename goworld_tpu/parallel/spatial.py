"""Spatially sharded AOI: grid-column strips with halo exchange.

The entity-sharded engine (parallel/mesh.py) all-gathers EVERY feature
array every tick so each device can rebuild the whole world's grid — an
O(N) replicated broadcast plus a replicated N-key sort per device. This
engine shards the *grid* instead: the torus's columns are split into D
contiguous strips, each device owns the entity rows whose cell lies in its
strip, and per tick the only cross-device traffic is a ``ppermute`` of the
boundary-strip rows (cells within one interaction radius of a seam,
covering BOTH epochs so enter/leave diffs at the seam stay exact) to the
two ring neighbors. Communication drops from O(N) to O(boundary), and the
per-tick table build sorts only a strip's rows instead of all N.

Host-side layout (the part jax never sees):

- Entity→shard assignment is recomputed from the slab's ``xz`` columns
  each dispatch with ONE CELL of hysteresis: a row migrates only after its
  cell is a full column past the seam, so seam-straddlers don't thrash.
  The ownership invariant at every dispatch is
  ``cx ∈ [strip_lo - 1, strip_hi]`` (one column of slack each side).
- Strip boundaries come from observed column density — an
  equal-population split re-planned at a slow cadence (and immediately
  when a strip overflows its row budget) — the AoiZora-style
  density-aware placement seed (PAPERS.md).
- Row permutation: device rows ``[d*chunk, (d+1)*chunk)`` hold the slots
  assigned to shard d (active slots sorted by slot id, then inactive
  fill). When any slot migrates, the PREVIOUS epoch is re-uploaded in the
  new layout from the host mirror, so the device diff never sees a
  migration as a despawn+spawn — event streams are migration-transparent.

Exactness contract (same event sets as the single-device engine):

- Each query's 3×3 cell neighborhood, in both epochs, is fully populated
  on its owner: neighbors exchange the rows whose current OR previous
  cell lies within 3 columns of the seam, and strips are kept ≥ 4
  columns wide so one ring hop suffices.
- Cell-capacity drops break ties by SLOT id (ops/neighbor.sorted_ranks_by),
  so a seam cell's surviving set is identical on every shard holding a
  copy — and identical to the single-device engine's.
- Ticks the strip invariants cannot cover — a teleport whose previous
  cell escapes the halo, a halo-budget overflow, a strip whose population
  exceeds its row budget even after a re-plan — fall back to the exact
  all-gather program (parallel/mesh._sharded_step) for that tick, counted
  on ``aoi_shard_fallback_total{reason}``.

Same host interface as the other engines: ``step_async`` returns a
pending with ONE blocking packed readback in ``collect()``, storm paging
beyond the per-shard inline budget, and the ``meta_dirty=False`` upload
elision (which additionally requires an unchanged row permutation here).

Two device backends share the halo layout (ISSUE 15):

- **jnp** — strip-local candidate-matrix math (the original tier).
- **pallas / pallas_interpret** — the strip-local KERNEL slab: each
  device scatters its own+ghost rows into a
  ``[space_slots, gz+2, strip_cols+4, F, LANES]`` dense cell layout and
  launches the dual-mask event kernel there, so the kernel grid, the
  table build/sort, and the event drain are all strip-local — the
  all-gather + replicated grid rebuild of mesh._sharded_step_pallas
  never happens on this path (see the "Pallas strip tier" section
  below). Fallback ticks run the exact jnp all-gather program on either
  backend.

Both backends take the seam-free single-pass fast tick: a replicated
guard (per-shard scalars pmax/psum-reduced — ops/neighbor._fast_guard's
eligibility) lets steady-state ticks compute the leave diff on the
CURRENT grid — one combined pass / one dual-output kernel launch —
halving the per-tick candidate math; guard outcomes ride the packed
header as ``last_fast_tick`` / ``aoi_spatial_fast_ticks_total``.

Strip→device placement is topology-aware (AoiZora, PAPERS.md): strips
are ring-ordered by construction, so ``plan_placement`` orders the mesh
devices along a coordinate snake and ring-adjacent strips land on
interconnect-adjacent chips; rigs without device coords keep ring order.
"""

from __future__ import annotations

import functools
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from goworld_tpu import telemetry
from goworld_tpu.ops.neighbor import (
    LANES,
    _PACK,
    NeighborParams,
    _apply_fused_logic,
    _bins,
    _compiled_event_kernel,
    _drain_bits,
    _drain_ids,
    _gather_cands,
    _pair_valid,
    _scatter_feats,
    bins_reference,
    check_radius,
    check_space_ids,
    sorted_ranks_by,
)
from goworld_tpu.parallel.compat import resolve_shard_map
from goworld_tpu.telemetry import sentinel
from goworld_tpu.parallel.mesh import (
    SHARD_AXIS,
    ShardedPendingStep,
    _jitted_sharded_drain,
    _jitted_sharded_step,
    _jitted_sharded_step_fused,
)

# Seam-free single-pass ticks (ISSUE 15): steady-state ticks whose
# replicated guard held, so the leave diff rode the CURRENT grid — one
# combined pass (jnp) / one dual-output kernel launch (pallas) instead of
# two grid passes. Module-scope registration (gwlint R5).
_M_FAST_TICKS = telemetry.counter(
    "aoi_spatial_fast_ticks_total",
    "Spatial-engine ticks served by the seam-free single-pass fast path "
    "(replicated displacement guard held; leave diff rode the current "
    "grid).",
)
# Topology-aware strip→device placement (AoiZora, PAPERS.md): total
# interconnect distance (manhattan over device coords) of the strip ring,
# for the adopted placement vs the naive mesh order it replaced.
_M_RING_DISTANCE = telemetry.gauge(
    "aoi_strip_ring_distance",
    "Sum of interconnect (manhattan coord) distances between ring-adjacent "
    "strip devices, per placement order.",
    ("order",),
)

# Halo feature-block bytes per exchanged row: f32 (px, pz, x, z) + i32
# (pspc, spc, slot) + bool (pact, act). Radius does NOT travel: the pair
# predicate only reads the QUERY side's radius, and queries never leave
# their owner.
HALO_ROW_BYTES = 4 * 4 + 3 * 4 + 2 * 1

# Minimum strip width (columns). 3 is the correctness floor (a 3-column
# halo band must not reach past the adjacent strip); 4 adds one column of
# margin so the band arithmetic never wraps into the same strip twice.
MIN_STRIP_COLS = 4


def _build_table_spatial(p: NeighborParams, bucket, active, slots, chunk):
    """Strip-local table build over the combined (own + ghost) rows.

    Differs from ops/neighbor._build_table in two load-bearing ways: table
    values are COMBINED-ROW indices (sentinel n_rows), and cell-capacity
    ties break by SLOT id — every shard holding a copy of a seam cell
    must drop the same members the single-device engine would.
    Returns (table, in_table bool[n_rows], own_dropped)."""
    n_rows = bucket.shape[0]
    m = p.cell_capacity
    key = jnp.where(active, bucket, p.num_buckets)
    order, sorted_key, rank = sorted_ranks_by(key, slots, n_rows)
    ok = (sorted_key < p.num_buckets) & (rank < m)
    table_size = p.num_buckets * m
    dst = jnp.where(ok, sorted_key * m + rank, table_size)
    table = jnp.full((table_size,), n_rows, dtype=jnp.int32)
    table = table.at[dst].set(order.astype(jnp.int32), mode="drop")
    in_table = jnp.zeros((n_rows,), bool).at[order].set(ok)
    dropped_sorted = (sorted_key < p.num_buckets) & ~ok
    own_dropped = jnp.sum(dropped_sorted & (order < chunk)).astype(jnp.int32)
    return table, in_table, own_dropped


def _exchange_halo(
    p: NeighborParams, n_dev: int,
    ppos_l, pact_l, pspc_l, prad_l,
    pos_l, act_l, spc_l, rad_l,
    slot_l, send_lo_idx, send_hi_idx,
):
    """The halo ``ppermute``: pack both seam bands, exchange with the two
    ring neighbors, and return the combined own+ghost feature arrays
    ([chunk + 2h] rows, own rows first). Shared by the jnp and Pallas
    spatial step bodies — the exchanged bytes are identical on both tiers
    (radius does not travel; ghost queries are never extracted, so their
    radius rows may be zero)."""
    n = p.capacity
    chunk = pos_l.shape[0]

    def pack_band(idx):
        safe = jnp.minimum(idx, chunk - 1)
        pad = idx >= chunk
        f32b = jnp.stack(
            [ppos_l[safe, 0], ppos_l[safe, 1], pos_l[safe, 0], pos_l[safe, 1]],
            axis=1,
        )
        i32b = jnp.stack(
            [pspc_l[safe], spc_l[safe], jnp.where(pad, n, slot_l[safe])],
            axis=1,
        )
        boolb = jnp.stack([pact_l[safe] & ~pad, act_l[safe] & ~pad], axis=1)
        return f32b, i32b, boolb

    fwd = [(i, (i + 1) % n_dev) for i in range(n_dev)]
    bwd = [(i, (i - 1) % n_dev) for i in range(n_dev)]

    def exchange(blocks, perm):
        return tuple(
            jax.lax.ppermute(b, SHARD_AXIS, perm=perm) for b in blocks
        )

    # from_left = my predecessor's high-seam band; from_right = my
    # successor's low-seam band.
    from_left = exchange(pack_band(send_hi_idx), fwd)
    from_right = exchange(pack_band(send_lo_idx), bwd)

    def unpack(blocks):
        f32b, i32b, boolb = blocks
        return (
            f32b[:, 0:2], f32b[:, 2:4],  # ppos, pos
            i32b[:, 0], i32b[:, 1], i32b[:, 2],  # pspc, spc, slot
            boolb[:, 0], boolb[:, 1],  # pact, act
        )

    gl_ppos, gl_pos, gl_pspc, gl_spc, gl_slot, gl_pact, gl_act = unpack(
        from_left
    )
    gr_ppos, gr_pos, gr_pspc, gr_spc, gr_slot, gr_pact, gr_act = unpack(
        from_right
    )
    h = gl_pos.shape[0]
    zeros_h = jnp.zeros((h,), jnp.float32)
    return (
        jnp.concatenate([pos_l, gl_pos, gr_pos], axis=0),
        jnp.concatenate([ppos_l, gl_ppos, gr_ppos], axis=0),
        jnp.concatenate([act_l, gl_act, gr_act]),
        jnp.concatenate([pact_l, gl_pact, gr_pact]),
        jnp.concatenate([spc_l, gl_spc, gr_spc]),
        jnp.concatenate([pspc_l, gl_pspc, gr_pspc]),
        jnp.concatenate([slot_l, gl_slot, gr_slot]),
        jnp.concatenate([rad_l, zeros_h, zeros_h]),
        jnp.concatenate([prad_l, zeros_h, zeros_h]),
    )


def _fast_guard_strip(p: NeighborParams, ppos_l, pact_l, pspc_l, prad_l,
                      pos_l, act_l, spc_l, dropped_total):
    """The seam-free single-pass guard, replicated across strips: the same
    eligibility as ops/neighbor._fast_guard (no deactivation, no space
    change, zero capacity drops, displacement small enough that every pair
    valid in EITHER epoch sits inside the CURRENT grid's 3x3 halo), with
    the per-shard scalars reduced over the mesh so the ``cond`` resolves
    identically on every shard. Own rows partition the slot space, so the
    local reductions cover every entity exactly once."""
    both = pact_l & act_l
    deact = jnp.any(pact_l & ~act_l).astype(jnp.int32)
    spchg = jnp.any(both & (pspc_l != spc_l)).astype(jnp.int32)
    disp2 = jnp.max(
        jnp.where(both, jnp.sum((pos_l - ppos_l) ** 2, axis=1), 0.0)
    )
    prad_max = jnp.max(jnp.where(pact_l, prad_l, 0.0))
    deact_g = jax.lax.pmax(deact, SHARD_AXIS) > 0
    spchg_g = jax.lax.pmax(spchg, SHARD_AXIS) > 0
    disp_g = jnp.sqrt(jax.lax.pmax(disp2, SHARD_AXIS))
    prad_g = jax.lax.pmax(prad_max, SHARD_AXIS)
    return (
        (~deact_g)
        & (~spchg_g)
        & (dropped_total == 0)
        & (2.0 * disp_g + prad_g <= p.cell_size)
    )


def _spatial_step_impl(
    p: NeighborParams,
    events_inline: int,
    halo_cap: int,
    n_dev: int,
    ppos_l, pact_l, pspc_l, prad_l,
    pos_l, act_l, spc_l, rad_l,
    slot_l,
    send_lo_idx,
    send_hi_idx,
):
    n = p.capacity
    chunk = pos_l.shape[0]
    h = halo_cap
    n_all = chunk + 2 * h

    (pos_all, ppos_all, act_all, pact_all, spc_all, pspc_all, slot_all,
     _, _) = _exchange_halo(
        p, n_dev, ppos_l, pact_l, pspc_l, prad_l,
        pos_l, act_l, spc_l, rad_l, slot_l, send_lo_idx, send_hi_idx,
    )

    cxc, czc, smc = _bins(p, pos_all, spc_all)
    cxp, czp, smp = _bins(p, ppos_all, pspc_all)
    buc_c = (smc * p.grid_z + czc) * p.grid_x + cxc
    buc_p = (smp * p.grid_z + czp) * p.grid_x + cxp
    # Strip-local sorts over chunk + 2h keys — the replicated N-key sorts
    # of the all-gather formulation are what this engine deletes.
    table_c, av_c, own_drop = _build_table_spatial(
        p, buc_c, act_all, slot_all, chunk
    )
    table_p, av_p, _ = _build_table_spatial(
        p, buc_p, pact_all, slot_all, chunk
    )
    dropped_total = jax.lax.psum(own_drop, SHARD_AXIS).astype(jnp.int32)

    q_iota = jnp.arange(chunk, dtype=jnp.int32)

    def emask(cand, q_pos, q_av, q_spc, q_rad, pos_a, av_a, spc_a):
        safe = jnp.minimum(cand, n_all - 1)
        not_self = (cand < n_all) & (cand != q_iota[:, None])
        return _pair_valid(
            q_av[:, None],
            q_spc[:, None],
            (q_rad * q_rad)[:, None],
            q_pos[:, 0][:, None],
            q_pos[:, 1][:, None],
            av_a[safe],
            spc_a[safe],
            pos_a[:, 0][safe],
            pos_a[:, 1][safe],
            not_self,
        )

    # Enter pass: candidates from the current grid, own rows as queries.
    cand_c = _gather_cands(p, table_c, cxc[:chunk], czc[:chunk], smc[:chunk])
    vc = emask(cand_c, pos_l, av_c[:chunk], spc_l, rad_l,
               pos_all, av_c, spc_all)
    vp_on_c = emask(cand_c, ppos_l, av_p[:chunk], pspc_l, prad_l,
                    ppos_all, av_p, pspc_all)
    enter_mask = vc & ~vp_on_c

    # Leave pass: seam-free single-pass fast path (ISSUE 15) when the
    # replicated guard holds — the leave mask is vp_on_c & ~vc over the
    # already-gathered current candidates, skipping the previous grid's
    # candidate gather and both epoch-mask passes (the engine's dominant
    # per-tick FLOPs; both table SORTS stay, av_p feeds vp_on_c). Other
    # ticks pay the full previous-grid pass.
    fast = _fast_guard_strip(
        p, ppos_l, pact_l, pspc_l, prad_l, pos_l, act_l, spc_l,
        dropped_total,
    )

    def fast_fn():
        return vp_on_c & ~vc, cand_c

    def slow_fn():
        cand_p = _gather_cands(
            p, table_p, cxp[:chunk], czp[:chunk], smp[:chunk]
        )
        vp = emask(cand_p, ppos_l, av_p[:chunk], pspc_l, prad_l,
                   ppos_all, av_p, pspc_all)
        vc_on_p = emask(cand_p, pos_l, av_c[:chunk], spc_l, rad_l,
                        pos_all, av_c, spc_all)
        return vp & ~vc_on_p, cand_p

    leave_mask, cand_l = jax.lax.cond(fast, fast_fn, slow_fn)

    def slot_of(cand):
        return slot_all[jnp.minimum(cand, n_all - 1)]

    enter_ids = jnp.where(enter_mask, slot_of(cand_c), n)
    leave_ids = jnp.where(leave_mask, slot_of(cand_l), n)
    n_enters = jnp.sum(enter_mask).astype(jnp.int32)
    n_leaves = jnp.sum(leave_mask).astype(jnp.int32)

    ep, ei = _drain_ids(enter_ids, n, events_inline, jnp.int32(0))
    lp, li = _drain_ids(leave_ids, n, events_inline, jnp.int32(0))

    def slotize(pairs):
        ent = pairs[:, 0]
        ent = jnp.where(
            ent < chunk, slot_l[jnp.minimum(ent, chunk - 1)], n
        )
        return jnp.stack([ent, pairs[:, 1]], axis=1)

    header = jnp.stack(
        [
            jnp.stack([n_enters, n_leaves]),
            jnp.stack([dropped_total, fast.astype(jnp.int32)]),
            jnp.stack([ei[events_inline - 1], li[events_inline - 1]]),
        ]
    ).astype(jnp.int32)
    # Replicated per-shard counts: same storm-paging convergence contract
    # as parallel/mesh._sharded_step (ShardedPendingStep reads them).
    counts_all = jax.lax.all_gather(header[0], SHARD_AXIS)  # [D, 2]
    out = jnp.concatenate(
        [header, counts_all, slotize(ep), slotize(lp)], axis=0
    )
    return enter_ids, leave_ids, out


def _spatial_drain(
    p: NeighborParams, events_inline: int, chunk: int,
    ids_l: jax.Array,  # [chunk, 9M] this shard's SLOT-id event matrix
    slot_l: jax.Array,  # [chunk] row → slot
    start_l: jax.Array,  # [1] resume cursor (local flat index)
):
    n = p.capacity
    pairs, idx = _drain_ids(ids_l, n, events_inline, start_l[0])
    ent = pairs[:, 0]
    ent = jnp.where(ent < chunk, slot_l[jnp.minimum(ent, chunk - 1)], n)
    pairs = jnp.stack([ent, pairs[:, 1]], axis=1)
    return pairs, idx[None]


def _spatial_step_fused_impl(
    p: NeighborParams,
    events_inline: int,
    halo_cap: int,
    n_dev: int,
    programs,
    ppos_l, pact_l, pspc_l, prad_l,
    pos_l, act_l, spc_l, rad_l,
    slot_l,
    send_lo_idx,
    send_hi_idx,
    y_l, yaw_l, sel_l, dt_l, *cols_l,
):
    """The spatial halo-exchange step plus fused entity logic on this
    shard's LOCAL rows. The logic is elementwise per row — it never
    crosses a seam, needs no halo, and leaves every layout invariant of
    the spatial step untouched (the diff runs on the dispatched epoch
    exactly as unfused). Logic inputs/outputs are in ROW-permuted layout:
    the host uploads sel/y/yaw/columns through the same ``perm`` as the
    positions, and writes the outputs back through the dispatch-time perm
    snapshot (a strip migration or re-plan between dispatches therefore
    CANNOT misroute or reset a column — the satellite contract pinned in
    tests/test_spatial.py)."""
    enter_ids, leave_ids, out = _spatial_step_impl(
        p, events_inline, halo_cap, n_dev,
        ppos_l, pact_l, pspc_l, prad_l,
        pos_l, act_l, spc_l, rad_l,
        slot_l, send_lo_idx, send_hi_idx,
    )
    new_pos, new_y, new_yaw, new_cols = _apply_fused_logic(
        programs, pos_l, y_l, yaw_l, sel_l, dt_l[0], cols_l
    )
    return enter_ids, leave_ids, out, (new_pos, new_y, new_yaw) + new_cols


@functools.lru_cache(maxsize=None)
def _jitted_spatial_step_fused(
    params: NeighborParams, mesh: Mesh, events_inline: int, halo_cap: int,
    programs: tuple, n_cols: int,
):
    shard_map = resolve_shard_map()
    body = functools.partial(
        _spatial_step_fused_impl, params, events_inline, halo_cap,
        mesh.devices.size, programs,
    )
    spec = P(SHARD_AXIS)
    mapped = shard_map(
        body,
        mesh=mesh,
        in_specs=(spec,) * (15 + n_cols),
        out_specs=(spec, spec, spec, (spec,) * (3 + n_cols)),
    )
    return sentinel.SentinelJit("spatial_step_fused", jax.jit(mapped))


@functools.lru_cache(maxsize=None)
def _jitted_spatial_step(
    params: NeighborParams, mesh: Mesh, events_inline: int, halo_cap: int
):
    shard_map = resolve_shard_map()
    body = functools.partial(
        _spatial_step_impl, params, events_inline, halo_cap,
        mesh.devices.size,
    )
    spec = P(SHARD_AXIS)
    mapped = shard_map(
        body,
        mesh=mesh,
        in_specs=(spec,) * 11,
        out_specs=(spec, spec, spec),
    )
    return sentinel.SentinelJit("spatial_step", jax.jit(mapped))


@functools.lru_cache(maxsize=None)
def _jitted_spatial_drain(
    params: NeighborParams, mesh: Mesh, events_inline: int, chunk: int
):
    shard_map = resolve_shard_map()
    body = functools.partial(_spatial_drain, params, events_inline, chunk)
    spec = P(SHARD_AXIS)
    mapped = shard_map(
        body, mesh=mesh, in_specs=(spec, spec, spec),
        out_specs=(spec, spec),
    )
    return sentinel.SentinelJit("spatial_drain", jax.jit(mapped))


# --- Pallas strip tier (ISSUE 15) --------------------------------------------
#
# The kernel-tier analog of the jnp halo exchange above: each device
# builds a STRIP-LOCAL dense cell slab over its own+ghost rows and feeds
# the existing dual-mask event kernel (ops/neighbor._event_kernel) a
# [space_slots, gz+2, cols_cap+4, F, LANES] layout instead of a slice of
# a replicated full-torus grid — the kernel grid, the table build/sort,
# and the event drain are all strip-local, and the only cross-device
# traffic is the same seam-band ppermute the jnp tier moves. Column
# geometry per shard (w = this strip's width, all offsets mod grid_x):
#
#   world column:  lo-2  lo-1  lo ... hi-1   hi   hi+1
#   local column:    0     1    2 ...  w+1   w+2   w+3      (lx)
#   role:          ghost  QUERY ...... QUERY QUERY ghost
#
# Own rows may sit one column outside the strip (the hysteresis slack),
# so query columns span [lo-1, hi] and candidate columns [lo-2, hi+1] —
# exactly the 3-column seam bands the halo exchange already ships. The
# slab's x extent is the STATIC cols_cap + 4 (cols_cap caps strip width;
# plan_strips enforces it), z keeps the torus wrap; columns past this
# strip's dynamic width are NaN cells the kernel skims through. Ghost
# rows appear as un-extracted queries; far ghost columns (a ghost's other
# epoch far from the seam) fall outside every own query's 3x3 block, and
# any pair they could carry is > cell_size apart — excluded exactly.


def _build_table_strip(
    p: NeighborParams, bucket, active, slots, num_buckets, chunk
):
    """Strip-local LANES-stride table for the kernel slab. Like
    _build_table_spatial, capacity ties break by SLOT id (seam cells exist
    as copies on two shards — the drop set must be identical everywhere
    and identical to the single-device engine's). Table values are SLOT
    ids (sentinel N) so the bit drain emits pairs directly; ``tpos`` is
    each combined row's flat table position (-1 = dropped/absent), whose
    % LANES is the row's kernel lane. Returns
    (table, tpos, own_dropped, order, dst)."""
    n_rows = bucket.shape[0]
    cap = min(p.cell_capacity, LANES)
    key = jnp.where(active, bucket, num_buckets)
    order, sorted_key, rank = sorted_ranks_by(key, slots, n_rows)
    ok = (sorted_key < num_buckets) & (rank < cap)
    table_size = num_buckets * LANES
    dst = jnp.where(ok, sorted_key * LANES + rank, table_size)
    table = jnp.full((table_size,), p.capacity, dtype=jnp.int32)
    table = table.at[dst].set(slots[order].astype(jnp.int32), mode="drop")
    tpos = jnp.zeros((n_rows,), jnp.int32).at[order].set(
        jnp.where(ok, dst, -1).astype(jnp.int32)
    )
    dropped_sorted = (sorted_key < num_buckets) & ~ok
    own_dropped = jnp.sum(dropped_sorted & (order < chunk)).astype(jnp.int32)
    return table, tpos, own_dropped, order, dst


def _scatter_slotown(p: NeighborParams, dst, order, slot_all, chunk: int,
                     gx_ext: int):
    """Dense slot/own plane for the in-kernel drain (ISSUE 19 leg b):
    the cells-slab geometry with two i32 planes per lane in place of the
    F float features — plane 0 the tabled lane's SLOT id (sentinel
    ``capacity``), plane 1 its OWN flag (row < chunk: ghost rows must not
    emit events; their owner shard emits them). Same one-scatter build and
    z-wrap halo ring as _scatter_feats; x ghost columns are physical."""
    n_rows = slot_all.shape[0]
    table_size = p.space_slots * p.grid_z * gx_ext * LANES
    own = (jnp.arange(n_rows, dtype=jnp.int32) < chunk).astype(jnp.int32)
    vals = jnp.stack([slot_all.astype(jnp.int32), own], axis=1)  # [N, 2]
    flat = jnp.full((table_size, 2), p.capacity, jnp.int32).at[:, 1].set(0)
    flat = flat.at[dst].set(vals[order], mode="drop")
    plane = flat.reshape(p.space_slots, p.grid_z, gx_ext, LANES, 2)
    plane = plane.transpose(0, 1, 2, 4, 3)  # [S, gz, gxe, 2, LANES]
    return jnp.pad(
        plane, ((0, 0), (1, 1), (0, 0), (0, 0), (0, 0)), mode="wrap"
    )


def _spatial_step_pallas_impl(
    p: NeighborParams,
    events_inline: int,
    halo_cap: int,
    n_dev: int,
    interpret: bool,
    cols_cap: int,
    drain_inline: int,
    ppos_l, pact_l, pspc_l, prad_l,
    pos_l, act_l, spc_l, rad_l,
    slot_l,
    send_lo_idx,
    send_hi_idx,
    strip_lo,  # [1] i32: this shard's first owned column
):
    """Per-shard strip+halo Pallas body (see the section comment). Returns
    (enter drain ctx x4, table_c, leave drain ctx x4, table_l, out) —
    the same 11-output contract as parallel/mesh._sharded_step_pallas,
    with drain contexts in strip-local coordinates."""
    n = p.capacity
    chunk = pos_l.shape[0]
    h = halo_cap
    n_all = chunk + 2 * h
    gz = p.grid_z
    gxe = cols_cap + 4  # slab x extent: query cols + 2 ghost cols per side
    qcols = cols_cap + 2  # kernel grid columns (strip + hysteresis slack)
    nb_local = p.space_slots * gz * gxe
    w_words = 9 * LANES // _PACK
    kernel = _compiled_event_kernel(
        p, interpret, rows=gz, cols=qcols, drain_inline=drain_inline
    )
    kernel_dual = _compiled_event_kernel(
        p, interpret, rows=gz, cols=qcols, dual=True,
        drain_inline=drain_inline,
    )

    (pos_all, ppos_all, act_all, pact_all, spc_all, pspc_all, slot_all,
     rad_all, prad_all) = _exchange_halo(
        p, n_dev, ppos_l, pact_l, pspc_l, prad_l,
        pos_l, act_l, spc_l, rad_l, slot_l, send_lo_idx, send_hi_idx,
    )

    cxc, czc, smc = _bins(p, pos_all, spc_all)
    cxp, czp, smp = _bins(p, ppos_all, pspc_all)
    base = strip_lo[0] - 2
    lxc = jnp.mod(cxc - base, p.grid_x)
    lxp = jnp.mod(cxp - base, p.grid_x)
    # Rows outside the slab's column span (a ghost's OTHER epoch far from
    # the seam) are absent from that epoch's strip table — NaN-poisoned
    # like a capacity drop, which is exact: any pair they could carry with
    # an own query is > cell_size apart in that epoch.
    in_c = lxc < gxe
    in_p = lxp < gxe
    buc_c = jnp.where(in_c, (smc * gz + czc) * gxe + lxc, nb_local)
    buc_p = jnp.where(in_p, (smp * gz + czp) * gxe + lxp, nb_local)
    # Strip-local LANES-stride sorts over chunk + 2h keys — the replicated
    # N-row sort + full-grid scatter of the all-gather kernel tier
    # (parallel/mesh._sharded_step_pallas) are what this path deletes.
    table_c, tpos_c, own_drop, order_c, dst_c = _build_table_strip(
        p, buc_c, act_all & in_c, slot_all, nb_local, chunk
    )
    table_p, tpos_p, _, order_p, dst_p = _build_table_strip(
        p, buc_p, pact_all & in_p, slot_all, nb_local, chunk
    )
    dropped_total = jax.lax.psum(own_drop, SHARD_AXIS).astype(jnp.int32)

    # Each epoch's x row poisoned by its OWN table validity
    # (ops/neighbor._step_pallas — fresh spawns must not be suppressed by
    # stale previous positions).
    xs_c = jnp.where(tpos_c >= 0, pos_all[:, 0], jnp.nan)
    xs_p = jnp.where(tpos_p >= 0, ppos_all[:, 0], jnp.nan)
    cur_feats = (xs_c, pos_all[:, 1], spc_all, rad_all)
    prev_feats = (xs_p, ppos_all[:, 1], pspc_all, prad_all)
    cells_c = _scatter_feats(p, dst_c, order_c, cur_feats, prev_feats,
                             gx_ext=gxe)

    def extract(packed_cells, lx, cz, sm, tpos):
        """Packed event words of the OWN rows binned in this slab."""
        lane = tpos[:chunk] % LANES
        ocol = lx[:chunk] - 1  # kernel output col: slab col minus ghost col
        flat = packed_cells.reshape(-1, w_words)
        oflat = ((sm[:chunk] * gz + cz[:chunk]) * qcols + ocol) * LANES + lane
        mine = (tpos[:chunk] >= 0) & (ocol >= 0) & (ocol < qcols)
        safe = jnp.clip(oflat, 0, flat.shape[0] - 1)
        return jnp.where(mine[:, None], flat[safe], 0)  # i32[chunk, W]

    # Seam-free single-pass fast tick (ISSUE 15): when the replicated
    # guard holds, ONE dual-output kernel launch on the current slab
    # yields both masks; other ticks pay the second scatter+kernel pass on
    # the previous slab. Both strip tables always build (xs poisoning and
    # drain contexts need them) — the kernel pass is what halves.
    fast = _fast_guard_strip(
        p, ppos_l, pact_l, pspc_l, prad_l, pos_l, act_l, spc_l,
        dropped_total,
    )

    if drain_inline:
        # In-kernel drain (ISSUE 19 leg b): the launch itself emits the
        # compacted (query slot, other slot) pairs — the XLA rank-select
        # below never runs on these ticks. Both branches slice their pairs
        # block to the [2, drain_inline] enter/leave regions so the cond
        # unifies; emission is already slot-valued and own-masked.
        so_c = _scatter_slotown(p, dst_c, order_c, slot_all, chunk, gxe)

        def fast_fn():
            pk2, prs = kernel_dual(cells_c, so_c)
            return (pk2[..., :w_words], pk2[..., w_words:],
                    lxc, czc, smc, tpos_c, table_c,
                    prs[:, :drain_inline],
                    prs[:, drain_inline:2 * drain_inline])

        def slow_fn():
            pk_e, prs_e = kernel(cells_c, so_c)
            cells_p = _scatter_feats(p, dst_p, order_p, prev_feats,
                                     cur_feats, gx_ext=gxe)
            so_p = _scatter_slotown(p, dst_p, order_p, slot_all, chunk, gxe)
            # Epoch symmetry: the prev-grid launch's "enter" mask
            # (valid_prev ∧ ¬valid_cur) IS the leave set.
            pk_l, prs_l = kernel(cells_p, so_p)
            return (pk_e, pk_l, lxp, czp, smp, tpos_p, table_p,
                    prs_e[:, :drain_inline], prs_l[:, :drain_inline])

        (pk_e, pk_l, l_lx, l_cz, l_sm, l_tpos, l_table, prs_e, prs_l
         ) = jax.lax.cond(fast, fast_fn, slow_fn)
    else:
        def fast_fn():
            pk2 = kernel_dual(cells_c)  # [S, gz, qcols, LANES, 2W]
            return (pk2[..., :w_words], pk2[..., w_words:],
                    lxc, czc, smc, tpos_c, table_c)

        def slow_fn():
            pk_e = kernel(cells_c)
            cells_p = _scatter_feats(p, dst_p, order_p, prev_feats,
                                     cur_feats, gx_ext=gxe)
            pk_l = kernel(cells_p)
            return (pk_e, pk_l, lxp, czp, smp, tpos_p, table_p)

        pk_e, pk_l, l_lx, l_cz, l_sm, l_tpos, l_table = jax.lax.cond(
            fast, fast_fn, slow_fn
        )
        prs_e = prs_l = None
    packed_e = extract(pk_e, lxc, czc, smc, tpos_c)
    packed_l = extract(pk_l, l_lx, l_cz, l_sm, l_tpos)
    n_enters = jnp.sum(jax.lax.population_count(packed_e)).astype(jnp.int32)
    n_leaves = jnp.sum(jax.lax.population_count(packed_l)).astype(jnp.int32)

    if drain_inline:
        ep = jnp.transpose(prs_e)  # [events_inline, 2], already slot ids
        lp = jnp.transpose(prs_l)
    else:
        ep, _ = _drain_bits(p, packed_e, lxc[:chunk], czc[:chunk],
                            smc[:chunk], table_c, jnp.int32(0),
                            max_events=events_inline, gx_ext=gxe,
                            wrap_x=False)
        lp, _ = _drain_bits(p, packed_l, l_lx[:chunk], l_cz[:chunk],
                            l_sm[:chunk], l_table, jnp.int32(0),
                            max_events=events_inline, gx_ext=gxe,
                            wrap_x=False)

    def slotize(pairs):
        if drain_inline:
            return pairs  # kernel pairs are slot-valued already
        ent = pairs[:, 0]
        ent = jnp.where(ent < chunk, slot_l[jnp.minimum(ent, chunk - 1)], n)
        return jnp.stack([ent, pairs[:, 1]], axis=1)

    zero = jnp.int32(0)
    header = jnp.stack(
        [
            jnp.stack([n_enters, n_leaves]),
            jnp.stack([dropped_total, fast.astype(jnp.int32)]),
            jnp.stack([zero, zero]),  # rank paging resumes at events_inline
        ]
    ).astype(jnp.int32)
    # Replicated per-shard counts — see _spatial_step_impl.
    counts_all = jax.lax.all_gather(header[0], SHARD_AXIS)  # [D, 2]
    out = jnp.concatenate(
        [header, counts_all, slotize(ep), slotize(lp)], axis=0
    )
    enter_ctx = (packed_e, lxc[:chunk], czc[:chunk], smc[:chunk], table_c)
    leave_ctx = (packed_l, l_lx[:chunk], l_cz[:chunk], l_sm[:chunk], l_table)
    return enter_ctx + leave_ctx + (out,)


def _spatial_drain_bits(
    p: NeighborParams, events_inline: int, cols_cap: int,
    packed_l,  # [chunk, W] this shard's own-row packed event words
    lx_l, cz_l, sm_l,  # [chunk] strip-local bin coords of the pass's grid
    table_l,  # [nb_local * LANES] slot-id table of the pass's grid
    slot_l,  # [chunk] row → slot (dispatch-time perm snapshot)
    start_l,  # [1] resume EVENT RANK
):
    """Pallas-strip storm paging: rank-select past the inline budget, own
    rows mapped to slots through the dispatch-time perm snapshot."""
    n = p.capacity
    chunk = packed_l.shape[0]
    pairs, total = _drain_bits(
        p, packed_l, lx_l, cz_l, sm_l, table_l, start_l[0],
        max_events=events_inline, gx_ext=cols_cap + 4, wrap_x=False,
    )
    ent = pairs[:, 0]
    ent = jnp.where(ent < chunk, slot_l[jnp.minimum(ent, chunk - 1)], n)
    pairs = jnp.stack([ent, pairs[:, 1]], axis=1)
    return pairs, total[None]


def _spatial_step_pallas_fused_impl(
    p: NeighborParams,
    events_inline: int,
    halo_cap: int,
    n_dev: int,
    interpret: bool,
    cols_cap: int,
    drain_inline: int,
    programs,
    ppos_l, pact_l, pspc_l, prad_l,
    pos_l, act_l, spc_l, rad_l,
    slot_l,
    send_lo_idx,
    send_hi_idx,
    strip_lo,
    y_l, yaw_l, sel_l, dt_l, *cols_l,
):
    """The Pallas strip step plus fused entity logic on this shard's LOCAL
    rows — identical logic contract to _spatial_step_fused_impl (row-
    permuted inputs, perm-snapshot writeback)."""
    res = _spatial_step_pallas_impl(
        p, events_inline, halo_cap, n_dev, interpret, cols_cap,
        drain_inline,
        ppos_l, pact_l, pspc_l, prad_l,
        pos_l, act_l, spc_l, rad_l,
        slot_l, send_lo_idx, send_hi_idx, strip_lo,
    )
    new_pos, new_y, new_yaw, new_cols = _apply_fused_logic(
        programs, pos_l, y_l, yaw_l, sel_l, dt_l[0], cols_l
    )
    return res + ((new_pos, new_y, new_yaw) + new_cols,)


@functools.lru_cache(maxsize=None)
def _jitted_spatial_step_pallas(
    params: NeighborParams, mesh: Mesh, events_inline: int, halo_cap: int,
    interpret: bool, cols_cap: int, drain_inline: int = 0,
):
    assert drain_inline in (0, events_inline)
    shard_map = resolve_shard_map()
    body = functools.partial(
        _spatial_step_pallas_impl, params, events_inline, halo_cap,
        mesh.devices.size, interpret, cols_cap, drain_inline,
    )
    spec = P(SHARD_AXIS)
    mapped = shard_map(
        body,
        mesh=mesh,
        in_specs=(spec,) * 12,
        out_specs=(spec,) * 11,
        # pallas_call's out_shape carries no varying-mesh-axes annotation;
        # skip the vma check (outputs are explicitly per-shard here) —
        # same reasoning as parallel/mesh._jitted_sharded_step_pallas.
        check_vma=False,
    )
    return sentinel.SentinelJit("spatial_step_pallas", jax.jit(mapped))


@functools.lru_cache(maxsize=None)
def _jitted_spatial_step_pallas_fused(
    params: NeighborParams, mesh: Mesh, events_inline: int, halo_cap: int,
    interpret: bool, cols_cap: int, programs: tuple, n_cols: int,
    drain_inline: int = 0,
):
    assert drain_inline in (0, events_inline)
    shard_map = resolve_shard_map()
    body = functools.partial(
        _spatial_step_pallas_fused_impl, params, events_inline, halo_cap,
        mesh.devices.size, interpret, cols_cap, drain_inline, programs,
    )
    spec = P(SHARD_AXIS)
    mapped = shard_map(
        body,
        mesh=mesh,
        in_specs=(spec,) * (16 + n_cols),
        out_specs=(spec,) * 11 + ((spec,) * (3 + n_cols),),
        check_vma=False,
    )
    return sentinel.SentinelJit("spatial_step_pallas_fused", jax.jit(mapped))


@functools.lru_cache(maxsize=None)
def _jitted_spatial_drain_bits(
    params: NeighborParams, mesh: Mesh, events_inline: int, cols_cap: int
):
    shard_map = resolve_shard_map()
    body = functools.partial(
        _spatial_drain_bits, params, events_inline, cols_cap
    )
    spec = P(SHARD_AXIS)
    mapped = shard_map(
        body, mesh=mesh, in_specs=(spec,) * 7, out_specs=(spec, spec),
    )
    return sentinel.SentinelJit("spatial_drain_bits", jax.jit(mapped))


def plan_strips(
    col_pop: np.ndarray, n_dev: int, min_cols: int = MIN_STRIP_COLS,
    max_cols: int | None = None,
) -> np.ndarray:
    """Equal-population strip boundaries from an observed column histogram.

    Returns int32[D+1] with boundaries[0] == 0 and boundaries[D] == grid_x.
    Each strip gets ≥ min_cols columns (the halo-correctness floor); the
    split otherwise walks the population cumsum so every strip carries
    ~1/D of the entities — hot columns get narrow strips, empty space gets
    wide ones (the AoiZora-style density-aware placement seed).

    ``max_cols`` caps every strip's width (the Pallas tier's static slab
    extent, cols_cap): sparse regions then spread over several capped
    strips instead of one wide one. Requires n_dev * max_cols >= grid_x.
    """
    gx = len(col_pop)
    if gx < n_dev * min_cols:
        raise ValueError(
            f"grid_x {gx} < {n_dev} shards * {min_cols} min columns"
        )
    if max_cols is not None and gx > n_dev * max_cols:
        raise ValueError(
            f"grid_x {gx} > {n_dev} shards * {max_cols} max columns"
        )
    cum = np.concatenate([[0], np.cumsum(col_pop, dtype=np.int64)])
    total = cum[-1]
    bounds = np.zeros(n_dev + 1, np.int32)
    bounds[n_dev] = gx
    for d in range(1, n_dev):
        target = total * d // n_dev
        b = int(np.searchsorted(cum, target, side="left"))
        # Clamp so every strip (including the ones still to come) keeps
        # its minimum width — and, under a width cap, so no strip placed
        # OR remaining can exceed it.
        b = max(b, int(bounds[d - 1]) + min_cols)
        b = min(b, gx - (n_dev - d) * min_cols)
        if max_cols is not None:
            b = min(b, int(bounds[d - 1]) + max_cols)
            b = max(b, gx - (n_dev - d) * max_cols)
        bounds[d] = b
    return bounds


def ring_link_distance(coords: list, order: np.ndarray) -> int:
    """Total interconnect distance of the strip ring under a device order:
    sum of manhattan distances between consecutive (and wrap-around)
    devices' mesh coordinates — the quantity every halo ``ppermute`` pays
    per tick, which topology-aware placement minimizes."""
    k = len(order)
    total = 0
    for i in range(k):
        a = coords[int(order[i])]
        b = coords[int(order[(i + 1) % k])]
        total += sum(abs(int(x) - int(y)) for x, y in zip(a, b))
    return total


def plan_placement(devices: list) -> np.ndarray:
    """Topology-aware strip→device placement (AoiZora, PAPERS.md): an
    index permutation ``order`` such that ``devices[order[i]]`` hosts
    strip i, chosen so ring-adjacent strips land on interconnect-adjacent
    chips. Devices exposing mesh ``coords`` (TPU) are walked in a
    boustrophedon (snake) over (z, y, x) — adjacent steps on a full grid
    are single-hop — with same-chip cores kept consecutive; the snake is
    adopted only when it strictly beats the given order's ring distance.
    Devices without coords (CPU/GPU rigs) fall back to ring order
    (identity)."""
    k = len(devices)
    ident = np.arange(k, dtype=np.int64)
    coords = [getattr(d, "coords", None) for d in devices]
    if k < 2 or any(c is None for c in coords):
        return ident
    coords = [tuple(int(v) for v in c) + (0, 0, 0) for c in coords]
    coords = [c[:3] for c in coords]
    ys = sorted({c[1] for c in coords})
    yi = {v: i for i, v in enumerate(ys)}

    def key(i: int):
        x, y, z = coords[i]
        core = int(getattr(devices[i], "core_on_chip", 0) or 0)
        yr = yi[y] if z % 2 == 0 else len(ys) - 1 - yi[y]
        xr = x if (z + yi[y]) % 2 == 0 else -x
        return (z, yr, xr, core)

    snake = np.asarray(sorted(range(k), key=key), dtype=np.int64)
    if ring_link_distance(coords, snake) < ring_link_distance(coords, ident):
        return snake
    return ident


class SpatialShardedNeighborEngine:
    """Grid-strip sharded AOI engine (see module docstring).

    Interface parity with ShardedNeighborEngine: ``reset`` /
    ``step_async`` / ``step``, one packed readback per tick, paging past
    the per-shard inline budget. Extra observability attributes:
    ``last_mode`` ("spatial" | "fallback:<reason>"), ``last_fast_tick``
    (the seam-free single-pass guard held on the last collected tick),
    ``shard_population`` (np int64[D] active rows per shard at the last
    dispatch), ``halo_bytes_per_tick`` (structural ppermute payload), and
    the telemetry counters wired in ``__init__``.

    ``backend``: "auto" = the strip-local Pallas kernel slab on TPU, the
    jnp candidate math elsewhere; "pallas" / "pallas_interpret" / "jnp"
    force a path. Both backends move the SAME halo bands — the Pallas
    tier additionally keeps the kernel grid, table sort, and event drain
    strip-local (``strip_cols`` caps a strip's width, the kernel slab's
    static extent). ``placement``: "topology" reorders the mesh so
    ring-adjacent strips land on interconnect-adjacent devices
    (plan_placement; identity on rigs without device coords), "ring"
    keeps the given mesh order.
    """

    def __init__(
        self,
        params: NeighborParams,
        mesh: Mesh,
        halo_cap: int | None = None,
        replan_interval: int = 64,
        prewarm_fallback: bool = True,
        backend: str = "auto",
        strip_cols: int | None = None,
        placement: str = "topology",
        inkernel_drain: bool = True,
    ) -> None:
        if backend == "auto":
            backend = "pallas" if jax.default_backend() == "tpu" else "jnp"
        if backend not in ("jnp", "pallas", "pallas_interpret"):
            raise ValueError(f"unknown backend {backend!r}")
        if placement not in ("topology", "ring"):
            raise ValueError(
                f"placement must be topology|ring, got {placement!r}"
            )
        n_dev = int(mesh.devices.size)
        if n_dev < 2:
            raise ValueError("spatial sharding needs >= 2 devices")
        if params.capacity % (8 * n_dev) != 0:
            raise ValueError(
                f"capacity {params.capacity} must be a multiple of 8*{n_dev}"
            )
        if params.max_events % n_dev != 0:
            raise ValueError(
                f"max_events {params.max_events} must be divisible by {n_dev}"
            )
        if params.grid_x < MIN_STRIP_COLS * n_dev:
            raise ValueError(
                f"grid_x {params.grid_x} < {MIN_STRIP_COLS}*{n_dev} "
                f"(each strip needs >= {MIN_STRIP_COLS} columns for the "
                f"halo contract); raise [aoi] grid or lower mesh_shards"
            )
        if backend != "jnp" and params.cell_capacity > LANES:
            raise ValueError(
                f"pallas path supports cell_capacity <= {LANES}, "
                f"got {params.cell_capacity}"
            )
        # Topology-aware strip→device placement (tentpole a): strip i
        # always lives at mesh position i, so placing strips IS ordering
        # the mesh's devices. Re-plans move strip boundaries, never strip
        # order, so the adjacency the placement buys survives them.
        self.placement = placement
        devs = list(mesh.devices.reshape(-1))
        self.placement_order = plan_placement(devs)
        if placement == "topology" and not np.array_equal(
            self.placement_order, np.arange(n_dev)
        ):
            mesh = Mesh(
                np.asarray([devs[i] for i in self.placement_order]),
                (SHARD_AXIS,),
            )
        coords = [getattr(d, "coords", None) for d in devs]
        if all(c is not None for c in coords):
            _M_RING_DISTANCE.labels("ring").set(
                ring_link_distance(coords, np.arange(n_dev)))
            _M_RING_DISTANCE.labels("placed").set(
                ring_link_distance(
                    coords,
                    self.placement_order if placement == "topology"
                    else np.arange(n_dev)))
        self.params = params
        self.mesh = mesh
        self.backend = backend
        self.n_devices = n_dev
        self.chunk = params.capacity // n_dev
        self.events_inline = params.max_events // n_dev
        gx = params.grid_x
        if backend != "jnp":
            # Static kernel-slab width cap. Default: 2x the uniform strip,
            # clamped to planner feasibility on both sides.
            ceil_w = -(-gx // n_dev)
            if strip_cols is None:
                strip_cols = min(
                    gx - (n_dev - 1) * MIN_STRIP_COLS, 2 * ceil_w
                )
            strip_cols = int(strip_cols)
            if strip_cols < ceil_w:
                raise ValueError(
                    f"strip_cols {strip_cols} < ceil(grid_x/{n_dev}) = "
                    f"{ceil_w}: {n_dev} capped strips cannot cover "
                    f"{gx} columns"
                )
            if strip_cols + 4 > gx:
                raise ValueError(
                    f"strip_cols {strip_cols} + 4 ghost columns exceeds "
                    f"grid_x {gx}; lower strip_cols (the strip slab must "
                    f"not wrap onto itself)"
                )
            self._max_cols: int | None = strip_cols
        else:
            self._max_cols = None
        self.strip_cols = self._max_cols
        if halo_cap is None:
            # ~6 band columns of the uniform-density column population,
            # doubled for clustering, clamped to the chunk (an overflow
            # past this budget falls back for the tick, it never breaks).
            est = 12 * params.capacity // params.grid_x
            halo_cap = max(64, min(self.chunk, ((est + 7) // 8) * 8))
        self.halo_cap = int(halo_cap)
        self.replan_interval = int(replan_interval)
        self.halo_bytes_per_tick = (
            n_dev * 2 * self.halo_cap * HALO_ROW_BYTES
        )
        # What the all-gather formulation moves instead: every OTHER
        # shard's rows, both epochs (pos 8B + act 1B + spc 4B + rad 4B
        # each), received by each of the D devices. The Pallas kernel
        # tier's all-gather formulation (mesh._sharded_step_pallas) moves
        # the same eight feature arrays, so one equivalent serves both.
        self.allgather_bytes_per_tick = (
            n_dev * (params.capacity - self.chunk) * 34
        )
        # In-kernel drain ([aoi] pallas_inkernel_drain, ISSUE 19 leg b):
        # steady strip ticks emit their compacted event pairs from the
        # kernel launch itself; the XLA rank-select stays compiled in as
        # the storm-paging program (a tick whose events overflow the
        # inline budget repages WHOLLY through it — kernel emission is
        # cell-major, so its partial window cannot be rank-resumed).
        self.inkernel_drain = bool(inkernel_drain)
        self.drain_inline = (
            self.events_inline if (backend != "jnp" and inkernel_drain)
            else 0
        )
        if backend == "jnp":
            self._jit_step = _jitted_spatial_step(
                params, mesh, self.events_inline, self.halo_cap
            )
            self._jit_drain = _jitted_spatial_drain(
                params, mesh, self.events_inline, self.chunk
            )
        else:
            self._jit_step = _jitted_spatial_step_pallas(
                params, mesh, self.events_inline, self.halo_cap,
                backend == "pallas_interpret", self.strip_cols,
                self.drain_inline,
            )
            self._jit_drain = _jitted_spatial_drain_bits(
                params, mesh, self.events_inline, self.strip_cols
            )
        # Exact all-gather program for ticks the strip invariants cannot
        # cover (teleports past the halo, halo overflow, strip overflow).
        # BOTH backends fall back to the jnp all-gather program: fallback
        # ticks are rare by construction, and one exact program keeps the
        # oracle surface single (the kernel tier's honesty note, README).
        self._jit_fallback = _jitted_sharded_step(
            params, mesh, self.events_inline
        )
        self._jit_fallback_drain = _jitted_sharded_drain(
            params, mesh, self.events_inline, self.chunk
        )
        self._flat_end = self.chunk * 9 * params.cell_capacity
        self._sharding = NamedSharding(mesh, P(SHARD_AXIS))
        self._state: tuple | None = None
        self.last_grid_dropped = 0
        self.last_mode = "spatial"
        self.last_fast_tick = False
        self.total_fast_ticks = 0
        self.shard_population = np.zeros(n_dev, np.int64)
        self.total_migrations = 0
        self.total_fallbacks = 0
        self.total_replans = 0
        from goworld_tpu import telemetry

        telemetry.gauge(
            "aoi_shard_count",
            "Device shards of the spatially sharded AOI engine.",
        ).set(n_dev)
        self._m_shard_entities = telemetry.gauge(
            "aoi_shard_entities",
            "Active entity rows owned by each AOI grid-strip shard at the "
            "last dispatch.",
            ("shard",),
        )
        self._m_halo_bytes = telemetry.counter(
            "aoi_halo_bytes_total",
            "Bytes ppermuted between shards for AOI halo exchange "
            "(structural: halo_cap rows x 2 directions x D shards per "
            "spatial tick).",
        )
        self._m_allgather_bytes = telemetry.counter(
            "aoi_allgather_bytes_total",
            "Bytes the exact all-gather fallback program moves between "
            "shards (every other shard's rows, both epochs) on ticks the "
            "strip invariants cannot cover.",
        )
        # The structural comms story as live gauges (previously only a
        # bench headline): what one spatial tick moves vs what the
        # all-gather formulation would move — their ratio is THE point of
        # the spatial engine, now watchable on /metrics and /cluster.
        telemetry.gauge(
            "aoi_halo_bytes_per_tick",
            "Structural ppermute payload of one spatial tick "
            "(halo_cap rows x 2 directions x D shards).",
        ).set(self.halo_bytes_per_tick)
        telemetry.gauge(
            "aoi_allgather_equiv_bytes_per_tick",
            "What the all-gather formulation would move per tick at this "
            "tier (every other shard's rows, both epochs, on D devices).",
        ).set(self.allgather_bytes_per_tick)
        self._m_migrations = telemetry.counter(
            "aoi_shard_migrations_total",
            "Entities reassigned to a different AOI grid-strip shard "
            "(hysteresis: one full cell past the seam).",
        )
        self._m_fallback = telemetry.counter(
            "aoi_shard_fallback_total",
            "Ticks the spatial engine ran the exact all-gather program "
            "instead of the halo exchange.",
            ("reason",),
        )
        self._m_replans = telemetry.counter(
            "aoi_shard_replans_total",
            "Density-driven strip re-plans adopted (equal-population "
            "boundary moves).",
        )
        # Per-seam observed halo payload (ROADMAP item 5): the wire moves
        # the structural halo_cap envelope, but the OCCUPIED rows of each
        # band are what a comms regression shows up in — counted per
        # directed seam link into the shared aoi_link_bytes_total family
        # (children prebuilt; _build_bands records the occupancy).
        from goworld_tpu.parallel.mesh import _M_LINK_BYTES

        self._halo_link_children = tuple(
            (_M_LINK_BYTES.labels("halo", f"{s}->{(s - 1) % n_dev}"),
             _M_LINK_BYTES.labels("halo", f"{s}->{(s + 1) % n_dev}"))
            for s in range(n_dev))
        self._last_band_counts: np.ndarray | None = None
        if prewarm_fallback:
            # The fallback program compiles lazily on its (rare) first
            # tick otherwise — a synchronous XLA compile inside the game
            # loop. Best-effort daemon warmup, same pattern as
            # BatchAOIService._prewarm_next_tier.
            threading.Thread(
                target=self._prewarm_fallback, name="aoi-spatial-fallback",
                daemon=True,
            ).start()

    # --- host-side shard layout ---------------------------------------------

    def _prewarm_fallback(self) -> None:
        try:
            n = self.params.capacity
            put = lambda x: jax.device_put(x, self._sharding)  # noqa: E731
            z = (
                put(np.zeros((n, 2), np.float32)),
                put(np.zeros((n,), bool)),
                put(np.zeros((n,), np.int32)),
                put(np.zeros((n,), np.float32)),
            )
            jax.block_until_ready(self._jit_fallback(*z, *z)[2])
        except Exception:  # pragma: no cover - prewarm is best-effort
            pass

    def reset(self) -> None:
        n = self.params.capacity
        gx = self.params.grid_x
        d = self.n_devices
        self.boundaries = np.array(
            [round(i * gx / d) for i in range(d)] + [gx], np.int32
        )
        self._rebuild_col_owner()
        self.perm = np.arange(n, dtype=np.int32)
        self.row_of = np.arange(n, dtype=np.int32)
        self.assign = (self.perm // self.chunk).astype(np.int32)
        zeros = (
            np.zeros((n, 2), np.float32),
            np.zeros((n,), bool),
            np.zeros((n,), np.int32),
            np.zeros((n,), np.float32),
        )
        self._host_prev = zeros
        self._prev_cx = bins_reference(self.params, zeros[0], zeros[2])[0]
        self._dispatches = 0
        self._perm_dirty = False
        put = lambda x: jax.device_put(x, self._sharding)  # noqa: E731
        self._state = tuple(put(a) for a in zeros)
        self._perm_dev = put(self.perm)

    def _rebuild_col_owner(self) -> None:
        gx = self.params.grid_x
        owner = np.empty(gx, np.int32)
        for d in range(self.n_devices):
            owner[self.boundaries[d]:self.boundaries[d + 1]] = d
        self._col_owner = owner
        # Hysteresis band columns, one per side of each strip.
        self._band_lo = (self.boundaries[:-1] - 1) % gx
        self._band_hi = self.boundaries[1:] % gx
        # Per-shard strip origin for the Pallas slab's local-column map;
        # a dynamic [D] input, so boundary moves never retrace the jit.
        self._strip_lo_dev = jax.device_put(
            np.ascontiguousarray(self.boundaries[:-1], dtype=np.int32),
            self._sharding,
        )

    def carried_epoch(self) -> tuple:
        """The last dispatched world in SLOT space (what the tier-growth
        reseed needs — the device state is row-permuted here)."""
        return tuple(np.array(a) for a in self._host_prev)

    def _in_strip_or_band(self, cx: np.ndarray, shard: np.ndarray):
        """Hysteresis keep-test: column inside the shard's strip, or in
        its one-column slack band on either side."""
        return (
            (self._col_owner[cx] == shard)
            | (cx == self._band_lo[shard])
            | (cx == self._band_hi[shard])
        )

    def _rehome_prev_only(self, prev_act, cur_act) -> int:
        """Re-home rows active ONLY in the previous epoch onto the strip
        owning their PREVIOUS cell (see step_async — keeps adopted
        re-plans from stranding a despawned row's prev cell outside its
        band). Returns the number of rows moved."""
        prev_only = np.flatnonzero(prev_act & ~cur_act)
        if not len(prev_only):
            return 0
        keep = self._in_strip_or_band(
            self._prev_cx[prev_only], self.assign[prev_only]
        )
        movers = prev_only[~keep]
        if len(movers):
            self.assign[movers] = self._col_owner[self._prev_cx[movers]]
            self._perm_dirty = True
        return int(len(movers))

    def _replan(self, cx: np.ndarray, active: np.ndarray) -> bool:
        """Re-split strips from the observed column density; adopt only
        when the split meaningfully improves the worst strip load."""
        pop = np.bincount(cx[active], minlength=self.params.grid_x)
        new = plan_strips(pop, self.n_devices, max_cols=self._max_cols)
        if np.array_equal(new, self.boundaries):
            return False
        cum = np.concatenate([[0], np.cumsum(pop, dtype=np.int64)])

        def worst(bounds):
            loads = cum[bounds[1:]] - cum[bounds[:-1]]
            return int(loads.max()) if len(loads) else 0

        if worst(new) > 0.9 * worst(self.boundaries):
            return False
        self.boundaries = new
        self._rebuild_col_owner()
        self.total_replans += 1
        self._m_replans.inc()
        return True

    def _rebuild_perm(self, placed: np.ndarray) -> None:
        """Row layout from the current assignment: shard d's rows hold its
        PLACED slots (active in either epoch — a freshly-despawned slot
        must stay on the strip its previous-epoch pairs live on, or its
        neighbors' leave events would never find it) in slot order, then
        free fill (deterministic)."""
        n = self.params.capacity
        d = self.n_devices
        chunk = self.chunk
        perm = np.empty(n, np.int32)
        inactive = np.flatnonzero(~placed).astype(np.int32)
        cursor = 0
        for s in range(d):
            mine = np.flatnonzero(placed & (self.assign == s)).astype(
                np.int32
            )
            k = len(mine)
            assert k <= chunk, "strip overflow must fall back before here"
            perm[s * chunk:s * chunk + k] = mine
            fill = chunk - k
            pad = inactive[cursor:cursor + fill]
            perm[s * chunk + k:(s + 1) * chunk] = pad
            # Inactive slots inherit the shard of the row that parks them
            # (keeps the keep-test well-defined when they activate).
            self.assign[pad] = s
            cursor += fill
        self.perm = perm
        self.row_of = np.empty(n, np.int32)
        self.row_of[perm] = np.arange(n, dtype=np.int32)

    # --- dispatch -----------------------------------------------------------

    # Fused entity logic is supported: per-row elementwise programs ride
    # the spatial launch in row-permuted layout (see _spatial_step_fused).
    supports_fused_logic = True

    def step_async(
        self,
        pos: np.ndarray,
        active: np.ndarray,
        space: np.ndarray,
        radius: np.ndarray,
        meta_dirty: bool = True,
        logic: tuple | None = None,
    ):
        assert self._state is not None, "call reset() first"
        check_radius(self.params, radius, active)
        if self.backend != "jnp":
            check_space_ids(space, active)
        p = self.params
        gx = p.grid_x
        # Copies, not views: these become the host prev mirror and must
        # not alias caller buffers (same contract as the other engines).
        cur = (
            np.array(pos, np.float32),
            np.array(active, bool),
            np.array(space, np.int32),
            np.array(radius, np.float32),
        )
        cur_pos, cur_act, cur_spc, _ = cur
        cx = bins_reference(p, cur_pos, cur_spc)[0]
        self._dispatches += 1

        from goworld_tpu.telemetry import tracing

        halo_span = tracing.child_scope("tick.halo")
        t0 = time.monotonic()

        perm_rebuilt = False
        migrations = 0
        prev_act = self._host_prev[1]
        # Slow-cadence density re-plan.
        if (
            self.replan_interval
            and self._dispatches % self.replan_interval == 0
            and self._replan(cx, cur_act)
        ):
            self._perm_dirty = True
        # Hysteresis migration: move a row only when its cell is a full
        # column past the seam.
        act_idx = np.flatnonzero(cur_act)
        keep = self._in_strip_or_band(cx[act_idx], self.assign[act_idx])
        movers = act_idx[~keep]
        if len(movers):
            self.assign[movers] = self._col_owner[cx[movers]]
            migrations += len(movers)
            self._perm_dirty = True
        # Prev-epoch-only rows (freshly despawned) re-home by their
        # PREVIOUS column: their only remaining job is hosting their
        # prev-epoch pairs, so an adopted re-plan that moved boundaries
        # several columns must carry them to the new owner of that cell —
        # otherwise the stranded prev cell trips the teleport guard and
        # the tick pays the exact all-gather fallback for no reason.
        migrations += self._rehome_prev_only(prev_act, cur_act)

        fallback_reason = None
        # Row placement covers slots live in EITHER epoch: a slot that
        # just despawned still owns a row on its strip this tick so its
        # neighbors' leave events resolve there.
        placed_idx = np.flatnonzero(cur_act | prev_act)
        counts = np.bincount(
            self.assign[placed_idx], minlength=self.n_devices
        ).astype(np.int64)
        if counts.max(initial=0) > self.chunk:
            # A strip outgrew its row budget: re-plan NOW; if one column
            # is hotter than a whole shard's budget even alone, spatial
            # sharding cannot represent it — exact fallback.
            if self._replan(cx, cur_act):
                # Boundary move: reassign by owner column (hysteresis slack
                # resets), counting only rows that actually changed shard.
                new_assign = self._col_owner[cx[act_idx]]
                migrations += int((new_assign != self.assign[act_idx]).sum())
                self.assign[act_idx] = new_assign
                self._perm_dirty = True
                migrations += self._rehome_prev_only(prev_act, cur_act)
                counts = np.bincount(
                    self.assign[placed_idx], minlength=self.n_devices
                ).astype(np.int64)
            if counts.max(initial=0) > self.chunk:
                fallback_reason = "strip_overflow"
        self.shard_population = counts

        if fallback_reason is None:
            # Teleport guard: every row active in the PREVIOUS epoch must
            # have its previous cell inside its (current) shard's slack
            # band, or its leave pass would reach past the halo.
            pa_idx = np.flatnonzero(prev_act)
            ok = self._in_strip_or_band(
                self._prev_cx[pa_idx], self.assign[pa_idx]
            )
            if not ok.all():
                fallback_reason = "teleport"

        if self._perm_dirty and fallback_reason != "strip_overflow":
            # Bands are expressed as LOCAL row indices, so the layout must
            # be rebuilt before they are selected. (The dirty flag is
            # persistent state: a strip-overflow fallback tick defers the
            # rebuild — chunk cannot hold the strip — without losing it.)
            self._rebuild_perm(cur_act | prev_act)
            self._perm_dirty = False
            perm_rebuilt = True
        send_lo = send_hi = None
        if fallback_reason is None:
            send_lo, send_hi, overflow = self._build_bands(
                cx, cur_act, prev_act
            )
            if overflow:
                fallback_reason = "halo_overflow"
        if migrations:
            self.total_migrations += migrations
            self._m_migrations.inc(migrations)
        for d in range(self.n_devices):
            self._m_shard_entities.labels(str(d)).set(int(counts[d]))
        if halo_span is not None:
            halo_span.args["migrations"] = migrations
            halo_span.args["mode"] = fallback_reason or "spatial"
            tracing.record_span(
                halo_span.name, t0, time.monotonic() - t0,
                halo_span.ctx.trace_id, halo_span.ctx.span_id,
                halo_span.parent_id, halo_span.args,
            )

        put = lambda x: jax.device_put(x, self._sharding)  # noqa: E731
        perm = self.perm
        if perm_rebuilt:
            # The previous epoch must live in the NEW layout or the device
            # diff would read a migration as despawn+spawn. Cheap at the
            # host tier: four slot-space gathers + uploads.
            hp = self._host_prev
            self._state = (
                put(hp[0][perm]), put(hp[1][perm]),
                put(hp[2][perm]), put(hp[3][perm]),
            )
            self._perm_dev = put(perm)
        if meta_dirty or perm_rebuilt:
            meta = (
                put(cur[1][perm]), put(cur[2][perm]), put(cur[3][perm])
            )
        else:
            meta = self._state[1:4]
        cur_dev = (put(cur[0][perm]),) + meta

        fused_out = None
        logic_dev: tuple = ()
        if logic is not None:
            # Row-permuted upload of the fused-logic inputs: the programs
            # run per LOCAL row, so sel/y/yaw/columns travel through the
            # same perm as the positions; dt rides as a [D] sharded array
            # (one scalar per shard body).
            programs, sel, y, yaw, dt, cols = logic
            logic_dev = (
                put(np.asarray(y, np.float32)[perm]),
                put(np.asarray(yaw, np.float32)[perm]),
                put(np.asarray(sel, np.int32)[perm]),
                put(np.full(self.n_devices, dt, np.float32)),
            ) + tuple(put(np.asarray(c)[perm]) for c in cols)

        if fallback_reason is None:
            if self.backend != "jnp":
                band_args = (
                    self._perm_dev, put(send_lo), put(send_hi),
                    self._strip_lo_dev,
                )
                if logic is not None:
                    jit_fused = _jitted_spatial_step_pallas_fused(
                        self.params, self.mesh, self.events_inline,
                        self.halo_cap, self.backend == "pallas_interpret",
                        self.strip_cols, tuple(logic[0]), len(logic[5]),
                        self.drain_inline,
                    )
                    res = jit_fused(
                        *self._state, *cur_dev, *band_args, *logic_dev,
                    )
                    fused_out = res[11]
                else:
                    res = self._jit_step(*self._state, *cur_dev, *band_args)
                enter_ctx = ("pallas",) + tuple(res[0:5]) + (self._perm_dev,)
                leave_ctx = ("pallas",) + tuple(res[5:10]) + (self._perm_dev,)
                out = res[10]
            else:
                if logic is not None:
                    jit_fused = _jitted_spatial_step_fused(
                        self.params, self.mesh, self.events_inline,
                        self.halo_cap, tuple(logic[0]), len(logic[5]),
                    )
                    enter_ids, leave_ids, out, fused_out = jit_fused(
                        *self._state, *cur_dev, self._perm_dev,
                        put(send_lo), put(send_hi), *logic_dev,
                    )
                else:
                    enter_ids, leave_ids, out = self._jit_step(
                        *self._state, *cur_dev, self._perm_dev,
                        put(send_lo), put(send_hi),
                    )
                enter_ctx = ("spatial", enter_ids, self._perm_dev)
                leave_ctx = ("spatial", leave_ids, self._perm_dev)
            self.last_mode = "spatial"
            self._m_halo_bytes.inc(self.halo_bytes_per_tick)
            if self._last_band_counts is not None:
                for s in range(self.n_devices):
                    lo_n, hi_n = self._last_band_counts[s]
                    if lo_n:
                        self._halo_link_children[s][0].inc(
                            int(lo_n) * HALO_ROW_BYTES)
                    if hi_n:
                        self._halo_link_children[s][1].inc(
                            int(hi_n) * HALO_ROW_BYTES)
            pending = ShardedPendingStep(self, enter_ctx, leave_ctx, out)
            # The strip-local bit drain pages by event RANK; everything
            # else (jnp ids, the jnp all-gather fallback) by flat index.
            pending.rank_paging = self.backend != "jnp"
            # In-kernel drain pairs are cell-major: an overflowing shard's
            # inline window is order-incompatible with rank resume, so
            # collect() discards it and repages that shard from rank 0.
            pending.full_repage = self.drain_inline > 0
        else:
            if logic is not None:
                jit_fused = _jitted_sharded_step_fused(
                    self.params, self.mesh, self.events_inline,
                    tuple(logic[0]), len(logic[5]),
                )
                enter_ids, leave_ids, out, fused_out = jit_fused(
                    *self._state, *cur_dev, *logic_dev,
                )
            else:
                enter_ids, leave_ids, out = self._jit_fallback(
                    *self._state, *cur_dev
                )
            enter_ctx = ("fallback", enter_ids)
            leave_ctx = ("fallback", leave_ids)
            self.last_mode = f"fallback:{fallback_reason}"
            self.total_fallbacks += 1
            self._m_fallback.labels(fallback_reason).inc()
            self._m_allgather_bytes.inc(self.allgather_bytes_per_tick)
            pending = _FallbackPendingStep(
                self, enter_ctx, leave_ctx, out, perm.copy()
            )
            # The fallback is the jnp all-gather program on EITHER backend:
            # its cursors are flat matrix indices.
            pending.rank_paging = False

        if fused_out is not None:
            from goworld_tpu.ops.neighbor import start_host_copy

            for arr in fused_out:
                start_host_copy(arr)
            # Outputs are in ROW space: the perm SNAPSHOT maps row→slot at
            # writeback time, immune to later migrations/re-plans.
            pending.fused = (tuple(logic[0]), np.asarray(logic[1]),
                             perm.copy(), fused_out)

        self._state = cur_dev
        self._host_prev = cur
        self._prev_cx = cx
        return pending

    def warmup_fused(self, programs: tuple, col_dtypes: tuple) -> None:
        """Compile BOTH fused programs (spatial + exact fallback) for this
        program set without touching engine state — the spatial analog of
        NeighborEngine.warmup_fused (restore-path prewarm)."""
        n = self.params.capacity
        d = self.n_devices
        gx = self.params.grid_x
        put = lambda x: jax.device_put(x, self._sharding)  # noqa: E731
        zeros = (
            put(np.zeros((n, 2), np.float32)),
            put(np.zeros((n,), bool)),
            put(np.zeros((n,), np.int32)),
            put(np.zeros((n,), np.float32)),
        )
        logic_dev = (
            put(np.zeros(n, np.float32)),
            put(np.zeros(n, np.float32)),
            put(np.zeros(n, np.int32)),
            put(np.zeros(d, np.float32)),
        ) + tuple(put(np.zeros(n, np.dtype(dt))) for dt in col_dtypes)
        ncols = len(col_dtypes)
        perm = put(np.arange(n, dtype=np.int32))
        empty_band = put(np.full(d * self.halo_cap, self.chunk, np.int32))
        if self.backend != "jnp":
            strip_lo = put(np.asarray(
                [round(i * gx / d) for i in range(d)], np.int32))
            jit_sp = _jitted_spatial_step_pallas_fused(
                self.params, self.mesh, self.events_inline, self.halo_cap,
                self.backend == "pallas_interpret", self.strip_cols,
                tuple(programs), ncols, self.drain_inline,
            )
            jax.block_until_ready(
                jit_sp(*zeros, *zeros, perm, empty_band, empty_band,
                       strip_lo, *logic_dev)[10])
        else:
            jit_sp = _jitted_spatial_step_fused(
                self.params, self.mesh, self.events_inline, self.halo_cap,
                tuple(programs), ncols,
            )
            jax.block_until_ready(
                jit_sp(*zeros, *zeros, perm, empty_band, empty_band,
                       *logic_dev)[2])
        jit_fb = _jitted_sharded_step_fused(
            self.params, self.mesh, self.events_inline,
            tuple(programs), ncols,
        )
        jax.block_until_ready(jit_fb(*zeros, *zeros, *logic_dev)[2])

    def fused_trace_count(self, programs: tuple) -> int:
        """Trace count of the fused SPATIAL jit for ``programs`` (the
        no-fresh-trace restore gate; the fallback jit is warmed alongside
        but not counted here)."""
        if self.backend != "jnp":
            jit_sp: object = _jitted_spatial_step_pallas_fused(
                self.params, self.mesh, self.events_inline, self.halo_cap,
                self.backend == "pallas_interpret", self.strip_cols,
                tuple(programs), self._warmed_ncols(programs),
            )
        else:
            jit_sp = _jitted_spatial_step_fused(
                self.params, self.mesh, self.events_inline, self.halo_cap,
                tuple(programs), self._warmed_ncols(programs),
            )
        try:
            return int(jit_sp._cache_size())
        except Exception:  # pragma: no cover - private-API drift
            return -1

    def _note_step_flags(self, flags: int) -> None:
        """Header-flag hook (ShardedPendingStep.collect): bit 0 = the
        seam-free single-pass guard held for the collected tick."""
        self.last_fast_tick = bool(flags & 1)
        if flags & 1:
            self.total_fast_ticks += 1
            _M_FAST_TICKS.inc()

    @staticmethod
    def _warmed_ncols(programs: tuple) -> int:
        return sum(len(p.columns) for p in programs)

    def _build_bands(self, cx, cur_act, prev_act):
        """Per-shard send-index arrays for both seams (flattened
        [D*halo_cap], sentinel chunk) from current AND previous columns."""
        gx = self.params.grid_x
        d = self.n_devices
        h = self.halo_cap
        rel = np.flatnonzero(cur_act | prev_act)
        sh = self.assign[rel]
        lo = self.boundaries[sh]
        hi = self.boundaries[sh + 1]
        c = cx[rel]
        pc = self._prev_cx[rel]

        def in_lo_band(col, act_mask):
            return act_mask & (((col - (lo - 1)) % gx) < 3)

        def in_hi_band(col, act_mask):
            return act_mask & (((col - (hi - 2)) % gx) < 3)

        ca = cur_act[rel]
        pa = prev_act[rel]
        low = in_lo_band(c, ca) | in_lo_band(pc, pa)
        high = in_hi_band(c, ca) | in_hi_band(pc, pa)
        if d == 2:
            # Ring of two: both bands land on the same peer — one copy.
            high &= ~low
        send_lo = np.full(d * h, self.chunk, np.int32)
        send_hi = np.full(d * h, self.chunk, np.int32)
        counts = np.zeros((d, 2), np.int64)
        for s in range(d):
            for i, (mask, buf) in enumerate(((low, send_lo),
                                             (high, send_hi))):
                slots = rel[mask & (sh == s)]
                if len(slots) > h:
                    self._last_band_counts = None
                    return None, None, True
                rows = np.sort(self.row_of[slots] - s * self.chunk)
                buf[s * h:s * h + len(rows)] = rows
                counts[s, i] = len(rows)
        self._last_band_counts = counts
        return send_lo, send_hi, False

    def _page(self, ctx: tuple, deficit: np.ndarray, starts: np.ndarray):
        """Per-shard chunked drain for events beyond the inline budget;
        ctx[0] picks the program: "spatial" = jnp id-matrix drain (flat-
        index paging), "pallas" = strip-local bit drain (event-RANK
        paging), anything else = the jnp all-gather fallback drain."""
        mode = ctx[0]
        chunks: list[np.ndarray] = []
        starts = starts.copy()
        deficit = deficit.copy()
        rank_paging = mode == "pallas"
        while deficit.any():
            st = jax.device_put(
                np.asarray(starts, np.int32), self._sharding
            )
            if mode == "pallas":
                pairs, aux = self._jit_drain(*ctx[1:6], ctx[6], st)
            elif mode == "spatial":
                pairs, aux = self._jit_drain(ctx[1], ctx[2], st)
            else:
                pairs, aux = self._jit_fallback_drain(ctx[1], st)
            pairs = np.asarray(pairs)
            aux = np.asarray(aux)
            e = self.events_inline
            for d in range(self.n_devices):
                take = int(min(e, deficit[d]))
                if take <= 0:
                    continue
                chunks.append(pairs[d * e:d * e + take])
                deficit[d] -= take
                if rank_paging:
                    starts[d] += take
                elif deficit[d] > 0:
                    starts[d] = aux[d, take - 1] + 1
                else:
                    starts[d] = self._flat_end
        return chunks

    def step(self, pos, active, space, radius):
        return self.step_async(pos, active, space, radius).collect()


class _FallbackPendingStep(ShardedPendingStep):
    """A fallback tick's pending step: the all-gather program speaks ROW
    ids — map the collected pairs back to entity slots through the row
    permutation snapshotted at dispatch (the live perm may rotate under a
    pipelined consumer before collect())."""

    __slots__ = ("_perm",)

    def __init__(self, engine, enter_ctx, leave_ctx, out, perm) -> None:
        super().__init__(engine, enter_ctx, leave_ctx, out)
        self._perm = perm

    def collect(self):
        enters, leaves, dropped = super().collect()
        if len(enters):
            enters = self._perm[enters]
        if len(leaves):
            leaves = self._perm[leaves]
        return enters, leaves, dropped

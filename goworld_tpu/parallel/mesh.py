"""Entity-sharded AOI over a device mesh.

The reference scales by sharding entities/spaces across game processes, with
no cross-process AOI at all (SURVEY.md §5.7: AOI is strictly per-Space,
per-game). The TPU-native design goes further: entity slots are sharded over
a mesh axis; each tick, **positions are all-gathered over ICI** so every
device sees the whole world, then each device computes the enter/leave event
diffs only for the entity rows it owns (the same event-native two-grid
pairwise formulation as ops/neighbor.py — exact sets, no truncation). This
is the "sequence parallelism" of this domain (BASELINE.json config 5: 1M
entities, 8 game processes → v5e-16 pod).

Communication per tick = one all-gather of the per-entity feature arrays
(~1 MB at 100k entities) — rides ICI, far below its bandwidth. Grid builds
are replicated per device (cheap: one sort of N keys each); the O(N·9M)
candidate math — the actual FLOPs — is perfectly sharded on query rows.

Host interface parity with the single-device engine (round-2 upgrade):
``step_async`` dispatches without blocking and ``collect()`` performs
exactly ONE blocking device→host read — every shard packs its header +
inline event pairs into one stacked ``[D * (3 + 2E), 2]`` buffer. Event
storms beyond the inline budget page through per-shard chunked drains.

Collectives are XLA's (all_gather inside shard_map); there is no NCCL/MPI
analog to port — the reference's TCP star stays the control plane
(SURVEY.md §5.8).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from goworld_tpu.parallel.compat import resolve_shard_map
from goworld_tpu.telemetry import sentinel
from goworld_tpu.ops.neighbor import (
    LANES,
    _PACK,
    NeighborParams,
    _apply_fused_logic,
    _bins,
    _build_table,
    _fast_guard,
    _compiled_event_kernel,
    _drain_bits,
    _drain_ids,
    _epoch_mask,
    _gather_cands,
    _scatter_feats,
    check_radius,
    check_space_ids,
    start_host_copy,
)

SHARD_AXIS = "shard"

from goworld_tpu import telemetry  # noqa: E402  (after SHARD_AXIS constant)

# Transfer accounting for the all-gather tiers (ISSUE 15 satellite): what
# one entity-sharded tick structurally moves between devices — every
# other shard's rows, both epochs — live beside the spatial tier's halo
# gauges so the comms story is comparable on /metrics, /cluster and
# gwtop. Module-scope registration (gwlint R5); same family the spatial
# engine's fallback ticks account into.
_M_ALLGATHER_EQUIV = telemetry.gauge(
    "aoi_allgather_equiv_bytes_per_tick",
    "What the all-gather formulation moves per tick at this tier (every "
    "other shard's rows, both epochs, on D devices).",
)
_M_ALLGATHER_TOTAL = telemetry.counter(
    "aoi_allgather_bytes_total",
    "Bytes moved between shards by all-gather AOI ticks (the entity-"
    "sharded tier every tick; the spatial tier only on exact-fallback "
    "ticks).",
)
# Per-link transfer accounting (ROADMAP item 5): what each receiving
# device/host/seam pulls per tick, attributable after the fact through
# the history frames every process records. tier: ici-allgather (entity-
# sharded within a host), dcn-allgather (multihost cross-host slice),
# halo (the spatial tier's seam ppermute — OBSERVED band occupancy, not
# the structural halo_cap envelope).
_M_LINK_BYTES = telemetry.counter(
    "aoi_link_bytes_total",
    "Per-link device-comms bytes by tier (ici-allgather / dcn-allgather "
    "/ halo) and link (receiving device, host slice, or strip seam).",
    ("tier", "link"),
)


def make_mesh(n_devices: int | None = None, devices: list | None = None) -> Mesh:
    """Build a 1-D mesh over the entity-shard axis.

    Prefers explicitly passed devices; otherwise takes the first n of
    jax.devices(). For CPU-hosted multi-device testing, set
    ``--xla_force_host_platform_device_count`` (tests/conftest.py does).
    """
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            if len(devices) < n_devices:
                # Fall back to virtual CPU devices when the default platform
                # has too few chips (e.g. one real TPU during development).
                cpu = jax.devices("cpu")
                if len(cpu) >= n_devices:
                    devices = cpu
                else:
                    raise ValueError(
                        f"need {n_devices} devices, have {len(devices)} "
                        f"{devices[0].platform} and {len(cpu)} cpu"
                    )
            devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (SHARD_AXIS,))


def _sharded_step(
    p: NeighborParams,
    events_inline: int,  # per-shard inline event budget E
    ppos_l, pact_l, pspc_l, prad_l,  # this shard's previous-tick rows
    pos_l, act_l, spc_l, rad_l,  # this shard's current-tick rows
):
    """Per-shard body run under shard_map. Returns
    (enter_ids [chunk, 9M], leave_ids [chunk, 9M], out [3+2E, 2])."""
    n = p.capacity
    m = p.cell_capacity
    chunk = pos_l.shape[0]
    shard = jax.lax.axis_index(SHARD_AXIS)
    q_ids = shard * chunk + jnp.arange(chunk, dtype=jnp.int32)

    # ICI all-gather: full world view of both epochs on every device.
    gather = lambda x: jax.lax.all_gather(x, SHARD_AXIS, tiled=True)  # noqa: E731
    pos, act, spc, rad = gather(pos_l), gather(act_l), gather(spc_l), gather(rad_l)
    ppos, pact, pspc, prad = (
        gather(ppos_l), gather(pact_l), gather(pspc_l), gather(prad_l),
    )

    cxc, czc, smc = _bins(p, pos, spc)
    cxp, czp, smp = _bins(p, ppos, pspc)
    buc_c = (smc * p.grid_z + czc) * p.grid_x + cxc
    buc_p = (smp * p.grid_z + czp) * p.grid_x + cxp
    # Replicated table builds (one N-key sort each); identical on all shards.
    table_c, slot_c, dropped_c, _, _ = _build_table(p, buc_c, act, m)
    table_p, slot_p, _, _, _ = _build_table(p, buc_p, pact, m)
    av_c = slot_c >= 0
    av_p = slot_p >= 0

    lo = shard * chunk
    sl = lambda x: jax.lax.dynamic_slice_in_dim(x, lo, chunk)  # noqa: E731
    sl2 = lambda x: jax.lax.dynamic_slice_in_dim(x, lo, chunk, axis=0)  # noqa: E731

    # Enter pass: candidates from the current grid, this shard's queries.
    cand_c = _gather_cands(p, table_c, sl(cxc), sl(czc), sl(smc))
    vc = _epoch_mask(p, cand_c, q_ids, sl2(pos), sl(av_c), sl(spc), sl(rad),
                     pos, av_c, spc)
    vp_on_c = _epoch_mask(p, cand_c, q_ids, sl2(ppos), sl(av_p), sl(pspc),
                          sl(prad), ppos, av_p, pspc)
    enter_mask = vc & ~vp_on_c

    # Leave pass: single-pass fast path when the displacement guard holds
    # (ops/neighbor._step_jnp — the guard's inputs are replicated after the
    # all-gather, so the cond resolves identically on every shard).
    fast = _fast_guard(p, ppos, pact, pspc, prad, pos, act, spc, dropped_c)

    def fast_fn():
        return vp_on_c & ~vc, cand_c

    def slow_fn():
        cand_p = _gather_cands(p, table_p, sl(cxp), sl(czp), sl(smp))
        vp = _epoch_mask(p, cand_p, q_ids, sl2(ppos), sl(av_p), sl(pspc),
                         sl(prad), ppos, av_p, pspc)
        vc_on_p = _epoch_mask(p, cand_p, q_ids, sl2(pos), sl(av_c), sl(spc),
                              sl(rad), pos, av_c, spc)
        return vp & ~vc_on_p, cand_p

    leave_mask, cand_l = jax.lax.cond(fast, fast_fn, slow_fn)

    enter_ids = jnp.where(enter_mask, cand_c, n)
    leave_ids = jnp.where(leave_mask, cand_l, n)
    n_enters = jnp.sum(enter_mask).astype(jnp.int32)
    n_leaves = jnp.sum(leave_mask).astype(jnp.int32)

    def globalize(pairs):
        ent = pairs[:, 0]
        ent = jnp.where(ent < chunk, ent + lo, n)
        return jnp.stack([ent, pairs[:, 1]], axis=1)

    ep, ei = _drain_ids(enter_ids, n, events_inline, jnp.int32(0))
    lp, li = _drain_ids(leave_ids, n, events_inline, jnp.int32(0))
    header = jnp.stack(
        [
            jnp.stack([n_enters, n_leaves]),
            jnp.stack([dropped_c, jnp.int32(0)]),
            jnp.stack([ei[events_inline - 1], li[events_inline - 1]]),
        ]
    ).astype(jnp.int32)
    # EVERY shard's counts, replicated into each block: a multi-controller
    # host (parallel/multihost.py) can only read its own shards, but storm
    # paging must dispatch the SAME number of global drain calls on every
    # process — the replicated counts are what make the loops converge.
    counts_all = jax.lax.all_gather(header[0], SHARD_AXIS)  # [D, 2]
    out = jnp.concatenate(
        [header, counts_all, globalize(ep), globalize(lp)], axis=0
    )
    return enter_ids, leave_ids, out


def _sharded_step_pallas(
    p: NeighborParams,
    events_inline: int,
    interpret: bool,
    n_dev: int,
    ppos_l, pact_l, pspc_l, prad_l,
    pos_l, act_l, spc_l, rad_l,
):
    """Per-shard body running the dense-cell Pallas kernel on a SLAB of the
    grid (VERDICT r2 #3: pod = single-chip kernel × N, not oracle × N).

    Inputs stay entity-row sharded (the host's natural layout) and are
    all-gathered over ICI; the *work* is sharded over grid rows: each device
    scatters the replicated cell layout, slices its ``grid_z / D`` rows
    (plus torus halo), launches the kernel there, and drains events for the
    entities binned in its slab — every event is emitted exactly once
    because each entity lives in exactly one cell per pass.
    """
    n = p.capacity
    # n_dev rides in statically from the jit builder: jax.lax.axis_size
    # does not exist on this image's jax (0.4.37), and the mesh size is a
    # compile-time constant here anyway (rows must be static).
    rows = p.grid_z // n_dev
    shard = jax.lax.axis_index(SHARD_AXIS)
    lo = shard * rows
    w_words = 9 * LANES // _PACK
    kernel = _compiled_event_kernel(p, interpret, rows)
    kernel_dual = _compiled_event_kernel(p, interpret, rows, dual=True)

    gather = lambda x: jax.lax.all_gather(x, SHARD_AXIS, tiled=True)  # noqa: E731
    pos, act, spc, rad = gather(pos_l), gather(act_l), gather(spc_l), gather(rad_l)
    ppos, pact, pspc, prad = (
        gather(ppos_l), gather(pact_l), gather(pspc_l), gather(prad_l),
    )

    # Build both epochs' grids ONCE; each pass then shares them (the enter
    # pass's candidate grid is the leave pass's B-visibility grid and vice
    # versa — building per pass would do 4 argsorts where 2 suffice).
    def one_grid(xpos, xact, xspc):
        cx, cz, sm = _bins(p, xpos, xspc)
        buc = (sm * p.grid_z + cz) * p.grid_x + cx
        table, slot, dropped, order, dst = _build_table(p, buc, xact, LANES)
        return cx, cz, sm, table, slot, dropped, order, dst

    cxc, czc, smc, table_c, slot_c, dropped_c, order_c, dst_c = one_grid(
        pos, act, spc
    )
    cxp, czp, smp, table_p, slot_p, _, order_p, dst_p = one_grid(
        ppos, pact, pspc
    )
    # x rows poisoned by each epoch's own slot validity (ops/neighbor:
    # _step_pallas) — NaN replaces the av occupancy rows of round 2.
    xs_c = jnp.where(slot_c >= 0, pos[:, 0], jnp.nan)
    xs_p = jnp.where(slot_p >= 0, ppos[:, 0], jnp.nan)
    cur_feats = (xs_c, pos[:, 1], spc, rad)
    prev_feats = (xs_p, ppos[:, 1], pspc, prad)

    cells_c = _scatter_feats(p, dst_c, order_c, cur_feats, prev_feats)
    slab_c = jax.lax.dynamic_slice_in_dim(cells_c, lo, rows + 2, axis=1)

    # Single-launch fast path (ops/neighbor._step_pallas): the guard's
    # inputs are replicated after the all-gather, so the cond resolves
    # identically on every shard. Fast ticks run ONE dual-output kernel on
    # the current grid's slab; other ticks pay the second feats+kernel pass
    # on the previous grid.
    fast = _fast_guard(p, ppos, pact, pspc, prad, pos, act, spc, dropped_c)

    def fast_fn():
        pk2 = kernel_dual(slab_c)  # [S, rows, gx, LANES, 2W]
        return (pk2[..., :w_words], pk2[..., w_words:],
                cxc, czc, smc, table_c, slot_c)

    def slow_fn():
        pk_e = kernel(slab_c)
        cells_p = _scatter_feats(p, dst_p, order_p, prev_feats, cur_feats)
        slab_p = jax.lax.dynamic_slice_in_dim(cells_p, lo, rows + 2, axis=1)
        pk_l = kernel(slab_p)
        return (pk_e, pk_l, cxp, czp, smp, table_p, slot_p)

    pk_e, pk_l, lcx, lcz, lsm, ltable, lslot = jax.lax.cond(
        fast, fast_fn, slow_fn
    )

    def extract(packed_cells, cx, cz, sm, slot):
        """Per-entity packed words for entities binned in THIS slab."""
        lane = slot % LANES
        local_bucket = (sm * rows + (cz - lo)) * p.grid_x + cx
        local_flat = local_bucket * LANES + lane
        mine = (slot >= 0) & (cz >= lo) & (cz < lo + rows)
        flat = packed_cells.reshape(-1, w_words)
        safe = jnp.clip(local_flat, 0, flat.shape[0] - 1)
        pe = jnp.where(mine[:, None], flat[safe], 0)  # i32[N, W]
        return pe, jnp.sum(jax.lax.population_count(pe)).astype(jnp.int32)

    packed_e, n_enters = extract(pk_e, cxc, czc, smc, slot_c)
    packed_l, n_leaves = extract(pk_l, lcx, lcz, lsm, lslot)

    ep, _ = _drain_bits(p, packed_e, cxc, czc, smc, table_c, jnp.int32(0),
                        max_events=events_inline)
    lp, _ = _drain_bits(p, packed_l, lcx, lcz, lsm, ltable, jnp.int32(0),
                        max_events=events_inline)
    zero = jnp.int32(0)
    header = jnp.stack(
        [
            jnp.stack([n_enters, n_leaves]),
            jnp.stack([dropped_c, zero]),
            jnp.stack([zero, zero]),  # rank paging resumes at events_inline
        ]
    ).astype(jnp.int32)
    # Replicated per-shard counts — see _sharded_step (multihost paging).
    counts_all = jax.lax.all_gather(header[0], SHARD_AXIS)  # [D, 2]
    out = jnp.concatenate([header, counts_all, ep, lp], axis=0)
    enter_ctx = (packed_e, cxc, czc, smc, table_c)
    leave_ctx = (packed_l, lcx, lcz, lsm, ltable)
    return enter_ctx + leave_ctx + (out,)


def _sharded_drain_bits(
    p: NeighborParams, events_inline: int,
    packed_l, cx_l, cz_l, sm_l, table_l,  # per-shard drain context
    start_l: jax.Array,  # [1] resume RANK
):
    """Pallas-path storm paging: rows are global entity ids already."""
    pairs, total = _drain_bits(
        p, packed_l, cx_l, cz_l, sm_l, table_l, start_l[0],
        max_events=events_inline,
    )
    return pairs, total[None]


def _sharded_drain(
    p: NeighborParams, events_inline: int, chunk: int,
    ids_l: jax.Array,  # [chunk, 9M] this shard's event-id matrix
    start_l: jax.Array,  # [1] this shard's resume cursor (local flat index)
):
    n = p.capacity
    shard = jax.lax.axis_index(SHARD_AXIS)
    pairs, idx = _drain_ids(ids_l, n, events_inline, start_l[0])
    ent = jnp.where(pairs[:, 0] < chunk, pairs[:, 0] + shard * chunk, n)
    pairs = jnp.stack([ent, pairs[:, 1]], axis=1)
    return pairs, idx[None]


def _sharded_step_fused(
    p: NeighborParams, events_inline: int, programs,
    ppos_l, pact_l, pspc_l, prad_l,
    pos_l, act_l, spc_l, rad_l,
    y_l, yaw_l, sel_l, dt_l, *cols_l,
):
    """The all-gather step plus fused entity logic on this shard's LOCAL
    rows (elementwise — no extra comms). Used by the spatial engine's
    exact-fallback ticks so a teleport/overflow tick still advances the
    fused programs; outputs are in ROW space, mapped back through the
    dispatch-time perm snapshot by the caller."""
    enter_ids, leave_ids, out = _sharded_step(
        p, events_inline,
        ppos_l, pact_l, pspc_l, prad_l,
        pos_l, act_l, spc_l, rad_l,
    )
    new_pos, new_y, new_yaw, new_cols = _apply_fused_logic(
        programs, pos_l, y_l, yaw_l, sel_l, dt_l[0], cols_l
    )
    return enter_ids, leave_ids, out, (new_pos, new_y, new_yaw) + new_cols


@functools.lru_cache(maxsize=None)
def _jitted_sharded_step_fused(
    params: NeighborParams, mesh: Mesh, events_inline: int,
    programs: tuple, n_cols: int,
):
    shard_map = resolve_shard_map()

    body = functools.partial(
        _sharded_step_fused, params, events_inline, programs
    )
    spec = P(SHARD_AXIS)
    mapped = shard_map(
        body,
        mesh=mesh,
        in_specs=(spec,) * (12 + n_cols),
        out_specs=(spec, spec, spec, (spec,) * (3 + n_cols)),
    )
    return sentinel.SentinelJit("sharded_step_fused", jax.jit(mapped))


@functools.lru_cache(maxsize=None)
def _jitted_sharded_step(params: NeighborParams, mesh: Mesh, events_inline: int):
    shard_map = resolve_shard_map()

    body = functools.partial(_sharded_step, params, events_inline)
    spec = P(SHARD_AXIS)
    mapped = shard_map(
        body,
        mesh=mesh,
        in_specs=(spec,) * 8,
        out_specs=(spec, spec, spec),
    )
    # No donation: no output shares the previous-position buffer's
    # float32 layout, so XLA could never reuse it — donating only produced
    # the "Some donated buffers were not usable" dryrun warning. (The
    # previous meta buffers must not be donated regardless: with
    # meta_dirty=False they are passed as both epochs' meta.)
    return sentinel.SentinelJit("sharded_step", jax.jit(mapped))


@functools.lru_cache(maxsize=None)
def _jitted_sharded_step_pallas(
    params: NeighborParams, mesh: Mesh, events_inline: int, interpret: bool
):
    shard_map = resolve_shard_map()

    body = functools.partial(
        _sharded_step_pallas, params, events_inline, interpret,
        mesh.devices.size,
    )
    spec = P(SHARD_AXIS)
    mapped = shard_map(
        body,
        mesh=mesh,
        in_specs=(spec,) * 8,
        out_specs=(spec,) * 11,
        # pallas_call's out_shape carries no varying-mesh-axes annotation;
        # skip the vma check (outputs are explicitly per-shard here anyway).
        check_vma=False,
    )
    # No donation — same unusable-layout reasoning as _jitted_sharded_step.
    return sentinel.SentinelJit("sharded_step_pallas", jax.jit(mapped))


@functools.lru_cache(maxsize=None)
def _jitted_sharded_drain(
    params: NeighborParams, mesh: Mesh, events_inline: int, chunk: int
):
    shard_map = resolve_shard_map()

    body = functools.partial(_sharded_drain, params, events_inline, chunk)
    spec = P(SHARD_AXIS)
    mapped = shard_map(
        body, mesh=mesh, in_specs=(spec, spec), out_specs=(spec, spec)
    )
    return sentinel.SentinelJit("sharded_drain", jax.jit(mapped))


@functools.lru_cache(maxsize=None)
def _jitted_sharded_drain_bits(
    params: NeighborParams, mesh: Mesh, events_inline: int
):
    shard_map = resolve_shard_map()

    body = functools.partial(_sharded_drain_bits, params, events_inline)
    spec = P(SHARD_AXIS)
    mapped = shard_map(
        body, mesh=mesh, in_specs=(spec,) * 6, out_specs=(spec, spec)
    )
    return sentinel.SentinelJit("sharded_drain_bits", jax.jit(mapped))


class ShardedPendingStep:
    """In-flight sharded tick; ``collect()`` = ONE blocking host read of the
    stacked per-shard packed buffers, then (rare) storm paging."""

    __slots__ = ("_engine", "_enter_ctx", "_leave_ctx", "_out", "_collected",
                 "fused", "rank_paging", "full_repage")

    def __init__(self, engine, enter_ctx, leave_ctx, out) -> None:
        self._engine = engine
        self._enter_ctx = enter_ctx  # per-backend paging payload tuple
        self._leave_ctx = leave_ctx
        self._out = out
        self._collected = False
        # Fused-tick payload (same contract as PendingStep.fused): set by
        # the dispatching engine when the launch carried entity logic.
        self.fused = None
        # Paging cursor semantics of THIS tick's program: rank-based
        # (pallas bit drains) vs flat-index (jnp id drains). Engine-level
        # default; the spatial engine overrides per dispatch — its
        # pallas-backend SPATIAL ticks page by rank while its jnp
        # all-gather FALLBACK ticks page by flat index.
        self.rank_paging = engine.backend != "jnp"
        # In-kernel-drain ticks (parallel/spatial.py, ISSUE 19 leg b) emit
        # inline pairs in cell-major order: a shard whose events overflow
        # the inline budget cannot resume that window by rank — collect()
        # then discards the shard's inline rows and repages it from rank 0
        # through the XLA drain.
        self.full_repage = False
        start_host_copy(out)

    def is_ready(self) -> bool:
        """Non-blocking readiness probe (parity with PendingStep)."""
        try:
            return bool(self._out.is_ready())
        except AttributeError:
            return True

    def wait_device(self) -> None:
        """Block until the sharded step finishes computing (parity with
        PendingStep.wait_device — the aoi.drain latency seam)."""
        jax.block_until_ready(self._out)

    def collect(self) -> tuple[np.ndarray, np.ndarray, int]:
        assert not self._collected, "ShardedPendingStep already collected"
        self._collected = True
        eng = self._engine
        e = eng.events_inline
        nd = eng.n_devices
        # Block layout: 3 header rows, nd replicated-counts rows
        # (multihost paging convergence), e enter pairs, e leave pairs.
        block = 3 + nd + 2 * e
        out = np.asarray(self._out)  # THE round trip
        enters, leaves = [], []
        enter_deficit = np.zeros(nd, np.int64)
        leave_deficit = np.zeros(nd, np.int64)
        enter_starts = np.zeros(nd, np.int32)
        leave_starts = np.zeros(nd, np.int32)
        dropped = 0
        rank_paging = self.rank_paging
        full_repage = self.full_repage
        for d in range(nd):
            o = out[d * block:(d + 1) * block]
            n_e, n_l = int(o[0, 0]), int(o[0, 1])
            dropped = int(o[1, 0])  # replicated diagnostic, same on all
            if full_repage and n_e > e:
                enter_deficit[d] = n_e  # whole shard through the XLA drain
                enter_starts[d] = 0
            else:
                enters.append(o[3 + nd:3 + nd + min(n_e, e)])
                enter_deficit[d] = max(0, n_e - e)
                enter_starts[d] = e if rank_paging else int(o[2, 0]) + 1
            if full_repage and n_l > e:
                leave_deficit[d] = n_l
                leave_starts[d] = 0
            else:
                leaves.append(o[3 + nd + e:3 + nd + e + min(n_l, e)])
                leave_deficit[d] = max(0, n_l - e)
                leave_starts[d] = e if rank_paging else int(o[2, 1]) + 1
        if enter_deficit.any():
            enters += eng._page(self._enter_ctx, enter_deficit, enter_starts)
        if leave_deficit.any():
            leaves += eng._page(self._leave_ctx, leave_deficit, leave_starts)
        eng.last_grid_dropped = dropped
        # Header flags (out[1, 1], replicated): the spatial engines report
        # the seam-free fast-tick bit there; other programs write 0.
        note = getattr(eng, "_note_step_flags", None)
        if note is not None:
            note(int(out[1, 1]))
        return (
            np.concatenate(enters) if enters else np.empty((0, 2), np.int32),
            np.concatenate(leaves) if leaves else np.empty((0, 2), np.int32),
            dropped,
        )


class ShardedNeighborEngine:
    """Multi-device AOI engine: same semantics and event stream as the
    single-device engine, with entity rows sharded over a mesh
    (slot i lives on device i // (N / D)).

    ``backend``: "auto" = the Pallas slab kernel on TPU, the jnp candidate
    math elsewhere; "pallas" / "pallas_interpret" / "jnp" force a path. The
    Pallas path shards the KERNEL GRID (``grid_z / D`` rows per device)
    while inputs stay row-sharded — pod = single-chip kernel × N.
    """

    def __init__(self, params: NeighborParams, mesh: Mesh,
                 backend: str = "auto"):
        if backend == "auto":
            backend = "pallas" if jax.default_backend() == "tpu" else "jnp"
        if backend not in ("jnp", "pallas", "pallas_interpret"):
            raise ValueError(f"unknown backend {backend!r}")
        n_dev = mesh.devices.size
        if params.capacity % (8 * n_dev) != 0:
            raise ValueError(
                f"capacity {params.capacity} must be a multiple of 8*{n_dev}"
            )
        if params.max_events % n_dev != 0:
            raise ValueError(
                f"max_events {params.max_events} must be divisible by {n_dev}"
            )
        if backend != "jnp" and params.grid_z % n_dev != 0:
            raise ValueError(
                f"pallas path needs grid_z {params.grid_z} divisible by "
                f"{n_dev} (one slab of rows per device)"
            )
        self.params = params
        self.mesh = mesh
        self.backend = backend
        self.n_devices = n_dev
        self.chunk = params.capacity // n_dev
        # Inline budget per shard; total inline capacity stays max_events.
        self.events_inline = params.max_events // n_dev
        # Structural comms of one tick: every other shard's rows, both
        # epochs (pos 8B + act 1B + spc 4B + rad 4B each), on D devices.
        self.allgather_bytes_per_tick = (
            n_dev * (params.capacity - self.chunk) * 34
        )
        _M_ALLGATHER_EQUIV.set(self.allgather_bytes_per_tick)
        # Per-link split of the same structural total: each device pulls
        # every OTHER shard's rows (children prebuilt — label lookups
        # stay out of the tick).
        self._link_bytes = (params.capacity - self.chunk) * 34
        self._link_children = tuple(
            _M_LINK_BYTES.labels("ici-allgather", f"dev{d}")
            for d in range(n_dev))
        if backend == "jnp":
            self._jit_step = _jitted_sharded_step(
                params, mesh, self.events_inline
            )
            self._jit_drain = _jitted_sharded_drain(
                params, mesh, self.events_inline, self.chunk
            )
            self._flat_end = self.chunk * 9 * params.cell_capacity
        else:
            self._jit_step = _jitted_sharded_step_pallas(
                params, mesh, self.events_inline, backend == "pallas_interpret"
            )
            self._jit_drain = _jitted_sharded_drain_bits(
                params, mesh, self.events_inline
            )
            self._flat_end = params.capacity * 9 * LANES
        self._sharding = NamedSharding(mesh, P(SHARD_AXIS))
        self._state: tuple | None = None
        self.last_grid_dropped = 0

    def reset(self) -> None:
        n = self.params.capacity
        put = lambda x: jax.device_put(x, self._sharding)  # noqa: E731
        # device_put from NUMPY, never from an intermediate jax array: a jax
        # array can carry a sharding over the same device set in a different
        # order, which trips jax's different-device-order reshard path
        # (dispatch.py _different_device_order_reshard asserts NamedSharding).
        self._state = (
            put(np.zeros((n, 2), np.float32)),
            put(np.zeros((n,), bool)),
            put(np.zeros((n,), np.int32)),
            put(np.zeros((n,), np.float32)),
        )

    def carried_epoch(self) -> tuple:
        """Last dispatched world in slot space (rows == slots here);
        see NeighborEngine.carried_epoch."""
        assert self._state is not None, "call reset() first"
        return tuple(np.asarray(a) for a in self._state[0:4])

    def _page(
        self, ctx: tuple, deficit: np.ndarray, starts: np.ndarray
    ) -> list[np.ndarray]:
        """Per-shard chunked drain for events beyond the inline budget."""
        chunks: list[np.ndarray] = []
        starts = starts.copy()
        deficit = deficit.copy()
        rank_paging = self.backend != "jnp"
        while deficit.any():
            pairs, aux = self._jit_drain(
                *ctx, jax.device_put(np.asarray(starts, np.int32), self._sharding)
            )
            pairs = np.asarray(pairs)
            aux = np.asarray(aux)
            e = self.events_inline
            for d in range(self.n_devices):
                take = int(min(e, deficit[d]))
                if take <= 0:
                    continue
                chunks.append(pairs[d * e:d * e + take])
                deficit[d] -= take
                if deficit[d] > 0:
                    starts[d] = (
                        starts[d] + take if rank_paging else aux[d, take - 1] + 1
                    )
                else:
                    starts[d] = self._flat_end
        return chunks

    def step_async(
        self,
        pos: np.ndarray,
        active: np.ndarray,
        space: np.ndarray,
        radius: np.ndarray,
        meta_dirty: bool = True,
    ) -> ShardedPendingStep:
        """Dispatch one tick without blocking (parity with NeighborEngine,
        including the ``meta_dirty=False`` upload-elision contract)."""
        assert self._state is not None, "call reset() first"
        check_radius(self.params, radius, active)
        if self.backend != "jnp":
            check_space_ids(space, active)
        put = lambda x: jax.device_put(x, self._sharding)  # noqa: E731
        # np.array (copying, not asarray): state must not alias caller
        # buffers — see NeighborEngine.step_async. Numpy (not jnp) inputs by
        # design: see reset().
        if meta_dirty:
            meta = (
                put(np.array(active, bool)),
                put(np.array(space, np.int32)),
                put(np.array(radius, np.float32)),
            )
        else:
            meta = self._state[1:4]
        cur = (put(np.array(pos, np.float32)),) + meta
        if self.backend == "jnp":
            enter_ids, leave_ids, out = self._jit_step(*self._state, *cur)
            enter_ctx: tuple = (enter_ids,)
            leave_ctx: tuple = (leave_ids,)
        else:
            res = self._jit_step(*self._state, *cur)
            enter_ctx, leave_ctx, out = res[0:5], res[5:10], res[10]
        self._state = cur
        _M_ALLGATHER_TOTAL.inc(self.allgather_bytes_per_tick)
        for child in self._link_children:
            child.inc(self._link_bytes)
        return ShardedPendingStep(self, enter_ctx, leave_ctx, out)

    def step(
        self,
        pos: np.ndarray,
        active: np.ndarray,
        space: np.ndarray,
        radius: np.ndarray,
    ) -> tuple[np.ndarray, np.ndarray, int]:
        """Run one tick; returns host (enter_pairs, leave_pairs, dropped)."""
        return self.step_async(pos, active, space, radius).collect()

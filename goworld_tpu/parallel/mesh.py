"""Entity-sharded AOI over a device mesh.

The reference scales by sharding entities/spaces across game processes, with
no cross-process AOI at all (SURVEY.md §5.7: AOI is strictly per-Space,
per-game). The TPU-native design goes further: entity slots are sharded over
a mesh axis; each tick, **positions are all-gathered over ICI** so every
device sees the whole world, then each device computes neighbor sets and
enter/leave diffs only for the slots it owns. This is the "sequence
parallelism" of this domain (BASELINE.json config 5: 1M entities, 8 game
processes → v5e-16 pod).

Communication per tick = one all-gather of [N, 2] f32 positions + [N] masks
(~1 MB at 100k entities) — rides ICI, far below its bandwidth. Grid build is
replicated per device (cheap: one sort of N keys); the O(N·9M) candidate math
— the actual FLOPs — is perfectly sharded.

Collectives are XLA's (all_gather inside shard_map); there is no NCCL/MPI
analog to port — the reference's TCP star stays the control plane
(SURVEY.md §5.8).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from goworld_tpu.ops.neighbor import (
    MatrixStepResult,
    NeighborParams,
    _bucket_of,
    _build_grid,
    _jitted_drain,
    _neighbor_sets,
    _row_membership,
)

SHARD_AXIS = "shard"


def make_mesh(n_devices: int | None = None, devices: list | None = None) -> Mesh:
    """Build a 1-D mesh over the entity-shard axis.

    Prefers explicitly passed devices; otherwise takes the first n of
    jax.devices(). For CPU-hosted multi-device testing, set
    ``--xla_force_host_platform_device_count`` (tests/conftest.py does).
    """
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            if len(devices) < n_devices:
                # Fall back to virtual CPU devices when the default platform
                # has too few chips (e.g. one real TPU during development).
                cpu = jax.devices("cpu")
                if len(cpu) >= n_devices:
                    devices = cpu
                else:
                    raise ValueError(
                        f"need {n_devices} devices, have {len(devices)} "
                        f"{devices[0].platform} and {len(cpu)} cpu"
                    )
            devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (SHARD_AXIS,))


def _sharded_step(
    p: NeighborParams,
    prev_nb: jax.Array,  # i32[chunk, K] this shard's previous neighbor lists
    pos_l: jax.Array,  # f32[chunk, 2] this shard's positions
    active_l: jax.Array,
    space_l: jax.Array,
    radius_l: jax.Array,
) -> MatrixStepResult:
    """Per-shard body run under shard_map."""
    n = p.capacity
    chunk = pos_l.shape[0]
    shard = jax.lax.axis_index(SHARD_AXIS)
    q_ids = shard * chunk + jnp.arange(chunk, dtype=jnp.int32)

    # ICI all-gather: full world view on every device.
    pos = jax.lax.all_gather(pos_l, SHARD_AXIS, tiled=True)  # [N, 2]
    active = jax.lax.all_gather(active_l, SHARD_AXIS, tiled=True)
    space = jax.lax.all_gather(space_l, SHARD_AXIS, tiled=True)

    cx = jnp.floor(pos[:, 0] / p.cell_size).astype(jnp.int32)
    cz = jnp.floor(pos[:, 1] / p.cell_size).astype(jnp.int32)
    bucket = _bucket_of(p, cx, cz, space)
    grid, grid_dropped = _build_grid(p, bucket, active)

    neighbors, overflow = _neighbor_sets(
        p, grid, pos, active, space, q_ids, pos_l, active_l, space_l, radius_l
    )

    entered = ~_row_membership(prev_nb, neighbors, n) & (neighbors < n)
    left = ~_row_membership(neighbors, prev_nb, n) & (prev_nb < n)

    # Event matrices with global ids in non-event slots = sentinel n; the host
    # drains them in chunks exactly like the single-device engine (the [N, K]
    # event matrices are sharded on rows, so flat indices stay global).
    enter_ids = jnp.where(entered, neighbors, n)
    leave_ids = jnp.where(left, prev_nb, n)
    n_enters = jnp.sum(entered).astype(jnp.int32)
    n_leaves = jnp.sum(left).astype(jnp.int32)
    # grid_dropped is identical on every shard (computed from the all-gathered
    # world); divide after psum-free sum on host instead of psumming here.
    return MatrixStepResult(
        neighbors,
        enter_ids,
        leave_ids,
        n_enters[None],
        n_leaves[None],
        overflow[None],
        grid_dropped[None],
    )


@functools.lru_cache(maxsize=None)
def _jitted_sharded_step(params: NeighborParams, mesh: Mesh):
    from jax import shard_map

    body = functools.partial(_sharded_step, params)
    spec = P(SHARD_AXIS)
    mapped = shard_map(
        body,
        mesh=mesh,
        in_specs=(spec, spec, spec, spec, spec),
        out_specs=MatrixStepResult(
            neighbors=spec,
            enter_ids=spec,
            leave_ids=spec,
            n_enters=spec,
            n_leaves=spec,
            overflow=spec,
            grid_dropped=spec,
        ),
    )
    return jax.jit(mapped, donate_argnums=(0,))


class ShardedNeighborEngine:
    """Multi-device AOI engine: same semantics as NeighborEngine, with entity
    slots sharded over a mesh. Slot i lives on device i // (N / D).

    Event results come back as D per-shard blocks; ``step`` flattens them.
    """

    def __init__(self, params: NeighborParams, mesh: Mesh):
        n_dev = mesh.devices.size
        if params.capacity % (8 * n_dev) != 0:
            raise ValueError(
                f"capacity {params.capacity} must be a multiple of 8*{n_dev}"
            )
        self.params = params
        self.mesh = mesh
        self.n_devices = n_dev
        self._jit_step = _jitted_sharded_step(params, mesh)
        self._jit_drain = _jitted_drain(params)
        self._sharding = NamedSharding(mesh, P(SHARD_AXIS))
        self._neighbors: jax.Array | None = None

    def reset(self) -> None:
        n, k = self.params.capacity, self.params.max_neighbors
        self._neighbors = jax.device_put(
            jnp.full((n, k), n, dtype=jnp.int32), self._sharding
        )

    def step_device(self, pos, active, space, radius) -> MatrixStepResult:
        assert self._neighbors is not None, "call reset() first"
        put = lambda x: jax.device_put(x, self._sharding)  # noqa: E731
        res = self._jit_step(
            self._neighbors, put(pos), put(active), put(space), put(radius)
        )
        self._neighbors = res.neighbors
        return res

    def _drain_all(self, ids: jax.Array, total: int) -> np.ndarray:
        """Chunked event drain, identical semantics to NeighborEngine: the
        [N, K] event matrix is row-sharded, so global flat indices page
        through all shards in order."""
        if total == 0:
            return np.empty((0, 2), np.int32)
        chunks = []
        start = jnp.int32(0)
        remaining = total
        while remaining > 0:
            pairs, idx = self._jit_drain(ids, start)
            take = min(self.params.max_events, remaining)
            chunks.append(np.asarray(pairs[:take]))
            remaining -= take
            if remaining > 0:
                start = idx[take - 1] + 1
        return np.concatenate(chunks)

    def step(
        self,
        pos: np.ndarray,
        active: np.ndarray,
        space: np.ndarray,
        radius: np.ndarray,
    ) -> tuple[np.ndarray, np.ndarray, int]:
        """Run one tick; returns host (enter_pairs, leave_pairs, overflow)."""
        from goworld_tpu.ops.neighbor import check_radius

        check_radius(self.params, radius, active)
        res = self.step_device(
            jnp.asarray(pos, jnp.float32),
            jnp.asarray(active, jnp.bool_),
            jnp.asarray(space, jnp.int32),
            jnp.asarray(radius, jnp.float32),
        )
        n_e = int(np.sum(np.asarray(res.n_enters)))
        n_l = int(np.sum(np.asarray(res.n_leaves)))
        enters = self._drain_all(res.enter_ids, n_e)
        leaves = self._drain_all(res.leave_ids, n_l)
        return enters, leaves, int(np.sum(np.asarray(res.overflow)))

"""shard_map resolution across jax versions.

``parallel/mesh.py`` was written against ``jax.shard_map`` (the stable
export, jax >= 0.6); this image ships jax 0.4.37, which only exports it as
``jax.experimental.shard_map.shard_map`` — and with the older
``check_rep`` spelling of the varying-manual-axes check that newer jax
calls ``check_vma``. Resolving here (ONE place) is what turns the whole
``parallel`` package plus its 7 tier-1 tests from a module-level skip into
running code on this image.
"""

from __future__ import annotations

import functools
import inspect


@functools.lru_cache(maxsize=1)
def _resolved():
    try:
        import jax

        fn = getattr(jax, "shard_map", None)
        if fn is None:
            from jax.experimental.shard_map import shard_map as fn
    except ImportError:
        return None, frozenset()
    return fn, frozenset(inspect.signature(fn).parameters)


def shard_map_available() -> bool:
    """True when SOME shard_map exists (stable or experimental) — the
    tests' module-level guard (tests/test_parallel.py) asks this instead
    of hasattr(jax, "shard_map")."""
    return _resolved()[0] is not None


def resolve_shard_map():
    """The callable ``shard_map(f, mesh=, in_specs=, out_specs=,
    check_vma=)`` with the varying-axes-check kwarg adapted to whatever
    this jax build spells it (``check_vma`` new, ``check_rep`` old;
    dropped entirely if neither exists)."""
    fn, params = _resolved()
    if fn is None:
        raise ImportError(
            "no shard_map in this jax build (neither jax.shard_map nor "
            "jax.experimental.shard_map)"
        )

    def shard_map(f, mesh, in_specs, out_specs, check_vma=None):
        kw = {}
        if check_vma is not None:
            if "check_vma" in params:
                kw["check_vma"] = check_vma
            elif "check_rep" in params:
                kw["check_rep"] = check_vma
        return fn(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)

    return shard_map

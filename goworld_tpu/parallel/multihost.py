"""Multi-HOST (multi-process) sharded AOI — the DCN tier of the scaling
story.

Single-host scaling shards entity rows over one process's devices
(parallel/mesh.py — the ICI tier). This module runs the SAME shard_map
step across multiple jax processes (multi-controller SPMD): each host
contributes its local devices to one global mesh, owns the entity rows
sharded onto them, uploads only its local slab, and reads back only the
events of entities it owns. The all-gather inside the step then rides ICI
within a host and DCN between hosts — exactly how a v5e multi-host pod
runs, and the data-plane analog of the reference's one-process-per-game
TCP fabric (SURVEY.md §5.8: NCCL/MPI's slot is XLA collectives).

Multi-controller rules this module encodes:

- Global arrays are built with ``jax.make_array_from_process_local_data``
  (a process cannot device_put onto non-addressable devices).
- EVERY process must dispatch every global computation. Storm paging
  loops are therefore driven by the REPLICATED per-shard counts that the
  step all-gathers into each output block (mesh.py) — all processes see
  every shard's deficit and dispatch the same number of drain calls,
  each keeping only its own shards' pairs.
- ``collect()`` reads only addressable shards: a host receives exactly
  the events of the entity rows it owns (its games'), which is the
  delivery each game process wants anyway.

Bootstrap: call :func:`init_multihost` (a thin jax.distributed wrapper)
before any jax use, then build the engine on every process with the same
params. Tested by spawning real OS processes over the Gloo CPU backend
(tests/test_multihost.py) — the localhost analog of a multi-host pod,
mirroring how the reference CI tests its multi-process cluster.

shard_map itself resolves through parallel/compat.py (stable
``jax.shard_map`` or the experimental export, whichever this jax build
has) via the jitted step/drain builders shared with parallel/mesh.py —
this module constructs on jax 0.4.x images too. The spatially sharded
engine (parallel/spatial.py) is single-controller only for now: its
host-side strip planner assumes one process owns the whole slot space.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from goworld_tpu.ops.neighbor import (
    NeighborParams,
    check_radius,
    check_space_ids,
)
from goworld_tpu.parallel.mesh import (
    SHARD_AXIS,
    _M_ALLGATHER_EQUIV,
    _M_ALLGATHER_TOTAL,
    _M_LINK_BYTES,
    _jitted_sharded_drain,
    _jitted_sharded_drain_bits,
    _jitted_sharded_step,
    _jitted_sharded_step_pallas,
    make_mesh,
    start_host_copy,
)


def init_multihost(
    coordinator_address: str, num_processes: int, process_id: int
) -> None:
    """Join the multi-controller runtime (call before ANY jax use).

    On CPU test rigs combine with ``--xla_force_host_platform_device_count``
    for several local devices per process; on TPU pods the plugin provides
    the topology and this reduces to jax.distributed.initialize.
    """
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


class MultiHostPendingStep:
    """In-flight multi-host tick: collect() reads only LOCAL shards."""

    __slots__ = ("_engine", "_enter_ctx", "_leave_ctx", "_out", "_collected")

    def __init__(self, engine, enter_ctx, leave_ctx, out) -> None:
        self._engine = engine
        self._enter_ctx = enter_ctx  # per-backend paging payload tuple
        self._leave_ctx = leave_ctx
        self._out = out
        self._collected = False
        start_host_copy(out)

    def is_ready(self) -> bool:
        try:
            return bool(self._out.is_ready())
        except AttributeError:
            return True

    def wait_device(self) -> None:
        """Block until the local shard's step finishes computing (parity
        with PendingStep.wait_device — the aoi.drain latency seam)."""
        jax.block_until_ready(self._out)

    def collect(self) -> tuple[np.ndarray, np.ndarray, int]:
        """(local_enters, local_leaves, dropped): pairs whose ENTITY side
        lives on this process (global ids)."""
        assert not self._collected, "already collected"
        self._collected = True
        eng = self._engine
        e = eng.events_inline
        nd = eng.n_devices
        block = 3 + nd + 2 * e
        # Local shards only — the only addressable data in multi-controller.
        shards = sorted(
            self._out.addressable_shards, key=lambda s: s.index[0].start
        )
        local = {
            s.index[0].start // block: np.asarray(s.data) for s in shards
        }
        counts_all = next(iter(local.values()))[3:3 + nd]  # replicated
        enters, leaves = [], []
        dropped = 0
        for d, o in local.items():
            n_e, n_l = int(o[0, 0]), int(o[0, 1])
            dropped = int(o[1, 0])
            enters.append(o[3 + nd:3 + nd + min(n_e, e)])
            leaves.append(o[3 + nd + e:3 + nd + e + min(n_l, e)])
        # Storm paging: loop counts derive from the REPLICATED counts, so
        # every process dispatches the same global drain sequence and then
        # keeps only its local shards' chunks.
        rank_paging = eng.backend != "jnp"
        for which, ctx, bucket in (
            ("enter", self._enter_ctx, enters),
            ("leave", self._leave_ctx, leaves),
        ):
            col = 0 if which == "enter" else 1
            deficit = np.maximum(
                0, counts_all[:, col].astype(np.int64) - e
            )
            # jnp-path paging resumes AFTER the last drained flat position
            # (per-shard data, read from the local header); the pallas path
            # pages by event RANK — a globally known cursor.
            local_starts = {
                d: (e if rank_paging else int(o[2, col]) + 1)
                for d, o in local.items()
            }
            rounds = int(np.ceil(deficit / e).max()) if deficit.any() else 0
            cursor = np.zeros(nd, np.int64)
            for _ in range(rounds):
                start_global = eng._make_starts(local_starts)
                pairs, aux = eng._jit_drain(*ctx, start_global)
                for s in sorted(
                    pairs.addressable_shards,
                    key=lambda s: s.index[0].start,
                ):
                    d = s.index[0].start // e
                    take = int(min(e, deficit[d] - cursor[d]))
                    if take > 0:
                        arr = np.asarray(s.data)
                        bucket.append(arr[:take])
                for s in aux.addressable_shards:
                    d = s.index[0].start  # aux is [D, E] (jnp) / [D, 1]
                    taken = int(min(e, max(0, deficit[d] - cursor[d])))
                    if taken > 0:
                        local_starts[d] = (
                            local_starts[d] + taken if rank_paging
                            else int(np.asarray(s.data)[0, taken - 1]) + 1
                        )
                cursor += np.minimum(e, np.maximum(0, deficit - cursor))
        eng.last_grid_dropped = dropped
        return (
            np.concatenate(enters) if enters else np.empty((0, 2), np.int32),
            np.concatenate(leaves) if leaves else np.empty((0, 2), np.int32),
            dropped,
        )


class MultiHostNeighborEngine:
    """Per-process handle on the cross-host engine.

    Every process constructs it with identical params over the same global
    mesh and steps it with its LOCAL entity rows — rows
    [process_lo, process_lo + local_capacity). ``backend``: "jnp" (CPU
    rigs), "pallas" (TPU pods — grid-row kernel slabs per device, as in
    ShardedNeighborEngine), or "pallas_interpret" (tests).
    """

    def __init__(self, params: NeighborParams, mesh: Mesh | None = None,
                 backend: str = "jnp"):
        if mesh is None:
            mesh = make_mesh()  # ALL global devices
        if backend not in ("jnp", "pallas", "pallas_interpret"):
            raise ValueError(f"unknown backend {backend!r}")
        n_dev = mesh.devices.size
        if params.capacity % (8 * n_dev) != 0:
            raise ValueError(
                f"capacity {params.capacity} must be a multiple of 8*{n_dev}"
            )
        if params.max_events % n_dev != 0:
            raise ValueError(
                f"max_events {params.max_events} must be divisible by {n_dev}"
            )
        if backend != "jnp" and params.grid_z % n_dev != 0:
            raise ValueError(
                f"pallas path needs grid_z {params.grid_z} divisible by "
                f"{n_dev} (one slab of rows per device)"
            )
        self.params = params
        self.mesh = mesh
        self.backend = backend
        self.n_devices = n_dev
        self.chunk = params.capacity // n_dev
        self.events_inline = params.max_events // n_dev
        # Transfer accounting (ISSUE 15 satellite): the DCN tier pays the
        # same structural all-gather as the single-host entity tier —
        # rode ICI within a host, DCN between hosts. Live on /metrics so
        # the pod-scale comms story is visible beside the spatial tier's
        # halo gauges. The strip+halo Pallas path stays single-controller
        # (parallel/spatial.py owns the whole slot space host-side); its
        # pallas kernels here still ride the shared slab-kernel builders.
        self.allgather_bytes_per_tick = (
            n_dev * (params.capacity - self.chunk) * 34
        )
        _M_ALLGATHER_EQUIV.set(self.allgather_bytes_per_tick)
        if backend == "jnp":
            self._jit_step = _jitted_sharded_step(
                params, mesh, self.events_inline
            )
            self._jit_drain = _jitted_sharded_drain(
                params, mesh, self.events_inline, self.chunk
            )
        else:
            self._jit_step = _jitted_sharded_step_pallas(
                params, mesh, self.events_inline,
                backend == "pallas_interpret",
            )
            self._jit_drain = _jitted_sharded_drain_bits(
                params, mesh, self.events_inline
            )
        self._sharding = NamedSharding(mesh, P(SHARD_AXIS))
        self._starts_sharding = NamedSharding(mesh, P(SHARD_AXIS))
        # This process's slice of the entity-row space.
        local_dev = set(jax.local_devices())
        mesh_list = list(mesh.devices.reshape(-1))
        owned = [i for i, d in enumerate(mesh_list) if d in local_dev]
        if owned != list(range(owned[0], owned[0] + len(owned))):
            raise ValueError(
                "local devices must be contiguous in the mesh; build the "
                "mesh from jax.devices() order"
            )
        self.local_lo = owned[0] * self.chunk
        self.local_capacity = len(owned) * self.chunk
        self._state: tuple | None = None
        self.last_grid_dropped = 0
        # Per-link split of THIS process's slice of the all-gather: local
        # devices pull each other's rows over ICI and every remote
        # shard's rows over DCN (ROADMAP item 5 — the two link tiers of
        # a pod, attributable per host after the fact).
        n_local = len(owned)
        host = f"host{jax.process_index()}"
        self._ici_bytes = n_local * (n_local - 1) * self.chunk * 34
        self._dcn_bytes = n_local * (n_dev - n_local) * self.chunk * 34
        self._m_link_ici = _M_LINK_BYTES.labels("ici-allgather", host)
        self._m_link_dcn = _M_LINK_BYTES.labels("dcn-allgather", host)

    # --- multi-controller array builders ------------------------------------

    def _put(self, local_np: np.ndarray) -> jax.Array:
        gshape = (self.params.capacity,) + local_np.shape[1:]
        return jax.make_array_from_process_local_data(
            self._sharding, np.ascontiguousarray(local_np), gshape
        )

    def _make_starts(self, local_starts: dict[int, int]) -> jax.Array:
        local = np.array(
            [
                local_starts.get(d, 0)
                for d in sorted(local_starts)
            ],
            np.int32,
        )
        return jax.make_array_from_process_local_data(
            self._starts_sharding, local, (self.n_devices,)
        )

    def reset(self) -> None:
        lc = self.local_capacity
        self._state = (
            self._put(np.zeros((lc, 2), np.float32)),
            self._put(np.zeros((lc,), bool)),
            self._put(np.zeros((lc,), np.int32)),
            self._put(np.zeros((lc,), np.float32)),
        )

    def step_async(
        self,
        pos: np.ndarray,
        active: np.ndarray,
        space: np.ndarray,
        radius: np.ndarray,
        meta_dirty: bool = True,
    ) -> MultiHostPendingStep:
        """Dispatch one tick with this process's LOCAL rows
        ([local_capacity, ...] arrays)."""
        assert self._state is not None, "call reset() first"
        assert len(pos) == self.local_capacity, (
            f"pass LOCAL rows ({self.local_capacity}), got {len(pos)}"
        )
        check_radius(self.params, radius, active)
        if self.backend != "jnp":
            check_space_ids(space, active)
        if meta_dirty:
            meta = (
                self._put(np.array(active, bool)),
                self._put(np.array(space, np.int32)),
                self._put(np.array(radius, np.float32)),
            )
        else:
            meta = self._state[1:4]
        cur = (self._put(np.array(pos, np.float32)),) + meta
        if self.backend == "jnp":
            # Entity-row sharding: a process's local events are exactly
            # its own entities' events.
            enter_ids, leave_ids, out = self._jit_step(*self._state, *cur)
            enter_ctx: tuple = (enter_ids,)
            leave_ctx: tuple = (leave_ids,)
        else:
            # Grid-row (SPATIAL) sharding: each device emits the events of
            # entities binned in ITS slab — every event exactly once, but
            # a process receives events by CELL ownership, not row
            # ownership (spatial partitioning; route or re-shard if row
            # ownership is required).
            res = self._jit_step(*self._state, *cur)
            enter_ctx, leave_ctx, out = res[0:5], res[5:10], res[10]
        self._state = cur
        _M_ALLGATHER_TOTAL.inc(self.allgather_bytes_per_tick)
        if self._ici_bytes:
            self._m_link_ici.inc(self._ici_bytes)
        if self._dcn_bytes:
            self._m_link_dcn.inc(self._dcn_bytes)
        return MultiHostPendingStep(self, enter_ctx, leave_ctx, out)

    def step(self, pos, active, space, radius):
        return self.step_async(pos, active, space, radius).collect()

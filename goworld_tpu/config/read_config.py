"""INI configuration shared by every process in a deployment.

Reference parity: ``engine/config/read_config.go`` — one ``goworld.ini`` read
by dispatchers, gates, games and the CLI. Sections (read_config.go:239-314):

- ``[deployment]``: desired process counts — also the readiness barrier
  (DispatcherService.go:446-476).
- ``[dispatcherN]`` / ``[gameN]`` / ``[gateN]``: per-process sections, each
  inheriting defaults from ``[dispatcher_common]`` / ``[game_common]`` /
  ``[gate_common]`` (read_config.go:316-470).
- ``[storage]``, ``[kvdb]``, ``[debug]``.

TPU addition: ``[aoi]`` configures the compute plane (backend, capacities,
device mesh axis sizes) — no reference analog.
"""

from __future__ import annotations

import configparser
import dataclasses
import threading
from typing import Optional

DEFAULT_CONFIG_FILES = ("goworld.ini",)


@dataclasses.dataclass
class DeploymentConfig:
    desired_games: int = 1
    desired_gates: int = 1
    desired_dispatchers: int = 1


@dataclasses.dataclass
class DispatcherConfig:
    host: str = "127.0.0.1"
    port: int = 0
    http_addr: str = ""
    log_file: str = ""
    log_level: str = "info"

    @property
    def addr(self) -> tuple[str, int]:
        return (self.host, self.port)


@dataclasses.dataclass
class GameConfig:
    boot_entity: str = ""
    save_interval: float = 300.0
    http_addr: str = ""
    log_file: str = ""
    log_level: str = "info"
    position_sync_interval: float = 0.1  # server→client cadence (read_config.go:328)
    # Per-game override of [aoi] platform ("" = inherit): on single-client
    # TPU transports exactly ONE game process may hold the chip — set
    # aoi_platform=tpu on that game and cpu on the rest.
    aoi_platform: str = ""


@dataclasses.dataclass
class GateConfig:
    host: str = "127.0.0.1"
    port: int = 0
    ws_addr: str = ""  # websocket listen addr ("host:port" or "")
    http_addr: str = ""
    log_file: str = ""
    log_level: str = "info"
    compress_connection: bool = False
    # Codec when compress_connection is on. "snappy" fills the slot the
    # reference fills with snappy (ClientProxy.go:42-45), but the WIRE
    # deliberately diverges: the reference wraps the whole connection in
    # snappy STREAM framing, while this engine compresses each packet
    # independently with the snappy BLOCK format, selected per packet by a
    # length-prefix flag bit (netutil/packet_conn.py) — so enabling is
    # one-sided safe and tiny packets skip the codec. Both in-repo ends
    # match; reference Go clients would NOT interoperate on this wire.
    # zlib retained as an option.
    compress_format: str = "snappy"  # snappy | zlib
    # Reliable-UDP wire protocol beside TCP: "kcp" = the real KCP segment
    # protocol (reference parity, GateService.go:134-165 via kcp-go;
    # netutil/kcp.py); "native" = the in-repo ARQ (netutil/rudp.py).
    rudp_protocol: str = "kcp"  # kcp | native
    # FEC shards for the kcp protocol ("data,parity"; "off" disables).
    # 10,3 is the reference's exact dial shape (ListenWithOptions(addr,
    # nil, 10, 3)): every 10 data datagrams carry 3 Reed-Solomon parity
    # datagrams so lost packets reconstruct without a retransmit RTT.
    # Clients must match (netutil/fec.py).
    rudp_fec: str = "10,3"
    encrypt_connection: bool = False
    rsa_key: str = ""
    rsa_cert: str = ""
    heartbeat_timeout: float = 30.0
    position_sync_interval: float = 0.1  # client→server coalescing cadence

    @property
    def addr(self) -> tuple[str, int]:
        return (self.host, self.port)


@dataclasses.dataclass
class ClusterConfig:
    """Game/gate↔dispatcher link resilience knobs (``[cluster]``; defaults
    mirror consts.py — no reference analog: GoWorld drops packets to down
    dispatchers and reconnects on a fixed 1 s interval)."""

    # Byte cap of the per-link replay ring buffering sends while a
    # dispatcher link is down (0 = legacy drop-on-down).
    down_buffer_bytes: int = 2 * 1024 * 1024
    # Close links silent past this many seconds (HEARTBEAT msgtype sent on
    # idle links every timeout/3 by both ends); 0 disables liveness kills.
    peer_heartbeat_timeout: float = 10.0
    # Default deadline of ClusterClient.wait_connected().
    wait_connected_timeout: float = 10.0
    # Reconnect backoff ceiling (base is consts.RECONNECT_INTERVAL;
    # delays are full-jittered).
    reconnect_max_interval: float = 15.0
    # Cluster-link transport: "tcp" (default) or "uds" — Unix-domain
    # game↔dispatcher↔gate sockets for co-located single-host deploys
    # (same framing/heartbeats/replay rings; dispatchers serve BOTH
    # listeners, games/gates dial the socket path derived from each
    # dispatcher's configured port — dispatchercluster.cluster.uds_path_for).
    transport: str = "tcp"
    # Directory holding the uds socket files ("" = system temp dir; keep
    # it short — sun_path caps at ~108 bytes).
    uds_dir: str = ""
    # Size trigger for position-sync aggregation buffers (dispatcher
    # per-game, gate per-dispatcher): flush immediately once a buffer
    # reaches this many bytes instead of sitting out the tick/sync
    # interval. 0 disables the trigger (tick-interval flush only).
    sync_flush_bytes: int = 32 * 1024


@dataclasses.dataclass
class StorageConfig:
    type: str = "filesystem"
    directory: str = "_entity_storage"  # filesystem backend
    url: str = ""  # network backends
    db: str = "goworld"
    # redis_cluster seed nodes, from ``start_nodes_N = host:port`` keys
    # (reference read_config.go:492-493).
    start_nodes: list = dataclasses.field(default_factory=list)
    # Save-retry / circuit-breaker knobs (storage/__init__.py): retries
    # back off retry_base_interval → retry_max_interval (doubling); after
    # circuit_failure_threshold consecutive failures the circuit opens and
    # saves defer into a deferred_bytes_cap-bounded queue until a
    # half-open probe (after circuit_cooldown seconds) succeeds.
    retry_base_interval: float = 1.0
    retry_max_interval: float = 30.0
    circuit_failure_threshold: int = 5
    circuit_cooldown: float = 5.0
    deferred_bytes_cap: int = 8 * 1024 * 1024


@dataclasses.dataclass
class KVDBConfig:
    type: str = "filesystem"
    directory: str = "_kvdb"
    url: str = ""
    db: str = "goworld"
    collection: str = "kvdb"
    start_nodes: list = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class AOIConfig:
    """TPU compute-plane knobs (no reference analog; see SURVEY.md §7)."""

    backend: str = "auto"  # auto | xzlist | tpu
    # JAX platform for the batched engine: "auto" keeps jax's default
    # (the TPU when one is attached). MUST be "cpu" for CPU-only deploys on
    # TPU-image hosts: the TPU plugin ignores the JAX_PLATFORMS env var, so
    # a game process would otherwise silently grab the chip (and on
    # single-client transports, fight other processes for it).
    platform: str = "auto"  # auto | cpu | tpu
    cell_capacity: int = 64
    max_entities: int = 16384  # padded capacity of the batched engine
    mesh_shards: int = 1  # device shards of the batched engine's mesh
    # How mesh_shards > 1 splits the work: "spatial" shards the AOI grid
    # into column strips with halo exchange (O(boundary) comms,
    # parallel/spatial.py; on TPU the strip-local Pallas kernel tier);
    # "entity" shards entity rows with a full all-gather per tick
    # (parallel/mesh.py).
    shard_mode: str = "spatial"  # spatial | entity
    # Strip→device placement of the spatial tier: "topology" reorders the
    # mesh from device coords so ring-adjacent strips land on
    # interconnect-adjacent chips (AoiZora-style; identity on rigs
    # without coords), "ring" keeps the mesh order as given.
    strip_placement: str = "topology"  # topology | ring
    # Static strip-width cap (columns) of the Pallas spatial tier's
    # kernel slab. 0 = derive (2x the uniform strip width, clamped to
    # planner feasibility). Ignored by the jnp spatial backend.
    pallas_strip_cols: int = 0
    # In-kernel event drain of the Pallas spatial tier: the kernel launch
    # itself emits the compacted (slot, slot) event pairs through SMEM
    # cursors, so a steady strip tick needs no XLA rank-select pass.
    # Overflowing ticks repage wholly through the XLA drain (exact).
    # Ignored by the jnp spatial backend.
    pallas_inkernel_drain: bool = True
    # Grid geometry (0 = derive from max_entities; see params_from_config).
    grid: int = 0  # cells per side (grid_x = grid_z)
    cell_size: float = 0.0  # cell side length; must be >= max AOI distance
    space_slots: int = 0  # space-id folding slots
    # Multi-HOST (DCN) tier: every game process joins ONE jax.distributed
    # mesh and the AOI step runs as multi-controller SPMD across them
    # (parallel/multihost.py). Set the coordinator to "host:port" (served
    # by the first game); processes defaults to the number of games. The
    # AOI tick then runs in LOCKSTEP at the fixed position_sync_interval
    # cadence on every game (collectives require every process to dispatch
    # the same op sequence). Mutually exclusive with mesh_shards > 1.
    multihost_coordinator: str = ""  # "" = disabled
    multihost_processes: int = 0  # 0 = len(games)
    # Persistent XLA compilation cache for the batched engine's jits:
    # "auto" = <process cwd>/.goworld_jax_cache (the cwd already hosts
    # freeze files), "off" = disabled, anything else = explicit dir. The
    # point is the RESPAWN path: a freeze->restore restart re-compiles
    # every step jit from scratch (~4-6 s on a small host) inside the
    # 5 s RPC window buffered clients are waiting out; with the cache the
    # restored process LOADS the executables instead (measured 6.0 s ->
    # 2.5 s boot-to-warm on the verify rig).
    compilation_cache: str = "auto"  # auto | off | <dir>
    # Delivery model of the batched engine: "pipelined" (default — diffs
    # land one game tick late, the loop never stalls on device compute) or
    # "sync" (diffs land the same tick, within one readback of the step
    # completing — the p99 < 5 ms axis — at the cost of the logic loop
    # stalling for the step's device time every AOI tick). xzlist is
    # inherently synchronous and ignores this.
    delivery: str = "pipelined"  # pipelined | sync
    # Sync-mode stall ceiling (seconds): how long one AOI tick may block
    # the logic loop waiting for the device before the step is parked for
    # deferred (pipelined-style) delivery and aoi_sync_degrade_total
    # increments. Sub-second by default so a slow/wedged device degrades
    # to one-tick-late diffs instead of freezing every RPC (the old
    # hardcoded bound was 30 s — VERDICT r5 weak #5). Ignored unless
    # delivery = sync.
    sync_wait_budget: float = 0.5
    # Fuse per-class columnar tick programs (entity/columns.columnar_tick
    # / vmapped_position_tick) INTO the batched engine's step launch:
    # steady-state ticks then run move + entity logic + neighbor interest
    # as ONE device launch, logic riding the AOI cadence with its outputs
    # written back at the next dispatch. Classes with hand-written
    # on_tick_batch bodies — and the entity-sharded/multihost engine
    # tiers — automatically stay host-side. Ignored by xzlist.
    fuse_logic: bool = False


@dataclasses.dataclass
class EntityConfig:
    """Columnar entity-slab knobs (``[entity]``; entity/slabs.py)."""

    # Initial slot capacity of the per-process entity slab store. The
    # store doubles on demand, so this is purely a pre-sizing knob: set it
    # near the expected steady-state entity count to avoid growth
    # reallocation (and, with the batched AOI backend, early engine tier
    # jumps) during login storms.
    slab_initial: int = 256


@dataclasses.dataclass
class SyncConfig:
    """Adaptive per-client position sync (``[sync]``; entity/slabs.py —
    ROADMAP item 5: per-client cost must go sublinear in neighbors x tick
    rate). Defaults preserve the legacy full-rate/full-precision path
    bit-for-bit."""

    # Per-tier emission periods in collections, ascending, first must be 1
    # (tier 0 = near neighbors at full rate). ("1",) disables tiering.
    tier_cadences: tuple[int, ...] = (1,)
    # Delta records carry int16 multiples of 2^-quantize_bits world units
    # between keyframes; 0 = full-precision records only (delta off).
    quantize_bits: int = 0
    # Collections between forced full-precision keyframes per pair.
    keyframe_interval: int = 32
    # distance/AOI-radius classification band: <= near_ratio -> tier 0,
    # >= far_ratio -> last tier, linear spread between.
    near_ratio: float = 0.5
    far_ratio: float = 0.8
    # Host-side re-classification cadence (collections); the batched AOI
    # engine's in-launch tier pass supersedes it.
    retier_interval: int = 8


@dataclasses.dataclass
class RebalanceConfig:
    """Telemetry-driven live rebalancer knobs (``[rebalance]``;
    rebalance/planner.py + rebalance/migrator.py — no reference analog:
    GoWorld's LBC heap only places NEW entities; this moves LIVE ones)."""

    # Master switch: when off, dispatchers collect load reports (the LBC
    # heap still uses them) but never plan migrations.
    enabled: bool = False
    # Which dispatcher runs the planner (exactly one must drive, and
    # dispatchers do not talk to each other; every dispatcher receives the
    # same load reports, so any id works — pick one).
    driver_dispatcher: int = 1
    # Seconds between planning rounds.
    interval: float = 1.0
    # Seconds between per-game load reports (game-side send cadence).
    report_interval: float = 1.0
    # Pause planning when any connected game's report is older than this
    # (stale telemetry must pause the rebalancer, never steer it).
    stale_after: float = 3.0
    # Hysteresis: plan moves only while donor.entities - receiver.entities
    # is at least this (prevents thrash around the balanced point).
    min_entity_delta: int = 4
    # Cap on entities moved per planning round (convergence is staged so a
    # plan never outruns the load reports that justify it).
    max_moves_per_round: int = 4
    # Game-side deadline per migration: past it the migrator cancels
    # (CANCEL_MIGRATE) and the entity stays where it was (rolled back).
    migrate_timeout: float = 5.0
    # Seconds a just-moved (or just-rolled-back) entity is exempt from
    # re-selection; doubles per consecutive rollback of the same entity.
    cooldown: float = 5.0
    # Cap on WHOLE-SPACE handoffs per planning round (ISSUE 18). 0 keeps
    # the planner entity-granular: a donor space whose kind has no
    # receiver-side twin simply stays put. Nonzero lets the bin-packer
    # move the space itself through the two-phase SPACE_MIGRATE protocol.
    max_space_moves_per_round: int = 0
    # Host the planner in the sharded RebalancePlannerService entity
    # instead of the driver dispatcher: the planner then fails over with
    # the service plane (a dead host's shard is re-claimed by a surviving
    # game and planning resumes from fresh GAME_LOAD_REPORT state).
    planner_service: bool = False


@dataclasses.dataclass
class ClientConfig:
    """Client/bot-side knobs (``[client]``)."""

    # Strict-bot per-RPC completion budget in seconds (bot_runner.py; the
    # reference hardcodes 5 s, ClientEntity.go:160-242). Reload windows on
    # slow rigs can legitimately exceed 5 s — widen this honestly instead
    # of eating a strict-mode flake.
    rpc_timeout: float = 5.0


@dataclasses.dataclass
class TelemetryConfig:
    """Distributed-tracing / flight-recorder knobs (``[telemetry]``;
    defaults mirror consts.py — telemetry/tracing.py)."""

    # Head-sampling denominator: 1-in-N ingress events start a trace
    # (0 disables tracing; 1 traces everything — test/debug only).
    trace_sample_rate: int = 1024
    # Finished-span ring size per process (drop-oldest).
    trace_ring_size: int = 4096
    # Game ticks busier than this many seconds trigger a flight-recorder
    # dump (ONE structured WARN + GET /flight); 0 disables the dump.
    slow_tick_budget: float = 0.1
    # How many tick records the flight recorder keeps.
    flight_ring_size: int = 240
    # Cluster observability plane (telemetry/collector.py): the driver
    # dispatcher scrapes every configured http_addr's /snapshot at this
    # cadence and serves the aggregate as GET /cluster (gwtop's source).
    # 0 disables the collector.
    cluster_snapshot_interval: float = 1.0
    # Device-runtime sentinel (telemetry/sentinel.py): launches after
    # which a fresh XLA trace of an engine step jit counts as a
    # steady-state retrace (jit_retrace_events_total + ONE structured
    # WARN naming the arg shape/dtype delta).
    retrace_warm_ticks: int = 32
    # Crash-survivable history ring (telemetry/history.py): when
    # history_dir is non-empty every process appends periodic telemetry
    # frames to <history_dir>/<process-name>/ — the per-process black box
    # post-mortem bundles collect. Empty = off (the default).
    history_dir: str = ""
    # Seconds between history frames (the writer rides its own asyncio
    # cadence task, never the logic loop).
    history_interval: float = 1.0
    # On-disk ring geometry: fixed-size segments, drop-oldest. Disk use
    # is bounded by history_segments * history_segment_bytes per process.
    history_segment_bytes: int = 262144
    history_segments: int = 8


@dataclasses.dataclass
class SLOConfig:
    """Cluster SLO budgets (``[slo]``; telemetry/slo.py). Budgets left
    unset (None) are not evaluated; ``enabled()`` is true when any budget
    is set. The driver dispatcher's ClusterCollector judges every poll
    against these and publishes per-budget compliance + multi-window burn
    rate in ``GET /cluster`` (gwtop's SLO column); ``run_scenario`` and
    the chaos harness accept the same object as a hard gate."""

    # Game tick p99 wall-clock budget, seconds (game_tick_phase_seconds
    # {phase=total} — the flight recorder's tick).
    tick_p99_budget: Optional[float] = None
    # Client delivery p99 budget, seconds: the sync_send phase p99 — the
    # slice of the tick spent fanning updates out to gates/clients.
    delivery_p99_budget: Optional[float] = None
    # Max tolerated strict-bot error rate (errors per bot), chaos/bench
    # gates only — there is no cluster-side metric for bot errors.
    bot_error_rate: Optional[float] = None
    # Max tolerated steady-state retraces, cluster-wide (the floor gates
    # pin 0; None = don't judge).
    steady_state_retraces: Optional[int] = None
    # Fraction of polls allowed out of budget before burn rate hits 1.0
    # (SRE error-budget convention: burn = violation_rate/error_budget).
    error_budget: float = 0.01
    # Burn-rate windows, in collector polls (short ≈ page-now, long ≈
    # budget-trend; 12/120 polls at the default 1 s cadence).
    burn_short_polls: int = 12
    burn_long_polls: int = 120

    def enabled(self) -> bool:
        return any(v is not None for v in (
            self.tick_p99_budget, self.delivery_p99_budget,
            self.bot_error_rate, self.steady_state_retraces))


@dataclasses.dataclass
class ScenarioConfig:
    """Scenario-matrix runner knobs (``[scenario]``; goworld_tpu/
    scenarios/).  These parameterize DEVELOPMENT runs only — bench.py's
    gate mode always passes the registry's fixed config + seed so
    committed floors never drift with an operator's ini."""

    # Seed for ad-hoc scenario runs (the registry's per-scenario fixed
    # seed is used when < 0).
    seed: int = -1
    # Engine ad-hoc runs default to: batched | sharded.
    default_engine: str = "batched"
    # Multiplier on each scenario's tick count for ad-hoc soak/smoke
    # runs (1.0 = the registered length; floors always use 1.0).
    ticks_scale: float = 1.0


@dataclasses.dataclass
class LogConfig:
    """Process-wide logging knobs (``[log]``)."""

    # "text" = the zap-parity line format (default); "json" = one JSON
    # object per line with level/ts/source and automatic trace_id
    # injection inside active trace spans (utils/gwlog.py).
    format: str = "text"


@dataclasses.dataclass
class DebugConfig:
    debug: bool = False


@dataclasses.dataclass
class GoWorldConfig:
    deployment: DeploymentConfig = dataclasses.field(default_factory=DeploymentConfig)
    dispatchers: dict[int, DispatcherConfig] = dataclasses.field(default_factory=dict)
    games: dict[int, GameConfig] = dataclasses.field(default_factory=dict)
    gates: dict[int, GateConfig] = dataclasses.field(default_factory=dict)
    storage: StorageConfig = dataclasses.field(default_factory=StorageConfig)
    kvdb: KVDBConfig = dataclasses.field(default_factory=KVDBConfig)
    aoi: AOIConfig = dataclasses.field(default_factory=AOIConfig)
    entity: EntityConfig = dataclasses.field(default_factory=EntityConfig)
    cluster: ClusterConfig = dataclasses.field(default_factory=ClusterConfig)
    sync: SyncConfig = dataclasses.field(default_factory=SyncConfig)
    rebalance: RebalanceConfig = dataclasses.field(default_factory=RebalanceConfig)
    client: ClientConfig = dataclasses.field(default_factory=ClientConfig)
    telemetry: TelemetryConfig = dataclasses.field(default_factory=TelemetryConfig)
    slo: SLOConfig = dataclasses.field(default_factory=SLOConfig)
    scenario: ScenarioConfig = dataclasses.field(default_factory=ScenarioConfig)
    log: LogConfig = dataclasses.field(default_factory=LogConfig)
    debug: DebugConfig = dataclasses.field(default_factory=DebugConfig)


_lock = threading.Lock()
_config_file: Optional[str] = None
_config: Optional[GoWorldConfig] = None


def set_config_file(path: str) -> None:
    global _config_file, _config
    with _lock:
        _config_file = path
        _config = None


def set_config(cfg: GoWorldConfig) -> None:
    """Inject a config object directly (tests / embedded clusters)."""
    global _config
    with _lock:
        _config = cfg


def get() -> GoWorldConfig:
    global _config
    with _lock:
        if _config is None:
            _config = _load(_config_file)
        return _config


def reload() -> GoWorldConfig:
    global _config
    with _lock:
        _config = _load(_config_file)
        return _config


def _read_start_nodes(section) -> list:
    """``start_nodes_1 = host:port`` etc, sorted by numeric suffix for
    determinism (reference read_config.go:492-493 collects them into a
    StringSet; non-numeric suffixes sort after, lexicographically)."""
    nodes = []
    for name in section:
        if name.startswith("start_nodes_") and section[name].strip():
            suffix = name[len("start_nodes_"):]
            key = (0, int(suffix), "") if suffix.isdigit() else (1, 0, suffix)
            nodes.append((key, section[name].strip()))
    return [v for _, v in sorted(nodes)]


def _load(path: Optional[str]) -> GoWorldConfig:
    # Inline `;` comments, like the reference's go-ini (read_config.go:20).
    cp = configparser.ConfigParser(inline_comment_prefixes=(";",))
    if path is not None:
        read = cp.read(path)
        if not read:
            raise FileNotFoundError(f"config file not found: {path}")
    else:
        cp.read(DEFAULT_CONFIG_FILES)

    cfg = GoWorldConfig()

    if cp.has_section("deployment"):
        s = cp["deployment"]
        cfg.deployment = DeploymentConfig(
            desired_games=s.getint("games", 1),
            desired_gates=s.getint("gates", 1),
            desired_dispatchers=s.getint("dispatchers", 1),
        )

    def merged(section: str, common: str) -> dict[str, str]:
        out: dict[str, str] = {}
        if cp.has_section(common):
            out.update(cp[common])
        if cp.has_section(section):
            out.update(cp[section])
        return out

    for i in range(1, cfg.deployment.desired_dispatchers + 1):
        s = merged(f"dispatcher{i}", "dispatcher_common")
        cfg.dispatchers[i] = DispatcherConfig(
            host=s.get("host", "127.0.0.1"),
            port=int(s.get("port", 14000 + i)),
            http_addr=s.get("http_addr", ""),
            log_file=s.get("log_file", ""),
            log_level=s.get("log_level", "info"),
        )

    for i in range(1, cfg.deployment.desired_games + 1):
        s = merged(f"game{i}", "game_common")
        cfg.games[i] = GameConfig(
            boot_entity=s.get("boot_entity", ""),
            save_interval=float(s.get("save_interval", 300)),
            http_addr=s.get("http_addr", ""),
            log_file=s.get("log_file", ""),
            log_level=s.get("log_level", "info"),
            position_sync_interval=float(s.get("position_sync_interval", 0.1)),
            aoi_platform=s.get("aoi_platform", "").strip().lower(),
        )

    for i in range(1, cfg.deployment.desired_gates + 1):
        s = merged(f"gate{i}", "gate_common")
        cfg.gates[i] = GateConfig(
            host=s.get("host", "127.0.0.1"),
            port=int(s.get("port", 15000 + i)),
            ws_addr=s.get("ws_addr", ""),
            http_addr=s.get("http_addr", ""),
            log_file=s.get("log_file", ""),
            log_level=s.get("log_level", "info"),
            compress_connection=s.get("compress_connection", "false").lower() in ("1", "true", "yes"),
            compress_format=s.get("compress_format", "snappy").strip().lower(),
            rudp_protocol=s.get("rudp_protocol", "kcp").strip().lower(),
            rudp_fec=s.get("rudp_fec", "10,3").strip().lower(),
            encrypt_connection=s.get("encrypt_connection", "false").lower() in ("1", "true", "yes"),
            rsa_key=s.get("rsa_key", ""),
            rsa_cert=s.get("rsa_cert", ""),
            heartbeat_timeout=float(s.get("heartbeat_timeout", 30)),
            position_sync_interval=float(s.get("position_sync_interval", 0.1)),
        )

    if cp.has_section("storage"):
        s = cp["storage"]
        cfg.storage = StorageConfig(
            type=s.get("type", "filesystem"),
            directory=s.get("directory", "_entity_storage"),
            url=s.get("url", ""),
            db=s.get("db", "goworld"),
            start_nodes=_read_start_nodes(s),
            retry_base_interval=float(s.get("retry_base_interval", 1.0)),
            retry_max_interval=float(s.get("retry_max_interval", 30.0)),
            circuit_failure_threshold=int(
                s.get("circuit_failure_threshold", 5)),
            circuit_cooldown=float(s.get("circuit_cooldown", 5.0)),
            deferred_bytes_cap=int(
                s.get("deferred_bytes_cap", 8 * 1024 * 1024)),
        )
    if cp.has_section("kvdb"):
        s = cp["kvdb"]
        cfg.kvdb = KVDBConfig(
            type=s.get("type", "filesystem"),
            directory=s.get("directory", "_kvdb"),
            url=s.get("url", ""),
            db=s.get("db", "goworld"),
            collection=s.get("collection", "kvdb"),
            start_nodes=_read_start_nodes(s),
        )
    if cp.has_section("aoi"):
        s = cp["aoi"]
        cfg.aoi = AOIConfig(
            backend=s.get("backend", "auto").strip().lower(),
            platform=s.get("platform", "auto").strip().lower(),
            cell_capacity=int(s.get("cell_capacity", 64)),
            max_entities=int(s.get("max_entities", 16384)),
            mesh_shards=int(s.get("mesh_shards", 1)),
            shard_mode=s.get("shard_mode", "spatial").strip().lower(),
            strip_placement=s.get(
                "strip_placement", "topology").strip().lower(),
            pallas_strip_cols=int(s.get("pallas_strip_cols", 0)),
            pallas_inkernel_drain=s.get(
                "pallas_inkernel_drain", "true").strip().lower()
            in ("1", "true", "yes"),
            compilation_cache=s.get("compilation_cache", "auto").strip(),
            grid=int(s.get("grid", 0)),
            cell_size=float(s.get("cell_size", 0.0)),
            space_slots=int(s.get("space_slots", 0)),
            multihost_coordinator=s.get("multihost_coordinator", "").strip(),
            multihost_processes=int(s.get("multihost_processes", 0)),
            delivery=s.get("delivery", "pipelined").strip().lower(),
            sync_wait_budget=float(s.get("sync_wait_budget", 0.5)),
            fuse_logic=s.get("fuse_logic", "false").strip().lower()
            in ("1", "true", "yes"),
        )
    if cp.has_section("cluster"):
        s = cp["cluster"]
        cfg.cluster = ClusterConfig(
            down_buffer_bytes=int(s.get("down_buffer_bytes", 2 * 1024 * 1024)),
            peer_heartbeat_timeout=float(s.get("peer_heartbeat_timeout", 10.0)),
            wait_connected_timeout=float(s.get("wait_connected_timeout", 10.0)),
            reconnect_max_interval=float(s.get("reconnect_max_interval", 15.0)),
            transport=s.get("transport", "tcp").strip().lower(),
            uds_dir=s.get("uds_dir", "").strip(),
            sync_flush_bytes=int(s.get("sync_flush_bytes", 32 * 1024)),
        )
    if cp.has_section("entity"):
        cfg.entity = EntityConfig(
            slab_initial=int(cp["entity"].get("slab_initial", 256)),
        )
    if cp.has_section("sync"):
        s = cp["sync"]
        cfg.sync = SyncConfig(
            tier_cadences=tuple(
                int(v) for v in
                s.get("tier_cadences", "1").replace(" ", "").split(",")
                if v),
            quantize_bits=int(s.get("quantize_bits", 0)),
            keyframe_interval=int(s.get("keyframe_interval", 32)),
            near_ratio=float(s.get("near_ratio", 0.5)),
            far_ratio=float(s.get("far_ratio", 0.8)),
            retier_interval=int(s.get("retier_interval", 8)),
        )
    if cp.has_section("rebalance"):
        s = cp["rebalance"]
        cfg.rebalance = RebalanceConfig(
            enabled=s.get("enabled", "false").lower() in ("1", "true", "yes"),
            driver_dispatcher=int(s.get("driver_dispatcher", 1)),
            interval=float(s.get("interval", 1.0)),
            report_interval=float(s.get("report_interval", 1.0)),
            stale_after=float(s.get("stale_after", 3.0)),
            min_entity_delta=int(s.get("min_entity_delta", 4)),
            max_moves_per_round=int(s.get("max_moves_per_round", 4)),
            migrate_timeout=float(s.get("migrate_timeout", 5.0)),
            cooldown=float(s.get("cooldown", 5.0)),
            max_space_moves_per_round=int(
                s.get("max_space_moves_per_round", 0)),
            planner_service=s.get("planner_service", "false").lower()
            in ("1", "true", "yes"),
        )
    if cp.has_section("client"):
        cfg.client = ClientConfig(
            rpc_timeout=float(cp["client"].get("rpc_timeout", 5.0)),
        )
    if cp.has_section("telemetry"):
        s = cp["telemetry"]
        cfg.telemetry = TelemetryConfig(
            trace_sample_rate=int(s.get("trace_sample_rate", 1024)),
            trace_ring_size=int(s.get("trace_ring_size", 4096)),
            slow_tick_budget=float(s.get("slow_tick_budget", 0.1)),
            flight_ring_size=int(s.get("flight_ring_size", 240)),
            cluster_snapshot_interval=float(
                s.get("cluster_snapshot_interval", 1.0)),
            retrace_warm_ticks=int(s.get("retrace_warm_ticks", 32)),
            history_dir=s.get("history_dir", "").strip(),
            history_interval=float(s.get("history_interval", 1.0)),
            history_segment_bytes=int(s.get("history_segment_bytes", 262144)),
            history_segments=int(s.get("history_segments", 8)),
        )
    if cp.has_section("slo"):
        s = cp["slo"]

        def _opt_f(v):
            v = v.strip()
            return float(v) if v else None  # "" = budget unset

        retr = s.get("steady_state_retraces", "").strip()
        cfg.slo = SLOConfig(
            tick_p99_budget=_opt_f(s.get("tick_p99_budget", "")),
            delivery_p99_budget=_opt_f(s.get("delivery_p99_budget", "")),
            bot_error_rate=_opt_f(s.get("bot_error_rate", "")),
            steady_state_retraces=int(retr) if retr else None,
            error_budget=float(s.get("error_budget", 0.01)),
            burn_short_polls=int(s.get("burn_short_polls", 12)),
            burn_long_polls=int(s.get("burn_long_polls", 120)),
        )
    if cp.has_section("scenario"):
        s = cp["scenario"]
        cfg.scenario = ScenarioConfig(
            seed=int(s.get("seed", -1)),
            default_engine=s.get("default_engine", "batched"),
            ticks_scale=float(s.get("ticks_scale", 1.0)),
        )
    if cp.has_section("log"):
        cfg.log = LogConfig(
            format=cp["log"].get("format", "text").strip().lower(),
        )
    if cp.has_section("debug"):
        cfg.debug = DebugConfig(debug=cp["debug"].getboolean("debug", False))

    _validate(cfg)
    return cfg


def parse_fec(spec: str, gid=None) -> tuple[int, int] | None:
    """"data,parity" → (d, p); "off" → None; anything else raises."""
    if spec == "off":
        return None
    where = f"gate{gid}: " if gid is not None else ""
    try:
        d_s, p_s = spec.split(",")
        d, p = int(d_s), int(p_s)
    except ValueError:
        raise ValueError(
            f"{where}rudp_fec must be 'data,parity' or 'off', got {spec!r}"
        ) from None
    if not (1 <= d <= 128 and 1 <= p <= 128):
        raise ValueError(f"{where}rudp_fec shards must be in [1, 128]")
    if d + p > 255:
        # GF(2^8) Vandermonde rows repeat at alpha^255 = 1: a 256-shard
        # code silently degenerates (duplicate rows → singular subsets).
        raise ValueError(f"{where}rudp_fec data+parity must be <= 255")
    return d, p


def _validate(cfg: GoWorldConfig) -> None:
    """Sanity checks, mirroring read_config.go:538-661."""
    if cfg.aoi.backend not in ("auto", "xzlist", "tpu"):
        raise ValueError(
            f"[aoi] backend must be auto|xzlist|tpu, got {cfg.aoi.backend!r}"
        )
    if cfg.aoi.platform not in ("auto", "cpu", "tpu"):
        # A typo here would silently put a CPU-deploy game on the chip
        # (GameService only acts on the exact value "cpu") — fail loudly.
        raise ValueError(
            f"[aoi] platform must be auto|cpu|tpu, got {cfg.aoi.platform!r}"
        )
    a = cfg.aoi
    if a.max_entities < 8:
        raise ValueError("[aoi] max_entities must be >= 8")
    if not (1 <= a.cell_capacity <= 128):
        raise ValueError("[aoi] cell_capacity must be in [1, 128]")
    if a.mesh_shards < 1:
        raise ValueError("[aoi] mesh_shards must be >= 1")
    if a.shard_mode not in ("spatial", "entity"):
        raise ValueError("[aoi] shard_mode must be spatial or entity")
    if a.strip_placement not in ("topology", "ring"):
        raise ValueError(
            f"[aoi] strip_placement must be topology or ring, "
            f"got {a.strip_placement!r}"
        )
    if a.pallas_strip_cols < 0:
        # Negative would silently disable the width cap the Pallas slab's
        # static extent depends on — reject loudly (0 = derive).
        raise ValueError(
            "[aoi] pallas_strip_cols must be >= 0 (0 = derive)")
    if not a.compilation_cache:
        raise ValueError(
            "[aoi] compilation_cache must be auto, off, or a directory")
    if a.grid != 0 and not (4 <= a.grid <= 512):
        raise ValueError("[aoi] grid must be 0 (derive) or in [4, 512]")
    if a.cell_size < 0.0:
        # A negative cell size would bin every entity into garbage cells
        # and silently return wrong neighbor sets.
        raise ValueError("[aoi] cell_size must be >= 0 (0 = default)")
    if a.space_slots < 0:
        raise ValueError("[aoi] space_slots must be >= 0 (0 = default)")
    if a.delivery not in ("pipelined", "sync"):
        raise ValueError(
            f"[aoi] delivery must be pipelined|sync, got {a.delivery!r}"
        )
    if a.sync_wait_budget <= 0:
        # 0 would park every sync step unconditionally (sync mode that
        # never delivers same-tick); negative is nonsense.
        raise ValueError("[aoi] sync_wait_budget must be > 0 seconds")
    if a.delivery == "sync" and a.multihost_coordinator:
        # Sync delivery stalls the loop inside device collectives; on the
        # DCN tier a dead peer would turn that stall into a permanent
        # wedge of every survivor's logic loop AND defeat the freeze
        # flush's liveness bound (code-review r5). The multihost tier is
        # pipelined by design — frame-skipping keeps a dead peer
        # degraded-but-live.
        raise ValueError(
            "[aoi] delivery = sync is incompatible with "
            "multihost_coordinator (a dead peer would wedge every "
            "survivor's logic loop inside a collective); use pipelined"
        )
    for gid, g in cfg.gates.items():
        if g.compress_format not in ("snappy", "zlib"):
            raise ValueError(
                f"gate{gid}: compress_format must be snappy|zlib, "
                f"got {g.compress_format!r}"
            )
        if g.rudp_protocol not in ("kcp", "native"):
            raise ValueError(
                f"gate{gid}: rudp_protocol must be kcp|native, "
                f"got {g.rudp_protocol!r}"
            )
        parse_fec(g.rudp_fec, gid)  # raises on malformed spec
    for gid, g in cfg.games.items():
        if g.aoi_platform not in ("", "auto", "cpu", "tpu"):
            raise ValueError(
                f"game{gid}: aoi_platform must be auto|cpu|tpu, "
                f"got {g.aoi_platform!r}"
            )
    if a.multihost_coordinator:
        if a.backend == "xzlist":
            raise ValueError(
                "[aoi] multihost_coordinator requires the batched backend "
                "(backend = tpu or auto), not xzlist"
            )
        if a.mesh_shards > 1:
            raise ValueError(
                "[aoi] multihost_coordinator and mesh_shards > 1 are "
                "mutually exclusive (single-host ICI tier vs multi-host "
                "DCN tier)"
            )
        nproc = a.multihost_processes or len(cfg.games)
        if nproc < 2:
            raise ValueError(
                "[aoi] multihost needs >= 2 processes (games); for one "
                "process use mesh_shards instead"
            )
        if a.multihost_processes and a.multihost_processes != len(cfg.games):
            raise ValueError(
                f"[aoi] multihost_processes ({a.multihost_processes}) must "
                f"match the number of games ({len(cfg.games)}) — every game "
                f"joins the mesh"
            )
        plats = {
            (g.aoi_platform or a.platform) for g in cfg.games.values()
        }
        if len(plats) > 1:
            raise ValueError(
                "[aoi] multihost requires every game on the SAME jax "
                f"platform (one global mesh); got {sorted(plats)}"
            )
        cadences = {g.position_sync_interval for g in cfg.games.values()}
        if len(cadences) > 1:
            # Dispatches are readiness-gated so differing cadences cannot
            # diverge the global op sequence, but the slowest game would
            # silently pace every other game's AOI — surprising enough to
            # reject outright.
            raise ValueError(
                "[aoi] multihost requires the same position_sync_interval "
                f"on every game; got {sorted(cadences)}"
            )
    cl = cfg.cluster
    if cl.down_buffer_bytes < 0:
        raise ValueError("[cluster] down_buffer_bytes must be >= 0 (0 = drop)")
    if cl.peer_heartbeat_timeout < 0:
        raise ValueError(
            "[cluster] peer_heartbeat_timeout must be >= 0 (0 = disabled)")
    if cl.wait_connected_timeout <= 0:
        raise ValueError("[cluster] wait_connected_timeout must be > 0")
    if cl.reconnect_max_interval <= 0:
        raise ValueError("[cluster] reconnect_max_interval must be > 0")
    if cl.transport not in ("tcp", "uds"):
        # A typo here would leave games dialing TCP while the operator
        # believes the cluster rides unix sockets — fail loudly.
        raise ValueError(
            f"[cluster] transport must be tcp|uds, got {cl.transport!r}")
    if cl.sync_flush_bytes < 0:
        raise ValueError(
            "[cluster] sync_flush_bytes must be >= 0 (0 = tick-only flush)")
    sy = cfg.sync
    if not sy.tier_cadences or sy.tier_cadences[0] != 1:
        # Tier 0 is the full-rate tier by contract: new/near pairs land
        # there, so a first cadence != 1 would throttle EVERY pair.
        raise ValueError(
            "[sync] tier_cadences must be a non-empty ascending list "
            "starting at 1 (tier 0 = full rate), got "
            f"{list(sy.tier_cadences)}")
    if any(b <= a for a, b in zip(sy.tier_cadences, sy.tier_cadences[1:])):
        raise ValueError(
            "[sync] tier_cadences must be strictly ascending, got "
            f"{list(sy.tier_cadences)}")
    if any(c > 1024 for c in sy.tier_cadences):
        raise ValueError("[sync] tier cadences above 1024 would stall "
                         "distant pairs for tens of seconds")
    if not 0 <= sy.quantize_bits <= 14:
        # 15+ fractional bits leave the int16 delta range below one world
        # unit — any real movement would force a keyframe every record.
        raise ValueError(
            f"[sync] quantize_bits must be in [0, 14], got "
            f"{sy.quantize_bits}")
    if sy.keyframe_interval < 2:
        raise ValueError("[sync] keyframe_interval must be >= 2 "
                         "collections (1 would disable deltas implicitly)")
    if not 0.0 < sy.near_ratio < sy.far_ratio <= 1.0:
        raise ValueError(
            "[sync] requires 0 < near_ratio < far_ratio <= 1.0, got "
            f"near_ratio={sy.near_ratio} far_ratio={sy.far_ratio}")
    if sy.retier_interval < 1:
        raise ValueError("[sync] retier_interval must be >= 1")
    rb = cfg.rebalance
    if rb.driver_dispatcher < 1:
        raise ValueError("[rebalance] driver_dispatcher must be >= 1")
    if rb.enabled and rb.driver_dispatcher not in cfg.dispatchers \
            and cfg.dispatchers:
        # A driver id naming no configured dispatcher means NO dispatcher
        # ever plans — the operator believes rebalancing is on while it is
        # silently dead. Fail loudly.
        raise ValueError(
            f"[rebalance] driver_dispatcher = {rb.driver_dispatcher} names "
            f"no configured dispatcher (have {sorted(cfg.dispatchers)})")
    if rb.interval <= 0 or rb.report_interval <= 0:
        raise ValueError(
            "[rebalance] interval and report_interval must be > 0 seconds")
    if rb.stale_after < rb.report_interval:
        # A staleness window shorter than the report cadence pauses the
        # planner permanently between perfectly healthy reports.
        raise ValueError(
            "[rebalance] stale_after must be >= report_interval")
    if rb.min_entity_delta < 1:
        raise ValueError("[rebalance] min_entity_delta must be >= 1")
    if rb.max_moves_per_round < 1:
        raise ValueError("[rebalance] max_moves_per_round must be >= 1")
    if rb.migrate_timeout <= 0:
        raise ValueError("[rebalance] migrate_timeout must be > 0 seconds")
    if rb.cooldown < 0:
        raise ValueError("[rebalance] cooldown must be >= 0 seconds")
    if rb.max_space_moves_per_round < 0:
        raise ValueError(
            "[rebalance] max_space_moves_per_round must be >= 0 "
            "(0 = whole-space moves disabled)")
    if cfg.client.rpc_timeout <= 0:
        raise ValueError("[client] rpc_timeout must be > 0 seconds")
    t = cfg.telemetry
    if t.trace_sample_rate < 0:
        raise ValueError(
            "[telemetry] trace_sample_rate must be >= 0 (0 = off, N = 1/N)")
    if t.trace_ring_size < 1:
        raise ValueError("[telemetry] trace_ring_size must be >= 1")
    if t.slow_tick_budget < 0:
        raise ValueError(
            "[telemetry] slow_tick_budget must be >= 0 (0 = no slow dumps)")
    if t.flight_ring_size < 1:
        raise ValueError("[telemetry] flight_ring_size must be >= 1")
    if t.cluster_snapshot_interval < 0:
        raise ValueError(
            "[telemetry] cluster_snapshot_interval must be >= 0 seconds "
            "(0 = no cluster collector)")
    if t.retrace_warm_ticks < 1:
        raise ValueError("[telemetry] retrace_warm_ticks must be >= 1")
    if t.history_interval <= 0:
        raise ValueError("[telemetry] history_interval must be > 0 seconds")
    if t.history_segment_bytes < 4096:
        raise ValueError(
            "[telemetry] history_segment_bytes must be >= 4096")
    if t.history_segments < 2:
        raise ValueError(
            "[telemetry] history_segments must be >= 2 (the ring needs a "
            "previous segment to survive rotation)")
    slo = cfg.slo
    for key, v in (("tick_p99_budget", slo.tick_p99_budget),
                   ("delivery_p99_budget", slo.delivery_p99_budget),
                   ("bot_error_rate", slo.bot_error_rate)):
        if v is not None and v < 0:
            raise ValueError(f"[slo] {key} must be >= 0")
    if slo.steady_state_retraces is not None and slo.steady_state_retraces < 0:
        raise ValueError("[slo] steady_state_retraces must be >= 0")
    if not (0.0 < slo.error_budget <= 1.0):
        raise ValueError("[slo] error_budget must be in (0, 1]")
    if slo.burn_short_polls < 1 or slo.burn_long_polls < slo.burn_short_polls:
        raise ValueError(
            "[slo] burn windows must satisfy 1 <= burn_short_polls "
            "<= burn_long_polls")
    sc = cfg.scenario
    if sc.default_engine not in ("batched", "sharded"):
        raise ValueError(
            f"[scenario] default_engine must be batched|sharded, "
            f"got {sc.default_engine!r}")
    if not (0.0 < sc.ticks_scale <= 100.0):
        raise ValueError(
            "[scenario] ticks_scale must be in (0, 100]")
    if cfg.log.format not in ("text", "json"):
        raise ValueError(
            f"[log] format must be text|json, got {cfg.log.format!r}")
    st = cfg.storage
    if st.retry_base_interval <= 0 or st.retry_max_interval <= 0:
        raise ValueError("[storage] retry intervals must be > 0 seconds")
    if st.retry_max_interval < st.retry_base_interval:
        raise ValueError(
            "[storage] retry_max_interval must be >= retry_base_interval")
    if st.circuit_failure_threshold < 1:
        # 0 would open the circuit before the first attempt — saves would
        # never reach the backend at all.
        raise ValueError("[storage] circuit_failure_threshold must be >= 1")
    if st.circuit_cooldown <= 0:
        raise ValueError("[storage] circuit_cooldown must be > 0 seconds")
    if st.deferred_bytes_cap < 0:
        raise ValueError("[storage] deferred_bytes_cap must be >= 0")
    for section, c in (("storage", cfg.storage), ("kvdb", cfg.kvdb)):
        if c.type == "redis_cluster" and not c.start_nodes:
            # read_config.go:555-556,617-619: fatal without seed nodes.
            raise ValueError(
                f"must have at least 1 start_nodes for [{section}].redis_cluster"
            )
    if cfg.deployment.desired_dispatchers < 1:
        raise ValueError("deployment.dispatchers must be >= 1")
    if cfg.deployment.desired_games < 1:
        raise ValueError("deployment.games must be >= 1")
    seen: dict[tuple[str, int], str] = {}
    for did, d in cfg.dispatchers.items():
        key = (d.host, d.port)
        if key in seen:
            raise ValueError(f"dispatcher{did} addr {key} duplicates {seen[key]}")
        seen[key] = f"dispatcher{did}"
    for gid, g in cfg.gates.items():
        key = (g.host, g.port)
        if key in seen:
            raise ValueError(f"gate{gid} addr {key} duplicates {seen[key]}")
        seen[key] = f"gate{gid}"
        if g.encrypt_connection and not (g.rsa_key and g.rsa_cert):
            raise ValueError(f"gate{gid}: encrypt_connection requires rsa_key and rsa_cert")


# --- typed accessors (reference read_config.go:178-214) ---------------------

def get_deployment() -> DeploymentConfig:
    return get().deployment


def get_game(gameid: int) -> GameConfig:
    return get().games[gameid]


def get_gate(gateid: int) -> GateConfig:
    return get().gates[gateid]


def get_dispatcher(dispid: int) -> DispatcherConfig:
    return get().dispatchers[dispid]


def get_game_ids() -> list[int]:
    return sorted(get().games)


def get_gate_ids() -> list[int]:
    return sorted(get().gates)


def get_dispatcher_ids() -> list[int]:
    return sorted(get().dispatchers)


def get_storage() -> StorageConfig:
    return get().storage


def get_kvdb() -> KVDBConfig:
    return get().kvdb

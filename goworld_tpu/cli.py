"""Ops CLI: build | start | stop | kill | reload | status for a server dir.

Reference parity: ``cmd/goworld`` (SURVEY.md §2.3) — ``build`` compiles the
server (build.go:9-56; here: byte-compile), ``start`` spawns dispatchers →
games → gates waiting for each group's supervisor tag in its log
(start.go:17-126), ``stop`` SIGTERMs gates → games → dispatchers
(stop.go:11-60), ``reload`` SIGHUP-freezes the games then restarts them with
``-restore`` under the (possibly rebuilt) code (reload.go:10-33), ``status``
reports which configured processes are alive (status.go:14-115).

Process bookkeeping is pidfile-based (``<name>.pid`` = "pid starttime" in the
run directory), verified against the kernel start time in /proc/<pid>/stat so
a recycled PID belonging to an unrelated process is never signalled.

Usage:
    python -m goworld_tpu.cli start examples.test_game [-configfile goworld.ini]
    python -m goworld_tpu.cli stop
    python -m goworld_tpu.cli reload examples.test_game
    python -m goworld_tpu.cli status
"""

from __future__ import annotations

import argparse
import compileall
import importlib.util
import os
import signal
import subprocess
import sys
import time

from goworld_tpu import consts
from goworld_tpu.config import get as get_config, set_config_file

START_TIMEOUT = 60.0  # per-process tag wait (start.go waits per process)
STOP_TIMEOUT = 30.0
FREEZE_TIMEOUT = 30.0  # consts.go FREEZE_TIMEOUT is 10s; allow slack


# --- pidfile bookkeeping -----------------------------------------------------


def _pidfile(run_dir: str, name: str) -> str:
    return os.path.join(run_dir, f"{name}.pid")


def _logfile(run_dir: str, name: str) -> str:
    return os.path.join(run_dir, f"{name}.out.log")


def _read_pid(run_dir: str, name: str) -> tuple[int, int | None] | None:
    """Returns (pid, starttime) from the pidfile; starttime is None for
    legacy single-field pidfiles."""
    try:
        with open(_pidfile(run_dir, name)) as f:
            fields = f.read().split()
            pid = int(fields[0])
            start = int(fields[1]) if len(fields) > 1 else None
            return pid, start
    except (OSError, ValueError, IndexError):
        return None


def _proc_cmdline(pid: int) -> str:
    try:
        with open(f"/proc/{pid}/cmdline", "rb") as f:
            return f.read().replace(b"\0", b" ").decode(errors="replace")
    except OSError:
        return ""


def _proc_starttime(pid: int) -> int | None:
    """Kernel start time (clock ticks since boot, /proc/<pid>/stat field 22).
    Stable for the process's lifetime and never reused together with the same
    PID, so (pid, starttime) uniquely identifies the process we spawned."""
    try:
        with open(f"/proc/{pid}/stat", "rb") as f:
            stat = f.read().decode(errors="replace")
        # Field 2 (comm) may contain spaces/parens; fields after the closing
        # paren are well-formed.
        rest = stat.rsplit(")", 1)[1].split()
        return int(rest[19])  # field 22 overall = index 19 after comm
    except (OSError, ValueError, IndexError):
        return None


def _alive(pidinfo: tuple[int, int | None] | None, expect: str) -> bool:
    """Alive AND still the process we started (guards stale pidfile reuse)."""
    if pidinfo is None:
        return False
    pid, start = pidinfo
    cmdline = _proc_cmdline(pid)
    if not cmdline:
        return False  # dead (or unreadable) — never "matches"
    if start is not None:
        # Strong identity: a recycled PID has a different kernel start time.
        return _proc_starttime(pid) == start
    # Legacy pidfile without a start time: fall back to the cmdline marker.
    return (expect or "python") in cmdline


def _process_names(cfg) -> dict[str, list[str]]:
    return {
        "dispatcher": [f"dispatcher{i}" for i in sorted(cfg.dispatchers)],
        "game": [f"game{i}" for i in sorted(cfg.games)],
        "gate": [f"gate{i}" for i in sorted(cfg.gates)],
    }


def _expect_marker(kind: str, name: str, server_module: str | None) -> str:
    """Substring of the child cmdline that identifies this process kind."""
    if kind == "dispatcher":
        return "goworld_tpu.dispatcher"
    if kind == "gate":
        return "goworld_tpu.gate"
    return server_module or ""


# --- spawn + tag wait --------------------------------------------------------


def _spawn_nowait(run_dir: str, name: str, argv: list[str]):
    """Launch the process and return (proc, log offset) without waiting for
    its supervisor tag — callers spawn a batch, then wait for every tag
    (parallel restart halves a reload's client-visible freeze window: each
    game is a fresh interpreter with seconds of import/warmup cost)."""
    log_path = _logfile(run_dir, name)
    logf = open(log_path, "ab")
    logf.write(f"\n--- spawn {time.strftime('%F %T')}: {' '.join(argv)}\n".encode())
    logf.flush()
    offset = logf.tell()  # only log content from THIS spawn satisfies the tag
    proc = subprocess.Popen(
        argv, stdout=logf, stderr=subprocess.STDOUT, cwd=run_dir,
        start_new_session=True,  # survives the CLI exiting (daemon-ish)
    )
    logf.close()
    start = _proc_starttime(proc.pid)
    with open(_pidfile(run_dir, name), "w") as f:
        f.write(str(proc.pid) if start is None else f"{proc.pid} {start}")
    return proc, offset


def _spawn(run_dir: str, name: str, argv: list[str], tag: str) -> None:
    proc, offset = _spawn_nowait(run_dir, name, argv)
    _wait_tag(run_dir, name, tag, proc, offset)


def _wait_tag(run_dir: str, name: str, tag: str, proc=None, offset: int = 0) -> None:
    """Scan the child's log (from this spawn's offset — logs append across
    restarts so reload forensics keep the pre-freeze half) for its
    supervisor tag (start.go:98-126)."""
    log_path = _logfile(run_dir, name)
    deadline = time.monotonic() + START_TIMEOUT
    while time.monotonic() < deadline:
        try:
            with open(log_path, "rb") as f:
                f.seek(offset)
                if tag.encode() in f.read():
                    print(f"  {name}: started ok")
                    return
        except OSError:
            pass
        if proc is not None and proc.poll() is not None:
            sys.exit(f"{name} exited with code {proc.returncode}; see {log_path}")
        time.sleep(0.05)
    sys.exit(f"timeout waiting for {name} start tag; see {log_path}")


def _truncate_log(run_dir: str, name: str) -> None:
    # Tags are matched by scanning the whole log; stale tags from a previous
    # run must not satisfy the wait.
    try:
        os.truncate(_logfile(run_dir, name), 0)
    except OSError:
        pass


# --- commands ----------------------------------------------------------------


def cmd_build(args) -> int:
    """Byte-compile the server module tree (parity with `goworld build`)."""
    spec = importlib.util.find_spec(args.server_module)
    if spec is None:
        sys.exit(f"server module {args.server_module!r} not found")
    targets = spec.submodule_search_locations or [os.path.dirname(spec.origin or "")]
    ok = all(compileall.compile_dir(t, quiet=1) for t in targets)
    from goworld_tpu import native

    print(f"native wire framing: {native.prebuild()}")
    print(f"build {'ok' if ok else 'FAILED'}: {list(targets)}")
    return 0 if ok else 1


def cmd_start(args) -> int:
    from goworld_tpu import native

    impl = native.prebuild()  # one compile here, not N racing in children
    print(f"native wire framing: {impl}")
    cfg = get_config()
    run_dir = os.path.abspath(args.dir)
    names = _process_names(cfg)
    configfile = os.path.abspath(args.configfile) if args.configfile else ""
    cfg_argv = ["-configfile", configfile] if configfile else []

    for name in [n for group in names.values() for n in group]:
        _truncate_log(run_dir, name)

    print(f"starting {len(names['dispatcher'])} dispatcher(s) ...")
    for i, name in zip(sorted(cfg.dispatchers), names["dispatcher"]):
        _spawn(run_dir, name,
               [sys.executable, "-m", "goworld_tpu.dispatcher", "-dispid", str(i)] + cfg_argv,
               consts.DISPATCHER_STARTED_TAG)
    print(f"starting {len(names['game'])} game(s) [{args.server_module}] ...")
    # Spawn the whole game batch BEFORE waiting on any tag: an AOI
    # multihost game blocks at the jax.distributed barrier until every
    # peer game is up, so sequential spawn-then-wait would deadlock (and
    # batching is faster for plain deploys too).
    spawned = []
    for i, name in zip(sorted(cfg.games), names["game"]):
        argv = [sys.executable, "-m", args.server_module, "-gid", str(i)] + cfg_argv
        if args.restore:
            argv.append("-restore")
        spawned.append((name,) + _spawn_nowait(run_dir, name, argv))
    try:
        for name, proc, offset in spawned:
            _wait_tag(run_dir, name, consts.GAME_STARTED_TAG, proc, offset)
    except SystemExit:
        # One game failed to boot: reap its batch-mates — otherwise they
        # linger daemonized (a multihost peer sits wedged at the mesh
        # barrier holding its ports) and the next `start` fails on
        # port conflicts until a manual `kill`.
        for name, proc, _ in spawned:
            if proc.poll() is None:
                proc.terminate()
        raise
    print(f"starting {len(names['gate'])} gate(s) ...")
    for i, name in zip(sorted(cfg.gates), names["gate"]):
        _spawn(run_dir, name,
               [sys.executable, "-m", "goworld_tpu.gate", "-gid", str(i)] + cfg_argv,
               consts.GATE_STARTED_TAG)
    print("cluster started")
    return 0


def _stop_group(run_dir: str, kind: str, names: list[str], sig: int,
                server_module: str | None) -> None:
    expect = _expect_marker(kind, "", server_module)
    pids = []
    for name in names:
        pid = _read_pid(run_dir, name)
        if not _alive(pid, expect):
            print(f"  {name}: not running")
            continue
        try:
            os.kill(pid[0], sig)
        except ProcessLookupError:
            print(f"  {name}: already gone")
            continue
        pids.append((name, pid))
    deadline = time.monotonic() + STOP_TIMEOUT
    for name, pid in pids:
        while _alive(pid, expect) and time.monotonic() < deadline:
            time.sleep(0.05)
        if _alive(pid, expect):
            print(f"  {name}: did not exit; killing")
            try:
                os.kill(pid[0], signal.SIGKILL)
            except ProcessLookupError:
                pass
        else:
            print(f"  {name}: stopped")
        try:
            os.unlink(_pidfile(run_dir, name))
        except OSError:
            pass


def cmd_stop(args, sig: int = signal.SIGTERM) -> int:
    cfg = get_config()
    run_dir = os.path.abspath(args.dir)
    names = _process_names(cfg)
    # Reference order: gates first (detach clients), then games (save all
    # entities), then dispatchers (stop.go:11-60).
    print("stopping gates ...")
    _stop_group(run_dir, "gate", names["gate"], sig, None)
    print("stopping games ...")
    _stop_group(run_dir, "game", names["game"], sig, getattr(args, "server_module", None))
    print("stopping dispatchers ...")
    _stop_group(run_dir, "dispatcher", names["dispatcher"], sig, None)
    return 0


def cmd_kill(args) -> int:
    return cmd_stop(args, sig=signal.SIGKILL)


def cmd_reload(args) -> int:
    """Freeze games (SIGHUP) → wait for exit → restart with -restore.

    Dispatchers buffer the frozen games' packets and gates keep their client
    sockets, so clients ride through the swap (SURVEY.md §3.5).
    """
    cfg = get_config()
    run_dir = os.path.abspath(args.dir)
    names = _process_names(cfg)["game"]
    expect = args.server_module
    frozen = []
    for i, name in zip(sorted(cfg.games), names):
        pid = _read_pid(run_dir, name)
        if not _alive(pid, expect):
            print(f"  {name}: not running; skipping")
            continue
        try:
            os.kill(pid[0], signal.SIGHUP)
        except ProcessLookupError:
            print(f"  {name}: already gone; skipping")
            continue
        frozen.append((name, pid, i))
    for name, pid, _ in frozen:
        deadline = time.monotonic() + FREEZE_TIMEOUT
        while _alive(pid, expect) and time.monotonic() < deadline:
            time.sleep(0.05)
        if _alive(pid, expect):
            sys.exit(f"{name} did not freeze within {FREEZE_TIMEOUT}s")
        print(f"  {name}: freezed")
    configfile = os.path.abspath(args.configfile) if args.configfile else ""
    cfg_argv = ["-configfile", configfile] if configfile else []
    # Spawn ALL restores first, then wait for every tag: the restart cost
    # (interpreter + imports + engine warmup, seconds per game) overlaps
    # instead of serializing, shrinking the window clients must ride out.
    # No truncation on reload: the pre-freeze log half is the forensic
    # record of what led into the swap (_wait_tag scans from the new
    # spawn marker, so stale tags can't satisfy the wait).
    started = []
    for name, _, i in frozen:
        proc, offset = _spawn_nowait(
            run_dir, name,
            [sys.executable, "-m", args.server_module, "-gid", str(i),
             "-restore"] + cfg_argv,
        )
        started.append((name, proc, offset))
    try:
        for name, proc, offset in started:
            _wait_tag(run_dir, name, consts.GAME_STARTED_TAG, proc, offset)
    except SystemExit:
        # Same reap as cmd_start's batch spawn: one failed restore must
        # not leave its batch-mates daemonized (a multihost peer sits
        # wedged at the mesh barrier holding its ports, and the next
        # start/reload fails on port conflicts until a manual `kill`).
        for name, proc, _ in started:
            if proc.poll() is None:
                proc.terminate()
        raise
    print("reload complete")
    return 0


def cmd_status(args) -> int:
    cfg = get_config()
    run_dir = os.path.abspath(args.dir)
    names = _process_names(cfg)
    total = alive = 0
    for kind, group in names.items():
        for name in group:
            total += 1
            pid = _read_pid(run_dir, name)
            up = _alive(pid, _expect_marker(kind, name, getattr(args, "server_module", None) or ""))
            alive += bool(up)
            print(f"  {name}: {'RUNNING pid=' + str(pid[0]) if up else 'not running'}")
    print(f"{alive}/{total} processes running")
    return 0 if alive == total else 1


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="goworld_tpu.cli",
                                     description="goworld_tpu ops CLI (cmd/goworld parity)")
    parser.add_argument("command",
                        choices=["build", "start", "stop", "kill", "reload", "status"])
    parser.add_argument("server_module", nargs="?", default=None,
                        help="python module of the game server (e.g. examples.test_game)")
    parser.add_argument("-configfile", default="goworld.ini" if os.path.exists("goworld.ini") else "")
    parser.add_argument("-dir", default=".", help="run directory (pidfiles + logs)")
    parser.add_argument("-restore", action="store_true", help="start games with -restore")
    args = parser.parse_args(argv)

    if args.configfile:
        set_config_file(os.path.abspath(args.configfile))
    if args.command in ("build", "start", "reload") and not args.server_module:
        parser.error(f"{args.command} requires a server module")
    return {
        "build": cmd_build,
        "start": cmd_start,
        "stop": cmd_stop,
        "kill": cmd_kill,
        "reload": cmd_reload,
        "status": cmd_status,
    }[args.command](args)


if __name__ == "__main__":
    sys.exit(main())

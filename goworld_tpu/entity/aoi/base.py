"""AOI manager interface (reference: aoi.AOIManager seam, Space.go:33)."""

from __future__ import annotations


class AOIManagerBase:
    """Per-space AOI manager interface.

    ``enter``/``leave``/``moved`` update membership; implementations fire
    ``entity.on_enter_aoi(other)`` / ``entity.on_leave_aoi(other)`` either
    synchronously (CPU sweep) or at the next ``tick()`` (batched TPU).
    """

    def enter(self, entity, x: float, z: float) -> None:
        raise NotImplementedError

    def leave(self, entity) -> None:
        raise NotImplementedError

    def moved(self, entity, x: float, z: float) -> None:
        raise NotImplementedError

    def tick(self) -> None:
        """Deliver pending diffs (no-op for synchronous backends)."""

    def destroy(self) -> None:
        """Space destroyed: release resources."""

"""AOI (area-of-interest) managers.

The seam mirrors the reference's ``aoi.AOIManager`` interface
(Space.go:33,105: Enter/Leave/Moved + OnEnterAOI/OnLeaveAOI callbacks on
entities). Two implementations:

- ``XZListAOIManager`` — CPU sweep-list, per-space, synchronous callbacks
  (reimplementation of the go-aoi XZList idea, SURVEY.md §2.4).
- ``BatchAOIService`` + ``BatchSpaceAOIManager`` — the TPU path: all spaces'
  positions batched into one NeighborEngine launch per tick; enter/leave
  diffs delivered at tick boundaries (SURVEY.md §7.1).
"""

from goworld_tpu.entity.aoi.base import AOIManagerBase
from goworld_tpu.entity.aoi.xzlist import XZListAOIManager
from goworld_tpu.entity.aoi.batched import BatchAOIService, BatchSpaceAOIManager

__all__ = [
    "AOIManagerBase",
    "XZListAOIManager",
    "BatchAOIService",
    "BatchSpaceAOIManager",
]

"""CPU sweep-list AOI manager.

Reference parity: the go-aoi ``XZListAOIManager`` (SURVEY.md §2.4 — sweep
lists sorted by coordinate, O(candidates) neighborhood diffing, one uniform
AOI distance per manager, callbacks fired synchronously inside Enter/Leave/
Moved). This in-repo implementation keeps a list sorted by x; neighbor
queries bisect the x-range then filter by z and euclidean distance —
O(log n + candidates) per update, which matches the reference's per-move
cost profile at demo scales (~hundreds of entities per space).
"""

from __future__ import annotations

import bisect

from goworld_tpu.entity.aoi.base import AOIManagerBase


class _Tracker:
    __slots__ = ("entity", "x", "z", "neighbors")

    def __init__(self, entity, x: float, z: float) -> None:
        self.entity = entity
        self.x = x
        self.z = z
        self.neighbors: set[_Tracker] = set()


class XZListAOIManager(AOIManagerBase):
    def __init__(self, distance: float) -> None:
        self.distance = float(distance)
        self._trackers: dict[object, _Tracker] = {}
        # Sweep list of (x, id(tracker), tracker) kept sorted by x.
        self._xlist: list[tuple[float, int, _Tracker]] = []

    # --- membership --------------------------------------------------------

    def enter(self, entity, x: float, z: float) -> None:
        if entity in self._trackers:
            return
        t = _Tracker(entity, x, z)
        self._trackers[entity] = t
        # Mirror the AOI distance into the slab radius column: the
        # adaptive-sync tier classification (entity/slabs.py) reads it
        # for every backend, and only the batched service fills it
        # otherwise.
        slot = getattr(entity, "_slot", -1)
        slabs = getattr(entity, "_slabs", None)
        if slot >= 0 and slabs is not None:
            slabs.radius[slot] = self.distance
        bisect.insort(self._xlist, (x, id(t), t))
        self._update_neighbors(t)

    def leave(self, entity) -> None:
        t = self._trackers.pop(entity, None)
        if t is None:
            return
        self._xlist.remove((t.x, id(t), t))
        for other in list(t.neighbors):
            self._unlink(t, other)

    def moved(self, entity, x: float, z: float) -> None:
        t = self._trackers.get(entity)
        if t is None:
            return
        self._xlist.remove((t.x, id(t), t))
        t.x = x
        t.z = z
        bisect.insort(self._xlist, (x, id(t), t))
        self._update_neighbors(t)

    # --- internals ---------------------------------------------------------

    def _candidates(self, t: _Tracker):
        d = self.distance
        lo = bisect.bisect_left(self._xlist, (t.x - d, -1, None))
        hi = bisect.bisect_right(self._xlist, (t.x + d, 1 << 62, None))
        for i in range(lo, hi):
            other = self._xlist[i][2]
            if other is not t:
                yield other

    def _in_range(self, a: _Tracker, b: _Tracker) -> bool:
        dx = a.x - b.x
        dz = a.z - b.z
        return dx * dx + dz * dz <= self.distance * self.distance

    def _update_neighbors(self, t: _Tracker) -> None:
        current: set[_Tracker] = set()
        for other in self._candidates(t):
            if self._in_range(t, other):
                current.add(other)
        for other in list(t.neighbors - current):
            self._unlink(t, other)
        for other in current - t.neighbors:
            self._link(t, other)

    @staticmethod
    def _link(a: _Tracker, b: _Tracker) -> None:
        a.neighbors.add(b)
        b.neighbors.add(a)
        a.entity.on_enter_aoi(b.entity)
        b.entity.on_enter_aoi(a.entity)

    @staticmethod
    def _unlink(a: _Tracker, b: _Tracker) -> None:
        a.neighbors.discard(b)
        b.neighbors.discard(a)
        a.entity.on_leave_aoi(b.entity)
        b.entity.on_leave_aoi(a.entity)

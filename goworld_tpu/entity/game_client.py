"""Server-side proxy of an entity's connected client.

Reference parity: ``engine/entity/GameClient.go:16-121`` — every message to
the client is routed through the dispatcher selected by the *owner entity's*
id (GameClient.go:114-121), so client-bound traffic stays FIFO with the
entity's other traffic.
"""

from __future__ import annotations

from goworld_tpu import dispatchercluster


class GameClient:
    __slots__ = ("clientid", "gateid", "owner_id", "gate_gen")

    def __init__(self, clientid: str, gateid: int, owner_id: str,
                 gate_gen: int = 0) -> None:
        self.clientid = clientid
        self.gateid = gateid
        self.owner_id = owner_id
        # Generation of the gate PROCESS this client connected through
        # (minted per gate boot, carried on NOTIFY_CLIENT_CONNECTED): a
        # restarted gate's stale-client detach names the valid generation,
        # so the broadcast is ordering-independent — it can never detach a
        # client that connected through the NEW gate process, no matter
        # which dispatcher link delivered it first. 0 = unknown (legacy).
        self.gate_gen = gate_gen

    def _sender(self):
        return dispatchercluster.select_by_entity_id(self.owner_id)

    # --- entity mirror lifecycle ------------------------------------------

    def send_create_entity(self, entity, is_player: bool) -> None:
        # Own client sees Client+AllClients attrs; other clients (AOI
        # neighbors) see AllClients attrs only (Entity.go:814-917).
        attrs = entity.client_attrs() if is_player else entity.all_client_attrs()
        pos = entity.position
        self._sender().send_create_entity_on_client(
            self.gateid,
            self.clientid,
            is_player,
            entity.id,
            entity.typename,
            attrs,
            pos.x,
            pos.y,
            pos.z,
            entity.yaw,
        )

    def send_destroy_entity(self, entity) -> None:
        self._sender().send_destroy_entity_on_client(
            self.gateid, self.clientid, entity.typename, entity.id
        )

    # --- attr streaming ----------------------------------------------------

    def send_map_attr_change(self, eid: str, path: list, key: str, val) -> None:
        self._sender().send_notify_map_attr_change_on_client(
            self.gateid, self.clientid, eid, path, key, val
        )

    def send_map_attr_del(self, eid: str, path: list, key: str) -> None:
        self._sender().send_notify_map_attr_del_on_client(
            self.gateid, self.clientid, eid, path, key
        )

    def send_map_attr_clear(self, eid: str, path: list) -> None:
        self._sender().send_notify_map_attr_clear_on_client(
            self.gateid, self.clientid, eid, path
        )

    def send_list_attr_change(self, eid: str, path: list, index: int, val) -> None:
        self._sender().send_notify_list_attr_change_on_client(
            self.gateid, self.clientid, eid, path, index, val
        )

    def send_list_attr_append(self, eid: str, path: list, val) -> None:
        self._sender().send_notify_list_attr_append_on_client(
            self.gateid, self.clientid, eid, path, val
        )

    def send_list_attr_pop(self, eid: str, path: list) -> None:
        self._sender().send_notify_list_attr_pop_on_client(
            self.gateid, self.clientid, eid, path
        )

    # --- RPC / filter props -------------------------------------------------

    def call(self, eid: str, method: str, args: tuple) -> None:
        self._sender().send_call_entity_method_on_client(
            self.gateid, self.clientid, eid, method, args
        )

    def set_filter_prop(self, key: str, val: str) -> None:
        self._sender().send_set_clientproxy_filter_prop(
            self.gateid, self.clientid, key, val
        )

    def clear_filter_props(self) -> None:
        self._sender().send_clear_clientproxy_filter_props(self.gateid, self.clientid)

    def __repr__(self) -> str:
        return f"GameClient<{self.clientid}@gate{self.gateid}>"

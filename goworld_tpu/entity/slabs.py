"""Columnar entity slabs: structure-of-arrays storage for hot entity state.

The "Essence of Entity Component System" refactor (ROADMAP item 2): the
per-entity hot fields — position/yaw, sync flags, client binding — live in
process-wide numpy columns indexed by a per-entity SLOT, and the Python
``Entity`` object holds only the slot (its ``position``/``yaw``/``client``
attributes are descriptor views over these columns, entity/entity.py).
What this buys:

- ``collect_entity_sync_infos`` becomes pure column ops: the own-client
  rows are one boolean-mask gather over the flag slab and the neighbor
  fan-out rows come from a slot-indexed interest-edge table instead of a
  Python loop over every entity's ``interested_by`` set — the per-gate
  wire buffers are built by column assignment with zero Python row tuples
  (the ``game_pack`` hop that dominated the fan-out pipeline in ISSUE 6's
  per-hop breakdown).
- The batched AOI engine reads positions STRAIGHT from the slab: the
  ``xz`` column is the (N, 2) float32 array ``NeighborEngine.step_async``
  takes, so a position write IS the AOI update (aoi/batched.py allocates
  its slots from this store — one slot space, no mirroring).
- Per-class batched behaviors: a class defining a classmethod
  ``on_tick_batch(view)`` gets ONE call per tick over a
  :class:`SlabTickView` of all its live entities (``run_tick_batches``),
  replacing N per-entity timer callbacks; ``vmapped_position_tick`` lifts
  a pure numeric per-entity function into that hook via jax.jit+vmap
  (AsyncTaichi's imperative-to-batched lowering, PAPERS.md).

Slot lifecycle (mirrors the AOI engine's quarantine contract): a slot is
allocated at entity construction and released at destroy; while a batched
AOI service is attached, released slots are QUARANTINED until the engine
step that observed their deactivation has delivered its events — the
entity mapping survives quarantine so in-flight leave diffs still resolve,
and a slot can never be re-issued (aliased) mid-tick. Release always
clears the flag/client/eid columns first, so the vectorized sync collect
structurally cannot emit rows for destroyed entities or unbound clients.
"""

from __future__ import annotations

import inspect
import time
from typing import Optional

import numpy as np

from goworld_tpu import telemetry
from goworld_tpu.entity.columns import ColumnSpec, columnar_tick
from goworld_tpu.utils import gwutils

# sync-info flags (Entity.go sifSyncOwnClient / sifSyncNeighborClients).
# Defined HERE (entity/entity.py re-exports them) so the columnar collect
# needs no import of the entity module.
SIF_SYNC_OWN_CLIENT = 1
SIF_SYNC_NEIGHBOR_CLIENTS = 2

_INITIAL_CAPACITY = 256
_INITIAL_EDGES = 256

# One wire block of the game→dispatcher→gate sync fan-out:
# [clientid(16)][sync record: eid(16) + x,y,z,yaw float32] — the canonical
# layout lives with the other wire dtypes in proto/conn.py.
from goworld_tpu.proto.conn import (  # noqa: E402
    CLIENT_DELTA_SYNC_BLOCK_DTYPE,
    CLIENT_SYNC_BLOCK_DTYPE,
)

# --- adaptive per-client sync telemetry ([sync]; module-scope per R5) --------
_M_TIER_EDGES = telemetry.gauge(
    "sync_tier_edges",
    "Interest pairs per sync cadence tier at the last classification "
    "(tier 0 = full rate; higher tiers sync at 1/cadence).", ("tier",))
_M_SYNC_RECORDS = telemetry.counter(
    "sync_records_total",
    "Position-sync records emitted by the tiered collect, by encoding "
    "(keyframe = full-precision 48 B block, delta = quantized 40 B block).",
    ("kind",))
_M_SYNC_BYTES = telemetry.counter(
    "sync_wire_bytes_total",
    "Wire bytes of the game-side sync buffers, by encoding.", ("kind",))
_M_SYNC_SUPPRESSED = telemetry.counter(
    "sync_records_suppressed_total",
    "Neighbor sync rows gated off by their pair's cadence tier (the "
    "sublinear fan-out win, as a live counter).")
_M_BYTES_PER_CLIENT = telemetry.gauge(
    "sync_bytes_per_client_per_s",
    "Rolling sync wire bytes per bound client per second served by this "
    "game (~1 s window; live while [sync] tiering/quantization is on — "
    "the gwtop SYNC column's bytes half).")
_M_KEYFRAMES_FORCED = telemetry.counter(
    "sync_keyframes_forced_total",
    "Full-precision keyframes forced outside the periodic schedule "
    "(new_pair: first emission for a pair; rebind: the watcher's client "
    "changed since the baseline; teleport: delta overflowed the int16 "
    "range).", ("reason",))
_EMPTY = b""
_KIND_KEY = _M_SYNC_RECORDS.labels("keyframe")
_KIND_DELTA = _M_SYNC_RECORDS.labels("delta")
_BYTES_KEY = _M_SYNC_BYTES.labels("keyframe")
_BYTES_DELTA = _M_SYNC_BYTES.labels("delta")
_FORCED_NEW = _M_KEYFRAMES_FORCED.labels("new_pair")
_FORCED_REBIND = _M_KEYFRAMES_FORCED.labels("rebind")
_FORCED_TELEPORT = _M_KEYFRAMES_FORCED.labels("teleport")


class SyncTuning:
    """Resolved [sync] knobs on the slab store (config/read_config.py
    SyncConfig; defaults = the legacy full-rate/full-precision path)."""

    __slots__ = ("cadences", "quantize_bits", "step", "keyframe_interval",
                 "near_ratio", "far_ratio", "retier_interval", "enabled")

    def __init__(self, tier_cadences=(1,), quantize_bits=0,
                 keyframe_interval=32, near_ratio=0.5, far_ratio=0.8,
                 retier_interval=8) -> None:
        self.cadences = np.asarray(tier_cadences, np.int32)
        self.quantize_bits = int(quantize_bits)
        self.step = np.float32(2.0 ** -self.quantize_bits)
        self.keyframe_interval = int(keyframe_interval)
        self.near_ratio = float(near_ratio)
        self.far_ratio = float(far_ratio)
        self.retier_interval = int(retier_interval)
        # The legacy path is the special case of one full-rate tier and
        # full precision; anything else takes the tiered collect.
        self.enabled = len(self.cadences) > 1 or self.quantize_bits > 0


def classify_tiers(d2: np.ndarray, radius: np.ndarray, n_tiers: int,
                   near_ratio: float, far_ratio: float,
                   last_d2: np.ndarray | None = None) -> np.ndarray:
    """Distance/approach-rate tier classification, shared verbatim by the
    host re-tier pass and the test oracles (the device pass in
    ops/neighbor.py mirrors this formula in jnp — pinned by parity tests).

    ratio = dist / watcher AOI radius: <= near_ratio -> tier 0,
    >= far_ratio -> the last tier, linear spread between. A pair whose
    distance SHRANK since the previous classification (``last_d2``) is
    approaching and drops one tier toward full rate — an inbound player
    must sharpen before arrival, not after."""
    r2 = np.maximum(radius.astype(np.float32) ** 2, np.float32(1e-12))
    ratio2 = d2 / r2
    span = max(far_ratio - near_ratio, 1e-9)
    frac = (np.sqrt(ratio2) - near_ratio) / span
    tier = 1 + np.floor(frac * (n_tiers - 1)).astype(np.int32)
    tier = np.clip(tier, 0, n_tiers - 1)
    tier[ratio2 <= near_ratio * near_ratio] = 0
    if last_d2 is not None:
        tier = np.where(d2 < last_d2, np.maximum(tier - 1, 0), tier)
    return tier.astype(np.uint8)


class _TickBucket:
    """Live entities of one on_tick_batch class: a dense entity list with a
    mirrored slot array (swap-remove keeps both O(1) per add/remove)."""

    __slots__ = ("entities", "slots", "index", "last_tick")

    def __init__(self) -> None:
        self.entities: list = []
        self.slots = np.empty(8, np.int32)
        self.index: dict[int, int] = {}  # id(entity) -> dense position
        self.last_tick = 0.0

    def add(self, entity, slot: int) -> None:
        key = id(entity)
        if key in self.index:
            return
        n = len(self.entities)
        if n == len(self.slots):
            self.slots = np.resize(self.slots, n * 2)
        self.entities.append(entity)
        self.slots[n] = slot
        self.index[key] = n

    def remove(self, entity) -> None:
        pos = self.index.pop(id(entity), None)
        if pos is None:
            return
        last = len(self.entities) - 1
        if pos != last:
            moved = self.entities[last]
            self.entities[pos] = moved
            self.slots[pos] = self.slots[last]
            self.index[id(moved)] = pos
        self.entities.pop()


class SlabTickView:
    """One class's entities as columns, handed to ``on_tick_batch``.

    ``x``/``y``/``z``/``yaw`` are float32 gathers (copies — mutate freely);
    ``entities`` is the matching object list and ``dt`` the seconds since
    this class's previous batch tick. ``set_position_yaw`` writes columns
    back, marks every written entity for own+neighbor client sync (the
    exact ``_set_position_yaw`` contract), and notifies non-columnar AOI
    backends; entities destroyed by the hook mid-batch are skipped.
    """

    __slots__ = ("_slabs", "_slots", "entities", "dt")

    def __init__(self, slabs: "EntitySlabs", slots: np.ndarray,
                 entities: list, dt: float) -> None:
        self._slabs = slabs
        self._slots = slots
        self.entities = entities
        self.dt = dt

    def __len__(self) -> int:
        return len(self.entities)

    @property
    def slots(self) -> np.ndarray:
        return self._slots

    @property
    def x(self) -> np.ndarray:
        return self._slabs.xz[self._slots, 0]

    @property
    def y(self) -> np.ndarray:
        return self._slabs.y[self._slots]

    @property
    def z(self) -> np.ndarray:
        return self._slabs.xz[self._slots, 1]

    @property
    def yaw(self) -> np.ndarray:
        return self._slabs.yaw[self._slots]

    def col(self, name: str) -> np.ndarray:
        """Gathered copy of a declared Column attr for this view's rows
        (entity/columns.py); mutate freely, write back via set_col."""
        return self._slabs.columns[name][self._slots]

    def set_col(self, name: str, values) -> None:
        """Write a Column attr for every row of the view. No sync flags —
        Column attrs stream per-entity via attrs.set(), not via the batch
        path (columns.py module docstring). Rows whose entity was
        destroyed mid-batch are quarantined slots; the stale write is
        harmless (defaults are rewritten at re-allocation)."""
        s = self._slabs
        s.columns[name][self._slots] = values
        # Host-side hook writes win over an in-flight fused tick's
        # writeback (aoi/batched.py _consume_fused).
        s.fused_dirty[self._slots] = True

    def set_position_yaw(self, x=None, y=None, z=None, yaw=None) -> None:
        s = self._slabs
        slots = self._slots
        entities = self.entities
        # A hook may destroy entities mid-batch (their slots are released/
        # quarantined); write only the still-live rows.
        alive = np.fromiter(
            (not getattr(e, "_destroyed", False) for e in entities),
            bool, count=len(entities))
        if not alive.all():
            idx = np.flatnonzero(alive)
            slots = slots[idx]
            entities = [entities[i] for i in idx]
            x = x if x is None else np.asarray(x)[idx]
            y = y if y is None else np.asarray(y)[idx]
            z = z if z is None else np.asarray(z)[idx]
            yaw = yaw if yaw is None else np.asarray(yaw)[idx]
        if x is not None:
            s.xz[slots, 0] = x
        if y is not None:
            s.y[slots] = y
        if z is not None:
            s.xz[slots, 1] = z
        if yaw is not None:
            s.yaw[slots] = yaw
        s.flags[slots] |= SIF_SYNC_OWN_CLIENT | SIF_SYNC_NEIGHBOR_CLIENTS
        # Host hook wrote positions: an in-flight fused tick's writeback
        # must not clobber them (aoi/batched.py _consume_fused).
        s.fused_dirty[slots] = True
        # Non-columnar AOI backends (xzlist) keep per-entity structures;
        # the batched manager reads positions from the slab directly
        # (positions_in_slabs) and needs no per-entity notification.
        if x is not None or z is not None:
            nx = s.xz[slots, 0]
            nz = s.xz[slots, 1]
            for i, e in enumerate(entities):
                sp = getattr(e, "space", None)
                if sp is None:
                    continue
                mgr = getattr(sp, "aoi_mgr", None)
                if mgr is None or getattr(mgr, "positions_in_slabs", False):
                    continue
                desc = getattr(e, "_type_desc", None)
                if desc is not None and desc.use_aoi:
                    mgr.moved(e, float(nx[i]), float(nz[i]))


def vmapped_position_tick(fn):
    """Lift a pure per-entity numeric function into an ``on_tick_batch``
    classmethod: ``fn(x, y, z, yaw, dt) -> (x, y, z, yaw)`` on scalars,
    applied to every live entity of the class in ONE ``jax.jit(jax.vmap)``
    call per tick (compiled once, cached on the hook; numpy fallback when
    jax is unavailable). The column-free case of
    :func:`goworld_tpu.entity.columns.columnar_tick`, which this now
    delegates to — and therefore fusion-eligible like any columnar hook
    (``[aoi] fuse_logic`` compiles ``fn`` into the AOI step jit)."""
    return columnar_tick(fn, ())


class EntitySlabs:
    """The process-wide slab store: one slot per live entity."""

    def __init__(self, capacity: int = _INITIAL_CAPACITY) -> None:
        capacity = max(8, int(capacity))
        self.capacity = capacity
        self.xz = np.zeros((capacity, 2), np.float32)
        self.y = np.zeros(capacity, np.float32)
        self.yaw = np.zeros(capacity, np.float32)
        self.flags = np.zeros(capacity, np.uint8)
        self.syncing = np.zeros(capacity, np.uint8)
        self.gateid = np.zeros(capacity, np.int32)
        self.cid = np.zeros(capacity, "S16")
        # Mirror of `cid != b""` kept as bool so the per-collect masks are
        # byte-flag gathers, not 16-byte string compares.
        self.has_client = np.zeros(capacity, bool)
        self.eid = np.zeros(capacity, "S16")
        # Batched-AOI meta columns (the engine's active/space/radius inputs
        # live here so one growth path covers every per-slot array).
        self.active = np.zeros(capacity, bool)
        self.space_ids = np.zeros(capacity, np.int32)
        self.radius = np.zeros(capacity, np.float32)
        # Declared attr columns (entity/columns.py): one process-wide
        # array per Column attr name, allocated lazily on the first
        # entity of a declaring type and shared across types (specs must
        # match). Ride the same grow/quarantine/recycle machinery as the
        # built-in columns.
        self.columns: dict[str, np.ndarray] = {}
        self.column_specs: dict[str, ColumnSpec] = {}
        # Host-write fence for the fused tick (aoi/batched.py): a slot
        # whose position/yaw/columns were written host-side since the
        # last fused dispatch is skipped by that dispatch's writeback —
        # host writes (teleports, client sync, restore, release/realloc)
        # win over the in-flight device logic for that slot.
        self.fused_dirty = np.zeros(capacity, bool)
        self.entities: list = [None] * capacity
        self._free: list[int] = list(range(capacity - 1, -1, -1))
        self._quarantine: list[int] = []
        self.used = 0
        # Hard ceiling (multihost AOI slabs are fixed-size); None = grow.
        self.max_capacity: Optional[int] = None
        self.exhausted_hint = ""
        # The attached batched-AOI service, if any: released slots then
        # defer recycling to its dispatch/deliver cycle (see module doc).
        self.aoi_service = None
        # Interest edges, slot-indexed: edge (subject, watcher) exists iff
        # watcher.interested_in contains subject (maintained by
        # Entity.interest/uninterest). _edge_refs[slot] counts edges
        # touching a slot so release() can skip the purge scan when the
        # interest sets were already severed (the normal path).
        self._e_subj = np.zeros(_INITIAL_EDGES, np.int32)
        self._e_wat = np.zeros(_INITIAL_EDGES, np.int32)
        self._e_n = 0
        self._e_map: dict[int, int] = {}
        self._edge_refs = np.zeros(capacity, np.int32)
        # Adaptive-sync per-edge state ([sync]; swap-removed in tandem
        # with the edge itself): cadence tier, delta baseline (the exact
        # position the watcher's client last converged to), whether that
        # baseline is live, the clientid it was established against
        # (self-healing rebind detection), the collection at/after which
        # a periodic keyframe is due, unsent-movement pending, and the
        # distance^2 at the last classification (approach detection).
        self._e_tier = np.zeros(_INITIAL_EDGES, np.uint8)
        self._e_base = np.zeros((_INITIAL_EDGES, 4), np.float32)
        self._e_bvalid = np.zeros(_INITIAL_EDGES, bool)
        self._e_bcid = np.zeros(_INITIAL_EDGES, "S16")
        self._e_key_at = np.zeros(_INITIAL_EDGES, np.int64)
        self._e_pending = np.zeros(_INITIAL_EDGES, bool)
        self._e_last_d2 = np.full(_INITIAL_EDGES, np.inf, np.float32)
        # Edge-churn-only version (the broader _topo_version also counts
        # bindings/flags): guards the device tier writeback — a tier
        # vector computed against a different edge layout is discarded.
        self._edge_version = 0
        # Edge delta log for the fused interest-edge delivery (aoi/
        # batched.py): while a list is installed here (swapped fresh at
        # every AOI dispatch that ships device edge verdicts), every
        # edge add/remove appends its key so the decode can tell which
        # verdicts the pipelined delivery window made stale. None = off.
        self.edge_log: list | None = None
        # Own-client delta baselines, per SLOT (an entity syncing to its
        # own client rides full rate but still delta-encodes).
        self.own_base = np.zeros((capacity, 4), np.float32)
        self.own_bvalid = np.zeros(capacity, bool)
        self.own_bcid = np.zeros(capacity, "S16")
        self.own_key_at = np.zeros(capacity, np.int64)
        # [sync] tuning + collection sequence; device-pass bookkeeping
        # (True while an attached batched AOI service ships tiers inside
        # the engine launch — host re-tiering then stands down).
        self.sync = SyncTuning()
        self._collect_seq = 0
        self.device_tiers = False
        # ~1 s rolling window feeding sync_bytes_per_client_per_s.
        self._rate_stamp = time.monotonic()
        self._rate_bytes = 0
        # Per-class batched tick hooks (on_tick_batch classes only).
        self._tick_buckets: dict[type, _TickBucket] = {}
        # Steady-state sync-selection cache: a mover population that flags
        # the same slots with the same bits every collection (the common
        # case — avatars moving every tick) re-derives an IDENTICAL
        # selection, so the row selection, the per-gate grouping, and the
        # cid/eid halves of the wire blocks are reused verbatim and only
        # the position columns are refilled. Keyed by a topology version
        # bumped on every input the selection reads besides the flags
        # (interest edges, client bindings, syncing marks, slot release) +
        # a memcmp of the flagged slots/bits.
        self._topo_version = 0
        self._sync_cache = None  # (flagged, f, version, sel, out, gates_dict)
        telemetry.gauge(
            "entity_slab_capacity",
            "Allocated slot capacity of the entity slab store.",
        ).set_function(lambda: self.capacity)
        telemetry.gauge(
            "entity_slab_used",
            "Live (allocated, unreleased) entity slab slots.",
        ).set_function(lambda: self.used)

    # --- allocation ---------------------------------------------------------

    def alloc(self, entity) -> int:
        """Allocate a slot for ``entity`` (its row starts zeroed)."""
        if not self._free:
            if (self.max_capacity is not None
                    and self.capacity >= self.max_capacity):
                raise RuntimeError(
                    self.exhausted_hint
                    or f"entity slab capacity {self.capacity} exhausted")
            self._grow(self.capacity * 2)
        slot = self._free.pop()
        self.entities[slot] = entity
        self.used += 1
        cls = type(entity)
        desc = getattr(cls, "_type_desc", None)
        colspecs = getattr(desc, "column_attrs", None)
        if colspecs:
            for spec in colspecs.values():
                self.ensure_column(spec)[slot] = spec.default
        # A fresh allocation invalidates any in-flight fused writeback
        # aimed at this slot's previous tenant (aoi/batched.py).
        self.fused_dirty[slot] = True
        if getattr(cls, "on_tick_batch", None) is not None:
            self._tick_register(cls, entity, slot)
        return slot

    def ensure_column(self, spec: ColumnSpec) -> np.ndarray:
        """Get-or-create the slab column for ``spec``. Two entity types
        may share a column name only with an identical (dtype, default)
        spec — the storage is one array."""
        cur = self.column_specs.get(spec.name)
        if cur is not None:
            if cur != spec:
                raise ValueError(
                    f"Column {spec.name!r} redeclared with a different "
                    f"spec: {cur} vs {spec}")
            return self.columns[spec.name]
        arr = np.full(self.capacity, spec.default, spec.np_dtype)
        self.columns[spec.name] = arr
        self.column_specs[spec.name] = spec
        return arr

    def release(self, slot: int, entity=None) -> None:
        """Destroy-time release: clear the row's sync-visible columns (so
        the vectorized collect can never emit for it), purge any interest
        edges still referencing it, and quarantine or recycle the slot."""
        e = self.entities[slot] if entity is None else entity
        self._topo_version += 1
        self.flags[slot] = 0
        self.syncing[slot] = 0
        self.cid[slot] = b""
        self.has_client[slot] = False
        self.eid[slot] = b""
        self.gateid[slot] = 0
        # Delta-sync baselines die with the tenant: the next entity on
        # this slot must keyframe before any delta (own_bcid mismatch
        # would also catch it, but an explicit clear is cheaper to reason
        # about than a 16-byte compare saving us).
        self.own_bvalid[slot] = False
        self.own_bcid[slot] = b""
        # Columns reset to their declared defaults (a quarantined slot's
        # stale values must never leak into its next tenant) and the slot
        # is fenced against any in-flight fused writeback.
        for name, arr in self.columns.items():
            arr[slot] = self.column_specs[name].default
        self.fused_dirty[slot] = True
        if self.active[slot]:
            self.active[slot] = False
            if self.aoi_service is not None:
                self.aoi_service._meta_dirty = True
        if self._edge_refs[slot]:
            self._purge_edges(slot)
        if e is not None:
            cls = type(e)
            bucket = self._tick_buckets.get(cls)
            if bucket is not None:
                bucket.remove(e)
        self.used -= 1
        if self.aoi_service is not None:
            # The entity mapping survives quarantine: the in-flight engine
            # step may still deliver this slot's leave events.
            self._quarantine.append(slot)
        else:
            self.entities[slot] = None
            self._free.append(slot)

    def take_quarantine(self) -> list[int]:
        """Hand the current quarantine to the AOI dispatch that will observe
        these slots' deactivation (recycled via :meth:`recycle` after that
        step's events have been delivered)."""
        q = self._quarantine
        self._quarantine = []
        return q

    def recycle(self, slots) -> None:
        for slot in slots:
            self.entities[slot] = None
            self._free.append(slot)

    def ensure_capacity(self, n: int) -> None:
        if n > self.capacity:
            cap = self.capacity
            while cap < n:
                cap *= 2
            self._grow(max(cap, n))

    def _grow(self, n: int) -> None:
        old = self.capacity

        def pad(arr, shape, dtype):
            out = np.zeros(shape, dtype)
            out[: arr.shape[0]] = arr
            return out

        self.xz = pad(self.xz, (n, 2), np.float32)
        self.y = pad(self.y, (n,), np.float32)
        self.yaw = pad(self.yaw, (n,), np.float32)
        self.flags = pad(self.flags, (n,), np.uint8)
        self.syncing = pad(self.syncing, (n,), np.uint8)
        self.gateid = pad(self.gateid, (n,), np.int32)
        self.cid = pad(self.cid, (n,), "S16")
        self.has_client = pad(self.has_client, (n,), bool)
        self.eid = pad(self.eid, (n,), "S16")
        self.active = pad(self.active, (n,), bool)
        self.space_ids = pad(self.space_ids, (n,), np.int32)
        self.radius = pad(self.radius, (n,), np.float32)
        self.fused_dirty = pad(self.fused_dirty, (n,), bool)
        self.own_base = pad(self.own_base, (n, 4), np.float32)
        self.own_bvalid = pad(self.own_bvalid, (n,), bool)
        self.own_bcid = pad(self.own_bcid, (n,), "S16")
        self.own_key_at = pad(self.own_key_at, (n,), np.int64)
        for name, arr in self.columns.items():
            # New rows start at the column's declared default, not zero.
            spec = self.column_specs[name]
            grown = np.full(n, spec.default, arr.dtype)
            grown[: arr.shape[0]] = arr
            self.columns[name] = grown
        self._edge_refs = pad(self._edge_refs, (n,), np.int32)
        self.entities.extend([None] * (n - old))
        # New slots go UNDER existing free ones so pop() hands out the
        # lowest unused index first (keeps engine-visible slots dense).
        self._free = list(range(n - 1, old - 1, -1)) + self._free
        self.capacity = n

    # --- interest edges -----------------------------------------------------

    def edge_add(self, subj: int, watcher: int) -> None:
        key = (subj << 32) | watcher
        if key in self._e_map:
            return
        n = self._e_n
        if n == len(self._e_subj):
            self._e_subj = np.resize(self._e_subj, n * 2)
            self._e_wat = np.resize(self._e_wat, n * 2)
            self._e_tier = np.resize(self._e_tier, n * 2)
            base = np.zeros((n * 2, 4), np.float32)
            base[:n] = self._e_base
            self._e_base = base
            self._e_bvalid = np.resize(self._e_bvalid, n * 2)
            self._e_bcid = np.resize(self._e_bcid, n * 2)
            self._e_key_at = np.resize(self._e_key_at, n * 2)
            self._e_pending = np.resize(self._e_pending, n * 2)
            self._e_last_d2 = np.resize(self._e_last_d2, n * 2)
        self._e_subj[n] = subj
        self._e_wat[n] = watcher
        # Fresh pair: full rate until classified, no baseline — the
        # FIRST emission (the subject's next movement) is a forced
        # keyframe; until then the client renders the position the
        # CREATE_ENTITY_ON_CLIENT carried, exactly like the legacy path.
        self._e_tier[n] = 0
        self._e_bvalid[n] = False
        self._e_pending[n] = False
        self._e_last_d2[n] = np.inf
        self._e_map[key] = n
        self._e_n = n + 1
        self._edge_refs[subj] += 1
        self._edge_refs[watcher] += 1
        self._topo_version += 1
        self._edge_version += 1
        if self.edge_log is not None:
            self.edge_log.append(key)

    def edge_remove(self, subj: int, watcher: int) -> None:
        key = (subj << 32) | watcher
        idx = self._e_map.pop(key, None)
        if idx is None:
            return
        last = self._e_n - 1
        if idx != last:
            ls, lw = int(self._e_subj[last]), int(self._e_wat[last])
            self._e_subj[idx] = ls
            self._e_wat[idx] = lw
            self._e_tier[idx] = self._e_tier[last]
            self._e_base[idx] = self._e_base[last]
            self._e_bvalid[idx] = self._e_bvalid[last]
            self._e_bcid[idx] = self._e_bcid[last]
            self._e_key_at[idx] = self._e_key_at[last]
            self._e_pending[idx] = self._e_pending[last]
            self._e_last_d2[idx] = self._e_last_d2[last]
            self._e_map[(ls << 32) | lw] = idx
        self._e_n = last
        self._edge_refs[subj] -= 1
        self._edge_refs[watcher] -= 1
        self._topo_version += 1
        self._edge_version += 1
        if self.edge_log is not None:
            self.edge_log.append(key)

    def edge_count(self) -> int:
        return self._e_n

    def _purge_edges(self, slot: int) -> None:
        """Backstop for release(): drop edges still naming a slot whose
        interest sets were not severed (destroy outside any AOI space)."""
        n = self._e_n
        subj, wat = self._e_subj[:n], self._e_wat[:n]
        hits = np.flatnonzero((subj == slot) | (wat == slot))
        for s, w in [(int(subj[i]), int(wat[i])) for i in hits]:
            self.edge_remove(s, w)

    # --- vectorized sync collection ----------------------------------------

    def touch_sync_topology(self) -> None:
        """Invalidate the steady-state sync-selection cache. Called on every
        selection input EXCEPT the flags themselves: interest-edge changes,
        client bind/unbind, syncing-mark changes, slot release."""
        self._topo_version += 1

    def collect_sync_selection(self):
        """Stage 1 of the columnar ``collect_entity_sync_infos`` (the
        ``game_collect`` hop): select which (subject, destination) slot
        pairs emit a sync row this collection. Own-client rows are one
        boolean-mask gather over the flag slab (client bound, not
        client-driven); neighbor rows come from the slot-indexed interest
        edges (watcher has a client). Flags clear for every flagged slot,
        row or not — the legacy per-entity contract. Returns ``None`` when
        nothing is flagged, else an opaque selection for :meth:`pack_sync`.

        Steady-state fast path: when the flagged slots+bits are memcmp-
        identical to the previous collection and nothing the selection
        reads has changed since (``_topo_version``), the previous
        selection — including the per-gate grouping and the cid/eid halves
        of the wire blocks — is reused verbatim; only the float columns
        are refilled by pack_sync."""
        flags = self.flags
        flagged = np.flatnonzero(flags)
        if flagged.size == 0:
            return None
        f = flags[flagged]
        cache = self._sync_cache
        if (
            cache is not None
            and cache[2] == self._topo_version
            and np.array_equal(cache[0], flagged)
            and np.array_equal(cache[1], f)
        ):
            flags[flagged] = 0
            return cache
        has_client = self.has_client
        own = flagged[
            (f & SIF_SYNC_OWN_CLIENT).astype(bool)
            & has_client[flagged]
            & (self.syncing[flagged] == 0)
        ]
        n = self._e_n
        if n:
            subj, wat = self._e_subj[:n], self._e_wat[:n]
            m = (
                (flags[subj] & SIF_SYNC_NEIGHBOR_CLIENTS).astype(bool)
                & has_client[wat]
            )
            nsubj, nwat = subj[m], wat[m]
        else:
            nsubj = nwat = np.empty(0, np.int64)
        flags[flagged] = 0
        subjects = np.concatenate([own, nsubj])
        if subjects.size == 0:
            return None
        dests = np.concatenate([own, nwat])
        gates = self.gateid[dests]
        # Order rows by (gate, destination slot): per-gate buffers come out
        # as ONE contiguous slice each, and within a gate every client's
        # rows form a contiguous run — the gate's demux then slices runs
        # straight off the wire buffer without re-sorting (gate/service.py
        # _handle_sync_on_clients).
        if (gates == gates[0]).all():
            order = np.argsort(dests, kind="stable")
        else:
            order = np.argsort(
                (gates.astype(np.int64) << 32) | dests, kind="stable")
        so, do, gs = subjects[order], dests[order], gates[order]
        out = np.empty(len(so), CLIENT_SYNC_BLOCK_DTYPE)
        out["cid"] = self.cid[do]
        out["eid"] = self.eid[so]
        bounds = [0] + (np.flatnonzero(gs[1:] != gs[:-1]) + 1).tolist()
        bounds.append(len(gs))
        per_gate = {
            int(gs[bounds[i]]): out[bounds[i]:bounds[i + 1]]
            for i in range(len(bounds) - 1)
        }
        cache = (flagged, f, self._topo_version, (so, do, gs), out, per_gate)
        self._sync_cache = cache
        return cache

    def pack_sync(self, selection) -> dict[int, np.ndarray]:
        """Stage 2 (the ``game_pack`` hop): one structured array of
        [cid + sync record] wire blocks per destination gate, built by
        column assignment — zero Python row tuples. The cid/eid halves were
        filled when the selection was built (they are selection-invariant);
        this refills the position/yaw columns from the live slabs. The
        returned per-gate arrays are views into one shared buffer, valid
        until the next collection."""
        so = selection[3][0]
        out = selection[4]
        out["x"] = self.xz[so, 0]
        out["y"] = self.y[so]
        out["z"] = self.xz[so, 1]
        out["yaw"] = self.yaw[so]
        return selection[5]

    def collect_sync(self) -> dict[int, np.ndarray]:
        """Both stages in one call (tests / embedded drivers)."""
        sel = self.collect_sync_selection()
        return {} if sel is None else self.pack_sync(sel)

    # --- adaptive per-client sync ([sync]; ROADMAP item 5) -------------------

    def configure_sync(self, cfg) -> None:
        """Apply a [sync] section (config/read_config.py SyncConfig — any
        object with its fields works — or a pre-built SyncTuning).
        Defaults keep the legacy path."""
        if isinstance(cfg, SyncTuning):
            self.sync = cfg
            return
        self.sync = SyncTuning(
            tier_cadences=tuple(cfg.tier_cadences),
            quantize_bits=cfg.quantize_bits,
            keyframe_interval=cfg.keyframe_interval,
            near_ratio=cfg.near_ratio,
            far_ratio=cfg.far_ratio,
            retier_interval=cfg.retier_interval,
        )

    def _set_tier_gauges(self, tier: np.ndarray) -> None:
        counts = np.bincount(tier, minlength=len(self.sync.cadences))
        for i, c in enumerate(counts.tolist()):
            _M_TIER_EDGES.labels(str(i)).set(c)

    def retier_host(self) -> None:
        """Host-side tier classification of every interest pair: ONE
        vectorized sweep over the edge table amortizing all clients'
        range queries (the batched AOI engine's in-launch tier pass
        supersedes this — ops/neighbor.py — and writes the same column
        via :meth:`apply_device_tiers`)."""
        n = self._e_n
        if n == 0:
            return
        subj, wat = self._e_subj[:n], self._e_wat[:n]
        d = self.xz[subj] - self.xz[wat]
        d2 = d[:, 0] * d[:, 0] + d[:, 1] * d[:, 1]
        sy = self.sync
        tier = classify_tiers(d2, self.radius[wat], len(sy.cadences),
                              sy.near_ratio, sy.far_ratio,
                              self._e_last_d2[:n])
        self._e_tier[:n] = tier
        self._e_last_d2[:n] = d2
        self._set_tier_gauges(tier)

    def snapshot_edges_for_tiering(self):
        """(edge_version, count, subj copy, wat copy) — what the batched
        AOI dispatch ships to the device tier pass. Copies, because the
        edge table swap-removes while the step is in flight."""
        n = self._e_n
        return (self._edge_version, n,
                self._e_subj[:n].copy(), self._e_wat[:n].copy())

    def apply_device_tiers(self, edge_version: int, count: int,
                           tiers: np.ndarray) -> bool:
        """Write a device-computed tier vector back, iff the edge layout
        is unchanged since the snapshot (edge churn between dispatch and
        writeback discards it — affected pairs keep their previous tier
        and brand-new pairs default to full rate: conservative, never
        stale)."""
        if edge_version != self._edge_version or count != self._e_n:
            return False
        if count:
            self._e_tier[:count] = tiers[:count]
            self._set_tier_gauges(self._e_tier[:count])
        return True

    def collect_sync_packets(self) -> dict[int, tuple[bytes, bytes]]:
        """The game-facing sync collection: per destination gate, a
        (full_records, delta_records) byte-buffer pair — full = 48 B
        [cid + keyframe] blocks for SYNC_POSITION_YAW_ON_CLIENTS, delta =
        40 B [cid + quantized delta] blocks for the v6
        SYNC_POSITION_YAW_DELTA_ON_CLIENTS. With the default [sync]
        config this is exactly the legacy full-rate path (same cache,
        same bytes, empty delta halves)."""
        if not self.sync.enabled:
            sel = self.collect_sync_selection()
            if sel is None:
                return {}
            return {g: (arr.tobytes(), b"")
                    for g, arr in self.pack_sync(sel).items()}
        out = self._collect_sync_tiered()
        return out if out is not None else {}

    def _emit_mask(self, seq: int):
        """Stage 1 of the tiered collect: which edges emit THIS collection.
        Movement latches per edge (``_e_pending``) so a mover's final
        position always flows out when its pair's tier next comes due —
        a tier-k pair is never staler than its cadence, and a stationary
        world emits nothing at any tier."""
        n = self._e_n
        if n == 0:
            return None
        sy = self.sync
        flags = self.flags
        subj, wat = self._e_subj[:n], self._e_wat[:n]
        pend = self._e_pending[:n]
        pend |= (flags[subj] & SIF_SYNC_NEIGHBOR_CLIENTS).astype(bool)
        watc = self.has_client[wat]
        cad = sy.cadences[self._e_tier[:n]]
        phase = (subj.astype(np.int64) * 2654435761 + wat) % cad
        due = (seq % cad) == phase
        bvalid = self._e_bvalid[:n]
        rebind = bvalid & (self._e_bcid[:n] != self.cid[wat])
        forced = (~bvalid) | rebind | (self._e_key_at[:n] <= seq)
        emit = pend & watc & (due | forced)
        suppressed = int(np.count_nonzero(pend & watc & ~emit))
        if suppressed:
            _M_SYNC_SUPPRESSED.inc(suppressed)
        eidx = np.flatnonzero(emit)
        pend[eidx] = False
        return eidx

    def _collect_sync_tiered(self) -> dict[int, tuple[bytes, bytes]] | None:
        """The tiered + delta-encoded collection (single stage: selection,
        quantization, baseline advance and wire pack in one vectorized
        pass — the steady-state cache doesn't apply because the due
        pattern cycles with the tier cadences).

        Encoding contract (mirrored by the client decode and pinned by the
        roundtrip fuzz in tests/test_synctier.py): a pair's first emission
        — and any emission after a client rebind, past the periodic
        keyframe schedule, or whose delta overflows int16 — is a KEYFRAME
        carrying exact float32 position/yaw; every other emission is a
        delta record of int16 multiples of 2^-quantize_bits. The sender's
        baseline advances by the QUANTIZED delta (never to the true
        position), so the receiver reconstructs the baseline bit-exactly
        and the error vs truth stays <= step/2 forever — quantization
        error cannot accumulate."""
        sy = self.sync
        seq = self._collect_seq
        self._collect_seq = seq + 1
        if (not self.device_tiers and len(sy.cadences) > 1
                and seq % sy.retier_interval == 0):
            self.retier_host()
        flags = self.flags
        flagged = np.flatnonzero(flags)
        eidx = self._emit_mask(seq)
        if flagged.size == 0 and (eidx is None or eidx.size == 0):
            return None
        if flagged.size:
            f = flags[flagged]
            own = flagged[
                (f & SIF_SYNC_OWN_CLIENT).astype(bool)
                & self.has_client[flagged]
                & (self.syncing[flagged] == 0)
            ]
            flags[flagged] = 0
        else:
            own = np.empty(0, np.int64)
        if eidx is None:
            eidx = np.empty(0, np.int64)
        es = self._e_subj[eidx]
        ew = self._e_wat[eidx]
        k_own = own.size
        rs = np.concatenate([own, es])  # subject slot per row
        rd = np.concatenate([own, ew])  # destination slot per row
        if rs.size == 0:
            return None
        pos = np.empty((rs.size, 4), np.float32)
        pos[:, 0] = self.xz[rs, 0]
        pos[:, 1] = self.y[rs]
        pos[:, 2] = self.xz[rs, 1]
        pos[:, 3] = self.yaw[rs]
        base = np.concatenate([self.own_base[own], self._e_base[eidx]])
        bvalid_raw = np.concatenate(
            [self.own_bvalid[own], self._e_bvalid[eidx]])
        cid_ok = np.concatenate(
            [self.own_bcid[own] == self.cid[own],
             self._e_bcid[eidx] == self.cid[ew]])
        bvalid = bvalid_raw & cid_ok
        key_at = np.concatenate(
            [self.own_key_at[own], self._e_key_at[eidx]])
        if sy.quantize_bits == 0:
            key = np.ones(rs.size, bool)
            qd = np.zeros((rs.size, 4), np.int16)
            new_base = pos
        else:
            qf = np.rint((pos - base) / sy.step)
            over = (np.abs(qf) > 32767.0).any(axis=1)
            key = (~bvalid) | over | (key_at <= seq)
            qd = qf.astype(np.int16)
            new_base = np.where(
                key[:, None], pos,
                base + qf.astype(np.float32) * sy.step)
            new_n = int(np.count_nonzero(~bvalid_raw))
            rebind_n = int(np.count_nonzero(bvalid_raw & ~cid_ok))
            tele_n = int(np.count_nonzero(over & bvalid))
            if new_n:
                _FORCED_NEW.inc(new_n)
            if rebind_n:
                _FORCED_REBIND.inc(rebind_n)
            if tele_n:
                _FORCED_TELEPORT.inc(tele_n)
        # Baseline/schedule advance, written back per source table.
        keyed = np.flatnonzero(key)
        new_key_at = np.where(key, seq + sy.keyframe_interval, key_at)
        self.own_base[own] = new_base[:k_own]
        self.own_bvalid[own] = True
        self.own_bcid[own] = self.cid[own]
        self.own_key_at[own] = new_key_at[:k_own]
        self._e_base[eidx] = new_base[k_own:]
        self._e_bvalid[eidx] = True
        self._e_bcid[eidx] = self.cid[ew]
        self._e_key_at[eidx] = new_key_at[k_own:]
        gates_r = self.gateid[rd]
        full = self._pack_rows(
            np.flatnonzero(key), rs, rd, gates_r, pos, qd,
            CLIENT_SYNC_BLOCK_DTYPE)
        delta = self._pack_rows(
            np.flatnonzero(~key), rs, rd, gates_r, pos, qd,
            CLIENT_DELTA_SYNC_BLOCK_DTYPE)
        if keyed.size:
            _KIND_KEY.inc(int(keyed.size))
            _BYTES_KEY.inc(int(keyed.size) * CLIENT_SYNC_BLOCK_DTYPE.itemsize)
        n_delta = rs.size - keyed.size
        if n_delta:
            _KIND_DELTA.inc(n_delta)
            _BYTES_DELTA.inc(n_delta * CLIENT_DELTA_SYNC_BLOCK_DTYPE.itemsize)
        self._rate_bytes += (
            int(keyed.size) * CLIENT_SYNC_BLOCK_DTYPE.itemsize
            + n_delta * CLIENT_DELTA_SYNC_BLOCK_DTYPE.itemsize)
        now = time.monotonic()
        if now - self._rate_stamp >= 1.0:
            clients = int(np.count_nonzero(self.has_client))
            _M_BYTES_PER_CLIENT.set(
                self._rate_bytes / (now - self._rate_stamp)
                / max(1, clients))
            self._rate_stamp = now
            self._rate_bytes = 0
        merged = {
            g: (full.get(g, _EMPTY), delta.get(g, _EMPTY))
            for g in (full.keys() | delta.keys())
        }
        return merged or None

    def _pack_rows(self, idx: np.ndarray, rs: np.ndarray, rd: np.ndarray,
                   gates_r: np.ndarray, pos: np.ndarray, qd: np.ndarray,
                   dtype: np.dtype) -> dict[int, bytes]:
        """Pack one encoding's rows into per-gate wire buffers, ordered by
        (gate, destination slot) so each client's records form one
        contiguous run for the gate's run-slicing demux."""
        if idx.size == 0:
            return {}
        g = gates_r[idx]
        order = np.argsort(
            (g.astype(np.int64) << 32) | rd[idx], kind="stable")
        idx = idx[order]
        g = g[order]
        out = np.empty(idx.size, dtype)
        out["cid"] = self.cid[rd[idx]]
        out["eid"] = self.eid[rs[idx]]
        if dtype is CLIENT_SYNC_BLOCK_DTYPE:
            out["x"] = pos[idx, 0]
            out["y"] = pos[idx, 1]
            out["z"] = pos[idx, 2]
            out["yaw"] = pos[idx, 3]
        else:
            out["dx"] = qd[idx, 0]
            out["dy"] = qd[idx, 1]
            out["dz"] = qd[idx, 2]
            out["dyaw"] = qd[idx, 3]
        bounds = [0] + (np.flatnonzero(g[1:] != g[:-1]) + 1).tolist()
        bounds.append(idx.size)
        return {
            int(g[bounds[i]]): out[bounds[i]:bounds[i + 1]].tobytes()
            for i in range(len(bounds) - 1)
        }

    # --- per-class batched tick hooks --------------------------------------

    def _tick_register(self, cls: type, entity, slot: int) -> None:
        bucket = self._tick_buckets.get(cls)
        if bucket is None:
            hook = inspect.getattr_static(cls, "on_tick_batch", None)
            if not isinstance(hook, (classmethod, staticmethod)):
                raise TypeError(
                    f"{cls.__name__}.on_tick_batch must be a classmethod "
                    f"(one call per CLASS per tick over a SlabTickView)")
            bucket = self._tick_buckets[cls] = _TickBucket()
            bucket.last_tick = time.monotonic()
        bucket.add(entity, slot)

    def prewarm_tick_hooks(self) -> None:
        """Dummy-shaped compile of every adopted class's batched tick jit
        at its CURRENT live population (columnar_tick.prewarm, with the
        class's declared column dtypes). The restore path calls this
        before the cluster re-handshake so the first live tick triggers
        no fresh trace; hooks without a prewarm surface (hand-written
        on_tick_batch bodies) are skipped — whatever they lazily build is
        their own contract. Classes the attached AOI service runs FUSED
        skip the per-class jit (it never executes there) and are instead
        covered by the service's fused-step prewarm, called at the end."""
        svc = self.aoi_service
        take = getattr(svc, "takes_over_tick", None)  # duck test doubles
        for cls, bucket in list(self._tick_buckets.items()):
            n = len(bucket.entities)
            if n == 0:
                continue
            if take is not None and take(cls):
                continue
            hook = inspect.getattr_static(cls, "on_tick_batch", None)
            fn = getattr(hook, "__func__", None)
            pw = getattr(fn, "prewarm", None)
            if pw is None:
                continue
            prog = getattr(fn, "fused_program", None)
            dtypes = None
            if prog is not None and prog.columns:
                dtypes = tuple(
                    self.column_specs[c].dtype for c in prog.columns
                    if c in self.column_specs) or None
            gwutils.run_panicless(
                lambda p=pw, k=n, d=dtypes: p(k, col_dtypes=d))
        pf = getattr(svc, "prewarm_fused", None)
        if pf is not None:
            gwutils.run_panicless(pf)

    def run_tick_batches(self, now: float | None = None) -> None:
        """Fire each adopted class's ``on_tick_batch`` once over its live
        entities (the vectorized replacement for per-entity timers).
        Classes the attached AOI service runs FUSED ([aoi] fuse_logic) are
        skipped: their program executes inside the engine step at the AOI
        cadence instead (aoi/batched.py). Their ``last_tick`` stays fresh
        so a later fallback to host-side execution resumes with a sane
        dt, not one spanning the whole fused period."""
        if not self._tick_buckets:
            return
        if now is None:
            now = time.monotonic()
        take = getattr(self.aoi_service, "takes_over_tick", None)
        for cls, bucket in list(self._tick_buckets.items()):
            n = len(bucket.entities)
            if n == 0:
                continue
            if take is not None and take(cls):
                bucket.last_tick = now
                continue
            dt = now - bucket.last_tick
            bucket.last_tick = now
            view = SlabTickView(
                self, bucket.slots[:n].copy(), list(bucket.entities), dt)
            gwutils.run_panicless(lambda c=cls, v=view: c.on_tick_batch(v))

"""Columnar entity slabs: structure-of-arrays storage for hot entity state.

The "Essence of Entity Component System" refactor (ROADMAP item 2): the
per-entity hot fields — position/yaw, sync flags, client binding — live in
process-wide numpy columns indexed by a per-entity SLOT, and the Python
``Entity`` object holds only the slot (its ``position``/``yaw``/``client``
attributes are descriptor views over these columns, entity/entity.py).
What this buys:

- ``collect_entity_sync_infos`` becomes pure column ops: the own-client
  rows are one boolean-mask gather over the flag slab and the neighbor
  fan-out rows come from a slot-indexed interest-edge table instead of a
  Python loop over every entity's ``interested_by`` set — the per-gate
  wire buffers are built by column assignment with zero Python row tuples
  (the ``game_pack`` hop that dominated the fan-out pipeline in ISSUE 6's
  per-hop breakdown).
- The batched AOI engine reads positions STRAIGHT from the slab: the
  ``xz`` column is the (N, 2) float32 array ``NeighborEngine.step_async``
  takes, so a position write IS the AOI update (aoi/batched.py allocates
  its slots from this store — one slot space, no mirroring).
- Per-class batched behaviors: a class defining a classmethod
  ``on_tick_batch(view)`` gets ONE call per tick over a
  :class:`SlabTickView` of all its live entities (``run_tick_batches``),
  replacing N per-entity timer callbacks; ``vmapped_position_tick`` lifts
  a pure numeric per-entity function into that hook via jax.jit+vmap
  (AsyncTaichi's imperative-to-batched lowering, PAPERS.md).

Slot lifecycle (mirrors the AOI engine's quarantine contract): a slot is
allocated at entity construction and released at destroy; while a batched
AOI service is attached, released slots are QUARANTINED until the engine
step that observed their deactivation has delivered its events — the
entity mapping survives quarantine so in-flight leave diffs still resolve,
and a slot can never be re-issued (aliased) mid-tick. Release always
clears the flag/client/eid columns first, so the vectorized sync collect
structurally cannot emit rows for destroyed entities or unbound clients.
"""

from __future__ import annotations

import inspect
import time
from typing import Optional

import numpy as np

from goworld_tpu import telemetry
from goworld_tpu.entity.columns import ColumnSpec, columnar_tick
from goworld_tpu.utils import gwutils

# sync-info flags (Entity.go sifSyncOwnClient / sifSyncNeighborClients).
# Defined HERE (entity/entity.py re-exports them) so the columnar collect
# needs no import of the entity module.
SIF_SYNC_OWN_CLIENT = 1
SIF_SYNC_NEIGHBOR_CLIENTS = 2

_INITIAL_CAPACITY = 256
_INITIAL_EDGES = 256

# One wire block of the game→dispatcher→gate sync fan-out:
# [clientid(16)][sync record: eid(16) + x,y,z,yaw float32] — the canonical
# layout lives with the other wire dtypes in proto/conn.py.
from goworld_tpu.proto.conn import CLIENT_SYNC_BLOCK_DTYPE  # noqa: E402


class _TickBucket:
    """Live entities of one on_tick_batch class: a dense entity list with a
    mirrored slot array (swap-remove keeps both O(1) per add/remove)."""

    __slots__ = ("entities", "slots", "index", "last_tick")

    def __init__(self) -> None:
        self.entities: list = []
        self.slots = np.empty(8, np.int32)
        self.index: dict[int, int] = {}  # id(entity) -> dense position
        self.last_tick = 0.0

    def add(self, entity, slot: int) -> None:
        key = id(entity)
        if key in self.index:
            return
        n = len(self.entities)
        if n == len(self.slots):
            self.slots = np.resize(self.slots, n * 2)
        self.entities.append(entity)
        self.slots[n] = slot
        self.index[key] = n

    def remove(self, entity) -> None:
        pos = self.index.pop(id(entity), None)
        if pos is None:
            return
        last = len(self.entities) - 1
        if pos != last:
            moved = self.entities[last]
            self.entities[pos] = moved
            self.slots[pos] = self.slots[last]
            self.index[id(moved)] = pos
        self.entities.pop()


class SlabTickView:
    """One class's entities as columns, handed to ``on_tick_batch``.

    ``x``/``y``/``z``/``yaw`` are float32 gathers (copies — mutate freely);
    ``entities`` is the matching object list and ``dt`` the seconds since
    this class's previous batch tick. ``set_position_yaw`` writes columns
    back, marks every written entity for own+neighbor client sync (the
    exact ``_set_position_yaw`` contract), and notifies non-columnar AOI
    backends; entities destroyed by the hook mid-batch are skipped.
    """

    __slots__ = ("_slabs", "_slots", "entities", "dt")

    def __init__(self, slabs: "EntitySlabs", slots: np.ndarray,
                 entities: list, dt: float) -> None:
        self._slabs = slabs
        self._slots = slots
        self.entities = entities
        self.dt = dt

    def __len__(self) -> int:
        return len(self.entities)

    @property
    def slots(self) -> np.ndarray:
        return self._slots

    @property
    def x(self) -> np.ndarray:
        return self._slabs.xz[self._slots, 0]

    @property
    def y(self) -> np.ndarray:
        return self._slabs.y[self._slots]

    @property
    def z(self) -> np.ndarray:
        return self._slabs.xz[self._slots, 1]

    @property
    def yaw(self) -> np.ndarray:
        return self._slabs.yaw[self._slots]

    def col(self, name: str) -> np.ndarray:
        """Gathered copy of a declared Column attr for this view's rows
        (entity/columns.py); mutate freely, write back via set_col."""
        return self._slabs.columns[name][self._slots]

    def set_col(self, name: str, values) -> None:
        """Write a Column attr for every row of the view. No sync flags —
        Column attrs stream per-entity via attrs.set(), not via the batch
        path (columns.py module docstring). Rows whose entity was
        destroyed mid-batch are quarantined slots; the stale write is
        harmless (defaults are rewritten at re-allocation)."""
        s = self._slabs
        s.columns[name][self._slots] = values
        # Host-side hook writes win over an in-flight fused tick's
        # writeback (aoi/batched.py _consume_fused).
        s.fused_dirty[self._slots] = True

    def set_position_yaw(self, x=None, y=None, z=None, yaw=None) -> None:
        s = self._slabs
        slots = self._slots
        entities = self.entities
        # A hook may destroy entities mid-batch (their slots are released/
        # quarantined); write only the still-live rows.
        alive = np.fromiter(
            (not getattr(e, "_destroyed", False) for e in entities),
            bool, count=len(entities))
        if not alive.all():
            idx = np.flatnonzero(alive)
            slots = slots[idx]
            entities = [entities[i] for i in idx]
            x = x if x is None else np.asarray(x)[idx]
            y = y if y is None else np.asarray(y)[idx]
            z = z if z is None else np.asarray(z)[idx]
            yaw = yaw if yaw is None else np.asarray(yaw)[idx]
        if x is not None:
            s.xz[slots, 0] = x
        if y is not None:
            s.y[slots] = y
        if z is not None:
            s.xz[slots, 1] = z
        if yaw is not None:
            s.yaw[slots] = yaw
        s.flags[slots] |= SIF_SYNC_OWN_CLIENT | SIF_SYNC_NEIGHBOR_CLIENTS
        # Host hook wrote positions: an in-flight fused tick's writeback
        # must not clobber them (aoi/batched.py _consume_fused).
        s.fused_dirty[slots] = True
        # Non-columnar AOI backends (xzlist) keep per-entity structures;
        # the batched manager reads positions from the slab directly
        # (positions_in_slabs) and needs no per-entity notification.
        if x is not None or z is not None:
            nx = s.xz[slots, 0]
            nz = s.xz[slots, 1]
            for i, e in enumerate(entities):
                sp = getattr(e, "space", None)
                if sp is None:
                    continue
                mgr = getattr(sp, "aoi_mgr", None)
                if mgr is None or getattr(mgr, "positions_in_slabs", False):
                    continue
                desc = getattr(e, "_type_desc", None)
                if desc is not None and desc.use_aoi:
                    mgr.moved(e, float(nx[i]), float(nz[i]))


def vmapped_position_tick(fn):
    """Lift a pure per-entity numeric function into an ``on_tick_batch``
    classmethod: ``fn(x, y, z, yaw, dt) -> (x, y, z, yaw)`` on scalars,
    applied to every live entity of the class in ONE ``jax.jit(jax.vmap)``
    call per tick (compiled once, cached on the hook; numpy fallback when
    jax is unavailable). The column-free case of
    :func:`goworld_tpu.entity.columns.columnar_tick`, which this now
    delegates to — and therefore fusion-eligible like any columnar hook
    (``[aoi] fuse_logic`` compiles ``fn`` into the AOI step jit)."""
    return columnar_tick(fn, ())


class EntitySlabs:
    """The process-wide slab store: one slot per live entity."""

    def __init__(self, capacity: int = _INITIAL_CAPACITY) -> None:
        capacity = max(8, int(capacity))
        self.capacity = capacity
        self.xz = np.zeros((capacity, 2), np.float32)
        self.y = np.zeros(capacity, np.float32)
        self.yaw = np.zeros(capacity, np.float32)
        self.flags = np.zeros(capacity, np.uint8)
        self.syncing = np.zeros(capacity, np.uint8)
        self.gateid = np.zeros(capacity, np.int32)
        self.cid = np.zeros(capacity, "S16")
        # Mirror of `cid != b""` kept as bool so the per-collect masks are
        # byte-flag gathers, not 16-byte string compares.
        self.has_client = np.zeros(capacity, bool)
        self.eid = np.zeros(capacity, "S16")
        # Batched-AOI meta columns (the engine's active/space/radius inputs
        # live here so one growth path covers every per-slot array).
        self.active = np.zeros(capacity, bool)
        self.space_ids = np.zeros(capacity, np.int32)
        self.radius = np.zeros(capacity, np.float32)
        # Declared attr columns (entity/columns.py): one process-wide
        # array per Column attr name, allocated lazily on the first
        # entity of a declaring type and shared across types (specs must
        # match). Ride the same grow/quarantine/recycle machinery as the
        # built-in columns.
        self.columns: dict[str, np.ndarray] = {}
        self.column_specs: dict[str, ColumnSpec] = {}
        # Host-write fence for the fused tick (aoi/batched.py): a slot
        # whose position/yaw/columns were written host-side since the
        # last fused dispatch is skipped by that dispatch's writeback —
        # host writes (teleports, client sync, restore, release/realloc)
        # win over the in-flight device logic for that slot.
        self.fused_dirty = np.zeros(capacity, bool)
        self.entities: list = [None] * capacity
        self._free: list[int] = list(range(capacity - 1, -1, -1))
        self._quarantine: list[int] = []
        self.used = 0
        # Hard ceiling (multihost AOI slabs are fixed-size); None = grow.
        self.max_capacity: Optional[int] = None
        self.exhausted_hint = ""
        # The attached batched-AOI service, if any: released slots then
        # defer recycling to its dispatch/deliver cycle (see module doc).
        self.aoi_service = None
        # Interest edges, slot-indexed: edge (subject, watcher) exists iff
        # watcher.interested_in contains subject (maintained by
        # Entity.interest/uninterest). _edge_refs[slot] counts edges
        # touching a slot so release() can skip the purge scan when the
        # interest sets were already severed (the normal path).
        self._e_subj = np.zeros(_INITIAL_EDGES, np.int32)
        self._e_wat = np.zeros(_INITIAL_EDGES, np.int32)
        self._e_n = 0
        self._e_map: dict[int, int] = {}
        self._edge_refs = np.zeros(capacity, np.int32)
        # Per-class batched tick hooks (on_tick_batch classes only).
        self._tick_buckets: dict[type, _TickBucket] = {}
        # Steady-state sync-selection cache: a mover population that flags
        # the same slots with the same bits every collection (the common
        # case — avatars moving every tick) re-derives an IDENTICAL
        # selection, so the row selection, the per-gate grouping, and the
        # cid/eid halves of the wire blocks are reused verbatim and only
        # the position columns are refilled. Keyed by a topology version
        # bumped on every input the selection reads besides the flags
        # (interest edges, client bindings, syncing marks, slot release) +
        # a memcmp of the flagged slots/bits.
        self._topo_version = 0
        self._sync_cache = None  # (flagged, f, version, sel, out, gates_dict)
        telemetry.gauge(
            "entity_slab_capacity",
            "Allocated slot capacity of the entity slab store.",
        ).set_function(lambda: self.capacity)
        telemetry.gauge(
            "entity_slab_used",
            "Live (allocated, unreleased) entity slab slots.",
        ).set_function(lambda: self.used)

    # --- allocation ---------------------------------------------------------

    def alloc(self, entity) -> int:
        """Allocate a slot for ``entity`` (its row starts zeroed)."""
        if not self._free:
            if (self.max_capacity is not None
                    and self.capacity >= self.max_capacity):
                raise RuntimeError(
                    self.exhausted_hint
                    or f"entity slab capacity {self.capacity} exhausted")
            self._grow(self.capacity * 2)
        slot = self._free.pop()
        self.entities[slot] = entity
        self.used += 1
        cls = type(entity)
        desc = getattr(cls, "_type_desc", None)
        colspecs = getattr(desc, "column_attrs", None)
        if colspecs:
            for spec in colspecs.values():
                self.ensure_column(spec)[slot] = spec.default
        # A fresh allocation invalidates any in-flight fused writeback
        # aimed at this slot's previous tenant (aoi/batched.py).
        self.fused_dirty[slot] = True
        if getattr(cls, "on_tick_batch", None) is not None:
            self._tick_register(cls, entity, slot)
        return slot

    def ensure_column(self, spec: ColumnSpec) -> np.ndarray:
        """Get-or-create the slab column for ``spec``. Two entity types
        may share a column name only with an identical (dtype, default)
        spec — the storage is one array."""
        cur = self.column_specs.get(spec.name)
        if cur is not None:
            if cur != spec:
                raise ValueError(
                    f"Column {spec.name!r} redeclared with a different "
                    f"spec: {cur} vs {spec}")
            return self.columns[spec.name]
        arr = np.full(self.capacity, spec.default, spec.np_dtype)
        self.columns[spec.name] = arr
        self.column_specs[spec.name] = spec
        return arr

    def release(self, slot: int, entity=None) -> None:
        """Destroy-time release: clear the row's sync-visible columns (so
        the vectorized collect can never emit for it), purge any interest
        edges still referencing it, and quarantine or recycle the slot."""
        e = self.entities[slot] if entity is None else entity
        self._topo_version += 1
        self.flags[slot] = 0
        self.syncing[slot] = 0
        self.cid[slot] = b""
        self.has_client[slot] = False
        self.eid[slot] = b""
        self.gateid[slot] = 0
        # Columns reset to their declared defaults (a quarantined slot's
        # stale values must never leak into its next tenant) and the slot
        # is fenced against any in-flight fused writeback.
        for name, arr in self.columns.items():
            arr[slot] = self.column_specs[name].default
        self.fused_dirty[slot] = True
        if self.active[slot]:
            self.active[slot] = False
            if self.aoi_service is not None:
                self.aoi_service._meta_dirty = True
        if self._edge_refs[slot]:
            self._purge_edges(slot)
        if e is not None:
            cls = type(e)
            bucket = self._tick_buckets.get(cls)
            if bucket is not None:
                bucket.remove(e)
        self.used -= 1
        if self.aoi_service is not None:
            # The entity mapping survives quarantine: the in-flight engine
            # step may still deliver this slot's leave events.
            self._quarantine.append(slot)
        else:
            self.entities[slot] = None
            self._free.append(slot)

    def take_quarantine(self) -> list[int]:
        """Hand the current quarantine to the AOI dispatch that will observe
        these slots' deactivation (recycled via :meth:`recycle` after that
        step's events have been delivered)."""
        q = self._quarantine
        self._quarantine = []
        return q

    def recycle(self, slots) -> None:
        for slot in slots:
            self.entities[slot] = None
            self._free.append(slot)

    def ensure_capacity(self, n: int) -> None:
        if n > self.capacity:
            cap = self.capacity
            while cap < n:
                cap *= 2
            self._grow(max(cap, n))

    def _grow(self, n: int) -> None:
        old = self.capacity

        def pad(arr, shape, dtype):
            out = np.zeros(shape, dtype)
            out[: arr.shape[0]] = arr
            return out

        self.xz = pad(self.xz, (n, 2), np.float32)
        self.y = pad(self.y, (n,), np.float32)
        self.yaw = pad(self.yaw, (n,), np.float32)
        self.flags = pad(self.flags, (n,), np.uint8)
        self.syncing = pad(self.syncing, (n,), np.uint8)
        self.gateid = pad(self.gateid, (n,), np.int32)
        self.cid = pad(self.cid, (n,), "S16")
        self.has_client = pad(self.has_client, (n,), bool)
        self.eid = pad(self.eid, (n,), "S16")
        self.active = pad(self.active, (n,), bool)
        self.space_ids = pad(self.space_ids, (n,), np.int32)
        self.radius = pad(self.radius, (n,), np.float32)
        self.fused_dirty = pad(self.fused_dirty, (n,), bool)
        for name, arr in self.columns.items():
            # New rows start at the column's declared default, not zero.
            spec = self.column_specs[name]
            grown = np.full(n, spec.default, arr.dtype)
            grown[: arr.shape[0]] = arr
            self.columns[name] = grown
        self._edge_refs = pad(self._edge_refs, (n,), np.int32)
        self.entities.extend([None] * (n - old))
        # New slots go UNDER existing free ones so pop() hands out the
        # lowest unused index first (keeps engine-visible slots dense).
        self._free = list(range(n - 1, old - 1, -1)) + self._free
        self.capacity = n

    # --- interest edges -----------------------------------------------------

    def edge_add(self, subj: int, watcher: int) -> None:
        key = (subj << 32) | watcher
        if key in self._e_map:
            return
        n = self._e_n
        if n == len(self._e_subj):
            self._e_subj = np.resize(self._e_subj, n * 2)
            self._e_wat = np.resize(self._e_wat, n * 2)
        self._e_subj[n] = subj
        self._e_wat[n] = watcher
        self._e_map[key] = n
        self._e_n = n + 1
        self._edge_refs[subj] += 1
        self._edge_refs[watcher] += 1
        self._topo_version += 1

    def edge_remove(self, subj: int, watcher: int) -> None:
        key = (subj << 32) | watcher
        idx = self._e_map.pop(key, None)
        if idx is None:
            return
        last = self._e_n - 1
        if idx != last:
            ls, lw = int(self._e_subj[last]), int(self._e_wat[last])
            self._e_subj[idx] = ls
            self._e_wat[idx] = lw
            self._e_map[(ls << 32) | lw] = idx
        self._e_n = last
        self._edge_refs[subj] -= 1
        self._edge_refs[watcher] -= 1
        self._topo_version += 1

    def edge_count(self) -> int:
        return self._e_n

    def _purge_edges(self, slot: int) -> None:
        """Backstop for release(): drop edges still naming a slot whose
        interest sets were not severed (destroy outside any AOI space)."""
        n = self._e_n
        subj, wat = self._e_subj[:n], self._e_wat[:n]
        hits = np.flatnonzero((subj == slot) | (wat == slot))
        for s, w in [(int(subj[i]), int(wat[i])) for i in hits]:
            self.edge_remove(s, w)

    # --- vectorized sync collection ----------------------------------------

    def touch_sync_topology(self) -> None:
        """Invalidate the steady-state sync-selection cache. Called on every
        selection input EXCEPT the flags themselves: interest-edge changes,
        client bind/unbind, syncing-mark changes, slot release."""
        self._topo_version += 1

    def collect_sync_selection(self):
        """Stage 1 of the columnar ``collect_entity_sync_infos`` (the
        ``game_collect`` hop): select which (subject, destination) slot
        pairs emit a sync row this collection. Own-client rows are one
        boolean-mask gather over the flag slab (client bound, not
        client-driven); neighbor rows come from the slot-indexed interest
        edges (watcher has a client). Flags clear for every flagged slot,
        row or not — the legacy per-entity contract. Returns ``None`` when
        nothing is flagged, else an opaque selection for :meth:`pack_sync`.

        Steady-state fast path: when the flagged slots+bits are memcmp-
        identical to the previous collection and nothing the selection
        reads has changed since (``_topo_version``), the previous
        selection — including the per-gate grouping and the cid/eid halves
        of the wire blocks — is reused verbatim; only the float columns
        are refilled by pack_sync."""
        flags = self.flags
        flagged = np.flatnonzero(flags)
        if flagged.size == 0:
            return None
        f = flags[flagged]
        cache = self._sync_cache
        if (
            cache is not None
            and cache[2] == self._topo_version
            and np.array_equal(cache[0], flagged)
            and np.array_equal(cache[1], f)
        ):
            flags[flagged] = 0
            return cache
        has_client = self.has_client
        own = flagged[
            (f & SIF_SYNC_OWN_CLIENT).astype(bool)
            & has_client[flagged]
            & (self.syncing[flagged] == 0)
        ]
        n = self._e_n
        if n:
            subj, wat = self._e_subj[:n], self._e_wat[:n]
            m = (
                (flags[subj] & SIF_SYNC_NEIGHBOR_CLIENTS).astype(bool)
                & has_client[wat]
            )
            nsubj, nwat = subj[m], wat[m]
        else:
            nsubj = nwat = np.empty(0, np.int64)
        flags[flagged] = 0
        subjects = np.concatenate([own, nsubj])
        if subjects.size == 0:
            return None
        dests = np.concatenate([own, nwat])
        gates = self.gateid[dests]
        # Order rows by (gate, destination slot): per-gate buffers come out
        # as ONE contiguous slice each, and within a gate every client's
        # rows form a contiguous run — the gate's demux then slices runs
        # straight off the wire buffer without re-sorting (gate/service.py
        # _handle_sync_on_clients).
        if (gates == gates[0]).all():
            order = np.argsort(dests, kind="stable")
        else:
            order = np.argsort(
                (gates.astype(np.int64) << 32) | dests, kind="stable")
        so, do, gs = subjects[order], dests[order], gates[order]
        out = np.empty(len(so), CLIENT_SYNC_BLOCK_DTYPE)
        out["cid"] = self.cid[do]
        out["eid"] = self.eid[so]
        bounds = [0] + (np.flatnonzero(gs[1:] != gs[:-1]) + 1).tolist()
        bounds.append(len(gs))
        per_gate = {
            int(gs[bounds[i]]): out[bounds[i]:bounds[i + 1]]
            for i in range(len(bounds) - 1)
        }
        cache = (flagged, f, self._topo_version, (so, do, gs), out, per_gate)
        self._sync_cache = cache
        return cache

    def pack_sync(self, selection) -> dict[int, np.ndarray]:
        """Stage 2 (the ``game_pack`` hop): one structured array of
        [cid + sync record] wire blocks per destination gate, built by
        column assignment — zero Python row tuples. The cid/eid halves were
        filled when the selection was built (they are selection-invariant);
        this refills the position/yaw columns from the live slabs. The
        returned per-gate arrays are views into one shared buffer, valid
        until the next collection."""
        so = selection[3][0]
        out = selection[4]
        out["x"] = self.xz[so, 0]
        out["y"] = self.y[so]
        out["z"] = self.xz[so, 1]
        out["yaw"] = self.yaw[so]
        return selection[5]

    def collect_sync(self) -> dict[int, np.ndarray]:
        """Both stages in one call (tests / embedded drivers)."""
        sel = self.collect_sync_selection()
        return {} if sel is None else self.pack_sync(sel)

    # --- per-class batched tick hooks --------------------------------------

    def _tick_register(self, cls: type, entity, slot: int) -> None:
        bucket = self._tick_buckets.get(cls)
        if bucket is None:
            hook = inspect.getattr_static(cls, "on_tick_batch", None)
            if not isinstance(hook, (classmethod, staticmethod)):
                raise TypeError(
                    f"{cls.__name__}.on_tick_batch must be a classmethod "
                    f"(one call per CLASS per tick over a SlabTickView)")
            bucket = self._tick_buckets[cls] = _TickBucket()
            bucket.last_tick = time.monotonic()
        bucket.add(entity, slot)

    def prewarm_tick_hooks(self) -> None:
        """Dummy-shaped compile of every adopted class's batched tick jit
        at its CURRENT live population (columnar_tick.prewarm, with the
        class's declared column dtypes). The restore path calls this
        before the cluster re-handshake so the first live tick triggers
        no fresh trace; hooks without a prewarm surface (hand-written
        on_tick_batch bodies) are skipped — whatever they lazily build is
        their own contract. Classes the attached AOI service runs FUSED
        skip the per-class jit (it never executes there) and are instead
        covered by the service's fused-step prewarm, called at the end."""
        svc = self.aoi_service
        take = getattr(svc, "takes_over_tick", None)  # duck test doubles
        for cls, bucket in list(self._tick_buckets.items()):
            n = len(bucket.entities)
            if n == 0:
                continue
            if take is not None and take(cls):
                continue
            hook = inspect.getattr_static(cls, "on_tick_batch", None)
            fn = getattr(hook, "__func__", None)
            pw = getattr(fn, "prewarm", None)
            if pw is None:
                continue
            prog = getattr(fn, "fused_program", None)
            dtypes = None
            if prog is not None and prog.columns:
                dtypes = tuple(
                    self.column_specs[c].dtype for c in prog.columns
                    if c in self.column_specs) or None
            gwutils.run_panicless(
                lambda p=pw, k=n, d=dtypes: p(k, col_dtypes=d))
        pf = getattr(svc, "prewarm_fused", None)
        if pf is not None:
            gwutils.run_panicless(pf)

    def run_tick_batches(self, now: float | None = None) -> None:
        """Fire each adopted class's ``on_tick_batch`` once over its live
        entities (the vectorized replacement for per-entity timers).
        Classes the attached AOI service runs FUSED ([aoi] fuse_logic) are
        skipped: their program executes inside the engine step at the AOI
        cadence instead (aoi/batched.py). Their ``last_tick`` stays fresh
        so a later fallback to host-side execution resumes with a sane
        dt, not one spanning the whole fused period."""
        if not self._tick_buckets:
            return
        if now is None:
            now = time.monotonic()
        take = getattr(self.aoi_service, "takes_over_tick", None)
        for cls, bucket in list(self._tick_buckets.items()):
            n = len(bucket.entities)
            if n == 0:
                continue
            if take is not None and take(cls):
                bucket.last_tick = now
                continue
            dt = now - bucket.last_tick
            bucket.last_tick = now
            view = SlabTickView(
                self, bucket.slots[:n].copy(), list(bucket.entities), dt)
            gwutils.run_panicless(lambda c=cls, v=view: c.on_tick_batch(v))

"""The Entity: unit of state, logic, RPC and interest.

Reference parity: ``engine/entity/Entity.go`` — lifecycle hooks
(Entity.go:100-120), attrs with client streaming (Entity.go:814-917), client
ownership (SetClient/GiveClientTo, Entity.go:678-765), AOI interest sets
(Entity.go:227-246), per-entity timers surviving migration
(Entity.go:268-390,637), RPC dispatch with caller-permission flags derived
from the ``_Client``/``_AllClients`` method-name suffixes (rpc_desc.go:8-46,
enforcement Entity.go:483-540), migration pack/unpack (Entity.go:631-651,
956-1115) and position/yaw sync (Entity.go:430-440,1221-1267).
"""

from __future__ import annotations

from typing import Optional

from goworld_tpu import dispatchercluster
from goworld_tpu.entity.attrs import (
    LIST_APPEND,
    LIST_CHANGE,
    LIST_POP,
    MAP_CHANGE,
    MAP_CLEAR,
    MAP_DEL,
    MapAttr,
)
from goworld_tpu.entity.columns import ColumnSpec
from goworld_tpu.entity.game_client import GameClient
# sync-info flags (Entity.go sifSyncOwnClient / sifSyncNeighborClients) —
# defined beside the columnar flag slab they index, re-exported here.
from goworld_tpu.entity.slabs import (
    SIF_SYNC_NEIGHBOR_CLIENTS,
    SIF_SYNC_OWN_CLIENT,
)
from goworld_tpu.entity.vector import Vector3
from goworld_tpu.proto import FilterOp
from goworld_tpu.utils import gwlog, gwutils


class EntityTypeDesc:
    """Declarative per-type attr flags and AOI participation
    (EntityManager.go:24-36,65-101)."""

    def __init__(self, typename: str, entity_class: type) -> None:
        self.typename = typename
        self.entity_class = entity_class
        self.is_space = False
        self.use_aoi = False
        self.aoi_distance = 0.0
        self.client_attrs: set[str] = set()
        self.all_clients_attrs: set[str] = set()
        self.persistent_attrs: set[str] = set()
        # Declared Column attrs (entity/columns.py): numeric attrs whose
        # storage is a slab column instead of the per-entity dict.
        self.column_attrs: dict[str, "ColumnSpec"] = {}

    def set_use_aoi(self, use: bool, distance: float = 100.0) -> None:
        self.use_aoi = use
        self.aoi_distance = distance

    def define_attr(self, name: str, *flags: str,
                    dtype: str = "float32", default: float = 0.0) -> None:
        """Flags: "Client", "AllClients", "Persistent" (attr.go:5-10;
        AllClients implies Client) plus "Column" (entity/columns.py): a
        numeric attr stored in a process-wide slab column — reads/writes
        through ``entity.attrs`` proxy to the column, per-class batched
        tick hooks (``columnar_tick``) vectorize over it, and with
        ``[aoi] fuse_logic`` the batched AOI step updates it on-device.
        ``dtype``/``default`` apply to Column attrs only."""
        for f in flags:
            if f == "Client":
                self.client_attrs.add(name)
            elif f == "AllClients":
                self.client_attrs.add(name)
                self.all_clients_attrs.add(name)
            elif f == "Persistent":
                self.persistent_attrs.add(name)
            elif f == "Column":
                self.column_attrs[name] = ColumnSpec(
                    name, dtype=dtype, default=default)
            else:
                raise ValueError(f"unknown attr flag {f!r}")

    @property
    def is_persistent(self) -> bool:
        return bool(self.persistent_attrs)


class Entity:
    """Base class of all game entities (and, via Space, of spaces)."""

    # Set per-subclass at registration.
    _type_desc: EntityTypeDesc = None  # type: ignore[assignment]

    def __init__(self) -> None:
        # Filled by entity_manager.create; kept minimal here so subclasses
        # never need to call super().__init__ with args.
        # Hot state (position/yaw, sync flags, client binding) lives in the
        # process slab store (entity/slabs.py); this object holds the slot
        # and the descriptors below view the columns.
        from goworld_tpu.entity import entity_manager

        slabs = entity_manager.runtime.slabs
        self._slabs = slabs
        self._slot = slabs.alloc(self)
        self._id: str = ""
        self.attrs: MapAttr = None  # type: ignore[assignment]
        self.space = None  # Optional[Space]
        self._client: Optional[GameClient] = None
        self.interested_in: set[Entity] = set()
        self.interested_by: set[Entity] = set()
        self._destroyed = False
        # Snapshot of (x, y, z, yaw) taken when the slot is released, so
        # post-destroy reads stay valid after the slot is recycled.
        self._final_pos_yaw = (0.0, 0.0, 0.0, 0.0)
        self._timers: dict[int, tuple] = {}  # tid → (handle, interval, method, args)
        self._timer_seq = 0
        self._save_timer = None
        self._enter_space_request: tuple | None = None  # (spaceid, pos, time, nonce)
        self._enter_space_nonce = 0  # per-entity request sequence

    # --- slab-backed hot state (entity/slabs.py) ---------------------------

    @property
    def id(self) -> str:
        return self._id

    @id.setter
    def id(self, value: str) -> None:
        self._id = value
        if self._slot >= 0:
            self._slabs.eid[self._slot] = value.encode("ascii", "replace")
            # The eid column is baked into cached sync selections.
            self._slabs.touch_sync_topology()

    @property
    def position(self) -> Vector3:
        i = self._slot
        if i < 0:
            x, y, z, _ = self._final_pos_yaw
            return Vector3(x, y, z)
        s = self._slabs
        return Vector3(float(s.xz[i, 0]), float(s.y[i]), float(s.xz[i, 1]))

    @position.setter
    def position(self, pos: Vector3) -> None:
        i = self._slot
        if i < 0:
            self._final_pos_yaw = (
                pos.x, pos.y, pos.z, self._final_pos_yaw[3])
            return
        s = self._slabs
        s.xz[i] = (pos.x, pos.z)
        s.y[i] = pos.y
        # Host write fence: an in-flight fused tick must not clobber this
        # (entity/slabs.py fused_dirty; aoi/batched.py _consume_fused).
        s.fused_dirty[i] = True

    @property
    def yaw(self) -> float:
        i = self._slot
        if i < 0:
            return self._final_pos_yaw[3]
        return float(self._slabs.yaw[i])

    @yaw.setter
    def yaw(self, value: float) -> None:
        i = self._slot
        if i < 0:
            x, y, z, _ = self._final_pos_yaw
            self._final_pos_yaw = (x, y, z, value)
            return
        self._slabs.yaw[i] = value
        self._slabs.fused_dirty[i] = True

    @property
    def client(self) -> Optional[GameClient]:
        return self._client

    @client.setter
    def client(self, c: Optional[GameClient]) -> None:
        # Mirrors the binding into the cid/gateid columns so the vectorized
        # sync collect routes (or drops) rows without touching the object.
        self._client = c
        i = self._slot
        if i < 0:
            return
        s = self._slabs
        if c is None:
            s.cid[i] = b""
            s.has_client[i] = False
            s.gateid[i] = 0
        else:
            s.cid[i] = c.clientid.encode("ascii", "replace")
            s.has_client[i] = True
            s.gateid[i] = c.gateid
        s.touch_sync_topology()

    @property
    def _sync_info_flag(self) -> int:
        i = self._slot
        return int(self._slabs.flags[i]) if i >= 0 else 0

    @_sync_info_flag.setter
    def _sync_info_flag(self, value: int) -> None:
        if self._slot >= 0:
            self._slabs.flags[self._slot] = value

    @property
    def _syncing_from_client(self) -> bool:
        i = self._slot
        return bool(self._slabs.syncing[i]) if i >= 0 else False

    @_syncing_from_client.setter
    def _syncing_from_client(self, value: bool) -> None:
        if self._slot >= 0:
            self._slabs.syncing[self._slot] = 1 if value else 0
            self._slabs.touch_sync_topology()

    def _release_slab_slot(self) -> None:
        i = self._slot
        if i < 0:
            return
        s = self._slabs
        self._final_pos_yaw = (
            float(s.xz[i, 0]), float(s.y[i]), float(s.xz[i, 1]),
            float(s.yaw[i]))
        # Column-backed attr roots snapshot their cells the same way the
        # final position is snapshotted, so post-destroy reads stay valid
        # after the slot is recycled (entity/columns.py).
        snap = getattr(self.attrs, "_snapshot_columns", None)
        if snap is not None:
            snap()
        self._slot = -1
        s.release(i, self)

    # --- identity ----------------------------------------------------------

    @property
    def typename(self) -> str:
        return self._type_desc.typename

    @property
    def type_desc(self) -> EntityTypeDesc:
        return self._type_desc

    def is_space_entity(self) -> bool:
        return self._type_desc.is_space

    def is_destroyed(self) -> bool:
        return self._destroyed

    def is_persistent(self) -> bool:
        return self._type_desc.is_persistent

    def __repr__(self) -> str:
        return f"{self.typename}<{self.id}>"

    # --- lifecycle hooks (Entity.go:100-120) -------------------------------

    def on_init(self) -> None:
        pass

    def on_attrs_ready(self) -> None:
        pass

    def on_created(self) -> None:
        pass

    def on_game_ready(self) -> None:
        pass

    def on_destroy(self) -> None:
        pass

    def on_migrate_out(self) -> None:
        pass

    def on_migrate_in(self) -> None:
        pass

    def on_freeze(self) -> None:
        pass

    def on_restored(self) -> None:
        pass

    def on_enter_space(self) -> None:
        pass

    def on_leave_space(self, space) -> None:
        pass

    def on_client_connected(self) -> None:
        pass

    def on_client_disconnected(self) -> None:
        pass

    # --- destroy -----------------------------------------------------------

    def destroy(self) -> None:
        self._destroy(is_migrate=False)

    def _destroy(self, is_migrate: bool) -> None:
        """Entity.go:136-157: leave space, run OnDestroy (unless migrating),
        save persistent state, drop client quietly on migrate."""
        if self._destroyed:
            return
        self._destroyed = True
        if self.space is not None:
            self.space._leave(self)
        if not is_migrate:
            gwutils.run_panicless(self.on_destroy)
            if self.client is not None:
                self.client.send_destroy_entity(self)
                self._set_client_locally(None)
            if self.is_persistent():
                self._save()
        elif self.client is not None:
            # Migrate-out: drop the binding quietly (no client-side destroy;
            # the target game reattaches the same client, Entity.go:1092-1101)
            # but DO release the local clientid→entity ownership mapping.
            self._set_client_locally(None)
        self._cancel_all_timers()
        from goworld_tpu.entity import entity_manager

        entity_manager.on_entity_destroyed(self, is_migrate)
        # Last: release the slab slot (clears flag/client columns so the
        # vectorized sync collect cannot emit for this entity; quarantined
        # while a batched AOI step may still deliver its leave events).
        self._release_slab_slot()

    # --- attrs -------------------------------------------------------------

    def _bind_attrs(self, attrs: MapAttr) -> None:
        self.attrs = attrs
        attrs._owner_cb = self._on_attr_change

    def client_attrs(self) -> dict:
        """Attrs visible to the entity's own client (Client + AllClients)."""
        return self.attrs.to_dict_filtered(self._type_desc.client_attrs)

    def all_client_attrs(self) -> dict:
        """Attrs visible to *other* clients (AllClients only)."""
        return self.attrs.to_dict_filtered(self._type_desc.all_clients_attrs)

    def persistent_attrs(self) -> dict:
        return self.attrs.to_dict_filtered(self._type_desc.persistent_attrs)

    def _on_attr_change(self, kind: str, path: list, *args) -> None:
        """Stream attr mutations to interested clients (Entity.go:814-917).

        The change's top-level key decides visibility: "Client" keys go to the
        own client only; "AllClients" keys also go to every client that has
        this entity in its AOI view.
        """
        desc = self._type_desc
        targets: list[GameClient] = []
        if not path and kind == MAP_CLEAR:
            # Root clear wipes every key: each client mirror holds only its
            # visible subset, so a clear is correct for all of them.
            if desc.client_attrs and self.client is not None:
                targets.append(self.client)
            if desc.all_clients_attrs:
                for other in self.interested_by:
                    if other.client is not None:
                        targets.append(other.client)
            for t in targets:
                self._send_attr_change(t, kind, path, args)
            return
        top = path[0] if path else (args[0] if kind in (MAP_CHANGE, MAP_DEL) else None)
        if top is None:
            return
        if top in desc.client_attrs and self.client is not None:
            targets.append(self.client)
        if top in desc.all_clients_attrs:
            for other in self.interested_by:
                if other.client is not None:
                    targets.append(other.client)
        if not targets:
            return
        for t in targets:
            self._send_attr_change(t, kind, path, args)

    def _send_attr_change(self, t: GameClient, kind: str, path: list, args: tuple) -> None:
        eid = self.id
        if kind == MAP_CHANGE:
            t.send_map_attr_change(eid, path, args[0], args[1])
        elif kind == MAP_DEL:
            t.send_map_attr_del(eid, path, args[0])
        elif kind == MAP_CLEAR:
            t.send_map_attr_clear(eid, path)
        elif kind == LIST_CHANGE:
            t.send_list_attr_change(eid, path, args[0], args[1])
        elif kind == LIST_APPEND:
            t.send_list_attr_append(eid, path, args[0])
        elif kind == LIST_POP:
            t.send_list_attr_pop(eid, path)

    # --- timers (Entity.go:268-390) ----------------------------------------

    def add_callback(self, delay: float, method: str, *args) -> int:
        """One-shot timer calling ``self.<method>(*args)``; survives migration."""
        return self._add_timer(delay, 0.0, method, args)

    def add_timer(self, interval: float, method: str, *args) -> int:
        """Repeating timer; survives migration."""
        return self._add_timer(interval, interval, method, args)

    def _add_timer(self, first_delay: float, repeat: float, method: str, args: tuple) -> int:
        """Repeating timers are one-shot chains: every fire re-arms, so the
        packed remaining-time is always exact for migrate/freeze."""
        if not isinstance(method, str):
            raise TypeError(
                "entity timers take a method NAME so they can migrate "
                "with the entity (Entity.go:268-281)"
            )
        from goworld_tpu.entity import entity_manager

        self._timer_seq += 1
        tid = self._timer_seq
        svc = entity_manager.runtime.timer_service_for(self)
        h = svc.add_callback(first_delay, lambda: self._fire_timer(tid))
        deadline = entity_manager.runtime.now() + first_delay
        self._timers[tid] = (h, repeat, method, args, deadline)
        return tid

    def cancel_timer(self, tid: int) -> None:
        t = self._timers.pop(tid, None)
        if t is not None:
            t[0].cancel()

    def _cancel_all_timers(self) -> None:
        for h, *_ in self._timers.values():
            h.cancel()
        self._timers.clear()
        if self._save_timer is not None:
            self._save_timer.cancel()
            self._save_timer = None

    def _fire_timer(self, tid: int) -> None:
        t = self._timers.get(tid)
        if t is None or self._destroyed:
            return
        _, repeat, method, args, _ = t
        if repeat > 0:
            from goworld_tpu.entity import entity_manager

            svc = entity_manager.runtime.timer_service_for(self)
            h = svc.add_callback(repeat, lambda: self._fire_timer(tid))
            self._timers[tid] = (h, repeat, method, args,
                                 entity_manager.runtime.now() + repeat)
        else:
            self._timers.pop(tid, None)
        fn = getattr(self, method, None)
        if fn is None:
            gwlog.errorf("%s: timer method %s not found", self, method)
            return
        fn(*args)

    def _pack_timers(self) -> list:
        """Serialize timers as (remaining, repeat, method, args) for
        migrate/freeze (Entity.go:637)."""
        from goworld_tpu.entity import entity_manager

        now = entity_manager.runtime.now()
        out = []
        for h, repeat, method, args, deadline in self._timers.values():
            out.append([max(0.0, deadline - now), repeat, method, list(args)])
        return out

    def _restore_timers(self, packed: list) -> None:
        for remaining, repeat, method, args in packed:
            # First fire after the packed remaining time, then the interval.
            self._add_timer(remaining, repeat, method, tuple(args))

    # --- RPC (Entity.go:442-540) -------------------------------------------

    def call(self, eid: str, method: str, *args) -> None:
        """Call a method on any entity anywhere (EntityManager.go:433-446)."""
        from goworld_tpu.entity import entity_manager

        entity_manager.call_entity(eid, method, *args)

    def call_local(self, method: str, args: tuple) -> None:
        fn = getattr(self, method, None)
        if fn is None:
            gwlog.errorf("%s: local call to unknown method %s", self, method)
            return
        gwutils.run_panicless(lambda: fn(*args))

    def on_call_from_remote(self, method: str, args: tuple, from_clientid: str | None) -> None:
        """Dispatch an incoming RPC with permission checks
        (Entity.go:483-540): methods named ``*_Client`` may only be called by
        the entity's own client; ``*_AllClients`` by any client; others only
        server-side (from_clientid None)."""
        if method.startswith("_"):
            gwlog.errorf("%s: refusing RPC to private method %s", self, method)
            return
        fn = getattr(self, method, None)
        if fn is None or not callable(fn) or not _is_rpc_method(type(self), method):
            gwlog.errorf("%s: RPC to unknown method %s", self, method)
            return
        if from_clientid is not None:
            if method.endswith("_Client"):
                if self.client is None or self.client.clientid != from_clientid:
                    gwlog.errorf(
                        "%s: client %s may not call %s (owner only)",
                        self, from_clientid, method,
                    )
                    return
            elif method.endswith("_AllClients"):
                pass
            else:
                gwlog.errorf(
                    "%s: client %s may not call server-only method %s",
                    self, from_clientid, method,
                )
                return
        gwutils.run_panicless(lambda: fn(*args))

    # --- client ownership (Entity.go:678-765) ------------------------------

    def set_client(self, client: Optional[GameClient]) -> None:
        """Attach/detach the entity's client; replays world state to a newly
        attached client: own entity (as player), current space, AOI neighbors."""
        old = self.client
        if old is not None and client is not None and old.clientid == client.clientid:
            return
        if old is not None:
            old.send_destroy_entity(self)
            self._set_client_locally(None)
            gwutils.run_panicless(self.on_client_disconnected)
        if client is not None:
            client.owner_id = self.id
            self._set_client_locally(client)
            client.send_create_entity(self, is_player=True)
            # Replay neighbors to the fresh client (Entity.go:698-718).
            for other in self.interested_in:
                client.send_create_entity(other, is_player=False)
            gwutils.run_panicless(self.on_client_connected)

    def _set_client_locally(self, client: Optional[GameClient]) -> None:
        from goworld_tpu.entity import entity_manager

        if self.client is not None:
            entity_manager.on_client_detached(self.client.clientid, self)
        self.client = client
        if client is not None:
            entity_manager.on_client_attached(client.clientid, self)

    def give_client_to(self, other: "Entity") -> None:
        """Transfer this entity's client to ``other`` (Entity.go:752-765)."""
        client = self.client
        if client is None:
            return
        # Detach quietly: no destroy-entity — the new owner's create replaces
        # the player entity on the client.
        self._set_client_locally(None)
        gwutils.run_panicless(self.on_client_disconnected)
        other.set_client(client)

    def notify_client_disconnected(self) -> None:
        """Called when the gate reports the client's socket died."""
        self._set_client_locally(None)
        gwutils.run_panicless(self.on_client_disconnected)

    # --- client RPC convenience -------------------------------------------

    def call_client(self, method: str, *args) -> None:
        if self.client is not None:
            self.client.call(self.id, method, args)

    def call_all_clients(self, method: str, *args) -> None:
        """Call own client + every client seeing this entity (AllClients RPC)."""
        if self.client is not None:
            self.client.call(self.id, method, args)
        for other in self.interested_by:
            if other.client is not None:
                other.client.call(self.id, method, args)

    def call_filtered_clients(self, key: str, op: str | FilterOp, val: str, method: str, *args) -> None:
        """Broadcast to clients by gate-held filter props (Entity.go:1150-1170).

        Deviation from the reference: routed through exactly ONE dispatcher
        (any dispatcher reaches every gate). The reference broadcasts to all
        dispatchers AND each dispatcher re-broadcasts to all gates
        (dispatchercluster.go:50-62 + DispatcherService.go:846-848), which
        delivers D copies per client in a D-dispatcher deployment.
        """
        ops = {"=": FilterOp.EQ, "!=": FilterOp.NE, "<": FilterOp.LT,
               "<=": FilterOp.LTE, ">": FilterOp.GT, ">=": FilterOp.GTE}
        fop = ops[op] if isinstance(op, str) else op
        dispatchercluster.select_by_entity_id(self.id).send_call_filtered_client_proxies(
            fop, key, val, method, args
        )

    def set_filter_prop(self, key: str, val: str) -> None:
        if self.client is not None:
            self.client.set_filter_prop(key, val)

    # --- AOI interest (Entity.go:227-246) ----------------------------------

    def on_enter_aoi(self, other: "Entity") -> None:
        self.interest(other)

    def on_leave_aoi(self, other: "Entity") -> None:
        self.uninterest(other)

    def on_aoi_batch(self, enters: list, leaves: list) -> None:
        """One batched AOI callback per entity per tick (the vectorized
        delivery path, aoi/batched.py): ``leaves`` then ``enters`` are all
        the neighbors this entity lost/gained this tick, in engine event
        order. The default preserves the per-pair contract exactly —
        leave-before-enter within the tick, per-pair destroyed checks at
        fire time (a hook may destroy entities mid-batch) — so subclasses
        overriding only the per-pair hooks behave identically whether the
        service routes them through here or through the legacy fallback.
        Override THIS hook to consume the whole tick's diff in one call
        (batch client pushes, group spawn logic) without per-pair Python
        dispatch."""
        for other in leaves:
            if self._destroyed:
                return
            self.on_leave_aoi(other)
        for other in enters:
            if self._destroyed:
                return
            if not other.is_destroyed():
                self.on_enter_aoi(other)

    def interest(self, other: "Entity") -> None:
        # Idempotent by design: the batched AOI plane delivers diffs one
        # tick late (aoi/batched.py), so edge races — an entity destroyed
        # inside the window suppresses its enter but its leave still arrives
        # next tick — are reconciled HERE, not in the engine. go-aoi fires
        # exactly-once synchronously and needs no such guard
        # (Entity.go:236-246); our pipelined model does: without it a
        # client receives destroys for entities it never saw (found live by
        # the strict bot fleet, round 3).
        if other in self.interested_in:
            return
        self.interested_in.add(other)
        other.interested_by.add(self)
        self._edge_update(other, add=True)
        if self.client is not None:
            gwlog.debugf("%s interest %s -> create on client %s",
                         self, other, self.client)
            self.client.send_create_entity(other, is_player=False)

    def uninterest(self, other: "Entity") -> None:
        if other not in self.interested_in:
            return  # see interest(): leave may arrive without its enter
        self.interested_in.discard(other)
        other.interested_by.discard(self)
        self._edge_update(other, add=False)
        if self.client is not None:
            gwlog.debugf("%s uninterest %s -> destroy on client %s",
                         self, other, self.client)
            self.client.send_destroy_entity(other)

    def is_interested_in(self, other: "Entity") -> bool:
        return other in self.interested_in

    def _edge_update(self, other: "Entity", add: bool) -> None:
        """Mirror the interest relation into the slot-indexed edge table
        the vectorized sync collect reads (subject=other, watcher=self).
        Skipped for cross-store pairs (test harnesses mixing runtimes)."""
        oslot = getattr(other, "_slot", -1)
        if (
            self._slot < 0
            or oslot < 0
            or getattr(other, "_slabs", None) is not self._slabs
        ):
            return
        if add:
            self._slabs.edge_add(oslot, self._slot)
        else:
            self._slabs.edge_remove(oslot, self._slot)

    # --- position / movement (Entity.go:430-440,1189-1205) -----------------

    def distance_to(self, other: "Entity") -> float:
        """Distance to another entity (Entity.go DistanceTo)."""
        return self.position.distance_to(other.position)

    def face_to(self, other: "Entity") -> None:
        """Turn to face another entity (Entity.go FaceTo)."""
        self.set_yaw((other.position - self.position).dir_to_yaw())

    def set_position(self, pos: Vector3) -> None:
        self._set_position_yaw(pos, self.yaw)

    def set_yaw(self, yaw: float) -> None:
        self._set_position_yaw(self.position, yaw)

    def _set_position_yaw(self, pos: Vector3, yaw: float) -> None:
        self.position = pos
        self.yaw = yaw
        if self.space is not None:
            self.space._move(self, pos)
        self._sync_info_flag |= SIF_SYNC_NEIGHBOR_CLIENTS | SIF_SYNC_OWN_CLIENT

    def set_client_syncing(self, syncing: bool) -> None:
        """Allow the entity's client to drive position/yaw (Entity.go:430-440)."""
        self._syncing_from_client = syncing

    def on_sync_position_yaw_from_client(self, x: float, y: float, z: float, yaw: float) -> None:
        if not self._syncing_from_client or self._destroyed:
            return
        self.position = Vector3(x, y, z)
        self.yaw = yaw
        if self.space is not None:
            self.space._move(self, self.position)
        # Own client already knows; only neighbors need the update.
        self._sync_info_flag |= SIF_SYNC_NEIGHBOR_CLIENTS

    # --- space entry / migration (Entity.go:956-1115) ----------------------

    def enter_space(self, spaceid: str, pos: Vector3) -> None:
        """Enter a space: local fast path, else cross-game migration."""
        from goworld_tpu.entity import entity_manager

        if self._enter_space_request is not None:
            # The LATEST enter wins: cancel the pending request and proceed.
            # The reference instead rejects while isEnteringSpace
            # (Entity.go:1000-1004) — safe for it because its bots never
            # race a reload — but an ack lost to a freeze window would then
            # wedge the entity's space-hopping for the whole migrate
            # window. Superseding is protocol-safe here: CANCEL_MIGRATE
            # releases any dispatcher block, and the per-request NONCE
            # guarantees the old request's late acks can't drive the new
            # one into an unblocked migration.
            gwlog.debugf("%s: enter_space supersedes a pending enter", self)
            self.cancel_enter_space()
        space = entity_manager.get_space(spaceid)
        if space is not None:
            entity_manager.runtime.post(lambda: self._enter_local_space(space, pos))
            return
        # Cross-game: ask the dispatcher which game owns the space. Routed by
        # the SPACE id — its dispatch record lives on hash(spaceid)'s
        # dispatcher (reference SelectByEntityID(spaceID), Entity.go:1006-1012).
        self._enter_space_nonce += 1
        nonce = self._enter_space_nonce
        self._enter_space_request = (
            spaceid, pos, entity_manager.runtime.now(), nonce
        )
        dispatchercluster.select_by_entity_id(spaceid).send_query_space_gameid_for_migrate(
            spaceid, self.id, nonce
        )

    def _enter_local_space(self, space, pos: Vector3) -> None:
        if self._destroyed or space.is_destroyed():
            return
        if space is self.space:
            return
        if self.space is not None:
            self.space._leave(self)
        space._enter(self, pos)

    def cancel_enter_space(self) -> None:
        if self._enter_space_request is None:
            return
        self._enter_space_request = None
        dispatchercluster.select_by_entity_id(self.id).send_cancel_migrate(self.id)

    def _enter_space_request_valid(self, spaceid: str, nonce: int) -> bool:
        """Validity checks on migration acks (Entity.go:1026-1058): entity
        destroyed, request superseded, or request timed out → cancel."""
        from goworld_tpu import consts
        from goworld_tpu.entity import entity_manager

        req = self._enter_space_request
        if req is None:
            return False
        rspaceid, _, t0, rnonce = req
        if rspaceid != spaceid or rnonce != nonce:
            # Stale ack for a superseded request — ignore it; the current
            # request stays live. The NONCE check matters even for the same
            # space id: a buffered ack for an expired-and-canceled request
            # must not drive a newer request into REAL_MIGRATE, because the
            # cancel already released the dispatcher's block (the reference
            # compares space ids only, but it also never replaces a pending
            # request before the full migrate window elapses).
            return False
        if self._destroyed:
            self.cancel_enter_space()
            return False
        if entity_manager.runtime.now() - t0 > consts.DISPATCHER_MIGRATE_TIMEOUT:
            gwlog.warnf("%s: enter space %s timed out", self, spaceid)
            self.cancel_enter_space()
            return False
        return True

    def on_query_space_gameid_ack(self, spaceid: str, gameid: int,
                                  nonce: int = 0) -> None:
        """Step 2 of cross-game EnterSpace (Entity.go:1026-1058): the
        dispatcher told us which game owns the target space."""
        from goworld_tpu.entity import entity_manager

        if not self._enter_space_request_valid(spaceid, nonce):
            return
        if gameid == 0:
            gwlog.warnf("%s: space %s not found anywhere", self, spaceid)
            self.cancel_enter_space()
            return
        if gameid == entity_manager.runtime.gameid:
            # The space appeared locally since we asked — fast path after all.
            space = entity_manager.get_space(spaceid)
            if space is None:
                gwlog.warnf("%s: space %s reported local but not found", self, spaceid)
                self.cancel_enter_space()
                return
            _, pos, _, _ = self._enter_space_request
            self._enter_space_request = None
            entity_manager.runtime.post(lambda: self._enter_local_space(space, pos))
            return
        dispatchercluster.select_by_entity_id(self.id).send_migrate_request(
            self.id, spaceid, gameid, nonce
        )

    def on_migrate_request_ack(self, spaceid: str, space_gameid: int,
                               nonce: int = 0) -> None:
        """Step 3: dispatcher blocked our RPC stream; pack and really migrate
        (Entity.go:1092-1101)."""
        from goworld_tpu.entity import entity_manager

        if not self._enter_space_request_valid(spaceid, nonce):
            return
        from goworld_tpu.entity import entity_manager

        _, pos, _, _ = self._enter_space_request
        self._enter_space_request = None
        data = self.get_migrate_data()
        # Rebuild into the *target* space at the requested position; keep
        # the ORIGINAL space so a bounce-home (dead target game) restores
        # the entity where it was, not into the nil space.
        data["prev_space_id"] = data.get("space_id")
        data["space_id"] = spaceid
        data["pos"] = [pos.x, pos.y, pos.z]
        sender = dispatchercluster.select_by_entity_id(self.id)
        gwutils.run_panicless(self.on_migrate_out)
        self._destroy(is_migrate=True)
        sender.send_real_migrate(
            self.id, space_gameid, data,
            source_game=entity_manager.runtime.gameid)

    def get_migrate_data(self) -> dict:
        """Everything needed to rebuild the entity elsewhere
        (Entity.go:631-651): all attrs, client binding, pos/yaw, timers,
        space id, sync flag."""
        client = None
        if self.client is not None:
            client = {"clientid": self.client.clientid,
                      "gateid": self.client.gateid,
                      "gen": self.client.gate_gen}
        return {
            "type": self.typename,
            "attrs": self.attrs.to_dict(),
            "client": client,
            "pos": [self.position.x, self.position.y, self.position.z],
            "yaw": self.yaw,
            "timers": self._pack_timers(),
            "space_id": self.space.id if self.space is not None else None,
            "syncing": self._syncing_from_client,
            # A pending-but-uncollected sync flag travels with the entity:
            # a move flagged just before migrate-out would otherwise be
            # silently dropped with the slab slot (the clients never see
            # the final pre-hop position). restore_entity re-arms it.
            "sync_flag": self._sync_info_flag,
        }

    get_freeze_data = get_migrate_data  # freeze data ≡ migrate data (§5.4)

    # --- persistence (Entity.go:150,215-217) -------------------------------

    def save(self) -> None:
        if self.is_persistent():
            self._save()

    def _save(self) -> None:
        from goworld_tpu.entity import entity_manager

        entity_manager.runtime.save_entity(self.typename, self.id, self.persistent_attrs())

    def _start_save_timer(self, interval: float) -> None:
        from goworld_tpu.entity import entity_manager

        if interval > 0 and self.is_persistent():
            self._save_timer = entity_manager.runtime.timer_service_for(self).add_timer(
                interval, self._on_save_timer
            )

    def _on_save_timer(self) -> None:
        if not self._destroyed:
            self._save()


def _is_rpc_method(cls: type, method: str) -> bool:
    """A method is RPC-exposed iff defined on a subclass of Entity (not on
    Entity/Space base themselves) — the analog of the reference scanning only
    user-defined methods into the rpc table (rpc_desc.go:8-46)."""
    from goworld_tpu.entity.space import Space

    fn = getattr(cls, method, None)
    if fn is None or not callable(fn):
        return False
    for klass in cls.__mro__:
        if method in vars(klass):
            return klass not in (Entity, Space)
    return False

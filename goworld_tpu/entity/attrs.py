"""Nested attribute tree with change streaming.

Reference parity: ``engine/entity`` attr system — ``MapAttr`` (MapAttr.go:12-19,
set: MapAttr.go:83-116), ``ListAttr`` (ListAttr.go:11-18), per-key flags
(attr.go:5-10), value uniformization (attr.go:39-75: everything becomes
int/float/bool/str or nested Map/List), path computation (attr.go:12-36) and
client push-down (Entity.go:814-917).

Every mutation on a subtree that is client-visible produces one change record
routed to the owning entity, which forwards it to the client proxy — that is
how nested attr edits stream to clients incrementally instead of re-sending
whole trees.
"""

from __future__ import annotations

from typing import Any, Callable, Iterator

# Change record kinds pushed to the owner entity.
MAP_CHANGE = "map_change"  # (path, key, value)
MAP_DEL = "map_del"  # (path, key)
MAP_CLEAR = "map_clear"  # (path,)
LIST_CHANGE = "list_change"  # (path, index, value)
LIST_APPEND = "list_append"  # (path, value)
LIST_POP = "list_pop"  # (path,)


def uniform_attr_type(v: Any):
    """Normalize a plain value into attr-storable form (attr.go:39-75)."""
    if v is None or isinstance(v, (bool, int, float, str, bytes)):
        return v
    if isinstance(v, MapAttr) or isinstance(v, ListAttr):
        return v
    if isinstance(v, dict):
        m = MapAttr()
        m.assign(v)
        return m
    if isinstance(v, (list, tuple)):
        l = ListAttr()
        l.extend(v)
        return l
    raise TypeError(f"unsupported attr value type: {type(v)!r}")


def _plain(v: Any):
    """Convert attr values back to plain Python (for wire / storage)."""
    if isinstance(v, MapAttr):
        return v.to_dict()
    if isinstance(v, ListAttr):
        return v.to_list()
    return v


class _AttrNode:
    """Shared parent/owner bookkeeping for Map/List attr nodes."""

    __slots__ = ("parent", "pkey", "_owner_cb", "flag_key")

    def __init__(self) -> None:
        self.parent: _AttrNode | None = None
        self.pkey: Any = None  # key (in parent map) or index (in parent list)
        # Root-only: callback(kind, path, *args) → owning entity.
        self._owner_cb: Callable | None = None
        # Root-only hint: which top-level key this subtree hangs under.
        self.flag_key: str | None = None

    # --- path / owner ------------------------------------------------------

    def _root(self) -> "_AttrNode":
        node = self
        while node.parent is not None:
            node = node.parent
        return node

    def path(self) -> list:
        """Path from root to this node (attr.go:12-36), as [key/index, ...]."""
        out: list = []
        node = self
        while node.parent is not None:
            out.append(node.pkey)
            node = node.parent
        out.reverse()
        return out

    def top_key(self) -> str | None:
        """The top-level key this node lives under (flags are per top key)."""
        node = self
        while node.parent is not None:
            if node.parent.parent is None:
                return node.pkey if isinstance(node.pkey, str) else None
            node = node.parent
        return None

    def _notify(self, kind: str, *args) -> None:
        root = self._root()
        if root._owner_cb is not None:
            root._owner_cb(kind, self.path(), *args)

    def _adopt(self, v: Any, key: Any) -> None:
        if isinstance(v, (MapAttr, ListAttr)):
            if v.parent is not None or v._owner_cb is not None:
                raise ValueError("attr subtree already attached elsewhere")
            v.parent = self
            v.pkey = key

    @staticmethod
    def _release(v: Any) -> None:
        if isinstance(v, (MapAttr, ListAttr)):
            v.parent = None
            v.pkey = None


class MapAttr(_AttrNode):
    """String-keyed attribute map (MapAttr.go:12-19)."""

    __slots__ = ("_data",)

    def __init__(self) -> None:
        super().__init__()
        self._data: dict[str, Any] = {}

    # --- mutation ----------------------------------------------------------

    def set(self, key: str, value: Any) -> None:
        v = uniform_attr_type(value)
        old = self._data.get(key)
        self._release(old)
        self._adopt(v, key)
        self._data[key] = v
        self._notify(MAP_CHANGE, key, _plain(v))

    __setitem__ = set

    def set_default(self, key: str, value: Any):
        if key not in self._data:
            self.set(key, value)
        return self._data[key]

    def delete(self, key: str) -> None:
        if key in self._data:
            self._release(self._data.pop(key))
            self._notify(MAP_DEL, key)

    __delitem__ = delete

    def clear(self) -> None:
        for v in self._data.values():
            self._release(v)
        self._data.clear()
        self._notify(MAP_CLEAR)

    def assign(self, d: dict) -> None:
        for k, v in d.items():
            self.set(k, v)

    # --- access ------------------------------------------------------------

    def get(self, key: str, default: Any = None) -> Any:
        return self._data.get(key, default)

    def __getitem__(self, key: str) -> Any:
        return self._data[key]

    def get_int(self, key: str, default: int = 0) -> int:
        v = self._data.get(key, default)
        return int(v) if v is not None else default

    def get_float(self, key: str, default: float = 0.0) -> float:
        v = self._data.get(key, default)
        return float(v) if v is not None else default

    def get_str(self, key: str, default: str = "") -> str:
        v = self._data.get(key, default)
        return str(v) if v is not None else default

    def get_bool(self, key: str, default: bool = False) -> bool:
        v = self._data.get(key, default)
        return bool(v) if v is not None else default

    def get_map(self, key: str) -> "MapAttr":
        """Get-or-create a nested MapAttr."""
        v = self._data.get(key)
        if not isinstance(v, MapAttr):
            v = MapAttr()
            self.set(key, v)
        return v

    def get_list(self, key: str) -> "ListAttr":
        v = self._data.get(key)
        if not isinstance(v, ListAttr):
            v = ListAttr()
            self.set(key, v)
        return v

    def has(self, key: str) -> bool:
        return key in self._data

    __contains__ = has

    def keys(self):
        return self._data.keys()

    def items(self):
        return self._data.items()

    def __len__(self) -> int:
        return len(self._data)

    def __iter__(self) -> Iterator[str]:
        return iter(self._data)

    # --- conversion --------------------------------------------------------

    def to_dict(self) -> dict:
        return {k: _plain(v) for k, v in self._data.items()}

    def to_dict_filtered(self, keys) -> dict:
        return {k: _plain(v) for k, v in self._data.items() if k in keys}

    def __repr__(self) -> str:
        return f"MapAttr({self.to_dict()!r})"


class ListAttr(_AttrNode):
    """Index-addressed attribute list (ListAttr.go:11-18)."""

    __slots__ = ("_data",)

    def __init__(self) -> None:
        super().__init__()
        self._data: list[Any] = []

    # --- mutation ----------------------------------------------------------

    def append(self, value: Any) -> None:
        v = uniform_attr_type(value)
        self._adopt(v, len(self._data))
        self._data.append(v)
        self._notify(LIST_APPEND, _plain(v))

    def extend(self, values) -> None:
        for v in values:
            self.append(v)

    def set(self, index: int, value: Any) -> None:
        v = uniform_attr_type(value)
        old = self._data[index]
        self._release(old)
        self._adopt(v, index)
        self._data[index] = v
        self._notify(LIST_CHANGE, index, _plain(v))

    __setitem__ = set

    def pop(self) -> Any:
        v = self._data.pop()
        self._release(v)
        self._notify(LIST_POP)
        return _plain(v)

    # --- access ------------------------------------------------------------

    def __getitem__(self, index: int) -> Any:
        return self._data[index]

    def __len__(self) -> int:
        return len(self._data)

    def __iter__(self):
        return iter(self._data)

    def to_list(self) -> list:
        return [_plain(v) for v in self._data]

    def __repr__(self) -> str:
        return f"ListAttr({self.to_list()!r})"

"""Columnar ECS attributes: declarative per-type slab columns + fusable
per-class tick programs.

The reference engine's unit of state is the Entity with dict-shaped ATTRS
(Entity.go:814-917); PR 6 made position/sync state columnar, but numeric
game attrs (health, score, cooldowns) still lived in per-entity Python
dicts. This module closes that gap ("The Essence of Entity Component
System", PAPERS.md):

- ``EntityTypeDesc.define_attr(name, "Column", dtype=..., default=...)``
  declares a numeric attr whose storage is a process-wide slab column
  (entity/slabs.py) indexed by the entity's slot. Per-entity reads and
  writes keep the ordinary attrs surface — ``e.attrs["hp"]``,
  ``e.attrs.set("hp", 5)``, ``to_dict()`` — via :class:`ColumnBackedMapAttr`,
  which proxies Column keys to the column and leaves every other key in
  the dict exactly as before. Because ``to_dict`` merges column values,
  Column attrs ride the EXISTING migrate/freeze msgpack blob and the
  persistence snapshots with zero wire-format changes (the schema digest
  stays pinned — tests/test_rebalance.py).

- :func:`columnar_tick` lifts a pure per-entity numeric function over
  (x, y, z, yaw, dt, *columns) into an ``on_tick_batch`` classmethod —
  the generalization of ``slabs.vmapped_position_tick`` to declared
  Column attrs — and tags it with a :class:`FusedProgram` so the batched
  AOI service can compile the SAME function INTO the engine step jit
  (``[aoi] fuse_logic``): steady-state ticks then run move + entity logic
  + neighbor interest as ONE device launch (the AsyncTaichi inter-kernel
  fusion end-state, PAPERS.md; see ops/neighbor.py ``_apply_fused_logic``
  and aoi/batched.py for the delivery contract).

Client streaming: a per-entity ``set()`` on a Column attr notifies the
normal attr-change stream (Client/AllClients flags keep working); batch
writes (``SlabTickView.set_col`` or the fused step) are server-side state
updates and do not stream per-change — by design, exactly like position,
which has its own vectorized sync channel.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import numpy as np

from goworld_tpu.entity.attrs import MAP_CHANGE, MapAttr

# Column dtypes are numeric-only: columns exist to vectorize and to ride
# the device step; strings/blobs stay ordinary dict attrs.
_ALLOWED_DTYPES = ("float32", "float64", "int32", "int64", "bool")


@dataclasses.dataclass(frozen=True)
class ColumnSpec:
    """One declared attr column: name, numpy dtype name, default value.

    Frozen + comparable: two entity types may declare the same column name
    only with an identical spec (the storage is one process-wide array)."""

    name: str
    dtype: str = "float32"
    default: float = 0.0

    def __post_init__(self) -> None:
        if self.dtype not in _ALLOWED_DTYPES:
            raise ValueError(
                f"Column {self.name!r}: dtype must be one of "
                f"{_ALLOWED_DTYPES}, got {self.dtype!r}"
            )

    @property
    def np_dtype(self) -> np.dtype:
        return np.dtype(self.dtype)

    def to_python(self, value: Any) -> Any:
        """Column cell -> plain Python scalar (msgpack/storage-safe)."""
        if self.dtype == "bool":
            return bool(value)
        if self.dtype.startswith("int"):
            return int(value)
        return float(value)


@dataclasses.dataclass(frozen=True)
class FusedProgram:
    """A fusable per-class tick program: ``fn(x, y, z, yaw, dt, *cols) ->
    (x, y, z, yaw, *cols)`` on scalars, plus the Column names it reads and
    writes (in order). Hashable (fn by identity) — the engine's fused step
    jit caches per program tuple (ops/neighbor._jitted_step_packed_fused).
    """

    fn: Callable
    columns: tuple[str, ...] = ()


def columnar_tick(fn: Callable, columns=()):
    """Lift ``fn(x, y, z, yaw, dt, *cols) -> (x, y, z, yaw, *cols)`` into
    an ``on_tick_batch`` classmethod over every live entity of the class.

    Unfused execution (the default, and the automatic fallback on engines
    without fusion support): ONE ``jax.jit(jax.vmap)`` call per class per
    tick over the slab view's position columns plus the declared Column
    attrs, with results written back through ``set_position_yaw`` /
    ``set_col`` (sync flags set, numpy fallback when jax is unavailable).

    Fused execution (``[aoi] fuse_logic`` + a single-device or spatially
    sharded batched engine): the SAME ``fn`` is compiled into the AOI step
    jit and this hook never runs — the per-class jit is never even traced
    (tests assert ``jit_cache_size() == 0`` there). The fused tick applies
    ``fn`` to the dispatched epoch and writes results back at the next
    dispatch, so logic rides the AOI cadence with ``dt`` = inter-dispatch
    seconds; write ``fn`` dt-scaled (an integrator), not per-call-counted.

    The returned hook carries ``prewarm(n, dt, col_dtypes)`` and
    ``jit_cache_size()`` like ``vmapped_position_tick`` (the freeze→restore
    warmup surface), plus ``fused_program`` (the fusion tag).
    """
    columns = tuple(columns)
    ncols = len(columns)
    state: dict = {}

    def _batched():
        batched = state.get("fn")
        if batched is None:
            try:
                import jax

                jitted = jax.jit(jax.vmap(
                    fn, in_axes=(0, 0, 0, 0, None) + (0,) * ncols))
                state["jitted"] = jitted

                def batched(x, y, z, yaw, dt, *cols):
                    out = jitted(x, y, z, yaw, dt, *cols)
                    return tuple(np.asarray(o) for o in out)

            except Exception:  # pragma: no cover - jax is in the image
                batched = fn
            state["fn"] = batched
        return batched

    def hook(cls, view) -> None:
        if len(view) == 0:
            return
        cols = [view.col(c) for c in columns]
        out = _batched()(
            view.x, view.y, view.z, view.yaw, np.float32(view.dt), *cols)
        view.set_position_yaw(*out[:4])
        for name, arr in zip(columns, out[4:]):
            view.set_col(name, arr)

    def prewarm(n: int, dt: float = 0.05, col_dtypes=None) -> None:
        """Dummy-shaped compile at population ``n`` (results discarded);
        the restore path calls this before the cluster re-handshake so
        the first live tick pays no XLA trace (slabs.prewarm_tick_hooks).
        ``col_dtypes`` must match the declared columns' slab dtypes or the
        real call would still re-trace (float32 assumed when omitted)."""
        if n <= 0:
            return
        z = np.zeros(n, np.float32)
        dts = col_dtypes or ("float32",) * ncols
        cols = [np.zeros(n, np.dtype(d)) for d in dts]
        _batched()(z, z, z, z, np.float32(dt), *cols)

    def jit_cache_size() -> int:
        """Compiled-trace count of the unfused per-class jit (0 before
        first use — and 0 FOREVER while the class runs fused, which is the
        one-launch regression gate's assertion)."""
        jitted = state.get("jitted")
        if jitted is None:
            return 0
        try:
            return int(jitted._cache_size())
        except Exception:  # pragma: no cover - private-API drift
            return -1

    hook.prewarm = prewarm
    hook.jit_cache_size = jit_cache_size
    hook.fused_program = FusedProgram(fn, columns)
    return classmethod(hook)


class ColumnBackedMapAttr(MapAttr):
    """Root attrs map for entity types with Column attrs.

    Column keys proxy to the entity's slab column row; everything else is
    the plain dict MapAttr. Always the ROOT of the attr tree (columns are
    top-level keys by construction), so ``path()`` is empty for column
    notifications and the client push-down sees ordinary MAP_CHANGEs.

    After the entity's slot is released (destroy), reads fall back to a
    snapshot taken at release time — same contract as the entity's
    ``_final_pos_yaw``."""

    __slots__ = ("_entity", "_slabs", "_colspecs", "_final", "_primed")

    def __init__(self, entity, slabs, colspecs: dict[str, ColumnSpec]) -> None:
        super().__init__()
        self._entity = entity
        self._slabs = slabs
        self._colspecs = colspecs
        self._final: dict[str, Any] | None = None
        self._primed: dict[str, Any] | None = None

    # --- column cell access -------------------------------------------------

    def _col_get(self, key: str) -> Any:
        primed = self._primed
        if primed is not None and key in primed:
            return primed[key]
        spec = self._colspecs[key]
        slot = self._entity._slot
        if slot < 0:
            if self._final is not None and key in self._final:
                return self._final[key]
            return spec.to_python(spec.default)
        return spec.to_python(self._slabs.columns[key][slot])

    def _col_set(self, key: str, value: Any) -> None:
        if self._primed is not None:
            # A write inside a primed window (an overridden snapshot hook
            # mutating state) must be visible to subsequent reads.
            self._primed.pop(key, None)
        spec = self._colspecs[key]
        slot = self._entity._slot
        if slot < 0:
            if self._final is None:
                self._final = {}
            self._final[key] = spec.to_python(value)
            return
        self._slabs.columns[key][slot] = value
        # Protect the write from an in-flight fused tick's writeback
        # (aoi/batched.py _consume_fused): host writes win.
        self._slabs.fused_dirty[slot] = True

    def prime_columns(self, values: dict[str, Any]) -> None:
        """Install a batch-gathered column value cache (columnar batch
        persistence, entity/entity_manager.py): within the primed window
        every column read returns the pre-gathered plain-Python value
        instead of touching the slab row, so a per-type snapshot round
        costs ONE fancy-index gather per column instead of one slab read
        per entity per key. Values must be exactly what ``to_python``
        would return (the gather uses ndarray.tolist(), which performs
        the identical widening) — bit-identity of freeze/migrate blobs
        is asserted by tests/test_columns.py."""
        self._primed = values

    def unprime_columns(self) -> None:
        self._primed = None

    def _snapshot_columns(self) -> None:
        """Called by Entity._release_slab_slot just before the slot goes:
        post-destroy reads (late saves, diagnostics) stay valid."""
        self._final = {k: self._col_get(k) for k in self._colspecs}

    # --- mutation (column keys intercepted) ---------------------------------

    def set(self, key: str, value: Any) -> None:
        if key in self._colspecs:
            self._col_set(key, value)
            self._notify(MAP_CHANGE, key, self._col_get(key))
            return
        super().set(key, value)

    __setitem__ = set

    def set_default(self, key: str, value: Any):
        if key in self._colspecs:
            return self._col_get(key)  # a column always has a value
        return super().set_default(key, value)

    def delete(self, key: str) -> None:
        if key in self._colspecs:
            raise ValueError(
                f"Column attr {key!r} cannot be deleted (slab storage); "
                f"set it to its default instead")
        super().delete(key)

    __delitem__ = delete

    def clear(self) -> None:
        for key, spec in self._colspecs.items():
            self._col_set(key, spec.default)
        super().clear()

    # --- access (columns merged) --------------------------------------------

    def get(self, key: str, default: Any = None) -> Any:
        if key in self._colspecs:
            return self._col_get(key)
        return super().get(key, default)

    def __getitem__(self, key: str) -> Any:
        if key in self._colspecs:
            return self._col_get(key)
        return super().__getitem__(key)

    def get_int(self, key: str, default: int = 0) -> int:
        v = self.get(key, default)
        return int(v) if v is not None else default

    def get_float(self, key: str, default: float = 0.0) -> float:
        v = self.get(key, default)
        return float(v) if v is not None else default

    def get_str(self, key: str, default: str = "") -> str:
        v = self.get(key, default)
        return str(v) if v is not None else default

    def get_bool(self, key: str, default: bool = False) -> bool:
        v = self.get(key, default)
        return bool(v) if v is not None else default

    def has(self, key: str) -> bool:
        return key in self._colspecs or super().has(key)

    __contains__ = has

    def keys(self):
        return list(self._colspecs) + list(self._data.keys())

    def items(self):
        for k in self._colspecs:
            yield k, self._col_get(k)
        yield from self._data.items()

    def __len__(self) -> int:
        return len(self._colspecs) + len(self._data)

    def __iter__(self):
        return iter(self.keys())

    # --- conversion (migrate/freeze/persist ride these) ---------------------

    def to_dict(self) -> dict:
        out = {k: self._col_get(k) for k in self._colspecs}
        out.update(super().to_dict())
        return out

    def to_dict_filtered(self, keys) -> dict:
        out = {k: self._col_get(k) for k in self._colspecs if k in keys}
        out.update(super().to_dict_filtered(keys))
        return out


def make_attr_root(desc, entity) -> MapAttr:
    """The attr root for a fresh/restored entity: column-backed when the
    type declares Column attrs, the plain MapAttr otherwise (zero overhead
    for column-free types — the common case stays exactly as before)."""
    colspecs = getattr(desc, "column_attrs", None)
    if colspecs:
        return ColumnBackedMapAttr(entity, entity._slabs, colspecs)
    return MapAttr()

"""Entity registration, creation, routing and process-level operations.

Reference parity: ``engine/entity/EntityManager.go`` — type registry with
declarative attr flags (:154-193), createEntity (:233-277), restoreEntity
(:279-339), load-with-persistent-filter (:341-375), Call routing (:433-446),
CallNilSpaces (:448-459), Freeze/RestoreFreezedEntities (:554-656) — plus
``SpaceManager.go`` and the nil-space bookkeeping of ``space_ops.go:32-50``.

The ``Runtime`` object is the seam between pure entity logic and the process
around it (timers, post queue, storage, AOI backend, dispatcher presence); a
default Runtime makes the whole runtime unit-testable in-process, matching
how reference entity tests run without a dispatcher (SURVEY.md §4.1).
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from typing import Optional, Type

import numpy as np

from goworld_tpu import consts, dispatchercluster, telemetry
from goworld_tpu.common import gen_entity_id, gen_fixed_entity_id
from goworld_tpu.entity.columns import ColumnBackedMapAttr, make_attr_root
from goworld_tpu.entity.entity import (
    Entity,
    EntityTypeDesc,
)
from goworld_tpu.entity.game_client import GameClient
from goworld_tpu.entity.slabs import EntitySlabs
from goworld_tpu.entity.space import SPACE_KIND_NIL, Space
from goworld_tpu.entity.vector import Vector3
from goworld_tpu.utils import gwlog, gwutils, post as post_mod
from goworld_tpu.utils.timer import TimerService

# Sync fan-out per-hop attribution (shared family with game_pack in
# game/service.py and the dispatcher/gate hops): the game-side half is
# split into collect (flag scan + interest-edge gather over the slabs)
# and pack (per-gate structured-array build + wire bytes) so a fan-out
# regression names the sub-stage (bench.py --fanout hop_shares).
_HOP = telemetry.counter(
    "fanout_hop_seconds_total",
    "Busy wall seconds per sync fan-out hop (game_collect|game_pack|"
    "game_send|dispatcher_route|gate_demux|client_write).",
    ("hop",))
_HOP_COLLECT = _HOP.labels("game_collect")
_HOP_PACK = _HOP.labels("game_pack")

# Host-phase attribution, persist half (the delivery half lives in
# aoi/batched.py — telemetry.counter get-or-creates, so both modules share
# one family): wall seconds spent building freeze/migrate/save snapshots,
# including the columnar batch gather that feeds them.
_PHASE_PERSIST = telemetry.counter(
    "aoi_host_phase_seconds_total",
    "Busy wall seconds per host-side tick phase (delivery|persist).",
    ("phase",)).labels("persist")


class Runtime:
    """Process context for entity logic (see module docstring)."""

    def __init__(self) -> None:
        self.gameid: int = 1
        # Columnar hot-state store (entity/slabs.py): every Entity gets a
        # slot at construction; the batched AOI engine allocates from the
        # SAME slot space.
        self.slabs = EntitySlabs()
        self.timer_service = TimerService()
        self.save_interval: float = 0.0  # 0 = no periodic save (tests)
        self.position_sync_interval: float = consts.POSITION_SYNC_INTERVAL
        self.aoi_backend: str = "xzlist"  # xzlist | batched
        self.aoi_service = None  # BatchAOIService, lazily created
        self.aoi_params = None  # NeighborParams override
        self.aoi_mesh_shards: int = 1  # [aoi] mesh_shards: devices to shard over
        # [aoi] shard_mode: spatial (grid-strip halo exchange) | entity
        # (all-gather rows); only read when mesh_shards > 1.
        self.aoi_shard_mode: str = "spatial"
        # [aoi] strip_placement: topology (AoiZora-style strip→device
        # placement from mesh coords) | ring (mesh order as given).
        self.aoi_strip_placement: str = "topology"
        # [aoi] pallas_strip_cols: static strip-width cap of the Pallas
        # spatial tier's kernel slab (0 = derive: 2x the uniform strip).
        self.aoi_pallas_strip_cols: int = 0
        # [aoi] pallas_inkernel_drain: the Pallas spatial tier's kernel
        # launch emits the compacted event pairs itself (steady strip
        # ticks run no XLA rank-select pass).
        self.aoi_pallas_inkernel_drain: bool = True
        # Multi-HOST (DCN) tier: True once this process has joined the
        # jax.distributed mesh ([aoi] multihost_coordinator; the game
        # service calls init_multihost before any jax use).
        self.aoi_multihost: bool = False
        self.aoi_delivery: str = "pipelined"  # [aoi] delivery: pipelined | sync
        # [aoi] fuse_logic: compile per-class columnar tick programs INTO
        # the batched engine's step launch (entity/columns.py; one device
        # launch per steady-state tick).
        self.aoi_fuse_logic: bool = False
        # [aoi] sync_wait_budget: sync-mode stall ceiling before degrading
        # to deferred delivery (batched.py SYNC_WAIT_BUDGET rationale).
        self.aoi_sync_wait_budget: float = 0.5
        self.storage = None  # object with .save/.load/.exists (storage module)
        self.game_service = None  # the running GameService, if any

    def post(self, cb) -> None:
        post_mod.post(cb)

    def now(self) -> float:
        return time.monotonic()

    def timer_service_for(self, entity) -> TimerService:
        return self.timer_service

    # --- AOI backend -------------------------------------------------------

    def get_aoi_service(self):
        if self.aoi_service is None:
            from goworld_tpu.entity.aoi.batched import BatchAOIService
            from goworld_tpu.ops.neighbor import NeighborParams

            params = self.aoi_params or NeighborParams()
            self.aoi_service = BatchAOIService(
                params, mesh_shards=self.aoi_mesh_shards,
                multihost=self.aoi_multihost,
                shard_mode=self.aoi_shard_mode,
                fuse_logic=self.aoi_fuse_logic,
                strip_placement=self.aoi_strip_placement,
                pallas_strip_cols=self.aoi_pallas_strip_cols,
                pallas_inkernel_drain=self.aoi_pallas_inkernel_drain,
            )
            self.aoi_service.delivery = self.aoi_delivery
            self.aoi_service.sync_wait_budget = self.aoi_sync_wait_budget
        return self.aoi_service

    def new_aoi_manager(self, distance: float):
        if self.aoi_backend == "xzlist":
            from goworld_tpu.entity.aoi.xzlist import XZListAOIManager

            return XZListAOIManager(distance)
        from goworld_tpu.entity.aoi.batched import BatchSpaceAOIManager

        return BatchSpaceAOIManager(self.get_aoi_service(), distance)

    # --- persistence -------------------------------------------------------

    def save_entity(self, typename: str, eid: str, data: dict) -> None:
        if self.storage is not None:
            self.storage.save(typename, eid, data)

    def load_entity(self, typename: str, eid: str) -> Optional[dict]:
        if self.storage is not None:
            return self.storage.load(typename, eid)
        return None

    # --- ticking (tests / embedded) ----------------------------------------

    def tick(self) -> None:
        self.timer_service.tick()
        self.slabs.run_tick_batches(self.now())
        if self.aoi_service is not None:
            self.aoi_service.tick()
        post_mod.tick()


runtime = Runtime()

_registry: dict[str, EntityTypeDesc] = {}
_space_class: Optional[Type[Space]] = None
_entities: dict[str, Entity] = {}
_spaces: dict[str, Space] = {}
_client_owners: dict[str, Entity] = {}
_save_interval_override: Optional[float] = None


# --- registration (EntityManager.go:154-193) --------------------------------


def register_entity(entity_class: Type[Entity], typename: str | None = None) -> EntityTypeDesc:
    name = typename or entity_class.__name__
    if name in _registry:
        raise ValueError(f"entity type {name!r} already registered")
    desc = EntityTypeDesc(name, entity_class)
    desc.is_space = issubclass(entity_class, Space)
    if desc.is_space:
        # AOI enablement must survive storage round-trips (Space.go:117-125).
        desc.define_attr("_EnableAOI", "Persistent")
    describe = getattr(entity_class, "describe_entity_type", None)
    if describe is not None:
        describe(desc)
    entity_class._type_desc = desc
    _registry[name] = desc
    return desc


def register_space(space_class: Type[Space]) -> EntityTypeDesc:
    """Register THE space class of this game (reference RegisterSpace)."""
    global _space_class
    desc = register_entity(space_class)
    _space_class = space_class
    return desc


def get_entity_type_desc(typename: str) -> EntityTypeDesc:
    return _registry[typename]


# --- creation (EntityManager.go:233-277) ------------------------------------


def create_entity_locally(
    typename: str,
    eid: str | None = None,
    attrs: dict | None = None,
    space: Space | None = None,
    pos: Vector3 | None = None,
) -> Entity:
    desc = _registry.get(typename)
    if desc is None:
        raise KeyError(f"entity type {typename!r} not registered")
    if desc.is_space:
        raise TypeError(f"{typename} is a space type; use create_space_locally")
    return _new_entity(desc, eid, attrs, space, pos)


def _new_entity(
    desc: EntityTypeDesc,
    eid: str | None,
    attrs: dict | None,
    space: Space | None,
    pos: Vector3 | None,
    kind: int | None = None,
) -> Entity:
    e = desc.entity_class()
    e.id = eid or gen_entity_id()
    if e.id in _entities:
        raise ValueError(f"entity id {e.id} already exists")
    # Column-declaring types get a column-backed root (entity/columns.py):
    # Column keys proxy to the slab columns, everything else stays dict.
    root = make_attr_root(desc, e)
    e._bind_attrs(root)
    if attrs:
        root.assign(attrs)
    if isinstance(e, Space) and kind is not None:
        e.kind = kind
    _entities[e.id] = e
    if isinstance(e, Space):
        _spaces[e.id] = e
    elif space is None:
        # Default membership: every entity lives in the nil space until it
        # enters a real one (EntityManager.go:250 `entity.Space = nilSpace`;
        # pointer-only, no AOI/entity-set bookkeeping). Without this a
        # freshly loaded Avatar answers GetSpaceID with "" and the Account
        # re-login flow dies on enter_space("").
        e.space = get_nil_space()
    gwutils.run_panicless(e.on_init)
    if isinstance(e, Space):
        e._maybe_restore_aoi()
        gwutils.run_panicless(e.on_space_init)
    gwutils.run_panicless(e.on_attrs_ready)
    # Tell the dispatcher this entity lives here (DispatcherService.go:643-661).
    dispatchercluster.select_by_entity_id(e.id).send_notify_create_entity(e.id)
    interval = _save_interval_override if _save_interval_override is not None else runtime.save_interval
    e._start_save_timer(interval)
    gwutils.run_panicless(e.on_created)
    if isinstance(e, Space):
        gwutils.run_panicless(e.on_space_created)
    if space is not None:
        space._enter(e, pos or Vector3())
    gwlog.debugf("created %r in space %s", e,
                 e.space.id if not isinstance(e, Space) and e.space else "-")
    return e


def create_space_locally(kind: int, eid: str | None = None, attrs: dict | None = None) -> Space:
    if _space_class is None:
        raise RuntimeError("no space class registered (register_space)")
    if kind == SPACE_KIND_NIL:
        raise ValueError("kind 0 is reserved for nil spaces")
    return _new_entity(_space_class._type_desc, eid, attrs, None, None, kind=kind)  # type: ignore[union-attr]


def create_space_somewhere(kind: int) -> None:
    """Ask the dispatcher to create a space on the least-loaded game."""
    if not dispatchercluster.is_connected():
        create_space_locally(kind)
        return
    eid = gen_entity_id()
    dispatchercluster.select_by_entity_id(eid).send_create_entity_somewhere(
        0, _space_class._type_desc.typename, eid, {"_kind": kind}  # type: ignore[union-attr]
    )


def create_nil_space(gameid: int) -> Space:
    """The per-game nil space with deterministic id (space_ops.go:32-46)."""
    if _space_class is None:
        raise RuntimeError("no space class registered (register_space)")
    eid = get_nil_space_id(gameid)
    return _new_entity(_space_class._type_desc, eid, None, None, None, kind=SPACE_KIND_NIL)


def get_nil_space_id(gameid: int) -> str:
    return gen_fixed_entity_id(gameid)


def get_nil_space() -> Optional[Space]:
    return _spaces.get(get_nil_space_id(runtime.gameid))


def get_game_id() -> int:
    """This game process's id (goworld.GetGameID)."""
    return runtime.gameid


def get_online_games() -> set[int]:
    """Ids of the games currently connected to the cluster
    (goworld.GetOnlineGames, fed by NOTIFY_GAME_CONNECTED/DISCONNECTED).
    Embedded/test runtimes without a GameService know only themselves."""
    gs = runtime.game_service
    games = {runtime.gameid}
    if gs is not None:
        games |= set(gs.online_games)
    return games


def now() -> float:
    """Monotonic engine time (drives timers and service bookkeeping)."""
    return runtime.now()


def create_entity_somewhere(typename: str, attrs: dict | None = None, gameid: int = 0) -> str:
    """Create on some game (0 = dispatcher load-balanced choose,
    DispatcherService.go:529-542). Returns the pre-generated entity id."""
    eid = gen_entity_id()
    if not dispatchercluster.is_connected():
        create_entity_locally(typename, eid=eid, attrs=attrs)
        return eid
    dispatchercluster.select_by_entity_id(eid).send_create_entity_somewhere(
        gameid, typename, eid, attrs or {}
    )
    return eid


# --- load from storage (EntityManager.go:341-375) ---------------------------


def load_entity_locally(typename: str, eid: str) -> Optional[Entity]:
    if eid in _entities:
        return _entities[eid]
    data = runtime.load_entity(typename, eid)
    if data is None:
        return None
    desc = _registry[typename]
    persistent = {k: v for k, v in data.items() if k in desc.persistent_attrs}
    return _new_entity(desc, eid, persistent, None, None)


def load_entity_somewhere(typename: str, eid: str, gameid: int = 0) -> None:
    if not dispatchercluster.is_connected():
        load_entity_locally(typename, eid)
        return
    dispatchercluster.select_by_entity_id(eid).send_load_entity_somewhere(
        typename, eid, gameid
    )


# --- lookup / call (EntityManager.go:103-152,433-446) -----------------------


def get_entity(eid: str) -> Optional[Entity]:
    return _entities.get(eid)


def get_space(eid: str) -> Optional[Space]:
    return _spaces.get(eid)


def get_entities_by_type(typename: str) -> list[Entity]:
    return [e for e in _entities.values() if e.typename == typename]


def entities() -> dict[str, Entity]:
    return _entities


def call_entity(eid: str, method: str, *args) -> None:
    """Local direct dispatch, else route via the entity's dispatcher."""
    e = _entities.get(eid)
    if e is not None:
        e.on_call_from_remote(method, args, None)
        return
    dispatchercluster.select_by_entity_id(eid).send_call_entity_method(eid, method, args)


def call_nil_spaces(method: str, *args) -> None:
    """Call a method on every game's nil space (EntityManager.go:448-459)."""
    ns = get_nil_space()
    if ns is not None:
        ns.on_call_from_remote(method, args, None)
    if dispatchercluster.is_connected():
        dispatchercluster.select_by_entity_id(
            get_nil_space_id(runtime.gameid)
        ).send_call_nil_spaces(runtime.gameid, method, args)


def handle_call(eid: str, method: str, args: tuple, clientid: str | None) -> None:
    e = _entities.get(eid)
    if e is None:
        gwlog.warnf("call %s on unknown entity %s (migrated away?)", method, eid)
        return
    e.on_call_from_remote(method, args, clientid)


# --- client bookkeeping ------------------------------------------------------


def on_client_attached(clientid: str, entity: Entity) -> None:
    _client_owners[clientid] = entity


def on_client_detached(clientid: str, entity: Entity) -> None:
    if _client_owners.get(clientid) is entity:
        del _client_owners[clientid]


def get_client_owner(clientid: str) -> Optional[Entity]:
    return _client_owners.get(clientid)


def on_gate_disconnected(gateid: int, valid_gen: int = 0) -> None:
    """Detach the clients of a dead gate (EntityManager.go:145-152).

    ``valid_gen`` != 0: the gate RESTARTED — its clients of other
    generations are dead, but clients that already connected through the
    new process (carrying valid_gen) stay attached. This makes the detach
    broadcast safe under cross-dispatcher reordering: it can arrive after
    the new gate's first clients and still only touch the dead ones."""
    for e in [e for e in _client_owners.values()
              if e.client and e.client.gateid == gateid
              and (valid_gen == 0 or e.client.gate_gen != valid_gen)]:
        e.notify_client_disconnected()


# --- destroy bookkeeping -----------------------------------------------------


def on_entity_destroyed(entity: Entity, is_migrate: bool) -> None:
    _entities.pop(entity.id, None)
    if not is_migrate:
        dispatchercluster.select_by_entity_id(entity.id).send_notify_destroy_entity(
            entity.id
        )


def on_space_destroyed(space: Space) -> None:
    _spaces.pop(space.id, None)


# --- save interval -----------------------------------------------------------


def set_save_interval(interval: float) -> None:
    global _save_interval_override
    _save_interval_override = interval


# --- game-ready --------------------------------------------------------------


def on_game_ready() -> None:
    """Deployment became ready: notify nil space first, then all entities."""
    ns = get_nil_space()
    if ns is not None:
        gwutils.run_panicless(ns.on_game_ready)
    for e in list(_entities.values()):
        if e is not ns:
            gwutils.run_panicless(e.on_game_ready)


# --- position sync collection (Entity.go:1221-1267) --------------------------


def collect_entity_sync_infos() -> dict[int, tuple[bytes, bytes]]:
    """Build the coalesced sync buffers per gate — a (full_records,
    delta_records) pair: full = [clientid(16) + 32B keyframe] blocks,
    delta = [clientid(16) + 24B quantized-delta] blocks (empty under the
    default [sync] config, where this is exactly the legacy full-rate
    path). Pure column ops over the entity slabs: the own-client rows are
    one boolean-mask gather over the flag slab and the neighbor fan-out
    rows come from the slot-indexed interest-edge table gated by each
    pair's cadence tier, so cost scales with flagged rows + DUE edges,
    not entity count x neighbors. Destroyed entities and unbound clients
    are dropped STRUCTURALLY: slot release / client unbind clear the flag
    and cid columns the masks read. Wall time lands on
    fanout_hop_seconds_total{hop=game_collect|game_pack} (the two
    game-side sub-hops of bench.py --fanout's breakdown)."""
    slabs = runtime.slabs
    t0 = time.perf_counter()
    if not slabs.sync.enabled:
        sel = slabs.collect_sync_selection()
        t1 = time.perf_counter()
        _HOP_COLLECT.inc(t1 - t0)
        if sel is None:
            return {}
        out = {
            gateid: (arr.tobytes(), b"")
            for gateid, arr in slabs.pack_sync(sel).items()
        }
        _HOP_PACK.inc(time.perf_counter() - t1)
        return out
    out = slabs.collect_sync_packets()
    _HOP_COLLECT.inc(time.perf_counter() - t0)
    return out


# --- migration receive side (EntityManager.go:279-339) -----------------------


def restore_entity(eid: str, data: dict, is_migrate: bool) -> Entity:
    """Rebuild an entity from migrate/freeze data: struct, attrs, timers,
    client binding, space membership."""
    desc = _registry[data["type"]]
    e = desc.entity_class()
    e.id = eid
    if e.id in _entities:
        raise ValueError(f"restore: entity {eid} already exists")
    # Column attrs travel inside data["attrs"] as plain scalars (they are
    # merged into to_dict by the column-backed root); assign() routes them
    # straight back into the slab columns of the fresh slot.
    root = make_attr_root(desc, e)
    e._bind_attrs(root)
    root.assign(data["attrs"])
    if isinstance(e, Space):
        e.kind = data.get("kind", SPACE_KIND_NIL)
    _entities[e.id] = e
    if isinstance(e, Space):
        _spaces[e.id] = e
    else:
        e.space = get_nil_space()  # default membership, as in _new_entity
    gwutils.run_panicless(e.on_init)
    if isinstance(e, Space):
        e._maybe_restore_aoi()
        gwutils.run_panicless(e.on_space_init)
    gwutils.run_panicless(e.on_attrs_ready)
    if is_migrate:
        dispatchercluster.select_by_entity_id(e.id).send_notify_create_entity(e.id)
    interval = _save_interval_override if _save_interval_override is not None else runtime.save_interval
    e._start_save_timer(interval)
    e._syncing_from_client = data.get("syncing", False)
    e._restore_timers(data.get("timers", []))
    client = data.get("client")
    if client is not None:
        # Reattach quietly: the client already has the entity mirror.
        gc = GameClient(client["clientid"], client["gateid"], e.id,
                        gate_gen=client.get("gen", 0))
        e.client = gc
        on_client_attached(gc.clientid, e)
    pos = data.get("pos") or [0.0, 0.0, 0.0]
    e.position = Vector3(*pos)
    e.yaw = data.get("yaw", 0.0)
    # Re-arm a sync flag that was pending at pack time (see
    # get_migrate_data): the next collect delivers the position the old
    # game never got to send.
    flag = data.get("sync_flag", 0)
    if flag:
        e._sync_info_flag = flag
    spaceid = data.get("space_id")
    if spaceid:
        space = _spaces.get(spaceid)
        if space is None:
            # Bounce-home rollback: the payload names the TARGET space,
            # which only exists on the (dead) target game — fall back to
            # the space the entity was packed out of, so a rolled-back
            # migration puts it exactly where it was.
            space = _spaces.get(data.get("prev_space_id") or "")
        if space is not None:
            space._enter(e, e.position)
    if is_migrate:
        gwutils.run_panicless(e.on_migrate_in)
    else:
        gwutils.run_panicless(e.on_restored)
    return e


# --- whole-space migration (ISSUE 18; no reference analog) -------------------


def pack_space(space: Space) -> tuple[dict, list]:
    """Pack a FROZEN space and every member into one transferable bundle
    and destroy the local copies (migrate semantics: no on_destroy hooks,
    no NOTIFY_DESTROY — the receiver's restore re-announces everything).

    Returns ``(bundle, queued_joins)``: the bundle is the one
    SPACE_MIGRATE_DATA payload; ``queued_joins`` are the (entity, pos)
    pairs that tried to enter while frozen — the caller re-dispatches each
    via ``enter_space`` AFTER sending the bundle, so the re-routed join
    rides the same dispatcher FIFO behind the data and finds the updated
    space route. Membership is frozen, so every packed member is in the
    PREPARE-time member list whose streams the dispatchers parked — no
    member can slip into the snapshot unparked."""
    if not space.frozen:
        raise ValueError(f"pack_space: space {space.id} is not frozen")
    members: dict[str, dict] = {}
    # Deterministic order (by id): restore replays in sorted order too,
    # so donor-side pack and receiver-side restore walk the same sequence.
    # on_migrate_out hooks run BEFORE the primed window — they may mutate
    # column attrs, which the batch gather must see.
    ordered = sorted(space.entities, key=lambda e: e.id)
    for e in ordered:
        gwutils.run_panicless(e.on_migrate_out)
    with primed_column_snapshot(ordered):
        for e in ordered:
            members[e.id] = e.get_migrate_data()
    sdata = space.get_migrate_data()
    sdata["kind"] = space.kind
    bundle = {"space": sdata, "members": members}
    queued = list(space._pending_enters)
    space._pending_enters = []
    # The migrate-destroy's release-time column snapshot (_snapshot_columns)
    # walks every declared column per entity — ride one primed gather too.
    with primed_column_snapshot(ordered):
        for e in ordered:
            e._destroy(is_migrate=True)
    space._destroy(is_migrate=True)
    # Migrate-destroy skips on_destroy (user hooks must not fire for a
    # move), which is also where a space normally drops its AOI manager
    # and its _spaces index entry — do both explicitly.
    if space.aoi_mgr is not None:
        space.aoi_mgr.destroy()
        space.aoi_mgr = None
    _spaces.pop(space.id, None)
    return bundle, queued


def restore_space_bundle(spaceid: str, bundle: dict) -> Space:
    """Receiver side of SPACE_MIGRATE_DATA (and the donor's bounce-home
    rollback): restore the space FIRST — its NOTIFY_CREATE re-routes the
    space id — then every member (whose ``space_id`` now resolves locally;
    each member's NOTIFY_CREATE re-routes its eid and flushes the packets
    its dispatcher parked at PREPARE)."""
    sdata = bundle["space"]
    space = restore_entity(spaceid, sdata, is_migrate=True)
    if not isinstance(space, Space):
        raise ValueError(
            f"restore_space_bundle: {spaceid} restored as "
            f"{type(space).__name__}, expected a Space")
    for eid in sorted(bundle.get("members", {})):
        restore_entity(eid, bundle["members"][eid], is_migrate=True)
    return space


# --- columnar batch persistence (ISSUE 19) -----------------------------------


def _gather_column(spec, arr, n_slots, slots):
    """O(entities) core of the columnar snapshot gather (gwlint R2 hot
    path — loop-free by design; the per-entity cache stitch stays in
    ``primed_column_snapshot``, outside the guarded set, because it is
    plain dict stores): one fancy-index + bulk ``tolist`` per (type,
    column). ``ndarray.tolist()`` performs the identical numpy→Python
    widening as ``ColumnSpec.to_python`` for every allowed column dtype,
    so the gathered values are bit-identical to the per-entity slab-read
    walk they replace."""
    if arr is None:  # column never materialized: default everywhere
        return [spec.to_python(spec.default)] * n_slots
    return arr[slots].tolist()


@contextmanager
def primed_column_snapshot(entities):
    """Columnar batch persistence: pre-gather every declared Column attr
    for *entities* with ONE fancy-index gather per (entity type, column)
    and prime each entity's attr root, so the per-entity snapshot walk
    inside the ``with`` block (``get_freeze_data`` / ``get_migrate_data``
    / ``persistent_attrs``) reads the pre-gathered plain-Python cache
    instead of one slab-row read + scalar conversion per entity per key.

    Exactness: ``ndarray.tolist()`` performs the identical numpy→Python
    widening as ``ColumnSpec.to_python`` for every allowed column dtype,
    so the produced blobs are bit-identical to the unprimed walk
    (asserted by tests/test_columns.py and the chaos freeze→restore
    scenario). Entities without Column attrs, or whose slot is already
    released (reads fall back to the release-time ``_final`` snapshot),
    pass through untouched; a host write inside the window invalidates
    that key's primed value (columns.py ``_col_set``), so overridden
    snapshot hooks that mutate state stay correct.

    The whole window — gather plus the caller's walk — lands on
    ``aoi_host_phase_seconds_total{phase=persist}``."""
    t0 = time.perf_counter()
    by_type: dict[int, list] = {}
    for e in entities:
        root = getattr(e, "attrs", None)
        if isinstance(root, ColumnBackedMapAttr) and e._slot >= 0:
            by_type.setdefault(id(root._colspecs), []).append(e)
    primed: list[ColumnBackedMapAttr] = []
    for ents in by_type.values():
        colspecs = ents[0].attrs._colspecs
        columns = ents[0].attrs._slabs.columns
        slots = np.fromiter((e._slot for e in ents), np.int64, len(ents))
        caches: list[dict] = [{} for _ in ents]
        for name, spec in colspecs.items():
            vals = _gather_column(spec, columns.get(name), len(ents), slots)
            for cache, v in zip(caches, vals):
                cache[name] = v
        for e, cache in zip(ents, caches):
            e.attrs.prime_columns(cache)
            primed.append(e.attrs)
    try:
        yield
    finally:
        for root in primed:
            root.unprime_columns()
        _PHASE_PERSIST.inc(time.perf_counter() - t0)


def save_entities_batch(entities=None) -> int:
    """Save every persistent entity (default: all live entities) through
    one primed-column snapshot round — the bulk analog of ``Entity.save``
    for terminate/checkpoint sweeps. Returns the number saved."""
    if entities is None:
        entities = list(_entities.values())
    saved = 0
    with primed_column_snapshot(entities):
        for e in entities:
            if e.is_persistent() and not e.is_destroyed():
                gwutils.run_panicless(e.save)
                saved += 1
    return saved


# --- freeze / restore (EntityManager.go:554-656) -----------------------------


def freeze_entities(gameid: int) -> dict:
    """Pack every entity for process freeze. Requires exactly one nil space
    (EntityManager.go:578-584)."""
    nil_id = get_nil_space_id(gameid)
    if nil_id not in _spaces:
        raise RuntimeError("freeze requires the nil space to exist")
    frozen_spaces: dict[str, dict] = {}
    frozen_entities: dict[str, dict] = {}
    # on_freeze hooks run OUTSIDE the primed window: they may mutate column
    # attrs, and the batch gather must see those writes.
    for e in _entities.values():
        gwutils.run_panicless(e.on_freeze)
    with primed_column_snapshot(_entities.values()):
        for e in _entities.values():
            data = e.get_freeze_data()
            if isinstance(e, Space):
                data["kind"] = e.kind
                frozen_spaces[e.id] = data
            else:
                frozen_entities[e.id] = data
    return {
        "gameid": gameid,
        "nil_space_id": nil_id,
        "spaces": frozen_spaces,
        "entities": frozen_entities,
    }


def restore_freezed_entities(data: dict) -> None:
    """3-pass restore: nil space → other spaces → entities
    (EntityManager.go:630-643)."""
    nil_id = data["nil_space_id"]
    spaces = data["spaces"]
    if nil_id in spaces:
        restore_entity(nil_id, spaces[nil_id], is_migrate=False)
    for sid, sdata in spaces.items():
        if sid != nil_id:
            restore_entity(sid, sdata, is_migrate=False)
    for eid, edata in data["entities"].items():
        restore_entity(eid, edata, is_migrate=False)


# --- test / process reset ----------------------------------------------------


def cleanup_for_tests() -> None:
    """Reset all module state (tests and process teardown)."""
    global _space_class, _save_interval_override, runtime
    _entities.clear()
    _spaces.clear()
    _registry.clear()
    _client_owners.clear()
    _space_class = None
    _save_interval_override = None
    runtime = Runtime()
    post_mod.clear()


def reset_world() -> None:
    """Drop every entity, space, client binding, timer and slab slot but
    KEEP the type registry — models a game-process crash inside one test
    process (the chaos harness kills and recreates a GameService without
    forking): the "new process" starts from an empty world but the same
    registered entity classes."""
    global runtime
    _entities.clear()
    _spaces.clear()
    _client_owners.clear()
    runtime = Runtime()
    post_mod.clear()

"""3-vector math for entity positions.

Reference parity: ``engine/entity/Vector3.go:8-77`` (float32 semantics on the
wire; Python floats internally).
"""

from __future__ import annotations

import dataclasses
import math


@dataclasses.dataclass
class Vector3:
    x: float = 0.0
    y: float = 0.0
    z: float = 0.0

    def __add__(self, o: "Vector3") -> "Vector3":
        return Vector3(self.x + o.x, self.y + o.y, self.z + o.z)

    def __sub__(self, o: "Vector3") -> "Vector3":
        return Vector3(self.x - o.x, self.y - o.y, self.z - o.z)

    def __mul__(self, s: float) -> "Vector3":
        return Vector3(self.x * s, self.y * s, self.z * s)

    def length(self) -> float:
        return math.sqrt(self.x * self.x + self.y * self.y + self.z * self.z)

    def distance_to(self, o: "Vector3") -> float:
        return (self - o).length()

    def normalized(self) -> "Vector3":
        l = self.length()
        if l == 0:
            return Vector3()
        return Vector3(self.x / l, self.y / l, self.z / l)

    def dir_to_yaw(self) -> float:
        """Yaw (degrees) of the XZ-plane direction (Vector3.go DirToYaw)."""
        return math.degrees(math.atan2(self.x, self.z))

    def as_tuple(self) -> tuple[float, float, float]:
        return (self.x, self.y, self.z)


def yaw_to_dir(yaw: float) -> Vector3:  # gwlint: keep — Vector3 API parity (DirToYaw inverse)
    r = math.radians(yaw)
    return Vector3(math.sin(r), 0.0, math.cos(r))

"""Entity runtime: Entity/Space lifecycle, nested attrs with client sync,
RPC dispatch, AOI interest management, timers, migration and freeze/restore.

Reference parity: ``engine/entity`` (SURVEY.md §2.1, §2.6).
"""

from goworld_tpu.entity.attrs import MapAttr, ListAttr
from goworld_tpu.entity.columns import ColumnSpec, columnar_tick
from goworld_tpu.entity.entity import Entity
from goworld_tpu.entity.slabs import (
    EntitySlabs,
    SlabTickView,
    vmapped_position_tick,
)
from goworld_tpu.entity.space import Space
from goworld_tpu.entity.vector import Vector3
from goworld_tpu.entity.entity_manager import (
    register_entity,
    register_space,
    create_entity_locally,
    create_entity_somewhere,
    load_entity_locally,
    load_entity_somewhere,
    get_entity,
    get_entities_by_type,
    call_entity,
    call_nil_spaces,
    get_nil_space_id,
    get_nil_space,
    set_save_interval,
    entities,
    cleanup_for_tests,
    collect_entity_sync_infos,
    freeze_entities,
    restore_freezed_entities,
    on_game_ready,
    get_space,
    create_space_locally,
    create_space_somewhere,
    create_nil_space,
)

__all__ = [
    "MapAttr",
    "ListAttr",
    "ColumnSpec",
    "columnar_tick",
    "Entity",
    "EntitySlabs",
    "SlabTickView",
    "vmapped_position_tick",
    "Space",
    "Vector3",
    "register_entity",
    "register_space",
    "create_entity_locally",
    "create_entity_somewhere",
    "load_entity_locally",
    "load_entity_somewhere",
    "get_entity",
    "get_entities_by_type",
    "call_entity",
    "call_nil_spaces",
    "get_nil_space_id",
    "get_nil_space",
    "set_save_interval",
    "entities",
    "cleanup_for_tests",
    "collect_entity_sync_infos",
    "freeze_entities",
    "restore_freezed_entities",
    "on_game_ready",
    "get_space",
    "create_space_locally",
    "create_space_somewhere",
    "create_nil_space",
]

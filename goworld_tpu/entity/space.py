"""Spaces: containers of entities with optional AOI.

Reference parity: ``engine/entity/Space.go`` — Space is itself an entity
(Space.go:26-34); enter/leave/move with AOI bookkeeping (Space.go:188-261);
``EnableAOI`` picks the manager (Space.go:105-125); one **nil space** per game
with a deterministic id for cross-game placement and CallNilSpaces broadcast
(space_ops.go:32-46); the persisted ``_EnableAOI`` attr re-enables AOI after
freeze/restore (Space.go:117-125).
"""

from __future__ import annotations

from goworld_tpu.entity.entity import Entity
from goworld_tpu.entity.vector import Vector3
from goworld_tpu.utils import gwlog, gwutils

_ENABLE_AOI_KEY = "_EnableAOI"
SPACE_KIND_NIL = 0


class Space(Entity):
    """Base class for spaces; user spaces subclass this (MySpace etc.)."""

    def __init__(self) -> None:
        super().__init__()
        self.entities: set[Entity] = set()
        self.kind = SPACE_KIND_NIL
        self.aoi_mgr = None
        # Whole-space migration (ISSUE 18): while frozen, membership is
        # immutable — the freeze-time member list IS the handoff snapshot,
        # so a join landing mid-handoff queues instead of entering (the
        # modelcheck ``no_frozen_join_guard`` mutant shows the alternative:
        # the joiner is absent from the snapshot and destroyed by the pack).
        self.frozen = False
        self._pending_enters: list[tuple[Entity, Vector3]] = []

    # --- lifecycle ---------------------------------------------------------

    def on_space_init(self) -> None:
        pass

    def on_space_created(self) -> None:
        pass

    def on_space_destroy(self) -> None:
        pass

    def on_entity_enter_space(self, entity: Entity) -> None:
        pass

    def on_entity_leave_space(self, entity: Entity) -> None:
        pass

    def on_game_ready(self) -> None:
        """Nil space's on_game_ready is the user code entry point
        (Space.go:324-326)."""

    def on_destroy(self) -> None:
        # Evict remaining entities, then drop the AOI manager.
        for e in list(self.entities):
            self._leave(e)
        if self.aoi_mgr is not None:
            self.aoi_mgr.destroy()
            self.aoi_mgr = None
        gwutils.run_panicless(self.on_space_destroy)
        from goworld_tpu.entity import entity_manager

        entity_manager.on_space_destroyed(self)

    # --- nil space ---------------------------------------------------------

    def is_nil(self) -> bool:
        return self.kind == SPACE_KIND_NIL

    # --- AOI ---------------------------------------------------------------

    def enable_aoi(self, distance: float) -> None:
        """Turn on AOI for this space (Space.go:105-125). Backend comes from
        [aoi] config: xzlist (CPU, synchronous) or batched TPU engine."""
        if self.aoi_mgr is not None:
            gwlog.errorf("%s: AOI already enabled", self)
            return
        if len(self.entities) > 0:
            # Mirror of the reference's constraint (Space.go:118: panics if
            # entities exist): enabling late would miss existing members.
            raise RuntimeError("enable_aoi must be called before entities enter")
        self.attrs.set(_ENABLE_AOI_KEY, float(distance))
        self._create_aoi_manager(distance)

    def _create_aoi_manager(self, distance: float) -> None:
        from goworld_tpu.entity import entity_manager

        self.aoi_mgr = entity_manager.runtime.new_aoi_manager(distance)

    def _maybe_restore_aoi(self) -> None:
        """Re-enable AOI from the persisted attr after load/restore."""
        dist = self.attrs.get(_ENABLE_AOI_KEY)
        if dist and self.aoi_mgr is None:
            self._create_aoi_manager(float(dist))

    # --- whole-space migration freeze (ISSUE 18) ---------------------------

    def freeze_space(self) -> None:
        """Pin membership for a whole-space handoff: no entity may enter
        or re-enter until :meth:`unfreeze_space` (abort) or the pack
        destroys the space (commit). Joins queue in ``_pending_enters``."""
        self.frozen = True

    def unfreeze_space(self) -> None:
        """Abort path: unfreeze in place and replay every queued join —
        the space was never in zero places, so the joiners simply enter
        late (bounded by the handoff deadline)."""
        self.frozen = False
        pending, self._pending_enters = self._pending_enters, []
        for entity, pos in pending:
            if not entity.is_destroyed():
                self._enter(entity, pos)

    # --- membership (Space.go:188-261) -------------------------------------

    def _enter(self, entity: Entity, pos: Vector3) -> None:
        if self.is_nil():
            # Entering the nil space is membership by pointer only: no
            # hooks, no AOI, no entities set (Space.go:197-199).
            entity.space = self
            entity.position = pos
            return
        if self.frozen:
            # Mid-handoff join: queue it. unfreeze_space replays (abort);
            # the pack re-dispatches each joiner's enter_space AFTER the
            # SPACE_MIGRATE_DATA on the same dispatcher FIFO (commit), so
            # the re-routed join finds the updated space route.
            self._pending_enters.append((entity, pos))
            return
        entity.space = self
        entity.position = pos
        self.entities.add(entity)
        if self.aoi_mgr is not None and entity.type_desc.use_aoi:
            self.aoi_mgr.enter(entity, pos.x, pos.z)
        gwutils.run_panicless(entity.on_enter_space)
        gwutils.run_panicless(lambda: self.on_entity_enter_space(entity))

    def _leave(self, entity: Entity) -> None:
        if entity.space is not self:
            return
        if self.is_nil():
            return  # leaving the nil space does nothing (Space.go:233-236)
        if self.aoi_mgr is not None and entity.type_desc.use_aoi:
            self.aoi_mgr.leave(entity)
        self.entities.discard(entity)
        # Back to the default membership (Space.go:240 entity.Space = nilSpace).
        from goworld_tpu.entity import entity_manager

        entity.space = entity_manager.get_nil_space()
        gwutils.run_panicless(lambda: entity.on_leave_space(self))
        gwutils.run_panicless(lambda: self.on_entity_leave_space(entity))

    def _move(self, entity: Entity, pos: Vector3) -> None:
        if self.aoi_mgr is not None and entity.type_desc.use_aoi:
            self.aoi_mgr.moved(entity, pos.x, pos.z)

    # --- helpers -----------------------------------------------------------

    def create_entity(self, typename: str, pos: Vector3 | None = None, attrs: dict | None = None):
        """Create an entity directly into this space."""
        from goworld_tpu.entity import entity_manager

        return entity_manager.create_entity_locally(
            typename, attrs=attrs, space=self, pos=pos or Vector3()
        )

    def get_entity_count(self) -> int:
        return len(self.entities)

    def count_entities(self, typename: str) -> int:
        """Number of entities of one type in this space (Space.go CountEntities)."""
        return sum(1 for e in self.entities if e.typename == typename)

    def __repr__(self) -> str:
        return f"Space<{self.typename}|{self.id}|kind={self.kind}>"

"""Sharded singleton "service entities" with kvreg-based discovery.

Reference parity: ``engine/service/service.go:65-362`` —

- ``register_service(cls, shard_count)`` registers the entity type and the
  desired shard count (service.go:65-76).
- A reconcile pass (service.go:106-238) runs on deployment-ready, then
  periodically and on every kvreg update: it reads the ``Service/`` keyspace,
  rebuilds the name→[shard eids] map, destroys local service entities that
  lost their registration race, creates entities for shards this game won,
  and registers (with random delay, so games race fairly) any shard nobody
  owns yet. Keys: ``Service/<Name>#<shard>`` → ``game<N>`` claims ownership;
  ``Service/<Name>#<shard>/EntityID`` → the created entity id (force-written).
- Call routing (service.go:258-328): any (random shard), all, by shard index,
  by hashed shard key (``hash_string(key) % shard_count``).
"""

from __future__ import annotations

import random
from typing import Optional, Type

from goworld_tpu import kvreg
from goworld_tpu.common import hash_string
from goworld_tpu.entity import entity_manager
from goworld_tpu.entity.entity import Entity
from goworld_tpu.utils import gwlog

SERVICE_KVREG_PREFIX = "Service/"
SHARD_SEP = "#"  # must not be "/" (service.go:28)
MAX_SHARD_COUNT = 8192
CHECK_INTERVAL = 60.0  # seconds (service.go:23)
CHECK_DELAY_MAX = 0.5  # random delay before a reconcile pass (service.go:26)

_registered: dict[str, int] = {}  # service name → shard count
_service_map: dict[str, list[str]] = {}  # service name → [eid or ""] per shard
_gameid: int = 0
_check_handle = None
_started = False

# Calls issued before the target shard finished registering (cold start,
# post-restore window). The reference drops these with an error log
# (service.go:262-266), which silently breaks anything fired from an early
# OnCreated (e.g. pubsub subscribes — the subscription is then missing for
# the entity's whole life). We queue and replay them on the next reconcile
# instead; undeliverable calls are dropped loudly after a TTL.
PENDING_CALL_TTL = 30.0
PENDING_RETRY_INTERVAL = 0.5
MAX_PENDING_CALLS = 10000
_pending_calls: list = []  # (deadline, label, attempt() -> bool)
_flush_handle = None


def _defer(label: str, attempt) -> None:
    if len(_pending_calls) >= MAX_PENDING_CALLS:
        gwlog.errorf("service: pending-call queue full, dropping %s", label)
        return
    _pending_calls.append(
        (entity_manager.now() + PENDING_CALL_TTL, label, attempt)
    )
    # Reconcile passes flush the queue too, but they stop firing once
    # registration settles (next periodic is up to CHECK_INTERVAL away) —
    # a call deferred after the last kvreg update needs its own retry tick.
    _schedule_flush()


def _schedule_flush() -> None:
    global _flush_handle
    if _flush_handle is not None:
        return

    def fire() -> None:
        global _flush_handle
        _flush_handle = None
        _flush_pending()
        if _pending_calls:
            _schedule_flush()

    _flush_handle = entity_manager.runtime.timer_service.add_callback(
        PENDING_RETRY_INTERVAL, fire
    )


def _flush_pending() -> None:
    global _pending_calls
    if not _pending_calls:
        return
    now = entity_manager.now()
    remaining = []
    for deadline, label, attempt in _pending_calls:
        try:
            if attempt():
                continue
        except Exception:
            gwlog.trace_error("service: pending call %s raised", label)
            continue
        if now >= deadline:
            gwlog.errorf(
                "service: %s undeliverable for %gs, dropped",
                label, PENDING_CALL_TTL,
            )
        else:
            remaining.append((deadline, label, attempt))
    _pending_calls = remaining


def _service_id(name: str, shard: int) -> str:
    return f"{name}{SHARD_SEP}{shard}"


def _split_service_id(sid: str) -> tuple[str, int]:
    name, _, idx = sid.partition(SHARD_SEP)
    return name, int(idx)


def _reg_key(sid: str) -> str:
    return SERVICE_KVREG_PREFIX + sid


def register_service(entity_class: Type[Entity], shard_count: int = 1,
                     typename: Optional[str] = None) -> None:
    """Register a service entity type (service.go:65-76)."""
    if not 1 <= shard_count <= MAX_SHARD_COUNT:
        raise ValueError(f"invalid shard count {shard_count}")
    name = typename or entity_class.__name__
    if SHARD_SEP in name:
        raise ValueError(f"service name must not contain {SHARD_SEP!r}")
    entity_manager.register_entity(entity_class, name)
    _registered[name] = shard_count


def setup(gameid: int) -> None:
    """Wire the reconcile trigger into kvreg updates (service.go:78-81)."""
    global _gameid
    _gameid = gameid
    kvreg.watch(lambda key, val: check_services_later()
                if key.startswith(SERVICE_KVREG_PREFIX) else None)


def on_deployment_ready() -> None:
    """Start periodic reconcile (service.go:83-86)."""
    global _started
    if _started or not _registered:
        return
    _started = True
    entity_manager.runtime.timer_service.add_timer(CHECK_INTERVAL, check_services_later)
    check_services_later()


def check_services_later() -> None:
    """Schedule one reconcile pass after a small random delay, coalescing
    bursts of kvreg updates (service.go:92-102)."""
    global _check_handle
    if _check_handle is not None:
        _check_handle.cancel()

    def fire():
        global _check_handle
        _check_handle = None
        check_services()

    _check_handle = entity_manager.runtime.timer_service.add_callback(
        random.random() * CHECK_DELAY_MAX, fire
    )


def check_services() -> None:
    """One reconcile pass (service.go:106-238)."""
    global _service_map
    if not _registered:
        return
    registered_on_disp: dict[str, dict] = {}  # sid → {"owner": gameid, "eid": str}
    local_sids: set[str] = set()

    for key, val in kvreg.get_all().items():
        if not key.startswith(SERVICE_KVREG_PREFIX):
            continue
        path = key[len(SERVICE_KVREG_PREFIX):].split("/")
        if len(path) == 1:
            sid = path[0]
            info = registered_on_disp.setdefault(sid, {"owner": 0, "eid": ""})
            try:
                info["owner"] = int(val[4:])  # "game<N>"
            except ValueError:
                gwlog.errorf("service: bad owner value %s = %s", key, val)
                continue
            if info["owner"] == _gameid:
                local_sids.add(sid)
        elif len(path) == 2 and path[1] == "EntityID":
            registered_on_disp.setdefault(path[0], {"owner": 0, "eid": ""})["eid"] = val
        else:
            gwlog.errorf("service: unknown kvreg key %s", key)

    # Rebuild the global service map from fully-registered shards.
    new_map: dict[str, list[str]] = {}
    for sid, info in registered_on_disp.items():
        if not info["owner"] or not info["eid"]:
            continue
        name, shard = _split_service_id(sid)
        count = _registered.get(name, 0)
        if shard >= count:
            gwlog.errorf("service: shard index out of range: %s", sid)
            continue
        new_map.setdefault(name, [""] * count)[shard] = info["eid"]
    _service_map = new_map

    # Local service entities that lost the registration race → destroy.
    local_reg_eids = {
        registered_on_disp[sid]["eid"] for sid in local_sids if registered_on_disp[sid]["eid"]
    }
    for name in _registered:
        for e in entity_manager.get_entities_by_type(name):
            if e.id not in local_reg_eids:
                gwlog.warnf("service: destroying unregistered local %s %s", name, e.id)
                e.destroy()

    # Shards this game owns but has not created/announced yet.
    for sid in local_sids:
        eid = registered_on_disp[sid]["eid"]
        if not eid or entity_manager.get_entity(eid) is None:
            _create_service_entity(sid)

    # Shards nobody owns: race to claim them after a random delay.
    for name, count in _registered.items():
        for shard in range(count):
            sid = _service_id(name, shard)
            if registered_on_disp.get(sid, {}).get("owner"):
                continue
            gwlog.infof("service: %s unclaimed, registering", sid)
            entity_manager.runtime.timer_service.add_callback(
                random.random(),
                lambda sid=sid: kvreg.register(_reg_key(sid), f"game{_gameid}", False),
            )

    # Newly-registered shards may unblock queued early calls.
    _flush_pending()


def _create_service_entity(sid: str) -> None:
    name, _shard = _split_service_id(sid)
    e = entity_manager.create_entity_locally(name)
    kvreg.register(_reg_key(sid) + "/EntityID", e.id, True)
    gwlog.infof("service: created service entity %s: %s", sid, e)


# --- call routing (service.go:258-328) ---------------------------------------


def _eids(name: str) -> list[str]:
    return _service_map.get(name, [])


def _try_any(name: str, method: str, args: tuple) -> bool:
    eids = [e for e in _eids(name) if e]
    if not eids:
        return False
    entity_manager.call_entity(random.choice(eids), method, *args)
    return True


def call_service_any(name: str, method: str, *args) -> None:
    if not _try_any(name, method, args):
        _defer(f"any {name}.{method}",
               lambda: _try_any(name, method, args))


def _try_all(name: str, method: str, args: tuple) -> bool:
    # All shards must be live: a partial broadcast would silently skip the
    # still-registering shards, so wait for full readiness instead.
    if not check_service_entities_ready(name):
        return False
    for eid in _eids(name):
        entity_manager.call_entity(eid, method, *args)
    return True


def call_service_all(name: str, method: str, *args) -> None:
    if not _try_all(name, method, args):
        _defer(f"all {name}.{method}",
               lambda: _try_all(name, method, args))


def _try_shard(name: str, shard: int, method: str, args: tuple) -> bool:
    eids = _eids(name)
    if not 0 <= shard < len(eids):
        count = _registered.get(name, 0)
        if not 0 <= shard < count:  # permanently out of range: drop loudly
            gwlog.errorf(
                "call_service_shard %s.%s: bad shard %d", name, method, shard
            )
            return True
        return False
    if not eids[shard]:
        return False
    entity_manager.call_entity(eids[shard], method, *args)
    return True


def call_service_shard_index(name: str, shard: int, method: str, *args) -> None:
    if not _try_shard(name, shard, method, args):
        _defer(f"shard {name}#{shard}.{method}",
               lambda: _try_shard(name, shard, method, args))


def call_service_shard_key(name: str, key: str, method: str, *args) -> None:
    count = _registered.get(name, 0) or len(_eids(name))
    if not count:
        # Service name unknown on this game (not registered here): the shard
        # count is undiscoverable, so defer until the map reveals it.
        def attempt() -> bool:
            eids = _eids(name)
            if not eids:
                return False
            return _try_shard(name, shard_by_key(key, len(eids)), method, args)

        _defer(f"key {name}.{method}", attempt)
        return
    call_service_shard_index(name, shard_by_key(key, count), method, *args)


def shard_by_key(key: str, shard_count: int) -> int:
    return hash_string(key) % shard_count


def get_service_entity_id(name: str, shard: int = 0) -> str:
    eids = _eids(name)
    return eids[shard] if 0 <= shard < len(eids) else ""


def get_service_shard_count(name: str) -> int:
    return _registered.get(name, 0)


def check_service_entities_ready(name: str) -> bool:
    """All shards registered with live entity ids (service.go:340-362)."""
    count = _registered.get(name, 0)
    eids = _eids(name)
    return count > 0 and len(eids) == count and all(eids)


def clear_for_tests() -> None:
    global _service_map, _gameid, _check_handle, _started, _flush_handle
    _registered.clear()
    _service_map = {}
    _pending_calls.clear()
    if _flush_handle is not None:
        _flush_handle.cancel()
    _flush_handle = None
    _gameid = 0
    if _check_handle is not None:
        _check_handle.cancel()
    _check_handle = None
    _started = False

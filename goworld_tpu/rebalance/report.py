"""Per-game load-report schema + scalar load score.

Built on the game (one bson dict per ``[rebalance] report_interval``, sent
to EVERY dispatcher beside the legacy cpu-only GAME_LBC_INFO), consumed on
the dispatcher by both the LBC choose-game heap and the rebalance planner.

Schema (all keys always present; see ``build_load_report``):

- ``cpu``: process CPU percent over the last report interval.
- ``entities``: live entity count (spaces + nil space included — the
  planner compares games against each other, so the constant offset of
  per-game spaces cancels).
- ``tick_p95_ms``: p95 busy tick over the flight-recorder ring (the
  tick-phase histogram's tail, as one number).
- ``queue_depth``: packets waiting in the game logic queue at report time
  (the sync-queue dwell proxy: depth × tick time = dwell).
- ``spaces``: ``[[spaceid, kind, population], ...]`` for every non-nil
  space — the planner's donor/receiver-space view (CheetahGIS-style
  density partitioning needs per-region populations, not just totals).
"""

from __future__ import annotations

import time


def build_load_report(game_service) -> dict:
    """Build this game's load report (runs on the game logic loop — cheap:
    one pass over the spaces dict + a sorted copy of the flight ring)."""
    from goworld_tpu.entity import entity_manager as em

    spaces = []
    for sid, space in em._spaces.items():
        if space.is_nil():
            continue
        spaces.append([sid, int(space.kind), int(space.get_entity_count())])
    flight = game_service.flight
    totals = sorted(t["total_ms"] for t in flight.ticks())
    p95 = totals[int(0.95 * (len(totals) - 1))] if totals else 0.0
    return {
        "cpu": round(game_service.last_cpu_pct, 2),
        "entities": len(em.entities()),
        "tick_p95_ms": round(p95, 3),
        "queue_depth": game_service.queue_depth(),
        "spaces": spaces,
    }


def coerce_report(report: object) -> dict:
    """Validate a wire-received load report (dispatcher seam).  Raises
    ValueError — never TypeError — on any malformed shape, so a corrupt
    or hostile GAME_LOAD_REPORT keeps the raise-ValueError parser
    contract (gwlint R3 / the schema fuzz in tests/test_modelcheck.py).
    Returns the report with the numeric keys coerced to float/int."""
    if not isinstance(report, dict):
        raise ValueError(
            f"load report is {type(report).__name__}, expected dict")
    out = dict(report)
    try:
        for key in ("cpu", "tick_p95_ms"):
            out[key] = float(report.get(key, 0.0))
        for key in ("entities", "queue_depth"):
            out[key] = int(report.get(key, 0))
    except (TypeError, ValueError) as exc:
        raise ValueError(f"malformed load report field: {exc}") from exc
    spaces = out.get("spaces", [])
    if not isinstance(spaces, list):
        raise ValueError("load report 'spaces' is not a list")
    rows = []
    for row in spaces:
        # a malformed row would otherwise TypeError inside the planner's
        # unpack (`for sid, kind, count in ...`) — in the dispatcher TICK
        # loop, where an escape kills the task, not just one packet
        if not isinstance(row, (list, tuple)) or len(row) != 3:
            raise ValueError(f"load report space row malformed: {row!r}")
        sid, kind, count = row
        try:
            rows.append([str(sid), int(kind), int(count)])
        except (TypeError, ValueError) as exc:
            raise ValueError(
                f"load report space row malformed: {exc}") from exc
    out["spaces"] = rows
    return out


def load_score(report: dict) -> float:
    """Scalar load score. Entity count is the backbone (it is exact and
    moves exactly when the rebalancer acts); cpu, tick-p95 and queue depth
    weight in so two games with equal populations but unequal compute
    still rank (a game wedged on a slow tick reads hotter than its entity
    count alone says)."""
    return (
        float(report.get("entities", 0))
        + 0.5 * float(report.get("cpu", 0.0))
        + 0.05 * float(report.get("tick_p95_ms", 0.0))
        + 0.1 * float(report.get("queue_depth", 0))
    )


class ReportTable:
    """Dispatcher-side store of the latest report per game, with
    staleness bookkeeping (monotonic receive times)."""

    def __init__(self) -> None:
        self._reports: dict[int, tuple[dict, float]] = {}

    def update(self, gameid: int, report: dict,
               now: float | None = None) -> None:
        self._reports[gameid] = (
            report, time.monotonic() if now is None else now)

    def remove(self, gameid: int) -> None:
        self._reports.pop(gameid, None)

    def get(self, gameid: int) -> dict | None:
        entry = self._reports.get(gameid)
        return entry[0] if entry is not None else None

    def age(self, gameid: int, now: float | None = None) -> float:
        entry = self._reports.get(gameid)
        if entry is None:
            return float("inf")
        return (time.monotonic() if now is None else now) - entry[1]

    def games(self) -> list[int]:
        return sorted(self._reports)

    def entities(self, gameid: int) -> int:
        r = self.get(gameid)
        return int(r["entities"]) if r is not None else 0

"""Game-side execution of rebalance moves: hardened cross-game migration.

The dispatcher's REBALANCE_MIGRATE names (from_space, to_space, to_game,
count); this module picks the entities and drives each through the
existing ``enter_space`` cross-game machinery (QUERY_SPACE_GAMEID →
MIGRATE_REQUEST → REAL_MIGRATE), adding the guarantees the organic path
leaves to its 60 s dispatcher window:

- **per-migration deadline**: a migration not done by ``migrate_timeout``
  is cancelled (CANCEL_MIGRATE releases the dispatcher's RPC block) and
  counted ``timeout`` — the entity stays live on this game;
- **bounce-back detection**: if the dispatcher returned the entity home
  because the target game died mid-REAL_MIGRATE, the reappearance inside
  the confirmation window converts the outcome to ``rolled_back`` instead
  of a false ``done``;
- **cooldown with backoff**: a moved (or rolled-back) entity is exempt
  from re-selection for ``cooldown`` seconds, doubling per consecutive
  rollback — a flapping target game cannot make one entity ping-pong.

States per tracked entity id::

    pending     enter_space issued; watching for completion or deadline
    confirming  entity gone locally (REAL_MIGRATE sent); waiting out the
                bounce window before counting ``done``

Whole-SPACE migration (ISSUE 18) extends the same guarantees from entities
to spaces as a crash-safe two-phase handoff, proved model-first in
analysis/modelcheck.py (space_handoff / space_member_race):

- **PREPARE**: freeze the space (joins queue; members' pending entity
  migrates are cancelled LOCALLY — no CANCEL_MIGRATE, the stream must stay
  parked), then broadcast SPACE_MIGRATE_PREPARE carrying the freeze-time
  member list to every dispatcher; each parks exactly the LISTED members
  it routes to this game and acks on its own FIFO (the freeze-ack fence —
  every packet it forwarded pre-park has already arrived here).
- **COMMIT** ≡ all acks in: pack the space + members into ONE
  SPACE_MIGRATE_DATA (destroying the local copies) and send it via the
  space-owner dispatcher, which routes it exactly like REAL_MIGRATE —
  buffer behind a grace window, bounce HOME on a dead target. The
  receiver's restore re-announces every id (NOTIFY_CREATE re-routes and
  unparks). Queued joins re-dispatch behind the data on the same FIFO.
- **ABORT** ≡ the per-space deadline fires while preparing, or a
  dispatcher reports the target dead: unfreeze in place (queued joins
  replay) and broadcast SPACE_MIGRATE_ABORT so every dispatcher unparks.
  A bounced-home data payload restores in place (``rolled_back``).

A space is never in zero places: its last copy is always live on the
donor, live on the receiver, or the in-flight payload a dispatcher is
obligated to deliver or bounce (modelcheck invariant I1 for spaces).

States per tracked space id::

    preparing   PREPARE broadcast; counting acks, watching the deadline
    sent        SPACE_MIGRATE_DATA left; waiting out the bounce window
"""

from __future__ import annotations

import dataclasses

from goworld_tpu import consts
from goworld_tpu.utils import gwlog

# Seconds an entity must stay gone before a departure counts as done: long
# enough for a dispatcher bounce (dead target) to restore it, short enough
# that the counter is live. Bounces ride the same link the REAL_MIGRATE
# left on, so they arrive within an RTT of the dispatcher noticing.
CONFIRM_GRACE = 2.0

# The SPACE grace must additionally outlast the dispatcher's reconnect
# buffer: a SPACE_MIGRATE_DATA whose target is mid-restart parks behind
# the 5 s reconnect window and bounces home only when the game is declared
# DEAD — up to DISPATCHER_RECONNECT_BUFFER_WINDOW later. If the donor has
# already counted the handoff done by then, the bounce looks like a fresh
# receive and the rollback is misclassified (found live by the
# kill-receiver-mid-PREPARE chaos cross).
SPACE_CONFIRM_GRACE = (
    consts.DISPATCHER_RECONNECT_BUFFER_WINDOW + CONFIRM_GRACE)


@dataclasses.dataclass
class _Pending:
    deadline: float
    to_space: str
    nonce_spaceid: str  # the spaceid the enter targets (validity key)


@dataclasses.dataclass
class _PendingSpace:
    deadline: float
    to_game: int
    member_eids: list  # freeze-time membership (the PREPARE park list)
    need_acks: int     # number of dispatchers that must ack
    acks: set          # dispatcher ids acked so far
    state: str         # "preparing" | "sent"


class RebalanceMigrator:
    def __init__(self, migrate_timeout: float = 5.0,
                 cooldown: float = 5.0) -> None:
        self.migrate_timeout = migrate_timeout
        self.cooldown = cooldown
        self._pending: dict[str, _Pending] = {}
        self._confirming: dict[str, float] = {}
        # eid → (exempt-until, consecutive rollbacks)
        self._cooldowns: dict[str, tuple[float, int]] = {}
        self.done = 0
        self.rolled_back = 0
        self.timeouts = 0
        # --- whole-space handoffs (ISSUE 18) ---
        self._pending_spaces: dict[str, _PendingSpace] = {}
        # spaceid → (exempt-until, consecutive failures)
        self._space_cooldowns: dict[str, tuple[float, int]] = {}
        self.spaces_done = 0
        self.spaces_aborted = 0
        self.spaces_rolled_back = 0
        self.spaces_timeout = 0

    # --- selection -----------------------------------------------------------

    def eligible(self, space, now: float) -> list:
        """Movable entities of ``space``: live, client-facing or not, not
        already migrating, not on cooldown. Deterministic order (by id) so
        repeated commands act on a stable prefix."""
        if getattr(space, "frozen", False):
            # Mid-handoff: the freeze-time member list is already the
            # PREPARE park list; donating an entity now would mutate the
            # snapshot (modelcheck no_freeze_cancel_member duplicates it).
            return []
        out = []
        for e in space.entities:
            if e.is_destroyed() or e.is_space_entity():
                continue
            if e.id in self._pending or e.id in self._confirming:
                continue
            cd = self._cooldowns.get(e.id)
            if cd is not None and now < cd[0]:
                continue
            out.append(e)
        out.sort(key=lambda e: e.id)
        return out

    # --- execution -----------------------------------------------------------

    def migrate(self, entity, to_space: str, now: float) -> None:
        """Issue one hardened migration. Reuses the entity's current
        position — a rebalance move is an ops action, not a teleport."""
        self._pending[entity.id] = _Pending(
            now + self.migrate_timeout, to_space, to_space)
        entity.enter_space(to_space, entity.position)

    def handle_command(self, space, to_space: str, count: int,
                       now: float) -> int:
        """REBALANCE_MIGRATE entry: migrate up to ``count`` eligible
        entities of ``space`` into ``to_space``. Returns how many were
        issued."""
        moved = 0
        for e in self.eligible(space, now):
            if moved >= count:
                break
            self.migrate(e, to_space, now)
            moved += 1
        return moved

    # --- whole-space handoff (ISSUE 18) --------------------------------------

    def handle_space_command(self, space, to_game: int, now: float) -> bool:
        """REBALANCE_MIGRATE_SPACE entry: start the two-phase handoff of
        ``space`` to ``to_game``. Returns False when the command is
        refused (already in flight, on cooldown, nil, or self-targeted) —
        a stale command degrades to doing nothing, never to guessing."""
        from goworld_tpu import dispatchercluster
        from goworld_tpu.entity import entity_manager as em

        if (space.is_nil() or space.frozen
                or space.id in self._pending_spaces
                or to_game == em.runtime.gameid):
            return False
        cd = self._space_cooldowns.get(space.id)
        if cd is not None and now < cd[0]:
            return False
        space.freeze_space()
        # Cancel members' pending entity migrates LOCALLY (drop the
        # request; late acks fail the nonce check). Deliberately NOT
        # cancel_enter_space(): CANCEL_MIGRATE would flush the member's
        # dispatcher stream mid-handoff, and the stream must stay parked
        # until the member's NOTIFY_CREATE lands on the receiver (the
        # modelcheck no_freeze_cancel_member mutant duplicates the member
        # without this cancel; space_member_race pins the parking rule).
        member_eids = []
        for e in sorted(space.entities, key=lambda e: e.id):
            if e._enter_space_request is not None:
                gwlog.infof(
                    "rebalance: space %s freezing; locally cancelling "
                    "%s's pending enter", space.id, e.id)
                e._enter_space_request = None
            self._pending.pop(e.id, None)
            member_eids.append(e.id)
        senders = list(dispatchercluster.select_all())
        self._pending_spaces[space.id] = _PendingSpace(
            deadline=now + self.migrate_timeout, to_game=to_game,
            member_eids=member_eids, need_acks=len(senders), acks=set(),
            state="preparing")
        self._spaces_gauge()
        for sender in senders:
            sender.send_space_migrate_prepare(space.id, to_game, member_eids)
        gwlog.infof(
            "rebalance: space %s (%d members) PREPARE broadcast to %d "
            "dispatchers, target game %d", space.id, len(member_eids),
            len(senders), to_game)
        return True

    def on_space_prepare_ack(self, spaceid: str, dispatcherid: int,
                             now: float) -> None:
        """A dispatcher parked the listed members it owns and acked on its
        own FIFO — when every dispatcher has, all pre-park packets have
        been processed here (the freeze-ack fence) and the pack is safe."""
        p = self._pending_spaces.get(spaceid)
        if p is None or p.state != "preparing":
            return  # late ack of an aborted/completed handoff: stale
        p.acks.add(dispatcherid)
        if len(p.acks) >= p.need_acks:
            self._pack_and_send(spaceid, p, now)

    def _pack_and_send(self, spaceid: str, p: _PendingSpace,
                       now: float) -> None:
        from goworld_tpu import dispatchercluster
        from goworld_tpu.entity import entity_manager as em

        space = em.get_space(spaceid)
        if space is None or space.is_destroyed():
            # The space died between freeze and the last ack (game logic
            # destroyed it): nothing to move — unpark and forget.
            del self._pending_spaces[spaceid]
            self._spaces_gauge()
            self._abort_broadcast(spaceid, "space_destroyed")
            self._space_fail(spaceid, "aborted", now)
            return
        bundle, queued = em.pack_space(space)
        dispatchercluster.select_by_entity_id(spaceid).send_space_migrate_data(
            spaceid, p.to_game, bundle, source_game=em.runtime.gameid)
        p.state = "sent"
        p.deadline = now + SPACE_CONFIRM_GRACE
        # Queued joiners re-dispatch AFTER the data: their
        # QUERY_SPACE_GAMEID rides the same space-owner-dispatcher FIFO
        # behind SPACE_MIGRATE_DATA, so the answer names the receiver.
        for entity, pos in queued:
            if not entity.is_destroyed():
                entity.enter_space(spaceid, pos)
        gwlog.infof(
            "rebalance: space %s packed (%d members, %d queued joins "
            "re-dispatched); SPACE_MIGRATE_DATA sent toward game %d",
            spaceid, len(bundle["members"]), len(queued), p.to_game)

    def on_space_abort(self, spaceid: str, reason: str, now: float) -> None:
        """A dispatcher refused the PREPARE (target game dead) — unfreeze
        in place and tell every OTHER dispatcher to unpark (they may have
        parked already; the refusing one did not)."""
        p = self._pending_spaces.get(spaceid)
        if p is None or p.state != "preparing":
            return  # duplicate/late abort: the handoff already resolved
        del self._pending_spaces[spaceid]
        self._spaces_gauge()
        self._unfreeze_local(spaceid)
        self._abort_broadcast(spaceid, reason)
        self._space_fail(spaceid, "aborted", now)
        gwlog.warnf("rebalance: space %s handoff aborted (%s); unfrozen "
                    "in place", spaceid, reason)

    def on_space_data(self, spaceid: str, bundle: dict, source_game: int,
                      now: float) -> None:
        """Inbound SPACE_MIGRATE_DATA. Two meanings, exactly like
        on_arrived: a normal receive (restore the space + members live —
        every NOTIFY_CREATE re-routes and unparks), or the BOUNCE of our
        own handoff (the dispatcher returned it because the target died)
        — then the space restores where it was and the move rolls back."""
        from goworld_tpu import dispatchercluster
        from goworld_tpu.entity import entity_manager as em

        p = self._pending_spaces.pop(spaceid, None)
        self._spaces_gauge()
        em.restore_space_bundle(spaceid, bundle)
        if p is not None:
            # Release the parked streams NOW rather than letting each
            # dispatcher's deadline sweep do it: the members are live here
            # again and their routes never changed (idempotent with the
            # sweep — release is a pop).
            self._abort_broadcast(spaceid, "bounced_home")
            self._space_fail(spaceid, "rolled_back", now)
            gwlog.warnf(
                "rebalance: space %s bounced home (target game down); "
                "restored in place with %d members", spaceid,
                len(bundle.get("members", {})))
            return
        # Receiver side: announce completion so every dispatcher clears
        # its handoff entry, and start the newcomer's cooldown so this
        # game doesn't instantly re-donate it.
        self._space_cooldowns[spaceid] = (now + self.cooldown, 0)
        for sender in dispatchercluster.select_all():
            sender.send_space_migrate_ack(spaceid, em.runtime.gameid)
        gwlog.infof("rebalance: space %s restored here with %d members",
                    spaceid, len(bundle.get("members", {})))

    def _unfreeze_local(self, spaceid: str) -> None:
        from goworld_tpu.entity import entity_manager as em

        space = em.get_space(spaceid)
        if space is not None and space.frozen:
            space.unfreeze_space()

    @staticmethod
    def _abort_broadcast(spaceid: str, reason: str) -> None:
        from goworld_tpu import dispatchercluster

        for sender in dispatchercluster.select_all():
            sender.send_space_migrate_abort(spaceid, reason)

    def _space_fail(self, spaceid: str, outcome: str, now: float) -> None:
        self._space_count(outcome)
        if outcome == "timeout":
            self.spaces_timeout += 1
        elif outcome == "aborted":
            self.spaces_aborted += 1
        else:
            self.spaces_rolled_back += 1
        prev = self._space_cooldowns.get(spaceid)
        fails = (prev[1] if prev else 0) + 1
        self._space_cooldowns[spaceid] = (
            now + self.cooldown * (2 ** min(fails - 1, 6)), fails)

    @staticmethod
    def _space_count(outcome: str) -> None:
        from goworld_tpu import rebalance

        rebalance.SPACE_MIGRATIONS.labels(outcome).inc()

    def _spaces_gauge(self) -> None:
        from goworld_tpu import rebalance

        rebalance.SPACES_IN_FLIGHT.set(len(self._pending_spaces))

    # --- lifecycle notifications --------------------------------------------

    def on_arrived(self, eid: str, now: float) -> None:
        """An entity landed here via REAL_MIGRATE. Two meanings: a normal
        arrival (receiver side — start its cooldown so this game doesn't
        instantly re-donate the newcomer), or a BOUNCE of our own pending
        departure (the dispatcher sent it home because the target game
        died) — then the migration rolls back."""
        if eid in self._confirming or eid in self._pending:
            self._pending.pop(eid, None)
            self._confirming.pop(eid, None)
            self._fail(eid, "rolled_back", now)
            gwlog.warnf("rebalance: %s bounced home (target game down); "
                        "rolled back", eid)
            return
        self._cooldowns[eid] = (now + self.cooldown, 0)

    # --- the state machine ---------------------------------------------------

    def tick(self, now: float) -> None:
        """Advance every tracked migration (called from the game loop's
        entity_logic phase; O(tracked), zero when idle)."""
        if self._pending_spaces:
            self._tick_spaces(now)
        if not self._pending and not self._confirming:
            return
        from goworld_tpu.entity import entity_manager as em

        for eid, p in list(self._pending.items()):
            e = em.get_entity(eid)
            if e is None or e.is_destroyed():
                # REAL_MIGRATE left; hold the outcome until the bounce
                # window passes.
                del self._pending[eid]
                self._confirming[eid] = now + CONFIRM_GRACE
                continue
            req = e._enter_space_request
            if req is None or req[0] != p.nonce_spaceid:
                # Cancelled (dispatcher timeout path) or superseded by an
                # organic enter_space — either way OUR migration is over
                # and the entity stayed.
                del self._pending[eid]
                self._fail(eid, "rolled_back", now)
                continue
            if now >= p.deadline:
                del self._pending[eid]
                e.cancel_enter_space()
                self._fail(eid, "timeout", now)
                gwlog.warnf(
                    "rebalance: migration of %s to %s timed out after "
                    "%.1fs; cancelled (entity stays)", eid, p.to_space,
                    self.migrate_timeout)
        for eid, deadline in list(self._confirming.items()):
            if em.get_entity(eid) is not None:
                # Reappeared outside on_arrived (e.g. restored locally):
                # treat as a rollback all the same.
                del self._confirming[eid]
                self._fail(eid, "rolled_back", now)
            elif now >= deadline:
                del self._confirming[eid]
                self._count("done")
                self.done += 1
                self._cooldowns.pop(eid, None)

    def _tick_spaces(self, now: float) -> None:
        """Space-handoff deadlines. ``preparing`` past the deadline →
        ABORT: unfreeze in place, broadcast the abort so every dispatcher
        unparks, cooldown (modelcheck terminal I3: a space may never stay
        FROZEN forever — the no_unfreeze_on_abort mutant is exactly this
        rule deleted). ``sent`` past the bounce window → done: the data
        was delivered (or is the dispatcher's obligation now)."""
        for spaceid, p in list(self._pending_spaces.items()):
            if now < p.deadline:
                continue
            del self._pending_spaces[spaceid]
            self._spaces_gauge()
            if p.state == "preparing":
                self._unfreeze_local(spaceid)
                self._abort_broadcast(spaceid, "deadline")
                self._space_fail(spaceid, "timeout", now)
                gwlog.warnf(
                    "rebalance: space %s handoff timed out after %.1fs "
                    "with %d/%d acks; unfrozen in place", spaceid,
                    self.migrate_timeout, len(p.acks), p.need_acks)
            else:
                self._space_count("done")
                self.spaces_done += 1
                self._space_cooldowns.pop(spaceid, None)

    def _fail(self, eid: str, outcome: str, now: float) -> None:
        self._count(outcome)
        if outcome == "timeout":
            self.timeouts += 1
        else:
            self.rolled_back += 1
        prev = self._cooldowns.get(eid)
        fails = (prev[1] if prev else 0) + 1
        # Backoff: each consecutive rollback doubles the exemption.
        self._cooldowns[eid] = (
            now + self.cooldown * (2 ** min(fails - 1, 6)), fails)

    @staticmethod
    def _count(outcome: str) -> None:
        from goworld_tpu import rebalance

        rebalance.MIGRATIONS.labels(outcome).inc()

    @property
    def in_flight(self) -> int:
        return len(self._pending) + len(self._confirming)

    @property
    def spaces_in_flight(self) -> int:
        return len(self._pending_spaces)

"""Game-side execution of rebalance moves: hardened cross-game migration.

The dispatcher's REBALANCE_MIGRATE names (from_space, to_space, to_game,
count); this module picks the entities and drives each through the
existing ``enter_space`` cross-game machinery (QUERY_SPACE_GAMEID →
MIGRATE_REQUEST → REAL_MIGRATE), adding the guarantees the organic path
leaves to its 60 s dispatcher window:

- **per-migration deadline**: a migration not done by ``migrate_timeout``
  is cancelled (CANCEL_MIGRATE releases the dispatcher's RPC block) and
  counted ``timeout`` — the entity stays live on this game;
- **bounce-back detection**: if the dispatcher returned the entity home
  because the target game died mid-REAL_MIGRATE, the reappearance inside
  the confirmation window converts the outcome to ``rolled_back`` instead
  of a false ``done``;
- **cooldown with backoff**: a moved (or rolled-back) entity is exempt
  from re-selection for ``cooldown`` seconds, doubling per consecutive
  rollback — a flapping target game cannot make one entity ping-pong.

States per tracked entity id::

    pending     enter_space issued; watching for completion or deadline
    confirming  entity gone locally (REAL_MIGRATE sent); waiting out the
                bounce window before counting ``done``
"""

from __future__ import annotations

import dataclasses

from goworld_tpu.utils import gwlog

# Seconds an entity must stay gone before a departure counts as done: long
# enough for a dispatcher bounce (dead target) to restore it, short enough
# that the counter is live. Bounces ride the same link the REAL_MIGRATE
# left on, so they arrive within an RTT of the dispatcher noticing.
CONFIRM_GRACE = 2.0


@dataclasses.dataclass
class _Pending:
    deadline: float
    to_space: str
    nonce_spaceid: str  # the spaceid the enter targets (validity key)


class RebalanceMigrator:
    def __init__(self, migrate_timeout: float = 5.0,
                 cooldown: float = 5.0) -> None:
        self.migrate_timeout = migrate_timeout
        self.cooldown = cooldown
        self._pending: dict[str, _Pending] = {}
        self._confirming: dict[str, float] = {}
        # eid → (exempt-until, consecutive rollbacks)
        self._cooldowns: dict[str, tuple[float, int]] = {}
        self.done = 0
        self.rolled_back = 0
        self.timeouts = 0

    # --- selection -----------------------------------------------------------

    def eligible(self, space, now: float) -> list:
        """Movable entities of ``space``: live, client-facing or not, not
        already migrating, not on cooldown. Deterministic order (by id) so
        repeated commands act on a stable prefix."""
        out = []
        for e in space.entities:
            if e.is_destroyed() or e.is_space_entity():
                continue
            if e.id in self._pending or e.id in self._confirming:
                continue
            cd = self._cooldowns.get(e.id)
            if cd is not None and now < cd[0]:
                continue
            out.append(e)
        out.sort(key=lambda e: e.id)
        return out

    # --- execution -----------------------------------------------------------

    def migrate(self, entity, to_space: str, now: float) -> None:
        """Issue one hardened migration. Reuses the entity's current
        position — a rebalance move is an ops action, not a teleport."""
        self._pending[entity.id] = _Pending(
            now + self.migrate_timeout, to_space, to_space)
        entity.enter_space(to_space, entity.position)

    def handle_command(self, space, to_space: str, count: int,
                       now: float) -> int:
        """REBALANCE_MIGRATE entry: migrate up to ``count`` eligible
        entities of ``space`` into ``to_space``. Returns how many were
        issued."""
        moved = 0
        for e in self.eligible(space, now):
            if moved >= count:
                break
            self.migrate(e, to_space, now)
            moved += 1
        return moved

    # --- lifecycle notifications --------------------------------------------

    def on_arrived(self, eid: str, now: float) -> None:
        """An entity landed here via REAL_MIGRATE. Two meanings: a normal
        arrival (receiver side — start its cooldown so this game doesn't
        instantly re-donate the newcomer), or a BOUNCE of our own pending
        departure (the dispatcher sent it home because the target game
        died) — then the migration rolls back."""
        if eid in self._confirming or eid in self._pending:
            self._pending.pop(eid, None)
            self._confirming.pop(eid, None)
            self._fail(eid, "rolled_back", now)
            gwlog.warnf("rebalance: %s bounced home (target game down); "
                        "rolled back", eid)
            return
        self._cooldowns[eid] = (now + self.cooldown, 0)

    # --- the state machine ---------------------------------------------------

    def tick(self, now: float) -> None:
        """Advance every tracked migration (called from the game loop's
        entity_logic phase; O(tracked), zero when idle)."""
        if not self._pending and not self._confirming:
            return
        from goworld_tpu.entity import entity_manager as em

        for eid, p in list(self._pending.items()):
            e = em.get_entity(eid)
            if e is None or e.is_destroyed():
                # REAL_MIGRATE left; hold the outcome until the bounce
                # window passes.
                del self._pending[eid]
                self._confirming[eid] = now + CONFIRM_GRACE
                continue
            req = e._enter_space_request
            if req is None or req[0] != p.nonce_spaceid:
                # Cancelled (dispatcher timeout path) or superseded by an
                # organic enter_space — either way OUR migration is over
                # and the entity stayed.
                del self._pending[eid]
                self._fail(eid, "rolled_back", now)
                continue
            if now >= p.deadline:
                del self._pending[eid]
                e.cancel_enter_space()
                self._fail(eid, "timeout", now)
                gwlog.warnf(
                    "rebalance: migration of %s to %s timed out after "
                    "%.1fs; cancelled (entity stays)", eid, p.to_space,
                    self.migrate_timeout)
        for eid, deadline in list(self._confirming.items()):
            if em.get_entity(eid) is not None:
                # Reappeared outside on_arrived (e.g. restored locally):
                # treat as a rollback all the same.
                del self._confirming[eid]
                self._fail(eid, "rolled_back", now)
            elif now >= deadline:
                del self._confirming[eid]
                self._count("done")
                self.done += 1
                self._cooldowns.pop(eid, None)

    def _fail(self, eid: str, outcome: str, now: float) -> None:
        self._count(outcome)
        if outcome == "timeout":
            self.timeouts += 1
        else:
            self.rolled_back += 1
        prev = self._cooldowns.get(eid)
        fails = (prev[1] if prev else 0) + 1
        # Backoff: each consecutive rollback doubles the exemption.
        self._cooldowns[eid] = (
            now + self.cooldown * (2 ** min(fails - 1, 6)), fails)

    @staticmethod
    def _count(outcome: str) -> None:
        from goworld_tpu import rebalance

        rebalance.MIGRATIONS.labels(outcome).inc()

    @property
    def in_flight(self) -> int:
        return len(self._pending) + len(self._confirming)

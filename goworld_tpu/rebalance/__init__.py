"""Telemetry-driven live rebalancer (ROADMAP item 1; no reference analog).

GoWorld's load balancing stops at PLACEMENT: the dispatcher's CPU min-heap
(dispatcher/lbc.py) picks the least-loaded game for NEW entities, and a hot
game stays hot until its population churns away. This package takes the
same telemetry the engine already produces — tick-phase p95, queue depth,
entity counts, per-space populations — and moves LIVE entities between
games through the hardened cross-game migration path:

- ``report``: the per-game load-report schema (built game-side, consumed
  dispatcher-side) and the scalar load score.
- ``planner``: dispatcher-side planning — pick donor/receiver games and
  donor/receiver spaces, with hysteresis and hard pause conditions (stale
  telemetry, a game link mid-restart) so the rebalancer degrades to DOING
  NOTHING rather than guessing.
- ``migrator``: game-side execution — drive each commanded entity through
  ``enter_space``'s cross-game machinery with a per-migration deadline,
  CANCEL_MIGRATE rollback, bounce-back detection (an entity the dispatcher
  returned home because the target game died), and per-entity cooldown
  with rollback backoff so a flapping target cannot thrash.

Zero-loss contract (pinned by tests/test_rebalance.py and the multigame
chaos scenarios): client RPCs and position-sync records addressed at a
migrating entity buffer at the dispatcher for the migrate window and flush
to wherever the entity LANDS; a REAL_MIGRATE whose target game is gone
bounces home instead of dropping; a migration that cannot complete rolls
back to the source game. An entity is never in zero places.

CheetahGIS (PAPERS.md) is the exemplar for streaming spatial workload
partitioning — its density-aware streaming partitioner maps here to the
planner's per-space population view; the manycore range-query work informs
the batched interest re-registration the AOI plane already performs after
a move (the restored entity re-enters the target space in one hop).
"""

from __future__ import annotations

from goworld_tpu import telemetry

# Families register at module scope only (gwlint R5); children resolve at
# use sites. Outcomes: done = REAL_MIGRATE left for the target game and
# the entity did not bounce home; rolled_back = the pending request was
# cancelled/superseded or the entity bounced home; timeout = the migrator
# hit its per-migration deadline and cancelled (a rollback whose CAUSE is
# the deadline — counted separately so a flapping peer is visible).
MIGRATIONS = telemetry.counter(
    "rebalance_migrations_total",
    "Rebalancer-driven cross-game migrations by outcome "
    "(done|rolled_back|timeout).",
    ("outcome",))
# Dispatcher-side view of each game's scalar load score (rebalance/report
# load_score over the game's last report); NaN-free — removed when the
# game is declared down.
LOAD_SCORE = telemetry.gauge(
    "game_load_score",
    "Scalar load score per game from its last load report "
    "(entities + weighted cpu/tick-p95/queue-depth).",
    ("gameid",))
# Planner activity: rounds that produced moves, and rounds paused by each
# guard condition (visibility into "why is it not rebalancing").
PLANS = telemetry.counter(
    "rebalance_plans_total",
    "Planner rounds by result (moved|balanced|paused_stale|paused_links|"
    "paused_few).",
    ("result",))
# Whole-space handoffs (ISSUE 18; donor-side outcomes): done = the
# SPACE_MIGRATE_DATA left and did not bounce back within the confirm
# window; aborted = a dispatcher refused the PREPARE (dead target) or the
# space died pre-pack; timeout = the per-space deadline fired mid-PREPARE
# (unfrozen in place); rolled_back = the data bounced home and the space
# restored where it was.
SPACE_MIGRATIONS = telemetry.counter(
    "rebalance_space_migrations_total",
    "Whole-space handoffs by outcome "
    "(done|aborted|timeout|rolled_back).",
    ("outcome",))
# Spaces currently mid-handoff on this game (preparing or in the bounce
# window) — the gwtop REBAL column's "in flight" figure.
SPACES_IN_FLIGHT = telemetry.gauge(
    "rebalance_spaces_in_flight",
    "Whole-space handoffs currently tracked by this game's migrator.")
# Which game hosts the sharded planner service shard (0 on games not
# hosting it; every game publishes its own view — the collector surfaces
# the nonzero one). Dispatcher-local planning leaves this 0 everywhere.
PLANNER_HOST = telemetry.gauge(
    "rebalance_planner_host",
    "1 when this game hosts the RebalancePlannerService shard, else 0.")

from goworld_tpu.rebalance.migrator import RebalanceMigrator  # noqa: E402
from goworld_tpu.rebalance.planner import (  # noqa: E402
    Move,
    RebalancePlanner,
    SpaceMove,
)
from goworld_tpu.rebalance.report import build_load_report, load_score  # noqa: E402

__all__ = [
    "MIGRATIONS",
    "LOAD_SCORE",
    "PLANS",
    "SPACE_MIGRATIONS",
    "SPACES_IN_FLIGHT",
    "PLANNER_HOST",
    "Move",
    "SpaceMove",
    "RebalancePlanner",
    "RebalanceMigrator",
    "build_load_report",
    "load_score",
]

"""Dispatcher-side rebalance planning.

One planner instance lives on the driver dispatcher ([rebalance]
driver_dispatcher). Each planning round it looks at the latest per-game
load reports and either:

- emits up to ``max_moves_per_round`` entity moves from the hottest game's
  fattest space into a SAME-KIND space on the coldest game (moving between
  unlike kinds would be a gameplay decision, not an ops decision), or
- pauses, loudly classified: telemetry stale, a game link mid-restart,
  fewer than two reporting games, or simply balanced.

Anti-thrash design (the "converges, never oscillates" contract):

- hysteresis: no move unless donor minus receiver entity count is at least
  ``min_entity_delta``, and only ``delta // 2`` entities move in total —
  the plan aims AT the midpoint, never past it;
- report fencing: after issuing moves the planner refuses to plan the
  same pair again until BOTH games' reports were received after the
  issue time — a plan may never act on counts that predate its own
  previous moves (the classic double-move oscillation);
- the migrator's per-entity cooldown (game-side) is the third layer: even
  a confused plan cannot bounce one entity back and forth inside the
  cooldown window.
"""

from __future__ import annotations

import dataclasses

from goworld_tpu.rebalance.report import ReportTable, load_score
from goworld_tpu.utils import gwlog


@dataclasses.dataclass
class Move:
    """One planned transfer: ``count`` entities out of ``from_space`` on
    ``from_game`` into ``to_space`` on ``to_game`` (the donor game picks
    WHICH entities — the planner only sees populations)."""

    from_game: int
    to_game: int
    from_space: str
    to_space: str
    count: int


class RebalancePlanner:
    def __init__(self, cfg) -> None:
        self.cfg = cfg  # RebalanceConfig
        self.reports = ReportTable()
        # (donor, receiver) → monotonic time moves were last issued; both
        # games must report AFTER this before the pair is planned again.
        self._fenced: dict[tuple[int, int], float] = {}
        self.last_result = "idle"  # /healthz visibility

    # --- input ---------------------------------------------------------------

    def on_report(self, gameid: int, report: dict,
                  now: float | None = None) -> None:
        self.reports.update(gameid, report, now)

    def on_game_down(self, gameid: int) -> None:
        self.reports.remove(gameid)

    # --- planning ------------------------------------------------------------

    def plan(self, connected: set[int], now: float) -> list[Move]:
        """One planning round. ``connected`` = games with a live dispatcher
        link RIGHT NOW; a reporting game without a link is mid-restart and
        pauses the planner entirely (moving entities toward or away from a
        game whose state is unknown is exactly the thrash this guard
        exists to prevent)."""
        from goworld_tpu import rebalance

        games = self.reports.games()
        fresh = [g for g in games if g in connected]
        if any(g not in connected for g in games):
            # A reporting game without a live link is mid-restart: its
            # state is unknown, so the whole planner pauses (classified
            # before the count check — this is the restart case, not the
            # small-cluster case).
            return self._pause("paused_links", rebalance.PLANS)
        if len(fresh) < 2:
            return self._pause("paused_few", rebalance.PLANS)
        if any(self.reports.age(g, now) > self.cfg.stale_after
               for g in fresh):
            return self._pause("paused_stale", rebalance.PLANS)

        scored = sorted(
            fresh, key=lambda g: load_score(self.reports.get(g)))
        donor, receiver = scored[-1], scored[0]
        delta = (self.reports.entities(donor)
                 - self.reports.entities(receiver))
        if delta < self.cfg.min_entity_delta:
            self.last_result = "balanced"
            rebalance.PLANS.labels("balanced").inc()
            return []
        fence = self._fenced.get((donor, receiver))
        if fence is not None and (
            self.reports.age(donor, now) > now - fence
            or self.reports.age(receiver, now) > now - fence
        ):
            # One (or both) reports predate our previous moves for this
            # pair: acting again would double-count the same imbalance.
            self.last_result = "fenced"
            rebalance.PLANS.labels("balanced").inc()
            return []

        budget = min(self.cfg.max_moves_per_round, delta // 2)
        moves = self._pick_spaces(donor, receiver, budget)
        if not moves:
            self.last_result = "balanced"
            rebalance.PLANS.labels("balanced").inc()
            return []
        self._fenced[(donor, receiver)] = now
        self.last_result = (
            f"moved {sum(m.count for m in moves)} "
            f"game{donor}->game{receiver}")
        rebalance.PLANS.labels("moved").inc()
        gwlog.infof(
            "rebalance: plan %s (delta %d, scores %.1f -> %.1f)",
            self.last_result, delta,
            load_score(self.reports.get(donor)),
            load_score(self.reports.get(receiver)))
        return moves

    def _pause(self, reason: str, plans) -> list[Move]:
        self.last_result = reason
        plans.labels(reason).inc()
        return []

    def _pick_spaces(self, donor: int, receiver: int,
                     budget: int) -> list[Move]:
        """Donor spaces largest-first; for each, the emptiest SAME-KIND
        receiver space. Splits the budget across donor spaces as needed
        (a donor whose population is spread over many spaces still
        drains)."""
        donor_spaces = sorted(
            (self.reports.get(donor) or {}).get("spaces", []),
            key=lambda s: -s[2])
        recv_spaces = (self.reports.get(receiver) or {}).get("spaces", [])
        by_kind: dict[int, list] = {}
        for sid, kind, count in recv_spaces:
            by_kind.setdefault(int(kind), []).append([sid, kind, count])
        moves: list[Move] = []
        for sid, kind, count in donor_spaces:
            if budget <= 0:
                break
            targets = by_kind.get(int(kind))
            if not targets or count <= 0:
                continue
            target = min(targets, key=lambda s: s[2])
            n = min(budget, int(count))
            moves.append(Move(donor, receiver, sid, target[0], n))
            budget -= n
            target[2] += n  # keep later picks spreading, not stacking
        return moves

"""Dispatcher-side rebalance planning.

One planner instance lives on the driver dispatcher ([rebalance]
driver_dispatcher) — or, with [rebalance] planner_service, inside the
sharded RebalancePlannerService entity so a dead planner host fails over
with the service plane (rebalance/planner_service.py). Each planning round
it looks at the latest per-game load reports and greedily bin-packs load
across ALL reporting games:

- donors are visited hottest-first (by load score); each donor drains
  toward the coldest receiver (by projected entity count, updated as the
  round plans — two donors aiming at one receiver see each other's moves);
- per donor/receiver pair, up to ``max_moves_per_round`` entities move
  from the donor's fattest spaces into SAME-KIND spaces on the receiver
  (moving between unlike kinds would be a gameplay decision, not an ops
  decision);
- when the receiver has NO same-kind space to absorb into, the pair may
  instead move a WHOLE SPACE (largest-first-fit among donor spaces whose
  population fits inside the pair's delta), bounded by
  ``max_space_moves_per_round`` (0 disables — the default) and executed
  by the crash-safe two-phase handoff in rebalance/migrator.py;
- or the round pauses, loudly classified: telemetry stale, a game link
  mid-restart, fewer than two reporting games, or simply balanced.

Anti-thrash design (the "converges, never oscillates" contract):

- hysteresis: no pair is planned unless donor minus receiver entity count
  is at least ``min_entity_delta``, and only ``delta // 2`` entities move
  per pair — the plan aims AT the midpoint, never past it (a whole-space
  move requires the space's population to fit inside the delta for the
  same reason);
- report fencing: after issuing moves the planner refuses to plan the
  same pair again until BOTH games' reports were received after the
  issue time — a plan may never act on counts that predate its own
  previous moves (the classic double-move oscillation);
- the migrator's per-entity/per-space cooldown (game-side) is the third
  layer: even a confused plan cannot bounce one entity or space back and
  forth inside the cooldown window.
"""

from __future__ import annotations

import dataclasses

from goworld_tpu.rebalance.report import ReportTable, load_score
from goworld_tpu.utils import gwlog


@dataclasses.dataclass
class Move:
    """One planned transfer: ``count`` entities out of ``from_space`` on
    ``from_game`` into ``to_space`` on ``to_game`` (the donor game picks
    WHICH entities — the planner only sees populations)."""

    from_game: int
    to_game: int
    from_space: str
    to_space: str
    count: int


@dataclasses.dataclass
class SpaceMove:
    """One planned whole-space handoff: ``spaceid`` (with every member)
    leaves ``from_game`` for ``to_game`` through the two-phase
    SPACE_MIGRATE protocol (rebalance/migrator.py). ``count`` is the
    population at planning time (projection bookkeeping only)."""

    from_game: int
    to_game: int
    spaceid: str
    count: int


def plan_to_wire(plans: list) -> dict:
    """Serialize a round's plans for the REBALANCE_PLAN push (the sharded
    planner service sends this to a dispatcher for validation/dispatch)."""
    return {
        "moves": [[m.from_game, m.to_game, m.from_space, m.to_space,
                   m.count] for m in plans if isinstance(m, Move)],
        "space_moves": [[m.from_game, m.to_game, m.spaceid, m.count]
                        for m in plans if isinstance(m, SpaceMove)],
    }


def plan_from_wire(payload: dict) -> list:
    """Inverse of :func:`plan_to_wire`; ValueError on malformed input
    (the wire-parser contract — a bad plan must not half-execute)."""
    if not isinstance(payload, dict):
        raise ValueError(f"plan payload is {type(payload).__name__}")
    out: list = []
    try:
        for row in payload.get("moves", []):
            fg, tg, fs, ts, n = row
            out.append(Move(int(fg), int(tg), str(fs), str(ts), int(n)))
        for row in payload.get("space_moves", []):
            fg, tg, sid, n = row
            out.append(SpaceMove(int(fg), int(tg), str(sid), int(n)))
    except (TypeError, ValueError) as exc:
        raise ValueError(f"malformed plan payload: {exc}") from exc
    return out


class RebalancePlanner:
    def __init__(self, cfg) -> None:
        self.cfg = cfg  # RebalanceConfig
        self.reports = ReportTable()
        # (donor, receiver) → monotonic time moves were last issued; both
        # games must report AFTER this before the pair is planned again.
        self._fenced: dict[tuple[int, int], float] = {}
        self.last_result = "idle"  # /healthz visibility

    # --- input ---------------------------------------------------------------

    def on_report(self, gameid: int, report: dict,
                  now: float | None = None) -> None:
        self.reports.update(gameid, report, now)

    def on_game_down(self, gameid: int) -> None:
        self.reports.remove(gameid)

    # --- planning ------------------------------------------------------------

    def plan(self, connected: set[int], now: float) -> list:
        """One planning round. ``connected`` = games with a live dispatcher
        link RIGHT NOW; a reporting game without a link is mid-restart and
        pauses the planner entirely (moving entities toward or away from a
        game whose state is unknown is exactly the thrash this guard
        exists to prevent). Returns a list of Move / SpaceMove."""
        from goworld_tpu import rebalance

        games = self.reports.games()
        fresh = [g for g in games if g in connected]
        if any(g not in connected for g in games):
            # A reporting game without a live link is mid-restart: its
            # state is unknown, so the whole planner pauses (classified
            # before the count check — this is the restart case, not the
            # small-cluster case).
            return self._pause("paused_links", rebalance.PLANS)
        if len(fresh) < 2:
            return self._pause("paused_few", rebalance.PLANS)
        if any(self.reports.age(g, now) > self.cfg.stale_after
               for g in fresh):
            return self._pause("paused_stale", rebalance.PLANS)

        # Working copies the round mutates as it plans: projected entity
        # counts per game, and per-game space rows ([sid, kind, count]) so
        # a moved space's kind becomes absorbable at its receiver within
        # the same round's later pairs.
        proj = {g: self.reports.entities(g) for g in fresh}
        spaces = {
            g: [list(s)
                for s in (self.reports.get(g) or {}).get("spaces", [])]
            for g in fresh
        }
        entity_budget = self.cfg.max_moves_per_round
        space_budget = self.cfg.max_space_moves_per_round
        donors = sorted(
            fresh, key=lambda g: -load_score(self.reports.get(g)))
        plans: list = []
        for donor in donors:
            if entity_budget <= 0 and space_budget <= 0:
                break
            receiver = min(
                (g for g in fresh if g != donor), key=lambda g: proj[g])
            delta = proj[donor] - proj[receiver]
            if delta < self.cfg.min_entity_delta:
                continue
            fence = self._fenced.get((donor, receiver))
            if fence is not None and (
                self.reports.age(donor, now) > now - fence
                or self.reports.age(receiver, now) > now - fence
            ):
                # One (or both) reports predate our previous moves for
                # this pair: acting again would double-count the same
                # imbalance.
                continue
            pair: list = self._pick_spaces(
                spaces[donor], spaces[receiver], donor, receiver,
                min(entity_budget, delta // 2))
            if not pair and space_budget > 0:
                # No same-kind receiver space absorbs entities: move a
                # whole space instead (the bin-packer's placement step).
                pair = self._pick_whole_spaces(
                    spaces[donor], spaces[receiver], donor, receiver,
                    delta, space_budget)
                space_budget -= len(pair)
            else:
                entity_budget -= sum(m.count for m in pair)
            if not pair:
                continue
            moved = sum(m.count for m in pair)
            proj[donor] -= moved
            proj[receiver] += moved
            self._fenced[(donor, receiver)] = now
            plans.extend(pair)

        if not plans:
            self.last_result = (
                "fenced" if self._fenced else "balanced")
            rebalance.PLANS.labels("balanced").inc()
            return []
        n_ent = sum(m.count for m in plans if isinstance(m, Move))
        n_sp = sum(1 for m in plans if isinstance(m, SpaceMove))
        self.last_result = f"moved {n_ent} entities + {n_sp} spaces"
        rebalance.PLANS.labels("moved").inc()
        gwlog.infof(
            "rebalance: plan %s across %d games (scores %s)",
            self.last_result, len(fresh),
            {g: round(load_score(self.reports.get(g)), 1) for g in fresh})
        return plans

    def _pause(self, reason: str, plans) -> list:
        self.last_result = reason
        plans.labels(reason).inc()
        return []

    @staticmethod
    def _pick_spaces(donor_spaces: list, recv_spaces: list, donor: int,
                     receiver: int, budget: int) -> list:
        """Donor spaces largest-first; for each, the emptiest SAME-KIND
        receiver space. Splits the budget across donor spaces as needed
        (a donor whose population is spread over many spaces still
        drains). Mutates the working rows so later pairs in the same
        round see this pair's moves."""
        by_kind: dict[int, list] = {}
        for row in recv_spaces:
            by_kind.setdefault(int(row[1]), []).append(row)
        moves: list = []
        for row in sorted(donor_spaces, key=lambda s: -s[2]):
            if budget <= 0:
                break
            sid, kind, count = row[0], row[1], row[2]
            targets = by_kind.get(int(kind))
            if not targets or count <= 0:
                continue
            target = min(targets, key=lambda s: s[2])
            n = min(budget, int(count))
            moves.append(Move(donor, receiver, sid, target[0], n))
            budget -= n
            row[2] -= n
            target[2] += n  # keep later picks spreading, not stacking
        return moves

    @staticmethod
    def _pick_whole_spaces(donor_spaces: list, recv_spaces: list,
                           donor: int, receiver: int, delta: int,
                           budget: int) -> list:
        """Largest-first-fit whole-space placement: move donor spaces
        (population descending) whose population fits inside HALF the
        pair's remaining delta — a move of ``c`` changes the imbalance
        from ``delta`` to ``delta - 2c``, so ``2c <= delta`` is exactly
        "never past the midpoint": the receiver never ends up hotter than
        the donor, every move strictly improves, no ping-pong (a space of
        4 with delta 4 would flip 8/4 into 4/8 forever). The moved row
        transfers to the receiver's working list, so its kind absorbs
        entity moves in later pairs of the same round."""
        moves: list = []
        for row in sorted(donor_spaces, key=lambda s: -s[2]):
            if budget <= 0 or delta < 1:
                break
            sid, count = row[0], int(row[2])
            if count < 1 or 2 * count > delta:
                continue
            moves.append(SpaceMove(donor, receiver, sid, count))
            donor_spaces.remove(row)
            recv_spaces.append(row)
            delta -= 2 * count
            budget -= 1
        return moves

"""The rebalance planner as a sharded service entity (ISSUE 18).

With ``[rebalance] planner_service`` on, planning moves off the driver
dispatcher into a single-shard :class:`RebalancePlannerService` hosted on
whichever game wins the ``Service/RebalancePlannerService#0`` kvreg race.
Crash-survivability falls out of the service plane's existing machinery:

- the host game dies → the dispatcher's game-down purge releases the
  shard's kvreg claim (empty-value deletions, replicated), every surviving
  game's reconcile sees it unclaimed and races to re-claim, and the new
  host's planner resumes from the next GAME_LOAD_REPORT round — the report
  table is soft state that refills within one ``report_interval``;
- games push their load reports here via ``call_service_shard_key`` (the
  same deferred-call path every service call rides), so reports queued
  during the failover window deliver to the NEW shard;
- the computed plan goes to a dispatcher as one REBALANCE_PLAN push; the
  dispatcher stays the authority on dispatch (config gate + per-game
  liveness), so a stale or split-brain service cannot move entities.

The planner logic itself (rebalance/planner.py) is identical in both
homes — bin-packing, hysteresis, fencing, pause guards — only the driving
loop differs: an entity timer here, the dispatcher tick loop there.
"""

from __future__ import annotations

import time

from goworld_tpu.entity.entity import Entity
from goworld_tpu.utils import gwlog

SERVICE_NAME = "RebalancePlannerService"
SHARD_COUNT = 1  # one planner; shard_by_key("planner", 1) == 0
REPORT_SHARD_KEY = "planner"


class RebalancePlannerService(Entity):
    """Single-shard planning service. State is deliberately soft: the
    report table rebuilds from live GAME_LOAD_REPORT pushes, and the
    pair fences it loses on failover only cost one conservative round."""

    @classmethod
    def describe_entity_type(cls, desc):
        pass  # no persisted attrs: every field rebuilds from live reports

    def on_init(self) -> None:
        from goworld_tpu import rebalance
        from goworld_tpu.config.read_config import RebalanceConfig
        from goworld_tpu.entity import entity_manager
        from goworld_tpu.rebalance.planner import RebalancePlanner

        gs = entity_manager.runtime.game_service
        self._rb_cfg = (gs.cfg.rebalance if gs is not None
                        else RebalanceConfig())
        self.planner = RebalancePlanner(self._rb_cfg)
        # on_init (not on_created) so a freeze→restore of the hosting game
        # re-raises the gauge: restore replays timers but never on_created.
        rebalance.PLANNER_HOST.set(1)

    def on_created(self) -> None:
        from goworld_tpu.entity import entity_manager

        self.add_timer(max(0.05, self._rb_cfg.interval), "PlanTick")
        gwlog.infof(
            "rebalance: planner service %s hosting on game %d "
            "(interval %.2fs)", self.id, entity_manager.runtime.gameid,
            self._rb_cfg.interval)

    def on_destroy(self) -> None:
        # Lost the registration race or host shutdown: stop claiming the
        # gauge so /cluster's planner-host view follows the live shard.
        from goworld_tpu import rebalance

        rebalance.PLANNER_HOST.set(0)

    # --- RPC: every game's _lbc_loop pushes here ---------------------------

    def ReportLoad(self, gameid, report) -> None:
        from goworld_tpu.rebalance.report import coerce_report

        self.planner.on_report(
            int(gameid), coerce_report(report), time.monotonic())

    # --- timer: one planning round per [rebalance] interval ----------------

    def PlanTick(self) -> None:
        from goworld_tpu import dispatchercluster
        from goworld_tpu.entity import entity_manager
        from goworld_tpu.rebalance.planner import plan_to_wire

        gs = entity_manager.runtime.game_service
        # Liveness view: the hosting game's NOTIFY_GAME_CONNECTED set plus
        # itself (the broadcast excludes the subject). Same contract as
        # the dispatcher's connected set: a reporting game missing from it
        # pauses the round (paused_links).
        connected = set(gs.online_games) | {gs.gameid} if gs else set()
        plans = self.planner.plan(connected, time.monotonic())
        if not plans:
            return
        dispatchercluster.select_by_entity_id(self.id).send_rebalance_plan(
            plan_to_wire(plans))
        gwlog.infof("rebalance: planner service pushed %d commands (%s)",
                    len(plans), self.planner.last_result)


def register() -> None:
    """Register the service type + shard (idempotent per process); called
    from the game boot path when [rebalance] planner_service is on."""
    from goworld_tpu import service as service_mod

    service_mod.register_service(RebalancePlannerService, SHARD_COUNT)

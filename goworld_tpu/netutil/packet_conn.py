"""Length-prefixed packet framing over an asyncio stream.

Reference parity: ``engine/netutil/PacketConnection.go:50-186`` — every wire
message is [u32 LE payload length][payload]; payloads are capped at 25 MiB
(PacketConnection.go:23). The reference queues sends and flushes on a 5 ms
timer to batch small writes (GoWorldConnection.go:437-452); asyncio's
transport write buffering plus an explicit ``flush_interval`` drain task
provides the same batching.

Optional per-packet compression (the reference wraps gate↔client conns in
snappy, ClientProxy.go:42-45; snappy isn't in this image, so zlib): when
enabled on both ends, payloads over a small threshold are deflated and the
length prefix's high bit marks them (the bit the reference reserves,
PAYLOAD_LEN_MASK).
"""

from __future__ import annotations

import asyncio
import struct
import zlib

from goworld_tpu import consts
from goworld_tpu.netutil.packet import Packet

_LEN = struct.Struct("<I")

_COMPRESSED_BIT = 0x80000000
_COMPRESS_THRESHOLD = 256  # don't deflate tiny packets (heartbeats, syncs)


class ConnectionClosed(Exception):
    pass


class PacketConnection:
    """Framed packet transport over an asyncio (reader, writer) pair."""

    def __init__(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        flush_interval: float = consts.FLUSH_INTERVAL,
    ) -> None:
        self._reader = reader
        self._writer = writer
        self._flush_interval = flush_interval
        self._pending: list[bytes] = []
        self._flush_task: asyncio.Task | None = None
        self._closed = False
        self._compress = False
        self.dropped = 0  # packets discarded because the conn was closed

    def enable_compression(self) -> None:
        """Turn on per-packet zlib for SENDS (recv always auto-detects via
        the length-prefix flag bit, so enabling is one-sided safe)."""
        self._compress = True

    @property
    def peername(self):
        try:
            return self._writer.get_extra_info("peername")
        except Exception:
            return None

    # --- send --------------------------------------------------------------

    def send_packet(self, msgtype: int, packet: Packet) -> None:
        """Queue one packet; wire format = [len][u16 msgtype][payload].

        Sends on a closed connection are counted and dropped (the reference
        likewise drops packets to dead peers; reconnect logic re-syncs state,
        DispatcherConnMgr.go:66-88)."""
        if self._closed:
            self.dropped += 1
            return
        payload = packet.payload
        total = 2 + len(payload)
        if total > consts.MAX_PACKET_SIZE:
            raise ValueError(f"packet too large: {total}")
        body = struct.pack("<H", msgtype) + payload
        flag = 0
        if self._compress and total >= _COMPRESS_THRESHOLD:
            deflated = zlib.compress(body, 1)
            if len(deflated) < len(body):
                body = deflated
                flag = _COMPRESSED_BIT
        buf = _LEN.pack(len(body) | flag) + body
        self._pending.append(buf)
        if self._flush_task is None or self._flush_task.done():
            self._flush_task = asyncio.get_running_loop().create_task(
                self._flush_later()
            )

    async def _flush_later(self) -> None:
        if self._flush_interval > 0:
            await asyncio.sleep(self._flush_interval)
        self.flush()

    def flush(self) -> None:
        if self._closed or not self._pending:
            return
        data = b"".join(self._pending)
        self._pending.clear()
        try:
            self._writer.write(data)
        except Exception:
            self._closed = True

    async def drain(self, hard: bool = False) -> None:
        """Flush queued packets into the transport and wait for it to drain.

        ``hard=True`` waits until the transport buffer is completely empty
        (write-buffer limits dropped to zero) — required before process exit
        (freeze/terminate), where normal drain() can return with bytes still
        in the user-space buffer that die with the process.
        """
        self.flush()
        try:
            if hard:
                self._writer.transport.set_write_buffer_limits(0, 0)
            await self._writer.drain()
        except Exception:
            self._closed = True
            raise ConnectionClosed("drain failed")

    # --- recv --------------------------------------------------------------

    async def recv_packet(self) -> tuple[int, Packet]:
        """Read one framed packet; returns (msgtype, packet)."""
        try:
            header = await self._reader.readexactly(4)
        except (asyncio.IncompleteReadError, ConnectionResetError, OSError):
            raise ConnectionClosed("connection closed while reading length")
        (raw_len,) = _LEN.unpack(header)
        compressed = bool(raw_len & _COMPRESSED_BIT)
        length = raw_len & consts.PAYLOAD_LEN_MASK
        if length < 2 or length > consts.MAX_PACKET_SIZE:
            raise ConnectionClosed(f"bad packet length {length}")
        try:
            body = await self._reader.readexactly(length)
        except (asyncio.IncompleteReadError, ConnectionResetError, OSError):
            raise ConnectionClosed("connection closed while reading body")
        if compressed:
            # Bounded inflate: client-controlled data must not be able to
            # balloon past the packet cap (decompression-bomb guard).
            try:
                d = zlib.decompressobj()
                body = d.decompress(body, consts.MAX_PACKET_SIZE)
                if d.unconsumed_tail or not d.eof:
                    raise ConnectionClosed("compressed packet exceeds size cap")
            except zlib.error as exc:
                raise ConnectionClosed(f"bad compressed packet: {exc}")
            if not 2 <= len(body) <= consts.MAX_PACKET_SIZE:
                raise ConnectionClosed(f"bad decompressed length {len(body)}")
        msgtype = struct.unpack_from("<H", body, 0)[0]
        return msgtype, Packet(body[2:])

    # --- close -------------------------------------------------------------

    def close(self) -> None:
        self._closed = True
        try:
            self._writer.close()
        except Exception:
            pass

    @property
    def closed(self) -> bool:
        return self._closed

"""Length-prefixed packet framing over an asyncio stream.

Reference parity: ``engine/netutil/PacketConnection.go:50-186`` — every wire
message is [u32 LE payload length][payload]; payloads are capped at 25 MiB
(PacketConnection.go:23). The reference queues sends and flushes on a 5 ms
timer to batch small writes (GoWorldConnection.go:437-452); asyncio's
transport write buffering plus an explicit ``flush_interval`` drain task
provides the same batching.
"""

from __future__ import annotations

import asyncio
import struct

from goworld_tpu import consts
from goworld_tpu.netutil.packet import Packet

_LEN = struct.Struct("<I")


class ConnectionClosed(Exception):
    pass


class PacketConnection:
    """Framed packet transport over an asyncio (reader, writer) pair."""

    def __init__(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        flush_interval: float = consts.FLUSH_INTERVAL,
    ) -> None:
        self._reader = reader
        self._writer = writer
        self._flush_interval = flush_interval
        self._pending: list[bytes] = []
        self._flush_task: asyncio.Task | None = None
        self._closed = False
        self.dropped = 0  # packets discarded because the conn was closed

    @property
    def peername(self):
        try:
            return self._writer.get_extra_info("peername")
        except Exception:
            return None

    # --- send --------------------------------------------------------------

    def send_packet(self, msgtype: int, packet: Packet) -> None:
        """Queue one packet; wire format = [len][u16 msgtype][payload].

        Sends on a closed connection are counted and dropped (the reference
        likewise drops packets to dead peers; reconnect logic re-syncs state,
        DispatcherConnMgr.go:66-88)."""
        if self._closed:
            self.dropped += 1
            return
        payload = packet.payload
        total = 2 + len(payload)
        if total > consts.MAX_PACKET_SIZE:
            raise ValueError(f"packet too large: {total}")
        buf = _LEN.pack(total) + struct.pack("<H", msgtype) + payload
        self._pending.append(buf)
        if self._flush_task is None or self._flush_task.done():
            self._flush_task = asyncio.get_running_loop().create_task(
                self._flush_later()
            )

    async def _flush_later(self) -> None:
        if self._flush_interval > 0:
            await asyncio.sleep(self._flush_interval)
        self.flush()

    def flush(self) -> None:
        if self._closed or not self._pending:
            return
        data = b"".join(self._pending)
        self._pending.clear()
        try:
            self._writer.write(data)
        except Exception:
            self._closed = True

    async def drain(self) -> None:
        self.flush()
        try:
            await self._writer.drain()
        except Exception:
            self._closed = True
            raise ConnectionClosed("drain failed")

    # --- recv --------------------------------------------------------------

    async def recv_packet(self) -> tuple[int, Packet]:
        """Read one framed packet; returns (msgtype, packet)."""
        try:
            header = await self._reader.readexactly(4)
        except (asyncio.IncompleteReadError, ConnectionResetError, OSError):
            raise ConnectionClosed("connection closed while reading length")
        (length,) = _LEN.unpack(header)
        if length < 2 or length > consts.MAX_PACKET_SIZE:
            raise ConnectionClosed(f"bad packet length {length}")
        try:
            body = await self._reader.readexactly(length)
        except (asyncio.IncompleteReadError, ConnectionResetError, OSError):
            raise ConnectionClosed("connection closed while reading body")
        msgtype = struct.unpack_from("<H", body, 0)[0]
        return msgtype, Packet(body[2:])

    # --- close -------------------------------------------------------------

    def close(self) -> None:
        self._closed = True
        try:
            self._writer.close()
        except Exception:
            pass

    @property
    def closed(self) -> bool:
        return self._closed

"""Length-prefixed packet framing over an asyncio stream.

Reference parity: ``engine/netutil/PacketConnection.go:50-186`` — every wire
message is [u32 LE payload length][payload]; payloads are capped at 25 MiB
(PacketConnection.go:23). The reference queues sends and flushes on a 5 ms
timer to batch small writes (GoWorldConnection.go:437-452); asyncio's
transport write buffering plus an explicit ``flush_interval`` drain task
provides the same batching.

Optional per-packet compression (the reference wraps gate↔client conns in
snappy, ClientProxy.go:42-45): payloads over a small threshold are
compressed with snappy (from-scratch codec in native/ — the library isn't
in the image; zlib remains selectable) and a length-prefix flag bit marks
the codec per packet (the bit role the reference reserves via
PAYLOAD_LEN_MASK), so recv auto-detects and enabling is one-sided safe.
"""

from __future__ import annotations

import asyncio
import collections
import struct

from goworld_tpu import consts, native
from goworld_tpu.netutil.packet import Packet

_COMPRESS_THRESHOLD = 256  # don't deflate tiny packets (heartbeats, syncs)
_RECV_CHUNK = 65536
# Frame header for the uncompressed scatter path: [u32 body_len][u16
# msgtype]. Must stay byte-identical to native.pack's framing (body_len
# counts the msgtype's 2 bytes; no compression flag bits set).
_FRAME_HDR = struct.Struct("<IH")

# Packets that rode an existing corked batch instead of paying their own
# transport write (gate tick-scoped coalescing; one series process-wide —
# connections churn too fast for per-conn labels, same reasoning as
# net_packets_total in proto/conn.py).
from goworld_tpu import telemetry as _telemetry

_COALESCED = _telemetry.counter(
    "net_coalesced_packets_total",
    "Packets flushed as part of a multi-packet corked batch (all but the "
    "first of each batch): writes saved by tick-scoped write coalescing.",
)
_WRITEV = _telemetry.counter(
    "net_writev_batches_total",
    "Multi-buffer flushes handed to the transport as a scatter list "
    "(writelines) instead of being joined into one copy first.",
)


def deframe(rbytes: bytearray, max_packet: int = 0):
    """One batched native.split over ``rbytes``, consuming the parsed
    prefix in place. Returns (frames, error): frames parsed BEFORE a
    malformed one are still returned, and error != None is connection-
    fatal for the caller. The single seam for the framing contract shared
    by the TCP, rudp, and kcp transports (code-review r5)."""
    frames, consumed, err = native.split(
        rbytes, max_packet or consts.MAX_PACKET_SIZE
    )
    if consumed:
        del rbytes[:consumed]
    return frames, err


class ConnectionClosed(Exception):
    pass


class PacketConnection:
    """Framed packet transport over an asyncio (reader, writer) pair."""

    def __init__(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        flush_interval: float = consts.FLUSH_INTERVAL,
    ) -> None:
        self._reader = reader
        self._writer = writer
        self._flush_interval = flush_interval
        # Scatter list of wire buffers awaiting flush. The uncompressed
        # send path appends TWO entries per packet — a 6-byte frame header
        # and the payload object itself (zero-copy) — so _pending_count
        # tracks packets separately from buffers.
        self._pending: list[bytes] = []
        self._pending_count = 0
        self._flush_task: asyncio.Task | None = None
        self._corked = False  # tick-scoped write coalescing (cork/uncork)
        self._closed = False
        self._compress = 0  # 0 off | 1 zlib | 2 snappy (native.pack modes)
        self.dropped = 0  # packets discarded because the conn was closed
        # Monotonic count of packets queued for send: the cluster-link
        # heartbeat layer compares it across intervals to detect idle
        # links (an int increment — no clock read on the send hot path).
        self.sent_packets = 0
        # Batched recv: raw bytes accumulate here and whole chunks are
        # deframed in one native.split call (C when available) — one await
        # + one parse per burst instead of two awaits per packet.
        # bytearray: `del [:consumed]` keeps multi-chunk reassembly of a
        # large packet linear (immutable += would be quadratic in copies
        # across the ~400 chunks of a near-cap 25 MB packet).
        self._rbytes = bytearray()
        self._rframes: collections.deque = collections.deque()
        self._recv_error: str | None = None

    def enable_compression(self, fmt: str = "snappy") -> None:
        """Turn on per-packet compression for SENDS (recv always
        auto-detects via the length-prefix flag bits, so enabling is
        one-sided safe). ``fmt``: "snappy" (reference parity,
        ClientProxy.go:42-45) or "zlib"."""
        if fmt not in ("snappy", "zlib"):
            raise ValueError(f"unknown compression format {fmt!r}")
        self._compress = 2 if fmt == "snappy" else 1

    @property
    def peername(self):
        try:
            return self._writer.get_extra_info("peername")
        except Exception:
            return None

    # --- send --------------------------------------------------------------

    def send_packet(self, msgtype: int, packet: Packet) -> None:
        """Queue one packet; wire format = [len][u16 msgtype][payload].

        Sends on a closed connection are counted and dropped (the reference
        likewise drops packets to dead peers; reconnect logic re-syncs state,
        DispatcherConnMgr.go:66-88)."""
        if self._closed:
            self.dropped += 1
            return
        payload = packet.payload
        body_len = len(payload) + 2
        if self._compress and body_len >= _COMPRESS_THRESHOLD:
            # Compression candidates take the codec path (one packed buf).
            self._pending.append(native.pack(
                msgtype, payload, self._compress,
                _COMPRESS_THRESHOLD, consts.MAX_PACKET_SIZE,
            ))
        else:
            # Scatter framing: header + payload as separate buffers — the
            # payload (already an immutable bytes on the forward path) is
            # never copied into a framed buffer; flush() hands the whole
            # scatter list to the transport.
            if body_len > consts.MAX_PACKET_SIZE:
                raise ValueError(f"packet too large: {body_len}")
            if not 0 <= msgtype <= 0xFFFF:
                raise ValueError(f"msgtype {msgtype} out of u16 range")
            self._pending.append(_FRAME_HDR.pack(body_len, msgtype))
            if payload:
                self._pending.append(payload)
        self._pending_count += 1
        self.sent_packets += 1
        if self._corked:
            return  # uncork() flushes the whole tick's scatter list at once
        if self._flush_task is None or self._flush_task.done():
            self._flush_task = asyncio.get_running_loop().create_task(
                self._flush_later()
            )

    def cork(self) -> None:
        """Suspend flushing: sends accumulate in the pending scatter list
        with no per-send flush-task bookkeeping until :meth:`uncork`. The
        gate's logic loop corks a connection for the span of one event
        batch (tick) so N per-client packets leave in ONE transport write.
        Idempotent; a connection left corked by an error path is still
        flushed by the next uncork() or close()."""
        self._corked = True

    def uncork(self) -> None:
        """Re-enable flushing and write the coalesced batch out now."""
        self._corked = False
        n = self._pending_count
        if n > 1:
            _COALESCED.inc(n - 1)
        self.flush()

    async def _flush_later(self) -> None:
        if self._flush_interval > 0:
            await asyncio.sleep(self._flush_interval)
        self.flush()

    def flush(self) -> None:
        if self._closed or not self._pending:
            return
        pending = self._pending
        self._pending = []
        self._pending_count = 0
        try:
            if len(pending) == 1:
                self._writer.write(pending[0])
            else:
                # Scatter-gather: the transport takes the buffer list as is
                # (writev-style; on interpreters whose transport implements
                # writelines via sendmsg this is zero-copy end to end, and
                # even the fallback join happens ONCE at the lowest layer
                # instead of once here and once there).
                _WRITEV.inc()
                self._writer.writelines(pending)
        except Exception:
            self._closed = True

    async def drain(self, hard: bool = False) -> None:
        """Flush queued packets into the transport and wait for it to drain.

        ``hard=True`` waits until the transport buffer is completely empty
        (write-buffer limits dropped to zero) — required before process exit
        (freeze/terminate), where normal drain() can return with bytes still
        in the user-space buffer that die with the process.
        """
        self.flush()
        try:
            if hard:
                self._writer.transport.set_write_buffer_limits(0, 0)
            await self._writer.drain()
        except Exception:
            self._closed = True
            raise ConnectionClosed("drain failed")

    # --- recv --------------------------------------------------------------

    async def recv_packet(self) -> tuple[int, Packet]:
        """Read one framed packet; returns (msgtype, packet).

        Bytes are read in chunks and deframed in batch (native.split —
        C when available): the per-packet inflate is bounded at
        MAX_PACKET_SIZE inside split (decompression-bomb guard)."""
        while not self._rframes:
            if self._recv_error is not None:
                # Parsed frames before the malformed one were delivered;
                # now the connection dies.
                raise ConnectionClosed(self._recv_error)
            try:
                chunk = await self._reader.read(_RECV_CHUNK)
            except (ConnectionResetError, OSError):
                raise ConnectionClosed("connection closed while reading")
            if not chunk:
                raise ConnectionClosed("connection closed while reading")
            self._rbytes += chunk
            frames, err = deframe(self._rbytes)
            self._rframes.extend(frames)
            if err is not None:
                self._recv_error = err
                if not self._rframes:
                    raise ConnectionClosed(err)
        msgtype, payload = self._rframes.popleft()
        return msgtype, Packet(payload)

    # --- close -------------------------------------------------------------

    def close(self) -> None:
        self._closed = True
        try:
            self._writer.close()
        except Exception:
            pass

    def abort(self) -> None:
        """Hard-kill the transport: discard buffered bytes and reset the
        connection (no FIN handshake). Used by the liveness watchdog to
        convert a half-open link into an immediate reconnect, and by the
        chaos harness to model a crashed peer (clean close would let the
        remote distinguish an orderly shutdown)."""
        self._closed = True
        try:
            self._writer.transport.abort()
        except Exception:
            try:
                self._writer.close()
            except Exception:
                pass

    @property
    def closed(self) -> bool:
        return self._closed

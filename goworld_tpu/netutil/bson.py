"""Minimal BSON codec (the subset entity/kv documents need).

Support layer for the from-scratch MongoDB client (netutil/mongo.py) — the
reference ships mgo-driver-backed mongodb backends; this image has no
driver, so the wire format is implemented directly (SURVEY.md §2.4 in-repo
equivalents rule).

Types: double, string, embedded document, array, bool, null, int32, int64.
Documents decode to dict, arrays to list; ints decode to int, doubles to
float. Encoding chooses int32/int64 by range and rejects unsupported types
loudly (entities serialize to exactly this subset — attrs.py uniformizes
values to int/float/bool/str/dict/list).
"""

from __future__ import annotations

import struct

_DOUBLE = 0x01
_STRING = 0x02
_DOC = 0x03
_ARRAY = 0x04
_BOOL = 0x08
_NULL = 0x0A
_INT32 = 0x10
_INT64 = 0x12

_I32 = struct.Struct("<i")
_I64 = struct.Struct("<q")
_F64 = struct.Struct("<d")


def _encode_value(out: bytearray, key: str, val) -> None:
    kb = key.encode("utf-8") + b"\x00"
    if isinstance(val, bool):  # before int: bool is an int subclass
        out += bytes([_BOOL]) + kb + (b"\x01" if val else b"\x00")
    elif isinstance(val, int):
        if -(2**31) <= val < 2**31:
            out += bytes([_INT32]) + kb + _I32.pack(val)
        else:
            out += bytes([_INT64]) + kb + _I64.pack(val)
    elif isinstance(val, float):
        out += bytes([_DOUBLE]) + kb + _F64.pack(val)
    elif isinstance(val, str):
        vb = val.encode("utf-8") + b"\x00"
        out += bytes([_STRING]) + kb + _I32.pack(len(vb)) + vb
    elif val is None:
        out += bytes([_NULL]) + kb
    elif isinstance(val, dict):
        out += bytes([_DOC]) + kb + encode(val)
    elif isinstance(val, (list, tuple)):
        out += bytes([_ARRAY]) + kb + encode(
            {str(i): v for i, v in enumerate(val)}
        )
    else:
        raise TypeError(f"bson: unsupported type {type(val).__name__} for {key!r}")


def encode(doc: dict) -> bytes:
    body = bytearray()
    for key, val in doc.items():
        _encode_value(body, str(key), val)
    return _I32.pack(len(body) + 5) + bytes(body) + b"\x00"


def _need(data: bytes, off: int, n: int) -> None:
    """Bounds guard: every wire-derived offset/length passes through here
    before a read, so a truncated or hostile document raises ValueError
    (like the unsupported-type path) instead of struct.error/IndexError
    out of the storage worker's decode."""
    if off < 0 or n < 0 or off + n > len(data):
        raise ValueError(
            f"bson: truncated document (need {n} bytes at {off}, "
            f"have {len(data)})")


def _read_cstring(data: bytes, off: int) -> tuple[str, int]:
    end = data.index(b"\x00", off)  # raises ValueError when unterminated
    return data[off:end].decode("utf-8"), end + 1


def _decode_value(kind: int, data: bytes, off: int):
    if kind == _DOUBLE:
        _need(data, off, 8)
        return _F64.unpack_from(data, off)[0], off + 8
    if kind == _STRING:
        _need(data, off, 4)
        (n,) = _I32.unpack_from(data, off)
        if n < 1:
            raise ValueError(f"bson: invalid string length {n}")
        _need(data, off + 4, n)
        s = data[off + 4:off + 4 + n - 1].decode("utf-8")
        return s, off + 4 + n
    if kind == _DOC:
        doc, n = _decode_doc(data, off)
        return doc, n
    if kind == _ARRAY:
        doc, n = _decode_doc(data, off)
        return [doc[k] for k in sorted(doc, key=int)], n
    if kind == _BOOL:
        _need(data, off, 1)
        return data[off] != 0, off + 1
    if kind == _NULL:
        return None, off
    if kind == _INT32:
        _need(data, off, 4)
        return _I32.unpack_from(data, off)[0], off + 4
    if kind == _INT64:
        _need(data, off, 8)
        return _I64.unpack_from(data, off)[0], off + 8
    raise ValueError(f"bson: unsupported element type 0x{kind:02x}")


def _decode_doc(data: bytes, off: int) -> tuple[dict, int]:
    _need(data, off, 4)
    (total,) = _I32.unpack_from(data, off)
    if total < 5:
        raise ValueError(f"bson: invalid document length {total}")
    _need(data, off, total)
    end = off + total - 1  # position of the trailing NUL
    off += 4
    doc: dict = {}
    while off < end:
        _need(data, off, 1)
        kind = data[off]
        key, off = _read_cstring(data, off + 1)
        doc[key], off = _decode_value(kind, data, off)
    return doc, end + 1


def decode(data: bytes) -> dict:
    doc, _ = _decode_doc(data, 0)
    return doc

"""KCP — the actual public ARQ protocol the reference's gate speaks.

Reference parity: the gate serves KCP beside TCP with turbo tuning
(``components/gate/GateService.go:134-165`` via xtaci/kcp-go;
``engine/consts/consts.go:122-131``: nodelay=1, interval=10 ms,
fastresend=2, nc=1, stream mode, ack-no-delay). ``netutil/rudp.py`` is the
in-repo ARQ with KCP-*parity recovery behavior* but its own 13-byte wire
format; THIS module implements the real KCP wire protocol from the public
specification (skywind3000/kcp), so a stock KCP peer can interoperate at
the segment level (VERDICT r4 missing #2).

Wire format (all little-endian; one UDP datagram carries >= 1 segments):

    [u32 conv][u8 cmd][u8 frg][u16 wnd][u32 ts][u32 sn][u32 una][u32 len]
    + len payload bytes                                   (24-byte header)

  cmd: 81 PUSH (data) | 82 ACK | 83 WASK (window probe) | 84 WINS (tell)
  frg: fragment countdown (stream mode always 0)
  wnd: sender's free receive-window slots;  una: next sn not yet received
  ts/sn: timestamp (ms) and sequence number — acks echo both

Protocol mechanics implemented exactly per the spec: cumulative una +
per-sn acks, fast retransmit on skip-count (fastresend), Jacobson/Karels
RTO with the 30 ms nodelay floor and nodelay x1.5 backoff, remote-window
tracking with zero-window probes (WASK/WINS with 7 s..120 s probe
backoff), slow-start/congestion-avoidance gated by nc, fragment
reassembly, dead-link detection at 20 transmissions of one segment.

No in-image KCP library or Go toolchain exists to cross-test against, so
the format is pinned the same way the snappy codec is: hand-computed
segment vectors in tests/test_kcp.py plus loss-matrix behavioral gates.
"""

from __future__ import annotations

import asyncio
import collections
import random
import struct
import time
from typing import Callable, Optional

from goworld_tpu import consts as gwconsts
from goworld_tpu import native
from goworld_tpu.netutil.packet import Packet
from goworld_tpu.netutil.packet_conn import ConnectionClosed

# Protocol constants (public KCP spec values).
RTO_NDL = 30  # nodelay min rto
RTO_MIN = 100
RTO_DEF = 200
RTO_MAX = 60000
CMD_PUSH = 81
CMD_ACK = 82
CMD_WASK = 83
CMD_WINS = 84
ASK_SEND = 1  # need to send WASK
ASK_TELL = 2  # need to send WINS
WND_SND = 32
WND_RCV = 128
MTU_DEF = 1400
INTERVAL_DEF = 100
OVERHEAD = 24
DEADLINK = 20
THRESH_INIT = 2
THRESH_MIN = 2
PROBE_INIT = 7000  # 7 s initial window-probe wait
PROBE_LIMIT = 120000  # 120 s max probe wait

_SEG_HDR = struct.Struct("<IBBHIII")  # conv cmd frg wnd ts sn una (+len u32)


def _itimediff(later: int, earlier: int) -> int:
    """Signed difference of two u32 millisecond clocks (wraps at 2^32)."""
    return ((later - earlier + 0x80000000) & 0xFFFFFFFF) - 0x80000000


class _Segment:
    __slots__ = ("conv", "cmd", "frg", "wnd", "ts", "sn", "una",
                 "resendts", "rto", "fastack", "xmit", "data")

    def __init__(self, data: bytes = b"") -> None:
        self.conv = 0
        self.cmd = 0
        self.frg = 0
        self.wnd = 0
        self.ts = 0
        self.sn = 0
        self.una = 0
        self.resendts = 0
        self.rto = 0
        self.fastack = 0
        self.xmit = 0
        self.data = data

    def encode(self) -> bytes:
        return _SEG_HDR.pack(self.conv, self.cmd, self.frg, self.wnd,
                             self.ts, self.sn, self.una) + struct.pack(
                                 "<I", len(self.data))


class KCP:
    """The KCP control block (protocol core; transport-agnostic).

    ``output(data)`` is called with ready-to-send datagrams (<= mtu).
    Drive with ``update(ms)`` at the configured interval and feed received
    datagrams to ``input(data)``. ``send``/``recv`` move user bytes.
    """

    def __init__(self, conv: int, output: Callable[[bytes], None]) -> None:
        self.conv = conv & 0xFFFFFFFF
        self.output = output
        self.snd_una = 0
        self.snd_nxt = 0
        self.rcv_nxt = 0
        self.ts_recent = 0
        self.ts_lastack = 0
        self.ssthresh = THRESH_INIT
        self.rx_rttval = 0
        self.rx_srtt = 0
        self.rx_rto = RTO_DEF
        self.rx_minrto = RTO_MIN
        self.snd_wnd = WND_SND
        self.rcv_wnd = WND_RCV
        self.rmt_wnd = WND_RCV
        self.cwnd = 0
        self.probe = 0
        self.mtu = MTU_DEF
        self.mss = self.mtu - OVERHEAD
        self.stream = False
        self.interval = INTERVAL_DEF
        self.ts_flush = INTERVAL_DEF
        self.nodelay = 0
        self.updated = False
        self.ts_probe = 0
        self.probe_wait = 0
        self.dead_link = DEADLINK
        self.incr = 0
        self.state = 0  # -1 once a segment hit dead_link transmissions
        self.current = 0
        self.nocwnd = 0
        self.fastresend = 0
        self.snd_queue: collections.deque[_Segment] = collections.deque()
        self.rcv_queue: collections.deque[_Segment] = collections.deque()
        self.snd_buf: collections.deque[_Segment] = collections.deque()
        self.rcv_buf: list[_Segment] = []  # kept sn-sorted
        self.acklist: list[tuple[int, int]] = []  # (sn, ts)
        self.xmit = 0

    # --- configuration ------------------------------------------------------

    def set_nodelay(self, nodelay: int, interval: int, resend: int,
                    nc: int) -> None:
        """The turbo knob quartet (reference: SetNoDelay(1, 10, 2, 1))."""
        if nodelay >= 0:
            self.nodelay = nodelay
            self.rx_minrto = RTO_NDL if nodelay else RTO_MIN
        if interval >= 0:
            self.interval = max(10, min(5000, interval))
        if resend >= 0:
            self.fastresend = resend
        if nc >= 0:
            self.nocwnd = nc

    def set_wndsize(self, sndwnd: int, rcvwnd: int) -> None:
        if sndwnd > 0:
            self.snd_wnd = sndwnd
        if rcvwnd > 0:
            self.rcv_wnd = max(rcvwnd, WND_RCV)

    def set_mtu(self, mtu: int) -> None:
        if mtu < 50 or mtu < OVERHEAD:
            raise ValueError("mtu too small")
        self.mtu = mtu
        self.mss = mtu - OVERHEAD

    # --- user data ----------------------------------------------------------

    def send(self, buffer: bytes) -> int:
        """Queue user bytes (stream mode coalesces into the tail segment;
        message mode fragments with frg countdown)."""
        if not buffer and not self.stream:
            return -1
        if self.stream and self.snd_queue:
            tail = self.snd_queue[-1]
            if len(tail.data) < self.mss:
                room = self.mss - len(tail.data)
                take = min(room, len(buffer))
                tail.data += buffer[:take]
                tail.frg = 0
                buffer = buffer[take:]
        if not buffer:
            return 0
        count = (len(buffer) + self.mss - 1) // self.mss
        if count == 0:
            count = 1
        if count >= WND_RCV:
            return -2  # unfragmentable against the protocol's frg field
        for i in range(count):
            seg = _Segment(buffer[i * self.mss:(i + 1) * self.mss])
            seg.frg = 0 if self.stream else (count - i - 1)
            self.snd_queue.append(seg)
        return 0

    def peeksize(self) -> int:
        if not self.rcv_queue:
            return -1
        seg = self.rcv_queue[0]
        if seg.frg == 0:
            return len(seg.data)
        if len(self.rcv_queue) < seg.frg + 1:
            return -1
        length = 0
        for s in self.rcv_queue:
            length += len(s.data)
            if s.frg == 0:
                break
        return length

    def recv(self) -> bytes | None:
        """One reassembled message (or stream chunk), or None."""
        if self.peeksize() < 0:
            return None
        recover = len(self.rcv_queue) >= self.rcv_wnd
        out = []
        while self.rcv_queue:
            seg = self.rcv_queue.popleft()
            out.append(seg.data)
            if seg.frg == 0:
                break
        self._move_rcv_buf()
        if (len(self.rcv_queue) < self.rcv_wnd) and recover:
            self.probe |= ASK_TELL  # window reopened: tell the peer
        return b"".join(out)

    # --- input path ---------------------------------------------------------

    def _update_ack(self, rtt: int) -> None:
        if self.rx_srtt == 0:
            self.rx_srtt = rtt
            self.rx_rttval = rtt // 2
        else:
            delta = abs(rtt - self.rx_srtt)
            self.rx_rttval = (3 * self.rx_rttval + delta) // 4
            self.rx_srtt = max(1, (7 * self.rx_srtt + rtt) // 8)
        rto = self.rx_srtt + max(self.interval, 4 * self.rx_rttval)
        self.rx_rto = max(self.rx_minrto, min(rto, RTO_MAX))

    def _shrink_buf(self) -> None:
        self.snd_una = self.snd_buf[0].sn if self.snd_buf else self.snd_nxt

    def _parse_ack(self, sn: int) -> None:
        if _itimediff(sn, self.snd_una) < 0 or \
                _itimediff(sn, self.snd_nxt) >= 0:
            return
        for i, seg in enumerate(self.snd_buf):
            if seg.sn == sn:
                del self.snd_buf[i]
                break
            if _itimediff(sn, seg.sn) < 0:
                break

    def _parse_una(self, una: int) -> None:
        while self.snd_buf and _itimediff(self.snd_buf[0].sn, una) < 0:
            self.snd_buf.popleft()

    def _parse_fastack(self, sn: int, ts: int) -> None:
        if _itimediff(sn, self.snd_una) < 0 or \
                _itimediff(sn, self.snd_nxt) >= 0:
            return
        for seg in self.snd_buf:
            if _itimediff(sn, seg.sn) < 0:
                break
            if sn != seg.sn:
                seg.fastack += 1

    def _parse_data(self, newseg: _Segment) -> None:
        sn = newseg.sn
        if _itimediff(sn, self.rcv_nxt + self.rcv_wnd) >= 0 or \
                _itimediff(sn, self.rcv_nxt) < 0:
            return
        # Ordered insert (dedup) from the back — bursts arrive in order.
        idx = len(self.rcv_buf)
        for i in range(len(self.rcv_buf) - 1, -1, -1):
            seg = self.rcv_buf[i]
            if seg.sn == sn:
                return  # duplicate
            if _itimediff(sn, seg.sn) > 0:
                idx = i + 1
                break
        else:
            idx = 0
        self.rcv_buf.insert(idx, newseg)
        self._move_rcv_buf()

    def _move_rcv_buf(self) -> None:
        while self.rcv_buf and self.rcv_buf[0].sn == self.rcv_nxt and \
                len(self.rcv_queue) < self.rcv_wnd:
            self.rcv_queue.append(self.rcv_buf.pop(0))
            self.rcv_nxt = (self.rcv_nxt + 1) & 0xFFFFFFFF

    def input(self, data: bytes) -> int:
        """Feed one received datagram (>= 1 segments). Returns 0, or < 0 on
        malformed/foreign input (caller drops the datagram)."""
        if len(data) < OVERHEAD:
            return -1
        prev_una = self.snd_una
        flag = False
        maxack = 0
        latest_ts = 0
        off = 0
        n = len(data)
        while n - off >= OVERHEAD:
            conv, cmd, frg, wnd, ts, sn, una = _SEG_HDR.unpack_from(
                data, off)
            (length,) = struct.unpack_from("<I", data, off + 20)
            off += OVERHEAD
            if conv != self.conv:
                return -1
            if n - off < length:
                return -2
            if cmd not in (CMD_PUSH, CMD_ACK, CMD_WASK, CMD_WINS):
                # Validated BEFORE applying wnd/una (ikcp_input order): a
                # malformed segment must not mutate the window or ack
                # state on its way to being rejected.
                return -3
            self.rmt_wnd = wnd
            self._parse_una(una)
            self._shrink_buf()
            if cmd == CMD_ACK:
                rtt = _itimediff(self.current, ts)
                if rtt >= 0:
                    self._update_ack(rtt)
                self._parse_ack(sn)
                self._shrink_buf()
                if not flag:
                    flag = True
                    maxack = sn
                    latest_ts = ts
                elif _itimediff(sn, maxack) > 0:
                    maxack = sn
                    latest_ts = ts
            elif cmd == CMD_PUSH:
                if _itimediff(sn, self.rcv_nxt + self.rcv_wnd) < 0:
                    self.acklist.append((sn, ts))
                    if _itimediff(sn, self.rcv_nxt) >= 0:
                        seg = _Segment(data[off:off + length])
                        seg.conv, seg.cmd, seg.frg = conv, cmd, frg
                        seg.wnd, seg.ts, seg.sn, seg.una = wnd, ts, sn, una
                        self._parse_data(seg)
            elif cmd == CMD_WASK:
                self.probe |= ASK_TELL
            # CMD_WINS: window update already absorbed via rmt_wnd
            off += length
        if flag:
            self._parse_fastack(maxack, latest_ts)
        # Congestion window growth on forward-progress acks (used only
        # when nc=0, but tracked regardless, per the spec).
        if _itimediff(self.snd_una, prev_una) > 0 and \
                self.cwnd < self.rmt_wnd:
            if self.cwnd < self.ssthresh:
                self.cwnd += 1
                self.incr += self.mss
            else:
                self.incr = max(self.incr, self.mss)
                self.incr += (self.mss * self.mss) // self.incr + \
                    (self.mss // 16)
                if (self.cwnd + 1) * self.mss <= self.incr:
                    self.cwnd = (self.incr + self.mss - 1) // max(
                        1, self.mss)
            if self.cwnd > self.rmt_wnd:
                self.cwnd = self.rmt_wnd
                self.incr = self.rmt_wnd * self.mss
        return 0

    # --- output path --------------------------------------------------------

    def _wnd_unused(self) -> int:
        return max(0, self.rcv_wnd - len(self.rcv_queue))

    def flush(self) -> None:
        if not self.updated:
            return
        current = self.current
        buf = bytearray()
        wnd_unused = self._wnd_unused()

        def emit(chunk: bytes) -> None:
            if len(buf) + len(chunk) > self.mtu and buf:
                self.output(bytes(buf))
                buf.clear()
            buf.extend(chunk)

        seg = _Segment()
        seg.conv = self.conv
        seg.cmd = CMD_ACK
        seg.wnd = wnd_unused
        seg.una = self.rcv_nxt
        # 1) pending acks
        for sn, ts in self.acklist:
            seg.sn, seg.ts = sn, ts
            emit(seg.encode())
        self.acklist.clear()
        # 2) zero-remote-window probing
        if self.rmt_wnd == 0:
            if self.probe_wait == 0:
                self.probe_wait = PROBE_INIT
                self.ts_probe = (current + self.probe_wait) & 0xFFFFFFFF
            elif _itimediff(current, self.ts_probe) >= 0:
                self.probe_wait = max(self.probe_wait, PROBE_INIT)
                self.probe_wait += self.probe_wait // 2
                self.probe_wait = min(self.probe_wait, PROBE_LIMIT)
                self.ts_probe = (current + self.probe_wait) & 0xFFFFFFFF
                self.probe |= ASK_SEND
        else:
            self.ts_probe = 0
            self.probe_wait = 0
        if self.probe & ASK_SEND:
            seg.cmd = CMD_WASK
            seg.sn, seg.ts = 0, 0
            emit(seg.encode())
        if self.probe & ASK_TELL:
            seg.cmd = CMD_WINS
            seg.sn, seg.ts = 0, 0
            emit(seg.encode())
        self.probe = 0
        # 3) move send-queue into the in-flight buffer within the window
        cwnd = min(self.snd_wnd, self.rmt_wnd)
        if not self.nocwnd:
            cwnd = min(self.cwnd, cwnd)
        while _itimediff(self.snd_nxt, self.snd_una + cwnd) < 0 and \
                self.snd_queue:
            newseg = self.snd_queue.popleft()
            newseg.conv = self.conv
            newseg.cmd = CMD_PUSH
            newseg.wnd = wnd_unused
            newseg.ts = current
            newseg.sn = self.snd_nxt
            self.snd_nxt = (self.snd_nxt + 1) & 0xFFFFFFFF
            newseg.una = self.rcv_nxt
            newseg.resendts = current
            newseg.rto = self.rx_rto
            newseg.fastack = 0
            newseg.xmit = 0
            self.snd_buf.append(newseg)
        # 4) (re)transmit in-flight segments
        resent = self.fastresend if self.fastresend > 0 else 0x7FFFFFFF
        rtomin = (self.rx_rto >> 3) if not self.nodelay else 0
        lost = False
        change = False
        for sseg in self.snd_buf:
            needsend = False
            if sseg.xmit == 0:
                needsend = True
                sseg.xmit += 1
                sseg.rto = self.rx_rto
                sseg.resendts = (current + sseg.rto + rtomin) & 0xFFFFFFFF
            elif _itimediff(current, sseg.resendts) >= 0:
                needsend = True
                sseg.xmit += 1
                self.xmit += 1
                if not self.nodelay:
                    sseg.rto += max(sseg.rto, self.rx_rto)
                else:
                    sseg.rto += self.rx_rto // 2  # nodelay x1.5 backoff
                sseg.resendts = (current + sseg.rto) & 0xFFFFFFFF
                lost = True
            elif sseg.fastack >= resent:
                needsend = True
                sseg.xmit += 1
                sseg.fastack = 0
                sseg.resendts = (current + sseg.rto) & 0xFFFFFFFF
                change = True
            if needsend:
                sseg.ts = current
                sseg.wnd = wnd_unused
                sseg.una = self.rcv_nxt
                emit(sseg.encode() + sseg.data)
                if sseg.xmit >= self.dead_link:
                    self.state = -1
        if buf:
            self.output(bytes(buf))
        # 5) congestion state updates
        if change:
            inflight = (self.snd_nxt - self.snd_una) & 0xFFFFFFFF
            self.ssthresh = max(THRESH_MIN, inflight // 2)
            self.cwnd = self.ssthresh + resent
            self.incr = self.cwnd * self.mss
        if lost:
            self.ssthresh = max(THRESH_MIN, cwnd // 2)
            self.cwnd = 1
            self.incr = self.mss
        if self.cwnd < 1:
            self.cwnd = 1
            self.incr = self.mss

    def update(self, current: int) -> None:
        """Clock the protocol (``current`` in ms, any epoch, wraps u32)."""
        self.current = current & 0xFFFFFFFF
        if not self.updated:
            self.updated = True
            self.ts_flush = self.current
        slap = _itimediff(self.current, self.ts_flush)
        if slap >= 10000 or slap < -10000:
            self.ts_flush = self.current
            slap = 0
        if slap >= 0:
            self.ts_flush = (self.ts_flush + self.interval) & 0xFFFFFFFF
            if _itimediff(self.current, self.ts_flush) >= 0:
                self.ts_flush = (self.current + self.interval) & 0xFFFFFFFF
            self.flush()

    def check(self, current: int) -> int:
        """Earliest ms at which update() has work (spec ikcp_check): the
        next flush tick or the earliest retransmit deadline."""
        current &= 0xFFFFFFFF
        if not self.updated:
            return current
        ts_flush = self.ts_flush
        slap = _itimediff(current, ts_flush)
        if slap >= 10000 or slap < -10000:
            ts_flush = current
        if _itimediff(current, ts_flush) >= 0:
            return current
        tm_packet = 0x7FFFFFFF
        for seg in self.snd_buf:
            diff = _itimediff(seg.resendts, current)
            if diff <= 0:
                return current
            tm_packet = min(tm_packet, diff)
        minimal = min(tm_packet, _itimediff(ts_flush, current),
                      self.interval)
        return (current + minimal) & 0xFFFFFFFF

    def idle(self) -> bool:
        """No in-flight data, nothing queued, no acks or probes owed —
        update() is a no-op until new input/send (session-layer parking)."""
        return (not self.snd_buf and not self.snd_queue
                and not self.acklist and self.probe == 0
                and self.rmt_wnd > 0)

    @property
    def has_acks(self) -> bool:
        """Pending acks owed to the peer (shared seam with the C core —
        native/kcpcore.c exposes the same attribute)."""
        return bool(self.acklist)

    def waiting_send(self) -> int:
        return len(self.snd_buf) + len(self.snd_queue)


# --- asyncio session layer ---------------------------------------------------

# Segments kcp.input rejected, by its return code (session layer counts —
# the protocol core stays dependency-free for the C-parity suite).
from goworld_tpu import telemetry as _telemetry

_KCP_MALFORMED = _telemetry.counter(
    "kcp_malformed_dropped_total",
    "Datagrams rejected by kcp.input: runt_or_foreign_conv (short header "
    "or wrong conversation id), bad_length (declared segment length "
    "exceeds the datagram), bad_cmd (unknown command byte).",
    ("reason",))
_KCP_INPUT_REASON = {
    -1: "runt_or_foreign_conv", -2: "bad_length", -3: "bad_cmd",
}

_MS_EPOCH = time.monotonic()


def _now_ms() -> int:
    return int((time.monotonic() - _MS_EPOCH) * 1000) & 0xFFFFFFFF


def make_core(conv: int, output: Callable[[bytes], None]):
    """The KCP control block — the C hot path (native/kcpcore.c) when
    built, else the pinned pure-Python reference above. Identical
    semantics; the parity suite pumps random lossy transfers through
    MIXED C/Python pairs and asserts identical delivered streams."""
    import os

    from goworld_tpu import native

    if native.KCPCore is not None and \
            os.environ.get("GWT_NO_NATIVE", "") != "1":
        return native.KCPCore(conv, output)
    return KCP(conv, output)


class KCPPacketConnection:
    """PacketConnection-shaped adapter over one KCP conversation, carrying
    the same framed packet stream as TCP (stream mode + native.split, the
    way the reference layers its framing over a kcp-go UDPSession)."""

    def __init__(
        self,
        conv: int,
        transmit: Callable[[bytes], None],
        on_close: Optional[Callable[["KCPPacketConnection"], None]] = None,
        fec: tuple[int, int] | None = (10, 3),
    ) -> None:
        self.conv = conv
        self._transmit = transmit
        self._on_close = on_close
        self.loss_simulation = 0.0
        # FEC(10,3) is the reference's exact dial shape
        # (ListenWithOptions(addr, nil, 10, 3)); None disables the FEC
        # framing entirely (plain KCP segments on the wire). Both ends
        # must agree — the 6-byte header is not self-identifying.
        if fec is not None:
            from goworld_tpu.netutil.fec import FECDecoder, FECEncoder

            self._fec_enc = FECEncoder(*fec)
            self._fec_dec = FECDecoder(*fec)
        else:
            self._fec_enc = self._fec_dec = None
        self.kcp = make_core(conv, self._output)
        if fec is not None:
            # Keep FEC-wrapped datagrams inside the 1400-byte budget: the
            # wrap adds 8 bytes (6 header + 2 size), so shrink the kcp
            # mtu by exactly that (kcp-go: SetMtu(mtuDefault-headerSize)).
            self.kcp.set_mtu(MTU_DEF - 8)
        # Reference turbo tuning (consts.go:122-131) + stream mode.
        self.kcp.set_nodelay(1, 10, 2, 1)
        self.kcp.stream = True
        self.kcp.set_wndsize(256, 256)
        self._compress = 0  # 0 off | 1 zlib | 2 snappy (native.pack modes)
        self._rbytes = bytearray()
        self._packets: asyncio.Queue = asyncio.Queue()
        self.closed = False
        self.dropped = 0
        self._peername = None
        self._wake = asyncio.Event()
        self._ticker = asyncio.get_running_loop().create_task(
            self._tick_loop())

    @property
    def peername(self):
        return self._peername

    def _output(self, data: bytes) -> None:
        datagrams = (self._fec_enc.encode(data)
                     if self._fec_enc is not None else (data,))
        for d in datagrams:
            if self.loss_simulation and \
                    random.random() < self.loss_simulation:
                continue
            self._transmit(d)

    async def _tick_loop(self) -> None:
        # Event-driven clocking (code-review r5): while the conversation
        # has work, wake at kcp.check()'s deadline (<= the 10 ms turbo
        # interval); while fully IDLE, park on the wake event so thousands
        # of quiet connections cost zero scheduler load. send_packet and
        # on_datagram kick the event.
        while not self.closed:
            self.kcp.update(_now_ms())
            if self.kcp.state < 0:
                self.close()  # dead link: 20 xmits of one segment
                return
            if self.kcp.idle():
                self._wake.clear()
                await self._wake.wait()
                continue
            nxt = self.kcp.check(_now_ms())
            delay = max(1, _itimediff(nxt, _now_ms())) / 1000.0
            self._wake.clear()
            try:
                await asyncio.wait_for(self._wake.wait(), timeout=delay)
            except asyncio.TimeoutError:
                pass

    def on_datagram(self, data: bytes) -> None:
        """Feed one received UDP datagram (FEC-unwrapped when enabled —
        reconstructed lost datagrams feed kcp right behind the real one).
        Datagrams kcp rejects (foreign conv, truncated declared length,
        unknown cmd) are dropped and counted per reason — the hostile-
        input visibility VERDICT r5 asked for."""
        if self._fec_dec is not None:
            payloads = self._fec_dec.decode(data)
        else:
            payloads = (data,)
        ok = False
        for p in payloads:
            rc = self.kcp.input(p)
            if rc >= 0:
                ok = True
            else:
                _KCP_MALFORMED.labels(
                    _KCP_INPUT_REASON.get(rc, "malformed")).inc()
        if not ok:
            return
        self._wake.set()  # un-park the ticker (acks/probes/window opened)
        # ACK_NO_DELAY: flush pending acks now, not at the next tick.
        if self.kcp.has_acks and self.kcp.updated:
            self.kcp.current = _now_ms()
            self.kcp.flush()
        # Drain every ready message FIRST, then deframe the lot in ONE C
        # split call — a restore burst delivers thousands of stream chunks
        # per client, and a Python→C crossing per chunk is measurable at
        # fleet scale (the TCP path batch-parses whole socket reads the
        # same way).
        got = False
        while True:
            msg = self.kcp.recv()
            if msg is None:
                break
            self._rbytes += msg
            got = True
        if not got:
            return
        from goworld_tpu.netutil.packet_conn import deframe

        frames, err = deframe(self._rbytes)
        for mt, payload in frames:
            self._packets.put_nowait((mt, Packet(payload)))
        if err is not None:
            self.close()  # malformed framed stream is fatal
            return

    # --- PacketConnection surface ------------------------------------------

    def enable_compression(self, fmt: str = "snappy") -> None:
        if fmt not in ("snappy", "zlib"):
            raise ValueError(f"unknown compression format {fmt!r}")
        self._compress = 2 if fmt == "snappy" else 1

    MAX_BACKLOG = 65536  # queued segments beyond the window → evict (the
    # WS/rudp transports' stalled-client policy; KCP itself is unbounded)

    def send_packet(self, msgtype: int, packet: Packet) -> None:
        from goworld_tpu.netutil.packet_conn import _COMPRESS_THRESHOLD

        if self.closed:
            self.dropped += 1
            return
        if self.kcp.waiting_send() > self.MAX_BACKLOG:
            self.dropped += 1
            self.close()  # stalled client: evict
            return
        buf = native.pack(msgtype, packet.payload, self._compress,
                          _COMPRESS_THRESHOLD, gwconsts.MAX_PACKET_SIZE)
        # kcp.send rejects buffers that fragment into >= WND_RCV segments
        # (the u8 frg field); chunk like kcp-go's UDPSession.Write does —
        # stream mode re-coalesces, so chunking is invisible on the wire.
        chunk = self.kcp.mss * 120
        for off in range(0, len(buf), chunk):
            if self.kcp.send(buf[off:off + chunk]) < 0:
                # Chunking guarantees this cannot happen; if it ever does,
                # a HALF-QUEUED frame would desync the framed byte stream
                # for the rest of the conversation — kill it instead.
                self.dropped += 1
                self.close()
                return
        self._wake.set()

    def flush(self) -> None:
        if not self.closed:
            self.kcp.update(_now_ms())

    async def drain(self, hard: bool = False) -> None:
        self.flush()
        if hard:
            # Freeze/terminate path: push retransmits until the peer acked
            # everything or a bounded budget elapses.
            deadline = time.monotonic() + 2.0
            while self.kcp.waiting_send() and time.monotonic() < deadline:
                self.kcp.update(_now_ms())
                await asyncio.sleep(self.kcp.interval / 1000.0)

    async def recv_packet(self) -> tuple[int, Packet]:
        item = await self._packets.get()
        if item is None:
            raise ConnectionClosed("kcp closed")
        return item

    def close(self) -> None:
        """KCP has no FIN on the wire (matching the protocol): the peer
        learns of the close via dead-link / the app-level heartbeat kill.
        The listener tombstones the (addr, conv) key so a still-
        retransmitting peer cannot resurrect a ghost session."""
        if self.closed:
            return
        self.closed = True
        self._ticker.cancel()
        self._packets.put_nowait(None)
        if self._on_close is not None:
            self._on_close(self)


class KCPListener(asyncio.DatagramProtocol):
    """Server side: sessions keyed by remote address on one UDP socket
    (kcp-go's Listener shape, GateService.go:134-144 — FEC parity shards
    carry no conv, so address is the only universal demux key; the conv
    is pinned from the opening PUSH and enforced by kcp.input)."""

    _TOMBSTONES = 1024  # recently closed (addr, conv) keys remembered
    # Session caps (ADVICE r5 #1): a session costs a ticker task + FEC
    # state + a full gate accept/boot pipeline, keyed by SPOOFABLE source
    # address — so a forged-source flood of 24-byte sn-0 PUSHes would
    # otherwise allocate without bound. Excess opens are dropped BEFORE
    # constructing KCPPacketConnection and counted on
    # kcp_sessions_dropped_total{reason}. A legitimate client behind the
    # caps retries its sn-0 PUSH (it retransmits until acked) and gets in
    # once load subsides. The per-IP cap bounds one unspoofed abuser (or
    # one NAT'd venue — size accordingly) well below the listener cap.
    MAX_SESSIONS = 4096
    MAX_SESSIONS_PER_IP = 64

    def __init__(
        self,
        on_accept: Callable[[KCPPacketConnection], None],
        fec: tuple[int, int] | None = (10, 3),
        max_sessions: int | None = None,
        max_sessions_per_ip: int | None = None,
    ) -> None:
        self._on_accept = on_accept
        self._fec = fec
        self._transport: Optional[asyncio.DatagramTransport] = None
        self._sessions: dict = {}
        self.max_sessions = max_sessions or self.MAX_SESSIONS
        self.max_sessions_per_ip = (
            max_sessions_per_ip or self.MAX_SESSIONS_PER_IP)
        self._per_ip: collections.Counter = collections.Counter()
        from goworld_tpu import telemetry

        self._m_dropped = telemetry.counter(
            "kcp_sessions_dropped_total",
            "sn-0 opens dropped by KCPListener session caps.",
            ("reason",))
        # Closed conversations must not resurrect (code-review r5): an
        # evicted client still retransmitting would otherwise re-create a
        # ghost session + boot flow on its next PUSH. FIFO-bounded so an
        # address churning conv ids can't grow it unboundedly.
        self._tombstones: collections.OrderedDict = collections.OrderedDict()
        self.loss_simulation = 0.0

    def connection_made(self, transport) -> None:
        self._transport = transport

    def _first_segment(self, data: bytes) -> bytes | None:
        """The raw KCP bytes of a datagram for session-opening decisions:
        None when it can't open one (parity shard, runt)."""
        if self._fec is not None:
            from goworld_tpu.netutil import fec as fecmod

            if len(data) < fecmod.DATA_OFF:
                return None
            (flag,) = struct.unpack_from("<H", data, 4)
            if flag != fecmod.TYPE_DATA:
                return None
            return data[fecmod.DATA_OFF:]
        return data

    def datagram_received(self, data: bytes, addr) -> None:
        sess = self._sessions.get(addr)
        if sess is None:
            seg = self._first_segment(data)
            if seg is None or len(seg) < OVERHEAD:
                return
            (conv,) = struct.unpack_from("<I", seg, 0)
            if (addr, conv) in self._tombstones:
                return  # closed conversation: never resurrect
            cmd = seg[4]
            if cmd != CMD_PUSH:
                return  # stray control segment for a dead conversation
            (sn,) = struct.unpack_from("<I", seg, 12)
            if sn != 0:
                # A NEW conversation's first-arriving push is sn 0 (sn 0
                # retransmits until acked, so loss can't starve this);
                # mid-stream sns are a dead/unknown conversation's
                # retransmits — don't boot a ghost proxy for them.
                return
            if len(self._sessions) >= self.max_sessions:
                self._m_dropped.labels("listener_cap").inc()
                return
            if self._per_ip[addr[0]] >= self.max_sessions_per_ip:
                self._m_dropped.labels("ip_cap").inc()
                return
            sess = KCPPacketConnection(
                conv,
                lambda d, a=addr: self._send_to(a, d),
                on_close=self._session_closed,
                fec=self._fec,
            )
            sess.loss_simulation = self.loss_simulation
            sess._peername = addr
            sess._listener_key = addr
            self._sessions[addr] = sess
            self._per_ip[addr[0]] += 1
            self._on_accept(sess)
        sess.on_datagram(data)

    def _session_closed(self, sess: KCPPacketConnection) -> None:
        key = getattr(sess, "_listener_key", None)
        if key is None:
            return
        if self._sessions.pop(key, None) is not None:
            # Decrement only on a real removal: close() can race a
            # tombstoned re-close and must not drive the count negative.
            self._per_ip[key[0]] -= 1
            if self._per_ip[key[0]] <= 0:
                del self._per_ip[key[0]]
        self._tombstones[(key, sess.conv)] = True
        while len(self._tombstones) > self._TOMBSTONES:
            self._tombstones.popitem(last=False)

    def _send_to(self, addr, data: bytes) -> None:
        if self._transport is not None:
            self._transport.sendto(data, addr)

    def close(self) -> None:
        for sess in list(self._sessions.values()):
            sess.close()
        if self._transport is not None:
            self._transport.close()


class _KCPClientProtocol(asyncio.DatagramProtocol):
    def __init__(self, ref: list) -> None:
        self._ref = ref

    def datagram_received(self, data: bytes, addr) -> None:
        sess = self._ref[0]
        if sess is None:
            return
        # The socket is connected to one server; conv/format checks happen
        # inside the session (FEC unwrap + kcp.input conv enforcement).
        sess.on_datagram(data)


async def connect_kcp(
    host: str, port: int, loss_simulation: float = 0.0,
    conv: int | None = None, fec: tuple[int, int] | None = (10, 3),
) -> KCPPacketConnection:
    """Client side: open a KCP conversation (random conv + FEC(10,3), the
    reference's exact dial shape, ClientBot.go:153) and return a
    PacketConnection-shaped transport. ``fec`` must match the server."""
    loop = asyncio.get_running_loop()
    ref: list = [None]
    transport, _ = await loop.create_datagram_endpoint(
        lambda: _KCPClientProtocol(ref), remote_addr=(host, port))
    if conv is None:
        conv = random.getrandbits(32) or 1
    sess = KCPPacketConnection(
        conv, transport.sendto,
        on_close=lambda s: transport.close(), fec=fec)
    sess.loss_simulation = loss_simulation
    sess._peername = (host, port)
    ref[0] = sess
    return sess

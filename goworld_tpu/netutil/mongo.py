"""From-scratch MongoDB wire-protocol client (OP_MSG).

Fills the reference's mongodb backend slots
(``engine/storage/backend/mongodb/mongodb.go``,
``engine/kvdb/backend/kvdb_mongodb.go``, ``ext/db/gwmongo``) without a
driver: modern servers speak OP_MSG (opcode 2013) — one kind-0 section
carrying a command document, reply likewise. Like the RESP2 client
(netutil/resp.py) this is a blocking socket + lock, run from the serial
storage/kvdb worker threads.

Supported commands: ping/hello, insert, update (upsert), delete, find (+
getMore cursor pagination). No auth/TLS/compression — connect to a local
or trusted mongod (the reference's CI services ran the same way).
"""

from __future__ import annotations

import socket
import struct
import threading
from typing import Optional

from goworld_tpu.netutil import bson

_OP_MSG = 2013
_HEADER = struct.Struct("<iiii")  # messageLength, requestID, responseTo, opCode


class MongoError(Exception):
    """Server-reported command failure ({ok: 0, ...} or writeErrors)."""

    def __init__(self, msg: str, code: int = 0) -> None:
        super().__init__(msg)
        self.code = code


DUPLICATE_KEY = 11000


class MongoClient:
    def __init__(self, host: str = "127.0.0.1", port: int = 27017,
                 timeout: float = 10.0) -> None:
        self.host = host
        self.port = port
        self.timeout = timeout
        self._sock: Optional[socket.socket] = None
        self._req_id = 0
        self._lock = threading.Lock()

    # --- transport ----------------------------------------------------------

    def _connect(self) -> None:
        self.close()
        s = socket.create_connection((self.host, self.port), self.timeout)
        s.settimeout(self.timeout)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = s

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _read_exact(self, n: int) -> bytes:
        chunks = []
        while n:
            b = self._sock.recv(n)
            if not b:
                raise ConnectionError("mongo: connection closed")
            chunks.append(b)
            n -= len(b)
        return b"".join(chunks)

    def _roundtrip(self, command: dict) -> dict:
        self._req_id += 1
        sections = b"\x00" + bson.encode(command)  # kind-0 section + doc
        msg = (
            _HEADER.pack(16 + 4 + len(sections), self._req_id, 0, _OP_MSG)
            + struct.pack("<i", 0)  # flagBits
            + sections
        )
        self._sock.sendall(msg)
        length, _, _, opcode = _HEADER.unpack(self._read_exact(16))
        payload = self._read_exact(length - 16)
        if opcode != _OP_MSG:
            raise MongoError(f"unexpected reply opcode {opcode}")
        # payload = flagBits i32, then sections; kind-0 section = one doc.
        off = 4
        if payload[off] != 0:
            raise MongoError(f"unexpected section kind {payload[off]}")
        reply = bson.decode(payload[off + 1:])
        return reply

    # --- commands -----------------------------------------------------------

    def command(self, db: str, command: dict) -> dict:
        """Run one command; transparent single reconnect on transport error
        (kvdb auto-reopen parity, kvdb.go:40-207)."""
        command = dict(command)
        command["$db"] = db
        with self._lock:
            if self._sock is None:
                self._connect()
            try:
                reply = self._roundtrip(command)
            except (OSError, ConnectionError):
                self._connect()
                reply = self._roundtrip(command)
        if not reply.get("ok"):
            raise MongoError(
                str(reply.get("errmsg", reply)), int(reply.get("code", 0))
            )
        errs = reply.get("writeErrors")
        if errs:
            first = errs[0]
            raise MongoError(
                str(first.get("errmsg", first)), int(first.get("code", 0))
            )
        return reply

    def ping(self, db: str = "admin") -> bool:
        return bool(self.command(db, {"ping": 1}).get("ok"))

    def insert(self, db: str, coll: str, docs: list[dict]) -> None:
        self.command(db, {"insert": coll, "documents": docs})

    def upsert(self, db: str, coll: str, query: dict, doc: dict) -> None:
        self.command(db, {
            "update": coll,
            "updates": [{"q": query, "u": doc, "upsert": True, "multi": False}],
        })

    def delete(self, db: str, coll: str, query: dict, limit: int = 0) -> int:
        r = self.command(db, {
            "delete": coll, "deletes": [{"q": query, "limit": limit}],
        })
        return int(r.get("n", 0))

    def find(self, db: str, coll: str, query: dict,
             projection: Optional[dict] = None, sort: Optional[dict] = None,
             limit: int = 0) -> list[dict]:
        cmd: dict = {"find": coll, "filter": query, "batchSize": 1000}
        if projection is not None:
            cmd["projection"] = projection
        if sort is not None:
            cmd["sort"] = sort
        if limit:
            cmd["limit"] = limit
        r = self.command(db, cmd)
        cursor = r.get("cursor", {})
        out = list(cursor.get("firstBatch", []))
        cid = cursor.get("id", 0)
        while cid:
            r = self.command(db, {"getMore": cid, "collection": coll,
                                  "batchSize": 1000})
            cursor = r.get("cursor", {})
            out.extend(cursor.get("nextBatch", []))
            cid = cursor.get("id", 0)
        return out

    def find_one(self, db: str, coll: str, query: dict) -> Optional[dict]:
        docs = self.find(db, coll, query, limit=1)
        return docs[0] if docs else None


def parse_mongo_url(url: str) -> dict:
    """``mongodb://host[:port]`` → MongoClient kwargs (no auth/options)."""
    rest = url
    if "://" in rest:
        scheme, rest = rest.split("://", 1)
        if scheme != "mongodb":
            raise ValueError(f"unsupported url scheme {scheme!r}")
    rest = rest.split("/", 1)[0]
    host, _, port = rest.partition(":")
    return {"host": host or "127.0.0.1", "port": int(port) if port else 27017}

"""WebSocket packet transport.

Reference parity: the gate serves WebSocket clients next to TCP/KCP
(gate.go:92-95 via golang.org/x/net/websocket; GateService.go:167-172).
Python-native design: the ``websockets`` library carries one packet per
binary message — WS frames preserve boundaries, so no length prefix is
needed; the wire body is [u16 msgtype][payload], identical to the TCP
framing minus the length word. Compression rides WS permessage-deflate
(negotiated by the library) instead of the TCP path's explicit zlib flag.

``WSPacketConnection`` presents the same surface as ``PacketConnection``
so ``GoWorldConnection`` and the gate logic are transport-agnostic. Sends
are serialized through one writer task per connection, mirroring how the
TCP path's pending-buffer flush keeps per-connection FIFO order.
"""

from __future__ import annotations

import asyncio
import struct

from goworld_tpu import consts
from goworld_tpu.netutil.packet import Packet
from goworld_tpu.netutil.packet_conn import ConnectionClosed


class WSPacketConnection:
    """PacketConnection-shaped adapter over a websockets protocol object."""

    # A stalled client may never drain its socket; beyond this many queued
    # packets the connection is evicted rather than growing without bound
    # (the TCP path gets the same protection from SO_SNDBUF + drop counters).
    MAX_QUEUED = 4096

    def __init__(self, ws) -> None:
        self._ws = ws
        self._closed = False
        self._outq: asyncio.Queue = asyncio.Queue()
        self._writer_task = asyncio.get_running_loop().create_task(self._writer())
        self.dropped = 0

    @property
    def peername(self):
        try:
            return self._ws.remote_address
        except Exception:
            return None

    def enable_compression(self, fmt: str = "snappy") -> None:
        pass  # permessage-deflate is negotiated at the WS handshake

    # --- send --------------------------------------------------------------

    def send_packet(self, msgtype: int, packet: Packet) -> None:
        if self._closed:
            self.dropped += 1
            return
        body = struct.pack("<H", msgtype) + packet.payload
        if len(body) > consts.MAX_PACKET_SIZE:
            raise ValueError(f"packet too large: {len(body)}")
        if self._outq.qsize() >= self.MAX_QUEUED:
            self.dropped += 1
            self.close()  # stalled client: evict instead of growing forever
            return
        self._outq.put_nowait(body)

    async def _writer(self) -> None:
        """Single writer → per-connection FIFO send order."""
        try:
            while True:
                body = await self._outq.get()
                await self._ws.send(body)
        except asyncio.CancelledError:
            pass
        except Exception:
            # Packets already queued will never reach the peer: account for
            # them as dropped and tear the socket down.
            self.close()
        finally:
            while not self._outq.empty():
                self._outq.get_nowait()
                self.dropped += 1

    def flush(self) -> None:
        pass  # the writer task drains continuously

    async def drain(self) -> None:
        pass  # the writer task drains continuously

    # --- recv --------------------------------------------------------------

    async def recv_packet(self) -> tuple[int, Packet]:
        try:
            msg = await self._ws.recv()
        except Exception:
            raise ConnectionClosed("websocket closed")
        if isinstance(msg, str):
            msg = msg.encode()
        if len(msg) < 2 or len(msg) > consts.MAX_PACKET_SIZE:
            raise ConnectionClosed(f"bad ws packet length {len(msg)}")
        msgtype = struct.unpack_from("<H", msg, 0)[0]
        return msgtype, Packet(bytes(msg[2:]))

    # --- close -------------------------------------------------------------

    def close(self) -> None:
        self._closed = True
        self._writer_task.cancel()
        try:
            task = asyncio.get_running_loop().create_task(self._ws.close())
            # Retrieve the result so the loop doesn't log "exception was
            # never retrieved" — but a CANCELLED close (loop teardown)
            # must be probed with cancelled() first: t.exception() raises
            # CancelledError out of the callback and spams the log.
            task.add_done_callback(
                lambda t: None if t.cancelled() else t.exception())
        except RuntimeError:
            pass

    @property
    def closed(self) -> bool:
        return self._closed

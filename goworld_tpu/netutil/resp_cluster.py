"""Cluster-aware Redis client over the from-scratch RESP2 client.

Reference parity: the reference ships redis_cluster backends for both
persistence planes via the ``chasex/redis-go-cluster`` driver
(``engine/storage/backend/redis_cluster/entity_storage_redis_cluster.go:1``,
``engine/kvdb/backend/kvdbrediscluster/kvdb_redis_cluster.go:1``); this is
the in-repo equivalent speaking the Redis Cluster protocol directly:

- key → slot via CRC16/XMODEM mod 16384, honoring ``{hash tag}`` sub-keys;
- topology from ``CLUSTER SLOTS`` against any live seed node;
- ``-MOVED <slot> host:port`` → refresh the slot map, retry on the new
  owner (permanent resharding);
- ``-ASK <slot> host:port`` → one-shot redirect preceded by ``ASKING``
  (slot mid-migration; the map is NOT updated);
- multi-key ops split per slot (cluster MGET across slots is CROSSSLOT);
- keyspace scans fan out over every master and merge.

Like RespClient, blocking sockets + a lock: the storage/kvdb job queues are
the concurrency layer (storage/__init__.py), mirroring the reference's
single storageRoutine.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from goworld_tpu.netutil.resp import Reply, RespClient, RespError

SLOTS = 16384

# CRC16/XMODEM (poly 0x1021, init 0) — the Redis Cluster key hash
# (cluster spec "Keys distribution model"). Table-driven, computed once.
_CRC_TABLE = []
for _byte in range(256):
    _crc = _byte << 8
    for _ in range(8):
        _crc = ((_crc << 1) ^ 0x1021) if (_crc & 0x8000) else (_crc << 1)
    _CRC_TABLE.append(_crc & 0xFFFF)


def crc16(data: bytes) -> int:
    crc = 0
    for b in data:
        crc = ((crc << 8) & 0xFFFF) ^ _CRC_TABLE[((crc >> 8) ^ b) & 0xFF]
    return crc


def key_slot(key: str | bytes) -> int:
    """Slot of a key, honoring ``{hash tag}``: when the key contains a
    non-empty brace section, only that section is hashed (lets callers pin
    related keys to one slot)."""
    k = key if isinstance(key, bytes) else key.encode("utf-8")
    start = k.find(b"{")
    if start >= 0:
        end = k.find(b"}", start + 1)
        if end > start + 1:  # non-empty tag only
            k = k[start + 1 : end]
    return crc16(k) % SLOTS


class ClusterDownError(Exception):
    """No seed/known node answered, or redirects did not converge."""


class RespClusterClient:
    """Slot-routed command execution over a pool of RespClients."""

    _MAX_REDIRECTS = 5

    def __init__(
        self,
        start_nodes: list[str],
        password: Optional[str] = None,
        timeout: float = 10.0,
        probe_timeout: Optional[float] = None,
    ) -> None:
        if not start_nodes:
            raise ValueError("redis_cluster requires at least one start node")
        self._seeds = [self._parse_addr(a) for a in start_nodes]
        self._password = password
        self._timeout = timeout
        self._conns: dict[tuple[str, int], RespClient] = {}
        # slot → (host, port) of the owning master; rebuilt on MOVED.
        self._slot_owner: dict[int, tuple[str, int]] = {}
        self._masters: list[tuple[str, int]] = []
        self._lock = threading.Lock()
        # Topology probes use a short timeout and skip recently-dead
        # nodes, so one unreachable master costs at most ~_probe_timeout
        # per refresh instead of the full command timeout per candidate.
        # Scales with the command timeout (slow/cross-region clusters
        # stay reachable) but never exceeds it.
        if probe_timeout is None:
            probe_timeout = max(2.0, timeout * 0.2)
        self._probe_timeout = min(timeout, probe_timeout)
        self._dead_until: dict[tuple[str, int], float] = {}
        self._DEAD_BACKOFF = 5.0

    @staticmethod
    def _parse_addr(addr: str) -> tuple[str, int]:
        host, _, port = addr.rpartition(":")
        return (host or "127.0.0.1", int(port))

    def _conn(self, addr: tuple[str, int]) -> RespClient:
        c = self._conns.get(addr)
        if c is None:
            # db is always 0: Redis Cluster supports only database 0.
            c = RespClient(
                host=addr[0], port=addr[1], db=0,
                password=self._password, timeout=self._timeout,
            )
            self._conns[addr] = c
        return c

    # --- topology -----------------------------------------------------------

    def _refresh_slots(self) -> None:
        """Rebuild the slot map from CLUSTER SLOTS via any live node.

        Probes use ``_probe_timeout`` (not the command timeout) on a
        throwaway connection and skip nodes marked dead within the last
        ``_DEAD_BACKOFF`` seconds, bounding the stall a dead node can
        inject into the refresh sweep (ADVICE r4)."""
        last_err: Exception | None = None
        now = time.monotonic()
        # dict.fromkeys: dedupe (a seed that is also a listed master must
        # not be probed twice per sweep) while preserving masters-first order.
        candidates = list(dict.fromkeys(list(self._masters) + self._seeds))
        dead = {a for a in candidates if self._dead_until.get(a, 0) > now}
        live_first = [a for a in candidates if a not in dead]
        live_first += [a for a in candidates if a in dead]  # last, not never
        for addr in live_first:
            probe = RespClient(
                host=addr[0], port=addr[1], db=0,
                password=self._password, timeout=self._probe_timeout,
            )
            try:
                reply = probe.execute_once("CLUSTER", "SLOTS")
            except (OSError, ConnectionError, RespError) as e:
                self._dead_until[addr] = time.monotonic() + self._DEAD_BACKOFF
                last_err = e
                continue
            finally:
                probe.close()
            owner: dict[int, tuple[str, int]] = {}
            masters: list[tuple[str, int]] = []
            for rng in reply or []:
                start, end = int(rng[0]), int(rng[1])
                master = rng[2]  # [ip, port, id?]
                maddr = (master[0].decode(), int(master[1]))
                if maddr not in masters:
                    masters.append(maddr)
                for slot in range(start, end + 1):
                    owner[slot] = maddr
            if not owner:
                last_err = ClusterDownError(f"{addr}: empty CLUSTER SLOTS")
                continue
            self._slot_owner = owner
            self._masters = masters
            self._dead_until.pop(addr, None)
            return
        raise ClusterDownError(f"no cluster node reachable: {last_err}")

    def _masters_locked(self) -> list[tuple[str, int]]:
        if not self._masters:
            self._refresh_slots()
        return list(self._masters)

    def masters(self) -> list[tuple[str, int]]:
        with self._lock:
            return self._masters_locked()

    # --- command execution --------------------------------------------------

    @staticmethod
    def _parse_redirect(
        msg: str, issuer: tuple[str, int] | None = None
    ) -> tuple[str, tuple[str, int]] | None:
        """``MOVED 3999 127.0.0.1:6381`` / ``ASK ...`` → (kind, addr).

        Redis emits ``MOVED 3999 :6381`` (empty host) when
        cluster-announce-ip is unset; standard cluster-client behavior is
        to reuse the host of the node that issued the redirect."""
        parts = msg.split()
        if len(parts) == 3 and parts[0] in ("MOVED", "ASK"):
            host, _, port = parts[2].rpartition(":")
            if not host and issuer is not None:
                host = issuer[0]
            return parts[0], (host, int(port))
        return None

    def execute(self, *args, key: str | bytes | None = None) -> Reply:
        """Route one command by ``key`` (defaults to args[1]) and follow
        MOVED/ASK redirects, refreshing the slot map on MOVED."""
        if key is None:
            if len(args) < 2:
                raise ValueError("cluster execute needs a routing key")
            key = args[1]
        with self._lock:
            if not self._slot_owner:
                self._refresh_slots()
            addr = self._slot_owner.get(key_slot(key))
            if addr is None:
                self._refresh_slots()
                addr = self._slot_owner.get(key_slot(key))
                if addr is None:
                    raise ClusterDownError(
                        f"slot {key_slot(key)} has no owner"
                    )
            asking = False
            for _ in range(self._MAX_REDIRECTS):
                conn = self._conn(addr)
                try:
                    if asking:
                        conn.execute("ASKING")
                    return conn.execute(*args)
                except RespError as e:
                    redirect = self._parse_redirect(str(e), issuer=addr)
                    if redirect is None:
                        raise
                    kind, addr = redirect
                    if kind == "MOVED":
                        # Permanent move: the whole map is stale.
                        self._refresh_slots()
                        asking = False
                    else:  # ASK: one-shot, no map update
                        asking = True
                except (OSError, ConnectionError):
                    # Node died: re-discover and retry on the new owner.
                    self._dead_until[addr] = (
                        time.monotonic() + self._DEAD_BACKOFF
                    )
                    self._refresh_slots()
                    naddr = self._slot_owner.get(key_slot(key))
                    if naddr is None or naddr == addr:
                        raise
                    addr = naddr
                    asking = False
            raise ClusterDownError(
                f"redirect loop for key {key!r} (> {self._MAX_REDIRECTS})"
            )

    # --- typed helpers (mirror RespClient) ----------------------------------

    def get(self, key: str) -> Optional[str]:
        v = self.execute("GET", key)
        return None if v is None else v.decode("utf-8")

    def set(self, key: str, val: str) -> None:
        self.execute("SET", key, val)

    def setnx(self, key: str, val: str) -> bool:
        return self.execute("SETNX", key, val) == 1

    def delete(self, key: str) -> int:
        return self.execute("DEL", key)

    def exists(self, key: str) -> bool:
        return self.execute("EXISTS", key) == 1

    def mget(self, keys: list[str]) -> list[Optional[str]]:
        """MGET split per slot (CROSSSLOT otherwise), order preserved."""
        if not keys:
            return []
        by_slot: dict[int, list[int]] = {}
        for i, k in enumerate(keys):
            by_slot.setdefault(key_slot(k), []).append(i)
        out: list[Optional[str]] = [None] * len(keys)
        for idxs in by_slot.values():
            vals = self.execute(
                "MGET", *[keys[i] for i in idxs], key=keys[idxs[0]]
            )
            for i, v in zip(idxs, vals):
                out[i] = None if v is None else v.decode("utf-8")
        return out

    def scan_keys(self, pattern: str) -> list[str]:
        """Full SCAN loop on EVERY master, merged (the keyspace is
        partitioned; reference List() runs the same loop through its
        cluster driver)."""
        out: list[str] = []
        with self._lock:
            for addr in self._masters_locked():
                conn = self._conn(addr)
                cursor = "0"
                while True:
                    reply = conn.execute(
                        "SCAN", cursor, "MATCH", pattern, "COUNT", "512"
                    )
                    cursor = reply[0].decode()
                    out.extend(k.decode("utf-8") for k in reply[1])
                    if cursor == "0":
                        break
        return sorted(set(out))

    def ping(self) -> bool:
        with self._lock:
            return all(
                self._conn(a).ping() for a in self._masters_locked()
            )

    def close(self) -> None:
        with self._lock:
            for c in self._conns.values():
                c.close()
            self._conns.clear()
            self._slot_owner.clear()
            self._masters.clear()

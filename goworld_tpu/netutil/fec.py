"""Forward error correction for the KCP transport (kcp-go FEC layout).

The reference's gate and client both construct KCP sessions with FEC
enabled — ``kcp.ListenWithOptions(addr, nil, 10, 3)`` /
``DialWithOptions`` (components/gate/GateService.go:134-135,
examples/test_client/ClientBot.go:153): every UDP datagram is wrapped in
a 6-byte FEC header and every 10 data datagrams are followed by 3 parity
datagrams, letting the receiver RECONSTRUCT lost datagrams without
waiting a retransmit round trip.

Wire layout (kcp-go's fec.go):

    [u32 LE seqid][u16 LE flag] + shard bytes
      flag: 0xf1 = data, 0xf2 = parity
      data shard bytes: [u16 LE size][payload]  (size counts itself +
      payload, so recovered shards know their true length; receivers
      feed payload = pkt[8:] straight to kcp.input on arrival)

seqids are consecutive across data AND parity: a group of (10+3) shards
occupies 13 consecutive seqids — 10 data then 3 parity. Parity shards
are a systematic Reed-Solomon code over GF(2^8) (poly 0x11d) of the data
shards zero-padded to the group's max length: any 10 of the 13 shards
reconstruct the group.

The RS matrix here is the classic systematic Vandermonde construction
(top square inverted so data rows are identity). No Go toolchain exists
in-image to bit-compare parity against kcp-go's matrix, so parity-shard
byte equality with kcp-go is unverified (documented); the header layout,
group geometry, and data-shard pass-through are pinned by vectors in
tests/test_kcp.py, and recovery is proven against induced datagram loss.
"""

from __future__ import annotations

import struct

TYPE_DATA = 0xF1
TYPE_PARITY = 0xF2
HEADER = struct.Struct("<IH")  # seqid, flag
HEADER_SIZE = 6
SIZE_OFF = HEADER_SIZE  # u16 LE size follows the header in data shards
DATA_OFF = HEADER_SIZE + 2

# Hostile-input ceiling on a single shard's bytes (VERDICT r5 missing test
# class: header/size fields from a hostile sender). Honest shards are
# bounded by the KCP mtu (~1400); RS reconstruction pads every shard of a
# group to the LONGEST member, so without a cap one forged jumbo datagram
# per group multiplies the GF(256) matmul work 40x. 16 KiB keeps any
# legitimate future mtu while bounding amplification.
MAX_SHARD = 16384

# Malformed datagrams dropped by the FEC layer, by reason (process-wide;
# per-connection labels would churn — same policy as net_packets_total).
from goworld_tpu import telemetry as _telemetry

_MALFORMED = _telemetry.counter(
    "fec_malformed_dropped_total",
    "Datagrams dropped by FEC decode: runt (shorter than the header), "
    "bad_flag (neither data nor parity), size_field (data shard whose "
    "declared u16 size exceeds its bytes), oversize (shard beyond "
    "MAX_SHARD).",
    ("reason",))


# --- GF(256) arithmetic (poly 0x11d, the RS standard kcp-go uses) ------------

_EXP = [0] * 512
_LOG = [0] * 256
_x = 1
for _i in range(255):
    _EXP[_i] = _x
    _LOG[_x] = _i
    _x <<= 1
    if _x & 0x100:
        _x ^= 0x11D
for _i in range(255, 512):
    _EXP[_i] = _EXP[_i - 255]


def _gmul(a: int, b: int) -> int:
    if a == 0 or b == 0:
        return 0
    return _EXP[_LOG[a] + _LOG[b]]


def _ginv(a: int) -> int:
    if a == 0:
        raise ZeroDivisionError("gf256 inverse of 0")
    return _EXP[255 - _LOG[a]]


# Byte-wise multiply-by-constant as a 256-entry translate table: Python's
# bytes.translate runs the hot loop in C.
_MUL_TABLE: dict[int, bytes] = {}


def _mul_table(c: int) -> bytes:
    t = _MUL_TABLE.get(c)
    if t is None:
        t = bytes(_gmul(c, x) for x in range(256))
        _MUL_TABLE[c] = t
    return t


def _mul_shard(c: int, shard: bytes) -> int:
    """c * shard as a big-int bitstring (XOR-accumulation friendly)."""
    if c == 0:
        return 0
    if c == 1:
        return int.from_bytes(shard, "big")
    return int.from_bytes(shard.translate(_mul_table(c)), "big")


def _matmul_rows(matrix_rows, shards: list[bytes], length: int):
    import os

    from goworld_tpu import native

    # Both implementations require equal-length shards; enforce here so
    # the C path (tail-pads/truncates) and the Python big-int path
    # (front-pads/overflows) can never silently diverge on malformed
    # input (code-review r5). Internal callers always ljust-pad.
    for s in shards:
        if len(s) != length:
            raise ValueError("rs shards must all equal the given length")
    if native.rs_matmul is not None and \
            os.environ.get("GWT_NO_NATIVE", "") != "1":
        # C hot loop (native/kcpcore.c rs_matmul): identical GF(256)
        # XOR-dot; the Python path below is the pinned reference the
        # parity test compares against.
        return native.rs_matmul(matrix_rows, shards, length)
    out = []
    for row in matrix_rows:
        acc = 0
        for c, shard in zip(row, shards):
            acc ^= _mul_shard(c, shard)
        out.append(acc.to_bytes(length, "big"))
    return out


def _invert(m: list[list[int]]) -> list[list[int]]:
    """Gauss-Jordan inverse over GF(256)."""
    n = len(m)
    a = [row[:] + [1 if i == j else 0 for j in range(n)]
         for i, row in enumerate(m)]
    for col in range(n):
        piv = next((r for r in range(col, n) if a[r][col]), None)
        if piv is None:
            raise ValueError("singular matrix")
        a[col], a[piv] = a[piv], a[col]
        inv = _ginv(a[col][col])
        a[col] = [_gmul(inv, v) for v in a[col]]
        for r in range(n):
            if r != col and a[r][col]:
                f = a[r][col]
                a[r] = [v ^ _gmul(f, a[col][c2])
                        for c2, v in enumerate(a[r])]
    return [row[n:] for row in a]


class ReedSolomon:
    """Systematic RS(data, parity) over GF(256): encode matrix rows are
    identity for data + parity rows from the inverted-top Vandermonde."""

    def __init__(self, data_shards: int, parity_shards: int) -> None:
        self.d = data_shards
        self.p = parity_shards
        n = data_shards + parity_shards
        vand = [[_EXP[(i * j) % 255] if i or j else 1
                 for j in range(data_shards)] for i in range(n)]
        # exp table power: element (i, j) = alpha^(i*j)
        top_inv = _invert([row[:] for row in vand[:data_shards]])
        self.matrix = [
            [self._dot(vand[r], top_inv, c) for c in range(data_shards)]
            for r in range(n)
        ]
        self.parity_rows = self.matrix[data_shards:]

    @staticmethod
    def _dot(row, m, col) -> int:
        acc = 0
        for k, v in enumerate(row):
            acc ^= _gmul(v, m[k][col])
        return acc

    def encode(self, data: list[bytes]) -> list[bytes]:
        """Parity shards for equal-length data shards."""
        assert len(data) == self.d
        length = len(data[0])
        return _matmul_rows(self.parity_rows, data, length)

    def reconstruct(self, shards: list[bytes | None]) -> list[bytes]:
        """Recover the DATA shards given any >= d of the d+p shards
        (None = missing). Returns the d data shards."""
        have = [(i, s) for i, s in enumerate(shards) if s is not None]
        if len(have) < self.d:
            raise ValueError("not enough shards")
        have = have[:self.d]
        length = len(have[0][1])
        sub = [self.matrix[i] for i, _ in have]
        inv = _invert(sub)
        return _matmul_rows(inv, [s for _, s in have], length)


_RS_CACHE: dict[tuple[int, int], ReedSolomon] = {}


def get_rs(data_shards: int, parity_shards: int) -> ReedSolomon:
    """The RS code is immutable per (d, p): build the matrix once per
    process, not once per encoder/decoder per connection (code-review
    r5 — the gate accepts thousands of clients)."""
    key = (data_shards, parity_shards)
    rs = _RS_CACHE.get(key)
    if rs is None:
        rs = _RS_CACHE[key] = ReedSolomon(data_shards, parity_shards)
    return rs


class FECEncoder:
    """Wrap outgoing datagrams as data shards; after every ``d`` of them
    emit ``p`` parity shards (consecutive seqids, kcp-go group layout)."""

    def __init__(self, data_shards: int = 10, parity_shards: int = 3) -> None:
        self.rs = get_rs(data_shards, parity_shards)
        # Wrap at a MULTIPLE of the group size (kcp-go's paws), never at
        # raw 2^32: 2^32 mod 13 != 0, so a raw wrap would permanently
        # misalign decoder groups (code-review r5).
        n = data_shards + parity_shards
        self._paws = (0xFFFFFFFF // n) * n
        self.next_seqid = 0
        self._group: list[bytes] = []  # shard bytes ([size][payload])

    def encode(self, payload: bytes) -> list[bytes]:
        """Returns the datagrams to transmit for this payload: the data
        shard, plus the group's parity shards when it completes."""
        shard = struct.pack("<H", len(payload) + 2) + payload
        out = [HEADER.pack(self.next_seqid, TYPE_DATA) + shard]
        self.next_seqid = (self.next_seqid + 1) % self._paws
        self._group.append(shard)
        if len(self._group) == self.rs.d:
            maxlen = max(len(s) for s in self._group)
            padded = [s.ljust(maxlen, b"\x00") for s in self._group]
            for par in self.rs.encode(padded):
                out.append(HEADER.pack(self.next_seqid, TYPE_PARITY) + par)
                self.next_seqid = (self.next_seqid + 1) % self._paws
            self._group.clear()
        return out


class FECDecoder:
    """Unwrap incoming datagrams; reconstruct lost data shards when a
    group reaches ``d`` received shards with data missing."""

    def __init__(self, data_shards: int = 10, parity_shards: int = 3,
                 window: int = 256) -> None:
        self.rs = get_rs(data_shards, parity_shards)
        self.n = data_shards + parity_shards
        import collections

        self.window = window  # remembered groups (anti-memory-growth)
        self._groups: dict[int, list[bytes | None]] = {}
        # FIFO-bounded: done-markers must not outlive the window (a late
        # duplicate for a forgotten group merely re-feeds kcp, which
        # dedups by sn).
        self._done: collections.OrderedDict = collections.OrderedDict()

    def decode(self, pkt: bytes) -> list[bytes]:
        """Feed one received datagram; returns kcp-ready payloads (the
        packet's own payload if it is a data shard, plus any payloads
        recovered by FEC reconstruction).

        Hostile header/size fields are bounds-checked BEFORE any slicing
        or group bookkeeping and dropped with a per-reason count on
        ``fec_malformed_dropped_total`` — a forged size/length must never
        reach the RS padding math or kcp (VERDICT r5)."""
        if len(pkt) < DATA_OFF:
            _MALFORMED.labels("runt").inc()
            return []
        seqid, flag = HEADER.unpack_from(pkt)
        if flag not in (TYPE_DATA, TYPE_PARITY):
            _MALFORMED.labels("bad_flag").inc()
            return []
        if len(pkt) - HEADER_SIZE > MAX_SHARD:
            _MALFORMED.labels("oversize").inc()
            return []
        out = []
        if flag == TYPE_DATA:
            # The declared size counts itself + payload; an honest sender
            # always writes exactly len(shard). Larger means a forged
            # field (would mis-trim peers' reconstructions), smaller than
            # the 2-byte prefix is nonsense — drop both.
            (size,) = struct.unpack_from("<H", pkt, SIZE_OFF)
            if size < 2 or size > len(pkt) - HEADER_SIZE:
                _MALFORMED.labels("size_field").inc()
                return []
            out.append(pkt[DATA_OFF:])
        group = seqid - (seqid % self.n)
        idx = seqid % self.n
        if self._done.get(group):
            return out
        entry = self._groups.get(group)
        if entry is None:
            # [shards, have, data_have]: counters tracked on insert, not
            # recounted per datagram (per-datagram hot path).
            entry = self._groups.setdefault(
                group, [[None] * self.n, 0, 0])
            # Bound memory: evict the oldest-INSERTED group beyond the
            # window (dict insertion order) — NOT min(): after the
            # encoder's seqid wrap, new groups have small ids and min()
            # would evict every new group on arrival, silently killing
            # recovery for the rest of the connection (code-review r5).
            while len(self._groups) > self.window:
                old = next(iter(self._groups))
                self._groups.pop(old, None)
                self._done.pop(old, None)
        shards = entry[0]
        if shards[idx] is None:
            shards[idx] = pkt[HEADER_SIZE:]
            entry[1] += 1
            if idx < self.rs.d:
                entry[2] += 1
        have, data_have = entry[1], entry[2]
        if have >= self.rs.d and data_have < self.rs.d:
            maxlen = max(len(s) for s in shards if s is not None)
            padded = [s.ljust(maxlen, b"\x00") if s is not None else None
                      for s in shards]
            try:
                data = self.rs.reconstruct(padded)
            except ValueError:
                return out
            for i in range(self.rs.d):
                if shards[i] is None:
                    (size,) = struct.unpack_from("<H", data[i])
                    if 2 <= size <= len(data[i]):
                        out.append(data[i][2:size])
            self._mark_done(group)
        elif data_have == self.rs.d:
            self._mark_done(group)
        return out

    def _mark_done(self, group: int) -> None:
        self._groups.pop(group, None)
        self._done[group] = True
        while len(self._done) > self.window:
            self._done.popitem(last=False)

"""From-scratch RESP2 (REdis Serialization Protocol) client.

Closes the reference's network-DB gap (VERDICT r2 missing #4) without any
driver dependency: the reference ships redigo-backed storage/kvdb backends
(``engine/kvdb/backend/kvdb_redis.go:11-69``,
``engine/storage/backend/redis/entity_storage_redis.go``); this is the
in-repo equivalent speaking the wire protocol directly.

Protocol (RESP2): requests are arrays of bulk strings
``*N\\r\\n$len\\r\\n<arg>\\r\\n...``; replies are ``+simple``, ``-error``,
``:integer``, ``$bulk`` (-1 = nil) or ``*array`` (recursive, -1 = nil).

The client is a blocking socket with a lock — storage/kvdb backends run on
serial worker threads (storage/__init__.py), so latency hiding happens at
the job-queue layer, exactly like the reference's storageRoutine. One
transparent reconnect per command covers idle-timeout disconnects.
"""

from __future__ import annotations

import socket
import threading
from typing import Optional, Union

Reply = Union[None, int, bytes, list]


class RespError(Exception):
    """Server-reported error reply (``-ERR ...``)."""


class RespClient:
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 6379,
        db: int = 0,
        password: Optional[str] = None,
        timeout: float = 10.0,
    ) -> None:
        self.host = host
        self.port = port
        self.db = db
        self.password = password
        self.timeout = timeout
        self._sock: Optional[socket.socket] = None
        self._buf = b""
        self._lock = threading.Lock()

    # --- connection ---------------------------------------------------------

    def _connect(self) -> None:
        self.close()
        s = socket.create_connection((self.host, self.port), self.timeout)
        s.settimeout(self.timeout)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = s
        self._buf = b""
        if self.password:
            self._roundtrip(("AUTH", self.password))
        if self.db:
            self._roundtrip(("SELECT", str(self.db)))

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    # --- protocol -----------------------------------------------------------

    @staticmethod
    def _serialize(args: tuple) -> bytes:
        parts = [b"*%d\r\n" % len(args)]
        for a in args:
            b = a if isinstance(a, bytes) else str(a).encode("utf-8")
            parts.append(b"$%d\r\n%s\r\n" % (len(b), b))
        return b"".join(parts)

    def _read_line(self) -> bytes:
        while b"\r\n" not in self._buf:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise ConnectionError("resp: connection closed")
            self._buf += chunk
        line, self._buf = self._buf.split(b"\r\n", 1)
        return line

    def _read_exact(self, n: int) -> bytes:
        while len(self._buf) < n:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise ConnectionError("resp: connection closed")
            self._buf += chunk
        data, self._buf = self._buf[:n], self._buf[n:]
        return data

    def _read_reply(self) -> Reply:
        line = self._read_line()
        kind, rest = line[:1], line[1:]
        if kind == b"+":
            return rest
        if kind == b"-":
            raise RespError(rest.decode("utf-8", "replace"))
        if kind == b":":
            return int(rest)
        if kind == b"$":
            n = int(rest)
            if n < 0:
                return None
            data = self._read_exact(n)
            self._read_exact(2)  # trailing \r\n
            return data
        if kind == b"*":
            n = int(rest)
            if n < 0:
                return None
            return [self._read_reply() for _ in range(n)]
        raise RespError(f"resp: bad reply type {line!r}")

    def _roundtrip(self, args: tuple) -> Reply:
        self._sock.sendall(self._serialize(args))
        return self._read_reply()

    # --- public -------------------------------------------------------------

    def execute(self, *args) -> Reply:
        """Send one command; RespError for server errors, one transparent
        reconnect for transport errors (auto-reopen, kvdb.go:40-207)."""
        with self._lock:
            if self._sock is None:
                self._connect()
            try:
                return self._roundtrip(args)
            except (OSError, ConnectionError):
                self._connect()
                return self._roundtrip(args)

    def execute_once(self, *args) -> Reply:
        """Single attempt, NO reconnect-retry: for liveness probes whose
        worst case must be bounded by one timeout, not two (the transparent
        retry in :meth:`execute` would double a dead node's cost)."""
        with self._lock:
            if self._sock is None:
                self._connect()
            return self._roundtrip(args)

    # Typed helpers (str in/out; values are UTF-8).

    def get(self, key: str) -> Optional[str]:
        v = self.execute("GET", key)
        return None if v is None else v.decode("utf-8")

    def set(self, key: str, val: str) -> None:
        self.execute("SET", key, val)

    def setnx(self, key: str, val: str) -> bool:
        return self.execute("SETNX", key, val) == 1

    def delete(self, key: str) -> int:
        return self.execute("DEL", key)

    def exists(self, key: str) -> bool:
        return self.execute("EXISTS", key) == 1

    def scan_keys(self, pattern: str) -> list[str]:
        """Full SCAN cursor loop with MATCH (never KEYS: SCAN is the
        non-blocking form a live server tolerates)."""
        out: list[str] = []
        cursor = "0"
        while True:
            reply = self.execute("SCAN", cursor, "MATCH", pattern, "COUNT", "512")
            cursor = reply[0].decode()
            out.extend(k.decode("utf-8") for k in reply[1])
            if cursor == "0":
                return out

    def mget(self, keys: list[str]) -> list[Optional[str]]:
        if not keys:
            return []
        vals = self.execute("MGET", *keys)
        return [None if v is None else v.decode("utf-8") for v in vals]

    def ping(self) -> bool:
        return self.execute("PING") in (b"PONG", b"pong")


def parse_redis_url(url: str) -> dict:
    """``redis://[:password@]host[:port][/db]`` → RespClient kwargs."""
    rest = url
    if "://" in rest:
        scheme, rest = rest.split("://", 1)
        if scheme != "redis":
            raise ValueError(f"unsupported url scheme {scheme!r}")
    password = None
    if "@" in rest:
        auth, rest = rest.rsplit("@", 1)
        password = auth.lstrip(":") or None
    db = 0
    if "/" in rest:
        rest, dbs = rest.split("/", 1)
        if dbs:
            db = int(dbs)
    host, _, port = rest.partition(":")
    return {
        "host": host or "127.0.0.1",
        "port": int(port) if port else 6379,
        "db": db,
        "password": password,
    }

"""msgpack codec helpers.

Reference parity: ``engine/netutil/MessagePackMsgPacker.go:13-29`` — all
structured payloads (RPC args, attrs, migrate data) travel as msgpack.
"""

from __future__ import annotations

import msgpack


def pack_msg(obj) -> bytes:
    return msgpack.packb(obj, use_bin_type=True)


def unpack_msg(data: bytes):
    return msgpack.unpackb(data, raw=False, strict_map_key=False)

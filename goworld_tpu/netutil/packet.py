"""Binary packet buffer with typed append/read codecs.

Reference parity: ``engine/netutil/Packet.go:83-89,210-503`` — a growable
payload buffer written with AppendUint16/AppendFloat32/AppendEntityID/
AppendVarStr/AppendData(msgpack)/AppendArgs and read back with the matching
Read* calls. The reference pools packets for GC pressure; in Python we rely
on bytearray and keep the same API shape (the hot path — position syncs —
batches many records into one packet exactly like the reference,
proto.go:135-139).

All integers little-endian, matching the reference's PACKET_ENDIAN.
"""

from __future__ import annotations

import struct

import msgpack

from goworld_tpu import consts
from goworld_tpu.common import ENTITYID_LENGTH

_U16 = struct.Struct("<H")
_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")
_F32 = struct.Struct("<f")
_F64 = struct.Struct("<d")


class PacketReadError(ValueError, IndexError):
    """A read past the end of a packet payload (truncated/hostile frame).

    Subclasses BOTH ValueError (the parser contract every wire module
    follows — gwlint R3, and the schema fuzz in tests/test_modelcheck.py:
    short or mutated buffers raise ValueError, never a bare struct.error
    or IndexError) and IndexError (what this seam raised historically, so
    existing catchers keep working)."""


class Packet:
    """Append-only write + cursor read packet payload.

    A packet constructed from ``bytes`` keeps that object as its buffer
    without copying: the dominant packet population — received frames that
    are only read and/or forwarded verbatim (the dispatcher's entire
    routing plane) — then pays ZERO payload copies end to end, because
    :attr:`payload` hands the same immutable object back out. The first
    append (or trailer strip) transparently converts to a private
    bytearray, so writers keep the exact legacy semantics."""

    __slots__ = ("_buf", "_rpos", "trace")

    def __init__(self, payload: bytes | bytearray | None = None) -> None:
        if payload is None or not len(payload):
            self._buf: bytes | bytearray = bytearray()
        elif type(payload) is bytes:
            self._buf = payload  # zero-copy read/forward fast path
        else:
            self._buf = bytearray(payload)
        self._rpos = 0
        # TraceContext attached by the recv seam when the wire msgtype
        # carried the tracing-trailer flag (telemetry/tracing.py); None
        # for the overwhelming majority of packets.
        self.trace = None

    def _wbuf(self) -> bytearray:
        """The mutable buffer, converting a shared read-only one on the
        first write (copy-on-write seam for the zero-copy constructor)."""
        if type(self._buf) is not bytearray:
            self._buf = bytearray(self._buf)
        return self._buf

    def pop_tail(self, n: int) -> bytes:
        """Remove and return the last ``n`` payload bytes (trailer strip)."""
        buf = self._wbuf()
        tail = bytes(buf[-n:])
        del buf[-n:]
        return tail

    # --- lifecycle ---------------------------------------------------------

    @property
    def payload(self) -> bytes:
        buf = self._buf
        return buf if type(buf) is bytes else bytes(buf)

    def payload_len(self) -> int:
        return len(self._buf)

    def unread_len(self) -> int:
        return len(self._buf) - self._rpos

    def set_read_pos(self, pos: int) -> None:
        self._rpos = pos

    # --- append ------------------------------------------------------------

    def append_byte(self, v: int) -> "Packet":
        self._wbuf().append(v & 0xFF)
        return self

    def append_bool(self, v: bool) -> "Packet":
        return self.append_byte(1 if v else 0)

    def append_uint16(self, v: int) -> "Packet":
        self._wbuf().extend(_U16.pack(v))
        return self

    def append_uint32(self, v: int) -> "Packet":
        self._wbuf().extend(_U32.pack(v))
        return self

    def append_uint64(self, v: int) -> "Packet":
        self._wbuf().extend(_U64.pack(v))
        return self

    def append_float32(self, v: float) -> "Packet":
        self._wbuf().extend(_F32.pack(v))
        return self

    def append_float64(self, v: float) -> "Packet":
        self._wbuf().extend(_F64.pack(v))
        return self

    def append_bytes(self, v: bytes) -> "Packet":
        self._wbuf().extend(v)
        return self

    def append_varbytes(self, v: bytes) -> "Packet":
        self.append_uint32(len(v))
        self._wbuf().extend(v)
        return self

    def append_varstr(self, v: str) -> "Packet":
        return self.append_varbytes(v.encode("utf-8"))

    def append_entity_id(self, eid: str) -> "Packet":
        b = eid.encode("ascii")
        if len(b) != ENTITYID_LENGTH:
            raise ValueError(f"bad entity id {eid!r}")
        self._wbuf().extend(b)
        return self

    def append_client_id(self, cid: str) -> "Packet":
        return self.append_entity_id(cid)

    def append_data(self, obj) -> "Packet":
        """Append a msgpack-encoded object (reference AppendData,
        Packet.go:419-437)."""
        return self.append_varbytes(
            msgpack.packb(obj, use_bin_type=True)
        )

    def append_args(self, args: tuple | list) -> "Packet":
        """Append RPC args: u16 count + one msgpack blob each
        (reference AppendArgs)."""
        self.append_uint16(len(args))
        for a in args:
            self.append_data(a)
        return self

    # --- read --------------------------------------------------------------

    def _take(self, n: int) -> memoryview:
        if self._rpos + n > len(self._buf):
            raise PacketReadError("packet read overflow")
        mv = memoryview(self._buf)[self._rpos : self._rpos + n]
        self._rpos += n
        return mv

    def read_byte(self) -> int:
        return self._take(1)[0]

    def read_bool(self) -> bool:
        return self.read_byte() != 0

    def read_uint16(self) -> int:
        return _U16.unpack(self._take(2))[0]

    def read_uint32(self) -> int:
        return _U32.unpack(self._take(4))[0]

    def read_uint64(self) -> int:
        return _U64.unpack(self._take(8))[0]

    def read_float32(self) -> float:
        return _F32.unpack(self._take(4))[0]

    def read_float64(self) -> float:
        return _F64.unpack(self._take(8))[0]

    def read_bytes(self, n: int) -> bytes:
        return bytes(self._take(n))

    def read_varbytes(self) -> bytes:
        n = self.read_uint32()
        if n > consts.MAX_PACKET_SIZE:
            raise ValueError(f"varbytes length {n} exceeds max packet size")
        return self.read_bytes(n)

    def read_varstr(self) -> str:
        return self.read_varbytes().decode("utf-8")

    def read_entity_id(self) -> str:
        return bytes(self._take(ENTITYID_LENGTH)).decode("ascii")

    def read_client_id(self) -> str:
        return self.read_entity_id()

    def read_data(self):
        blob = self.read_varbytes()
        try:
            return msgpack.unpackb(blob, raw=False)
        except ValueError:
            raise
        except Exception as exc:
            # msgpack's truncation/garbage errors are mostly ValueError
            # subclasses already; normalize the stragglers (OutOfData,
            # BufferFull derive from bare UnpackException) so every wire
            # parser keeps the raise-ValueError contract.
            raise PacketReadError(f"malformed msgpack payload: {exc}") from exc

    def read_args(self) -> list:
        n = self.read_uint16()
        return [self.read_data() for _ in range(n)]

    def read_rest(self) -> bytes:
        return self.read_bytes(self.unread_len())

"""From-scratch MySQL client-protocol implementation (text protocol).

Fills the reference's mysql backend slots
(``engine/storage/backend/mysql/entity_storage_mysql.go``,
``engine/kvdb/backend/kvdb_mysql.go``) without a driver. Implements the
classic wire protocol: [3-byte length][seq] framing, HandshakeV10 →
HandshakeResponse41 with ``mysql_native_password`` auth (auth-switch
handled; servers defaulting to caching_sha2_password should create the
user WITH mysql_native_password, the usual arrangement for thin clients),
then COM_QUERY with text result sets.

Like the RESP2/OP_MSG clients: blocking socket + lock, driven from the
serial storage/kvdb worker threads; one transparent reconnect.
"""

from __future__ import annotations

import hashlib
import socket
import struct
import threading
from typing import Optional

_CLIENT_LONG_PASSWORD = 0x1
_CLIENT_PROTOCOL_41 = 0x200
_CLIENT_SECURE_CONNECTION = 0x8000
_CLIENT_PLUGIN_AUTH = 0x80000
_CLIENT_CONNECT_WITH_DB = 0x8

_COM_QUIT = 0x01
_COM_QUERY = 0x03
_COM_PING = 0x0E


class MySQLError(Exception):
    def __init__(self, msg: str, code: int = 0) -> None:
        super().__init__(msg)
        self.code = code


def _native_password_token(password: str, scramble: bytes) -> bytes:
    """SHA1(pass) XOR SHA1(scramble + SHA1(SHA1(pass))) — the
    mysql_native_password proof."""
    if not password:
        return b""
    h1 = hashlib.sha1(password.encode("utf-8")).digest()
    h2 = hashlib.sha1(h1).digest()
    h3 = hashlib.sha1(scramble + h2).digest()
    return bytes(a ^ b for a, b in zip(h1, h3))


def _read_lenenc(data: bytes, off: int) -> tuple[Optional[int], int]:
    if off >= len(data):
        raise ValueError("mysql: truncated length-encoded integer")
    first = data[off]
    if first < 0xFB:
        return first, off + 1
    if first == 0xFB:  # NULL (in row context)
        return None, off + 1
    width = {0xFC: 2, 0xFD: 3}.get(first, 8)
    if off + 1 + width > len(data):
        raise ValueError("mysql: truncated length-encoded integer")
    if first == 0xFC:
        return struct.unpack_from("<H", data, off + 1)[0], off + 3
    if first == 0xFD:
        return int.from_bytes(data[off + 1:off + 4], "little"), off + 4
    return struct.unpack_from("<Q", data, off + 1)[0], off + 9


def escape(val: str) -> str:
    """SQL string-literal escaping for the text protocol."""
    out = val.replace("\\", "\\\\").replace("'", "\\'")
    return out.replace("\x00", "\\0").replace("\n", "\\n").replace("\r", "\\r")


class MySQLClient:
    def __init__(self, host: str = "127.0.0.1", port: int = 3306,
                 user: str = "root", password: str = "",
                 database: str = "", timeout: float = 10.0) -> None:
        self.host = host
        self.port = port
        self.user = user
        self.password = password
        self.database = database
        self.timeout = timeout
        self._sock: Optional[socket.socket] = None
        self._seq = 0
        self._lock = threading.Lock()

    # --- framing ------------------------------------------------------------

    def _read_exact(self, n: int) -> bytes:
        bufs = []
        while n:
            b = self._sock.recv(n)
            if not b:
                raise ConnectionError("mysql: connection closed")
            bufs.append(b)
            n -= len(b)
        return b"".join(bufs)

    def _read_packet(self) -> bytes:
        hdr = self._read_exact(4)
        length = int.from_bytes(hdr[:3], "little")
        self._seq = hdr[3] + 1
        return self._read_exact(length)

    def _send_packet(self, payload: bytes) -> None:
        self._sock.sendall(
            len(payload).to_bytes(3, "little") + bytes([self._seq & 0xFF])
            + payload
        )
        self._seq += 1

    # --- connect + auth -----------------------------------------------------

    def _connect(self) -> None:
        self.close()
        s = socket.create_connection((self.host, self.port), self.timeout)
        s.settimeout(self.timeout)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = s
        self._seq = 0
        greeting = self._read_packet()
        if greeting[0] == 0xFF:
            raise MySQLError(greeting[9:].decode("utf-8", "replace"))
        if greeting[0] != 10:
            raise MySQLError(f"unsupported protocol {greeting[0]}")
        off = 1
        off = greeting.index(b"\x00", off) + 1  # server version
        off += 4  # thread id
        scramble = greeting[off:off + 8]
        off += 8 + 1  # filler
        off += 2 + 1 + 2 + 2  # caps-low, charset, status, caps-high
        auth_len = greeting[off]
        off += 1 + 10  # reserved
        scramble += greeting[off:off + max(13, auth_len - 8)][:12]
        caps = (_CLIENT_LONG_PASSWORD | _CLIENT_PROTOCOL_41
                | _CLIENT_SECURE_CONNECTION | _CLIENT_PLUGIN_AUTH)
        if self.database:
            caps |= _CLIENT_CONNECT_WITH_DB
        token = _native_password_token(self.password, scramble)
        resp = struct.pack("<IIB23x", caps, 1 << 24, 33)  # utf8_general_ci
        resp += self.user.encode("utf-8") + b"\x00"
        resp += bytes([len(token)]) + token
        if self.database:
            resp += self.database.encode("utf-8") + b"\x00"
        resp += b"mysql_native_password\x00"
        self._send_packet(resp)
        reply = self._read_packet()
        if reply[0] == 0xFE:  # auth switch request
            plugin_end = reply.index(b"\x00", 1)
            new_scramble = reply[plugin_end + 1:].rstrip(b"\x00")
            self._send_packet(
                _native_password_token(self.password, new_scramble)
            )
            reply = self._read_packet()
        if reply[0] == 0xFF:
            code = struct.unpack_from("<H", reply, 1)[0]
            raise MySQLError(reply[9:].decode("utf-8", "replace"), code)

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._seq = 0
                self._send_packet(bytes([_COM_QUIT]))
            except OSError:
                pass
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    # --- queries ------------------------------------------------------------

    def _query_once(self, sql: str) -> tuple[int, list[list[Optional[str]]]]:
        self._seq = 0
        self._send_packet(bytes([_COM_QUERY]) + sql.encode("utf-8"))
        first = self._read_packet()
        if first[0] == 0xFF:
            code = struct.unpack_from("<H", first, 1)[0]
            raise MySQLError(first[9:].decode("utf-8", "replace"), code)
        if first[0] == 0x00:  # OK packet: lenenc affected_rows follows
            affected, _ = _read_lenenc(first, 1)
            return int(affected or 0), []
        ncols, _ = _read_lenenc(first, 0)
        for _ in range(ncols):  # column definitions (ignored)
            self._read_packet()
        pkt = self._read_packet()
        if pkt[0] == 0xFE and len(pkt) < 9:  # EOF after columns
            pkt = self._read_packet()
        rows: list[list[Optional[str]]] = []
        while not (pkt[0] == 0xFE and len(pkt) < 9):
            if pkt[0] == 0xFF:
                raise MySQLError(pkt[9:].decode("utf-8", "replace"))
            row: list[Optional[str]] = []
            off = 0
            while off < len(pkt):
                n, off = _read_lenenc(pkt, off)
                if n is None:
                    row.append(None)
                else:
                    row.append(pkt[off:off + n].decode("utf-8"))
                    off += n
            rows.append(row)
            pkt = self._read_packet()
        return 0, rows

    def query(self, sql: str) -> list[list[Optional[str]]]:
        """Run a statement, returning rows (SELECT) or [] (DML); see
        :meth:`execute` for affected-row counts."""
        return self._with_reconnect(sql)[1]

    def execute(self, sql: str) -> int:
        """Run a statement, returning the affected-row count."""
        return self._with_reconnect(sql)[0]

    def _with_reconnect(self, sql: str):
        with self._lock:
            if self._sock is None:
                self._connect()
            try:
                return self._query_once(sql)
            except (OSError, ConnectionError):
                self._connect()
                return self._query_once(sql)

    def ping(self) -> bool:
        with self._lock:
            if self._sock is None:
                self._connect()
            self._seq = 0
            self._send_packet(bytes([_COM_PING]))
            return self._read_packet()[0] == 0x00


def parse_mysql_url(url: str) -> dict:
    """``mysql://[user[:password]@]host[:port][/database]``."""
    rest = url
    if "://" in rest:
        scheme, rest = rest.split("://", 1)
        if scheme != "mysql":
            raise ValueError(f"unsupported url scheme {scheme!r}")
    user, password = "root", ""
    if "@" in rest:
        auth, rest = rest.rsplit("@", 1)
        user, _, password = auth.partition(":")
    database = ""
    if "/" in rest:
        rest, database = rest.split("/", 1)
    host, _, port = rest.partition(":")
    return {
        "host": host or "127.0.0.1",
        "port": int(port) if port else 3306,
        "user": user or "root",
        "password": password,
        "database": database,
    }

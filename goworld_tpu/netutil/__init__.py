"""Framed packet transport.

Reference parity: ``engine/netutil`` — 4-byte little-endian length prefix +
payload (PacketConnection.go:50-61), ``Packet`` append/read codecs
(Packet.go:210-503), msgpack for structured fields (MsgPacker.go:3-12), and
``ServeTCPForever`` (TCPServer.go:22). Async IO replaces goroutine-per-conn.
"""

from goworld_tpu.netutil.packet import Packet
from goworld_tpu.netutil.packet_conn import PacketConnection, ConnectionClosed
from goworld_tpu.netutil.msgpacker import pack_msg, unpack_msg
from goworld_tpu.netutil.tcp import serve_tcp_forever, connect_tcp

__all__ = [
    "Packet",
    "PacketConnection",
    "ConnectionClosed",
    "pack_msg",
    "unpack_msg",
    "serve_tcp_forever",
    "connect_tcp",
]

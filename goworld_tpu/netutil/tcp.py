"""TCP server/client helpers.

Reference parity: ``engine/netutil/TCPServer.go:22-65`` (ServeTCPForever with
retry) and the dial side used by dispatcherclient. Socket buffer sizes follow
consts (reference consts.go:14-61).
"""

from __future__ import annotations

import asyncio
import socket
from typing import Awaitable, Callable

from goworld_tpu import consts
from goworld_tpu.utils import gwlog

ConnHandler = Callable[[asyncio.StreamReader, asyncio.StreamWriter], Awaitable[None]]


def _tune_socket(sock: socket.socket) -> None:
    try:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        sock.setsockopt(
            socket.SOL_SOCKET, socket.SO_SNDBUF, consts.CONNECTION_WRITE_BUFFER_SIZE
        )
        sock.setsockopt(
            socket.SOL_SOCKET, socket.SO_RCVBUF, consts.CONNECTION_READ_BUFFER_SIZE
        )
    except OSError:
        pass


async def serve_tcp_forever(
    host: str, port: int, handler: ConnHandler
) -> asyncio.AbstractServer:
    """Start a TCP server; each connection runs ``handler`` in its own task
    (the asyncio analog of goroutine-per-conn, TCPServer.go:49-64)."""

    async def wrapped(reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        sock = writer.get_extra_info("socket")
        if sock is not None:
            _tune_socket(sock)
        try:
            await handler(reader, writer)
        except Exception as e:  # noqa: BLE001 - connection handlers must not kill the server
            gwlog.errorf("connection handler error from %s: %s",
                         writer.get_extra_info("peername"), e)
        finally:
            try:
                writer.close()
            except Exception:
                pass

    server = await asyncio.start_server(wrapped, host, port)
    return server


async def connect_tcp(
    host: str, port: int
) -> tuple[asyncio.StreamReader, asyncio.StreamWriter]:
    reader, writer = await asyncio.open_connection(host, port)
    sock = writer.get_extra_info("socket")
    if sock is not None:
        _tune_socket(sock)
    return reader, writer

"""Reliable-UDP client transport, from scratch (the reference's KCP slot).

Reference parity: the gate serves KCP (reliable UDP) on the same address as
TCP with turbo-mode tuning (``components/gate/GateService.go:134-165``,
``engine/consts/consts.go:122-131`` via xtaci/kcp-go). No ARQ library
exists in this image, so this is an in-repo equivalent (SURVEY.md §2.4
rule): a conversation-id + seq/ack + retransmit-timer protocol carrying
the same framed packet stream as TCP.

Wire format (one datagram per segment, 13-byte header):

    [u32 conv][u8 cmd][u32 seq][u32 ack]  + payload (DATA only)

- ``conv``: connection id, chosen by the client (kcp conversation id).
- DATA(1): ``seq`` = segment number; payload = next MSS-sized slice of the
  byte stream. The receiver reassembles in segment order and parses the
  TCP framing ([u32 len][u16 msgtype][payload]) from the ordered stream.
- ACK(2): ``ack`` = cumulative next-expected segment; ``seq`` = the
  segment that triggered this ack (a 1-slot SACK so the sender can drop
  out-of-order-received segments immediately).
- FIN(3): graceful close.

Loss recovery (KCP turbo parity, ``engine/consts/consts.go:122-131``):

- **Adaptive RTO** (Jacobson/Karels with Karn's rule): RTT is sampled from
  acks of segments transmitted exactly once; ``rto = srtt +
  max(tick, 4*rttvar)``, clamped to [30 ms, 1 s] (the 30 ms floor is KCP's
  nodelay minimum). Timeout backoff is the nodelay ×1.5, not the vanilla
  ×2 (KCP_NO_DELAY=1).
- **Fast resend** (KCP_ENABLE_FAST_RESEND=2): every ack counts, for each
  older in-flight segment, how many times it was "skipped"; at 2 skips the
  segment retransmits immediately without waiting its RTO.
- **Congestion control is OFF by default** (KCP_DISABLE_CONGESTION_CONTROL
  = 1, turbo nc mode): the window is the fixed SEND_WINDOW. Passing
  ``congestion=True`` enables slow-start/AIMD for adverse networks.

A 10 ms tick (turbo interval) drives timeouts. In-flight is windowed;
senders buffer beyond the window and evict the connection if the backlog
exceeds MAX_BACKLOG (the WS transport's stalled-client policy).
``loss_simulation`` drops outgoing datagrams randomly — the e2e tests'
induced-loss knob.
"""

from __future__ import annotations

import asyncio
import random
import struct
from typing import Callable, Optional

from goworld_tpu import consts, native
from goworld_tpu.netutil.packet import Packet
from goworld_tpu.netutil.packet_conn import (
    _COMPRESS_THRESHOLD, ConnectionClosed,
)

_HDR = struct.Struct("<IBII")
CMD_DATA = 1
CMD_ACK = 2
CMD_FIN = 3

MSS = 1200  # payload bytes per segment (under common 1500 MTU)
TICK_INTERVAL = 0.01  # 10 ms retransmit cadence (KCP turbo interval)
RTO_INIT = 0.05  # before the first RTT sample lands
RTO_MIN = 0.03  # KCP nodelay floor
RTO_MAX = 1.0
RTO_BACKOFF = 1.5  # nodelay-mode timeout growth (vanilla KCP doubles)
FAST_RESEND = 2  # KCP_ENABLE_FAST_RESEND: skipped-by-2-acks → retransmit
SEND_WINDOW = 256  # in-flight segments (flow-control cap)
MAX_BACKLOG = 65536  # queued segments beyond the window → evict
NO_SACK = 0xFFFFFFFF


class RUDPEndpoint:
    """One reliable conversation over a datagram ``transmit`` callable."""

    def __init__(
        self,
        conv: int,
        transmit: Callable[[bytes], None],
        on_close: Optional[Callable[["RUDPEndpoint"], None]] = None,
        congestion: bool = False,  # default = KCP turbo nc=1 (off)
    ) -> None:
        self.conv = conv
        self._transmit = transmit
        self._on_close = on_close
        self.closed = False
        self.loss_simulation = 0.0  # outgoing drop probability (tests)
        self._rng = random.Random(conv)
        # send side: seq → [bytes, deadline, rto, sent_time, xmits, fastack]
        self._snd_nxt = 0
        self._unacked: dict[int, list] = {}
        self._backlog: list[tuple[int, bytes]] = []  # beyond the window
        # RTT estimator (Jacobson/Karels; Karn's rule via xmits == 1)
        self.srtt = 0.0
        self.rttvar = 0.0
        self.rto = RTO_INIT
        # congestion (disabled by default, KCP_DISABLE_CONGESTION_CONTROL=1)
        self._congestion = congestion
        self._cwnd = 2.0
        self._ssthresh = float(SEND_WINDOW)
        self.fast_resends = 0  # diagnostics
        self.timeout_resends = 0
        # recv side
        self._rcv_nxt = 0
        self._ooo: dict[int, bytes] = {}  # out-of-order segments
        self._instream = bytearray()  # ordered byte stream, unparsed
        self._packets: asyncio.Queue = asyncio.Queue()  # parsed (msgtype, Packet)
        self._ticker = asyncio.get_running_loop().create_task(self._tick_loop())
        self.dropped = 0

    # --- datagram out -------------------------------------------------------

    def _raw_send(self, data: bytes) -> None:
        if self.loss_simulation and self._rng.random() < self.loss_simulation:
            return  # simulated network loss
        try:
            self._transmit(data)
        except OSError:
            pass  # datagram sends are best-effort; ARQ recovers

    def _send_segment(self, seq: int, payload: bytes) -> None:
        self._raw_send(
            _HDR.pack(self.conv, CMD_DATA, seq, self._rcv_nxt) + payload
        )

    def _send_ack(self, sacked: int) -> None:
        self._raw_send(_HDR.pack(self.conv, CMD_ACK, sacked, self._rcv_nxt))

    # --- public send --------------------------------------------------------

    def _window(self) -> int:
        """Effective in-flight cap: flow window, AND the congestion window
        when congestion control is on (off by default, turbo nc mode)."""
        if not self._congestion:
            return SEND_WINDOW
        return max(1, min(SEND_WINDOW, int(self._cwnd)))

    def send_bytes(self, data: bytes) -> None:
        """Queue bytes onto the reliable stream (split into MSS segments)."""
        if self.closed:
            self.dropped += 1
            return
        now = asyncio.get_running_loop().time()
        for off in range(0, len(data), MSS):
            seg = bytes(data[off:off + MSS])
            seq = self._snd_nxt
            self._snd_nxt += 1
            if len(self._unacked) < self._window():
                self._unacked[seq] = [seg, now + self.rto, self.rto, now, 1, 0]
                self._send_segment(seq, seg)
            else:
                self._backlog.append((seq, seg))
                if len(self._backlog) > MAX_BACKLOG:
                    self.close()  # stalled peer: evict
                    return

    # --- datagram in --------------------------------------------------------

    def on_datagram(self, cmd: int, seq: int, ack: int, payload: bytes) -> None:
        if self.closed:
            return
        # Every packet carries the peer's cumulative ack.
        self._apply_ack(ack)
        if cmd == CMD_DATA:
            if seq >= self._rcv_nxt and seq not in self._ooo:
                self._ooo[seq] = payload
                while self._rcv_nxt in self._ooo:
                    self._instream += self._ooo.pop(self._rcv_nxt)
                    self._rcv_nxt += 1
                self._parse_stream()
            self._send_ack(seq)
        elif cmd == CMD_ACK:
            if seq != NO_SACK:
                self._ack_one(seq)
                self._fast_ack(seq)
                self._refill_window()
        elif cmd == CMD_FIN:
            self.close(send_fin=False)

    def _ack_one(self, seq: int) -> None:
        """Retire one acked segment, sampling RTT per Karn's rule (only
        segments transmitted exactly once give unambiguous samples)."""
        ent = self._unacked.pop(seq, None)
        if ent is None:
            return
        if ent[4] == 1:
            rtt = asyncio.get_running_loop().time() - ent[3]
            if self.srtt == 0.0:
                self.srtt = rtt
                self.rttvar = rtt / 2.0
            else:
                self.rttvar = 0.75 * self.rttvar + 0.25 * abs(self.srtt - rtt)
                self.srtt = 0.875 * self.srtt + 0.125 * rtt
            self.rto = min(
                max(self.srtt + max(TICK_INTERVAL, 4.0 * self.rttvar),
                    RTO_MIN),
                RTO_MAX,
            )
        if self._congestion:  # slow start, then AIMD growth
            self._cwnd += 1.0 if self._cwnd < self._ssthresh else 1.0 / self._cwnd

    def _apply_ack(self, ack: int) -> None:
        if not self._unacked:
            return
        for seq in [s for s in self._unacked if s < ack]:
            self._ack_one(seq)
        # No _fast_ack here: a cumulative ack retires EVERY older segment,
        # so nothing in flight can have been skipped by it; skips are only
        # observable via the SACK seq on CMD_ACK.
        self._refill_window()

    def _fast_ack(self, acked: int) -> None:
        """KCP fast resend: segments older than an acked seq were 'skipped'
        by that ack; at FAST_RESEND skips, retransmit immediately instead of
        waiting for the RTO."""
        ripe = []
        for seq, ent in self._unacked.items():
            if seq < acked:
                ent[5] += 1
                if ent[5] >= FAST_RESEND:
                    ripe.append(seq)
        if not ripe:
            return
        now = asyncio.get_running_loop().time()
        for seq in sorted(ripe):
            ent = self._unacked[seq]
            ent[5] = 0
            ent[4] += 1
            ent[1] = now + ent[2]  # deadline pushed; rto unchanged
            self.fast_resends += 1
            self._send_segment(seq, ent[0])
        if self._congestion:
            inflight = len(self._unacked)
            self._ssthresh = max(inflight / 2.0, 2.0)
            self._cwnd = self._ssthresh + FAST_RESEND

    def _refill_window(self) -> None:
        now = asyncio.get_running_loop().time()
        while self._backlog and len(self._unacked) < self._window():
            seq, seg = self._backlog.pop(0)
            self._unacked[seq] = [seg, now + self.rto, self.rto, now, 1, 0]
            self._send_segment(seq, seg)

    def _parse_stream(self) -> None:
        """Parse [u32 len][u16 msgtype][payload] frames (TCP framing) out of
        the ordered stream — the shared batched deframe seam
        (packet_conn.deframe), with the same bounded-inflate guard as the
        TCP path."""
        from goworld_tpu.netutil.packet_conn import deframe

        frames, err = deframe(self._instream)
        for msgtype, payload in frames:
            self._packets.put_nowait((msgtype, Packet(payload)))
        if err is not None:
            self.close()  # malformed stream (frames before it delivered)

    # --- retransmit ---------------------------------------------------------

    async def _tick_loop(self) -> None:
        try:
            while not self.closed:
                await asyncio.sleep(TICK_INTERVAL)
                now = asyncio.get_running_loop().time()
                timed_out = False
                for seq, ent in self._unacked.items():
                    if now >= ent[1]:
                        ent[2] = min(ent[2] * RTO_BACKOFF, RTO_MAX)
                        ent[1] = now + ent[2]
                        ent[4] += 1
                        ent[5] = 0
                        timed_out = True
                        self.timeout_resends += 1
                        self._send_segment(seq, ent[0])
                if timed_out and self._congestion:
                    self._ssthresh = max(len(self._unacked) / 2.0, 2.0)
                    self._cwnd = 1.0
        except asyncio.CancelledError:
            pass

    # --- recv / close -------------------------------------------------------

    async def recv_packet(self) -> tuple[int, Packet]:
        item = await self._packets.get()
        if item is None:
            raise ConnectionClosed("rudp closed")
        return item

    def close(self, send_fin: bool = True) -> None:
        if self.closed:
            return
        self.closed = True
        if send_fin:
            self._raw_send(_HDR.pack(self.conv, CMD_FIN, 0, self._rcv_nxt))
        self._ticker.cancel()
        self._packets.put_nowait(None)  # wake pending recv
        if self._on_close is not None:
            self._on_close(self)


class RUDPPacketConnection:
    """PacketConnection-shaped adapter over an RUDPEndpoint (the surface
    GoWorldConnection needs; see netutil/ws_conn.py for the pattern)."""

    def __init__(self, endpoint: RUDPEndpoint, peername=None) -> None:
        self._ep = endpoint
        self._peername = peername
        self._compress = 0  # 0 off | 1 zlib | 2 snappy (native.pack modes)

    @property
    def peername(self):
        return self._peername

    @property
    def dropped(self) -> int:
        return self._ep.dropped

    def enable_compression(self, fmt: str = "snappy") -> None:
        """Same contract as PacketConnection.enable_compression (recv
        auto-detects per packet via the length-prefix flag bits)."""
        if fmt not in ("snappy", "zlib"):
            raise ValueError(f"unknown compression format {fmt!r}")
        self._compress = 2 if fmt == "snappy" else 1

    def send_packet(self, msgtype: int, packet: Packet) -> None:
        self._ep.send_bytes(
            native.pack(
                msgtype, packet.payload, self._compress,
                _COMPRESS_THRESHOLD,
                consts.MAX_PACKET_SIZE,
            )
        )

    def flush(self) -> None:
        pass  # segments transmit immediately; ARQ handles the rest

    async def drain(self, hard: bool = False) -> None:
        if hard:
            # Best-effort: wait briefly for the peer to ack everything.
            for _ in range(50):
                if not self._ep._unacked and not self._ep._backlog:
                    return
                await asyncio.sleep(TICK_INTERVAL)

    async def recv_packet(self) -> tuple[int, Packet]:
        return await self._ep.recv_packet()

    def close(self) -> None:
        self._ep.close()

    @property
    def closed(self) -> bool:
        return self._ep.closed


class RUDPListener(asyncio.DatagramProtocol):
    """Server side: one UDP socket on the gate's port; conversations keyed
    by conv id (GateService.go:134-165 serves KCP beside TCP the same way).
    ``on_accept(pconn)`` fires for each new conversation."""

    def __init__(
        self,
        on_accept: Callable[[RUDPPacketConnection], None],
        congestion: bool = False,
    ) -> None:
        self._on_accept = on_accept
        self._congestion = congestion
        self._transport: Optional[asyncio.DatagramTransport] = None
        self._convs: dict[int, RUDPEndpoint] = {}
        self._addrs: dict[int, tuple] = {}
        self.loss_simulation = 0.0  # applied to newly accepted conversations

    def connection_made(self, transport) -> None:
        self._transport = transport

    def datagram_received(self, data: bytes, addr) -> None:
        if len(data) < _HDR.size:
            return
        conv, cmd, seq, ack = _HDR.unpack_from(data, 0)
        ep = self._convs.get(conv)
        if ep is None:
            if cmd != CMD_DATA:
                return  # stray ack/fin for a dead conversation
            ep = RUDPEndpoint(
                conv,
                lambda d, c=conv: self._send_to(c, d),
                on_close=lambda e: self._forget(e.conv),
                congestion=self._congestion,
            )
            ep.loss_simulation = self.loss_simulation
            self._convs[conv] = ep
            self._addrs[conv] = addr
            self._on_accept(RUDPPacketConnection(ep, peername=addr))
        # Peer address may roam (kcp allows it): track the latest source.
        self._addrs[conv] = addr
        ep.on_datagram(cmd, seq, ack, data[_HDR.size:])

    def _send_to(self, conv: int, data: bytes) -> None:
        addr = self._addrs.get(conv)
        if self._transport is not None and addr is not None:
            self._transport.sendto(data, addr)

    def _forget(self, conv: int) -> None:
        self._convs.pop(conv, None)
        self._addrs.pop(conv, None)

    def close(self) -> None:
        for ep in list(self._convs.values()):
            ep.close()
        if self._transport is not None:
            self._transport.close()


class _RUDPClientProtocol(asyncio.DatagramProtocol):
    def __init__(self, endpoint_ref: list) -> None:
        self._ref = endpoint_ref

    def datagram_received(self, data: bytes, addr) -> None:
        ep = self._ref[0]
        if ep is None or len(data) < _HDR.size:
            return
        conv, cmd, seq, ack = _HDR.unpack_from(data, 0)
        if conv == ep.conv:
            ep.on_datagram(cmd, seq, ack, data[_HDR.size:])


async def connect_rudp(
    host: str, port: int, loss_simulation: float = 0.0,
    congestion: bool = False,
) -> RUDPPacketConnection:
    """Client side: open a UDP flow and return a PacketConnection-shaped
    transport (conversation id chosen randomly, kcp style). ``congestion``
    enables slow-start/AIMD for adverse networks (default matches the
    reference's turbo nc=1: off)."""
    loop = asyncio.get_running_loop()
    ref: list = [None]
    transport, _ = await loop.create_datagram_endpoint(
        lambda: _RUDPClientProtocol(ref), remote_addr=(host, port)
    )
    conv = random.getrandbits(32) or 1
    ep = RUDPEndpoint(
        conv,
        transport.sendto,
        on_close=lambda e: transport.close(),
        congestion=congestion,
    )
    ep.loss_simulation = loss_simulation
    ref[0] = ep
    return RUDPPacketConnection(ep, peername=(host, port))
